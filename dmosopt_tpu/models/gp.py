"""Exact Gaussian-process surrogates, TPU-native.

Capability match: reference `dmosopt/model.py:1182-1325` (`GPR_Matern`,
`GPR_RBF` — one sklearn GP per objective, `C*Matern(nu=2.5)+White` kernel,
SCE-UA hyperparameter search) and `dmosopt/model_gpytorch.py:1929-2167`
(`EGP_Matern` — exact GPyTorch GP per objective, Adam on the exact MLL;
`MEGP_Matern` :1623 — all objectives fit jointly).

TPU redesign: instead of a Python loop over objectives each running a
host-side global optimizer, hyperparameter fitting is ONE fused XLA
program — the negative log marginal likelihood of every (restart ×
objective) pair is computed by a batched Cholesky over an
``(S, d, N, N)`` kernel tensor (MXU work), optimized by Adam under
``lax.scan``, and the best restart per objective is selected with an
argmin. Multi-start random initialization over log-uniform bounded
hyperparameters replaces SCE-UA's shuffled-complex global search
(reference `model.py:1472-1753`) — same goal (avoid bad MLL local optima),
compiler-friendly mechanics.

Interface parity: ``__init__(xin, yin, nInput, nOutput, xlb, xub, ...)``,
``predict(x) -> (mean, var)``, ``evaluate(x) -> mean | (mean, var)``;
inputs normalized to the unit box, targets standardized per objective
(reference `model.py:1216-1229`, ``normalize_y=True``).
"""

from __future__ import annotations

import math
import warnings
from functools import partial
from typing import NamedTuple, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
import optax

from dmosopt_tpu.ops.filtering import filter_samples
from dmosopt_tpu.ops.sort import top_k_mo
from dmosopt_tpu.utils.prng import as_key

_JITTER = 1e-6
_LOG2PI = math.log(2.0 * math.pi)


# ------------------------------------------------------------------ kernels


def _scaled_sqdist(X1: jax.Array, X2: jax.Array, ls: jax.Array) -> jax.Array:
    """Pairwise squared distance of inputs scaled per-dimension by ``ls``
    (isotropic when ls has one element). The matmul runs at highest
    precision: TPU's default bf16 accumulation loses ~1e-2 absolute on the
    cancellation identity, enough to make Gram matrices indefinite."""
    A = X1 / ls
    B = X2 / ls
    a2 = jnp.sum(A * A, axis=-1, keepdims=True)
    b2 = jnp.sum(B * B, axis=-1, keepdims=True)
    sq = a2 + b2.T - 2.0 * jnp.matmul(A, B.T, precision="highest")
    return jnp.maximum(sq, 0.0)


def matern52(X1, X2, ls, amp):
    r = jnp.sqrt(_scaled_sqdist(X1, X2, ls) + 1e-30)
    s5r = math.sqrt(5.0) * r
    return amp * (1.0 + s5r + (5.0 / 3.0) * r * r) * jnp.exp(-s5r)


def rbf(X1, X2, ls, amp):
    return amp * jnp.exp(-0.5 * _scaled_sqdist(X1, X2, ls))


_KERNELS = {"matern52": matern52, "rbf": rbf}


# ------------------------------------------------- bounded parameterization


class _Bounds(NamedTuple):
    """Log-uniform sigmoid reparameterization: theta = lo*(hi/lo)^sigmoid(u).

    Keeps hyperparameters inside the same bounds the reference passes to
    sklearn (`model.py:1192-1194`) while letting Adam run unconstrained.
    """

    lo: jax.Array
    hi: jax.Array

    def forward(self, u):
        s = jax.nn.sigmoid(u)
        return self.lo * (self.hi / self.lo) ** s

    def inverse(self, theta):
        s = jnp.log(theta / self.lo) / jnp.log(self.hi / self.lo)
        s = jnp.clip(s, 1e-4, 1.0 - 1e-4)
        return jnp.log(s) - jnp.log1p(-s)


class GPParams(NamedTuple):
    u_amp: jax.Array  # ()
    u_ls: jax.Array  # (L,)  L = 1 (isotropic) or nInput (ARD)
    u_noise: jax.Array  # ()


class GPFit(NamedTuple):
    """Posterior state for a batch of d independent GPs (pytree)."""

    X: jax.Array  # (N, n) unit-box inputs (possibly bucket-padded)
    L: jax.Array  # (d, N, N) Cholesky of K + noise*I
    alpha: jax.Array  # (d, N)  (K + noise I)^-1 y_std
    amp: jax.Array  # (d,)
    ls: jax.Array  # (d, L)
    noise: jax.Array  # (d,)
    y_mean: jax.Array  # (d,)
    y_std: jax.Array  # (d,)
    nmll: jax.Array  # (d,) final negative log marginal likelihood
    train_mask: jax.Array  # (N,) 1 = real training row, 0 = bucket padding
    n_steps: Optional[jax.Array] = None  # () int32, Adam steps actually run
    best_start: Optional[jax.Array] = None  # (d,) winning restart index
    # (d, N, N) whitening factor W = L⁻¹, populated only by the
    # mesh-sharded fit (models/gp_sharded.py) whose final posterior pass
    # produces it for free; the matmul predictor adopts it instead of
    # re-paying the O(N³) inversion. Any posterior update that changes L
    # must drop or extend it (see models/refit.py) — a stale W is the
    # stale-predictor hazard in pytree form.
    whitened: Optional[jax.Array] = None


def _default_rel_jitter(dtype) -> float:
    """Amplitude-relative jitter by dtype: f32 Cholesky (the TPU-native
    dtype) fails outright at the reference's noise floor of 1e-9
    (`model.py:1194`) — smooth-kernel Gram matrices at moderate
    lengthscales have eigenvalues below f32 resolution, so f32 carries a
    1e-4·amp floor (~1% noise on standardized targets). f64 matches the
    reference's sklearn configuration and needs none."""
    return 1e-4 if dtype == jnp.float32 else 0.0


def _regularized_kernel(X, ls, amp, noise, kernel_fn, rel_jitter=None):
    """K + (noise + jitter) I, symmetrized; `rel_jitter` scales with the
    fitted amplitude and defaults from the input dtype (f32-safe floor,
    see `_default_rel_jitter`) so callers can't silently lose it."""
    if rel_jitter is None:
        rel_jitter = _default_rel_jitter(X.dtype)
    N = X.shape[0]
    jitter = _JITTER + rel_jitter * amp
    K = kernel_fn(X, X, ls, amp)
    K = 0.5 * (K + K.T)
    return K + (noise + jitter) * jnp.eye(N, dtype=X.dtype)


def _apply_train_mask(K, train_mask):
    """Decouple padded rows from the GP exactly: K_m = (m mᵀ)∘K + diag(1−m).
    With padded targets zeroed, the padded block is an identity whose
    quadratic term and log-determinant are both zero, so the masked MLL,
    posterior alpha, and (with masked cross-covariances) predictions equal
    the unpadded ones in exact arithmetic (f32 reduction order differs) —
    padding only buys a static shape."""
    if train_mask is None:
        return K
    m = train_mask.astype(K.dtype)
    return (m[:, None] * m[None, :]) * K + jnp.diag(1.0 - m)


def _nmll(params: GPParams, bounds3, X, y, kernel_fn, rel_jitter, train_mask=None):
    """Exact negative log marginal likelihood (per objective); `y` must
    already be zeroed on padded rows when `train_mask` is given."""
    b_amp, b_ls, b_noise = bounds3
    amp = b_amp.forward(params.u_amp)
    ls = b_ls.forward(params.u_ls)
    noise = b_noise.forward(params.u_noise)
    N = X.shape[0] if train_mask is None else jnp.sum(train_mask)
    K = _apply_train_mask(
        _regularized_kernel(X, ls, amp, noise, kernel_fn, rel_jitter), train_mask
    )
    L = jnp.linalg.cholesky(K)
    alpha = jax.scipy.linalg.cho_solve((L, True), y)
    return (
        0.5 * jnp.dot(y, alpha)
        + jnp.sum(jnp.log(jnp.diagonal(L)))
        + 0.5 * N * _LOG2PI
    )


def _select_better(improved, new_params: GPParams, best_params: GPParams) -> GPParams:
    """Elementwise best-iterate tracking over the restart grid. ``improved``
    broadcasts over each param's leading axes."""

    def pick(new, best):
        m = improved.reshape(improved.shape + (1,) * (new.ndim - improved.ndim))
        return jnp.where(m, new, best)

    return GPParams(*(pick(n, b) for n, b in zip(new_params, best_params)))


def _resolve_convergence_defaults(d, tol, check_every):
    """Resolve the "auto" convergence defaults by objective count.
    Bi-objective fits are quality-neutral under the fast pair (1e-3, 10)
    on every oracle (parity suite, zdt configs) and ~3x cheaper; for
    d > 2 only the strict pair (1e-4, 20) is evidenced — DTLZ7-m5 final
    HV collapses 10.32 -> 8.88 under (1e-3, 10), (1e-4, 10) OR
    (1e-3, 20), so both strict knobs are required (BASELINE.md)."""
    if tol == "auto":
        tol = 1e-3 if d <= 2 else 1e-4
    if check_every is None:
        check_every = 10 if d <= 2 else 20
    return tol, check_every


def _scan_with_convergence(step, carry0, n_iter, convergence_tol,
                           convergence_check_every, winner_fn, dt):
    """Run `lax.scan(step)` for up to `n_iter` iterations, checking a
    convergence criterion every `convergence_check_every` steps inside a
    `lax.while_loop`: stop once a whole chunk fails to improve any
    component of `winner_fn(best_vals)` (the quantity the fit returns)
    by more than `tol * max(1, |winner|)`. The carry layout is fixed:
    (params, opt_state, best_params, best_vals). inf -> finite
    improvements count as improving (delta inf); inf -> inf is nan (not
    improving); the first chunk always runs. `convergence_tol=None`
    restores the fixed-length scan; `n_iter` stays the hard cap.

    Returns (carry, n_steps) where n_steps is the () int32 count of
    optimizer steps actually executed (== n_iter when stopping is
    disabled or never triggered)."""
    chunk = (
        max(1, min(convergence_check_every, n_iter))
        if convergence_tol is not None
        else n_iter
    )
    if convergence_tol is None or chunk >= n_iter:
        carry, _ = jax.lax.scan(step, carry0, None, length=n_iter)
        return carry, jnp.asarray(n_iter, jnp.int32)

    tol = jnp.asarray(convergence_tol, dt)
    n_full, rem = divmod(n_iter, chunk)
    win0 = winner_fn(carry0[3])

    def cond(c):
        *_, best_vals, i, prev_win = c
        win = winner_fn(best_vals)
        delta = prev_win - win
        improving = jnp.any(delta > tol * jnp.maximum(1.0, jnp.abs(win)))
        # i == 0: both sides are inf (delta nan) — always run chunk 1
        return (i < n_full) & ((i == 0) | improving)

    def body(c):
        params, opt_state, best_params, best_vals, i, _ = c
        inner, _ = jax.lax.scan(
            step, (params, opt_state, best_params, best_vals), None,
            length=chunk,
        )
        return (*inner, i + 1, winner_fn(best_vals))

    carry = jax.lax.while_loop(
        cond, body,
        (*carry0, jnp.asarray(0, jnp.int32), jnp.full_like(win0, jnp.inf)),
    )
    *inner, i_done, prev_win = carry
    inner = tuple(inner)
    n_steps = i_done * chunk
    if rem:
        # only a run that exhausted every chunk without converging still
        # owes the remainder steps (exact n_iter semantics). The count
        # cap exits the while_loop before `cond` re-evaluates the final
        # chunk, so re-apply its improvement predicate here: a run whose
        # last full chunk already converged stops exactly there.
        win = winner_fn(inner[3])
        delta = prev_win - win
        improving = jnp.any(delta > tol * jnp.maximum(1.0, jnp.abs(win)))
        owes_rem = (i_done == n_full) & improving
        inner = jax.lax.cond(
            owes_rem,
            lambda c: jax.lax.scan(step, c, None, length=rem)[0],
            lambda c: c,
            inner,
        )
        n_steps = n_steps + jnp.where(owes_rem, rem, 0)
    return inner, n_steps.astype(jnp.int32)


@partial(
    jax.jit,
    static_argnames=(
        "kernel", "n_starts", "n_iter", "ard", "rel_jitter",
        "mesh", "model_axis", "convergence_tol", "convergence_check_every",
    ),
)
def fit_gp_batch(
    key: jax.Array,
    X: jax.Array,  # (N, n) unit box
    Y: jax.Array,  # (N, d) standardized targets
    lengthscale_bounds: Tuple[float, float] = (1e-3, 100.0),
    amplitude_bounds: Tuple[float, float] = (1e-4, 1e3),
    noise_bounds: Tuple[float, float] = (1e-9, 1e-2),
    kernel: str = "matern52",
    n_starts: int = 8,
    n_iter: int = 200,
    learning_rate: float = 0.1,
    ard: bool = False,
    rel_jitter: Optional[float] = None,
    train_mask: Optional[jax.Array] = None,
    mesh=None,
    model_axis: str = "model",
    convergence_tol="auto",
    convergence_check_every: Optional[int] = None,
    warm_start: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
) -> GPFit:
    """Fit d independent GPs with S random restarts each, as one program.

    The (S, d) grid of NMLLs shares a single batched Cholesky per Adam step;
    the best restart per objective wins (replaces SCE-UA global search,
    reference model.py:1419-1753). `train_mask` (N,) marks real rows when X/Y
    are bucket-padded to a static shape (see `_pad_to_bucket`); masked fits
    are exactly the unpadded fits.

    `convergence_tol` enables the in-graph analogue of the reference
    SCE-UA's convergence stop (model.py:1579-1596 `peps` criterion): the
    Adam scan runs in chunks of `convergence_check_every` steps inside a
    `lax.while_loop`, stopping once a whole chunk fails to improve ANY
    objective's winning (min-over-restarts) best NMLL by more than
    `tol * max(1, |nmll|)`. The winner is what the fit returns — a
    losing restart still wandering does not keep the loop alive. No host
    syncs; easy fits stop in a fraction of `n_iter`. `None` restores the
    fixed `n_iter`-step scan.

    The defaults resolve by objective count, mirroring the reference's
    per-context stopping configs (model_gpytorch.py:588-633):
    `convergence_tol="auto"` -> 1e-3 (d <= 2) / 1e-4 (d > 2), and
    `convergence_check_every=None` -> 10 / 20 respectively — see
    `_resolve_convergence_defaults` for the evidence.

    `warm_start`, when given, is a `(amp, ls, noise)` triple of
    per-objective hyperparameter arrays — shapes `(d,)`, `(d, L)`,
    `(d,)` — from a previous epoch's converged fit. Restart slot 0 then
    starts exactly at the warm values and the remaining slots are
    jittered around them (instead of around the reference's
    deterministic init), so a barely-moved refit converges within the
    first convergence chunk of `_scan_with_convergence`. The random
    draws are identical either way; `warm_start=None` (the default) is
    the unchanged cold path.

    With a `mesh` carrying a `model_axis` whose size divides `n_starts`,
    the restart axis of the whole Adam scan is sharded over that axis
    (data/X replicated; XLA inserts the final cross-restart argmin
    collective) — the second mesh axis next to the EA loop's population
    axis (see `parallel/mesh.py`, `__graft_entry__.dryrun_multichip`).
    """
    N, n = X.shape
    if train_mask is not None:
        Y = Y * train_mask[:, None].astype(Y.dtype)
    d = Y.shape[1]
    convergence_tol, convergence_check_every = _resolve_convergence_defaults(
        d, convergence_tol, convergence_check_every
    )
    Lls = n if ard else 1
    dt = X.dtype
    if rel_jitter is None:
        rel_jitter = _default_rel_jitter(dt)

    b_amp = _Bounds(jnp.asarray(amplitude_bounds[0], dt), jnp.asarray(amplitude_bounds[1], dt))
    b_ls = _Bounds(jnp.asarray(lengthscale_bounds[0], dt), jnp.asarray(lengthscale_bounds[1], dt))
    b_noise = _Bounds(jnp.asarray(noise_bounds[0], dt), jnp.asarray(noise_bounds[1], dt))
    bounds3 = (b_amp, b_ls, b_noise)
    kernel_fn = _KERNELS[kernel]

    # First start per objective = the reference's deterministic inits
    # (amp 1.0, ls 0.5, noise 1e-6, model.py:1221-1227); the rest random.
    # A warm start replaces that anchor with the previous epoch's
    # converged hyperparameters (slot 0 exact, the rest jittered around
    # it) — same key splits and draw shapes as the cold path.
    k1, k2, k3 = jax.random.split(key, 3)
    if warm_start is None:
        u0_amp = jnp.full((n_starts, d), b_amp.inverse(jnp.asarray(1.0, dt)))
        u0_ls = jnp.full((n_starts, d, Lls), b_ls.inverse(jnp.asarray(0.5, dt)))
        u0_noise = jnp.full((n_starts, d), b_noise.inverse(jnp.asarray(1e-6, dt)))
    else:
        w_amp, w_ls, w_noise = warm_start
        u0_amp = jnp.broadcast_to(
            b_amp.inverse(jnp.asarray(w_amp, dt)), (n_starts, d)
        )
        u0_ls = jnp.broadcast_to(
            b_ls.inverse(jnp.asarray(w_ls, dt)), (n_starts, d, Lls)
        )
        u0_noise = jnp.broadcast_to(
            b_noise.inverse(jnp.asarray(w_noise, dt)), (n_starts, d)
        )
    jitter_amp = 2.0 * jax.random.normal(k1, (n_starts, d), dt)
    jitter_ls = 2.0 * jax.random.normal(k2, (n_starts, d, Lls), dt)
    jitter_noise = 2.0 * jax.random.normal(k3, (n_starts, d), dt)
    mask = (jnp.arange(n_starts) > 0).astype(dt)
    params0 = GPParams(
        u_amp=u0_amp + mask[:, None] * jitter_amp,
        u_ls=u0_ls + mask[:, None, None] * jitter_ls,
        u_noise=u0_noise + mask[:, None] * jitter_noise,
    )
    if (
        mesh is not None
        and model_axis in mesh.axis_names
        and n_starts % mesh.shape[model_axis] == 0
    ):
        from dmosopt_tpu.parallel.mesh import population_sharding

        shard = population_sharding(mesh, model_axis)
        params0 = jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(a, shard), params0
        )

    # loss over the (S, d) grid: vmap over restarts, then objectives.
    def loss_one(p, y):
        return _nmll(p, bounds3, X, y, kernel_fn, rel_jitter, train_mask)

    def loss_grid(params):
        per_obj = jax.vmap(loss_one, in_axes=(0, 1))  # over objectives
        per_start = jax.vmap(lambda p: per_obj(p, Y))  # over restarts
        return per_start(params)  # (S, d)

    def total_loss(params):
        vals = loss_grid(params)
        return jnp.sum(jnp.where(jnp.isfinite(vals), vals, 0.0)), vals

    opt = optax.adam(learning_rate)
    opt_state0 = opt.init(params0)
    inf0 = jnp.full((n_starts, d), jnp.inf, dt)

    def step(carry, _):
        params, opt_state, best_params, best_vals = carry
        (_, vals), grads = jax.value_and_grad(total_loss, has_aux=True)(params)
        vals = jnp.where(jnp.isfinite(vals), vals, jnp.inf)
        improved = vals < best_vals
        best_params = _select_better(improved, params, best_params)
        best_vals = jnp.where(improved, vals, best_vals)
        grads = jax.tree_util.tree_map(jnp.nan_to_num, grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state, best_params, best_vals), None

    # the winner is what the fit returns — the best restart per
    # objective; a losing restart still wandering must not keep the
    # loop alive. tol None disables stopping; 0.0 is a real tolerance.
    (_, _, params, final), n_steps = _scan_with_convergence(
        step, (params0, opt_state0, params0, inf0), n_iter,
        convergence_tol, convergence_check_every,
        lambda best_vals: jnp.min(best_vals, axis=0), dt,
    )
    best = jnp.argmin(final, axis=0)  # (d,)

    take = lambda arr: jnp.take_along_axis(
        arr, best.reshape((1, d) + (1,) * (arr.ndim - 2)), axis=0
    )[0]
    amp = b_amp.forward(take(params.u_amp))
    ls = b_ls.forward(take(params.u_ls))
    noise = b_noise.forward(take(params.u_noise))

    def posterior(amp_i, ls_i, noise_i, y):
        K = _apply_train_mask(
            _regularized_kernel(X, ls_i, amp_i, noise_i, kernel_fn, rel_jitter),
            train_mask,
        )
        L = jnp.linalg.cholesky(K)
        alpha = jax.scipy.linalg.cho_solve((L, True), y)
        return L, alpha

    L, alpha = jax.vmap(posterior, in_axes=(0, 0, 0, 1))(amp, ls, noise, Y)
    nmll = jnp.min(final, axis=0)
    zeros = jnp.zeros((d,), dt)
    tm = jnp.ones((N,), dt) if train_mask is None else train_mask.astype(dt)
    return GPFit(X=X, L=L, alpha=alpha, amp=amp, ls=ls, noise=noise,
                 y_mean=zeros, y_std=jnp.ones((d,), dt), nmll=nmll,
                 train_mask=tm, n_steps=n_steps, best_start=best)


@partial(
    jax.jit,
    static_argnames=(
        "kernel", "n_starts", "n_iter", "rel_jitter",
        "convergence_tol", "convergence_check_every",
    ),
)
def fit_gp_shared(
    key: jax.Array,
    X: jax.Array,  # (N, n) unit box
    Y: jax.Array,  # (N, d) standardized targets
    lengthscale_bounds: Tuple[float, float] = (1e-3, 100.0),
    amplitude_bounds: Tuple[float, float] = (1e-4, 1e3),
    noise_bounds: Tuple[float, float] = (1e-9, 1e-2),
    kernel: str = "matern52",
    n_starts: int = 8,
    n_iter: int = 300,
    learning_rate: float = 0.1,
    rel_jitter: Optional[float] = None,
    train_mask: Optional[jax.Array] = None,
    convergence_tol="auto",
    convergence_check_every: Optional[int] = None,
) -> GPFit:
    """Joint multi-output fit: ONE shared ARD kernel for all d objectives,
    optimized on the summed exact MLL (the statistical coupling of the
    reference's multitask GP, model_gpytorch.py:1623-1926, without its
    Kronecker task covariance). Posterior stays per-objective.
    Convergence stopping follows `fit_gp_batch`: the loop exits once a
    whole chunk fails to improve the winning (min-over-restarts) summed
    MLL."""
    N, n = X.shape
    if train_mask is not None:
        Y = Y * train_mask[:, None].astype(Y.dtype)
    d = Y.shape[1]
    convergence_tol, convergence_check_every = _resolve_convergence_defaults(
        d, convergence_tol, convergence_check_every
    )
    dt = X.dtype
    if rel_jitter is None:
        rel_jitter = _default_rel_jitter(dt)

    b_amp = _Bounds(jnp.asarray(amplitude_bounds[0], dt), jnp.asarray(amplitude_bounds[1], dt))
    b_ls = _Bounds(jnp.asarray(lengthscale_bounds[0], dt), jnp.asarray(lengthscale_bounds[1], dt))
    b_noise = _Bounds(jnp.asarray(noise_bounds[0], dt), jnp.asarray(noise_bounds[1], dt))
    bounds3 = (b_amp, b_ls, b_noise)
    kernel_fn = _KERNELS[kernel]

    k1, k2, k3 = jax.random.split(key, 3)
    mask = (jnp.arange(n_starts) > 0).astype(dt)
    params0 = GPParams(
        u_amp=jnp.full((n_starts,), b_amp.inverse(jnp.asarray(1.0, dt)))
        + mask * 2.0 * jax.random.normal(k1, (n_starts,), dt),
        u_ls=jnp.full((n_starts, n), b_ls.inverse(jnp.asarray(0.5, dt)))
        + mask[:, None] * 2.0 * jax.random.normal(k2, (n_starts, n), dt),
        u_noise=jnp.full((n_starts,), b_noise.inverse(jnp.asarray(1e-6, dt)))
        + mask * 2.0 * jax.random.normal(k3, (n_starts,), dt),
    )

    def loss_start(p):
        # one Cholesky serves all d objectives (shared kernel)
        b_amp, b_ls, b_noise = bounds3
        amp = b_amp.forward(p.u_amp)
        ls = b_ls.forward(p.u_ls)
        noise = b_noise.forward(p.u_noise)
        K = _apply_train_mask(
            _regularized_kernel(X, ls, amp, noise, kernel_fn, rel_jitter),
            train_mask,
        )
        L = jnp.linalg.cholesky(K)
        alpha = jax.scipy.linalg.cho_solve((L, True), Y)  # (N, d)
        N_eff = N if train_mask is None else jnp.sum(train_mask)
        return (
            0.5 * jnp.sum(Y * alpha)
            + d * jnp.sum(jnp.log(jnp.diagonal(L)))
            + 0.5 * d * N_eff * _LOG2PI
        )

    def total_loss(params):
        vals = jax.vmap(loss_start)(params)  # (S,)
        return jnp.sum(jnp.where(jnp.isfinite(vals), vals, 0.0)), vals

    opt = optax.adam(learning_rate)

    def step(carry, _):
        params, opt_state, best_params, best_vals = carry
        (_, vals), grads = jax.value_and_grad(total_loss, has_aux=True)(params)
        vals = jnp.where(jnp.isfinite(vals), vals, jnp.inf)
        improved = vals < best_vals
        best_params = _select_better(improved, params, best_params)
        best_vals = jnp.where(improved, vals, best_vals)
        grads = jax.tree_util.tree_map(jnp.nan_to_num, grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state, best_params, best_vals), None

    (_, _, params, vals), n_steps = _scan_with_convergence(
        step,
        (params0, opt.init(params0), params0,
         jnp.full((n_starts,), jnp.inf, dt)),
        n_iter, convergence_tol, convergence_check_every, jnp.min, dt,
    )
    best = jnp.argmin(vals)
    amp = b_amp.forward(params.u_amp[best])
    ls = b_ls.forward(params.u_ls[best])
    noise = b_noise.forward(params.u_noise[best])

    K = _apply_train_mask(
        _regularized_kernel(X, ls, amp, noise, kernel_fn, rel_jitter), train_mask
    )
    L = jnp.linalg.cholesky(K)
    alpha = jax.scipy.linalg.cho_solve((L, True), Y)  # (N, d)
    return GPFit(
        X=X,
        L=jnp.broadcast_to(L, (d, N, N)),
        alpha=alpha.T,
        amp=jnp.broadcast_to(amp, (d,)),
        ls=jnp.broadcast_to(ls, (d, n)),
        noise=jnp.broadcast_to(noise, (d,)),
        y_mean=jnp.zeros((d,), dt),
        y_std=jnp.ones((d,), dt),
        nmll=jnp.broadcast_to(vals[best] / d, (d,)),
        train_mask=(
            jnp.ones((N,), dt) if train_mask is None else train_mask.astype(dt)
        ),
        n_steps=n_steps,
    )


# --------------------------------------------- problems-axis (multi-tenant)


def fit_gp_problems(
    keys: jax.Array,  # (P, ...) one PRNG key per problem
    X: jax.Array,  # (P, N, n) unit-box inputs, bucket-padded to a COMMON N
    Y: jax.Array,  # (P, N, d) standardized targets, zero on padded rows
    train_mask: jax.Array,  # (P, N) 1 = real row
    **common,
) -> GPFit:
    """`fit_gp_batch` lifted over a leading *problems* axis: ONE Adam
    loop (one XLA program) fits every tenant in a bucket.

    Each problem keeps its own restart grid, its own Adam moments, and
    its own best-iterate tracking — under `vmap` the per-problem
    trajectories are independent, so each tenant's result is the same
    math as its standalone `fit_gp_batch` call at the same padding
    bucket (modulo batched-kernel reduction order). The in-graph
    convergence stop lifts to "run while ANY problem's chunk still
    improves": early-converged tenants may take extra best-iterate-
    tracked steps, which can only improve their winning NMLL.

    `common` forwards `fit_gp_batch`'s static configuration
    (kernel/n_starts/n_iter/bounds/...); `mesh` is forced off — the
    problems axis is the batch axis here. Returns a `GPFit` whose every
    leaf carries a leading (P,) axis; slice per tenant with
    `tree_map(lambda a: a[i], fit)`.
    """
    common = dict(common)
    common.pop("mesh", None)
    common.pop("warm_start", None)

    def one(k, x, y, m):
        return fit_gp_batch(k, x, y, train_mask=m, mesh=None, **common)

    return jax.vmap(one)(keys, X, Y, train_mask)


def gp_predict_problems(fit: GPFit, Xq: jax.Array, kernel: str = "matern52"):
    """`gp_predict` over a problems-stacked `GPFit` (leading (P,) axis on
    every leaf) and per-problem query batches `Xq` (P, M, n). Returns
    ((P, M, d), (P, M, d)) — the solve-oracle math per tenant, batched
    into one program (jax-traceable; the multi-tenant inner EA scans
    it)."""

    def one(f, xq):
        return gp_predict(f, xq, kernel=kernel)

    return jax.vmap(one)(fit, Xq)


@partial(jax.jit, static_argnames=("kernel",))
def gp_predict(fit: GPFit, Xq: jax.Array, kernel: str = "matern52"):
    """Batched posterior mean/variance for all d GPs at query points (M, n).

    Variance includes the fitted noise level, matching sklearn's
    ``predict(return_std=True)`` with a WhiteKernel in the sum
    (reference model.py:1266-1270). Returns ((M, d), (M, d)).
    """
    kernel_fn = _KERNELS[kernel]

    def one(L, alpha, amp, ls, noise, ym, ys):
        Ks = kernel_fn(fit.X, Xq, ls, amp)  # (N, M)
        # padded training rows carry no information: zero their cross-
        # covariance so the posterior equals the unpadded one exactly
        Ks = Ks * fit.train_mask[:, None].astype(Ks.dtype)
        mean = Ks.T @ alpha
        v = jax.scipy.linalg.solve_triangular(L, Ks, lower=True)  # (N, M)
        var = amp + noise - jnp.sum(v * v, axis=0)
        var = jnp.maximum(var, 1e-12)
        return ym + ys * mean, ys * ys * var

    mean, var = jax.vmap(one)(
        fit.L, fit.alpha, fit.amp, fit.ls, fit.noise, fit.y_mean, fit.y_std
    )
    return mean.T, var.T


# ------------------------------------------- cross-epoch posterior updates


def _masked_nmll_from_chol(L, alpha, y, train_mask):
    """Exact NMLL given a factorized posterior: identical algebra to
    `_nmll`'s tail (padded rows contribute zero to every term)."""
    N_eff = jnp.sum(train_mask)
    return (
        0.5 * jnp.dot(y, alpha)
        + jnp.sum(jnp.log(jnp.diagonal(L)))
        + 0.5 * N_eff * _LOG2PI
    )


@partial(jax.jit, static_argnames=("kernel", "n_old", "n_new", "rel_jitter"))
def extend_cholesky_rank_k(
    L_old: jax.Array,  # (d, P, P) previous factor (identity on padded rows)
    X_pad: jax.Array,  # (P, n) inputs with rows [n_old, n_new) newly filled
    train_mask: jax.Array,  # (P,) 1 for rows < n_new
    Yn_pad: jax.Array,  # (P, d) standardized targets, zero beyond n_new
    amp: jax.Array,  # (d,)
    ls: jax.Array,  # (d, L)
    noise: jax.Array,  # (d,)
    kernel: str,
    n_old: int,
    n_new: int,
    rel_jitter: float,
):
    """Blocked rank-k Cholesky update: extend a cached posterior by the
    k = n_new - n_old rows appended inside the existing padding bucket.

    Because `_apply_train_mask` keeps padded rows exactly decoupled (an
    identity block), the previous factor's top-left (n_old, n_old) block
    is the Cholesky of the old training kernel and everything below it
    is zero/identity — so the update is the textbook block step
    L21 = K21 L11⁻ᵀ, L22 = chol(K22 − L21 L21ᵀ), at O(N²k) FLOPs per
    objective instead of the O(N³) refactorization, followed by an
    O(N²) re-solve of alpha against the full (unchanged + new) targets.
    An append that would cross the bucket boundary cannot use this path
    (the static shapes differ) — callers fall back to
    `posterior_from_params` at the new bucket.

    `n_old`/`n_new` are static: each (n_old, n_new, P) combination
    compiles its own (small — two triangular solves and a (k, k)
    Cholesky) program.

    Returns (L, alpha, nmll) with shapes ((d, P, P), (d, P), (d,)).
    """
    kernel_fn = _KERNELS[kernel]
    if rel_jitter is None:
        rel_jitter = _default_rel_jitter(X_pad.dtype)
    k = n_new - n_old

    def one(L_prev, amp_i, ls_i, noise_i, y):
        # only the appended rows' kernel blocks are needed — O(k·N·dim)
        # to build, not the full (P, P) kernel the O(N³) path forms.
        # Rows [n_old, n_new) are real against real columns [0, n_new),
        # so the train mask is identically 1 on every entry touched.
        rows = kernel_fn(X_pad[n_old:n_new], X_pad[:n_new], ls_i, amp_i)
        B = rows[:, :n_old]  # (k, n_old) cross-covariances
        jitter = _JITTER + rel_jitter * amp_i
        K22 = rows[:, n_old:n_new]
        K22 = 0.5 * (K22 + K22.T) + (noise_i + jitter) * jnp.eye(
            k, dtype=X_pad.dtype
        )
        L11 = L_prev[:n_old, :n_old]
        L21t = jax.scipy.linalg.solve_triangular(L11, B.T, lower=True)
        S = K22 - L21t.T @ L21t
        S = 0.5 * (S + S.T)
        L22 = jnp.linalg.cholesky(S)
        L_new = L_prev.at[n_old:n_new, :n_old].set(L21t.T)
        L_new = L_new.at[n_old:n_new, n_old:n_new].set(L22)
        alpha = jax.scipy.linalg.cho_solve((L_new, True), y)
        return L_new, alpha, _masked_nmll_from_chol(L_new, alpha, y, train_mask)

    return jax.vmap(one, in_axes=(0, 0, 0, 0, 1))(L_old, amp, ls, noise, Yn_pad)


@partial(jax.jit, static_argnames=("kernel", "rel_jitter"))
def posterior_from_params(
    X: jax.Array,  # (P, n)
    Yn: jax.Array,  # (P, d)
    train_mask: jax.Array,  # (P,)
    amp: jax.Array,  # (d,)
    ls: jax.Array,  # (d, L)
    noise: jax.Array,  # (d,)
    kernel: str,
    rel_jitter: float,
):
    """Full masked refactorization at fixed hyperparameters (no Adam):
    the fallback when a rank-k append crosses a bucket boundary, and the
    oracle the rank-k update is pinned against in tests.
    Returns (L, alpha, nmll) like `extend_cholesky_rank_k`."""
    kernel_fn = _KERNELS[kernel]

    def one(amp_i, ls_i, noise_i, y):
        K = _apply_train_mask(
            _regularized_kernel(X, ls_i, amp_i, noise_i, kernel_fn, rel_jitter),
            train_mask,
        )
        L = jnp.linalg.cholesky(K)
        alpha = jax.scipy.linalg.cho_solve((L, True), y)
        return L, alpha, _masked_nmll_from_chol(L, alpha, y, train_mask)

    return jax.vmap(one, in_axes=(0, 0, 0, 1))(amp, ls, noise, Yn)


def clone_with_fit(prev, fit: GPFit, fit_info: dict):
    """New surrogate of `prev`'s class sharing its normalization state
    but carrying an updated posterior — the result object of a rank-k
    append (or bucket-crossing refactorization), built without running
    the constructor's hyperparameter fit. The predictor cache is NOT
    carried over (it belongs to the previous posterior — serving it
    would be the stale-predictor hazard); callers that can extend it
    incrementally set `_predictor_obj` themselves afterwards."""
    new = object.__new__(type(prev))
    for attr in (
        "nInput", "nOutput", "xlb", "xub", "xrg",
        "_dtype", "return_mean_variance", "logger",
    ):
        setattr(new, attr, getattr(prev, attr))
    new._rel_jitter = getattr(prev, "_rel_jitter", None)
    new._predictor_spec = dict(getattr(prev, "_predictor_spec", None) or {})
    new._mesh = getattr(prev, "_mesh", None)
    new._predictor_obj = None
    new.fit = fit
    new.fit_info = fit_info
    return new


# ---------------------------------------------------------------- wrappers


def _gp_fit_info(fit: GPFit, n_iter: int) -> dict:
    """Host-side summary of one hyperparameter fit: winning per-objective
    NMLLs, their mean as the scalar `loss`, and the convergence-stop
    accounting (`n_steps` < `n_iter_max` means the in-graph criterion
    fired early)."""
    nmll = np.asarray(fit.nmll, dtype=np.float64)
    n_steps = int(fit.n_steps) if fit.n_steps is not None else int(n_iter)
    return {
        "loss": float(np.mean(nmll)),
        "nmll_per_objective": [float(v) for v in nmll],
        "n_steps": n_steps,
        "n_iter_max": int(n_iter),
        "early_stopped": n_steps < int(n_iter),
    }


def _prepare_training_data(
    model, xin, yin, nInput, nOutput, xlb, xub, nan, top_k, y_stats=None
):
    """Shared surrogate training-data pipeline (reference model.py:1206-1229):
    NaN policy, optional top-k truncation, unit-box x normalization, per-
    objective y standardization. Sets bounds attributes on ``model`` and
    returns (X_unit, Y_standardized, y_mean, y_std). ``y_stats`` — a
    ``(y_mean, y_std)`` pair — overrides the freshly computed
    standardization; the rank-k refit path uses it to keep a cached
    ``alpha`` consistent with the previous epoch's normalization."""
    model.nInput = int(nInput)
    model.nOutput = int(nOutput)
    model.xlb = np.asarray(xlb, dtype=np.float64)
    model.xub = np.asarray(xub, dtype=np.float64)
    model.xrg = np.where(model.xub - model.xlb == 0.0, 1.0, model.xub - model.xlb)

    xin = np.asarray(xin, dtype=np.float64)
    yin = np.asarray(yin, dtype=np.float64)
    if yin.ndim == 1:
        yin = yin.reshape(-1, 1)
    if nan is not None:
        yin, xin = filter_samples(yin, xin, nan=nan)
    xin, yin = top_k_mo(xin, yin, top_k)
    yin = np.nan_to_num(yin)

    X = (xin - model.xlb) / model.xrg
    if y_stats is None:
        y_mean = yin.mean(axis=0)
        y_std = yin.std(axis=0)
        y_std = np.where(y_std == 0.0, 1.0, y_std)
    else:
        y_mean = np.asarray(y_stats[0], dtype=np.float64)
        y_std = np.asarray(y_stats[1], dtype=np.float64)
    Yn = (yin - y_mean) / y_std
    return X, Yn, y_mean, y_std


def _bucket_size(N: int) -> int:
    """Static-shape bucket for a training-set size: multiples of 64 up to
    512, multiples of 256 beyond. MO-ASMO grows the training set every
    epoch (reference MOASMO.py:473-530 refits per epoch); bucketing keeps
    the fit/predict programs' shapes stable across epochs so XLA compiles
    once per bucket instead of once per epoch, at ≤(1+b/N)³ extra Cholesky
    FLOPs — negligible at the sizes where FLOPs matter."""
    step = 64 if N <= 512 else 256
    return max(step, step * -(-N // step))


def _pad_to_bucket(X: np.ndarray, Yn: np.ndarray, cap: Optional[int] = None):
    """Pad (X, Y) rows up to `_bucket_size` and return (X_pad, Y_pad, mask).
    Padded x rows sit at the unit-box center (any finite value works: the
    train mask decouples them exactly — see `_apply_train_mask`).
    ``cap`` overrides the per-N bucket size — the multi-tenant fit pads
    every tenant in a bucket to one common capacity (the max of their
    individual buckets) so the problems axis stacks."""
    N = X.shape[0]
    if cap is None:
        cap = _bucket_size(N)
    elif cap < N:
        raise ValueError(f"pad cap {cap} < {N} rows")
    if cap == N:
        return X, Yn, np.ones((N,), dtype=X.dtype)
    pad = cap - N
    X_pad = np.concatenate([X, np.full((pad, X.shape[1]), 0.5, X.dtype)])
    Y_pad = np.concatenate([Yn, np.zeros((pad, Yn.shape[1]), Yn.dtype)])
    mask = np.concatenate([np.ones((N,), X.dtype), np.zeros((pad,), X.dtype)])
    return X_pad, Y_pad, mask


def _resolve_dtype(dtype):
    """"float32"/"float64" (or numpy dtypes) -> jnp dtype; float64
    requires the global jax x64 mode and enables it on first use."""
    dt = jnp.float64 if np.dtype(dtype) == np.float64 else jnp.float32
    if dt == jnp.float64 and not jax.config.jax_enable_x64:
        warnings.warn(
            "dtype=float64 enables jax_enable_x64 globally for this process"
        )
        jax.config.update("jax_enable_x64", True)
    return dt


def _resolve_predictor_spec(
    predictor, nystrom_points, nystrom_probe_points, nystrom_mean_tol,
    nystrom_var_ratio_tol,
):
    """Validate and pack the exact-GP family's predictor options (the
    `GPPredictor` constructor kwargs minus fit/kernel/mesh)."""
    from dmosopt_tpu.models.predictor import PREDICTOR_MODES

    if predictor not in PREDICTOR_MODES:
        raise ValueError(
            f"predictor {predictor!r} not in {PREDICTOR_MODES}"
        )
    return dict(
        mode=predictor,
        nystrom_points=int(nystrom_points),
        nystrom_probe_points=int(nystrom_probe_points),
        nystrom_mean_tol=float(nystrom_mean_tol),
        nystrom_var_ratio_tol=float(nystrom_var_ratio_tol),
    )


def _resolve_surrogate_mesh_spec(spec):
    """Validate/normalize the exact-GP family's ``surrogate_mesh`` knob.

    None/False (the default) disables the sharded fit entirely — the
    single-device path stays byte-identical. True opts in with
    defaults; a dict overrides ``min_points`` (archive-size routing
    threshold, real rows), ``tile`` (Cholesky panel width, None =
    `gp_sharded.default_chol_tile`) and ``axis`` (mesh axis name,
    None = the mesh's first axis)."""
    if spec is None or spec is False:
        return None
    out = {"min_points": 4096, "tile": None, "axis": None}
    if spec is True:
        return out
    if isinstance(spec, dict):
        unknown = sorted(set(spec) - set(out))
        if unknown:
            raise ValueError(
                f"surrogate_mesh keys {unknown} not understood; "
                f"expected a subset of {sorted(out)}"
            )
        out.update(spec)
        out["min_points"] = int(out["min_points"])
        if out["tile"] is not None:
            out["tile"] = int(out["tile"])
        return out
    raise TypeError(
        f"surrogate_mesh must be None, bool, or dict; got {type(spec)!r}"
    )


class SurrogateMixin:
    """Shared surrogate wrapper surface: unit-box x normalization and the
    reference's ``predict``/``evaluate`` contract on top of a jax-traceable
    ``predict_normalized`` (shared by the exact-GP and SVGP families)."""

    _dtype = jnp.float32  # overridden per instance by dtype="float64"

    def normalize_x(self, xin):
        dt = self._dtype
        return (jnp.asarray(xin, dt) - self.xlb.astype(dt)) / self.xrg.astype(dt)

    def predict(self, xin):
        x = jnp.atleast_2d(jnp.asarray(xin, self._dtype))
        return self.predict_normalized(self.normalize_x(x))

    def evaluate(self, x):
        mean, var = self.predict(x)
        if self.return_mean_variance:
            return mean, var
        return mean

    def get_stats(self):
        """Fit-result summary (final loss, optimizer steps, early-stop)
        for epoch stats and the telemetry `train` phase event."""
        return dict(getattr(self, "fit_info", None) or {})


class GPR_Matern(SurrogateMixin):
    """Independent exact GP per objective, Matérn-5/2 kernel.

    API-compatible with reference ``GPR_Matern`` (model.py:1182-1275);
    hyperparameters from batched multi-start Adam instead of SCE-UA.

    ``dtype="float64"`` reproduces the reference's float64 sklearn
    numerics (no relative jitter; reference noise floor 1e-9) at the
    cost of enabling the global jax x64 mode — use on CPU or when
    surrogate precision near the noise floor matters more than MXU
    throughput. ``rel_jitter`` overrides the dtype default
    (see `_default_rel_jitter`).
    """

    kernel = "matern52"
    anisotropic_default = False

    def __init__(
        self,
        xin,
        yin,
        nInput: int,
        nOutput: int,
        xlb,
        xub,
        optimizer: str = "adam",
        seed=None,
        length_scale_bounds=(1e-3, 100.0),
        constant_kernel_bounds=(1e-4, 1e3),
        noise_level_bounds=(1e-9, 1e-2),
        anisotropic: Optional[bool] = None,
        return_mean_variance: bool = False,
        nan: Optional[str] = "remove",
        top_k: Optional[int] = None,
        n_starts: int = 8,
        n_iter: int = 200,
        learning_rate: float = 0.1,
        dtype="float32",
        rel_jitter: Optional[float] = None,
        convergence_tol="auto",
        convergence_check_every: Optional[int] = None,
        warm_start=None,
        predictor: str = "solve",
        nystrom_points: int = 512,
        nystrom_probe_points: int = 256,
        nystrom_mean_tol: float = 0.1,
        nystrom_var_ratio_tol: float = 3.0,
        mesh=None,
        surrogate_mesh=None,
        logger=None,
        **kwargs,
    ):
        self.return_mean_variance = return_mean_variance
        self.logger = logger
        self._dtype = dt = _resolve_dtype(dtype)
        self._predictor_spec = _resolve_predictor_spec(
            predictor, nystrom_points, nystrom_probe_points,
            nystrom_mean_tol, nystrom_var_ratio_tol,
        )
        self._mesh = mesh
        self._shard_spec = _resolve_surrogate_mesh_spec(surrogate_mesh)
        self._predictor_obj = None
        X, Yn, y_mean, y_std = _prepare_training_data(
            self, xin, yin, nInput, nOutput, xlb, xub, nan, top_k
        )
        n_real = X.shape[0]

        if anisotropic is None:
            anisotropic = self.anisotropic_default
        key = as_key(seed)
        X, Yn, tmask = _pad_to_bucket(X, Yn)
        if rel_jitter is None:
            rel_jitter = _default_rel_jitter(dt)
        self._rel_jitter = rel_jitter
        ws = None
        if warm_start is not None:
            # (amp, ls, noise) from a previous converged fit of the same
            # configuration (see fit_gp_batch's warm_start contract)
            w_amp, w_ls, w_noise = warm_start
            Lls = int(nInput) if anisotropic else 1
            w_ls = np.asarray(w_ls, dtype=np.float64)
            if w_ls.shape != (int(nOutput), Lls):
                raise ValueError(
                    f"warm_start lengthscales have shape {w_ls.shape}; "
                    f"this fit expects {(int(nOutput), Lls)} "
                    f"(anisotropic={bool(anisotropic)})"
                )
            ws = (
                jnp.asarray(w_amp, dt),
                jnp.asarray(w_ls, dt),
                jnp.asarray(w_noise, dt),
            )
        common = dict(
            lengthscale_bounds=tuple(length_scale_bounds),
            amplitude_bounds=tuple(constant_kernel_bounds),
            noise_bounds=tuple(noise_level_bounds),
            kernel=self.kernel,
            n_starts=n_starts,
            n_iter=n_iter,
            learning_rate=learning_rate,
            ard=bool(anisotropic),
            rel_jitter=rel_jitter,
            convergence_tol=convergence_tol,
            convergence_check_every=convergence_check_every,
            warm_start=ws,
        )
        fit = shard_info = None
        if self._shard_spec is not None and mesh is not None:
            fit, shard_info = self._try_fit_sharded(
                key, X, Yn, tmask, n_real, mesh, common
            )
        if fit is None:
            fit = fit_gp_batch(
                key,
                jnp.asarray(X, dt),
                jnp.asarray(Yn, dt),
                train_mask=jnp.asarray(tmask, dt),
                mesh=mesh,
                **common,
            )
        self.fit = fit._replace(
            y_mean=jnp.asarray(y_mean, dt),
            y_std=jnp.asarray(y_std, dt),
        )
        self.fit_info = _gp_fit_info(fit, n_iter)
        if shard_info:
            self.fit_info.update(shard_info)

    def _try_fit_sharded(self, key, X, Yn, tmask, n_real, mesh, common):
        """Route the hyperparameter fit through the mesh-sharded tiled
        Cholesky (models/gp_sharded.py) when the ``surrogate_mesh`` spec,
        the archive size, and the mesh/bucket shapes all allow it.

        Probe discipline (mirrors the Nyström predictor's gate): a
        sharded fit whose NMLL comes back non-finite is DISCARDED and
        the caller falls back to the single-device fit — the routed
        path may be slower to fail, never worse to serve. Returns
        ``(fit | None, fit_info_extras | None)``."""
        import time as _time

        from dmosopt_tpu.models import gp_sharded

        spec = self._shard_spec
        P = X.shape[0]
        axis = spec["axis"] or mesh.axis_names[0]
        if n_real < spec["min_points"] or not gp_sharded.mesh_compatible(
            mesh, axis, P
        ):
            return None, None
        dt = self._dtype
        tile = spec["tile"]
        if tile is None or tile < 1 or P % tile:
            # never crash the run on a tile that doesn't divide this
            # bucket (archives grow across buckets; a user tile tuned
            # for one bucket must degrade gracefully on the next)
            if tile is not None and self.logger is not None:
                self.logger.warning(
                    f"surrogate_mesh: tile {tile} does not divide the "
                    f"padding bucket {P}; using "
                    f"{gp_sharded.default_chol_tile(P)}"
                )
            tile = gp_sharded.default_chol_tile(P)
        n_devices = int(mesh.shape[axis])
        t0 = _time.perf_counter()
        fit = gp_sharded.fit_gp_sharded(
            key,
            jnp.asarray(X, dt),
            jnp.asarray(Yn, dt),
            train_mask=jnp.asarray(tmask, dt),
            mesh=mesh,
            shard_axis=axis,
            tile=tile,
            **common,
        )
        ok = bool(np.all(np.isfinite(np.asarray(fit.nmll))))
        wall = _time.perf_counter() - t0
        gp_sharded.record_sharded_fit(
            ok, wall, n_devices, tile, n_real, P, int(Yn.shape[1])
        )
        if not ok:
            if self.logger is not None:
                self.logger.warning(
                    f"surrogate_mesh: sharded fit at N={n_real} "
                    f"(bucket {P}, {n_devices} devices) produced a "
                    f"non-finite NMLL; falling back to the "
                    f"single-device fit"
                )
            return None, None
        if self._predictor_spec["mode"] == "solve":
            # the solve predictor never reads W = L⁻¹ — holding the
            # (d, P, P) factor alongside L would double the resident
            # fit memory for nothing at exactly the archive scale this
            # path exists to serve
            fit = fit._replace(whitened=None)
        return fit, {
            "sharded": True,
            "shard_devices": n_devices,
            "shard_tile": tile,
        }

    # jax-traceable prediction on unit-box-normalized input, routed
    # through the per-fit predictor (predictor="solve" — the default —
    # IS the verbatim `gp_predict` program; see models/predictor.py)
    def predict_normalized(self, Xq: jax.Array):
        return self._predictor().predict_normalized(Xq)

    def _predictor(self):
        if self._predictor_obj is None:
            from dmosopt_tpu.models.predictor import GPPredictor

            self._predictor_obj = GPPredictor(
                self.fit, self.kernel, mesh=self._mesh,
                rel_jitter=getattr(self, "_rel_jitter", None),
                **self._predictor_spec,
            )
            if (
                self._predictor_obj.regime == "nystrom"
                and getattr(self.fit, "whitened", None) is not None
            ):
                # a sharded fit's W = L⁻¹ was held only as the
                # distillation-probe-failure matmul fallback; the probe
                # passed, so release the (d, P, P) factor instead of
                # keeping dead cache resident all epoch
                self.fit = self.fit._replace(whitened=None)
                self._predictor_obj.fit = self.fit
        return self._predictor_obj

    def build_predictor(self):
        """Build (or return) the per-fit predictive cache eagerly — the
        per-epoch build `moasmo.train` triggers so the O(N³) cache
        preparation lands inside the timed `train` phase instead of the
        first EA generation."""
        return self._predictor()

    @property
    def predictor_regime(self) -> str:
        """Regime actually serving predictions (the requested mode, or
        `matmul` after a nystrom distillation-probe fallback)."""
        if self._predictor_obj is not None:
            return self._predictor_obj.regime
        return self._predictor_spec["mode"]


class GPR_RBF(GPR_Matern):
    """RBF-kernel variant (reference model.py:1278-1325)."""

    kernel = "rbf"


class EGP_Matern(GPR_Matern):
    """Exact GP with ARD lengthscales + Adam, the analog of the reference's
    GPyTorch path (model_gpytorch.py:1929-2167). On TPU the exact-GP math is
    identical to GPR_Matern; ARD-by-default and more Adam steps mirror the
    GPyTorch configuration."""

    anisotropic_default = True

    def __init__(self, *args, n_iter: int = 300, **kwargs):
        # reference knob name (model_gpytorch.py:1942 ``adam_lr``)
        if "adam_lr" in kwargs:
            kwargs.setdefault("learning_rate", float(kwargs.pop("adam_lr")))
        super().__init__(*args, n_iter=n_iter, **kwargs)


class MEGP_Matern(SurrogateMixin):
    """Multi-output exact GP fit jointly: one shared ARD kernel for all
    objectives, hyperparameters optimized on the SUM of per-objective exact
    MLLs via ``fit_gp_shared``. Capability analog of the reference's
    multitask GP (model_gpytorch.py:1623-1926), re-designed: instead of a
    Kronecker task covariance (hostile to static-shape batching), objectives
    share kernel hyperparameters — the coupling the reference's default
    rank-1 task matrix mostly captures — and keep independent posteriors, so
    predict is the same batched triangular solve as GPR.
    """

    kernel = "matern52"

    def __init__(
        self,
        xin,
        yin,
        nInput,
        nOutput,
        xlb,
        xub,
        seed=None,
        length_scale_bounds=(1e-3, 100.0),
        constant_kernel_bounds=(1e-4, 1e3),
        noise_level_bounds=(1e-9, 1e-2),
        return_mean_variance: bool = False,
        nan: Optional[str] = "remove",
        top_k: Optional[int] = None,
        n_starts: int = 8,
        n_iter: int = 300,
        learning_rate: float = 0.1,
        convergence_tol="auto",
        convergence_check_every: Optional[int] = None,
        predictor: str = "solve",
        nystrom_points: int = 512,
        nystrom_probe_points: int = 256,
        nystrom_mean_tol: float = 0.1,
        nystrom_var_ratio_tol: float = 3.0,
        logger=None,
        **kwargs,
    ):
        self.return_mean_variance = return_mean_variance
        self.logger = logger
        self._predictor_spec = _resolve_predictor_spec(
            predictor, nystrom_points, nystrom_probe_points,
            nystrom_mean_tol, nystrom_var_ratio_tol,
        )
        self._mesh = None
        self._predictor_obj = None
        X, Yn, y_mean, y_std = _prepare_training_data(
            self, xin, yin, nInput, nOutput, xlb, xub, nan, top_k
        )

        X, Yn, tmask = _pad_to_bucket(X, Yn)
        fit = fit_gp_shared(
            as_key(seed),
            jnp.asarray(X, jnp.float32),
            jnp.asarray(Yn, jnp.float32),
            train_mask=jnp.asarray(tmask, jnp.float32),
            lengthscale_bounds=tuple(length_scale_bounds),
            amplitude_bounds=tuple(constant_kernel_bounds),
            noise_bounds=tuple(noise_level_bounds),
            kernel=self.kernel,
            n_starts=n_starts,
            n_iter=n_iter,
            learning_rate=learning_rate,
            convergence_tol=convergence_tol,
            convergence_check_every=convergence_check_every,
        )
        self.fit = fit._replace(
            y_mean=jnp.asarray(y_mean, jnp.float32),
            y_std=jnp.asarray(y_std, jnp.float32),
        )
        self.fit_info = _gp_fit_info(fit, n_iter)

    predict_normalized = GPR_Matern.predict_normalized
    _predictor = GPR_Matern._predictor
    build_predictor = GPR_Matern.build_predictor
    predictor_regime = GPR_Matern.predictor_regime
