"""Problem-batched multi-tenant core: one compiled program per bucket.

The driver already multiplexes problems, but host-level: N tenants pay
N GP fits, N inner-EA scans, N Python epoch loops. This module lifts the
*problem* axis into the compiled programs themselves (the tensorized-EMO
thesis of PAPERS.md applied across optimizations, ROADMAP item 1):

- tenants are **bucketed** by (optimizer, dim, n_obj, popsize, GP fit
  config) — everything that decides compiled shapes and static
  hyperparameters;
- each bucket's surrogate fit runs as ONE Adam loop with a leading
  problems axis (`models.gp.fit_gp_problems`): per-tenant training sets
  are padded to a common `_bucket_size` capacity with masked rows, the
  same discipline `_pad_to_bucket` uses within one tenant;
- each bucket's inner EA runs as ONE `lax.scan` of a `vmap`-ped
  generate -> surrogate-predict -> update step over stacked optimizer
  states, with per-tenant PRNG key streams identical to the streams the
  sequential path would have drawn;
- tenants whose epoch phases differ (fewer generations left, joined
  late) coexist in a bucket through **inactive rows**: a per-generation
  (G, T) active mask freezes a finished tenant's state with `where`
  while the bucket program keeps its static shape.

Routing discipline (the PR 3/5/6 regime-split rule): buckets smaller
than ``min_bucket`` (default 2) — in particular every single-tenant run
— take the UNCHANGED sequential `DistOptStrategy.initialize_epoch`
path, which stays bitwise-pinned. Tenants whose configuration the
batched core does not cover (cycled optimizers, termination criteria,
refit controllers, mean-variance mode, adaptive populations, non-GPR
surrogates, meshes) fall back the same way, per tenant.

Per-tenant host randomness (``local_random`` draws, ``generate_initial``
sampling) is consumed in tenant order *before* any bucket runs, so the
shared generator advances through the identical sequence of draws the
sequential loop performs — per-tenant key streams match the sequential
path exactly; only batched-kernel reduction order differs.
"""

from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass, field
from types import SimpleNamespace
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from dmosopt_tpu.telemetry import span_scope
from dmosopt_tpu.telemetry.device_ledger import (
    compiled_cost_estimates,
    compiled_memory_bytes,
)

from dmosopt_tpu.config import resolve, default_optimizers
from dmosopt_tpu.models import Model
from dmosopt_tpu.models.gp import (
    _bucket_size,
    _default_rel_jitter,
    _pad_to_bucket,
    _prepare_training_data,
    fit_gp_problems,
    gp_predict_problems,
)
from dmosopt_tpu.moasmo import (
    LARGE_N_THRESHOLD,
    _feasible_subset,
    get_duplicates,
    remove_duplicates,
)
from dmosopt_tpu.ops import crowding_distance
from dmosopt_tpu.utils.prng import as_key

# Optimizers whose pure-function triple is known scannable AND
# vmappable over stacked states (static shapes, no host-side state).
_BATCHABLE_OPTIMIZERS = ("nsga2", "age")

# GPR_Matern kwargs the batched fit understands; anything else routes
# the tenant to the sequential path rather than silently dropping it.
_KNOWN_GP_KWARGS = frozenset({
    "seed", "n_starts", "n_iter", "learning_rate",
    "length_scale_bounds", "constant_kernel_bounds", "noise_level_bounds",
    "anisotropic", "nan", "top_k", "rel_jitter",
    "convergence_tol", "convergence_check_every",
    "predictor", "dtype", "large_n_threshold",
})


def bucket_label(dim: int, n_obj: int, pop: int) -> str:
    """Low-cardinality telemetry label for a bucket shape — the
    per-bucket aggregation axis that replaces per-tenant label values
    (64-256 tenants would explode every labeled series)."""
    return f"d{dim}_o{n_obj}_p{pop}"


# ------------------------------------------------------------- eligibility


def batch_eligibility(strat) -> Optional[str]:
    """None when `strat` can join a bucket this epoch; otherwise a short
    reason string (diagnostics + telemetry). The full check = the two
    archive-dependent gates (empty archive, dense-kernel threshold)
    around `_static_eligibility`'s configuration-only gates."""
    if strat.x is None:
        return "empty archive"
    reason = _static_eligibility(strat)
    if reason is not None:
        return reason
    kwargs = strat.surrogate_method_kwargs or {}
    threshold = kwargs.get("large_n_threshold", LARGE_N_THRESHOLD)
    if threshold and strat.x.shape[0] > threshold:
        return "archive beyond dense-kernel threshold"
    return None


def _static_eligibility(strat) -> Optional[str]:
    """The archive-INDEPENDENT part of `batch_eligibility`: every gate
    decidable from the tenant's static configuration alone."""
    if len(strat.optimizer_name) != 1:
        return "cycled optimizers"
    name = strat.optimizer_name[0]
    if not isinstance(name, str) or name not in _BATCHABLE_OPTIMIZERS:
        return f"optimizer {name!r} not batchable"
    if strat.surrogate_method_name != "gpr":
        return f"surrogate {strat.surrogate_method_name!r} not batchable"
    if strat.surrogate_custom_training is not None:
        return "custom surrogate training"
    if strat.sensitivity_method_name is not None:
        return "sensitivity analysis"
    if strat.feasibility_method_name is not None:
        return "feasibility model"
    if strat.optimize_mean_variance:
        return "mean-variance mode"
    if strat.termination is not None:
        return "termination criterion"
    if getattr(strat, "refit_controller", None) is not None:
        return "surrogate refit controller"
    if strat.mesh is not None:
        return "mesh"
    if strat.distance_metric is not None:
        return "distance metric override"
    if int(strat.num_generations) < 1:
        return "num_generations < 1"
    kwargs = strat.surrogate_method_kwargs or {}
    unknown = sorted(set(kwargs) - _KNOWN_GP_KWARGS)
    if unknown:
        return f"surrogate kwargs {unknown} not batchable"
    if kwargs.get("predictor", "solve") != "solve":
        return "non-solve predictor"
    if str(kwargs.get("dtype", "float32")) != "float32":
        return "non-float32 surrogate dtype"
    okw = strat.optimizer_kwargs[0] or {}
    if okw.get("adaptive_population_size"):
        return "adaptive population size"
    if "distance_metric" in okw:
        return "distance metric override"
    return None


def static_bucket_signature(strat) -> Optional[Tuple]:
    """The tenant's bucket signature from static configuration alone,
    or None when the static gates already rule the tenant out.

    `bucket_signature` depends only on static config (shapes, fit
    config, optimizer kwargs — never the archive), so statically
    eligible tenants can be grouped into PROVISIONAL buckets before
    their evaluations drain: the task-graph service step uses this to
    build one bucket node per group, and the full `batch_eligibility`
    recheck inside `initialize_epochs_batched` (pass 1) re-routes any
    member whose ARCHIVE disqualifies it (still empty, or past the
    dense-kernel threshold) to the sequential path — reproducing
    lockstep bucket membership exactly, since the archive gates are
    the only checks this signature skips."""
    if _static_eligibility(strat) is not None:
        return None
    return bucket_signature(
        strat, strat.optimizer_name[0], strat.optimizer_kwargs[0]
    )


def _fit_config(strat) -> Dict[str, Any]:
    """The `fit_gp_batch` static configuration the sequential
    GPR_Matern constructor would build from this strategy's surrogate
    kwargs (see models/gp.py GPR_Matern.__init__)."""
    kw = strat.surrogate_method_kwargs or {}
    anisotropic = kw.get("anisotropic")
    if anisotropic is None:
        anisotropic = False  # GPR_Matern.anisotropic_default
    rel_jitter = kw.get("rel_jitter")
    if rel_jitter is None:
        rel_jitter = _default_rel_jitter(jnp.float32)
    return dict(
        lengthscale_bounds=tuple(kw.get("length_scale_bounds", (1e-3, 100.0))),
        amplitude_bounds=tuple(kw.get("constant_kernel_bounds", (1e-4, 1e3))),
        noise_bounds=tuple(kw.get("noise_level_bounds", (1e-9, 1e-2))),
        kernel="matern52",
        n_starts=int(kw.get("n_starts", 8)),
        n_iter=int(kw.get("n_iter", 200)),
        learning_rate=float(kw.get("learning_rate", 0.1)),
        ard=bool(anisotropic),
        rel_jitter=rel_jitter,
        convergence_tol=kw.get("convergence_tol", "auto"),
        convergence_check_every=kw.get("convergence_check_every"),
    )


def bucket_signature(strat, optimizer_name: str, okw: Dict) -> Tuple:
    """Hashable key grouping tenants that may share one compiled
    program: compiled shapes (dim, n_obj, popsize) plus every static
    hyperparameter baked into the traced step or the fit."""
    fitcfg = tuple(sorted((k, repr(v)) for k, v in _fit_config(strat).items()))
    okw_key = tuple(sorted((k, repr(v)) for k, v in (okw or {}).items()))
    return (
        optimizer_name, int(strat.prob.dim), int(strat.prob.n_objectives),
        int(strat.population_size), fitcfg, okw_key,
    )


# ------------------------------------------------------------------ plans


@dataclass
class _TenantPlan:
    """One tenant's host-side epoch preparation: everything the bucket
    run needs, with this tenant's share of the shared RNG already
    consumed (in tenant order, mirroring the sequential path)."""

    pid: Any
    strat: Any
    optimizer: Any  # per-tenant optimizer instance (host bookkeeping)
    n_resample: int
    num_generations: int
    # EA seed population: feasible archive rows + generated design
    x0: np.ndarray  # feasible archive x (float32)
    y0: np.ndarray  # feasible archive y (float32)
    x_init: np.ndarray  # generate_initial sample (popsize, n) float32
    # surrogate training data (tenant bucket padding applied later)
    X_unit: np.ndarray  # (N_t, n) unit box, float64
    Yn: np.ndarray  # (N_t, d) standardized, float64
    y_mean: np.ndarray
    y_std: np.ndarray
    xlb32: np.ndarray  # (n,) float32 — predict-time normalization
    xrg32: np.ndarray
    bounds: np.ndarray  # (n, 2) float32
    fit_key: jax.Array
    init_key: jax.Array  # initialize_state key
    loop_key: jax.Array  # generation-loop key (pre-split per generation)
    stats: Dict[str, Any] = field(default_factory=dict)


def _build_plan(pid, strat, optimizer_name: str, okw: Dict) -> _TenantPlan:
    """Host-side per-tenant epoch prep, consuming `strat.local_random`
    through the SAME sequence of draws `moasmo.epoch` -> `optimize`
    performs: optimize's loop key, `generate_initial`'s numpy draws,
    `initialize_strategy`'s key — so per-tenant device key streams are
    identical to the sequential path's."""
    prob = strat.prob
    pop = int(strat.population_size)
    stats: Dict[str, Any] = {"model_init_start": time.time()}

    # --- training data (moasmo.train: feasible subset, dedupe, prep)
    x = np.asarray(strat.x).copy()
    y = np.asarray(strat.y).copy()
    _, (x, y) = _feasible_subset(strat.c, x, y)
    x, y = remove_duplicates(x, y)
    kw = strat.surrogate_method_kwargs or {}
    holder = SimpleNamespace()
    X_unit, Yn, y_mean, y_std = _prepare_training_data(
        holder, x, y, prob.dim, prob.n_objectives, prob.lb, prob.ub,
        kw.get("nan", "remove"), kw.get("top_k"),
    )
    fit_key = as_key(kw.get("seed"))

    # --- EA seed (moasmo.epoch lines: x_0/y_0 feasible subset)
    x0 = np.asarray(strat.x, dtype=np.float32).copy()
    y0 = np.asarray(strat.y, dtype=np.float32).copy()
    _, (x0, y0) = _feasible_subset(strat.c, x0, y0)

    # --- optimizer instance (moasmo.epoch's constructor spec)
    okw_merged: Dict[str, Any] = {
        "sampling_method": "slh", "mutation_rate": None, "nchildren": 1,
    }
    okw_merged.update(okw or {})
    optimizer_cls = resolve(optimizer_name, default_optimizers)
    mdl = Model(return_mean_variance=False)
    optimizer = optimizer_cls(
        nInput=prob.dim, nOutput=prob.n_objectives, popsize=pop,
        model=mdl, distance_metric=None, optimize_mean_variance=False,
        **okw_merged,
    )

    # --- shared-RNG draws, in the sequential path's exact order
    bounds = np.column_stack(
        (np.asarray(prob.lb), np.asarray(prob.ub))
    )
    key_opt = as_key(strat.local_random)  # optimize(): loop key
    x_init = np.asarray(
        optimizer.generate_initial(bounds, strat.local_random),
        dtype=np.float32,
    )
    key_strat = as_key(strat.local_random)  # initialize_strategy's key
    optimizer.key, init_key = jax.random.split(key_strat)
    optimizer.bounds = jnp.asarray(bounds, dtype=jnp.float32)
    _, loop_key = jax.random.split(key_opt)

    stats["model_init_end"] = time.time()
    return _TenantPlan(
        pid=pid, strat=strat, optimizer=optimizer,
        n_resample=int(pop * strat.resample_fraction),
        num_generations=int(strat.num_generations),
        x0=x0, y0=y0, x_init=x_init,
        X_unit=X_unit, Yn=Yn, y_mean=y_mean, y_std=y_std,
        xlb32=np.asarray(holder.xlb, np.float32),
        xrg32=np.asarray(holder.xrg, np.float32),
        bounds=np.asarray(bounds, np.float32),
        fit_key=fit_key, init_key=init_key, loop_key=loop_key,
        stats=stats,
    )


# ------------------------------------------------------------- bucket run

# Sub-chunk width of one bucket's surrogate fit. Independent per-problem
# Adam trajectories mean any split along the problems axis is
# result-identical (each tenant's fit equals its standalone
# `fit_gp_batch` either way); splitting lets chunks execute
# CONCURRENTLY from host threads — the CPU backend runs a batched
# Cholesky's batch dimension serially inside one execution, so one
# (64, ...) program is no faster than 64 sequential fits there, while 8
# threaded (8, ...) executions overlap across cores (measured 11x at
# T=64). On an accelerator the chunks pipeline through the device queue
# — same results, no penalty.
FIT_CHUNK = 8


def _fit_bucket(keys, Xs, Yns, masks, fitcfg):
    """One bucket's surrogate fit across the problems axis, dispatched
    as FIT_CHUNK-wide `fit_gp_problems` calls from a thread pool and
    re-concatenated. T <= FIT_CHUNK stays a single call."""
    T = int(Xs.shape[0])
    if T <= FIT_CHUNK:
        return fit_gp_problems(keys, Xs, Yns, masks, **fitcfg)
    import os
    from concurrent.futures import ThreadPoolExecutor

    spans = [(i, min(i + FIT_CHUNK, T)) for i in range(0, T, FIT_CHUNK)]

    def one(span):
        i, j = span
        return fit_gp_problems(
            keys[i:j], Xs[i:j], Yns[i:j], masks[i:j], **fitcfg
        )

    n_workers = min(len(spans), max(os.cpu_count() or 1, 1))
    with ThreadPoolExecutor(max_workers=n_workers) as pool:
        parts = list(pool.map(one, spans))
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.concatenate(leaves, axis=0), *parts
    )


def _stack_tree(trees):
    """Stack a list of identically-shaped pytrees along a new leading
    (tenants) axis."""
    return jax.tree_util.tree_map(lambda *leaves: jnp.stack(leaves), *trees)


def _slice_tree(tree, i):
    return jax.tree_util.tree_map(lambda a: a[i], tree)


# One compiled generation-loop program per (bucket signature, tenant
# count), reused across epochs and runs: the fit, normalization
# constants, states, keys and active mask are all ARGUMENTS, so the
# closure carries only the bucket's static configuration (the tracer
# optimizer and kernel name). Rebuilding the jit per epoch — the
# sequential path's per-optimize() cost — re-paid a multi-second
# trace+compile per bucket per epoch at T=64. FIFO-bounded: a
# long-lived service whose bucket populations fluctuate (a new (sig, T)
# per join/finish) must not pin compiled programs forever.
#
# Each entry is a `_BucketProgram`: the traced function plus explicitly
# AOT-compiled executables keyed by the argument shapes/dtypes. Going
# through `fn.lower(...).compile()` instead of jit's implicit dispatch
# makes every compile OBSERVABLE — wall seconds, XLA cost-analysis
# FLOPs/bytes, and (the retrace detector) a warning event whenever a
# (signature, T) key that already had an executable compiles again:
# shape drift (a training cap crossing a `_bucket_size` boundary, a
# changed generation budget) is exactly the silent multi-second stall
# the cache exists to prevent.
_PROGRAM_CACHE: Dict[Tuple, "_BucketProgram"] = {}
_PROGRAM_CACHE_MAX = 64
# guards the cache dict itself (lookup/insert/evict): the task-graph
# scheduler runs DIFFERENT buckets' epochs from concurrent nodes, and a
# concurrent insert+evict on a plain dict can drop a just-inserted
# program. Distinct buckets have distinct (sig, T) keys, so per-program
# state (`_BucketProgram.executables`) stays single-threaded; only the
# shared dict needs the lock, and nothing blocking runs under it —
# tracing/compiling happens outside.
_PROGRAM_CACHE_LOCK = threading.Lock()


class _BucketProgram:
    __slots__ = ("fn", "executables")

    def __init__(self, fn):
        self.fn = fn
        self.executables: Dict[Tuple, Any] = {}


def _sig_label(sig: Tuple) -> str:
    """Low-cardinality, human-greppable label for a bucket signature:
    the shape prefix plus a short hash of the full static config."""
    digest = hashlib.sha256(repr(sig).encode()).hexdigest()[:8]
    if len(sig) >= 4:
        return f"{sig[0]}_d{sig[1]}_o{sig[2]}_p{sig[3]}_{digest}"
    return digest


def _bucket_program(sig: Tuple, optimizer, kernel: str, T: int) -> "_BucketProgram":
    key = (sig, T)
    with _PROGRAM_CACHE_LOCK:
        prog = _PROGRAM_CACHE.get(key)
        if prog is not None:
            return prog
        while len(_PROGRAM_CACHE) >= _PROGRAM_CACHE_MAX:
            _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))

    @jax.jit
    def run_chunk(fit, xlb, xrg, states, keys, active):  # graftlint: disable=retrace-hazard -- cached in _PROGRAM_CACHE keyed by (bucket signature, T); the closure holds only static bucket config, all per-epoch state is arguments
        def batched_eval(x):  # (T, B, n) -> (T, B, d) surrogate means
            xq = (x - xlb[:, None, :]) / xrg[:, None, :]
            mean, _ = gp_predict_problems(fit, xq, kernel=kernel)
            return mean

        def gen_one(k, s):
            x_gen, s = optimizer.generate_strategy(k, s)
            return jnp.clip(x_gen, s.bounds[:, 0], s.bounds[:, 1]), s

        def step(states, inp):
            keys_t, act = inp
            x_gen, new_states = jax.vmap(gen_one)(keys_t, states)
            y_gen = batched_eval(x_gen)
            new_states = jax.vmap(optimizer.update_strategy)(
                new_states, x_gen, y_gen
            )
            # inactive rows: tenants past their generation budget keep
            # their state frozen while the program keeps its shape
            states = jax.tree_util.tree_map(
                lambda new, old: jnp.where(
                    act.reshape((T,) + (1,) * (new.ndim - 1)), new, old
                ),
                new_states, states,
            )
            return states, (x_gen, y_gen)

        return jax.lax.scan(step, states, (keys, active))

    prog = _BucketProgram(run_chunk)
    with _PROGRAM_CACHE_LOCK:
        # first writer wins on a racing double-build of the same key:
        # both closures trace identical programs, so returning the
        # existing entry keeps the retrace detector's bookkeeping on
        # one object
        existing = _PROGRAM_CACHE.get(key)
        if existing is not None:
            return existing
        _PROGRAM_CACHE[key] = prog
    return prog


def _run_bucket_program(
    prog: "_BucketProgram", sig: Tuple, T: int, args: Tuple,
    telemetry=None, logger=None, label: Optional[str] = None,
):
    """Execute the bucket's generation-loop program for these argument
    shapes, compiling (observably) when the shape is new. Returns
    (result, compile_seconds)."""
    shape_key = tuple(
        (tuple(leaf.shape), str(leaf.dtype))
        for leaf in jax.tree_util.tree_leaves(args)
    )
    compiled = prog.executables.get(shape_key)
    if compiled is not None:
        return compiled(*args), 0.0
    retrace = bool(prog.executables)
    t0 = time.perf_counter()
    compiled = prog.fn.lower(*args).compile()
    compile_s = time.perf_counter() - t0
    prog.executables[shape_key] = compiled
    sig_label = _sig_label(sig)
    if telemetry:
        flops, nbytes = compiled_cost_estimates(compiled)
        memory_bytes = compiled_memory_bytes(compiled)
        if telemetry.ledger is not None:
            # device-time ledger row: the bucket program executes under
            # the `ea_scan` span/annotation, so a later profiler capture
            # joins its device events to this compile-side row
            telemetry.ledger.record_compile(
                "ea_scan", compile_s, flops=flops, bytes_accessed=nbytes,
                memory_bytes=memory_bytes, bucket=label, retrace=retrace,
            )
        telemetry.inc("tenant_bucket_compiles_total", bucket=label)
        telemetry.event(
            "bucket_compile", bucket=label, signature=sig_label,
            n_tenants=T, compile_s=round(compile_s, 4),
            flops=flops, bytes_accessed=nbytes,
            memory_bytes=memory_bytes, retrace=retrace,
        )
        if retrace:
            telemetry.inc("tenant_bucket_retraces_total", bucket=label)
            telemetry.event(
                "bucket_retrace", bucket=label, signature=sig_label,
                n_tenants=T, compile_s=round(compile_s, 4),
                n_shapes=len(prog.executables),
            )
    if retrace and logger is not None:
        logger.warning(
            f"tenant bucket {sig_label} (T={T}) RECOMPILED for new "
            f"argument shapes ({len(prog.executables)} executables now "
            f"cached, {compile_s:.2f}s) — shape drift across epochs "
            f"re-pays the compile the program cache exists to avoid"
        )
    return compiled(*args), compile_s


def run_bucket_epoch(
    plans: List[_TenantPlan], sig: Tuple = (), telemetry=None, logger=None
):
    """Advance every tenant in one bucket by one epoch: one batched GP
    fit, one scanned+vmapped inner-EA program (compiled once per
    (bucket signature, tenant count), reused across epochs), then
    per-tenant host-side resample selection. Returns {pid: result dict}
    with exactly the surrogate-mode `moasmo.epoch` result shape."""
    T = len(plans)
    d = plans[0].Yn.shape[1]
    n = plans[0].X_unit.shape[1]
    pop = int(plans[0].optimizer.popsize)
    fitcfg = _fit_config(plans[0].strat)
    G_max = max(p.num_generations for p in plans)
    label = bucket_label(n, d, pop)

    # ---- batched surrogate fit: common bucket capacity, masked rows
    t_fit0 = time.perf_counter()
    cap = max(_bucket_size(p.X_unit.shape[0]) for p in plans)
    with span_scope(telemetry, "gp_fit", bucket=label, n_tenants=T) as fit_span:
        Xs, Yns, masks = [], [], []
        for p in plans:
            Xp, Yp, m = _pad_to_bucket(p.X_unit, p.Yn, cap=cap)
            Xs.append(jnp.asarray(Xp, jnp.float32))
            Yns.append(jnp.asarray(Yp, jnp.float32))
            masks.append(jnp.asarray(m, jnp.float32))
        keys = jnp.stack([p.fit_key for p in plans])
        Xs, Yns, masks = jnp.stack(Xs), jnp.stack(Yns), jnp.stack(masks)
        fit = _fit_bucket(keys, Xs, Yns, masks, fitcfg)
        fit = fit._replace(
            y_mean=jnp.asarray(np.stack([p.y_mean for p in plans]), jnp.float32),
            y_std=jnp.asarray(np.stack([p.y_std for p in plans]), jnp.float32),
        )
        jax.block_until_ready(fit.nmll)
    fit_wall = time.perf_counter() - t_fit0
    # per-tenant fit summaries, the `stats["objective"]` entry the
    # sequential epoch records via mdl.get_stats() (see _gp_fit_info)
    nmll_all = np.asarray(fit.nmll, dtype=np.float64)
    steps_all = (
        np.asarray(fit.n_steps) if fit.n_steps is not None else None
    )
    n_iter_max = int(fitcfg["n_iter"])
    for t, p in enumerate(plans):
        n_steps = (
            int(steps_all[t]) if steps_all is not None else n_iter_max
        )
        p.stats["objective"] = {
            "loss": float(np.mean(nmll_all[t])),
            "nmll_per_objective": [float(v) for v in nmll_all[t]],
            "n_steps": n_steps,
            "n_iter_max": n_iter_max,
            "early_stopped": n_steps < n_iter_max,
        }

    # ---- per-tenant normalization constants for predict
    xlb = jnp.asarray(np.stack([p.xlb32 for p in plans]))  # (T, n)
    xrg = jnp.asarray(np.stack([p.xrg32 for p in plans]))
    bounds = jnp.asarray(np.stack([p.bounds for p in plans]))  # (T, n, 2)
    kernel = fitcfg["kernel"]

    def batched_eval(x):  # (T, B, n) -> (T, B, d) surrogate means
        xq = (x - xlb[:, None, :]) / xrg[:, None, :]
        mean, _ = gp_predict_problems(fit, xq, kernel=kernel)
        return mean

    # ---- initial populations: y for the generated design comes from
    # the freshly fitted surrogates (one batched predict), then each
    # tenant's [archive ; design] rows pad to a common masked capacity
    t_ea0 = time.perf_counter()
    with span_scope(telemetry, "ea_scan", bucket=label, n_tenants=T) as ea_span:
        y_init = np.asarray(
            batched_eval(jnp.asarray(np.stack([p.x_init for p in plans])))
        ).astype(np.float32)
        prog = _bucket_program(sig, plans[0].optimizer, kernel, T)
        n_cat = [p.x0.shape[0] + p.x_init.shape[0] for p in plans]
        P_init = max(n_cat)
        Xcat = np.zeros((T, P_init, n), np.float32)
        Ycat = np.zeros((T, P_init, d), np.float32)
        Mcat = np.zeros((T, P_init), bool)
        for t, p in enumerate(plans):
            xc = np.vstack([p.x0, p.x_init])
            yc = np.vstack([p.y0, y_init[t]])
            Xcat[t, : n_cat[t]] = xc
            Ycat[t, : n_cat[t]] = yc
            Mcat[t, : n_cat[t]] = True

        optimizer = plans[0].optimizer  # bucket tracer: same static config

        def init_one(k, x, y, b, m):
            return optimizer.initialize_state(k, x, y, b, mask=m)

        states = jax.vmap(init_one)(
            jnp.stack([p.init_key for p in plans]),
            jnp.asarray(Xcat), jnp.asarray(Ycat), bounds, jnp.asarray(Mcat),
        )

        # ---- per-tenant generation keys: split(loop_key, G_t) exactly
        # as the sequential scan would, zero-padded to G_max for late
        # phases
        keys = np.zeros((T, G_max, 2), np.uint32)
        active = np.zeros((G_max, T), bool)
        for t, p in enumerate(plans):
            kt = jax.random.split(p.loop_key, p.num_generations)
            keys[t, : p.num_generations] = np.asarray(
                jax.random.key_data(kt)
                if jnp.issubdtype(kt.dtype, jax.dtypes.prng_key)
                else kt
            )
            active[: p.num_generations, t] = True
        keys_scan = jnp.asarray(np.swapaxes(keys, 0, 1))  # (G, T, 2)
        active_scan = jnp.asarray(active)

        (states, (x_traj, y_traj)), compile_s = _run_bucket_program(
            prog, sig, T,
            (fit, xlb, xrg, states, keys_scan, active_scan),
            telemetry=telemetry, logger=logger, label=label,
        )
        x_traj = np.asarray(x_traj)  # (G, T, noff, n)
        y_traj = np.asarray(y_traj)
        # one host materialization of the final states; per-tenant slices
        # below are numpy views, not T x n_leaves device dispatches
        states = jax.tree_util.tree_map(np.asarray, states)
    ea_wall = time.perf_counter() - t_ea0
    noff = x_traj.shape[2]

    # ---- per-tenant cost attribution: the bucket's measured walls,
    # split across its tenants so the shares SUM to the walls exactly.
    # Fit weights are masked-row-aware (each tenant's real training
    # rows, not the common padded cap); EA and compile weights are
    # active-mask-weighted (each tenant's generation budget — staggered
    # late joiners ride frozen rows for the rest). The per-tenant
    # shares land in `stats` (-> strategy stats -> `get_stats`, where
    # the 16-problem guard aggregates them to means), in the
    # `tenant_cost_seconds` counter, and as `tenant_cost` child spans
    # tiling the bucket's gp_fit / ea_scan spans.
    row_total = float(sum(p.X_unit.shape[0] for p in plans)) or float(T)
    gen_total = float(sum(p.num_generations for p in plans)) or float(T)
    ea_exec = max(ea_wall - compile_s, 0.0)
    costs = []
    for p in plans:
        w_fit = p.X_unit.shape[0] / row_total
        w_gen = p.num_generations / gen_total
        costs.append(
            {
                "fit": fit_wall * w_fit,
                "ea": ea_exec * w_gen,
                "compile": compile_s * w_gen,
            }
        )
    for p, c in zip(plans, costs):
        p.stats["cost_fit_seconds"] = c["fit"]
        p.stats["cost_ea_seconds"] = c["ea"]
        p.stats["cost_compile_seconds"] = c["compile"]
    if telemetry:
        for p, c in zip(plans, costs):
            for phase, v in c.items():
                telemetry.inc(
                    "tenant_cost_seconds", v, tenant=str(p.pid), phase=phase
                )
        tracer = telemetry.tracer
        if tracer is not None:
            for parent, phase in ((fit_span, "fit"), (ea_span, "ea")):
                if parent is None or parent.t_end is None:
                    continue
                # the shares sum to fit_wall/ea_wall, clocked over a
                # slightly LARGER interval than the span itself — clamp
                # both ends so the tiling never overruns the parent
                # into a negative-duration slice
                t_cursor = parent.t_start
                for p, c in zip(plans, costs):
                    share = c[phase] + (c["compile"] if phase == "ea" else 0.0)
                    t0 = min(t_cursor, parent.t_end)
                    t_cursor += share
                    tracer.record_span(
                        "tenant_cost", t0, min(t_cursor, parent.t_end),
                        parent=parent, tenant=str(p.pid), phase=phase,
                        bucket=label, seconds=round(share, 6),
                    )

    # ---- per-tenant host tail: flatten trajectories, dedupe, resample
    results = {}
    with span_scope(telemetry, "resample", bucket=label, n_tenants=T):
        for t, p in enumerate(plans):
            G_t = p.num_generations
            x_dev = x_traj[:G_t, t].reshape(-1, n)
            y_dev = y_traj[:G_t, t].reshape(-1, d)
            gen_index = np.concatenate(
                [np.zeros((n_cat[t],), np.uint32)]
                + [
                    np.full((noff,), g + 1, dtype=np.uint32)
                    for g in range(G_t)
                ]
            )
            x_all = np.vstack([Xcat[t, : n_cat[t]], x_dev])
            y_all = np.vstack([Ycat[t, : n_cat[t]], y_dev])

            p.optimizer.state = _slice_tree(states, t)
            best_x, best_y = (
                np.asarray(a) for a in p.optimizer.population_objectives
            )
            is_duplicate = get_duplicates(best_x, p.x0)
            best_x = best_x[~is_duplicate]
            best_y = best_y[~is_duplicate]
            D = np.asarray(crowding_distance(jnp.asarray(best_y)))
            idxr = D.argsort()[::-1][: p.n_resample]
            results[p.pid] = {
                "x_resample": best_x[idxr, :], "y_pred": best_y[idxr, :],
                "gen_index": gen_index, "x_sm": x_all, "y_sm": y_all,
                "optimizer": p.optimizer, "stats": dict(p.stats),
            }

    if telemetry:
        telemetry.inc("tenant_bucket_epochs_total", bucket=label)
        telemetry.inc("tenants_batched_total", T)
        telemetry.gauge("tenant_bucket_size", T, bucket=label)
        telemetry.observe("phase_duration_seconds", fit_wall, phase="train")
        telemetry.observe("phase_duration_seconds", ea_wall, phase="optimize")
        telemetry.event(
            "tenant_bucket", bucket=label, n_tenants=T,
            n_generations=G_max, train_cap=int(cap),
            fit_s=round(fit_wall, 4), ea_s=round(ea_wall, 4),
            gens_per_sec=(
                round(sum(p.num_generations for p in plans) / ea_wall, 3)
                if ea_wall > 0 else None
            ),
        )
    if logger is not None:
        logger.info(
            f"tenant bucket {bucket_label(n, d, pop)}: {T} tenants, "
            f"fit {fit_wall:.3f}s (cap {cap}), EA {ea_wall:.3f}s "
            f"({G_max} gens)"
        )
    return results


# ------------------------------------------------------------ entry point


def initialize_epochs_batched(
    strategies: Dict[Any, Any],
    epoch,
    *,
    min_bucket: int = 2,
    telemetry=None,
    logger=None,
    on_error=None,
):
    """Drive every strategy's epoch initialization, batching bucket-mates
    through one compiled program and routing everyone else through the
    unchanged sequential `initialize_epoch`.

    ``epoch`` is the epoch index shared by every strategy (the driver's
    case), or a ``{pid: epoch_index}`` dict when tenants' epoch phases
    are staggered (the service's case — tenants submitted at different
    times share buckets while keeping their own epoch numbering).

    Pass 1 (no side effects): eligibility + bucket sizing. Pass 2, in
    tenant order: sequential tenants run `initialize_epoch` NOW;
    batched tenants consume their shared-RNG draws NOW (so the global
    draw order matches the sequential loop) and defer device work.
    Then each bucket runs and installs its per-tenant results.
    Returns {pid: "batched" | "sequential" | "failed"} for
    tests/diagnostics.

    ``on_error``: optional ``callable(pid, exception)``. When provided,
    a PER-TENANT failure (a sequential `initialize_epoch` raising, a
    batched tenant's host-side plan build raising) is contained: the
    callback is invoked, the tenant's routing becomes ``"failed"``, and
    every other tenant proceeds — the service's failure-isolation
    contract. When None (the driver's case) such exceptions propagate,
    matching the historical fail-fast behavior.
    """
    epochs = (
        epoch if isinstance(epoch, dict)
        else {pid: epoch for pid in strategies}
    )
    # pass 1: eligibility and bucket membership. Folding completed
    # evaluations first (idempotent — initialize_epoch repeats it as a
    # no-op) lets epoch 0 see the just-drained initial design instead
    # of an empty archive; no randomness is consumed here.
    sigs: Dict[Any, Optional[Tuple]] = {}
    for pid, strat in strategies.items():
        strat._update_evals()
        reason = batch_eligibility(strat)
        if reason is None:
            sigs[pid] = bucket_signature(
                strat, strat.optimizer_name[0], strat.optimizer_kwargs[0]
            )
        else:
            sigs[pid] = None
            if logger is not None:
                logger.info(
                    f"tenant {pid}: sequential path ({reason})"
                )
            if telemetry:
                telemetry.inc("tenants_sequential_total")
    counts: Dict[Tuple, int] = {}
    for sig in sigs.values():
        if sig is not None:
            counts[sig] = counts.get(sig, 0) + 1

    # pass 2: tenant order — sequential inits and batched RNG draws
    # interleave exactly as the sequential loop would consume them
    buckets: Dict[Tuple, List[_TenantPlan]] = {}
    routing: Dict[Any, str] = {}
    for pid, strat in strategies.items():
        sig = sigs[pid]
        if sig is None or counts[sig] < min_bucket:
            try:
                strat.initialize_epoch(epochs[pid])
            except Exception as e:
                if on_error is None:
                    raise
                if logger is not None:
                    logger.exception(
                        f"tenant {pid}: sequential epoch init failed; "
                        f"isolating ({type(e).__name__})"
                    )
                on_error(pid, e)
                routing[pid] = "failed"
                continue
            routing[pid] = "sequential"
            continue
        try:
            name, okw = strat._cycled_optimizer()
            plan = _build_plan(pid, strat, name, okw)
        except Exception as e:
            if on_error is None:
                raise
            if logger is not None:
                logger.exception(
                    f"tenant {pid}: batched epoch plan failed; "
                    f"isolating ({type(e).__name__})"
                )
            on_error(pid, e)
            routing[pid] = "failed"
            continue
        buckets.setdefault(sig, []).append(plan)
        routing[pid] = "batched"

    for sig, plans in buckets.items():
        try:
            results = run_bucket_epoch(
                plans, sig, telemetry=telemetry, logger=logger
            )
        except Exception:
            # robustness over parity on the error path: the shared RNG
            # already advanced, so trajectories differ from a pure
            # sequential run, but every tenant still completes
            if logger is not None:
                logger.exception(
                    f"bucket {sig[:4]} batched epoch failed; falling "
                    f"back to the sequential path for its "
                    f"{len(plans)} tenant(s)"
                )
            for p in plans:
                try:
                    p.strat.initialize_epoch(epochs[p.pid])
                except Exception as e:
                    if on_error is None:
                        raise
                    on_error(p.pid, e)
                    routing[p.pid] = "failed"
                    continue
                routing[p.pid] = "sequential"
            continue
        for p in plans:
            p.strat.install_epoch_result(epochs[p.pid], results[p.pid])
    return routing
