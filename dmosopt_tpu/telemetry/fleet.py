"""Fleet telemetry rollup: cross-run aggregation of persisted telemetry.

Every run (and every service tenant) persists its observability to
HDF5 — per-epoch telemetry summaries (`/{opt_id}/telemetry`), closed
tracing spans (`/{opt_id}/telemetry_spans`), health-alert transitions
(`/{opt_id}/telemetry_alerts`), warm-refit hyperparameter state
(`/{opt_id}/{problem_id}/surrogate_refit`), and streamed fronts
(`/{opt_id}/fronts`). Until this module, **no code read that data
across runs**: each store was a silo. The fleet rollup scans N stores
(plain results stores and service checkpoints alike) into per-run
records, then folds them into **per-problem-signature distributions**
— converged lengthscales / amplitudes / noise floors (linear and
log10), surrogate fit steps, epochs-to-front, gens/sec, quarantine and
alert rates — emitted as one JSON fleet summary.

This is the data substrate ROADMAP item 5's fleet-learned priors will
consume: a new tenant whose problem signature matches the fleet can
warm-start its first GP fit from the signature's log-space
hyperparameter distribution instead of a cold restart grid.

Problem signatures are ``d<dim>_o<nobj>`` — the same axes the tenant
bucketing keys on (`dmosopt_tpu.tenants`), so a fleet prior lookup and
a bucket lookup agree on what "the same kind of problem" means.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional

import numpy as np

from dmosopt_tpu.utils import json_default

#: bumped when the fleet-summary JSON layout changes incompatibly
FLEET_SUMMARY_VERSION = 1

#: refit-state keys carrying positive hyperparameter vectors
_HYPER_KEYS = ("amp", "ls", "noise")


def problem_signature(dim: Optional[int], n_obj: Optional[int]) -> str:
    return f"d{dim if dim is not None else '?'}_o{n_obj if n_obj is not None else '?'}"


def _dist(values: List[float]) -> Optional[Dict[str, Any]]:
    """count/mean/std/min/max/median over finite values (None when
    nothing finite landed)."""
    arr = np.asarray(
        [float(v) for v in values if v is not None], dtype=np.float64
    )
    arr = arr[np.isfinite(arr)]
    if arr.size == 0:
        return None
    return {
        "count": int(arr.size),
        "mean": float(np.mean(arr)),
        "std": float(np.std(arr)),
        "min": float(np.min(arr)),
        "max": float(np.max(arr)),
        "median": float(np.median(arr)),
    }


def _log10_dist(values: List[float]) -> Optional[Dict[str, Any]]:
    pos = [v for v in values if v is not None and v > 0]
    if not pos:
        return None
    return _dist([math.log10(v) for v in pos])


# ------------------------------------------------------------------- scan


def _summaries_rollup(summaries: Dict[int, Dict]) -> Dict[str, Any]:
    """Fold one run's per-epoch telemetry summaries into run totals."""
    out: Dict[str, Any] = {"epochs": len(summaries)}
    wall = gens = fit_steps = evals = n_train = 0.0
    gps: List[float] = []
    losses: List[float] = []
    for s in summaries.values():
        wall += float(s.get("wall_s") or 0.0)
        gens += float(s.get("n_generations") or 0.0)
        fit_steps += float(s.get("fit_n_steps") or 0.0)
        n_train = max(n_train, float(s.get("n_train") or 0.0))
        ev = s.get("eval") or {}
        evals += float(ev.get("eval_n") or 0.0)
        if s.get("gens_per_sec") is not None:
            gps.append(float(s["gens_per_sec"]))
        if s.get("surrogate_loss") is not None:
            losses.append(float(s["surrogate_loss"]))
    out.update(
        wall_s_total=round(wall, 6),
        gens_total=int(gens),
        fit_steps_total=int(fit_steps),
        evals_total=int(evals),
        n_train_max=int(n_train),
        gens_per_sec_mean=(
            round(sum(gps) / len(gps), 3) if gps else None
        ),
        surrogate_loss_last=(losses[-1] if losses else None),
    )
    return out


def _spans_rollup(spans_by_epoch: Dict[int, list]) -> Dict[str, Dict]:
    """{span_name: {count, seconds}} across one run's persisted spans."""
    out: Dict[str, Dict] = {}
    for spans in spans_by_epoch.values():
        for sp in spans:
            name = sp.get("name", "?")
            g = out.setdefault(name, {"count": 0, "seconds": 0.0})
            g["count"] += 1
            g["seconds"] += float(sp.get("duration_s") or 0.0)
    for g in out.values():
        g["seconds"] = round(g["seconds"], 6)
    return out


def _alerts_rollup(alerts_by_epoch: Dict[int, list]) -> Dict[str, int]:
    """{rule: firing-transition count} across one run's persisted
    health alerts."""
    out: Dict[str, int] = {}
    for alerts in alerts_by_epoch.values():
        for a in alerts:
            if a.get("state") == "firing":
                out[a.get("rule", "?")] = out.get(a.get("rule", "?"), 0) + 1
    return out


def _space_dim(space_json: Optional[str]) -> Optional[int]:
    if not space_json:
        return None
    try:
        items = json.loads(space_json)
    except (TypeError, ValueError):
        return None
    if not isinstance(items, list):
        return None
    return sum(1 for it in items if isinstance(it, dict) and "lower" in it)


def _scan_results_store(path: str, h5) -> List[Dict[str, Any]]:
    from dmosopt_tpu.storage import (
        load_alerts_from_h5,
        load_fronts_from_h5,
        load_refit_state_from_h5,
        load_spans_from_h5,
        load_telemetry_from_h5,
    )

    records = []
    for opt_id in h5.keys():
        grp = h5[opt_id]
        if "parameter_space" not in grp.attrs:
            continue  # not a run group
        dim = _space_dim(grp.attrs.get("parameter_space"))
        obj_names = None
        if "objective_names" in grp.attrs:
            try:
                obj_names = json.loads(grp.attrs["objective_names"])
            except (TypeError, ValueError):
                obj_names = None
        n_obj = len(obj_names) if obj_names else None
        problem_ids = (
            [int(i) for i in grp["problem_ids"][:]]
            if "problem_ids" in grp
            else [0]
        )
        summaries = load_telemetry_from_h5(path, opt_id)
        refit: Dict[str, Any] = {}
        for pid in problem_ids:
            state = load_refit_state_from_h5(path, opt_id, pid)
            if state:
                refit[str(pid)] = {
                    k: state[k] for k in _HYPER_KEYS if k in state
                }
                for extra in ("n_train", "n_iter_max"):
                    if extra in state:
                        refit[str(pid)][extra] = state[extra]
        fronts = load_fronts_from_h5(path, opt_id)
        rec = {
            "store": path,
            "opt_id": opt_id,
            "kind": "store",
            "signature": problem_signature(dim, n_obj),
            "dim": dim,
            "n_obj": n_obj,
            "n_problems": len(problem_ids),
            "telemetry": _summaries_rollup(summaries),
            "spans": _spans_rollup(load_spans_from_h5(path, opt_id)),
            "alerts": _alerts_rollup(load_alerts_from_h5(path, opt_id)),
            "refit": refit,
        }
        if fronts:
            epochs = sorted(fronts)
            rec["fronts"] = {
                "n_epochs": len(epochs),
                "first_epoch": int(epochs[0]),
                "last_epoch": int(epochs[-1]),
            }
            rec["epochs_to_front"] = int(epochs[0]) + 1
        records.append(rec)
    return records


def _scan_service_checkpoint(path: str) -> List[Dict[str, Any]]:
    from dmosopt_tpu.storage import load_service_checkpoint_from_h5

    data = load_service_checkpoint_from_h5(path)
    records = []
    for key in sorted(data["tenants"], key=int):
        tp = data["tenants"][key]
        cfg = tp.get("config") or {}
        st = tp.get("state") or {}
        space = cfg.get("space") or {}
        dim = len(space) if space else None
        names = cfg.get("objective_names")
        n_obj = len(names) if names else None
        refit_state = st.get("refit") or None
        refit = (
            {
                "0": {
                    k: refit_state[k]
                    for k in (*_HYPER_KEYS, "n_train")
                    if k in refit_state
                }
            }
            if refit_state
            else {}
        )
        epochs_run = int(st.get("epochs_run", 0))
        quarantined = int(st.get("quarantined", 0))
        # the checkpoint carries no telemetry summaries, but its archive
        # IS the evaluation record: every archived row was one finite
        # evaluation, and quarantined rows were evaluations the archive
        # rejected — together they are the rate denominator
        x = (tp.get("arrays") or {}).get("x")
        n_archived = int(x.shape[0]) if x is not None else 0
        records.append(
            {
                "store": path,
                "opt_id": st.get("opt_id", f"tenant_{key}"),
                "kind": "service_checkpoint",
                "signature": problem_signature(dim, n_obj),
                "dim": dim,
                "n_obj": n_obj,
                "n_problems": 1,
                "telemetry": {
                    "epochs": epochs_run,
                    "evals_total": n_archived + quarantined,
                },
                "spans": {},
                "alerts": {},
                "refit": refit,
                "quarantined_total": quarantined,
                "eval_failures_total": int(st.get("eval_failures", 0)),
            }
        )
    return records


def scan_store(path: str) -> List[Dict[str, Any]]:
    """All run records in one HDF5 file — a results store yields one
    record per stored ``opt_id``, a service checkpoint one per stored
    tenant. Files of neither format yield an empty list."""
    try:
        import h5py
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "h5py is required for the fleet rollup but is not installed"
        ) from e
    with h5py.File(path, "r") as h5:
        if h5.attrs.get("format") == "dmosopt_tpu.service_checkpoint":
            checkpoint = True
        else:
            checkpoint = False
            records = _scan_results_store(path, h5)
    if checkpoint:
        records = _scan_service_checkpoint(path)
    return records


# ----------------------------------------------------------------- rollup


def _flatten_hyper(refit: Dict[str, Any], key: str) -> List[float]:
    out: List[float] = []
    for state in refit.values():
        v = state.get(key)
        if v is None:
            continue
        out.extend(float(x) for x in np.asarray(v, dtype=np.float64).ravel())
    return out


def rollup(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-run records into the per-signature fleet summary."""
    by_sig: Dict[str, List[Dict]] = {}
    for rec in records:
        by_sig.setdefault(rec["signature"], []).append(rec)

    signatures: Dict[str, Any] = {}
    for sig in sorted(by_sig):
        recs = by_sig[sig]
        amps: List[float] = []
        lss: List[float] = []
        noises: List[float] = []
        n_trains: List[float] = []
        for rec in recs:
            amps.extend(_flatten_hyper(rec.get("refit", {}), "amp"))
            lss.extend(_flatten_hyper(rec.get("refit", {}), "ls"))
            noises.extend(_flatten_hyper(rec.get("refit", {}), "noise"))
            for state in rec.get("refit", {}).values():
                if state.get("n_train") is not None:
                    n_trains.append(float(state["n_train"]))
        alert_totals: Dict[str, int] = {}
        quarantines: List[float] = []
        for rec in recs:
            for rule, n in rec.get("alerts", {}).items():
                alert_totals[rule] = alert_totals.get(rule, 0) + n
            if rec.get("quarantined_total") is not None:
                evals = float(
                    rec.get("telemetry", {}).get("evals_total") or 0
                )
                if evals > 0:  # a true rate needs a real denominator
                    quarantines.append(rec["quarantined_total"] / evals)
        entry = {
            "n_runs": len(recs),
            "n_problems": sum(r.get("n_problems", 1) for r in recs),
            "epochs": _dist(
                [r.get("telemetry", {}).get("epochs") for r in recs]
            ),
            "fit_steps": _dist(
                [r.get("telemetry", {}).get("fit_steps_total") for r in recs]
            ),
            "gens_per_sec": _dist(
                [r.get("telemetry", {}).get("gens_per_sec_mean") for r in recs]
            ),
            "epochs_to_front": _dist(
                [r.get("epochs_to_front") for r in recs]
            ),
            "n_train": _dist(n_trains),
            # the ROADMAP item-5 warm-start prior substrate: linear AND
            # log10 distributions of every converged hyperparameter seen
            # for this problem signature across the fleet
            "hyperparameters": {
                "amp": {"linear": _dist(amps), "log10": _log10_dist(amps)},
                "lengthscale": {
                    "linear": _dist(lss), "log10": _log10_dist(lss),
                },
                "noise": {
                    "linear": _dist(noises), "log10": _log10_dist(noises),
                },
            },
            "alert_firings": alert_totals,
            "quarantine_rate": _dist(quarantines),
        }
        signatures[sig] = entry

    return {
        "format": "dmosopt_tpu.fleet_summary",
        "version": FLEET_SUMMARY_VERSION,
        "n_stores": len({r["store"] for r in records}),
        "n_runs": len(records),
        "runs": records,
        "signatures": signatures,
    }


# ------------------------------------------------------- fleet directories


def fleet_dir_stores(fleet_dir: str) -> List[str]:
    """Every HDF5 store a fleet directory holds: per-worker service
    checkpoints (``workers/*/checkpoint.h5``) and per-tenant results
    stores (``results/*.h5``) — the input set `fleet_summary` rolls up
    for a whole fleet in one call (the ``fleet --dir`` CLI path).
    Layout names come from `dmosopt_tpu.fleet.wire` (imported at call
    time — the supervisor side imports this module's sibling package,
    so a module-level import would be a cycle)."""
    from dmosopt_tpu.fleet import wire

    out: List[str] = []
    workers_root = os.path.join(fleet_dir, "workers")
    if os.path.isdir(workers_root):
        for wid in sorted(os.listdir(workers_root)):
            ck = os.path.join(workers_root, wid, wire.CHECKPOINT_FILE)
            if os.path.isfile(ck):
                out.append(ck)
    results_root = wire.results_dir(fleet_dir)
    if os.path.isdir(results_root):
        for name in sorted(os.listdir(results_root)):
            if name.endswith(".h5"):
                out.append(os.path.join(results_root, name))
    return out


def scan_fleet_dir(fleet_dir: str) -> Dict[str, Any]:
    """Aggregate one fleet directory's control plane: the supervisor
    state file (placements, migration history, shed log) plus every
    worker's latest status-file heartbeat — the ``status --fleet-dir``
    CLI's data source. Liveness judgement is the CALLER's (it needs a
    clock); this scan only reports each status's ``ts``."""
    from dmosopt_tpu.fleet import wire

    state = None
    state_path = os.path.join(fleet_dir, wire.FLEET_STATE_FILE)
    if os.path.isfile(state_path):
        try:
            state = wire.read_json(state_path)
        except (OSError, ValueError):
            state = None
    workers: List[Dict[str, Any]] = []
    workers_root = os.path.join(fleet_dir, "workers")
    if os.path.isdir(workers_root):
        for wid in sorted(os.listdir(workers_root)):
            wdir = os.path.join(workers_root, wid)
            if not os.path.isdir(wdir):
                continue
            try:
                status = wire.read_json(os.path.join(wdir, wire.STATUS_FILE))
            except (OSError, ValueError):
                status = None
            workers.append(
                {
                    "worker_id": wid,
                    "dir": wdir,
                    "status": status,
                    "fenced": os.path.exists(
                        os.path.join(wdir, wire.FENCE_FILE)
                    ),
                    "has_checkpoint": os.path.isfile(
                        os.path.join(wdir, wire.CHECKPOINT_FILE)
                    ),
                }
            )
    return {"fleet_dir": fleet_dir, "state": state, "workers": workers}


def fleet_summary(paths: List[str]) -> Dict[str, Any]:
    """Scan every store and fold the records — the one-call entry point
    the ``fleet`` CLI subcommand (and item 5's prior loader) uses."""
    records: List[Dict[str, Any]] = []
    for path in paths:
        if not os.path.exists(path):
            raise FileNotFoundError(f"fleet: store not found: {path}")
        records.extend(scan_store(path))
    return rollup(records)


def write_fleet_summary(paths: List[str], output_path: str) -> Dict[str, Any]:
    summary = fleet_summary(paths)
    tmp = output_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(summary, fh, indent=2, default=json_default)
    os.replace(tmp, output_path)
    return summary
