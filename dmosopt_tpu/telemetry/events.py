"""Structured event log: typed per-epoch/per-phase records.

Events are the narrative complement to the metrics registry: where a
counter says "eval batches: 12", the event log says *which epoch's eval
phase took how long with what per-eval statistics*. Each record is an
`Event` (kind, timestamp, optional epoch, free-form fields) held in a
bounded in-memory ring buffer and, when a ``jsonl_path`` is configured,
appended to a JSON-lines file — one self-describing JSON object per
line, so a run's telemetry can be tailed, grepped, or loaded with any
JSON tooling while the run is still going.

Known kinds (free-form kinds are allowed; these are what the framework
emits and what ``Telemetry.epoch_summary`` understands):

- ``phase``   — one timed region of an epoch; fields always include
  ``phase`` (xinit | train | optimize | eval) and ``duration_s``.
- ``epoch``   — one driver epoch completed; ``duration_s``, counters.
- ``resample``— resample selection of an epoch; batch size, dedupe.
- ``compile_cache`` — persistent-cache accounting at run end.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

from dmosopt_tpu.utils import json_default


def jsonable(value):
    """Coerce numpy scalars/arrays and other common non-JSON types to
    plain Python so every event (and the HDF5 summary built from them)
    serializes without a custom encoder."""
    import numpy as np

    if isinstance(value, (np.integer,)):
        return int(value)
    if isinstance(value, (np.floating,)):
        return float(value)
    if isinstance(value, (np.bool_,)):
        return bool(value)
    if isinstance(value, np.ndarray):
        return [jsonable(v) for v in value.tolist()]
    if isinstance(value, dict):
        return {str(k): jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [jsonable(v) for v in value]
    return value


@dataclasses.dataclass(frozen=True)
class Event:
    kind: str
    ts: float
    epoch: Optional[int]
    fields: Dict[str, Any]

    def to_dict(self) -> Dict[str, Any]:
        out = {"kind": self.kind, "ts": self.ts}
        if self.epoch is not None:
            out["epoch"] = self.epoch
        out.update(self.fields)
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Event":
        d = dict(d)
        kind = d.pop("kind")
        ts = d.pop("ts")
        epoch = d.pop("epoch", None)
        return cls(kind=kind, ts=ts, epoch=epoch, fields=d)


class EventLog:
    """Bounded ring buffer of `Event`s with an optional JSONL sink.

    The sink can be size-bounded: with ``max_bytes`` set, a write that
    would grow the file past the bound first rotates it —
    ``events.jsonl`` becomes ``events.jsonl.1`` (existing ``.1`` shifts
    to ``.2`` and so on, at most ``keep`` rotated files are retained) —
    so a long-lived service's sink can never grow without bound.
    Rotations are counted in `rotations` and reported through the
    optional ``on_rotate`` callback (the `Telemetry` facade wires it to
    the ``telemetry_sink_rotations_total`` counter)."""

    def __init__(
        self,
        ring_size: int = 1024,
        jsonl_path: Optional[str] = None,
        max_bytes: Optional[int] = None,
        keep: int = 3,
    ):
        if ring_size <= 0:
            raise ValueError("ring_size must be positive")
        if max_bytes is not None and max_bytes <= 0:
            raise ValueError("max_bytes must be positive")
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self._ring: deque = deque(maxlen=int(ring_size))
        self.jsonl_path = jsonl_path
        self.max_bytes = int(max_bytes) if max_bytes is not None else None
        self.keep = int(keep)
        self.rotations = 0
        self.on_rotate = None  # callable, invoked AFTER each rotation
        self._lock = threading.Lock()
        self._fh = None
        self._bytes = 0
        self._rotate_disabled = False  # set after an unrotatable chain
        if jsonl_path is not None:
            self._fh = open(jsonl_path, "a", buffering=1)  # line-buffered
            try:
                self._bytes = os.path.getsize(jsonl_path)
            except OSError:
                self._bytes = 0

    def _rotate_locked(self) -> bool:
        """Rotate the sink file chain (caller holds the lock). The live
        file becomes ``.1``; ``.{keep}`` falls off the end. Returns
        True only when the chain actually moved; any failure degrades
        the sink (rotation disabled, or dark on an unreopenable path)
        instead of taking the run down."""
        self._fh.close()
        moved = True
        try:
            for i in range(self.keep, 0, -1):
                src = (
                    self.jsonl_path
                    if i == 1
                    else f"{self.jsonl_path}.{i - 1}"
                )
                if os.path.exists(src):
                    os.replace(src, f"{self.jsonl_path}.{i}")
        except OSError:
            # an unrotatable chain (EACCES/EXDEV...): keep appending to
            # the live file and stop attempting — retrying the doomed
            # close/replace/reopen cycle on every emit would add IO per
            # event and inflate the rotation counter with non-rotations
            moved = False
            self._rotate_disabled = True
        if moved:
            # the chain moved on disk: count it NOW, before the reopen
            # can fail — the counter must agree with the on-disk state
            # it explains, even when the sink then goes dark
            self.rotations += 1
        try:
            self._fh = open(self.jsonl_path, "a", buffering=1)  # graftlint: disable=lock-discipline -- rotation fires at most once per max_bytes of sink output, and the reopen MUST serialize with concurrent emit() writers on this same lock (an outside-the-lock reopen would race them onto a closed handle)
        except OSError:
            # disk-full/EMFILE at the reopen: the sink goes dark (emit
            # keeps the ring buffer; no more JSONL) rather than leaving
            # a closed handle for the next emit to crash on
            self._fh = None
            self._bytes = 0
            return moved
        try:
            self._bytes = os.path.getsize(self.jsonl_path)
        except OSError:
            self._bytes = 0
        return moved

    def emit(self, kind: str, epoch: Optional[int] = None, **fields) -> Event:
        if not isinstance(kind, str) or not kind:
            raise ValueError(f"event kind must be a non-empty string: {kind!r}")
        ev = Event(
            kind=kind,
            ts=time.time(),
            epoch=int(epoch) if epoch is not None else None,
            fields={k: jsonable(v) for k, v in fields.items()},
        )
        rotated = False
        with self._lock:
            self._ring.append(ev)
            if self._fh is not None:
                # fields are jsonable()-coerced above, but jax device
                # arrays (not np.ndarray) fall through it unchanged —
                # the duck-typed default catches those (BENCH_r03 class)
                line = json.dumps(ev.to_dict(), default=json_default) + "\n"
                # the file is text-mode UTF-8: size-account the encoded
                # byte length, not code points, or non-ASCII content
                # would let the file overrun the documented bound
                nbytes = len(line.encode("utf-8"))
                if (
                    self.max_bytes is not None
                    and not self._rotate_disabled
                    and self._bytes > 0
                    and self._bytes + nbytes > self.max_bytes
                ):
                    rotated = self._rotate_locked()
            if self._fh is not None:  # rotation may have gone dark
                self._fh.write(line)
                self._bytes += nbytes
                if kind in ("phase", "health_alert"):
                    # a phase close is the natural durability boundary
                    # (and a health-alert transition must never be lost
                    # to a crash — the alert IS the incident record):
                    # flush so a killed run's sink keeps everything up
                    # to its last completed phase and every alert fired
                    # before it, independent of the file object's
                    # buffering mode
                    self._fh.flush()
        if rotated and self.on_rotate is not None:
            self.on_rotate()
        return ev

    def flush(self):
        with self._lock:
            if self._fh is not None:
                self._fh.flush()

    def records(
        self, kind: Optional[str] = None, epoch: Optional[int] = None
    ) -> List[Event]:
        with self._lock:
            evs = list(self._ring)
        if kind is not None:
            evs = [e for e in evs if e.kind == kind]
        if epoch is not None:
            evs = [e for e in evs if e.epoch == epoch]
        return evs

    def __len__(self) -> int:
        return len(self._ring)

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def read_jsonl(path: str) -> Iterator[Event]:
    """Load events back from a JSONL sink (round-trip of `EventLog.emit`)."""
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                yield Event.from_dict(json.loads(line))
