"""Structured event log: typed per-epoch/per-phase records.

Events are the narrative complement to the metrics registry: where a
counter says "eval batches: 12", the event log says *which epoch's eval
phase took how long with what per-eval statistics*. Each record is an
`Event` (kind, timestamp, optional epoch, free-form fields) held in a
bounded in-memory ring buffer and, when a ``jsonl_path`` is configured,
appended to a JSON-lines file — one self-describing JSON object per
line, so a run's telemetry can be tailed, grepped, or loaded with any
JSON tooling while the run is still going.

Known kinds (free-form kinds are allowed; these are what the framework
emits and what ``Telemetry.epoch_summary`` understands):

- ``phase``   — one timed region of an epoch; fields always include
  ``phase`` (xinit | train | optimize | eval) and ``duration_s``.
- ``epoch``   — one driver epoch completed; ``duration_s``, counters.
- ``resample``— resample selection of an epoch; batch size, dedupe.
- ``compile_cache`` — persistent-cache accounting at run end.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

from dmosopt_tpu.utils import json_default


def jsonable(value):
    """Coerce numpy scalars/arrays and other common non-JSON types to
    plain Python so every event (and the HDF5 summary built from them)
    serializes without a custom encoder."""
    import numpy as np

    if isinstance(value, (np.integer,)):
        return int(value)
    if isinstance(value, (np.floating,)):
        return float(value)
    if isinstance(value, (np.bool_,)):
        return bool(value)
    if isinstance(value, np.ndarray):
        return [jsonable(v) for v in value.tolist()]
    if isinstance(value, dict):
        return {str(k): jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [jsonable(v) for v in value]
    return value


@dataclasses.dataclass(frozen=True)
class Event:
    kind: str
    ts: float
    epoch: Optional[int]
    fields: Dict[str, Any]

    def to_dict(self) -> Dict[str, Any]:
        out = {"kind": self.kind, "ts": self.ts}
        if self.epoch is not None:
            out["epoch"] = self.epoch
        out.update(self.fields)
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Event":
        d = dict(d)
        kind = d.pop("kind")
        ts = d.pop("ts")
        epoch = d.pop("epoch", None)
        return cls(kind=kind, ts=ts, epoch=epoch, fields=d)


class EventLog:
    """Bounded ring buffer of `Event`s with an optional JSONL sink."""

    def __init__(self, ring_size: int = 1024, jsonl_path: Optional[str] = None):
        if ring_size <= 0:
            raise ValueError("ring_size must be positive")
        self._ring: deque = deque(maxlen=int(ring_size))
        self.jsonl_path = jsonl_path
        self._lock = threading.Lock()
        self._fh = None
        if jsonl_path is not None:
            self._fh = open(jsonl_path, "a", buffering=1)  # line-buffered

    def emit(self, kind: str, epoch: Optional[int] = None, **fields) -> Event:
        if not isinstance(kind, str) or not kind:
            raise ValueError(f"event kind must be a non-empty string: {kind!r}")
        ev = Event(
            kind=kind,
            ts=time.time(),
            epoch=int(epoch) if epoch is not None else None,
            fields={k: jsonable(v) for k, v in fields.items()},
        )
        with self._lock:
            self._ring.append(ev)
            if self._fh is not None:
                # fields are jsonable()-coerced above, but jax device
                # arrays (not np.ndarray) fall through it unchanged —
                # the duck-typed default catches those (BENCH_r03 class)
                self._fh.write(
                    json.dumps(ev.to_dict(), default=json_default) + "\n"
                )
                if kind == "phase":
                    # a phase close is the natural durability boundary:
                    # flush so a killed run's sink keeps everything up
                    # to its last completed phase, independent of the
                    # file object's buffering mode
                    self._fh.flush()
        return ev

    def flush(self):
        with self._lock:
            if self._fh is not None:
                self._fh.flush()

    def records(
        self, kind: Optional[str] = None, epoch: Optional[int] = None
    ) -> List[Event]:
        with self._lock:
            evs = list(self._ring)
        if kind is not None:
            evs = [e for e in evs if e.kind == kind]
        if epoch is not None:
            evs = [e for e in evs if e.epoch == epoch]
        return evs

    def __len__(self) -> int:
        return len(self._ring)

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def read_jsonl(path: str) -> Iterator[Event]:
    """Load events back from a JSONL sink (round-trip of `EventLog.emit`)."""
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                yield Event.from_dict(json.loads(line))
