"""End-to-end telemetry: metrics registry + event log + device tracing.

One `Telemetry` object travels the whole stack — driver epoch loop,
per-problem strategies, the MO-ASMO phases, the evaluation backends and
the compile cache — so a run's observability has a single switchboard:

- `Telemetry.registry` (`MetricsRegistry`): counters/gauges/histograms.
- `Telemetry.log` (`EventLog`): typed per-epoch/per-phase records with a
  bounded ring buffer and an optional JSONL sink.
- `jax.profiler` device traces for selected epochs
  (``profile_dir`` / ``profile_epochs``, captured via
  `Telemetry.device_capture`, which also joins each capture's device
  events into `Telemetry.ledger` — the device-time ledger).

Configuration arrives through the driver's ``telemetry`` parameter
(``dopt_params["telemetry"]``): ``True``/``None`` for the on-by-default
instance, ``False`` to disable (the driver then holds no telemetry
object at all — zero calls on the hot path), a dict of `Telemetry`
constructor kwargs, or a ready-made `Telemetry` instance. The metric
name catalog lives in ``docs/observability.md`` and is enforced by
``make lint-metrics``.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Optional, Sequence, Union

from dmosopt_tpu.telemetry.device_ledger import DeviceLedger  # noqa: F401
from dmosopt_tpu.telemetry.events import Event, EventLog, jsonable, read_jsonl  # noqa: F401
from dmosopt_tpu.telemetry.exposition import (  # noqa: F401
    MetricsExporter,
    parse_openmetrics,
    render_openmetrics,
)
from dmosopt_tpu.telemetry.health import (  # noqa: F401
    HealthEngine,
    HealthRule,
    default_rulebook,
)
from dmosopt_tpu.telemetry.registry import MetricsRegistry  # noqa: F401
from dmosopt_tpu.telemetry.tracing import (  # noqa: F401
    Span,
    Tracer,
    validate_chrome_trace,
)

# Telemetry summaries merge these aggregates across a run's eval events
# (the rest of `eval_time_stats` — std/median — does not merge exactly).
_EVAL_MERGE_KEYS = ("eval_min", "eval_max", "eval_sum")


class Telemetry:
    """Facade over the registry + event log with phase-timer helpers.

    A disabled instance (``enabled=False``) is a true no-op: every
    mutator returns immediately without touching the registry or the
    log, and ``bool(tel)`` is False so call sites can skip whole
    instrumentation blocks. The framework goes one step further for
    ``telemetry=False`` runs: the driver holds ``None`` instead, so the
    hot path performs zero telemetry calls of any kind.
    """

    def __init__(
        self,
        enabled: bool = True,
        ring_size: int = 1024,
        jsonl_path: Optional[str] = None,
        jsonl_max_bytes: Optional[int] = None,
        jsonl_keep: int = 3,
        profile_dir: Optional[str] = None,
        profile_epochs: Optional[Sequence[int]] = None,
        histogram_buckets: Optional[Dict[str, Sequence[float]]] = None,
        label_series_limit: Optional[int] = 512,
        trace_path: Optional[str] = None,
        trace_max_spans: int = 16384,
    ):
        self.enabled = bool(enabled)
        self.registry = MetricsRegistry(
            histogram_buckets=histogram_buckets,
            series_limit=label_series_limit,
        )
        self.log = EventLog(
            ring_size=ring_size,
            jsonl_path=jsonl_path if self.enabled else None,
            max_bytes=jsonl_max_bytes,
            keep=jsonl_keep,
        )
        if self.enabled:
            # size-bounded sink rotation accounting (docs/observability.md)
            self.log.on_rotate = lambda: self.registry.counter_inc(
                "telemetry_sink_rotations_total"
            )
        # device-time ledger: per-compiled-program device truth, fed by
        # observable compiles always and by jax.profiler captures when
        # profiling is armed (`device_capture`). A disabled instance —
        # and a telemetry=False run, which holds no Telemetry at all —
        # has no ledger: zero hot-path calls stays pinned.
        self.ledger: Optional[DeviceLedger] = (
            DeviceLedger() if self.enabled else None
        )
        # spans are always collected on an enabled instance (they feed
        # per-epoch persistence and service introspection); `trace_path`
        # additionally exports them as Chrome trace-event JSON on close
        self.tracer: Optional[Tracer] = (
            Tracer(path=trace_path, max_spans=trace_max_spans)
            if self.enabled
            else None
        )
        self.profile_dir = profile_dir
        self.profile_epochs = (
            frozenset(int(e) for e in profile_epochs)
            if profile_epochs is not None
            else None
        )
        self.epoch: Optional[int] = None  # default epoch stamp for events
        # complete per-epoch event index for `epoch_summary`: the ring
        # buffer is bounded, so an event-heavy epoch (one eval drain per
        # generation in evaluation mode) could evict its own early
        # events before the driver persists the summary. Entries for
        # epochs older than the current one are pruned by `set_epoch`
        # (the driver persists each epoch before advancing).
        self._events_by_epoch: Dict[int, list] = {}

    def __bool__(self) -> bool:
        return self.enabled

    # -------------------------------------------------------------- state

    def set_epoch(self, epoch: Optional[int]):
        self.epoch = int(epoch) if epoch is not None else None
        if self.epoch is not None:
            for e in [e for e in self._events_by_epoch if e < self.epoch]:
                del self._events_by_epoch[e]

    def should_trace(self, epoch: int) -> bool:
        """Capture a device trace for this epoch? Requires a
        ``profile_dir``; ``profile_epochs=None`` traces every epoch,
        otherwise only the listed ones."""
        if not self.enabled or self.profile_dir is None:
            return False
        return self.profile_epochs is None or int(epoch) in self.profile_epochs

    @contextlib.contextmanager
    def device_capture(self, epoch: Optional[int] = None):
        """Capture a `jax.profiler` trace around the enclosed region and
        fold it into the device-time ledger on exit: the capture's
        device-event durations are joined to the host spans opened
        inside the region (by `TraceAnnotation` name and order), the
        trace-derived `device_busy_fraction` / `device_overlap_ratio`
        gauges are set, and per-tenant device seconds land in
        `tenant_device_seconds`. Replaces the bare
        `utils.profiling.device_trace` at driver/service capture sites;
        no-op (yields None) without a ``profile_dir`` or without jax."""
        if not self.enabled or self.profile_dir is None:
            yield None
            return
        try:
            import jax
        except Exception:
            yield None
            return
        mark = self.tracer.mark() if self.tracer is not None else 0
        t_start = time.time()
        started = False
        session = None
        # prefer a raw ProfilerSession with the PYTHON tracer disabled:
        # the default python tracer floods the capture with hundreds of
        # thousands of call events on large steps (T=64 services), which
        # both distorts the step's wall and evicts the TraceAnnotation
        # host events the ledger joins on — concurrent scheduler bucket
        # windows were observably dropped from the trace under it
        try:
            from jax._src.lib import xla_client

            opts = xla_client.profiler.ProfileOptions()
            opts.python_tracer_level = 0
            session = xla_client.profiler.ProfilerSession(opts)
            started = True
        except Exception:
            session = None
        if session is None:
            try:
                jax.profiler.start_trace(self.profile_dir)
                started = True
            except Exception:
                pass  # a refusing profiler must not kill the epoch
        if started:
            self.event("trace", epoch=epoch, profile_dir=self.profile_dir)
        try:
            yield self.ledger
        finally:
            if session is not None:
                try:
                    session.stop_and_export(str(self.profile_dir))
                except Exception:
                    started = False
            elif started:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    started = False
            if started and self.ledger is not None:
                spans = (
                    self.tracer.spans_since(mark)
                    if self.tracer is not None
                    else []
                )
                cap = self.ledger.ingest_profile_dir(
                    self.profile_dir, spans, newer_than=t_start
                )
                if cap is not None:
                    if cap.device_busy_fraction is not None:
                        self.gauge(
                            "device_busy_fraction", cap.device_busy_fraction
                        )
                    if cap.device_overlap_ratio is not None:
                        self.gauge(
                            "device_overlap_ratio", cap.device_overlap_ratio
                        )
                    for (tenant, phase), sec in sorted(
                        cap.tenant_device_seconds.items()
                    ):
                        self.inc(
                            "tenant_device_seconds", sec,
                            tenant=tenant, phase=phase,
                        )
                    self.event(
                        "device_capture", epoch=epoch, **cap.to_dict()
                    )

    # ------------------------------------------------------------ metrics

    def inc(self, name: str, value: float = 1.0, **labels):
        if self.enabled:
            self.registry.counter_inc(name, value, **labels)

    def gauge(self, name: str, value: float, **labels):
        if self.enabled:
            self.registry.gauge_set(name, value, **labels)

    def observe(self, name: str, value: float, **labels):
        if self.enabled:
            self.registry.histogram_observe(name, value, **labels)

    # ------------------------------------------------------------- events

    def event(self, kind: str, epoch: Optional[int] = None, **fields) -> Optional[Event]:
        if not self.enabled:
            return None
        ev = self.log.emit(
            kind, epoch=epoch if epoch is not None else self.epoch, **fields
        )
        if ev.epoch is not None:
            self._events_by_epoch.setdefault(ev.epoch, []).append(ev)
        return ev

    # ------------------------------------------------------------- spans

    def span(self, name: str, **labels):
        """Open one nested host-side tracing span (see
        `dmosopt_tpu.telemetry.tracing`). Disabled instances return a
        null context yielding None, so call sites stay one-liners."""
        if self.enabled and self.tracer is not None:
            return self.tracer.span(name, **labels)
        return contextlib.nullcontext(None)

    @contextlib.contextmanager
    def phase(self, phase: str, epoch: Optional[int] = None, **fields):
        """Time a region: on exit, observes `phase_duration_seconds`
        {phase=...} and emits one ``phase`` event. Yields a mutable dict
        the caller can extend with result fields (n_train, gens_per_sec,
        ...) before the event is written."""
        if not self.enabled:
            yield {}
            return
        extra: Dict[str, Any] = dict(fields)
        t0 = time.perf_counter()
        try:
            yield extra
        finally:
            dt = time.perf_counter() - t0
            self.observe("phase_duration_seconds", dt, phase=phase)
            self.event("phase", epoch=epoch, phase=phase, duration_s=dt, **extra)

    # ------------------------------------------------------------ summary

    def epoch_summary(self, epoch: int) -> Dict[str, Any]:
        """One epoch's events folded into a flat JSON-able summary dict:
        per-phase durations, EA throughput, surrogate-fit results, merged
        eval-time aggregates, resample accounting. This is what the
        driver persists into the HDF5 ``telemetry`` group and what the
        ``telemetry`` CLI renders. Reads the complete per-epoch event
        index when the epoch is still held there (current epoch and
        newer), falling back to the ring buffer for pruned epochs."""
        summary: Dict[str, Any] = {"epoch": int(epoch), "phases": {}}
        eval_agg = {"eval_n": 0, "eval_sum": 0.0, "eval_min": None, "eval_max": None}
        # a multi-problem epoch emits one train/optimize/resample event
        # per problem: summable counters accumulate, ratio fields
        # average, termination reasons union, and gens_per_sec is
        # recomputed from the totals below — last-writer-wins would pair
        # one problem's throughput with the summed durations
        mean_acc: Dict[str, list] = {}
        terminations: list = []
        events = self._events_by_epoch.get(int(epoch))
        if events is None:
            events = self.log.records(epoch=int(epoch))
        for ev in events:
            f = ev.fields
            if ev.kind == "phase":
                name = f.get("phase", "unknown")
                summary["phases"][name] = (
                    summary["phases"].get(name, 0.0) + float(f.get("duration_s", 0.0))
                )
                if name == "train":
                    for k in ("n_train", "duplicates_removed", "fit_n_steps"):
                        if k in f:
                            summary[k] = summary.get(k, 0) + f[k]
                    for k in ("feasible_fraction", "surrogate_loss"):
                        if f.get(k) is not None:
                            mean_acc.setdefault(k, []).append(float(f[k]))
                    if "surrogate" in f:
                        summary["surrogate"] = f["surrogate"]
                    if "fit_early_stopped" in f:
                        summary["fit_early_stopped"] = bool(
                            summary.get("fit_early_stopped", False)
                            or f["fit_early_stopped"]
                        )
                elif name == "optimize":
                    for k in ("n_generations", "n_evals"):
                        if k in f:
                            summary[k] = summary.get(k, 0) + f[k]
                    t = f.get("termination")
                    if t is not None and t not in terminations:
                        terminations.append(t)
                elif name == "xinit" and "n_points" in f:
                    summary["n_initial_points"] = f["n_points"]
                elif name == "eval":
                    n = int(f.get("n_evals", 0))
                    eval_agg["eval_n"] += n
                    if f.get("eval_sum", -1.0) and f.get("eval_sum", -1.0) > 0:
                        eval_agg["eval_sum"] += float(f["eval_sum"])
                    for k, red in (("eval_min", min), ("eval_max", max)):
                        v = f.get(k)
                        if v is not None and v > 0:
                            eval_agg[k] = (
                                v if eval_agg[k] is None else red(eval_agg[k], v)
                            )
            elif ev.kind == "epoch":
                summary["wall_s"] = f.get("duration_s")
                for k in ("eval_count", "save_count"):
                    if k in f:
                        summary[k] = f[k]
            elif ev.kind == "resample":
                for k in ("resample_batch", "resample_duplicates_removed"):
                    if k in f:
                        summary[k] = summary.get(k, 0) + f[k]
        for k, vals in mean_acc.items():
            summary[k] = sum(vals) / len(vals)
        if terminations:
            summary["termination"] = "+".join(terminations)
        opt_s = summary["phases"].get("optimize")
        if opt_s and summary.get("n_generations"):
            summary["gens_per_sec"] = round(summary["n_generations"] / opt_s, 3)
        if eval_agg["eval_n"]:
            eval_agg["eval_mean"] = (
                eval_agg["eval_sum"] / eval_agg["eval_n"]
                if eval_agg["eval_sum"]
                else None
            )
            summary["eval"] = eval_agg
        return jsonable(summary)

    def close(self):
        if self.tracer is not None and self.tracer.path is not None:
            try:
                self.tracer.export()
            except OSError:
                pass  # an unwritable trace path must not mask run teardown
        self.log.close()


def phase_scope(tel: Optional["Telemetry"], phase: str, epoch=None, **fields):
    """`tel.phase(...)` when telemetry is live, else a no-op context
    yielding a throwaway dict — instrumented call sites stay one-liners
    and a disabled run performs zero telemetry calls."""
    if tel:
        return tel.phase(phase, epoch=epoch, **fields)
    return contextlib.nullcontext({})


def span_scope(tel: Optional["Telemetry"], name: str, **labels):
    """`tel.span(...)` when telemetry is live, else a no-op context
    yielding None — the span analogue of `phase_scope`."""
    if tel:
        return tel.span(name, **labels)
    return contextlib.nullcontext(None)


def record_device_memory(tel: Optional["Telemetry"]):
    """Gauge per-device memory from `jax.local_devices()` where the
    backend reports it (TPU/GPU; CPU devices return None — no-op)."""
    if not tel:
        return
    try:
        import jax

        for dev in jax.local_devices():
            stats = dev.memory_stats()
            if not stats:
                continue
            for src, name in (
                ("bytes_in_use", "device_memory_bytes_in_use"),
                ("peak_bytes_in_use", "device_memory_peak_bytes"),
                ("bytes_limit", "device_memory_bytes_limit"),
            ):
                if src in stats:
                    tel.gauge(name, float(stats[src]), device=str(dev.id))
    except Exception:  # memory stats are best-effort on every backend
        pass


def create_telemetry(
    spec: Union[None, bool, Dict, Telemetry] = None,
) -> Optional[Telemetry]:
    """Resolve the driver's ``telemetry`` config value.

    ``None``/``True`` -> a default enabled `Telemetry`; ``False`` (or a
    dict with ``enabled: False``) -> ``None`` — the caller holds no
    telemetry object and its hot path makes zero telemetry calls; a
    dict -> `Telemetry(**dict)`; an existing instance passes through.
    """
    if spec is None or spec is True:
        return Telemetry()
    if spec is False:
        return None
    if isinstance(spec, Telemetry):
        return spec if spec.enabled else None
    if isinstance(spec, dict):
        if not spec.get("enabled", True):
            return None
        return Telemetry(**spec)
    raise TypeError(
        f"telemetry must be None, bool, dict, or Telemetry; got {type(spec)!r}"
    )
