"""Run-health engine: declarative alert rules over the telemetry state.

The observability stack before this module was entirely *passive*:
every incident class this project has actually hit — retrace storms,
writer death, quarantine spikes, eval-timeout surges, host contention,
device-busy collapse, label-series overflow — was visible only if a
human read the `status` CLI at the right moment. The health engine
makes the stack *active*: a set of declarative `HealthRule`s is
evaluated over the metrics snapshot (`MetricsRegistry.snapshot()`) and
the service's `introspect()` dict at every epoch/step boundary, each
rule carrying a metric expression, a threshold, a severity, and a
`for_steps` hysteresis, with a full firing -> resolved lifecycle.

Metric expressions (the `HealthRule.metric` string) name one source:

- ``counter:<name>`` — the SUM across every label series of that
  counter in the snapshot (an absent counter reads 0.0 — counters are
  zero until first incremented);
- ``gauge:<name>`` — the unlabeled series of that gauge, falling back
  to the mean across labeled series; an absent gauge reads ``None``
  and the rule is **skipped** that round (state frozen, never fired on
  missing data);
- ``introspect:<dotted.path>`` — a numeric (or bool) leaf of the
  introspection snapshot, e.g. ``introspect:queue_depths.writer_backlog``;
  a missing path skips the rule like an absent gauge.

``counter:``/``gauge:`` names are held to the docs/observability.md
metric catalog by graftlint's ``metrics-catalog`` rule (a rule
referencing an uncataloged metric turns ``make lint`` red), so alert
definitions cannot rot ahead of the catalog.

Evaluation is **deterministic**: no wall-clock or randomness enters a
firing decision — the same snapshot sequence produces the same alert
sequence, which is what lets `make health-smoke` pin the exact alert
set a seeded chaos plan fires (and pin a fault-free run to zero).
Alert transitions are events (``health_alert`` kind — JSONL sink +
per-epoch HDF5 via `storage.save_alerts_to_h5`, like spans), counted
in ``health_alerts_total{rule,severity}``, and surfaced through
``introspect()["health"]`` and the ``status`` CLI.

Thread-safety: `evaluate()` runs on the stepping thread while the
exposition exporter's request threads read `summary()` / `active()` /
`has_critical()` — all state transitions and reads run under one lock.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, fields
from typing import Any, Dict, List, Optional, Sequence, Union

SEVERITIES = ("info", "warning", "critical")

COMPARATORS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}

MODES = ("value", "delta")

#: expression grammar: source prefix + name/path
_EXPR_RE = re.compile(
    r"^(counter|gauge):([a-z][a-z0-9_]*)$|^introspect:([A-Za-z0-9_.]+)$"
)


@dataclass(frozen=True)
class HealthRule:
    """One declarative alert rule.

    name: alert identifier (snake_case; what fires and resolves).
    metric: the metric expression evaluated each round (module
        docstring grammar).
    threshold: the comparison boundary.
    severity: ``info`` / ``warning`` / ``critical`` — ``critical``
        alerts flip the exposition ``/healthz`` endpoint non-200.
    compare: ``>``, ``>=``, ``<``, ``<=`` (value vs. threshold).
    for_steps: hysteresis — the comparison must hold on this many
        CONSECUTIVE evaluations before the alert fires (a one-round
        blip on a `for_steps=2` rule never alerts).
    mode: ``value`` compares the resolved value itself; ``delta``
        compares the change since the previous evaluation (the shape
        for monotone counters: "more than N timeouts THIS step").
    description: what the alert means and what to do — rendered by the
        `status` CLI and carried on every transition event.
    """

    name: str
    metric: str
    threshold: float
    severity: str = "warning"
    compare: str = ">"
    for_steps: int = 1
    mode: str = "value"
    description: str = ""

    def __post_init__(self):
        if not re.match(r"^[a-z][a-z0-9_]*$", self.name):
            raise ValueError(f"rule name must be snake_case: {self.name!r}")
        if _EXPR_RE.match(self.metric) is None:
            raise ValueError(
                f"rule {self.name!r}: metric expression {self.metric!r} "
                f"must be 'counter:<name>', 'gauge:<name>' or "
                f"'introspect:<dotted.path>'"
            )
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"rule {self.name!r}: severity must be one of {SEVERITIES}"
            )
        if self.compare not in COMPARATORS:
            raise ValueError(
                f"rule {self.name!r}: compare must be one of "
                f"{tuple(COMPARATORS)}"
            )
        if self.mode not in MODES:
            raise ValueError(
                f"rule {self.name!r}: mode must be one of {MODES}"
            )
        if self.for_steps < 1:
            raise ValueError(f"rule {self.name!r}: for_steps must be >= 1")

    def to_dict(self) -> Dict[str, Any]:
        return {f.name: getattr(self, f.name) for f in fields(self)}

    @classmethod
    def from_spec(
        cls, spec: Union["HealthRule", Dict[str, Any]]
    ) -> "HealthRule":
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, dict):
            return cls(**spec)
        raise TypeError(
            f"health rule must be a HealthRule or dict; got {type(spec)!r}"
        )


def _resolve(metric: str, snapshot: Optional[Dict], introspect: Optional[Dict]):
    """Resolve one metric expression against the two sources. Returns
    a float, or None when the source cannot answer (rule is skipped)."""
    kind, _, name = metric.partition(":")
    if kind == "counter":
        series = (snapshot or {}).get("counters", {}).get(name)
        if series is None:
            return 0.0  # counters are zero until first incremented
        return float(sum(series.values()))
    if kind == "gauge":
        series = (snapshot or {}).get("gauges", {}).get(name)
        if not series:
            return None
        if "" in series:  # the unlabeled series
            return float(series[""])
        return float(sum(series.values()) / len(series))
    # introspect:<dotted.path>
    node: Any = introspect
    for part in name.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    if isinstance(node, bool):
        return 1.0 if node else 0.0
    if isinstance(node, (int, float)):
        return float(node)
    return None


def default_rulebook(include_host: bool = True) -> List[HealthRule]:
    """The seeded rulebook: one rule per incident class this project
    has actually hit (each cites its origin). With
    ``include_host=False`` the environment-sensitive rules (host
    contention — a function of the machine, not the run) are dropped:
    that subset is what the deterministic pins (`make health-smoke`,
    tests) evaluate, so a loaded CI host can never fail a
    "healthy run fires nothing" assertion.
    """
    rules = [
        HealthRule(
            name="bucket_retrace_storm",
            metric="counter:tenant_bucket_retraces_total",
            threshold=1.0, compare=">", mode="delta", severity="warning",
            description=(
                "2+ bucket-program retraces in one step: shape drift is "
                "re-paying multi-second compiles every epoch (see "
                "'Compile and retrace observability')"
            ),
        ),
        HealthRule(
            name="quarantine_spike",
            metric="counter:points_quarantined_total",
            threshold=0.0, compare=">", mode="delta", severity="warning",
            description=(
                "non-finite objective rows diverted from a driver "
                "archive this epoch — an objective is returning NaN/inf"
            ),
        ),
        HealthRule(
            name="tenant_quarantine_spike",
            metric="counter:tenant_points_quarantined_total",
            threshold=0.0, compare=">", mode="delta", severity="warning",
            description=(
                "non-finite objective rows quarantined out of a service "
                "tenant's archive this step (docs/robustness.md)"
            ),
        ),
        HealthRule(
            name="writer_backlog_growth",
            metric="introspect:queue_depths.writer_backlog",
            threshold=64.0, compare=">", for_steps=2, severity="warning",
            description=(
                "persistence closures are queueing faster than the "
                "background writer drains them across consecutive steps"
            ),
        ),
        HealthRule(
            name="writer_dead",
            metric="introspect:writer.failed",
            threshold=1.0, compare=">=", severity="critical",
            description=(
                "the background persistence writer died (write failed "
                "after its retry budget): fronts and checkpoints are NO "
                "LONGER written (docs/robustness.md)"
            ),
        ),
        HealthRule(
            name="eval_timeout_surge",
            metric="counter:eval_timeouts_total",
            threshold=2.0, compare=">", mode="delta", severity="warning",
            description=(
                "3+ evaluation attempts timed out this step — an "
                "objective is wedging past its EvalPolicy budget"
            ),
        ),
        HealthRule(
            name="eval_failure_surge",
            metric="counter:eval_failures_total",
            threshold=2.0, compare=">", mode="delta", severity="warning",
            description=(
                "3+ evaluation requests exhausted their retry budget "
                "this step"
            ),
        ),
        HealthRule(
            name="device_busy_collapse",
            metric="gauge:device_busy_fraction",
            threshold=0.1, compare="<", for_steps=2, severity="warning",
            description=(
                "trace-derived device utilization below 10% on "
                "consecutive profiled epochs — the device is idling "
                "(ROADMAP items 2/6; see 'Device-time ledger')"
            ),
        ),
        HealthRule(
            name="pipeline_overlap_collapse",
            metric="gauge:pipeline_overlap_ratio",
            threshold=0.05, compare="<", for_steps=2, severity="warning",
            description=(
                "evaluation batches are no longer overlapping driver "
                "work (serial-mode behavior in an overlap config)"
            ),
        ),
        HealthRule(
            name="scheduler_stall",
            metric="gauge:scheduler_stall_seconds",
            threshold=1.0, compare=">", for_steps=2, severity="warning",
            description=(
                "a device-launching task-graph node (bucket/seq) sat "
                "READY for over a second on consecutive steps while "
                "workers were busy elsewhere — ready nodes but an idle "
                "device; raise the scheduler concurrency or check for "
                "a host-bound eval hogging the pool (docs/parallel.md "
                "'Async task-graph epochs')"
            ),
        ),
        HealthRule(
            name="series_overflow",
            metric="counter:telemetry_series_overflow_total",
            threshold=0.0, compare=">", mode="delta", severity="warning",
            description=(
                "emissions are collapsing into overflow series — a "
                "label axis (per-tenant?) exceeded label_series_limit "
                "(see 'Label cardinality')"
            ),
        ),
    ]
    if include_host:
        rules.append(
            HealthRule(
                name="host_contention",
                metric="introspect:throughput.load_ratio",
                threshold=1.5, compare=">", for_steps=2, severity="warning",
                description=(
                    "1-minute loadavg above 1.5x cores on consecutive "
                    "steps: walls can be 3-9x inflated (the BENCH_r04/"
                    "r05 trap) — re-measure idle before trusting any "
                    "regression"
                ),
            )
        )
    return rules


class _RuleState:
    __slots__ = ("streak", "firing", "fired_step", "last_value", "prev_raw")

    def __init__(self):
        self.streak = 0
        self.firing = False
        self.fired_step: Optional[int] = None
        self.last_value: Optional[float] = None
        self.prev_raw: Optional[float] = None  # delta-mode baseline


class HealthEngine:
    """Evaluate a rulebook over (metrics snapshot, introspect snapshot)
    at every epoch/step boundary and manage each rule's
    firing -> resolved lifecycle.

    `telemetry` (optional) receives the side effects of every
    transition: one ``health_alert`` event (kind, rule, severity,
    state, value, threshold, step) and — on firing only — one
    ``health_alerts_total{rule,severity}`` counter increment. The
    engine itself never reads the clock: determinism is the contract
    the smoke gate pins.
    """

    def __init__(
        self,
        rules: Optional[Sequence[Union[HealthRule, Dict]]] = None,
        telemetry=None,
    ):
        self.rules: List[HealthRule] = [
            HealthRule.from_spec(r)
            for r in (default_rulebook() if rules is None else rules)
        ]
        names = [r.name for r in self.rules]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(f"duplicate health rule name(s): {sorted(dupes)}")
        self.telemetry = telemetry
        self._lock = threading.Lock()
        self._state: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules
        }
        #: every transition ever produced, in evaluation order
        self.alerts: List[Dict[str, Any]] = []

    # ---------------------------------------------------------- evaluate

    def evaluate(
        self,
        snapshot: Optional[Dict] = None,
        introspect: Optional[Dict] = None,
        step: Optional[int] = None,
        epoch: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """One evaluation round. Returns the transitions produced this
        round (possibly empty): dicts with ``rule``, ``severity``,
        ``state`` (``firing``/``resolved``), ``value``, ``threshold``,
        ``step``, ``description``."""
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            for rule in self.rules:
                st = self._state[rule.name]
                raw = _resolve(rule.metric, snapshot, introspect)
                if raw is None:
                    continue  # source cannot answer: state frozen
                if rule.mode == "delta":
                    base = st.prev_raw if st.prev_raw is not None else 0.0
                    value = raw - base
                    st.prev_raw = raw
                else:
                    value = raw
                st.last_value = value
                breach = COMPARATORS[rule.compare](value, rule.threshold)
                if breach:
                    st.streak += 1
                    if not st.firing and st.streak >= rule.for_steps:
                        st.firing = True
                        st.fired_step = step
                        transitions.append(
                            self._transition(rule, "firing", value, step, epoch)
                        )
                else:
                    st.streak = 0
                    if st.firing:
                        st.firing = False
                        transitions.append(
                            self._transition(rule, "resolved", value, step, epoch)
                        )
            self.alerts.extend(transitions)
        # telemetry side effects outside the engine lock (the registry
        # and event log have their own locks; holding ours across their
        # IO would invert the lock-discipline blocking rule)
        tel = self.telemetry
        if tel:
            for tr in transitions:
                if tr["state"] == "firing":
                    tel.inc(
                        "health_alerts_total",
                        rule=tr["rule"], severity=tr["severity"],
                    )
                tel.event(
                    "health_alert",
                    epoch=epoch,
                    rule=tr["rule"], severity=tr["severity"],
                    state=tr["state"], value=tr["value"],
                    threshold=tr["threshold"], step=tr["step"],
                    description=tr["description"],
                )
        return transitions

    @staticmethod
    def _transition(rule, state, value, step, epoch) -> Dict[str, Any]:
        return {
            "rule": rule.name,
            "severity": rule.severity,
            "state": state,
            "metric": rule.metric,
            "value": round(float(value), 6),
            "threshold": rule.threshold,
            "step": step,
            "epoch": epoch,
            "description": rule.description,
        }

    # ------------------------------------------------------------ queries

    def active(self) -> List[Dict[str, Any]]:
        """Currently firing alerts (rule, severity, since-step, last
        value), stable rulebook order."""
        with self._lock:
            return [
                {
                    "rule": r.name,
                    "severity": r.severity,
                    "since_step": self._state[r.name].fired_step,
                    "value": self._state[r.name].last_value,
                    "threshold": r.threshold,
                    "description": r.description,
                }
                for r in self.rules
                if self._state[r.name].firing
            ]

    def has_critical(self) -> bool:
        with self._lock:
            return any(
                r.severity == "critical" and self._state[r.name].firing
                for r in self.rules
            )

    def summary(self) -> Dict[str, Any]:
        """JSON-able engine snapshot for ``introspect()["health"]`` and
        the ``status`` CLI: firing alerts, per-severity firing counts,
        total transitions, and the rulebook size."""
        with self._lock:
            firing = [
                {
                    "rule": r.name,
                    "severity": r.severity,
                    "since_step": self._state[r.name].fired_step,
                    "value": self._state[r.name].last_value,
                }
                for r in self.rules
                if self._state[r.name].firing
            ]
            counts: Dict[str, int] = {}
            for f in firing:
                counts[f["severity"]] = counts.get(f["severity"], 0) + 1
            return {
                "status": (
                    "critical"
                    if any(f["severity"] == "critical" for f in firing)
                    else ("alerting" if firing else "ok")
                ),
                "firing": firing,
                "firing_counts": counts,
                "transitions_total": len(self.alerts),
                "rules": len(self.rules),
            }

    def transitions(
        self, epoch: Optional[int] = None, state: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        """Recorded transitions, optionally filtered by epoch and/or
        state — the per-epoch slice is what the driver persists to
        HDF5 beside the spans."""
        with self._lock:
            out = list(self.alerts)
        if epoch is not None:
            out = [t for t in out if t.get("epoch") == epoch]
        if state is not None:
            out = [t for t in out if t.get("state") == state]
        return out

    def fired(self) -> List[tuple]:
        """The deduplicated ``(rule, severity)`` set that has EVER
        fired, sorted — the exact object the smoke gate pins against
        its expected alert set."""
        with self._lock:
            return sorted(
                {
                    (t["rule"], t["severity"])
                    for t in self.alerts
                    if t["state"] == "firing"
                }
            )
