"""OpenMetrics exposition: render the metrics registry as scrape text,
serve it (plus health and introspection) over stdlib HTTP.

Two layers, both dependency-free:

- `render_openmetrics(snapshot)` turns a `MetricsRegistry.snapshot()`
  dict into OpenMetrics 1.0 text (counters, gauges, histograms with
  cumulative buckets, terminated by ``# EOF``), and
  `parse_openmetrics(text)` is the in-repo validating parser the tests
  round-trip through — exposition output must parse cleanly AND agree
  exactly with the snapshot it rendered.
- `MetricsExporter` is the first network-facing surface of the stack
  (the substrate ROADMAP item 1's front door grows from): an opt-in
  ``http.server`` thread serving

  - ``/metrics``  — the OpenMetrics rendering of a live snapshot,
  - ``/healthz``  — the health engine's alert state as JSON; responds
    ``503`` while any **critical** alert is firing, ``200`` otherwise
    (the k8s-style liveness contract), and
  - ``/statusz`` — the full ``introspect()`` snapshot as JSON.

  Written under the PR 11 concurrency rules: the server thread is an
  instance attribute joined on ``close()``, request handlers only call
  the three injected snapshot callbacks (each internally locked by its
  owner — registry lock, health-engine lock, service lock), and the
  exporter holds no mutable shared state of its own.

Naming: OpenMetrics requires counter *samples* to carry the ``_total``
suffix on their family name. Registry counters already named
``*_total`` expose family = name minus the suffix; the two cumulative
seconds counters without it (``tenant_cost_seconds``,
``tenant_device_seconds``) expose family = registry name and sample =
``<name>_total``. `parse_openmetrics` + `samples_as_snapshot` undo the
mapping, which is how the agree-exactly test closes the loop.
"""

from __future__ import annotations

import json
import math
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from dmosopt_tpu.utils import json_default

CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"


# ---------------------------------------------------------------- rendering


def _escape_label_value(v: str) -> str:
    return (
        v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _parse_label_str(label_str: str) -> List[Tuple[str, str]]:
    """Invert the registry's ``k=v,k2=v2`` label-series key. Label
    KEYS are code-controlled keyword identifiers (never ``,`` or
    ``=``), so each part's key is everything before its first ``=``;
    a value containing ``=`` stays intact, and a part WITHOUT ``=`` is
    a comma that belonged to the previous value (user-supplied
    ``opt_id``s land in ``tenant=`` labels verbatim) and is rejoined.
    The one residual ambiguity — a value containing the exact pattern
    ``,<word>=`` — is inherent to the flat key format."""
    if not label_str:
        return []
    out: List[Tuple[str, str]] = []
    for part in label_str.split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            out.append((k, v))
        elif out:
            k, v = out[-1]
            out[-1] = (k, v + "," + part)
    return out


def _format_labels(pairs: List[Tuple[str, str]]) -> str:
    if not pairs:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in pairs
    )
    return "{" + inner + "}"


def _format_value(v: float) -> str:
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _counter_family(name: str) -> str:
    return name[: -len("_total")] if name.endswith("_total") else name


def render_openmetrics(snapshot: Dict[str, Any]) -> str:
    """OpenMetrics 1.0 text for one registry snapshot. Families are
    emitted in sorted name order, series in sorted label order, so the
    output is byte-deterministic for a given snapshot."""
    lines: List[str] = []

    for name in sorted(snapshot.get("counters", {})):
        series = snapshot["counters"][name]
        family = _counter_family(name)
        lines.append(f"# TYPE {family} counter")
        for label_str in sorted(series):
            labels = _format_labels(_parse_label_str(label_str))
            lines.append(
                f"{family}_total{labels} "
                f"{_format_value(series[label_str])}"
            )

    for name in sorted(snapshot.get("gauges", {})):
        series = snapshot["gauges"][name]
        lines.append(f"# TYPE {name} gauge")
        for label_str in sorted(series):
            labels = _format_labels(_parse_label_str(label_str))
            lines.append(
                f"{name}{labels} {_format_value(series[label_str])}"
            )

    for name in sorted(snapshot.get("histograms", {})):
        series = snapshot["histograms"][name]
        lines.append(f"# TYPE {name} histogram")
        for label_str in sorted(series):
            summary = series[label_str]
            base = _parse_label_str(label_str)
            # snapshot buckets are per-bucket counts at the recorded
            # (non-zero) boundaries; OpenMetrics buckets are cumulative
            bounds = sorted(
                (
                    (math.inf if b == "inf" else float(b)), c
                )
                for b, c in (summary.get("buckets") or {}).items()
            )
            cum = 0
            for bound, count in bounds:
                cum += count
                if math.isinf(bound):
                    continue  # +Inf is emitted once below, = count
                lines.append(
                    f"{name}_bucket"
                    f"{_format_labels(base + [('le', _format_value(bound))])}"
                    f" {cum}"
                )
            lines.append(
                f"{name}_bucket"
                f"{_format_labels(base + [('le', '+Inf')])}"
                f" {summary['count']}"
            )
            lines.append(
                f"{name}_count{_format_labels(base)} {summary['count']}"
            )
            lines.append(
                f"{name}_sum{_format_labels(base)} "
                f"{_format_value(summary['sum'])}"
            )

    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# ------------------------------------------------------------------ parsing


class OpenMetricsParseError(ValueError):
    """Exposition text violating the (subset of the) OpenMetrics spec
    this stack emits."""


def _parse_sample_line(line: str) -> Tuple[str, Dict[str, str], float]:
    name, labels_part, rest = line, "", None
    if "{" in line:
        name, _, tail = line.partition("{")
        labels_part, closed, rest = tail.partition("}")
        if not closed:
            raise OpenMetricsParseError(f"unclosed label braces: {line!r}")
        rest = rest.strip()
    else:
        name, _, rest = line.partition(" ")
    if rest is None or not rest:
        raise OpenMetricsParseError(f"sample without a value: {line!r}")
    name = name.strip()
    if not name or not name.replace("_", "a").isalnum():
        raise OpenMetricsParseError(f"invalid sample name: {line!r}")
    labels: Dict[str, str] = {}
    if labels_part:
        # labels are k="v" pairs; values were escaped by the renderer
        for m_k, m_v in _iter_label_pairs(labels_part, line):
            labels[m_k] = m_v
    value_str = rest.split()[0]
    if value_str == "+Inf":
        value = math.inf
    elif value_str == "-Inf":
        value = -math.inf
    else:
        try:
            value = float(value_str)
        except ValueError as e:
            raise OpenMetricsParseError(
                f"non-numeric sample value: {line!r}"
            ) from e
    return name, labels, value


def _iter_label_pairs(labels_part: str, line: str):
    i, n = 0, len(labels_part)
    while i < n:
        eq = labels_part.find("=", i)
        if eq < 0:
            raise OpenMetricsParseError(f"malformed labels: {line!r}")
        key = labels_part[i:eq]
        if eq + 1 >= n or labels_part[eq + 1] != '"':
            raise OpenMetricsParseError(f"unquoted label value: {line!r}")
        j = eq + 2
        buf = []
        while j < n:
            ch = labels_part[j]
            if ch == "\\" and j + 1 < n:
                esc = labels_part[j + 1]
                buf.append(
                    {"n": "\n", "\\": "\\", '"': '"'}.get(esc, esc)
                )
                j += 2
                continue
            if ch == '"':
                break
            buf.append(ch)
            j += 1
        else:
            raise OpenMetricsParseError(f"unterminated label value: {line!r}")
        yield key, "".join(buf)
        i = j + 1
        if i < n and labels_part[i] == ",":
            i += 1


def parse_openmetrics(text: str) -> Dict[str, Dict[str, Any]]:
    """Validating parser for the exposition subset this module emits.

    Returns ``{family: {"type": ..., "samples": [(sample_name, labels,
    value), ...]}}``. Raises `OpenMetricsParseError` on: missing
    ``# EOF`` terminator (or content after it), samples before their
    ``# TYPE`` declaration, counter samples without the ``_total``
    suffix, histogram sample names outside the
    ``_bucket``/``_count``/``_sum`` triple, non-cumulative histogram
    buckets, a ``+Inf`` bucket disagreeing with ``_count``, negative
    counter/histogram values, or duplicate series."""
    families: Dict[str, Dict[str, Any]] = {}
    current: Optional[str] = None
    saw_eof = False
    seen_series = set()
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if saw_eof:
            raise OpenMetricsParseError("content after # EOF")
        if line == "# EOF":
            saw_eof = True
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                raise OpenMetricsParseError(f"malformed TYPE line: {line!r}")
            _, _, family, mtype = parts
            if mtype not in ("counter", "gauge", "histogram"):
                raise OpenMetricsParseError(
                    f"unsupported metric type {mtype!r}"
                )
            if family in families:
                raise OpenMetricsParseError(
                    f"duplicate TYPE declaration for {family!r}"
                )
            families[family] = {"type": mtype, "samples": []}
            current = family
            continue
        if line.startswith("#"):
            continue  # HELP/UNIT lines are legal, uninterpreted
        name, labels, value = _parse_sample_line(line)
        if current is None or not name.startswith(current):
            raise OpenMetricsParseError(
                f"sample {name!r} outside its family block"
            )
        mtype = families[current]["type"]
        suffix = name[len(current):]
        if mtype == "counter":
            if suffix != "_total":
                raise OpenMetricsParseError(
                    f"counter sample {name!r} must end in _total"
                )
            if value < 0:
                raise OpenMetricsParseError(
                    f"negative counter value on {name!r}"
                )
        elif mtype == "gauge":
            if suffix != "":
                raise OpenMetricsParseError(
                    f"gauge sample {name!r} must match its family name"
                )
        else:  # histogram
            if suffix not in ("_bucket", "_count", "_sum"):
                raise OpenMetricsParseError(
                    f"histogram sample {name!r} has invalid suffix"
                )
            if suffix == "_bucket" and "le" not in labels:
                raise OpenMetricsParseError(
                    f"histogram bucket without le label: {name!r}"
                )
            if suffix in ("_bucket", "_count") and value < 0:
                raise OpenMetricsParseError(
                    f"negative histogram value on {name!r}"
                )
        series_key = (name, tuple(sorted(labels.items())))
        if series_key in seen_series:
            raise OpenMetricsParseError(f"duplicate series {series_key!r}")
        seen_series.add(series_key)
        families[current]["samples"].append((name, labels, value))
    if not saw_eof:
        raise OpenMetricsParseError("missing # EOF terminator")
    _validate_histograms(families)
    return families


def _validate_histograms(families: Dict[str, Dict[str, Any]]):
    for family, fam in families.items():
        if fam["type"] != "histogram":
            continue
        # group by base label set
        groups: Dict[tuple, Dict[str, Any]] = {}
        for name, labels, value in fam["samples"]:
            base = tuple(
                sorted((k, v) for k, v in labels.items() if k != "le")
            )
            g = groups.setdefault(base, {"buckets": [], "count": None, "sum": None})
            suffix = name[len(family):]
            if suffix == "_bucket":
                le = labels["le"]
                bound = math.inf if le == "+Inf" else float(le)
                g["buckets"].append((bound, value))
            elif suffix == "_count":
                g["count"] = value
            else:
                g["sum"] = value
        for base, g in groups.items():
            if g["count"] is None or g["sum"] is None:
                raise OpenMetricsParseError(
                    f"histogram {family}{dict(base)} missing _count/_sum"
                )
            buckets = sorted(g["buckets"])
            if not buckets or not math.isinf(buckets[-1][0]):
                raise OpenMetricsParseError(
                    f"histogram {family}{dict(base)} missing +Inf bucket"
                )
            prev = -math.inf
            last = 0.0
            for bound, value in buckets:
                if bound <= prev:
                    raise OpenMetricsParseError(
                        f"histogram {family}{dict(base)} duplicate le"
                    )
                if value < last:
                    raise OpenMetricsParseError(
                        f"histogram {family}{dict(base)} buckets are not "
                        f"cumulative"
                    )
                prev, last = bound, value
            if buckets[-1][1] != g["count"]:
                raise OpenMetricsParseError(
                    f"histogram {family}{dict(base)} +Inf bucket "
                    f"!= _count"
                )


def samples_as_snapshot(
    families: Dict[str, Dict[str, Any]]
) -> Dict[str, Dict[str, Dict[str, float]]]:
    """Fold parsed counter/gauge families back into the registry's
    ``{"counters": {name: {label_str: value}}, "gauges": ...}`` shape
    (histogram summaries are not invertible from cumulative buckets —
    the agree-exactly test checks their count/sum samples directly)."""
    out: Dict[str, Dict[str, Dict[str, float]]] = {
        "counters": {}, "gauges": {},
    }
    for family, fam in families.items():
        if fam["type"] == "counter":
            for _name, labels, value in fam["samples"]:
                key = ",".join(
                    f"{k}={v}" for k, v in sorted(labels.items())
                )
                out["counters"].setdefault(family, {})[key] = value
        elif fam["type"] == "gauge":
            for _name, labels, value in fam["samples"]:
                key = ",".join(
                    f"{k}={v}" for k, v in sorted(labels.items())
                )
                out["gauges"].setdefault(family, {})[key] = value
    return out


# ----------------------------------------------------------------- exporter


class MetricsExporter:
    """Opt-in stdlib-HTTP exposition thread.

    ``snapshot_fn`` returns a `MetricsRegistry.snapshot()` dict (served
    on ``/metrics``); ``health_fn`` (optional) returns a
    `HealthEngine.summary()` dict (``/healthz``; ``503`` while its
    ``status`` is ``critical``); ``status_fn`` (optional) returns the
    ``introspect()`` snapshot (``/statusz``). Each callback is expected
    to do its own locking — the exporter adds no shared mutable state.

    Lifecycle (the PR 11 resource rule): `start()` binds the socket and
    launches one ``serve_forever`` thread; `close()` shuts the server
    down, joins the thread, and closes the socket. Request handling is
    single-threaded (one scrape at a time), which bounds the exposure
    surface of a misbehaving scraper to one queued request.
    """

    def __init__(
        self,
        snapshot_fn: Callable[[], Dict],
        health_fn: Optional[Callable[[], Optional[Dict]]] = None,
        status_fn: Optional[Callable[[], Dict]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        logger=None,
    ):
        self.snapshot_fn = snapshot_fn
        self.health_fn = health_fn
        self.status_fn = status_fn
        self.host = host
        self._requested_port = int(port)
        self.logger = logger
        self._server = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- server

    def start(self) -> "MetricsExporter":
        if self._server is not None:
            return self
        import http.server

        exporter = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # socket timeout per connection: the server is
            # single-threaded, so an idle keep-alive client (Prometheus
            # scrapers keep connections open between scrapes) would
            # otherwise hold serve_forever inside rfile.readline()
            # forever — blocking every other scraper AND the
            # server.shutdown() call in close()
            timeout = 5.0

            def log_message(self, fmt, *args):  # silence stderr chatter
                if exporter.logger is not None:
                    exporter.logger.debug(
                        "exporter: " + fmt % args
                    )

            def _send(self, code: int, body: bytes, content_type: str):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/metrics":
                        body = render_openmetrics(
                            exporter.snapshot_fn()
                        ).encode("utf-8")
                        self._send(200, body, CONTENT_TYPE)
                    elif path == "/healthz":
                        summary = (
                            exporter.health_fn()
                            if exporter.health_fn is not None
                            else None
                        )
                        if summary is None:
                            summary = {"status": "ok", "firing": []}
                        code = (
                            503 if summary.get("status") == "critical"
                            else 200
                        )
                        body = json.dumps(
                            summary, default=json_default
                        ).encode("utf-8")
                        self._send(code, body, "application/json")
                    elif path == "/statusz":
                        snap = (
                            exporter.status_fn()
                            if exporter.status_fn is not None
                            else {}
                        )
                        body = json.dumps(
                            snap, default=json_default
                        ).encode("utf-8")
                        self._send(200, body, "application/json")
                    else:
                        self._send(
                            404,
                            b'{"error": "not found; try /metrics, '
                            b'/healthz, /statusz"}',
                            "application/json",
                        )
                except Exception as e:  # a broken snapshot must not
                    # kill the exporter thread: the scrape gets a 500
                    try:
                        self._send(
                            500,
                            json.dumps({"error": str(e)}).encode("utf-8"),
                            "application/json",
                        )
                    except OSError:
                        pass  # client already gone

        self._server = http.server.HTTPServer(
            (self.host, self._requested_port), _Handler
        )
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="dmosopt-metrics-exporter",
            daemon=True,
        )
        self._thread.start()
        return self

    @property
    def port(self) -> Optional[int]:
        return (
            self._server.server_address[1]
            if self._server is not None
            else None
        )

    @property
    def url(self) -> Optional[str]:
        return (
            f"http://{self.host}:{self.port}"
            if self._server is not None
            else None
        )

    def close(self):
        server, self._server = self._server, None
        thread, self._thread = self._thread, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=10.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False
