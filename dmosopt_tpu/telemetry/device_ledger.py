"""Device-time ledger: per-compiled-program device truth.

Every performance number the project steered by before this module was
a host wall clock, and the project's own history shows host clocks lie:
BENCH_r04/r05 walls were 3-9x inflated by host contention (and silently
ran on CPU fallback), while ROADMAP items 2 and 6 gate on overlap and
on "profile what's left" — both questions about *device-busy* time,
which no `time.perf_counter()` difference can answer. The ledger is the
ground-truth layer those decisions read:

- **Compile-side accounting** (source a): every observably compiled
  program — the batched core's bucket programs (`dmosopt_tpu.tenants`,
  `fn.lower().compile()` since PR 9) and the sequential path's
  generation-loop program (`moasmo._optimize_on_device`, made explicit
  by this module's PR) — records compile wall seconds, XLA
  cost-analysis FLOPs / bytes-accessed, and the executable's memory
  footprint (argument + output + temp bytes: the HBM the program pins
  while it runs) into per-program rows via `record_compile`.
- **Trace-side accounting** (source b): when profiling is armed
  (`profile_dir` / `profile_epochs`, the plumbing PR 1 added), the
  owning driver/service wraps designated epochs in
  `Telemetry.device_capture`, which runs `jax.profiler`
  start/stop_trace and hands the captured Chrome trace to
  `ingest_chrome_trace`. The parser splits the trace into **host
  lanes** (the Python threads, where every `Tracer.span` also entered a
  same-named `jax.profiler.TraceAnnotation`) and **device lanes**
  (`/device:*` processes on TPU/GPU; the `tf_XLAEigen*` XLA threadpool
  workers on the CPU backend), joins each host span to its annotation
  occurrence BY NAME AND ORDER, and charges the device-lane busy time
  inside each annotation window to that span's program row. From the
  same pass it derives `device_busy_fraction` (device-busy union over
  the capture window) and `device_overlap_ratio` (device-busy union
  over the device timeline's extent — 1.0 means the device never
  idled between programs, the ROADMAP item 2/6 success metric), and
  attributes device seconds per tenant through the `tenant_cost` child
  spans that tile each bucket span.

The host-clock gauge (`pipeline_overlap_ratio`, driver.py) stays as the
cheap always-on estimate; the ledger is the ground truth whenever
profiling is armed. This module is deliberately **jax-free** (pure
parsing and bookkeeping — the `jax.profiler` calls live in
`Telemetry.device_capture`); the compiled-object helpers below only
duck-type `cost_analysis()` / `memory_analysis()`.

Nothing here runs on a hot path: `record_compile` fires once per
compiled shape, trace ingestion only on explicitly profiled epochs, and
a `telemetry=False` run holds no ledger at all (the zero-object pin).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: thread-name prefix of the XLA CPU backend's compute threadpool — the
#: "device lanes" of a CPU capture (TPU/GPU captures have real
#: `/device:*` processes instead)
_CPU_DEVICE_THREAD_PREFIX = "tf_XLAEigen"
#: zero-duration bookkeeping markers the CPU threadpool interleaves
#: with its real op events — never busy time
_MARKER_PREFIX = "ThreadpoolListener::"


# ------------------------------------------------- compiled-object helpers


def compiled_cost_estimates(compiled) -> Tuple[Optional[float], Optional[float]]:
    """(flops, bytes accessed) from XLA's cost analysis of a compiled
    executable; (None, None) where the backend does not report it."""
    try:
        analyses = compiled.cost_analysis()
        if isinstance(analyses, dict):
            analyses = [analyses]
        flops = sum(float(a.get("flops", 0.0)) for a in analyses)
        nbytes = sum(float(a.get("bytes accessed", 0.0)) for a in analyses)
        return flops, nbytes
    except Exception:
        return None, None


def compiled_memory_bytes(compiled) -> Optional[float]:
    """The executable's device-memory footprint (argument + output +
    temp bytes — what the program pins in HBM while it runs), or None
    where the backend does not report a memory analysis."""
    try:
        ma = compiled.memory_analysis()
        total = 0.0
        for attr in (
            "argument_size_in_bytes",
            "output_size_in_bytes",
            "temp_size_in_bytes",
        ):
            total += float(getattr(ma, attr, 0) or 0)
        return total
    except Exception:
        return None


# ----------------------------------------------------- interval utilities


def _merge_intervals(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Sorted union of (start, end) intervals."""
    out: List[Tuple[float, float]] = []
    for s, e in sorted(intervals):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def _total(intervals: Sequence[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in intervals)


def _clipped_total(
    intervals: Sequence[Tuple[float, float]], lo: float, hi: float
) -> float:
    """Total length of `intervals` clipped to [lo, hi] (intervals must
    already be a merged union, so the sum never double-counts)."""
    out = 0.0
    for s, e in intervals:
        if e <= lo:
            continue
        if s >= hi:
            break
        out += min(e, hi) - max(s, lo)
    return out


def _spans_overlap(spans) -> bool:
    """True when any two spans (sorted by t_start) overlap in host time
    — the signature of concurrent same-name scheduler nodes."""
    prev_end = None
    for s in spans:
        if prev_end is not None and s.t_start < prev_end:
            return True
        end = s.t_end if s.t_end is not None else s.t_start
        prev_end = end if prev_end is None else max(prev_end, end)
    return False


def _assign_windows(
    name_spans, windows: List[Tuple[float, float]]
) -> List[Optional[int]]:
    """Map each same-name host span (sorted by start) to the index of
    its annotation window, or None when unjoined.

    Serial spans — no host-time overlap, the lockstep/concurrency-1 case
    — keep the exact rank join with tail alignment: the k-th surviving
    span matches the k-th most-recent window. Under the concurrent
    task-graph scheduler, same-name spans from different worker threads
    can overlap, and their host-clock start order no longer predicts the
    trace-clock window order (the profiler orders windows by device
    enqueue); rank-joining would cross-wire device time between
    tenants' buckets. Overlapping spans instead greedily match each
    span (longest first) to the unused window whose duration is closest
    to the span's own — concurrent same-name spans carry distinct
    workloads, hence measurably distinct durations."""
    n_s, n_w = len(name_spans), len(windows)
    if not _spans_overlap(name_spans):
        offset = max(n_w - n_s, 0)
        return [
            (i + offset) if (i + offset) < n_w else None
            for i in range(n_s)
        ]
    assigned: List[Optional[int]] = [None] * n_s
    used = set()
    order = sorted(
        range(n_s), key=lambda i: -(name_spans[i].duration_s or 0.0)
    )
    for i in order:
        dur = name_spans[i].duration_s or 0.0
        best, best_diff = None, None
        for j in range(n_w):
            if j in used:
                continue
            diff = abs((windows[j][1] - windows[j][0]) - dur)
            if best_diff is None or diff < best_diff:
                best, best_diff = j, diff
        if best is not None:
            used.add(best)
            assigned[i] = best
    return assigned


# ------------------------------------------------------------ trace parse


@dataclass
class ParsedTrace:
    """One capture's relevant content, in seconds relative to the
    trace's own clock: per-name annotation windows (host lanes) and the
    per-lane merged busy intervals of every device lane."""

    annotations: Dict[str, List[Tuple[float, float]]]
    device_lanes: Dict[Tuple[Any, Any], List[Tuple[float, float]]]
    window: Tuple[float, float]  # extent of ALL trace events

    @property
    def device_busy(self) -> List[Tuple[float, float]]:
        """Union of busy intervals across every device lane."""
        merged: List[Tuple[float, float]] = []
        for lane in self.device_lanes.values():
            merged.extend(lane)
        return _merge_intervals(merged)


def parse_chrome_trace(trace: Dict[str, Any], span_names) -> ParsedTrace:
    """Split a `jax.profiler` Chrome trace into annotation windows (host
    events named exactly like one of `span_names` — the
    `TraceAnnotation`s every `Tracer.span` enters) and device-lane busy
    intervals (`/device:*` process events on accelerators, `tf_XLAEigen*`
    worker-thread events on the CPU backend, bookkeeping markers
    excluded)."""
    names = set(span_names)
    events = trace.get("traceEvents", []) or []
    device_pids = set()
    thread_names: Dict[Tuple[Any, Any], str] = {}
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "M":
            continue
        if ev.get("name") == "process_name":
            pname = str((ev.get("args") or {}).get("name", ""))
            if pname.startswith("/device:"):
                device_pids.add(ev.get("pid"))
        elif ev.get("name") == "thread_name":
            thread_names[(ev.get("pid"), ev.get("tid"))] = str(
                (ev.get("args") or {}).get("name", "")
            )

    annotations: Dict[str, List[Tuple[float, float]]] = {}
    lanes: Dict[Tuple[Any, Any], List[Tuple[float, float]]] = {}
    lo, hi = float("inf"), float("-inf")
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        ts = ev.get("ts")
        dur = ev.get("dur", 0)
        if not isinstance(ts, (int, float)) or not isinstance(dur, (int, float)):
            continue
        t0, t1 = ts / 1e6, (ts + dur) / 1e6
        lo, hi = min(lo, t0), max(hi, t1)
        key = (ev.get("pid"), ev.get("tid"))
        name = ev.get("name", "")
        on_device = ev.get("pid") in device_pids or thread_names.get(
            key, ""
        ).startswith(_CPU_DEVICE_THREAD_PREFIX)
        if on_device:
            if dur > 0 and not str(name).startswith(_MARKER_PREFIX):
                lanes.setdefault(key, []).append((t0, t1))
        elif name in names:
            annotations.setdefault(name, []).append((t0, t1))

    for key in lanes:
        lanes[key] = _merge_intervals(lanes[key])
    for name in annotations:
        annotations[name].sort()
    if lo > hi:
        lo = hi = 0.0
    return ParsedTrace(annotations=annotations, device_lanes=lanes, window=(lo, hi))


def load_capture(profile_dir: str, newer_than: Optional[float] = None):
    """The newest `jax.profiler` capture under `profile_dir`
    (`plugins/profile/<timestamp>/*.trace.json.gz`), parsed to a trace
    dict — or None when no capture (newer than `newer_than`, an
    mtime-seconds bound) exists."""
    paths = glob.glob(
        os.path.join(profile_dir, "plugins", "profile", "*", "*.trace.json*")
    )
    try:
        # a trace file can vanish between glob and stat (tmp cleaners,
        # concurrent cleanup of a shared profile_dir) — an unreadable
        # capture must never take the profiled epoch down
        if newer_than is not None:
            paths = [
                p for p in paths if os.path.getmtime(p) >= newer_than - 1.0
            ]
        if not paths:
            return None
        path = max(paths, key=os.path.getmtime)
    except OSError:
        return None
    opener = gzip.open if path.endswith(".gz") else open
    try:
        with opener(path, "rt") as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError, EOFError):
        return None


# ----------------------------------------------------------------- ledger


@dataclass
class ProgramRow:
    """Cumulative device-truth accounting for one program identity
    (host-span/annotation name + bucket label)."""

    program: str
    bucket: Optional[str] = None
    compiles: int = 0
    retraces: int = 0
    compile_s: float = 0.0
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    memory_bytes: Optional[float] = None
    device_time_s: float = 0.0
    host_time_s: float = 0.0
    n_spans: int = 0  # host spans seen during captures
    n_joined: int = 0  # host spans matched to a trace annotation

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "program": self.program,
            "compiles": self.compiles,
            "compile_s": round(self.compile_s, 6),
            "device_time_s": round(self.device_time_s, 6),
            "host_time_s": round(self.host_time_s, 6),
            "n_spans": self.n_spans,
            "n_joined": self.n_joined,
        }
        if self.bucket:
            out["bucket"] = self.bucket
        if self.retraces:
            out["retraces"] = self.retraces
        for k in ("flops", "bytes_accessed", "memory_bytes"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        if self.n_spans:
            out["join_fraction"] = round(self.n_joined / self.n_spans, 4)
        return out


@dataclass
class CaptureSummary:
    """One ingested profiler capture, already reduced to the ledger's
    vocabulary (seconds; fractions in [0, 1] where defined)."""

    window_s: float
    device_busy_s: float
    device_busy_fraction: Optional[float]
    device_overlap_ratio: Optional[float]
    n_spans: int
    n_joined: int
    tenant_device_seconds: Dict[Tuple[str, str], float] = field(default_factory=dict)

    @property
    def join_fraction(self) -> Optional[float]:
        return (self.n_joined / self.n_spans) if self.n_spans else None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "window_s": round(self.window_s, 6),
            "device_busy_s": round(self.device_busy_s, 6),
            "device_busy_fraction": (
                round(self.device_busy_fraction, 4)
                if self.device_busy_fraction is not None
                else None
            ),
            "device_overlap_ratio": (
                round(self.device_overlap_ratio, 4)
                if self.device_overlap_ratio is not None
                else None
            ),
            "n_spans": self.n_spans,
            "n_joined": self.n_joined,
            "join_fraction": (
                round(self.join_fraction, 4)
                if self.join_fraction is not None
                else None
            ),
        }


class DeviceLedger:
    """Per-compiled-program device accounting: compile-side rows fed by
    `record_compile`, trace-side device times folded in by
    `ingest_chrome_trace`. Thread-safe (compiles can land from the
    batched fit's worker threads)."""

    def __init__(self):
        self._rows: Dict[Tuple[str, Optional[str]], ProgramRow] = {}
        self._tenant_device: Dict[Tuple[str, str], float] = {}
        self.captures = 0
        self.last_capture: Optional[CaptureSummary] = None
        self._lock = threading.Lock()

    # ----------------------------------------------------------- compiles

    def record_compile(
        self,
        program: str,
        compile_s: float,
        *,
        flops: Optional[float] = None,
        bytes_accessed: Optional[float] = None,
        memory_bytes: Optional[float] = None,
        bucket: Optional[str] = None,
        retrace: bool = False,
    ) -> ProgramRow:
        """Record one observable compile of `program` (the host-span /
        annotation name its executions run under, e.g. ``ea_scan``)."""
        with self._lock:
            row = self._row_locked(program, bucket)
            row.compiles += 1
            row.compile_s += float(compile_s)
            if retrace:
                row.retraces += 1
            # cost/memory describe the LATEST executable (a retrace may
            # change shapes, and the newest program is the one running)
            if flops is not None:
                row.flops = float(flops)
            if bytes_accessed is not None:
                row.bytes_accessed = float(bytes_accessed)
            if memory_bytes is not None:
                row.memory_bytes = float(memory_bytes)
            return row

    def _row_locked(self, program: str, bucket: Optional[str]) -> ProgramRow:
        key = (program, bucket)
        row = self._rows.get(key)
        if row is None:
            row = self._rows[key] = ProgramRow(program=program, bucket=bucket)
        return row

    # ------------------------------------------------------------- traces

    def ingest_chrome_trace(
        self, trace: Dict[str, Any], host_spans
    ) -> Optional[CaptureSummary]:
        """Join one profiler capture against the host spans recorded
        during it and fold device times into the program rows.

        `host_spans`: the CLOSED `telemetry.tracing.Span`s opened while
        the capture ran (the caller brackets the capture with
        `Tracer.mark` / `spans_since`). Joining is per span name: when
        same-name spans are serial, the k-th host span named N matches
        the k-th trace annotation named N, because every `Tracer.span`
        entered exactly one same-named `TraceAnnotation` in open order;
        when they overlap (concurrent task-graph scheduler nodes),
        windows are matched by duration similarity instead
        (`_assign_windows`). Device time
        charged to a span is the device-lane busy union clipped to its
        annotation window; `tenant_cost` child spans split their
        parent's device seconds by their host-share weights (the same
        weights the host cost attribution uses)."""
        spans = [s for s in host_spans if s.t_end is not None]
        by_name: Dict[str, List] = {}
        children: Dict[int, List] = {}
        for s in spans:
            if s.name == "tenant_cost":
                if s.parent_id is not None:
                    children.setdefault(s.parent_id, []).append(s)
            else:
                by_name.setdefault(s.name, []).append(s)
        for lst in by_name.values():
            lst.sort(key=lambda s: (s.t_start, s.span_id))

        parsed = parse_chrome_trace(trace, by_name.keys())
        busy = parsed.device_busy
        window_s = max(parsed.window[1] - parsed.window[0], 0.0)
        busy_s = _total(busy)
        extent_s = (busy[-1][1] - busy[0][0]) if busy else 0.0

        cap = CaptureSummary(
            window_s=window_s,
            device_busy_s=busy_s,
            device_busy_fraction=(busy_s / window_s) if window_s > 0 else None,
            device_overlap_ratio=(busy_s / extent_s) if extent_s > 0 else None,
            n_spans=0,
            n_joined=0,
        )
        with self._lock:
            for name, name_spans in by_name.items():
                windows = parsed.annotations.get(name, [])
                # serial spans rank-join with eviction tail alignment
                # (the span buffer drops oldest-first); overlapping
                # spans — concurrent scheduler nodes — match windows by
                # duration similarity instead, see _assign_windows
                assign = _assign_windows(name_spans, windows)
                for i, sp in enumerate(name_spans):
                    bucket = (sp.labels or {}).get("bucket")
                    row = self._row_locked(name, bucket)
                    row.n_spans += 1
                    cap.n_spans += 1
                    row.host_time_s += sp.duration_s or 0.0
                    if assign[i] is None:
                        continue
                    a0, a1 = windows[assign[i]]
                    dev_s = _clipped_total(busy, a0, a1)
                    row.n_joined += 1
                    cap.n_joined += 1
                    row.device_time_s += dev_s
                    # per-tenant attribution: the tenant_cost children
                    # tile the parent span with the host attribution
                    # weights; reuse those shares for device seconds
                    kids = children.get(sp.span_id)
                    host_dur = sp.duration_s or 0.0
                    if kids and host_dur > 0 and dev_s > 0:
                        for kid in kids:
                            share = (kid.duration_s or 0.0) / host_dur
                            tenant = str((kid.labels or {}).get("tenant", "?"))
                            phase = str((kid.labels or {}).get("phase", "?"))
                            key = (tenant, phase)
                            amount = dev_s * share
                            cap.tenant_device_seconds[key] = (
                                cap.tenant_device_seconds.get(key, 0.0) + amount
                            )
                            self._tenant_device[key] = (
                                self._tenant_device.get(key, 0.0) + amount
                            )
            self.captures += 1
            self.last_capture = cap
        return cap

    def ingest_profile_dir(
        self, profile_dir: str, host_spans, newer_than: Optional[float] = None
    ) -> Optional[CaptureSummary]:
        """Locate, load, and ingest the newest capture under
        `profile_dir`. Returns None (no ledger mutation) when no capture
        is found or it fails to parse — an unreadable trace must never
        take the epoch down."""
        trace = load_capture(profile_dir, newer_than=newer_than)
        if trace is None:
            return None
        try:
            return self.ingest_chrome_trace(trace, host_spans)
        except Exception:
            return None

    # ------------------------------------------------------------ queries

    @property
    def has_data(self) -> bool:
        with self._lock:
            return bool(self._rows) or self.captures > 0

    @property
    def device_busy_fraction(self) -> Optional[float]:
        cap = self.last_capture
        return cap.device_busy_fraction if cap is not None else None

    @property
    def device_overlap_ratio(self) -> Optional[float]:
        cap = self.last_capture
        return cap.device_overlap_ratio if cap is not None else None

    def program_rows(self) -> List[ProgramRow]:
        with self._lock:
            return sorted(
                self._rows.values(), key=lambda r: (r.program, r.bucket or "")
            )

    def tenant_device_seconds(self) -> Dict[str, Dict[str, float]]:
        """{tenant: {phase: attributed device seconds}} (cumulative
        across captures)."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for (tenant, phase), v in self._tenant_device.items():
                out.setdefault(tenant, {})[phase] = round(v, 9)
        return out

    def summary(self) -> Dict[str, Any]:
        """JSON-able ledger snapshot: cumulative program rows, the last
        capture's fractions, and per-tenant device seconds — what
        `OptimizationService.introspect()` and the `status` CLI
        surface."""
        out: Dict[str, Any] = {
            "captures": self.captures,
            "programs": [r.to_dict() for r in self.program_rows()],
        }
        if self.last_capture is not None:
            out["last_capture"] = self.last_capture.to_dict()
            out["device_busy_fraction"] = self.last_capture.device_busy_fraction
            out["device_overlap_ratio"] = self.last_capture.device_overlap_ratio
        tenant = self.tenant_device_seconds()
        if tenant:
            out["tenant_device_seconds"] = tenant
        return out
