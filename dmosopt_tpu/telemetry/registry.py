"""Process-local metrics registry: counters, gauges, histograms.

The registry is deliberately tiny and dependency-free — a dict of
counters (monotonic floats), gauges (last value wins) and histograms
(fixed bucket boundaries, plus running min/max/sum/count), each keyed by
``(name, sorted label items)``. It is the in-process aggregation layer
under the telemetry facade: every emission is one dict update, cheap
enough to stay on by default, and `snapshot()` renders the whole state
as plain JSON-able types for logs, tests, and the HDF5 epoch summary.

Metric names are lowercase snake_case and must appear in the catalog in
``docs/observability.md`` (enforced by ``tools/lint_metrics.py`` /
``make lint-metrics``).
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Optional, Sequence, Tuple

# Log-spaced seconds-oriented default buckets: phase durations span
# ~1 ms (a cached surrogate predict) to minutes (a cold-compile epoch).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, math.inf,
)


def _label_key(labels: Dict) -> Tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_str(key: Tuple) -> str:
    return ",".join(f"{k}={v}" for k, v in key)


class _Histogram:
    __slots__ = ("buckets", "counts", "sum", "count", "min", "max")

    def __init__(self, buckets: Sequence[float]):
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs or bs[-1] != math.inf:
            bs = bs + (math.inf,)
        self.buckets = bs
        self.counts = [0] * len(bs)
        self.sum = 0.0
        self.count = 0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value: float):
        v = float(value)
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.sum += v
        self.count += 1
        self.min = min(self.min, v)
        self.max = max(self.max, v)

    def summary(self) -> Dict:
        out = {
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            "mean": (self.sum / self.count) if self.count else None,
            "buckets": {
                ("inf" if math.isinf(b) else repr(b)): c
                for b, c in zip(self.buckets, self.counts)
                if c
            },
        }
        return out


class MetricsRegistry:
    """Counters / gauges / histograms with labels.

    All mutators take the metric name, a value, and free-form keyword
    labels; each distinct label combination is an independent series.
    Thread-safe: the driver's evaluator thread pool may emit from
    worker threads.
    """

    # collapsed label set served once a metric exceeds the series limit
    _OVERFLOW_LABELS = (("overflow", "true"),)

    def __init__(
        self,
        histogram_buckets: Optional[Dict[str, Sequence[float]]] = None,
        series_limit: Optional[int] = 512,
    ):
        self._counters: Dict[Tuple, float] = {}
        self._gauges: Dict[Tuple, float] = {}
        self._histograms: Dict[Tuple, _Histogram] = {}
        self._buckets_by_name = dict(histogram_buckets or {})
        self._lock = threading.Lock()
        # label-cardinality guard: at most `series_limit` distinct label
        # combinations per metric name; later combinations collapse into
        # one {overflow="true"} series and are counted by the
        # `telemetry_series_overflow_total` counter. Per-tenant label
        # values at 64-256 tenants are exactly the explosion this
        # bounds; None disables the guard.
        self._series_limit = series_limit
        self._series_count: Dict[str, int] = {}

    # ------------------------------------------------------------ mutators

    def _guarded_key(self, store: Dict, name: str, labels: Dict) -> Tuple:
        """Series key for (name, labels), applying the cardinality
        guard. Caller must hold the lock."""
        key = (name, _label_key(labels))
        if self._series_limit is None or not labels or key in store:
            return key
        n = self._series_count.get(name, 0)
        if n >= self._series_limit:
            okey = ("telemetry_series_overflow_total", ())
            self._counters[okey] = self._counters.get(okey, 0.0) + 1.0
            return (name, self._OVERFLOW_LABELS)
        self._series_count[name] = n + 1
        return key

    def counter_inc(self, name: str, value: float = 1.0, **labels):
        if value < 0:
            raise ValueError(f"counter {name!r}: negative increment {value}")
        with self._lock:
            key = self._guarded_key(self._counters, name, labels)
            self._counters[key] = self._counters.get(key, 0.0) + float(value)

    def gauge_set(self, name: str, value: float, **labels):
        with self._lock:
            key = self._guarded_key(self._gauges, name, labels)
            self._gauges[key] = float(value)

    def histogram_observe(self, name: str, value: float, **labels):
        with self._lock:
            key = self._guarded_key(self._histograms, name, labels)
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = _Histogram(
                    self._buckets_by_name.get(name, DEFAULT_BUCKETS)
                )
            h.observe(value)

    # ------------------------------------------------------------- queries
    #
    # Queries hold the same lock as the mutators: a histogram summary
    # reads five fields of an object another thread may be mid-observe
    # on, and the exposition layer promises that what `/metrics` serves
    # agrees EXACTLY with a `snapshot()` taken at the same instant — a
    # lock-free read could serve a count that includes an observation
    # whose sum does not (a torn view).

    def counter_value(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get((name, _label_key(labels)), 0.0)

    def gauge_value(self, name: str, **labels) -> Optional[float]:
        with self._lock:
            return self._gauges.get((name, _label_key(labels)))

    def histogram_summary(self, name: str, **labels) -> Optional[Dict]:
        with self._lock:
            h = self._histograms.get((name, _label_key(labels)))
            return h.summary() if h is not None else None

    def metric_names(self) -> set:
        with self._lock:
            return {
                name
                for store in (self._counters, self._gauges, self._histograms)
                for (name, _) in store
            }

    def snapshot(self) -> Dict:
        """The whole registry as nested plain dicts:
        ``{"counters": {name: {label_str: value}}, "gauges": {...},
        "histograms": {name: {label_str: summary}}}``.

        The entire snapshot — every counter, gauge, and histogram
        summary — is built under ONE lock acquisition, so concurrent
        emission can never produce a torn view: what the OpenMetrics
        exposition serves is exactly one instant of the registry
        (pinned by the threaded hammer test in tests/test_telemetry.py).
        """
        with self._lock:
            out = {"counters": {}, "gauges": {}, "histograms": {}}
            for (name, key), v in self._counters.items():
                out["counters"].setdefault(name, {})[_label_str(key)] = v
            for (name, key), v in self._gauges.items():
                out["gauges"].setdefault(name, {})[_label_str(key)] = v
            for (name, key), h in self._histograms.items():
                out["histograms"].setdefault(name, {})[_label_str(key)] = h.summary()
            return out
