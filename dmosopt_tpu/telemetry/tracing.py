"""Span-based host tracing with Chrome trace-event export.

The metrics registry answers "how many / how long in aggregate"; the
event log answers "what happened this epoch"; spans answer *where a
specific epoch's wall time went and for whom*: each `Span` is one timed
host-side region (epoch -> gp_fit -> ea_scan -> resample ->
eval_dispatch/eval_drain -> h5_write) with a trace id, a span id, a
parent link, and free-form labels (tenant, bucket, phase). The span
taxonomy is cataloged in ``docs/observability.md`` and enforced by
graftlint's ``metrics-catalog`` rule, exactly like metric names.

Two consumers:

- **Chrome trace-event JSON** (`Tracer.export`): a
  ``{"traceEvents": [...]}`` file loadable in chrome://tracing or
  https://ui.perfetto.dev. Spans become complete ("X") events; labels
  and parent links ride in ``args``.
- **Per-epoch persistence** (`Tracer.drain` +
  `storage.save_spans_to_h5`): the driver stores each epoch's closed
  spans beside the telemetry summaries so a stored run's timeline
  survives resume.

Device alignment: every span opened through `Tracer.span` also enters a
``jax.profiler.TraceAnnotation`` of the same name, so host spans line
up with XLA op activity when a device trace (``profile_dir``) covers
the epoch.

Discipline (the graftlint hot-path-purity contract): spans are opened
from EAGER host code only — never inside a jit region, where the
context manager would time tracing instead of execution. Spans must
also never be held across a generator ``yield`` that hands control to
other span-opening code (the nesting stack is thread-local); intervals
measured around suspensions are recorded after the fact with
`Tracer.record_span`.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from dmosopt_tpu.utils import json_default


@dataclass
class Span:
    """One closed (or still-open) host-side timed region."""

    name: str
    trace_id: str
    span_id: int
    parent_id: Optional[int]
    t_start: float  # perf_counter seconds, same clock as Tracer
    t_end: Optional[float] = None
    labels: Dict[str, Any] = field(default_factory=dict)
    thread: int = 0

    @property
    def duration_s(self) -> Optional[float]:
        if self.t_end is None:
            return None
        return self.t_end - self.t_start

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "t_start": self.t_start,
            "t_end": self.t_end,
            "duration_s": self.duration_s,
        }
        if self.parent_id is not None:
            out["parent_id"] = self.parent_id
        if self.labels:
            out["labels"] = {str(k): v for k, v in self.labels.items()}
        return out


def _trace_annotation(name: str):
    """A `jax.profiler.TraceAnnotation` for `name`, or a null context
    when jax is unavailable (the tracer itself is jax-free)."""
    try:
        from jax.profiler import TraceAnnotation

        return TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


class Tracer:
    """Collects host-side spans; exports Chrome trace-event JSON.

    Thread-safe: each thread nests through its own span stack (a
    background-writer ``h5_write`` span is parentless on its own
    track), the span list is lock-protected. The buffer is bounded by
    ``max_spans``: past it, the OLDEST spans are evicted
    (already-drained ones first — they sit at the front), so per-epoch
    persistence keeps flowing on a long-lived service and the Chrome
    export keeps the most recent window; every eviction is counted in
    ``spans_dropped`` (a trace with a silent hole is worse than a
    truncated one that says so).
    """

    def __init__(
        self,
        path: Optional[str] = None,
        max_spans: int = 16384,
        trace_id: Optional[str] = None,
    ):
        self.path = path
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.max_spans = int(max_spans)
        self.spans_dropped = 0
        self._spans: List[Span] = []
        self._drained = 0  # index of the first span `drain` has not seen
        self._ids = itertools.count(1)
        self._last_id = 0  # highest id handed out (for `mark`)
        self._lock = threading.Lock()
        self._tls = threading.local()
        # perf_counter origin paired with a wall-clock stamp so exported
        # timestamps can be related to event-log `ts` values
        self.t0 = time.perf_counter()
        self.wall0 = time.time()

    # ------------------------------------------------------------- spans

    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _append(self, sp: Span) -> bool:
        with self._lock:
            if len(self._spans) >= self.max_spans:
                # evict the OLDEST span (already-drained ones sit at
                # the front by construction, so they go first): the
                # Chrome export keeps the most recent `max_spans`
                # window — a consumer investigating a slowdown needs
                # the run's tail, not its start — and per-epoch
                # persistence never goes dark. Evictions are counted
                # in `spans_dropped`.
                self._spans.pop(0)
                if self._drained > 0:
                    self._drained -= 1
                self.spans_dropped += 1
            self._spans.append(sp)
            self._last_id = max(self._last_id, sp.span_id)
            return True

    @contextlib.contextmanager
    def span(self, name: str, **labels):
        """Open one nested span around the enclosed region; yields the
        `Span` (labels may be added to ``span.labels`` before close).
        Also enters a same-named `jax.profiler.TraceAnnotation` so
        device traces line up with the host span."""
        stack = self._stack()
        parent = stack[-1] if stack else None
        sp = Span(
            name=name,
            trace_id=self.trace_id,
            span_id=next(self._ids),
            parent_id=parent.span_id if parent is not None else None,
            t_start=time.perf_counter(),
            labels={k: v for k, v in labels.items() if v is not None},
            thread=threading.get_ident(),
        )
        stack.append(sp)
        self._append(sp)
        try:
            with _trace_annotation(name):
                yield sp
        finally:
            sp.t_end = time.perf_counter()
            # defensive out-of-order close: remove by identity wherever
            # it sits (a mis-nested caller must not corrupt the stack)
            try:
                stack.remove(sp)
            except ValueError:
                pass

    def record_span(
        self,
        name: str,
        t_start: float,
        t_end: float,
        parent: Optional[Span] = None,
        **labels,
    ) -> Optional[Span]:
        """Record an already-measured interval (perf_counter seconds, the
        tracer's clock) as a closed span — used for attribution slices
        (per-tenant cost shares of a bucket span) and for intervals
        measured across generator suspensions, where a live ``with``
        span would mis-nest."""
        sp = Span(
            name=name,
            trace_id=self.trace_id,
            span_id=next(self._ids),
            parent_id=parent.span_id if parent is not None else None,
            t_start=float(t_start),
            t_end=float(t_end),
            labels={k: v for k, v in labels.items() if v is not None},
            thread=threading.get_ident(),
        )
        return sp if self._append(sp) else None

    # ----------------------------------------------------------- queries

    def spans(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s.name == name]
        return out

    def mark(self) -> int:
        """The highest span id handed out so far — bracket a region with
        `mark()` / `spans_since(mark)` to collect exactly the spans it
        opened (the device-ledger capture join uses this)."""
        with self._lock:
            return self._last_id

    def spans_since(self, mark: int) -> List[Span]:
        """Every buffered span opened after `mark`. Spans evicted by
        the buffer bound are gone — the device ledger tail-aligns the
        survivors to the most recent trace annotations, so eviction
        loses the evicted spans' device time without misattributing
        the survivors'."""
        with self._lock:
            return [s for s in self._spans if s.span_id > mark]

    def drain(self) -> List[Span]:
        """Closed spans not yet returned by a previous `drain` (the
        driver persists these per epoch). Spans stay in the export
        buffer — draining never shortens the Chrome export."""
        with self._lock:
            new, still_open = [], []
            for sp in self._spans[self._drained:]:
                (new if sp.t_end is not None else still_open).append(sp)
            # keep still-open spans (e.g. a writer span mid-flight) in
            # the undrained window so a later drain picks them up closed
            self._spans[self._drained:] = new + still_open
            self._drained += len(new)
            return new

    # ------------------------------------------------------------ export

    def to_chrome_trace(self) -> Dict[str, Any]:
        """The Chrome trace-event representation of every span recorded
        so far (open spans are clamped to now)."""
        now = time.perf_counter()
        events: List[Dict[str, Any]] = [
            {
                "ph": "M", "name": "process_name", "pid": 1, "tid": 0,
                "args": {"name": "dmosopt_tpu"},
            }
        ]
        with self._lock:
            spans = list(self._spans)
        tids: Dict[int, int] = {}
        for sp in spans:
            tid = tids.setdefault(sp.thread, len(tids) + 1)
        for thread, tid in tids.items():
            events.append(
                {
                    "ph": "M", "name": "thread_name", "pid": 1, "tid": tid,
                    "args": {"name": f"host-{tid}"},
                }
            )
        # a bounded buffer may have evicted a span whose children remain:
        # drop the dangling parent link (the child becomes a root in the
        # exported window) so the export stays schema-valid under
        # overflow — `spans_dropped` in otherData accounts for the loss
        exported_ids = {sp.span_id for sp in spans}
        for sp in spans:
            t_end = sp.t_end if sp.t_end is not None else now
            args: Dict[str, Any] = {
                "trace_id": sp.trace_id,
                "span_id": sp.span_id,
            }
            if sp.parent_id is not None and sp.parent_id in exported_ids:
                args["parent_id"] = sp.parent_id
            args.update({str(k): v for k, v in sp.labels.items()})
            events.append(
                {
                    "name": sp.name,
                    "cat": "host",
                    "ph": "X",
                    "ts": (sp.t_start - self.t0) * 1e6,  # microseconds
                    "dur": max(t_end - sp.t_start, 0.0) * 1e6,
                    "pid": 1,
                    "tid": tids[sp.thread],
                    "args": args,
                }
            )
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "trace_id": self.trace_id,
                "wall_start": self.wall0,
                "spans_dropped": self.spans_dropped,
            },
        }

    def export(self, path: Optional[str] = None) -> str:
        """Write the Chrome trace JSON to `path` (default: the tracer's
        configured path) and return the path written."""
        path = path or self.path
        if path is None:
            raise ValueError("no trace path configured")
        with open(path, "w") as fh:
            json.dump(self.to_chrome_trace(), fh, default=json_default)
        return path


def validate_chrome_trace(trace: Dict[str, Any]) -> List[str]:
    """Schema-check a Chrome trace-event object (the `make trace-smoke`
    gate): returns a list of problems, empty when valid. Checks the
    container shape, per-event required fields, phase-specific fields
    of complete ("X") events, and that every parent_id resolves to a
    span_id present in the trace."""
    problems: List[str] = []
    if not isinstance(trace, dict):
        return ["trace is not a JSON object"]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    span_ids = set()
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        for key in ("ph", "name", "pid", "tid"):
            if key not in ev:
                problems.append(f"event {i}: missing {key!r}")
        ph = ev.get("ph")
        if ph == "X":
            for key in ("ts", "dur"):
                v = ev.get(key)
                if not isinstance(v, (int, float)):
                    problems.append(f"event {i}: {key!r} not numeric")
                elif v < 0:
                    problems.append(f"event {i}: {key!r} negative")
            args = ev.get("args", {})
            if not isinstance(args, dict) or "span_id" not in args:
                problems.append(f"event {i}: X event without args.span_id")
            else:
                span_ids.add(args["span_id"])
        elif ph not in ("M",):
            problems.append(f"event {i}: unknown phase {ph!r}")
    for i, ev in enumerate(events):
        if isinstance(ev, dict) and ev.get("ph") == "X":
            parent = ev.get("args", {}).get("parent_id")
            if parent is not None and parent not in span_ids:
                problems.append(
                    f"event {i}: parent_id {parent} resolves to no span"
                )
    return problems


def load_chrome_trace(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        return json.load(fh)
