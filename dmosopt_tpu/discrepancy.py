"""L2 discrepancy / uniformity metrics as vectorized XLA reductions.

Same six metrics as the reference (dmosopt/discrepancy.py:38-151 —
Hickernell 1998 L2 discrepancies), with the O(n^2 d) Python loops replaced
by broadcast pairwise products so GLP's design search can vmap over
candidate lattices.
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def MD2(X: jax.Array) -> jax.Array:
    """Modified L2-discrepancy."""
    num, dim = X.shape
    D1 = (4.0 / 3.0) ** dim
    D2 = jnp.prod(3.0 - X**2, axis=1).sum()
    pair_max = jnp.maximum(X[:, None, :], X[None, :, :])
    D3 = jnp.prod(2.0 - pair_max, axis=-1).sum()
    return jnp.sqrt(D1 - D2 * (2.0 ** (1 - dim)) / num + D3 / num**2)


@jax.jit
def CD2(X: jax.Array) -> jax.Array:
    """Centered L2-discrepancy."""
    num, dim = X.shape
    D1 = (13.0 / 12.0) ** dim
    a = jnp.abs(X - 0.5)
    D2 = jnp.prod(1.0 + 0.5 * a - 0.5 * a**2, axis=1).sum()
    pair = (
        1.0
        + 0.5 * a[:, None, :]
        + 0.5 * a[None, :, :]
        - 0.5 * jnp.abs(X[:, None, :] - X[None, :, :])
    )
    D3 = jnp.prod(pair, axis=-1).sum()
    return jnp.sqrt(D1 - 2.0 * D2 / num + D3 / num**2)


@jax.jit
def SD2(X: jax.Array) -> jax.Array:
    """Symmetric L2-discrepancy."""
    num, dim = X.shape
    D1 = (4.0 / 3.0) ** dim
    D2 = jnp.prod(1.0 + 2.0 * X - 2.0 * X**2, axis=1).sum()
    diff = jnp.abs(X[:, None, :] - X[None, :, :])
    D3 = jnp.prod(1.0 - diff, axis=-1).sum()
    return jnp.sqrt(D1 - 2.0 * D2 / num + D3 * (2.0**dim) / num**2)


@jax.jit
def WD2(X: jax.Array) -> jax.Array:
    """Wrap-around L2-discrepancy."""
    num, dim = X.shape
    diff = jnp.abs(X[:, None, :] - X[None, :, :])
    D3 = jnp.prod(1.5 - diff * (1.0 - diff), axis=-1).sum()
    return jnp.sqrt(-((4.0 / 3.0) ** dim) + D3 / num**2)


@jax.jit
def MinDist(X: jax.Array) -> jax.Array:
    """Minimum point-to-point distance (to be maximized)."""
    n = X.shape[0]
    sq = jnp.sum((X[:, None, :] - X[None, :, :]) ** 2, axis=-1)
    sq = jnp.where(jnp.eye(n, dtype=bool), jnp.inf, sq)
    return jnp.sqrt(jnp.min(sq))


def corrscore(X) -> float:
    """Sum of squared upper-triangle correlations (reference computes
    np.corrcoef over rows, dmosopt/discrepancy.py:147-151)."""
    c = np.corrcoef(np.asarray(X))
    return float(np.sum(np.triu(c, 1) ** 2))


def all_metrics(X) -> dict:
    X = jnp.asarray(X)
    return {
        "MD2": float(MD2(X)),
        "CD2": float(CD2(X)),
        "SD2": float(SD2(X)),
        "WD2": float(WD2(X)),
        "MinDist": float(MinDist(X)),
        "corrscore": corrscore(X),
    }
