"""Zitzler-Deb-Thiele benchmark problems, batched and jittable.

Analytic definitions match the reference's example/test objective functions
(reference: tests/test_zdt1_nsga2_trs.py:10-21, examples/example_dmosopt_zdt*.py),
but evaluate whole populations at once: ``f(X) -> Y`` with X (B, n), Y (B, 2).
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def zdt1(x: jax.Array) -> jax.Array:
    x = jnp.atleast_2d(x)
    n = x.shape[1]
    f1 = x[:, 0]
    g = 1.0 + 9.0 / (n - 1) * jnp.sum(x[:, 1:], axis=1)
    h = 1.0 - jnp.sqrt(f1 / g)
    return jnp.stack([f1, g * h], axis=1)


@jax.jit
def zdt2(x: jax.Array) -> jax.Array:
    x = jnp.atleast_2d(x)
    n = x.shape[1]
    f1 = x[:, 0]
    g = 1.0 + 9.0 / (n - 1) * jnp.sum(x[:, 1:], axis=1)
    h = 1.0 - (f1 / g) ** 2
    return jnp.stack([f1, g * h], axis=1)


@jax.jit
def zdt3(x: jax.Array) -> jax.Array:
    x = jnp.atleast_2d(x)
    n = x.shape[1]
    f1 = x[:, 0]
    g = 1.0 + 9.0 / (n - 1) * jnp.sum(x[:, 1:], axis=1)
    h = 1.0 - jnp.sqrt(f1 / g) - (f1 / g) * jnp.sin(10.0 * jnp.pi * f1)
    return jnp.stack([f1, g * h], axis=1)


def zdt1_pareto(n_points: int = 100) -> np.ndarray:
    f1 = np.linspace(0, 1, n_points)
    return np.stack([f1, 1.0 - np.sqrt(f1)], axis=1)


def zdt2_pareto(n_points: int = 100) -> np.ndarray:
    f1 = np.linspace(0, 1, n_points)
    return np.stack([f1, 1.0 - f1**2], axis=1)


def zdt3_pareto(n_points: int = 100) -> np.ndarray:
    # disconnected front: keep only non-dominated part of the g=1 curve
    f1 = np.linspace(0, 1, n_points * 10)
    f2 = 1.0 - np.sqrt(f1) - f1 * np.sin(10.0 * np.pi * f1)
    pts = np.stack([f1, f2], axis=1)
    keep = np.ones(len(pts), dtype=bool)
    for i in range(len(pts)):
        if keep[i]:
            dominated = (pts[:, 0] <= pts[i, 0]) & (pts[:, 1] <= pts[i, 1])
            dominated &= (pts[:, 0] < pts[i, 0]) | (pts[:, 1] < pts[i, 1])
            if dominated.any():
                keep[i] = False
    return pts[keep][:: max(1, len(pts[keep]) // n_points)]


def distance_to_front(Y, front: np.ndarray) -> np.ndarray:
    """Per-point euclidean distance to a sampled analytic Pareto front
    (oracle from reference tests/test_zdt1_nsga2_trs.py:39-72)."""
    Y = np.asarray(Y)
    d = np.sqrt(((Y[:, None, :] - front[None, :, :]) ** 2).sum(-1))
    return d.min(axis=1)
