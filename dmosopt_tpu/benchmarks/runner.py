"""End-to-end benchmark capture harness.

Capability match: the reference's ``BenchmarkRunner`` / ``BenchmarkResult``
(reference: tests/test_moo_benchmarks.py:25-216) — run a MO-ASMO
optimization per DTLZ/WFG/MaF problem and record final hypervolume,
per-epoch HV trajectory, wall-clock, and termination reason to JSON.

TPU redesign: the benchmark objectives here are jittable batch functions,
so evaluation goes through the ``jax_objective`` path (one jitted,
mesh-shardable call per resample batch) instead of the reference's
per-point ``pp``-dict wrapper with a ``sys.modules`` injection hack.
The runner drives ``run_epoch`` itself, so the HV trajectory is measured
(one ``AdaptiveHyperVolume`` evaluation of the archive per epoch), not a
placeholder — the reference leaves ``hv_trajectory`` empty (its
``:171-172``).
"""

from __future__ import annotations

import json
import time
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from dmosopt_tpu import driver
from dmosopt_tpu.utils import json_default
from dmosopt_tpu.benchmarks.moo_benchmarks import (
    generate_problem_space,
    get_problem,
    get_problem_metadata,
)
from dmosopt_tpu.hv import AdaptiveHyperVolume


@dataclass
class BenchmarkResult:
    """Diagnostics from one benchmark optimization run
    (reference tests/test_moo_benchmarks.py:25-48)."""

    problem_name: str
    n_objectives: int
    n_variables: int
    converged: bool
    final_epoch: int
    final_hv: float
    computation_time_seconds: float
    termination_reason: str
    hv_trajectory: List[float] = field(default_factory=list)
    hv_method: str = ""
    hv_ci: float = 0.0
    n_archive: int = 0
    metadata: Dict = field(default_factory=dict)


class BenchmarkRunner:
    """Run benchmark problems through the full MO-ASMO loop and capture
    per-problem diagnostics to ``<output_dir>/<problem>_m<d>_result.json``."""

    def __init__(self, output_dir: str = "benchmark_results", mesh=None):
        self.output_dir = Path(output_dir)
        self.output_dir.mkdir(parents=True, exist_ok=True)
        self.mesh = mesh
        self.results: List[BenchmarkResult] = []

    # ------------------------------------------------------------- single

    def run_single_benchmark(
        self,
        problem_name: str,
        n_obj: int,
        n_var: Optional[int] = None,
        population_size: int = 64,
        num_generations: int = 50,
        n_epochs: int = 4,
        n_initial: int = 8,
        surrogate_method_name: Optional[str] = "gpr",
        surrogate_method_kwargs: Optional[dict] = None,
        optimizer_name="age",
        termination_conditions=None,
        hv_epsilon: Optional[float] = 0.05,
        random_seed: int = 42,
        save_json: bool = True,
        verbose: bool = False,
    ) -> BenchmarkResult:
        space = generate_problem_space(problem_name, n_obj, n_var=n_var)
        # the problem definitions are jittable batch maps over their own
        # native domains, which the space dict already encodes (WFG's
        # per-dimension [0, 2i] included) — the driver hands the objective
        # raw (B, n) parameter batches
        objective = get_problem(problem_name, n_obj)

        params = {
            "opt_id": f"{problem_name}_m{n_obj}",
            "obj_fun": objective,
            "jax_objective": True,
            "objective_names": [f"f{i + 1}" for i in range(n_obj)],
            "space": space,
            "problem_parameters": {},
            "n_initial": n_initial,
            "n_epochs": n_epochs,
            "population_size": population_size,
            "num_generations": num_generations,
            "resample_fraction": 0.25,
            "optimizer_name": optimizer_name,
            "surrogate_method_name": surrogate_method_name,
            "surrogate_method_kwargs": surrogate_method_kwargs
            or {"n_starts": 4, "n_iter": 100, "seed": 0},
            "termination_conditions": termination_conditions,
            "random_seed": random_seed,
            "mesh": self.mesh,
        }

        t0 = time.time()
        dopt = driver.dopt_init(params, verbose=verbose, initialize_strategy=True)

        # drive epochs by hand so the HV trajectory is measured per epoch
        hv_engine: Optional[AdaptiveHyperVolume] = None
        hv_trajectory: List[float] = []
        while dopt.epoch_count < dopt.n_epochs:
            dopt.run_epoch()
            y = dopt.optimizer_dict[0].y
            if y is None or y.shape[0] == 0:
                hv_trajectory.append(0.0)
                continue
            if hv_engine is None:
                # nadir-anchored, span-margined reference point, fixed
                # across the run so the trajectory is comparable epoch to
                # epoch (valid for objectives of any sign)
                from dmosopt_tpu.hv import default_reference_point

                ref = default_reference_point(y)
                hv_engine = AdaptiveHyperVolume(ref, epsilon=hv_epsilon)
            hv_trajectory.append(float(hv_engine.compute_hypervolume(y)))
        elapsed = time.time() - t0

        strategy = dopt.optimizer_dict[0]
        # report which criterion actually fired (the epoch budget always
        # ends the outer loop; `stop_reasons` says what ended the inner
        # ones). "Converged" means a quality/stagnation criterion fired,
        # not merely that a generation cap was hit.
        fired = (
            strategy.termination.stop_reasons()
            if strategy.termination is not None
            else []
        )
        reason = "+".join(fired) if fired else "epoch_budget"
        converged = any(r != "MaximumGenerationTermination" for r in fired)

        final_hv = hv_trajectory[-1] if hv_trajectory else 0.0
        result = BenchmarkResult(
            problem_name=problem_name,
            n_objectives=n_obj,
            n_variables=len(space),
            converged=converged,
            final_epoch=int(dopt.epoch_count + dopt.start_epoch),
            final_hv=final_hv,
            computation_time_seconds=elapsed,
            termination_reason=reason,
            hv_trajectory=hv_trajectory,
            hv_method=hv_engine.last_method if hv_engine is not None else "",
            hv_ci=float(hv_engine.last_ci) if hv_engine is not None else 0.0,
            n_archive=int(strategy.y.shape[0]) if strategy.y is not None else 0,
            metadata=get_problem_metadata(problem_name, n_obj),
        )
        self.results.append(result)
        if save_json:
            self._save_result(result)
        return result

    # -------------------------------------------------------------- tiers

    TIERS = {
        1: [("dtlz2", 3), ("dtlz1", 3), ("dtlz7", 3), ("maf2", 5)],
        2: [("dtlz3", 3), ("dtlz5", 3), ("dtlz4", 5), ("maf4", 5)],
        3: [("maf1", 10), ("maf2", 10), ("maf2", 15)],
        4: [("wfg1", 3), ("wfg4", 3)],
    }

    def run_tier(self, tier: int = 1, **kwargs) -> List[BenchmarkResult]:
        return [
            self.run_single_benchmark(name, n_obj, **kwargs)
            for name, n_obj in self.TIERS[tier]
        ]

    # ---------------------------------------------------------------- io

    def _save_result(self, result: BenchmarkResult):
        path = (
            self.output_dir
            / f"{result.problem_name}_m{result.n_objectives}_result.json"
        )
        path.write_text(json.dumps(asdict(result), indent=2, default=json_default))

    def save_summary(self, filename: str = "summary.json"):
        (self.output_dir / filename).write_text(
            json.dumps([asdict(r) for r in self.results], indent=2,
                       default=json_default)
        )
