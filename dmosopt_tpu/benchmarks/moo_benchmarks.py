"""Many-objective benchmark suite: DTLZ, WFG, MaF — batched and jittable.

Capability match: reference `dmosopt/benchmarks/moo_benchmarks.py` —
DTLZ1-5,7 (:21-260), WFG1/WFG4 (:286-382), MaF1/2/4 (:384-504),
`generate_problem_space` (:505) and `get_problem_metadata` (:557).

TPU redesign: the reference evaluates one point at a time with Python
loops over objectives. Here every problem maps a ``(B, n)`` batch to
``(B, m)`` objectives with cumulative-product shape math — directly
usable as a jitted/sharded batch objective or inside `lax.scan`
generation loops. Single points ``(n,)`` are auto-promoted.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np
import jax.numpy as jnp


def _as_batch(x):
    x = jnp.asarray(x, jnp.float32)
    single = x.ndim == 1
    return (x[None, :], True) if single else (x, False)


def _unbatch(f, single):
    return f[0] if single else f


def _shape_products(cos_terms, sin_terms, n_obj):
    """Generic DTLZ-style shape: f_i = prod_{j < m-1-i} cos_j * sin_{m-1-i}
    (sin term absent for i = 0). cos/sin terms are (B, m-1) arrays.
    Returns (B, m) WITHOUT the (1+g) factor."""
    B = cos_terms.shape[0]
    ones = jnp.ones((B, 1), cos_terms.dtype)
    # cp[:, t] = prod_{j < t} cos_j, t = 0..m-1
    cp = jnp.concatenate([ones, jnp.cumprod(cos_terms, axis=1)], axis=1)
    cols = []
    for i in range(n_obj):
        t = n_obj - 1 - i
        col = cp[:, t]
        if i > 0:
            col = col * sin_terms[:, t]
        cols.append(col)
    return jnp.stack(cols, axis=1)


def _g_rastrigin(xm):
    k = xm.shape[1]
    return 100.0 * (
        k + jnp.sum((xm - 0.5) ** 2 - jnp.cos(20.0 * jnp.pi * (xm - 0.5)), axis=1)
    )


def _g_sphere(xm):
    return jnp.sum((xm - 0.5) ** 2, axis=1)


def dtlz1(x, n_obj: int = 3):
    """Linear PF (sum f_i = 0.5), multi-modal g (reference :21-56)."""
    x, single = _as_batch(x)
    m = n_obj
    g = _g_rastrigin(x[:, m - 1 :])
    y = x[:, : m - 1]
    f = 0.5 * _shape_products(y, 1.0 - y, m) * (1.0 + g)[:, None]
    return _unbatch(f, single)


def dtlz2(x, n_obj: int = 3):
    """Spherical concave PF (reference :59-94)."""
    x, single = _as_batch(x)
    m = n_obj
    g = _g_sphere(x[:, m - 1 :])
    a = x[:, : m - 1] * (jnp.pi / 2.0)
    f = _shape_products(jnp.cos(a), jnp.sin(a), m) * (1.0 + g)[:, None]
    return _unbatch(f, single)


def dtlz3(x, n_obj: int = 3):
    """DTLZ2 shape with the multi-modal g (reference :97-133)."""
    x, single = _as_batch(x)
    m = n_obj
    g = _g_rastrigin(x[:, m - 1 :])
    a = x[:, : m - 1] * (jnp.pi / 2.0)
    f = _shape_products(jnp.cos(a), jnp.sin(a), m) * (1.0 + g)[:, None]
    return _unbatch(f, single)


def dtlz4(x, n_obj: int = 3, alpha: float = 100.0):
    """Biased spherical PF via x^alpha (reference :136-171)."""
    x, single = _as_batch(x)
    m = n_obj
    g = _g_sphere(x[:, m - 1 :])
    a = (x[:, : m - 1] ** alpha) * (jnp.pi / 2.0)
    f = _shape_products(jnp.cos(a), jnp.sin(a), m) * (1.0 + g)[:, None]
    return _unbatch(f, single)


def dtlz5(x, n_obj: int = 3):
    """Degenerate curve PF (reference :174-215)."""
    x, single = _as_batch(x)
    m = n_obj
    g = _g_sphere(x[:, m - 1 :])
    theta0 = x[:, :1] * (jnp.pi / 2.0)
    rest = (1.0 + 2.0 * g[:, None] * x[:, 1 : m - 1]) / (
        2.0 * (1.0 + g[:, None])
    ) * (jnp.pi / 2.0)
    theta = jnp.concatenate([theta0, rest], axis=1)
    f = _shape_products(jnp.cos(theta), jnp.sin(theta), m) * (1.0 + g)[:, None]
    return _unbatch(f, single)


def dtlz7(x, n_obj: int = 3):
    """Disconnected PF (reference :218-259)."""
    x, single = _as_batch(x)
    m = n_obj
    g = 1.0 + 9.0 * jnp.mean(x[:, m - 1 :], axis=1)
    f_head = x[:, : m - 1]
    h = m - jnp.sum(
        f_head / (1.0 + g[:, None]) * (1.0 + jnp.sin(3.0 * jnp.pi * f_head)),
        axis=1,
    )
    f_last = (1.0 + g) * h
    f = jnp.concatenate([f_head, f_last[:, None]], axis=1)
    return _unbatch(f, single)


# ------------------------------------------------------------------- WFG


def _block(i: int, ll: int, n_var: int) -> slice:
    """Shape-vector block i of width `ll`, clamped non-empty. The reference
    slices `t[i*ll:(i+1)*ll]` unguarded and crashes on empty blocks for
    n_obj >= 4 with its own default n_var (moo_benchmarks.py:326); here
    out-of-range blocks fall back to the trailing `ll` columns."""
    start = i * ll
    if start >= n_var:
        return slice(n_var - ll, n_var)
    return slice(start, min(start + ll, n_var))


def wfg_shape_linear(xv, m: int):
    """Linear WFG shape over the (B, m) shape vector (reference :262-271)."""
    return _shape_products(xv[:, : m - 1], 1.0 - xv[:, : m - 1], m)


def wfg_shape_convex(xv, m: int):
    """Convex WFG shape over the (B, m) shape vector (reference :274-283).

    Uses the half-angle forms 1-cos(t) = 2 sin^2(t/2) and
    1-sin(t) = 2 sin^2(pi/4 - t/2), which are cancellation-free in f32
    (the naive forms lose ~1e-3 relative accuracy near the extremes)."""
    t = xv[:, : m - 1] * (jnp.pi / 2.0)
    c = 2.0 * jnp.sin(t / 2.0) ** 2
    s = 2.0 * jnp.sin(jnp.pi / 4.0 - t / 2.0) ** 2
    return _shape_products(c, s, m)


def wfg1(x, n_obj: int = 3, k: Optional[int] = None):
    """Mixed-separability, biased/flat transformations (reference :286-333).
    Bounds: x_i in [0, 2i]."""
    x, single = _as_batch(x)
    n_var = x.shape[1]
    if k is None:
        k = n_obj - 1
    ll = n_var - k
    y = x / (2.0 * jnp.arange(1, n_var + 1))
    t1 = jnp.concatenate([y[:, :k], y[:, k:] ** 0.02], axis=1)
    t2 = jnp.concatenate([t1[:, :k], 0.35 + 0.65 * t1[:, k:]], axis=1)
    xv_cols = [
        jnp.max(t2[:, _block(i, ll, n_var)], axis=1) for i in range(n_obj - 1)
    ]
    xv_cols.append(jnp.mean(t2[:, -ll:], axis=1))
    xv = jnp.stack(xv_cols, axis=1)
    f = wfg_shape_convex(xv, n_obj) * (1.0 + jnp.arange(1, n_obj + 1))
    return _unbatch(f, single)


def wfg4(x, n_obj: int = 3, k: Optional[int] = None):
    """Multi-modal transformation, concave shape (reference :335-381)."""
    x, single = _as_batch(x)
    n_var = x.shape[1]
    if k is None:
        k = n_obj - 1
    ll = n_var - k
    y = x / (2.0 * jnp.arange(1, n_var + 1))
    t1 = y + 0.35 - 0.15 * jnp.cos(10.0 * jnp.pi * y - 5.0)
    xv_cols = [
        jnp.mean(t1[:, _block(i, ll, n_var)], axis=1) for i in range(n_obj - 1)
    ]
    xv_cols.append(jnp.mean(t1[:, -ll:], axis=1))
    xv = jnp.stack(xv_cols, axis=1)
    f = wfg_shape_convex(xv, n_obj) * (1.0 + jnp.arange(1, n_obj + 1))
    return _unbatch(f, single)


# ------------------------------------------------------------------- MaF


def maf1(x, n_obj: int = 5):
    """Linear PF, complex PS (reference :384-419)."""
    x, single = _as_batch(x)
    m = n_obj
    xm = x[:, m - 1 :]
    g = jnp.sum((xm - 0.5) ** 2 - jnp.cos(20.0 * jnp.pi * (xm - 0.5)), axis=1)
    y = x[:, : m - 1]
    f = _shape_products(y, 1.0 - y, m) * (1.0 + g)[:, None]
    return _unbatch(f, single)


def maf2(x, n_obj: int = 5):
    """Concave PF for many objectives (reference :422-457)."""
    x, single = _as_batch(x)
    m = n_obj
    g = _g_sphere(x[:, m - 1 :])
    a = x[:, : m - 1] * (jnp.pi / 2.0)
    f = _shape_products(jnp.cos(a), jnp.sin(a), m) * (1.0 + g)[:, None]
    return _unbatch(f, single)


def maf4(x, n_obj: int = 5):
    """Badly-scaled concave PF: objective i scaled by 100^i
    (reference :460-502)."""
    x, single = _as_batch(x)
    m = n_obj
    g = _g_sphere(x[:, m - 1 :])
    a = x[:, : m - 1] * (jnp.pi / 2.0)
    f = _shape_products(jnp.cos(a), jnp.sin(a), m) * (1.0 + g)[:, None]
    scales = 10.0 ** (2.0 * jnp.arange(m))
    f = f * scales[None, :]
    return _unbatch(f, single)


PROBLEMS = {
    "dtlz1": dtlz1,
    "dtlz2": dtlz2,
    "dtlz3": dtlz3,
    "dtlz4": dtlz4,
    "dtlz5": dtlz5,
    "dtlz7": dtlz7,
    "wfg1": wfg1,
    "wfg4": wfg4,
    "maf1": maf1,
    "maf2": maf2,
    "maf4": maf4,
}


def get_problem(problem_name: str, n_obj: int):
    """Batched objective `f(x) -> (B, n_obj)` for a named problem."""
    return partial(PROBLEMS[problem_name], n_obj=n_obj)


def generate_problem_space(
    problem_name: str, n_obj: int, n_var: Optional[int] = None
) -> dict:
    """dmosopt-style parameter space dict (reference :505-556)."""
    if n_var is None:
        if problem_name.startswith("dtlz"):
            if problem_name in ("dtlz1", "dtlz3"):
                n_var = n_obj + 4
            elif problem_name == "dtlz7":
                n_var = n_obj + 19
            else:
                n_var = n_obj + 9
        elif problem_name.startswith("wfg"):
            n_var = n_obj - 1 + 10
        elif problem_name.startswith("maf"):
            n_var = n_obj + 9
        else:
            n_var = n_obj + 10

    if problem_name.startswith("wfg"):
        return {f"x{i + 1}": [0.0, 2.0 * (i + 1)] for i in range(n_var)}
    return {f"x{i + 1}": [0.0, 1.0] for i in range(n_var)}


_METADATA = {
    "dtlz1": dict(difficulty="medium", pf_shape="linear", multi_modal=True,
                  expected_overlap_ratio="low", standard_n_obj_range=(3, 15),
                  tests_features=["multi_modality", "false_convergence"]),
    "dtlz2": dict(difficulty="easy", pf_shape="concave", multi_modal=False,
                  expected_overlap_ratio="high", standard_n_obj_range=(3, 30),
                  tests_features=["spherical_front", "clean_convergence"]),
    "dtlz3": dict(difficulty="very_hard", pf_shape="concave", multi_modal=True,
                  expected_overlap_ratio="high", standard_n_obj_range=(3, 10),
                  tests_features=["extreme_multi_modality"]),
    "dtlz4": dict(difficulty="medium", pf_shape="concave", multi_modal=False,
                  expected_overlap_ratio="high", standard_n_obj_range=(3, 15),
                  tests_features=["biased_density", "diversity"]),
    "dtlz5": dict(difficulty="medium", pf_shape="degenerate", multi_modal=False,
                  expected_overlap_ratio="low", standard_n_obj_range=(3, 10),
                  tests_features=["degenerate_front"]),
    "dtlz7": dict(difficulty="hard", pf_shape="disconnected", multi_modal=False,
                  expected_overlap_ratio="medium", standard_n_obj_range=(3, 10),
                  tests_features=["disconnected_regions", "adaptive_window"]),
    "wfg1": dict(difficulty="hard", pf_shape="mixed", multi_modal=False,
                 expected_overlap_ratio="medium", standard_n_obj_range=(3, 10),
                 tests_features=["bias", "flat_regions", "per_objective"]),
    "wfg4": dict(difficulty="hard", pf_shape="concave", multi_modal=True,
                 expected_overlap_ratio="high", standard_n_obj_range=(3, 10),
                 tests_features=["multi_modality"]),
    "maf1": dict(difficulty="medium", pf_shape="linear", multi_modal=True,
                 expected_overlap_ratio="low", standard_n_obj_range=(5, 30),
                 tests_features=["many_objective", "linear_front"]),
    "maf2": dict(difficulty="easy", pf_shape="concave", multi_modal=False,
                 expected_overlap_ratio="high", standard_n_obj_range=(5, 15),
                 tests_features=["many_objective_baseline"]),
    "maf4": dict(difficulty="hard", pf_shape="concave", multi_modal=False,
                 expected_overlap_ratio="high", standard_n_obj_range=(5, 15),
                 tests_features=["badly_scaled", "reference_point_adaptation"]),
}


def get_problem_metadata(problem_name: str, n_obj: int) -> dict:
    """Problem characteristics for test harnesses (reference :557-750)."""
    meta = dict(_METADATA[problem_name])
    lo, hi = meta["standard_n_obj_range"]
    meta["n_obj_in_standard_range"] = lo <= n_obj <= hi
    return meta
