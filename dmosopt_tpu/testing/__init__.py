"""Deterministic test doubles for the service stack.

`dmosopt_tpu.testing.faults` is the fault-injection harness (seeded
`FaultPlan` + `FaultyEvaluator` / `FaultyStore` wrappers) the chaos
suite and `make chaos` drive the ask/tell service with — see
docs/robustness.md.
"""

from dmosopt_tpu.testing.faults import (  # noqa: F401
    FAULT_PLAN_ENV,
    FaultPlan,
    FaultRule,
    FaultyEvaluator,
    FaultyStore,
)
