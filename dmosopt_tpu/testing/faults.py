"""Deterministic fault injection for the service stack.

The reference dmosopt survives its environment by construction — MPI
workers die, objectives wedge, and everything restarts from HDF5. Our
single-process service replaces that environment with threads and a
device queue, so its failure modes have to be *manufactured* to be
tested. This module injects them, reproducibly:

- `FaultPlan`: a seeded, declarative list of `FaultRule`s. Every
  injection decision is a **stateless hash** of (plan seed, rule index,
  target, per-target call index) — no shared RNG stream — so the same
  plan fires the same faults on the same calls regardless of thread
  interleaving or evaluation order.
- `FaultyEvaluator`: wraps any evaluator backend. For host evaluators
  the faults fire *inside the objective call* (``eval_fun``), so the
  real timeout/retry/abandonment machinery in
  `parallel.evaluator._HostEvalHandle` is genuinely exercised; for
  result-streaming backends (the jitted batch evaluator) faults apply
  at the result layer as each item is polled.
- `FaultyStore`: wraps persistence closures with transient IO errors —
  the `BackgroundWriter` retry path's test double.

Fault kinds: ``raise`` (objective exception), ``hang`` (sleep past the
eval timeout), ``delay`` (straggler: sleep, then succeed), ``nan``
(return non-finite objectives "successfully" — the archive-poisoning
case the quarantine guard exists for), ``io_error`` (transient
`OSError` from a store write), ``kill`` (SIGKILL the process — the
crash-resume test's deterministic kill switch), and the worker-level
kinds ``heartbeat_hang`` / ``partition`` (op ``"worker"``, consumed by
the fleet worker harness — see `dmosopt_tpu.fleet.worker`).

Env gating: `OptimizationService` checks ``DMOSOPT_FAULT_PLAN`` (a JSON
plan spec, or ``@/path/to/plan.json``) at construction and wraps every
tenant evaluator it builds, so bench runs and the chaos suite
(`make chaos`) can drive a whole unmodified service through failure
scenarios. Unset, nothing is imported and nothing is wrapped.
"""

from __future__ import annotations

import fnmatch
import hashlib
import json
import os
import signal
import threading
import time
from dataclasses import dataclass, fields
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

#: environment variable holding a JSON plan spec (or ``@path`` to one)
FAULT_PLAN_ENV = "DMOSOPT_FAULT_PLAN"

FAULT_KINDS = (
    "raise", "hang", "delay", "nan", "io_error", "kill",
    # worker-level kinds (op="worker"; interpreted by the fleet worker
    # harness once per supervision loop): "heartbeat_hang" suppresses
    # the status-file heartbeat while the rule keeps firing (the
    # wedged-but-alive worker the supervisor's deadline policy exists
    # for), "partition" additionally closes the worker's metrics
    # exporter so liveness probes blackhole (the network-partition
    # shape: the worker keeps computing, the supervisor sees nothing)
    "heartbeat_hang", "partition",
)

#: injection sites a rule can bind to ("worker" targets a fleet worker
#: id, consulted once per worker supervision loop)
FAULT_OPS = ("eval", "io", "worker")


class InjectedFault(RuntimeError):
    """The exception `raise`-kind eval faults throw — its own type so
    tests and logs can tell an injected failure from a real one."""


@dataclass(frozen=True)
class FaultRule:
    """One declarative injection rule.

    kind: one of `FAULT_KINDS`.
    target: fnmatch pattern over the injection target name (a tenant's
        ``opt_id`` for eval faults, the store label for io faults).
    op: injection site — ``"eval"`` (objective calls) or ``"io"``
        (persistence closures).
    p: per-call firing probability (seeded, stateless — see
        `FaultPlan._chance`); 1.0 fires on every matching call.
    after: skip the first `after` matching calls per target (calls are
        counted per (op, target), so "fail from epoch 2 on" is
        expressible as an initial-design + resample call count).
    count: stop after this many fires (None = unlimited) — transient
        faults are ``count=1``.
    delay_s: sleep seconds for ``hang`` / ``delay``.
    message: exception text for ``raise`` / ``io_error``.
    """

    kind: str
    target: str = "*"
    op: str = "eval"
    p: float = 1.0
    after: int = 0
    count: Optional[int] = None
    delay_s: float = 0.05
    message: str = "injected fault"

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"fault kind {self.kind!r} not in {FAULT_KINDS}")
        if self.op not in FAULT_OPS:
            raise ValueError(f"fault op {self.op!r} not in {FAULT_OPS}")
        if not (0.0 <= self.p <= 1.0):
            raise ValueError(f"fault p must be in [0, 1]; got {self.p}")


class FaultPlan:
    """A seeded set of fault rules with per-target call accounting.

    One plan instance is shared by every wrapper it drives (the service
    holds one per process run), so `after`/`count` windows are counted
    consistently across retries and epochs. `injected` logs every fire
    as ``(op, target, call_index, kind)`` for test assertions.
    """

    def __init__(
        self,
        rules: Sequence[Union[FaultRule, Dict[str, Any]]],
        seed: int = 0,
    ):
        self.seed = int(seed)
        self.rules: List[FaultRule] = [
            r if isinstance(r, FaultRule) else FaultRule(**r) for r in rules
        ]
        self._lock = threading.Lock()
        self._calls: Dict[Tuple[str, str], int] = {}
        self._fires: Dict[Tuple[int, str], int] = {}
        self.injected: List[Tuple[str, str, int, str]] = []

    # ------------------------------------------------------------ spec IO

    @classmethod
    def from_spec(cls, spec: Union[str, Dict[str, Any]]) -> "FaultPlan":
        """Build a plan from ``{"seed": int, "rules": [rule dicts]}`` (a
        dict or its JSON string)."""
        if isinstance(spec, str):
            spec = json.loads(spec)
        if not isinstance(spec, dict) or "rules" not in spec:
            raise ValueError(
                "fault plan spec must be a dict with a 'rules' list"
            )
        return cls(spec["rules"], seed=spec.get("seed", 0))

    @classmethod
    def from_env(cls, environ=None) -> Optional["FaultPlan"]:
        """The plan named by ``DMOSOPT_FAULT_PLAN`` (inline JSON, or
        ``@path`` to a JSON file), or None when the variable is unset —
        the zero-cost default."""
        environ = os.environ if environ is None else environ
        raw = environ.get(FAULT_PLAN_ENV)
        if not raw:
            return None
        if raw.startswith("@"):
            with open(raw[1:]) as fh:
                raw = fh.read()
        return cls.from_spec(raw)

    def to_spec(self) -> Dict[str, Any]:
        return {
            "seed": self.seed,
            "rules": [
                {f.name: getattr(r, f.name) for f in fields(r)}
                for r in self.rules
            ],
        }

    # ----------------------------------------------------------- decisions

    def _chance(self, rule_idx: int, target: str, call_index: int) -> float:
        """Stateless uniform draw in [0, 1): a hash of the full
        coordinate, so firing decisions are independent of thread
        interleaving and of every other rule's decisions."""
        h = hashlib.sha256(
            f"{self.seed}:{rule_idx}:{target}:{call_index}".encode()
        ).hexdigest()
        return int(h[:12], 16) / float(1 << 48)

    def next_fault(self, op: str, target: str) -> Optional[FaultRule]:
        """Record one call against (op, target) and return the rule that
        fires on it, if any (first matching rule wins)."""
        target = str(target)
        with self._lock:
            i = self._calls.get((op, target), 0)
            self._calls[(op, target)] = i + 1
            for ridx, rule in enumerate(self.rules):
                if rule.op != op or not fnmatch.fnmatch(target, rule.target):
                    continue
                if i < rule.after:
                    continue
                key = (ridx, target)
                if rule.count is not None and self._fires.get(key, 0) >= rule.count:
                    continue
                if rule.p < 1.0 and self._chance(ridx, target, i) >= rule.p:
                    continue
                self._fires[key] = self._fires.get(key, 0) + 1
                self.injected.append((op, target, i, rule.kind))
                return rule
        return None

    def calls(self, op: str, target: str) -> int:
        with self._lock:
            return self._calls.get((op, str(target)), 0)

    def fires(self, kind: Optional[str] = None, target: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                1
                for (_op, tgt, _i, k) in self.injected
                if (kind is None or k == kind)
                and (target is None or tgt == target)
            )


# ----------------------------------------------------------- result nan-ify


def _nanify(result):
    """Replace every numeric payload of a worker-protocol result dict
    (``{problem_id: y | (y, f[, c]), "time": t}``) with NaNs of the
    same shape — the "successful" non-finite return the quarantine
    guard exists for."""

    def nan_like(v):
        if isinstance(v, tuple):
            return tuple(nan_like(o) for o in v)
        arr = np.asarray(v, dtype=np.float64)
        return np.full_like(arr, np.nan)

    if not isinstance(result, dict):
        return nan_like(result)
    return {
        k: (v if k == "time" else nan_like(v)) for k, v in result.items()
    }


def _perform_eval_fault(rule: FaultRule):
    """Side-effecting part of an eval fault (everything except nan,
    which needs the real result). Returns normally for delay/hang."""
    if rule.kind == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    if rule.kind == "raise":
        raise InjectedFault(rule.message)
    if rule.kind in ("hang", "delay"):
        time.sleep(rule.delay_s)


# --------------------------------------------------------------- evaluators


class _FaultyHandle:
    """Result-layer fault application for streaming evaluator handles
    (the jitted batch backend, where per-call injection is impossible:
    the whole batch is one compiled program)."""

    def __init__(self, inner, plan: FaultPlan, target: str):
        self._inner = inner
        self._plan = plan
        self._target = target

    def _apply(self, item):
        if item is None:
            return None
        index, res = item
        rule = self._plan.next_fault("eval", self._target)
        if rule is None:
            return item
        if rule.kind in ("hang", "delay"):
            time.sleep(rule.delay_s)
            return item
        if rule.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        if rule.kind == "raise":
            from dmosopt_tpu.parallel.evaluator import EvalFailure

            return index, EvalFailure(InjectedFault(rule.message), 1)
        if rule.kind == "nan":
            return index, _nanify(res)
        return item

    def poll(self, timeout: Optional[float] = None):
        return self._apply(self._inner.poll(timeout))

    def drain_completed(self):
        return [self._apply(item) for item in self._inner.drain_completed()]

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultyEvaluator:
    """Wrap an evaluator backend with a fault plan.

    Host evaluators (anything exposing ``eval_fun``) get faults injected
    *at the objective-call layer*, so timeouts, retries, backoff and
    pool-abandonment run exactly as they would against a real flaky
    objective: the wrapper presents its own faulty ``eval_fun`` and
    builds the REAL `_HostEvalHandle` over itself, delegating the pool
    and abandonment accounting to the inner evaluator. Other backends
    get result-layer injection through a wrapped handle.

    The inner evaluator is NEVER mutated: a caller-owned evaluator
    stays clean after the service closes, and the same inner instance
    wrapped for two tenants counts each tenant's fault-plan call
    windows independently. All other attributes delegate, so the
    wrapper is drop-in anywhere an evaluator goes.
    """

    def __init__(self, inner, plan: FaultPlan, target: str):
        self.inner = inner
        self.plan = plan
        self.target = str(target)
        self._host = hasattr(inner, "eval_fun")
        if self._host:
            # own attribute (not a patch on inner): the host-handle
            # machinery reads its evaluator's `eval_fun`, and the
            # service's host-likeness probe is hasattr-based
            self.eval_fun = self._faulty_eval_fun

    def _faulty_eval_fun(self, payload):
        rule = self.plan.next_fault("eval", self.target)
        if rule is not None:
            _perform_eval_fault(rule)
            if rule.kind == "nan":
                return _nanify(self.inner.eval_fun(payload))
        return self.inner.eval_fun(payload)

    def evaluate_batch(self, space_vals_list):
        if self._host:
            # mirror HostFunEvaluator.evaluate_batch over the faulty
            # objective (inner's pool when one exists, else inline)
            pool = getattr(self.inner, "_pool", None)
            if pool is not None:
                return list(pool.map(self._faulty_eval_fun, space_vals_list))
            return [self._faulty_eval_fun(sv) for sv in space_vals_list]
        out = []
        for res in self.inner.evaluate_batch(space_vals_list):
            rule = self.plan.next_fault("eval", self.target)
            if rule is None:
                out.append(res)
                continue
            _perform_eval_fault(rule)
            out.append(_nanify(res) if rule.kind == "nan" else res)
        return out

    def submit_batch(self, space_vals_list, **kwargs):
        if self._host:
            from dmosopt_tpu.parallel.evaluator import _HostEvalHandle

            tel = getattr(self.inner, "telemetry", None)
            if tel:
                tel.inc("eval_batches_total", backend="host")
            # the REAL handle, with this wrapper as the evaluator: its
            # attempts call the faulty eval_fun while pool management
            # and abandonment accounting delegate to the inner instance
            return _HostEvalHandle(
                self, list(space_vals_list),
                kwargs.get("timeout"), kwargs.get("retries", 0),
                backoff=kwargs.get("backoff", 0.0),
                backoff_cap=kwargs.get("backoff_cap", 30.0),
            )
        handle = self.inner.submit_batch(space_vals_list, **kwargs)
        return _FaultyHandle(handle, self.plan, self.target)

    def close(self, *args, **kwargs):
        return self.inner.close(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.inner, name)


# -------------------------------------------------------------------- store


class FaultyStore:
    """Inject transient IO faults into persistence closures.

    ``wrap(fn)`` returns a closure that consults the plan before every
    execution: ``io_error`` raises `OSError` (the `BackgroundWriter`'s
    retryable class), ``raise`` raises a non-retryable error, ``delay``
    sleeps first. Submit wrapped closures to a writer to drive its
    retry/backoff/death paths deterministically.
    """

    def __init__(self, plan: FaultPlan, target: str = "writer"):
        self.plan = plan
        self.target = str(target)

    def wrap(self, fn):
        def wrapped(*args, **kwargs):
            rule = self.plan.next_fault("io", self.target)
            if rule is not None:
                if rule.kind in ("hang", "delay"):
                    time.sleep(rule.delay_s)
                elif rule.kind == "io_error":
                    raise OSError(rule.message)
                elif rule.kind == "raise":
                    raise InjectedFault(rule.message)
                elif rule.kind == "kill":
                    os.kill(os.getpid(), signal.SIGKILL)
            return fn(*args, **kwargs)

        return wrapped
