"""Ask/tell optimization service over the problem-batched core.

The driver runs a fixed set of problems to completion; the service is
the "millions of users" surface on top of the same machinery (ROADMAP
item 1): callers **submit** optimization problems at any time, each
submission joins a tenant **bucket at the next epoch boundary**, every
`step()` advances all active tenants by one epoch — bucket-mates
through ONE compiled program per bucket (`dmosopt_tpu.tenants`) — and
each tenant's improving non-dominated front **streams back** through
its handle as epochs complete.

Phase staggering is first-class: tenants submitted at different times
(or with different epoch budgets) share buckets whenever their shapes
and configs match, each keeping its own epoch numbering; a tenant whose
configuration the batched core does not cover simply runs the
sequential path inside the same service loop.

Evaluation of real-objective batches reuses the async evaluator API
(`submit_batch`): each step submits EVERY tenant's pending requests
before folding any of them, so jax-objective device batches and
host-objective thread pools overlap across tenants. Per-tenant
persistence rides the pipeline's ordered `BackgroundWriter`
(`storage.save_front_to_h5` per epoch).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import logging
import os
import threading
import time
from collections import deque
from collections.abc import Iterator
from dataclasses import asdict, dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from dmosopt_tpu.datatypes import EvalRequest, OptProblem, ParameterSpace
from dmosopt_tpu.driver import eval_obj_fun_sp
from dmosopt_tpu.parallel.evaluator import (
    EvalFailure,
    HostFunEvaluator,
    JaxBatchEvaluator,
)
from dmosopt_tpu.parallel.pipeline import BackgroundWriter
from dmosopt_tpu.strategy import DistOptStrategy
from dmosopt_tpu.telemetry import Telemetry, create_telemetry, span_scope
from dmosopt_tpu.utils import json_default

logger = logging.getLogger(__name__)

# per-epoch attributed-cost keys the batched core leaves in a
# strategy's stats dict (dmosopt_tpu.tenants cost attribution); the
# service pops them after each epoch into the tenant's cumulative
# handle costs
_COST_KEYS = (
    ("cost_fit_seconds", "fit"),
    ("cost_ea_seconds", "ea"),
    ("cost_compile_seconds", "compile"),
)

#: conservative per-attempt evaluation timeout applied when no
#: `EvalPolicy` names one — a wedged objective must not hang `step()`
#: forever even on an unconfigured service (docs/configuration.md)
DEFAULT_EVAL_TIMEOUT = 600.0


@dataclass(frozen=True)
class EvalPolicy:
    """Per-tenant evaluation fault policy (docs/robustness.md).

    timeout: per-attempt wall-clock budget in seconds for one objective
        call; ``None`` uses the service's ``default_eval_timeout``
        (never "wait forever" — that is how a wedged objective used to
        hang `step()`).
    retries: resubmissions allowed per request after a timeout or an
        objective exception (threaded into the evaluators' existing
        ``submit_batch(timeout=, retries=)`` machinery).
    backoff / backoff_cap: capped exponential backoff (jittered) before
        each retry attempt executes — see
        `parallel.evaluator.HostFunEvaluator.submit_batch`.
    on_eval_failure: what a request that exhausts its budget does to
        its tenant —
        ``"retire"`` (default): the tenant fails immediately, matching
        the pre-policy service behavior; bucket-mates are unaffected.
        ``"skip"``: the failed point is dropped from the fold and the
        tenant continues (degraded); only an epoch with ZERO successful
        evaluations counts against ``max_failed_epochs``.
        ``"quorum"``: like skip, but an epoch whose success fraction
        falls below ``min_success_fraction`` counts as failed.
    min_success_fraction: the quorum threshold (``"quorum"`` only).
    max_failed_epochs: consecutive failed epochs before a degraded
        tenant is retired (state ``"degraded"``, error on its handle —
        never an exception out of `step()`).
    """

    timeout: Optional[float] = None
    retries: int = 0
    backoff: float = 0.0
    backoff_cap: float = 30.0
    on_eval_failure: str = "retire"
    min_success_fraction: float = 0.5
    max_failed_epochs: int = 3

    def __post_init__(self):
        if self.on_eval_failure not in ("retire", "skip", "quorum"):
            raise ValueError(
                f"on_eval_failure must be 'retire', 'skip' or 'quorum'; "
                f"got {self.on_eval_failure!r}"
            )
        if not (0.0 < self.min_success_fraction <= 1.0):
            raise ValueError(
                f"min_success_fraction must be in (0, 1]; "
                f"got {self.min_success_fraction}"
            )
        if self.retries < 0 or self.max_failed_epochs < 1:
            raise ValueError(
                "retries must be >= 0 and max_failed_epochs >= 1"
            )
        if self.timeout is not None and self.timeout <= 0:
            raise ValueError(f"timeout must be positive; got {self.timeout}")

    @classmethod
    def from_spec(
        cls, spec: Union[None, Dict, "EvalPolicy"]
    ) -> Optional["EvalPolicy"]:
        """None passes through (caller falls back to the service
        default); a dict becomes constructor kwargs."""
        if spec is None or isinstance(spec, cls):
            return spec
        if isinstance(spec, dict):
            return cls(**spec)
        raise TypeError(
            f"eval_policy must be None, dict, or EvalPolicy; "
            f"got {type(spec)!r}"
        )


@dataclass
class FrontUpdate:
    """One streamed front improvement: the tenant's non-dominated set
    after `epoch` completed."""

    epoch: int
    x: np.ndarray
    y: np.ndarray


class TenantHandle:
    """Caller-facing view of one submitted optimization: stream front
    updates as they land, read the latest front, await completion."""

    def __init__(self, tenant_id: int, opt_id: str):
        self.tenant_id = tenant_id
        self.opt_id = opt_id
        self.done = False
        self.error: Optional[BaseException] = None
        # cumulative attributed cost of this tenant's share of its
        # buckets' compiled programs (dmosopt_tpu.tenants attribution;
        # zero for tenants that only rode the sequential path)
        self.cost_seconds: Dict[str, float] = {
            "fit": 0.0, "ea": 0.0, "compile": 0.0,
        }
        self._updates: deque = deque()
        self._latest: Optional[FrontUpdate] = None
        self._lock = threading.Lock()

    # ---- service side
    def _push(self, update: FrontUpdate):
        with self._lock:
            self._updates.append(update)
            self._latest = update

    # ---- caller side
    def updates(self) -> List[FrontUpdate]:
        """Drain the queued front updates (oldest first)."""
        with self._lock:
            out = list(self._updates)
            self._updates.clear()
        return out

    def best(self) -> Optional[FrontUpdate]:
        """The most recent front, or None before the first epoch."""
        with self._lock:
            return self._latest

    def result(self) -> FrontUpdate:
        if self.error is not None:
            raise self.error
        if not self.done:
            raise RuntimeError(
                f"tenant {self.opt_id!r} still running; call "
                f"OptimizationService.run() or step() first"
            )
        if self._latest is None:
            raise RuntimeError(
                f"tenant {self.opt_id!r} finished without completing an "
                f"epoch (no front was produced)"
            )
        return self._latest


@dataclass
class _Tenant:
    handle: TenantHandle
    strat: DistOptStrategy
    evaluator: Any
    owns_evaluator: bool
    n_epochs: int
    file_path: Optional[str]
    param_names: Tuple[str, ...]
    objective_names: Tuple[str, ...]
    epochs_run: int = 0
    # fault-policy state (docs/robustness.md): the resolved policy,
    # whether the evaluator honors per-request timeouts (host backends
    # do; a jitted batch is all-or-nothing), and degradation accounting
    policy: Optional[EvalPolicy] = None
    host_like: bool = False
    eval_failures: int = 0  # cumulative failed evaluation requests
    failed_epochs: int = 0  # CONSECUTIVE sub-quorum evaluation rounds
    degraded: bool = False
    quarantined_seen: int = 0  # strategy n_quarantined already counted
    last_success_fraction: Optional[float] = None
    # checkpoint/resume: the JSON-able submit kwargs needed to rebuild
    # this tenant's strategy in a fresh process
    submit_spec: Optional[Dict[str, Any]] = None


class OptimizationService:
    """Multi-tenant ask/tell optimization: submit problems any time,
    `step()` advances every active tenant one epoch (bucket-batched),
    fronts stream back per tenant. Not thread-safe for concurrent
    `step()` calls; `submit()` may be called from any thread."""

    def __init__(
        self,
        *,
        min_bucket: int = 2,
        telemetry=None,
        logger=logger,
        status_path: Optional[str] = None,
        eval_policy: Union[None, Dict, EvalPolicy] = None,
        default_eval_timeout: float = DEFAULT_EVAL_TIMEOUT,
        checkpoint_path: Optional[str] = None,
        health_rules=None,
        exporter=None,
        owner: Optional[str] = None,
        placement_epoch: int = 0,
        scheduler=None,
    ):
        self.min_bucket = int(min_bucket)
        # async task-graph epochs (docs/parallel.md "Async task-graph
        # epochs"): ``scheduler`` is None/False (the lockstep step,
        # default), True (auto worker count), an int concurrency
        # (1 = serial graph, the bitwise-parity mode), or a dict with a
        # ``concurrency`` key. When enabled, `step()` routes to
        # `_step_taskgraph`, which expresses the epoch as a per-tenant/
        # per-bucket task DAG executed by `parallel.taskgraph.TaskGraph`.
        from dmosopt_tpu.parallel.taskgraph import resolve_concurrency

        self.scheduler_concurrency = resolve_concurrency(scheduler)
        self._last_graph: Dict[str, Any] = {}
        # ownership lease (fleet migration wire format): `owner` names
        # the worker process whose checkpoints these are; the
        # supervisor's monotonically increasing `placement_epoch` is
        # the fencing token a checkpoint claim must beat. Both are
        # stamped into every checkpoint and verified by
        # `adopt_checkpoint` so two workers can never own one tenant
        # (docs/robustness.md "Fleet failure model").
        self.owner = owner
        self.placement_epoch = int(placement_epoch)
        self.telemetry = create_telemetry(telemetry)
        self._owns_telemetry = not isinstance(telemetry, Telemetry)
        self.logger = logger
        self.status_path = status_path
        # active health tier (docs/observability.md "Run-health
        # engine"): declarative alert rules evaluated over the metrics
        # snapshot + introspect() at every step boundary, firing ->
        # resolved lifecycle, surfaced via introspect()["health"], the
        # status CLI, and /healthz. ``health_rules`` is None (seeded
        # default rulebook), a rule list, or False (no engine). Only
        # built with live telemetry: a telemetry=False service holds no
        # health object and makes zero health calls.
        self.health = None
        if self.telemetry and health_rules is not False:
            from dmosopt_tpu.telemetry.health import HealthEngine

            self.health = HealthEngine(
                rules=health_rules, telemetry=self.telemetry
            )
        # opt-in OpenMetrics exposition (docs/observability.md
        # "OpenMetrics exposition"): ``exporter`` is None/False (off),
        # True (ephemeral port on 127.0.0.1), an int port, or a
        # MetricsExporter kwargs dict. The exporter thread is joined in
        # close().
        self.exporter = None
        if exporter:
            if self.telemetry is None:
                raise ValueError(
                    "exporter requires telemetry (the /metrics surface "
                    "IS the registry); got telemetry=False"
                )
            from dmosopt_tpu.telemetry.exposition import MetricsExporter

            kwargs = (
                dict(exporter)
                if isinstance(exporter, dict)
                else ({} if exporter is True else {"port": int(exporter)})
            )
            self.exporter = MetricsExporter(
                snapshot_fn=self.telemetry.registry.snapshot,
                health_fn=(
                    self.health.summary if self.health is not None else None
                ),
                status_fn=self.introspect,
                logger=self.logger,
                **kwargs,
            ).start()
        # service-wide fault policy default (per-submit eval_policy
        # overrides it) and the conservative per-attempt timeout used
        # when neither names one — a wedged objective cannot hang a
        # step forever even on an unconfigured service
        self.eval_policy = EvalPolicy.from_spec(eval_policy)
        self.default_eval_timeout = float(default_eval_timeout)
        # crash-safe resume: full per-tenant state snapshot rewritten
        # atomically (write-temp-rename) at every epoch boundary;
        # `OptimizationService.resume(checkpoint_path, ...)` rebuilds
        self.checkpoint_path = checkpoint_path
        # deterministic fault injection, env-gated: one seeded plan per
        # service so `after`/`count` windows span the whole run
        self._fault_plan = None
        if os.environ.get("DMOSOPT_FAULT_PLAN"):
            from dmosopt_tpu.testing.faults import FaultPlan

            self._fault_plan = FaultPlan.from_env()
        self._writer_error_logged = False
        self._pending: List[_Tenant] = []
        self._active: Dict[int, _Tenant] = {}
        self._ids = itertools.count()
        self._writer: Optional[BackgroundWriter] = None
        self._lock = threading.Lock()
        self._closed = False
        # introspection state: step/phase timings, the best
        # per-tenant-normalized step wall (the throughput baseline),
        # and retired-tenant bookkeeping. `_retired` keeps only the
        # most RECENT retirees (a long-lived service retires tenants
        # forever; an unbounded list would make every status snapshot
        # O(lifetime tenants)) while `_retired_counts` keeps the
        # accurate cumulative totals per state.
        self._steps_run = 0
        self._last_step: Dict[str, Any] = {}
        self._best_step_s_per_tenant: Optional[float] = None
        self._retired: deque = deque(maxlen=256)
        self._retired_counts: Dict[str, int] = {}

    # ------------------------------------------------------------ submit

    def submit(
        self,
        obj_fun,
        space: Dict[str, Any],
        objective_names,
        *,
        opt_id: Optional[str] = None,
        jax_objective: bool = True,
        n_epochs: int = 5,
        population_size: int = 64,
        num_generations: int = 50,
        n_initial: int = 8,
        initial_method: str = "slh",
        resample_fraction: float = 0.25,
        optimizer_name: str = "nsga2",
        optimizer_kwargs: Optional[Dict] = None,
        surrogate_method_name: str = "gpr",
        surrogate_method_kwargs: Optional[Dict] = None,
        random_seed: Optional[int] = None,
        file_path: Optional[str] = None,
        evaluator=None,
        eval_policy: Union[None, Dict, EvalPolicy] = None,
        surrogate_refit=None,
        objective_ref: Optional[str] = None,
        _restore: Optional[Dict[str, Any]] = None,
    ) -> TenantHandle:
        """Submit one optimization problem; it joins a bucket at the
        next epoch boundary (`step()`). ``obj_fun`` is a jax-traceable
        batch objective (``jax_objective=True``, evaluated through the
        jitted batch evaluator) or a per-point host function.
        ``eval_policy`` overrides the service-wide fault policy for
        this tenant (docs/robustness.md). Returns a `TenantHandle`
        streaming the tenant's fronts."""
        if self._closed:
            raise RuntimeError("service is closed")
        if surrogate_method_name is None:
            raise ValueError(
                "the service runs surrogate-mode epochs; "
                "surrogate_method_name=None is not supported"
            )
        if obj_fun is None and objective_ref:
            # the fleet wire format: a subprocess worker receives an
            # importable "module:attr" name instead of a closure
            from dmosopt_tpu.utils import import_object

            obj_fun = import_object(objective_ref)
        policy = EvalPolicy.from_spec(eval_policy) or self.eval_policy
        tenant_id = next(self._ids)
        opt_id = opt_id or f"tenant_{tenant_id}"
        handle = TenantHandle(tenant_id, opt_id)

        param_space = ParameterSpace.from_dict(space)
        eval_fun = partial(
            eval_obj_fun_sp, obj_fun, None, param_space, False, None, 0
        )
        prob = OptProblem(
            param_space.parameter_names, list(objective_names), None,
            lambda f: f, None, param_space, eval_fun, logger=self.logger,
        )
        owns_evaluator = evaluator is None
        if evaluator is None:
            evaluator = (
                JaxBatchEvaluator(obj_fun, problem_ids=[0])
                if jax_objective
                else HostFunEvaluator(eval_fun)
            )
            # owned evaluators report into the service's telemetry
            # (eval_timeouts/retries/failures_total — the degradation
            # accounting the policy layer is judged by)
            evaluator.telemetry = self.telemetry
        if self._fault_plan is not None:
            from dmosopt_tpu.testing.faults import FaultyEvaluator

            evaluator = FaultyEvaluator(evaluator, self._fault_plan, opt_id)
        strat = DistOptStrategy(
            prob,
            n_initial=n_initial,
            initial_method=initial_method,
            population_size=int(population_size),
            num_generations=int(num_generations),
            resample_fraction=float(resample_fraction),
            optimizer_name=optimizer_name,
            optimizer_kwargs=optimizer_kwargs,
            surrogate_method_name=surrogate_method_name,
            surrogate_method_kwargs=surrogate_method_kwargs,
            surrogate_refit=surrogate_refit,
            surrogate_refit_state=(
                (_restore or {}).get("state", {}).get("refit")
            ),
            local_random=np.random.default_rng(random_seed),
            logger=self.logger,
            telemetry=None,  # per-bucket service telemetry only
        )
        # everything a fresh process needs to rebuild this tenant from a
        # checkpoint (the objective itself is re-supplied to `resume`)
        submit_spec = {
            "space": space,
            "objective_names": list(objective_names),
            "jax_objective": bool(jax_objective),
            "n_epochs": int(n_epochs),
            "population_size": int(population_size),
            "num_generations": int(num_generations),
            "n_initial": int(n_initial),
            "initial_method": initial_method,
            "resample_fraction": float(resample_fraction),
            "optimizer_name": optimizer_name,
            "optimizer_kwargs": optimizer_kwargs,
            "surrogate_method_name": surrogate_method_name,
            "surrogate_method_kwargs": surrogate_method_kwargs,
            "random_seed": random_seed,
            "file_path": file_path,
            "objective_ref": objective_ref,
            "eval_policy": asdict(policy) if policy is not None else None,
            "surrogate_refit": (
                surrogate_refit
                if isinstance(surrogate_refit, (str, dict, type(None)))
                else None  # controller/config objects are not JSON-able
            ),
        }
        tenant = _Tenant(
            handle=handle, strat=strat, evaluator=evaluator,
            owns_evaluator=owns_evaluator, n_epochs=int(n_epochs),
            file_path=file_path,
            param_names=tuple(param_space.parameter_names),
            objective_names=tuple(objective_names),
            policy=policy,
            host_like=hasattr(evaluator, "eval_fun"),
            submit_spec=submit_spec,
        )
        if _restore is not None:
            self._apply_restore(tenant, _restore)
        with self._lock:
            self._pending.append(tenant)
        if self.telemetry:
            if _restore is not None and _restore.get("adopted"):
                self.telemetry.inc("tenants_adopted_total")
            elif _restore is not None:
                self.telemetry.inc("tenants_resumed_total")
            else:
                self.telemetry.inc("tenants_submitted_total")
        return handle

    # -------------------------------------------------------------- step

    def _admit_pending(self):
        with self._lock:
            admitted, self._pending = self._pending, []
            for t in admitted:
                self._active[t.handle.tenant_id] = t
        return len(admitted)

    def _retire(self, tenant: _Tenant, state: str):
        """Record one tenant leaving the active set: bounded recent
        snapshot + cumulative per-state count, under the lock so a
        monitoring thread's `introspect()` never races the mutation."""
        with self._lock:
            self._active.pop(tenant.handle.tenant_id, None)
            self._retired.append(self._retire_summary(tenant, state))
            self._retired_counts[state] = (
                self._retired_counts.get(state, 0) + 1
            )

    def _gather_tenant_rounds(self, tenant: _Tenant):
        """Pop the tenant's pending requests into single-problem
        evaluation rounds (the driver's `_gather_rounds` for one pid)."""
        task_args, task_reqs = [], []
        while True:
            req = tenant.strat.get_next_request()
            if req is None:
                break
            task_args.append({0: req.parameters})
            task_reqs.append(req)
        return task_args, task_reqs

    def _effective_timeout(self, tenant: _Tenant) -> float:
        pol = tenant.policy
        if pol is not None and pol.timeout is not None:
            return float(pol.timeout)
        return self.default_eval_timeout

    def _drain_deadline(self, tenant: _Tenant, n_requests: int) -> float:
        """Whole-batch wall-clock backstop for one tenant's drain. Host
        backends enforce the per-attempt timeout internally and may run
        requests sequentially through a narrow pool, so their backstop
        scales with the batch; a jitted batch is one device program —
        the per-attempt budget IS the batch budget. Either way the
        backstop only fires on work the per-request machinery cannot
        bound (a wedged device program, a broken custom evaluator)."""
        pol = tenant.policy or EvalPolicy()
        budget = self._effective_timeout(tenant) * (pol.retries + 1)
        budget += (pol.backoff_cap if pol.backoff > 0 else 0.0) * pol.retries
        if tenant.host_like:
            budget *= max(n_requests, 1)
        return budget + 30.0

    def _collect_results(self, tenant, handle, task_args):
        """Drain one tenant's submitted batch into a submission-order
        result list (entries are result dicts, `EvalFailure`s, or None
        for requests lost to the deadline backstop). Returns
        ``(results, fatal_exception)``."""
        n = len(task_args)
        if handle is None:
            # custom evaluator without submit_batch: the synchronous
            # call runs on a helper thread bounded by the same deadline
            # backstop — a wedged evaluate_batch cannot hang step()
            # (the thread itself cannot be killed; it is daemonic and
            # its tenant is failed)
            box: Dict[str, Any] = {}

            def call():
                try:
                    box["res"] = list(
                        tenant.evaluator.evaluate_batch(task_args)
                    )
                except Exception as e:
                    box["err"] = e

            th = threading.Thread(
                target=call, daemon=True, name="dmosopt-eval-batch"
            )
            th.start()
            th.join(self._drain_deadline(tenant, n))
            if th.is_alive():
                if self.telemetry:
                    self.telemetry.inc("eval_deadline_exceeded_total")
                return None, RuntimeError(
                    f"tenant {tenant.handle.opt_id!r}: evaluate_batch "
                    f"exceeded the evaluation deadline backstop"
                )
            if "err" in box:
                return None, box["err"]
            return box["res"], None
        buffered: Dict[int, Any] = {}
        deadline = time.monotonic() + self._drain_deadline(tenant, n)
        try:
            while not handle.done:
                if time.monotonic() >= deadline:
                    # wedged evaluation the per-request machinery could
                    # not bound: abandon what is still in flight and
                    # mark the missing requests timed out — the step
                    # must not hang even with no policy configured
                    handle.cancel_pending()
                    if self.telemetry:
                        self.telemetry.inc("eval_deadline_exceeded_total")
                    self.logger.warning(
                        f"tenant {tenant.handle.opt_id!r}: evaluation "
                        f"drain exceeded its deadline backstop with "
                        f"{n - len(buffered)} request(s) undelivered"
                    )
                    for i in range(n):
                        buffered.setdefault(
                            i, EvalFailure(None, 1, timed_out=True)
                        )
                    break
                item = handle.poll(timeout=1.0)
                if item is None:
                    continue
                buffered[item[0]] = item[1]
        except Exception as e:
            return None, e
        return [buffered.get(i) for i in range(n)], None

    def _fold_tenant_results(self, tenant: _Tenant, results, task_reqs) -> int:
        """Fold one tenant's results in submission order under its
        fault policy: failed points are dropped from the fold (or, for
        the default ``"retire"`` policy, fail the tenant), non-finite
        rows are quarantined by `DistOptStrategy.complete_request`, and
        sub-quorum epochs advance the degradation state machine."""
        pol = tenant.policy or EvalPolicy()
        n_total = len(task_reqs)
        n_failed = 0
        # requests that produced nothing the archive can use — exhausted
        # failures AND quarantined (non-finite) returns — kept for the
        # no-archive re-issue below, so a tenant whose whole design was
        # lost keeps retrying (bounded by max_failed_epochs) instead of
        # idling forever with an empty queue
        unusable_reqs: List[EvalRequest] = []
        n_evals = 0
        try:
            for res, req in zip(results, task_reqs):
                if res is None or isinstance(res, EvalFailure):
                    n_failed += 1
                    unusable_reqs.append(req)
                    if pol.on_eval_failure == "retire":
                        cause = (
                            res.error
                            if isinstance(res, EvalFailure)
                            else None
                        )
                        attempts = (
                            res.n_attempts
                            if isinstance(res, EvalFailure)
                            else 1
                        )
                        raise RuntimeError(
                            f"tenant {tenant.handle.opt_id!r}: evaluation "
                            f"failed after {attempts} attempt(s)"
                        ) from cause
                    continue
                wall = (
                    res.pop("time", -1.0) if isinstance(res, dict)
                    else -1.0
                )
                nq_before = tenant.strat.n_quarantined
                tenant.strat.complete_request(
                    req.parameters, np.asarray(res[0]),
                    epoch=req.epoch, pred=req.prediction, time=wall,
                )
                if tenant.strat.n_quarantined > nq_before:
                    unusable_reqs.append(req)
                n_evals += 1
        except Exception as e:
            # per-tenant failure isolation: a broken objective takes
            # ITS tenant out (handle.error carries the cause), never
            # the service or its bucket-mates
            self._fail_tenant(tenant, e)
            return n_evals

        # quarantine accounting: complete_request diverted non-finite
        # rows; they count as unsuccessful toward the quorum below
        n_quarantined = tenant.strat.n_quarantined - tenant.quarantined_seen
        if n_quarantined > 0:
            tenant.quarantined_seen = tenant.strat.n_quarantined
            if self.telemetry:
                self.telemetry.inc(
                    "tenant_points_quarantined_total", n_quarantined,
                    tenant=tenant.handle.opt_id,
                )
        if n_failed > 0:
            tenant.eval_failures += n_failed
            tenant.degraded = True
            if self.telemetry:
                self.telemetry.inc(
                    "tenant_eval_failures_total", n_failed,
                    tenant=tenant.handle.opt_id,
                )
            self.logger.warning(
                f"tenant {tenant.handle.opt_id!r}: {n_failed}/{n_total} "
                f"evaluation(s) failed this epoch; continuing degraded "
                f"({tenant.eval_failures} failures total)"
            )

        # successes are requests that produced a finite archive row:
        # quarantined rows completed "successfully" but contributed
        # nothing the surrogate can train on
        n_ok = max(n_evals - n_quarantined, 0)
        frac = (n_ok / n_total) if n_total else 1.0
        tenant.last_success_fraction = frac
        sub_quorum = (
            frac < pol.min_success_fraction
            if pol.on_eval_failure == "quorum"
            else n_ok == 0
        ) if n_total else False
        if sub_quorum:
            tenant.failed_epochs += 1
            if tenant.failed_epochs >= pol.max_failed_epochs:
                self._fail_tenant(
                    tenant,
                    RuntimeError(
                        f"tenant {tenant.handle.opt_id!r}: retired after "
                        f"{tenant.failed_epochs} consecutive sub-quorum "
                        f"evaluation round(s) "
                        f"(last success fraction {frac:.2f}, policy "
                        f"{pol.on_eval_failure!r})"
                    ),
                    state="degraded",
                )
            elif (
                tenant.strat.x is None
                and not tenant.strat.has_completed()
                and not tenant.strat.has_requests()
            ):
                # nothing evaluable ever landed (the whole initial
                # design failed or was quarantined): without an archive
                # the tenant cannot fit or resample, so re-issue the
                # unusable requests — transient faults get another
                # epoch, bounded by max_failed_epochs
                for req in unusable_reqs:
                    tenant.strat.append_request(req)
        else:
            tenant.failed_epochs = 0
        return n_evals

    def _drain_evaluations(self):
        """Evaluate every tenant's pending requests: submit ALL batches
        asynchronously first (device batches and host pools overlap
        across tenants) with each tenant's policy timeout/retry budget
        threaded into `submit_batch`, then fold each tenant's results
        in submission order under its fault policy."""
        inflight = []
        with span_scope(self.telemetry, "eval_dispatch"):
            for t in self._active.values():
                task_args, task_reqs = self._gather_tenant_rounds(t)
                if not task_args:
                    continue
                pol = t.policy or EvalPolicy()
                if hasattr(t.evaluator, "submit_batch"):
                    handle = t.evaluator.submit_batch(
                        task_args,
                        timeout=self._effective_timeout(t),
                        retries=pol.retries,
                        backoff=pol.backoff,
                        backoff_cap=pol.backoff_cap,
                    )
                else:
                    handle = None
                inflight.append((t, handle, task_args, task_reqs))

        n_evals = 0
        for t, handle, task_args, task_reqs in inflight:
            results, fatal = self._collect_results(t, handle, task_args)
            if fatal is not None:
                self._fail_tenant(t, fatal)
                continue
            n_evals += self._fold_tenant_results(t, results, task_reqs)
        return n_evals

    def _fail_tenant(
        self, tenant: _Tenant, error: BaseException, state: str = "failed"
    ):
        tenant.handle.error = error
        tenant.handle.done = True
        self._retire(tenant, state)
        if tenant.owns_evaluator and hasattr(tenant.evaluator, "close"):
            try:
                tenant.evaluator.close()
            except Exception:
                self.logger.exception(
                    f"tenant {tenant.handle.opt_id!r}: evaluator close "
                    f"failed during failure teardown"
                )
        self.logger.warning(
            f"tenant {tenant.handle.opt_id!r} failed and was retired "
            f"({type(error).__name__}: {error}); "
            f"{len(self._active)} tenant(s) continue"
        )
        if self.telemetry:
            self.telemetry.inc("tenants_failed_total")

    def _submit_write(self, fn, *args, **kwargs):
        """Queue one persistence closure. A dead writer (terminal write
        failure after its retry budget) degrades persistence instead of
        crashing the service: the submission is dropped, the failure is
        logged ONCE with its cause, and `introspect()`/the `status` CLI
        surface ``writer_failed`` — optimization itself continues."""
        if self._writer is None:
            self._writer = BackgroundWriter(telemetry=self.telemetry)
        try:
            self._writer.submit(fn, *args, **kwargs)
        except RuntimeError:
            self._note_writer_dead()

    def _note_writer_dead(self):
        if not self._writer_error_logged:
            self._writer_error_logged = True
            self.logger.exception(
                "background persistence writer is dead (write failed "
                "after its retry budget); the service continues WITHOUT "
                "persistence — fronts and checkpoints are no longer "
                "written (see introspect()['writer'])"
            )

    def _flush_writer(self):
        if self._writer is None:
            return
        try:
            self._writer.flush()
        except RuntimeError:
            self._note_writer_dead()

    def _stream_front(self, tenant: _Tenant, epoch: int):
        bx, by, _, _ = tenant.strat.get_best_evals()
        if bx is None:
            return
        tenant.handle._push(FrontUpdate(epoch, bx, by))
        if self.telemetry:
            self.telemetry.inc("tenant_front_updates_total")
        if tenant.file_path is not None:
            from dmosopt_tpu.storage import save_front_to_h5

            self._submit_write(
                save_front_to_h5,
                tenant.handle.opt_id, epoch, tenant.param_names,
                tenant.objective_names, bx, by, tenant.file_path,
                self.logger,
            )

    @contextlib.contextmanager
    def _step_phase(self, phases: Dict[str, float], name: str):
        """Time one sub-phase of `step()` into `phases` and
        `service_step_seconds{phase=}`. Tracing spans are composed at
        the call sites via `span_scope` so the span names stay
        string-literal-scannable by graftlint's metrics-catalog rule."""
        tel = self.telemetry
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            phases[name] = dt
            if tel:
                tel.observe("service_step_seconds", dt, phase=name)

    def _absorb_tenant_costs(self, tenant: _Tenant):
        """Move the epoch's attributed-cost keys from the strategy's
        stats into the handle's cumulative totals. Popping (not
        reading) matters: the stats dict persists across epochs, and a
        tenant that rides a bucket one epoch and the sequential path
        the next would otherwise re-count the stale share."""
        for key, phase in _COST_KEYS:
            v = tenant.strat.stats.pop(key, None)
            if v is not None:
                tenant.handle.cost_seconds[phase] += float(v)

    def step(self) -> int:
        """One epoch boundary: admit pending tenants, evaluate pending
        requests (initial designs and resample batches), advance every
        active tenant one epoch — bucket-mates batched — and stream
        fronts. Returns the number of tenants advanced.

        The step is decomposed into four timed phases — ``admit`` /
        ``eval`` / ``fit`` (the batched bucket advance, surrogate fit +
        inner EA) / ``fold`` (result installation + front streaming) —
        each observed into ``service_step_seconds{phase=}`` and, with
        tracing enabled, nested under one ``epoch`` span.

        With the task-graph scheduler enabled (``scheduler=`` knob) the
        same epoch runs as a task DAG instead — see `_step_taskgraph`;
        a scheduler concurrency of 1 executes the identical lockstep
        sequence and is bitwise-equal to this path."""
        if self._closed:
            raise RuntimeError("service is closed")
        if self.scheduler_concurrency:
            return self._step_taskgraph()
        from dmosopt_tpu.tenants import initialize_epochs_batched
        from dmosopt_tpu.datatypes import StrategyState

        t0 = time.perf_counter()
        phases: Dict[str, float] = {}
        n_advanced = 0
        # profiled steps (telemetry profile_dir/profile_epochs, keyed by
        # the service step index): the whole step body runs under a
        # jax.profiler capture that the device-time ledger ingests on
        # exit — per-program device times joined to this step's
        # gp_fit/ea_scan spans, per-tenant device seconds attributed
        # through the tenant_cost span shares (docs/observability.md
        # "Device-time ledger")
        trace_ctx = (
            self.telemetry.device_capture(self._steps_run)
            if self.telemetry and self.telemetry.should_trace(self._steps_run)
            else contextlib.nullcontext(None)
        )
        with trace_ctx, span_scope(self.telemetry, "epoch", step=self._steps_run):
            with self._step_phase(phases, "admit"), span_scope(
                self.telemetry, "admit"
            ):
                self._admit_pending()
            if not self._active:
                self._finish_step(t0, phases, 0)
                return 0
            with self._step_phase(phases, "eval"), span_scope(
                self.telemetry, "eval_drain"
            ):
                self._drain_evaluations()

            strategies, epochs = {}, {}
            for tid, t in self._active.items():
                if t.strat.x is None and not t.strat.has_completed():
                    # nothing evaluable has ever landed (a degraded
                    # tenant whose whole initial design failed): there
                    # is no archive to fit a surrogate on, so the
                    # tenant idles this step — its re-issued requests
                    # (or its retirement) are handled by the eval fold
                    continue
                strategies[tid] = t.strat
                epochs[tid] = t.epochs_run
            # no own span: the bucket runs open their gp_fit / ea_scan
            # spans (with tenant_cost children) directly under `epoch`
            with self._step_phase(phases, "fit"):
                initialize_epochs_batched(
                    strategies, epochs, min_bucket=self.min_bucket,
                    telemetry=self.telemetry, logger=self.logger,
                    # per-tenant epoch-init failures retire THAT tenant
                    # (handle.error carries the cause) instead of
                    # raising out of step() past its bucket-mates
                    on_error=lambda tid, e: self._fail_tenant(
                        self._active[tid], e
                    ),
                )

            with self._step_phase(phases, "fold"), span_scope(
                self.telemetry, "fold"
            ):
                finished = []
                for tid, t in list(self._active.items()):
                    if tid not in strategies:
                        continue  # idled (no archive) or failed at init
                    try:
                        resample = (t.epochs_run + 1) < t.n_epochs
                        state, _res, _evals = t.strat.update_epoch(
                            resample=resample
                        )
                        if state != StrategyState.CompletedEpoch:
                            raise RuntimeError(
                                f"tenant {t.handle.opt_id!r}: epoch did not "
                                f"complete in one update (state {state}); the "
                                f"service requires surrogate-mode tenants"
                            )
                        epoch = t.epochs_run
                        t.epochs_run += 1
                        self._absorb_tenant_costs(t)
                        self._stream_front(t, epoch)
                    except Exception as e:
                        self._fail_tenant(t, e)
                        continue
                    if t.epochs_run >= t.n_epochs:
                        finished.append(tid)

                for tid in finished:
                    t = self._active[tid]
                    t.handle.done = True
                    self._retire(t, "completed")
                    if t.owns_evaluator and hasattr(t.evaluator, "close"):
                        t.evaluator.close()
                    if self.telemetry:
                        self.telemetry.inc("tenants_completed_total")
            # epoch-boundary checkpoint BEFORE the flush: when step()
            # returns, the snapshot for this boundary is durable — a
            # kill -9 during the next epoch resumes from here
            self._checkpoint()
            self._flush_writer()
            n_advanced = len(strategies)
        if self.telemetry:
            self.telemetry.inc("service_epochs_total")
            self.telemetry.gauge("tenants_active", len(self._active))
            self.telemetry.observe(
                "phase_duration_seconds",
                time.perf_counter() - t0,
                phase="service_step",
            )
        self._finish_step(t0, phases, n_advanced)
        return n_advanced

    def _on_init_error(self, tid, e: BaseException):
        """Per-tenant epoch-init failure containment for graph bucket
        nodes: fail THAT tenant if it is still active (a concurrent
        eval-branch failure may already have retired it)."""
        with self._lock:
            t = self._active.get(tid)
        if t is not None:
            self._fail_tenant(t, e)

    def _step_taskgraph(self) -> int:
        """One epoch boundary as a task DAG (docs/parallel.md "Async
        task-graph epochs"): a ``dispatch`` node submits every tenant's
        pending evaluation batch, per-tenant ``eval`` nodes drain and
        fold results under each tenant's fault policy, per-provisional-
        bucket ``bucket`` nodes (grouped by `static_bucket_signature`,
        which needs no archive) and per-ineligible-tenant ``seq`` nodes
        run `initialize_epochs_batched` on their subset, per-tenant
        ``fold`` nodes install epochs and stream fronts through the
        BackgroundWriter, and a ``checkpoint`` node closes the step.

        A bucket node only waits on ITS members' eval nodes, so bucket
        B's fit/EA program launches while bucket A's host-side evals
        are still draining — the overlap the lockstep barrier forbids.
        Failures degrade per branch: a failed eval retires its tenant
        inside the eval node (never raising), so sibling branches keep
        running. At ``scheduler_concurrency == 1`` the nodes execute in
        creation order on the calling thread, which is exactly the
        lockstep sequence — bitwise parity with `step()`."""
        from dmosopt_tpu.tenants import (
            initialize_epochs_batched,
            static_bucket_signature,
        )
        from dmosopt_tpu.datatypes import StrategyState
        from dmosopt_tpu.parallel.taskgraph import DONE, FAILED, TaskGraph

        t0 = time.perf_counter()
        phases: Dict[str, float] = {}
        trace_ctx = (
            self.telemetry.device_capture(self._steps_run)
            if self.telemetry and self.telemetry.should_trace(self._steps_run)
            else contextlib.nullcontext(None)
        )
        run = None
        with trace_ctx, span_scope(self.telemetry, "epoch", step=self._steps_run):
            with self._step_phase(phases, "admit"), span_scope(
                self.telemetry, "admit"
            ):
                self._admit_pending()
            if not self._active:
                self._finish_step(t0, phases, 0)
                return 0
            # fold nodes run concurrently: create the writer up front so
            # the lazy `_submit_write` init cannot race
            if self._writer is None:
                self._writer = BackgroundWriter(telemetry=self.telemetry)

            tenants = list(self._active.items())
            graph = TaskGraph(f"step{self._steps_run}")
            inflight: Dict[int, Tuple] = {}

            def dispatch():
                with span_scope(self.telemetry, "eval_dispatch"):
                    for tid, t in tenants:
                        task_args, task_reqs = self._gather_tenant_rounds(t)
                        if not task_args:
                            continue
                        pol = t.policy or EvalPolicy()
                        if hasattr(t.evaluator, "submit_batch"):
                            handle = t.evaluator.submit_batch(
                                task_args,
                                timeout=self._effective_timeout(t),
                                retries=pol.retries,
                                backoff=pol.backoff,
                                backoff_cap=pol.backoff_cap,
                            )
                        else:
                            handle = None
                        inflight[tid] = (handle, task_args, task_reqs)

            dispatch_node = graph.add("dispatch", dispatch, kind="dispatch")

            def make_eval(tid, t):
                def eval_node():
                    entry = inflight.get(tid)
                    if entry is None:
                        return 0
                    handle, task_args, task_reqs = entry
                    results, fatal = self._collect_results(
                        t, handle, task_args
                    )
                    if fatal is not None:
                        self._fail_tenant(t, fatal)
                        return 0
                    return self._fold_tenant_results(t, results, task_reqs)

                return eval_node

            eval_nodes: Dict[int, Any] = {}
            for tid, t in tenants:
                eval_nodes[tid] = graph.add(
                    f"eval:{t.handle.opt_id}", make_eval(tid, t),
                    deps=[dispatch_node], kind="eval",
                    tenant=t.handle.opt_id,
                )

            # provisional grouping by STATIC bucket signature (no
            # archive needed): members whose archive disqualifies them
            # are re-routed sequential by the full eligibility recheck
            # inside `initialize_epochs_batched`, reproducing lockstep
            # bucket membership exactly
            group_members: Dict[Any, List[int]] = {}
            for tid, t in tenants:
                sig = static_bucket_signature(t.strat)
                key = sig if sig is not None else ("__seq__", tid)
                group_members.setdefault(key, []).append(tid)

            def make_group(tids):
                def group_node():
                    strategies, epochs = {}, {}
                    with self._lock:
                        members = [
                            (tid, self._active.get(tid)) for tid in tids
                        ]
                    for tid, t in members:
                        if t is None:
                            continue  # retired by its eval branch
                        if t.strat.x is None and not t.strat.has_completed():
                            # no archive ever landed: nothing to fit on;
                            # re-issue/retirement is the eval fold's job
                            continue
                        strategies[tid] = t.strat
                        epochs[tid] = t.epochs_run
                    if strategies:
                        initialize_epochs_batched(
                            strategies, epochs, min_bucket=self.min_bucket,
                            telemetry=self.telemetry, logger=self.logger,
                            on_error=self._on_init_error,
                        )
                    return frozenset(strategies)

                return group_node

            group_nodes: Dict[int, Any] = {}  # tid -> its group node
            member_tids: Dict[int, List[int]] = {}  # node seq -> members
            for key, tids in group_members.items():
                kind = "seq" if key[0] == "__seq__" else "bucket"
                first = self._active[tids[0]]
                name = (
                    f"seq:{first.handle.opt_id}" if kind == "seq"
                    else f"bucket:{key[0]}_d{key[1]}_o{key[2]}_p{key[3]}"
                )
                node = graph.add(
                    name, make_group(tids),
                    deps=[eval_nodes[tid] for tid in tids], kind=kind,
                    tenant=first.handle.opt_id if kind == "seq" else None,
                )
                member_tids[node.seq] = list(tids)
                for tid in tids:
                    group_nodes[tid] = node

            def make_fold(tid, t, group):
                def fold_node():
                    advanced = group.result or frozenset()
                    if tid not in advanced:
                        return False
                    with self._lock:
                        live = self._active.get(tid)
                    if live is None:
                        return False
                    try:
                        resample = (t.epochs_run + 1) < t.n_epochs
                        state, _res, _evals = t.strat.update_epoch(
                            resample=resample
                        )
                        if state != StrategyState.CompletedEpoch:
                            raise RuntimeError(
                                f"tenant {t.handle.opt_id!r}: epoch did "
                                f"not complete in one update (state "
                                f"{state}); the service requires "
                                f"surrogate-mode tenants"
                            )
                        epoch = t.epochs_run
                        t.epochs_run += 1
                        self._absorb_tenant_costs(t)
                        self._stream_front(t, epoch)
                    except Exception as e:
                        self._fail_tenant(t, e)
                        return False
                    if t.epochs_run >= t.n_epochs:
                        t.handle.done = True
                        self._retire(t, "completed")
                        if t.owns_evaluator and hasattr(t.evaluator, "close"):
                            t.evaluator.close()
                        if self.telemetry:
                            self.telemetry.inc("tenants_completed_total")
                    return True

                return fold_node

            fold_nodes = []
            for tid, t in tenants:
                fold_nodes.append(
                    graph.add(
                        f"fold:{t.handle.opt_id}",
                        make_fold(tid, t, group_nodes[tid]),
                        deps=[group_nodes[tid]], kind="fold",
                        tenant=t.handle.opt_id,
                    )
                )

            def checkpoint_node():
                self._checkpoint()
                self._flush_writer()

            ckpt = graph.add(
                "checkpoint", checkpoint_node, deps=fold_nodes,
                kind="checkpoint",
            )

            run = graph.run(
                concurrency=self.scheduler_concurrency,
                telemetry=self.telemetry, logger=self.logger,
            )

            # a failed bucket/seq node (an exception even the batched
            # core's sequential fallback could not contain) fails its
            # still-active members — per-branch degradation, never a
            # half-stepped tenant
            for node in run.failed:
                for tid in member_tids.get(node.seq, ()):
                    self._on_init_error(tid, node.error)
            if ckpt.state != DONE:
                # the checkpoint must happen even when a failed branch
                # skipped its node (every boundary durable — the
                # lockstep contract)
                self._checkpoint()
                self._flush_writer()
            if dispatch_node.state == FAILED:
                # lockstep parity: a dispatch-time failure (broken
                # evaluator plumbing) raises out of step()
                raise dispatch_node.error

            n_advanced = sum(
                len(n.result)
                for n in run.nodes
                if n.kind in ("bucket", "seq") and n.state == DONE and n.result
            )
            # per-phase extents from node timestamps (the lockstep
            # phases, derived instead of measured around barriers)
            for phase, kinds in (
                ("eval", ("dispatch", "eval")),
                ("fit", ("bucket", "seq")),
                ("fold", ("fold",)),
            ):
                starts = [
                    n.t_start for n in run.nodes
                    if n.kind in kinds and n.t_start is not None
                ]
                ends = [
                    n.t_end for n in run.nodes
                    if n.kind in kinds and n.t_end is not None
                ]
                if starts and ends:
                    phases[phase] = max(ends) - min(starts)
                    if self.telemetry:
                        self.telemetry.observe(
                            "service_step_seconds", phases[phase],
                            phase=phase,
                        )
        if self.telemetry:
            self.telemetry.inc("service_epochs_total")
            self.telemetry.gauge("tenants_active", len(self._active))
            self.telemetry.observe(
                "phase_duration_seconds",
                time.perf_counter() - t0,
                phase="service_step",
            )
            ledger = self.telemetry.ledger
            if ledger is not None and ledger.last_capture is not None:
                # device truth for the scheduler-stall rule: seconds the
                # device sat idle inside the last profiled capture
                cap = ledger.last_capture
                self.telemetry.gauge(
                    "scheduler_device_idle_gap_seconds",
                    max(cap.window_s - cap.device_busy_s, 0.0),
                )
        self._last_graph = run.to_dict() if run is not None else {}
        self._finish_step(t0, phases, n_advanced)
        return n_advanced

    def _finish_step(self, t0: float, phases: Dict[str, float], n_advanced: int):
        """Step-end bookkeeping: the whole-step timing series, the
        per-tenant-normalized throughput baseline, and the status-file
        snapshot."""
        wall = time.perf_counter() - t0
        if self.telemetry:
            self.telemetry.observe("service_step_seconds", wall, phase="step")
        self._steps_run += 1
        self._last_step = {
            "wall_s": wall,
            "n_advanced": n_advanced,
            "phases": {k: round(v, 6) for k, v in phases.items()},
        }
        if n_advanced > 0:
            per_tenant = wall / n_advanced
            self._last_step["wall_s_per_tenant"] = per_tenant
            if (
                self._best_step_s_per_tenant is None
                or per_tenant < self._best_step_s_per_tenant
            ):
                self._best_step_s_per_tenant = per_tenant
        snap = None
        if self.health is not None:
            # the active tier: rules over (registry snapshot,
            # introspect snapshot) at this step boundary — transitions
            # become health_alert events + health_alerts_total counts
            snap = self.introspect()
            self.health.evaluate(
                self.telemetry.registry.snapshot(),
                snap,
                step=self._steps_run,
            )
            # reuse the snapshot for the status write (introspect is a
            # full per-tenant walk — once per step, not twice), with
            # only the health block refreshed to this evaluation
            snap["health"] = self.health.summary()
        self._write_status(snap)

    # ------------------------------------------------- checkpoint / resume

    def _tenant_checkpoint(self, t: _Tenant) -> Dict[str, Any]:
        """One tenant's full resumable state: archive columns, pending
        request queue (the in-flight work a crash would lose — resume
        re-issues it), RNG state, epoch counters, degradation
        accounting, and warm-refit state."""
        s = t.strat
        if isinstance(s.reqs, Iterator):
            s.reqs = deque(s.reqs)
        reqs = list(s.reqs)
        arrays: Dict[str, Any] = {
            "x": s.x, "y": s.y, "f": s.f, "c": s.c, "t": s.t,
        }
        pred_width = 0
        if reqs:
            arrays["pending_x"] = np.stack(
                [np.asarray(r.parameters) for r in reqs]
            )
            arrays["pending_epoch"] = np.asarray(
                [int(r.epoch) for r in reqs], dtype=np.int64
            )
            has_pred = np.asarray(
                [r.prediction is not None for r in reqs], dtype=bool
            )
            arrays["pending_has_pred"] = has_pred
            real = [r.prediction for r in reqs if r.prediction is not None]
            if real:
                pred_width = int(np.asarray(real[0]).ravel().shape[0])
                preds = np.full(
                    (len(reqs), pred_width), np.nan,
                    dtype=np.asarray(real[0]).dtype,
                )
                for i, r in enumerate(reqs):
                    if r.prediction is not None:
                        preds[i] = np.asarray(r.prediction).ravel()
                arrays["pending_pred"] = preds
        refit_state = (
            s.refit_controller.export_state()
            if s.refit_controller is not None
            else None
        )
        state = {
            "opt_id": t.handle.opt_id,
            "tenant_id": t.handle.tenant_id,
            "epochs_run": t.epochs_run,
            "n_epochs": t.n_epochs,
            "epoch_index": s.epoch_index,
            "optimizer_draws": s.optimizer_draws,
            "rng_state": s.local_random.bit_generator.state,
            "eval_failures": t.eval_failures,
            "failed_epochs": t.failed_epochs,
            "degraded": t.degraded,
            "quarantined": s.n_quarantined,
            "quarantined_seen": t.quarantined_seen,
            "cost_seconds": dict(t.handle.cost_seconds),
            "pred_width": pred_width,
            "refit": refit_state,
        }
        return {"config": t.submit_spec, "state": state, "arrays": arrays}

    def _checkpoint_payload(self) -> Dict[str, Any]:
        with self._lock:
            tenants = list(self._active.values()) + list(self._pending)
        return {
            "service": {
                "ts": time.time(),
                "steps": self._steps_run,
                "min_bucket": self.min_bucket,
                # ownership lease: who wrote this snapshot, and at which
                # placement epoch — what `claim_service_checkpoint`
                # verifies before a migration may adopt these tenants
                "owner": self.owner,
                "placement_epoch": self.placement_epoch,
            },
            "tenants": {
                str(t.handle.tenant_id): self._tenant_checkpoint(t)
                for t in tenants
            },
        }

    def _checkpoint(self):
        """Queue one epoch-boundary state snapshot (atomic
        write-temp-rename inside `save_service_checkpoint_to_h5`);
        `step()` flushes the writer right after, so the boundary is
        durable by the time the step returns."""
        if self.checkpoint_path is None:
            return
        from dmosopt_tpu.storage import save_service_checkpoint_to_h5

        payload = self._checkpoint_payload()
        self._submit_write(
            save_service_checkpoint_to_h5, payload, self.checkpoint_path,
        )
        if self.telemetry:
            self.telemetry.inc("service_checkpoints_total")

    def _apply_restore(self, t: _Tenant, restore: Dict[str, Any]):
        """Overwrite a freshly constructed tenant with checkpointed
        state: archive, epoch counters, pending requests, RNG state.
        The construction-time xinit draw is irrelevant — the RNG state
        is restored wholesale AFTER it, and the request queue is
        replaced, so the resumed trajectory continues exactly where the
        checkpointed one stopped."""
        st = restore["state"]
        arrays = restore.get("arrays", {})
        s = t.strat
        s.x = arrays.get("x")
        s.y = arrays.get("y")
        s.f = arrays.get("f")
        s.c = arrays.get("c")
        s.t = arrays.get("t")
        s.epoch_index = int(st["epoch_index"])
        # replay the exact number of optimizer-cycle draws the
        # checkpointed run consumed (tracked, not derived: a
        # bucket-fallback epoch draws twice), so multi-optimizer
        # tenants resume on the right cycle position
        draws = int(st.get("optimizer_draws", s.epoch_index + 1))
        for _ in range(draws):
            next(s.optimizer_iter)
        s.optimizer_draws = draws
        s.local_random.bit_generator.state = st["rng_state"]
        s.n_quarantined = int(st.get("quarantined", 0))
        if s.n_quarantined:
            s.stats["n_quarantined"] = s.n_quarantined
        reqs: deque = deque()
        px = arrays.get("pending_x")
        if px is not None:
            eps = arrays.get("pending_epoch")
            has = arrays.get("pending_has_pred")
            preds = arrays.get("pending_pred")
            for i in range(px.shape[0]):
                pred = (
                    preds[i]
                    if preds is not None and has is not None and bool(has[i])
                    else None
                )
                reqs.append(EvalRequest(px[i], pred, int(eps[i])))
        s.reqs = reqs
        t.epochs_run = int(st["epochs_run"])
        t.eval_failures = int(st.get("eval_failures", 0))
        t.failed_epochs = int(st.get("failed_epochs", 0))
        t.degraded = bool(st.get("degraded", False))
        t.quarantined_seen = int(st.get("quarantined_seen", 0))
        stored_tid = int(st["tenant_id"])
        if not restore.get("adopted"):
            # resume in a fresh process keeps the stored ids; an
            # ADOPTING service already has its own tenants, so a
            # migrated tenant takes a fresh id (its opt_id is the
            # stable cross-worker identity)
            t.handle.tenant_id = stored_tid
        for k, v in (st.get("cost_seconds") or {}).items():
            t.handle.cost_seconds[k] = float(v)

    @classmethod
    def resume(
        cls,
        checkpoint_path: str,
        objectives: Dict[str, Any],
        *,
        evaluators: Optional[Dict[str, Any]] = None,
        min_bucket: Optional[int] = None,
        telemetry=None,
        logger=logger,
        status_path: Optional[str] = None,
        default_eval_timeout: float = DEFAULT_EVAL_TIMEOUT,
        checkpoint: bool = True,
        owner: Optional[str] = None,
        placement_epoch: Optional[int] = None,
        expected_owner: Optional[str] = None,
    ) -> Tuple["OptimizationService", Dict[str, TenantHandle]]:
        """Reconstruct a service from its epoch-boundary checkpoint.

        Rebuilds every stored (incomplete) tenant — archive, epoch
        counters, degradation state, RNG state — re-issues its pending
        (in-flight at crash time) evaluation requests, and returns
        ``(service, {opt_id: handle})``. Objective functions are code,
        not state: supply them per tenant through ``objectives``
        (matching each stored ``opt_id``), or a ready evaluator through
        ``evaluators``. The resumed run is seeded-trajectory-equivalent
        to the uninterrupted one from the checkpointed boundary on
        (pinned by tests/test_service_robustness.py); fronts streamed
        before the crash are in the tenants' own ``file_path`` stores,
        not replayed. With ``checkpoint=True`` (default) the resumed
        service keeps checkpointing to the same path.

        Lease handling (fleet migration, docs/robustness.md): with
        ``expected_owner`` set, resume refuses a checkpoint whose
        stored ``service.owner`` differs — the tenants were adopted by
        someone else. ``owner``/``placement_epoch`` default to the
        STORED lease, so a restarted worker resumes under its own
        identity; a tenant whose config carries an ``objective_ref``
        ("module:attr") needs no ``objectives`` entry."""
        from dmosopt_tpu.storage import (
            CheckpointLeaseError,
            load_service_checkpoint_from_h5,
        )

        data = load_service_checkpoint_from_h5(checkpoint_path)
        svc_meta = data["service"]
        stored_owner = svc_meta.get("owner")
        stored_epoch = int(svc_meta.get("placement_epoch") or 0)
        if expected_owner is not None and stored_owner != expected_owner:
            raise CheckpointLeaseError(
                f"resume: checkpoint {checkpoint_path!r} is owned by "
                f"{stored_owner!r}, not {expected_owner!r} (placement "
                f"epoch {stored_epoch}) — its tenants live elsewhere now"
            )
        svc = cls(
            min_bucket=(
                int(min_bucket)
                if min_bucket is not None
                else int(svc_meta.get("min_bucket", 2))
            ),
            telemetry=telemetry,
            logger=logger,
            status_path=status_path,
            default_eval_timeout=default_eval_timeout,
            checkpoint_path=checkpoint_path if checkpoint else None,
            owner=owner if owner is not None else stored_owner,
            placement_epoch=(
                int(placement_epoch)
                if placement_epoch is not None
                else stored_epoch
            ),
        )
        evaluators = evaluators or {}
        objectives = objectives or {}
        handles: Dict[str, TenantHandle] = {}
        max_tid = -1
        for key in sorted(data["tenants"], key=int):
            tp = data["tenants"][key]
            cfg = dict(tp["config"] or {})
            st = tp["state"]
            opt_id = st["opt_id"]
            obj = objectives.get(opt_id)
            evaluator = evaluators.get(opt_id)
            if obj is None and evaluator is None and not cfg.get(
                "objective_ref"
            ):
                raise ValueError(
                    f"resume: no objective (or evaluator, or stored "
                    f"objective_ref) supplied for stored tenant {opt_id!r}"
                )
            space = cfg.pop("space")
            objective_names = cfg.pop("objective_names")
            handles[opt_id] = svc.submit(
                obj, space, objective_names,
                opt_id=opt_id, evaluator=evaluator, _restore=tp, **cfg,
            )
            max_tid = max(max_tid, int(st["tenant_id"]))
        svc._ids = itertools.count(max_tid + 1)
        return svc, handles

    def adopt_checkpoint(
        self,
        checkpoint_path: str,
        objectives: Optional[Dict[str, Any]] = None,
        *,
        expected_owner: Optional[str],
        placement_epoch: int,
        evaluators: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, TenantHandle]:
        """Live tenant migration: adopt every incomplete tenant stored
        in ANOTHER worker's epoch-boundary checkpoint into this running
        service. The dead worker's tenants join this service's buckets
        at the next `step()` and continue seeded-trajectory-equivalent
        (the checkpoint restores archive, RNG state, epoch counters,
        pending requests and degradation accounting — the same contract
        `resume` pins bitwise).

        The adoption first CLAIMS the checkpoint's ownership lease
        (`storage.claim_service_checkpoint`): the stored owner must be
        ``expected_owner`` and the stored placement epoch must be older
        than ``placement_epoch``, and the claim rewrites the stored
        lease to this service's ``owner`` — so a second adopter raises
        `storage.CheckpointLeaseError` instead of double-owning the
        tenants. Objective functions resolve per tenant from
        ``objectives``/``evaluators`` or the stored ``objective_ref``.
        Returns ``{opt_id: TenantHandle}`` for the adopted tenants."""
        if self._closed:
            raise RuntimeError("service is closed")
        from dmosopt_tpu.storage import (
            claim_service_checkpoint,
            load_service_checkpoint_from_h5,
        )

        data = load_service_checkpoint_from_h5(checkpoint_path)
        objectives = objectives or {}
        evaluators = evaluators or {}
        # validate EVERY stored tenant BEFORE claiming the lease: the
        # claim is consumed (owner rewritten) even if adoption then
        # fails, which would orphan the tenants — a validation error
        # must leave the checkpoint adoptable by someone else
        own_ids = {
            t.handle.opt_id
            for t in list(self._active.values()) + list(self._pending)
        }
        for key in data["tenants"]:
            tp = data["tenants"][key]
            cfg = tp["config"] or {}
            opt_id = tp["state"]["opt_id"]
            if opt_id in own_ids:
                raise ValueError(
                    f"adopt: tenant {opt_id!r} already lives in this "
                    f"service — opt_ids are the cross-worker identity "
                    f"and must be fleet-unique"
                )
            if (
                objectives.get(opt_id) is None
                and evaluators.get(opt_id) is None
                and not cfg.get("objective_ref")
            ):
                raise ValueError(
                    f"adopt: no objective (or evaluator, or stored "
                    f"objective_ref) available for tenant {opt_id!r}"
                )
        claim_service_checkpoint(
            checkpoint_path, expected_owner, self.owner,
            int(placement_epoch), logger=self.logger,
        )
        handles: Dict[str, TenantHandle] = {}
        for key in sorted(data["tenants"], key=int):
            tp = dict(data["tenants"][key])
            cfg = dict(tp["config"] or {})
            st = tp["state"]
            opt_id = st["opt_id"]
            obj = objectives.get(opt_id)
            evaluator = evaluators.get(opt_id)
            space = cfg.pop("space")
            objective_names = cfg.pop("objective_names")
            tp["adopted"] = True
            handles[opt_id] = self.submit(
                obj, space, objective_names,
                opt_id=opt_id, evaluator=evaluator, _restore=tp, **cfg,
            )
        self.logger.info(
            f"adopted {len(handles)} tenant(s) from {checkpoint_path} "
            f"(previous owner {expected_owner!r}, placement epoch "
            f"{placement_epoch})"
        )
        return handles

    # ------------------------------------------------------ introspection

    @staticmethod
    def _tenant_snapshot(t: _Tenant, state: str) -> Dict[str, Any]:
        cost = dict(t.handle.cost_seconds)
        snap = {
            "opt_id": t.handle.opt_id,
            "tenant_id": t.handle.tenant_id,
            "state": state,
            "epoch": t.epochs_run,
            "n_epochs": t.n_epochs,
            "cost_seconds": {k: round(v, 6) for k, v in cost.items()},
        }
        # attributed throughput: the tenant's generation budget over its
        # attributed EA seconds per epoch — only meaningful once a
        # batched epoch has landed a cost share
        if cost.get("ea", 0.0) > 0 and t.epochs_run > 0:
            snap["gens_per_sec"] = round(
                t.strat.num_generations * t.epochs_run / cost["ea"], 3
            )
        # degradation state (docs/robustness.md): only surfaced once a
        # fault has actually touched the tenant, so healthy snapshots
        # stay exactly as small as before
        if t.degraded or t.eval_failures or t.failed_epochs:
            snap["degraded"] = t.degraded
            snap["eval_failures_total"] = t.eval_failures
            snap["failed_epochs_consecutive"] = t.failed_epochs
            if t.last_success_fraction is not None:
                snap["last_success_fraction"] = round(
                    t.last_success_fraction, 3
                )
        if t.quarantined_seen:
            snap["points_quarantined_total"] = t.quarantined_seen
        return snap

    def _retire_summary(self, t: _Tenant, state: str) -> Dict[str, Any]:
        return self._tenant_snapshot(t, state)

    def _throughput_check(self) -> Dict[str, Any]:
        """Loadavg-normalized step-throughput regression check — the
        BENCH_r04/r05 trap detected at runtime: a contended host
        inflates wall clocks 3-9x, so a slow step on a loaded machine
        reads ``host_contended`` (re-measure idle before believing it),
        while a slow step on an idle machine is a genuine
        ``regression_suspect``. Baseline = the best per-tenant step
        wall this service has seen."""
        try:
            load1 = os.getloadavg()[0]
        except OSError:  # pragma: no cover - platform without loadavg
            load1 = None
        ncpu = os.cpu_count() or 1
        last = self._last_step.get("wall_s_per_tenant")
        best = self._best_step_s_per_tenant
        out: Dict[str, Any] = {
            "last_step_s_per_tenant": round(last, 6) if last else last,
            "best_step_s_per_tenant": round(best, 6) if best else best,
            "loadavg_1m": round(load1, 2) if load1 is not None else None,
            "cpu_count": ncpu,
            "load_ratio": (
                round(load1 / ncpu, 3) if load1 is not None else None
            ),
        }
        if last is None or best is None:
            out["status"] = "no_data"
        elif last <= 2.0 * best:
            out["status"] = "ok"
        elif load1 is not None and load1 > 1.5 * ncpu:
            out["status"] = "host_contended"
            out["note"] = (
                "step wall regressed but the host is contended "
                "(1-min loadavg above 1.5x cores) — walls can be 3-9x "
                "inflated; re-measure idle before trusting this"
            )
        else:
            out["status"] = "regression_suspect"
            out["note"] = (
                "step wall regressed more than 2x against this "
                "service's best on an apparently idle host"
            )
        return out

    def introspect(self) -> Dict[str, Any]:
        """Live service snapshot: every tenant's state/epoch/attributed
        cost, queue depths (pending submissions, writer backlog),
        telemetry series-overflow state, the last step's per-phase
        seconds, and the loadavg-normalized throughput check. Plain
        JSON-able dict — also written to ``status_path`` after every
        step and rendered by the ``status`` CLI subcommand. Safe to
        call from a monitoring thread while another thread steps.
        ``tenant_counts`` is cumulative and exact; the ``tenants`` list
        shows active/pending tenants plus the most recent retirees (the
        `_retired` bound), not the full lifetime history."""
        with self._lock:
            pending_tenants = list(self._pending)
            active_tenants = list(self._active.values())
            retired = list(self._retired)
            counts = dict(self._retired_counts)
        tenants = [
            self._tenant_snapshot(t, "active") for t in active_tenants
        ]
        tenants.extend(
            self._tenant_snapshot(t, "pending") for t in pending_tenants
        )
        tenants.extend(retired)
        if active_tenants:
            counts["active"] = len(active_tenants)
        if pending_tenants:
            counts["pending"] = len(pending_tenants)
        overflow = 0.0
        if self.telemetry:
            overflow = self.telemetry.registry.counter_value(
                "telemetry_series_overflow_total"
            )
        snap = {
            "ts": time.time(),
            "closed": self._closed,
            "steps": self._steps_run,
            "tenant_counts": counts,
            "tenants": sorted(tenants, key=lambda t: t["tenant_id"]),
            "queue_depths": {
                "pending_submissions": len(pending_tenants),
                "writer_backlog": (
                    self._writer.queue_depth if self._writer is not None else 0
                ),
            },
            # persistence health: a dead writer degrades the service
            # (fronts/checkpoints stop) instead of crashing it — this is
            # where that state is visible (plus the `status` CLI)
            "writer": {
                "failed": (
                    self._writer.writer_failed
                    if self._writer is not None
                    else False
                ),
                "retries_total": (
                    self._writer.retries_total
                    if self._writer is not None
                    else 0
                ),
            },
            "checkpoint_path": self.checkpoint_path,
            "lease": {
                "owner": self.owner,
                "placement_epoch": self.placement_epoch,
            },
            "series_overflow_total": overflow,
            "last_step": dict(self._last_step),
            "throughput": self._throughput_check(),
        }
        if self.health is not None:
            # alert state (docs/observability.md "Run-health engine"):
            # firing alerts with severities — what /healthz serves and
            # the status CLI renders as the health block
            snap["health"] = self.health.summary()
        if self.exporter is not None:
            snap["exporter"] = {
                "host": self.exporter.host,
                "port": self.exporter.port,
                "url": self.exporter.url,
            }
        if self.telemetry and self.telemetry.tracer is not None:
            snap["trace_path"] = self.telemetry.tracer.path
            # span-buffer pressure: evictions past `trace_max_spans` —
            # invisible outside this dict before the device-truth PR
            snap["spans_dropped"] = self.telemetry.tracer.spans_dropped
        if self.scheduler_concurrency:
            # task-graph scheduler state (docs/parallel.md "Async
            # task-graph epochs"): last step's per-node states and
            # wait/run seconds — the host-side view the scheduler_*
            # metrics aggregate
            snap["scheduler"] = {
                "concurrency": self.scheduler_concurrency,
                "last_graph": dict(self._last_graph),
            }
        ledger = self.telemetry.ledger if self.telemetry else None
        if ledger is not None and ledger.has_data:
            # device truth (profiled steps only): per-program device
            # times, trace-derived busy/overlap fractions, per-tenant
            # device seconds — the ground truth the host-clock
            # throughput check above only estimates
            snap["device_ledger"] = ledger.summary()
        return snap

    def _write_status(self, snap: Optional[Dict[str, Any]] = None):
        """Atomically publish the introspection snapshot to
        ``status_path`` (tmp + rename, so a concurrent `status` CLI
        reader never sees a torn file). ``snap`` lets `_finish_step`
        reuse the snapshot it already built for the health evaluation.
        Best-effort: a failing status write must never take the
        service down."""
        if self.status_path is None:
            return
        try:
            tmp = self.status_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(
                    snap if snap is not None else self.introspect(),
                    fh, default=json_default,
                )
            os.replace(tmp, self.status_path)
        except OSError:
            self.logger.warning(
                f"status snapshot write to {self.status_path!r} failed",
                exc_info=True,
            )

    def run(self, max_steps: Optional[int] = None) -> int:
        """Step until every submitted tenant completes (or `max_steps`);
        returns the number of steps taken."""
        steps = 0
        while (self._active or self._pending) and (
            max_steps is None or steps < max_steps
        ):
            self.step()
            steps += 1
        return steps

    # ------------------------------------------------------------- close

    def close(self):
        if self._closed:
            return
        # graceful-shutdown checkpoint: still-running tenants' state
        # survives a deliberate close, so close() + resume() is a clean
        # migration (a tenant cancelled below is still incomplete in
        # the snapshot and resumes where it stopped)
        self._checkpoint()
        self._closed = True
        with self._lock:
            to_cancel = list(self._active.values()) + list(self._pending)
        for t in to_cancel:
            t.handle.done = True
            self._retire(t, "cancelled")
            if t.epochs_run < t.n_epochs and t.handle.error is None:
                # an interim (or absent) front must not read as a
                # completed optimization: result() re-raises this, while
                # best()/updates() still serve whatever was streamed
                t.handle.error = RuntimeError(
                    f"service closed before tenant {t.handle.opt_id!r} "
                    f"completed ({t.epochs_run}/{t.n_epochs} epochs)"
                )
            if t.owns_evaluator and hasattr(t.evaluator, "close"):
                try:
                    t.evaluator.close()
                except Exception:
                    self.logger.exception(
                        f"tenant {t.handle.opt_id!r}: evaluator close failed"
                    )
        with self._lock:
            self._active.clear()
            self._pending = []
        if self._writer is not None:
            try:
                self._writer.close()
            except RuntimeError:
                self._note_writer_dead()
            self._writer = None
        self._write_status()
        if self.exporter is not None:
            # after the final status write: the last scrape a prober
            # can land observes the closed-service snapshot, then the
            # exporter thread is joined (the PR 11 lifecycle rule)
            self.exporter.close()
            self.exporter = None
        if self.telemetry is not None and self._owns_telemetry:
            # exports the Chrome trace when a trace_path is configured
            self.telemetry.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
