"""Ask/tell optimization service over the problem-batched core.

The driver runs a fixed set of problems to completion; the service is
the "millions of users" surface on top of the same machinery (ROADMAP
item 1): callers **submit** optimization problems at any time, each
submission joins a tenant **bucket at the next epoch boundary**, every
`step()` advances all active tenants by one epoch — bucket-mates
through ONE compiled program per bucket (`dmosopt_tpu.tenants`) — and
each tenant's improving non-dominated front **streams back** through
its handle as epochs complete.

Phase staggering is first-class: tenants submitted at different times
(or with different epoch budgets) share buckets whenever their shapes
and configs match, each keeping its own epoch numbering; a tenant whose
configuration the batched core does not cover simply runs the
sequential path inside the same service loop.

Evaluation of real-objective batches reuses the async evaluator API
(`submit_batch`): each step submits EVERY tenant's pending requests
before folding any of them, so jax-objective device batches and
host-objective thread pools overlap across tenants. Per-tenant
persistence rides the pipeline's ordered `BackgroundWriter`
(`storage.save_front_to_h5` per epoch).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dmosopt_tpu.datatypes import OptProblem, ParameterSpace
from dmosopt_tpu.driver import eval_obj_fun_sp
from dmosopt_tpu.parallel.evaluator import (
    EvalFailure,
    HostFunEvaluator,
    JaxBatchEvaluator,
)
from dmosopt_tpu.parallel.pipeline import BackgroundWriter
from dmosopt_tpu.strategy import DistOptStrategy
from dmosopt_tpu.telemetry import Telemetry, create_telemetry, span_scope
from dmosopt_tpu.utils import json_default

logger = logging.getLogger(__name__)

# per-epoch attributed-cost keys the batched core leaves in a
# strategy's stats dict (dmosopt_tpu.tenants cost attribution); the
# service pops them after each epoch into the tenant's cumulative
# handle costs
_COST_KEYS = (
    ("cost_fit_seconds", "fit"),
    ("cost_ea_seconds", "ea"),
    ("cost_compile_seconds", "compile"),
)


@dataclass
class FrontUpdate:
    """One streamed front improvement: the tenant's non-dominated set
    after `epoch` completed."""

    epoch: int
    x: np.ndarray
    y: np.ndarray


class TenantHandle:
    """Caller-facing view of one submitted optimization: stream front
    updates as they land, read the latest front, await completion."""

    def __init__(self, tenant_id: int, opt_id: str):
        self.tenant_id = tenant_id
        self.opt_id = opt_id
        self.done = False
        self.error: Optional[BaseException] = None
        # cumulative attributed cost of this tenant's share of its
        # buckets' compiled programs (dmosopt_tpu.tenants attribution;
        # zero for tenants that only rode the sequential path)
        self.cost_seconds: Dict[str, float] = {
            "fit": 0.0, "ea": 0.0, "compile": 0.0,
        }
        self._updates: deque = deque()
        self._latest: Optional[FrontUpdate] = None
        self._lock = threading.Lock()

    # ---- service side
    def _push(self, update: FrontUpdate):
        with self._lock:
            self._updates.append(update)
            self._latest = update

    # ---- caller side
    def updates(self) -> List[FrontUpdate]:
        """Drain the queued front updates (oldest first)."""
        with self._lock:
            out = list(self._updates)
            self._updates.clear()
        return out

    def best(self) -> Optional[FrontUpdate]:
        """The most recent front, or None before the first epoch."""
        with self._lock:
            return self._latest

    def result(self) -> FrontUpdate:
        if self.error is not None:
            raise self.error
        if not self.done:
            raise RuntimeError(
                f"tenant {self.opt_id!r} still running; call "
                f"OptimizationService.run() or step() first"
            )
        if self._latest is None:
            raise RuntimeError(
                f"tenant {self.opt_id!r} finished without completing an "
                f"epoch (no front was produced)"
            )
        return self._latest


@dataclass
class _Tenant:
    handle: TenantHandle
    strat: DistOptStrategy
    evaluator: Any
    owns_evaluator: bool
    n_epochs: int
    file_path: Optional[str]
    param_names: Tuple[str, ...]
    objective_names: Tuple[str, ...]
    epochs_run: int = 0


class OptimizationService:
    """Multi-tenant ask/tell optimization: submit problems any time,
    `step()` advances every active tenant one epoch (bucket-batched),
    fronts stream back per tenant. Not thread-safe for concurrent
    `step()` calls; `submit()` may be called from any thread."""

    def __init__(
        self,
        *,
        min_bucket: int = 2,
        telemetry=None,
        logger=logger,
        status_path: Optional[str] = None,
    ):
        self.min_bucket = int(min_bucket)
        self.telemetry = create_telemetry(telemetry)
        self._owns_telemetry = not isinstance(telemetry, Telemetry)
        self.logger = logger
        self.status_path = status_path
        self._pending: List[_Tenant] = []
        self._active: Dict[int, _Tenant] = {}
        self._ids = itertools.count()
        self._writer: Optional[BackgroundWriter] = None
        self._lock = threading.Lock()
        self._closed = False
        # introspection state: step/phase timings, the best
        # per-tenant-normalized step wall (the throughput baseline),
        # and retired-tenant bookkeeping. `_retired` keeps only the
        # most RECENT retirees (a long-lived service retires tenants
        # forever; an unbounded list would make every status snapshot
        # O(lifetime tenants)) while `_retired_counts` keeps the
        # accurate cumulative totals per state.
        self._steps_run = 0
        self._last_step: Dict[str, Any] = {}
        self._best_step_s_per_tenant: Optional[float] = None
        self._retired: deque = deque(maxlen=256)
        self._retired_counts: Dict[str, int] = {}

    # ------------------------------------------------------------ submit

    def submit(
        self,
        obj_fun,
        space: Dict[str, Any],
        objective_names,
        *,
        opt_id: Optional[str] = None,
        jax_objective: bool = True,
        n_epochs: int = 5,
        population_size: int = 64,
        num_generations: int = 50,
        n_initial: int = 8,
        initial_method: str = "slh",
        resample_fraction: float = 0.25,
        optimizer_name: str = "nsga2",
        optimizer_kwargs: Optional[Dict] = None,
        surrogate_method_name: str = "gpr",
        surrogate_method_kwargs: Optional[Dict] = None,
        random_seed: Optional[int] = None,
        file_path: Optional[str] = None,
        evaluator=None,
    ) -> TenantHandle:
        """Submit one optimization problem; it joins a bucket at the
        next epoch boundary (`step()`). ``obj_fun`` is a jax-traceable
        batch objective (``jax_objective=True``, evaluated through the
        jitted batch evaluator) or a per-point host function. Returns a
        `TenantHandle` streaming the tenant's fronts."""
        if self._closed:
            raise RuntimeError("service is closed")
        if surrogate_method_name is None:
            raise ValueError(
                "the service runs surrogate-mode epochs; "
                "surrogate_method_name=None is not supported"
            )
        tenant_id = next(self._ids)
        opt_id = opt_id or f"tenant_{tenant_id}"
        handle = TenantHandle(tenant_id, opt_id)

        param_space = ParameterSpace.from_dict(space)
        eval_fun = partial(
            eval_obj_fun_sp, obj_fun, None, param_space, False, None, 0
        )
        prob = OptProblem(
            param_space.parameter_names, list(objective_names), None,
            lambda f: f, None, param_space, eval_fun, logger=self.logger,
        )
        owns_evaluator = evaluator is None
        if evaluator is None:
            evaluator = (
                JaxBatchEvaluator(obj_fun, problem_ids=[0])
                if jax_objective
                else HostFunEvaluator(eval_fun)
            )
        strat = DistOptStrategy(
            prob,
            n_initial=n_initial,
            initial_method=initial_method,
            population_size=int(population_size),
            num_generations=int(num_generations),
            resample_fraction=float(resample_fraction),
            optimizer_name=optimizer_name,
            optimizer_kwargs=optimizer_kwargs,
            surrogate_method_name=surrogate_method_name,
            surrogate_method_kwargs=surrogate_method_kwargs,
            local_random=np.random.default_rng(random_seed),
            logger=self.logger,
            telemetry=None,  # per-bucket service telemetry only
        )
        tenant = _Tenant(
            handle=handle, strat=strat, evaluator=evaluator,
            owns_evaluator=owns_evaluator, n_epochs=int(n_epochs),
            file_path=file_path,
            param_names=tuple(param_space.parameter_names),
            objective_names=tuple(objective_names),
        )
        with self._lock:
            self._pending.append(tenant)
        if self.telemetry:
            self.telemetry.inc("tenants_submitted_total")
        return handle

    # -------------------------------------------------------------- step

    def _admit_pending(self):
        with self._lock:
            admitted, self._pending = self._pending, []
            for t in admitted:
                self._active[t.handle.tenant_id] = t
        return len(admitted)

    def _retire(self, tenant: _Tenant, state: str):
        """Record one tenant leaving the active set: bounded recent
        snapshot + cumulative per-state count, under the lock so a
        monitoring thread's `introspect()` never races the mutation."""
        with self._lock:
            self._active.pop(tenant.handle.tenant_id, None)
            self._retired.append(self._retire_summary(tenant, state))
            self._retired_counts[state] = (
                self._retired_counts.get(state, 0) + 1
            )

    def _gather_tenant_rounds(self, tenant: _Tenant):
        """Pop the tenant's pending requests into single-problem
        evaluation rounds (the driver's `_gather_rounds` for one pid)."""
        task_args, task_reqs = [], []
        while True:
            req = tenant.strat.get_next_request()
            if req is None:
                break
            task_args.append({0: req.parameters})
            task_reqs.append(req)
        return task_args, task_reqs

    def _drain_evaluations(self):
        """Evaluate every tenant's pending requests: submit ALL batches
        asynchronously first (device batches and host pools overlap
        across tenants), then fold each tenant's results in submission
        order."""
        inflight = []
        with span_scope(self.telemetry, "eval_dispatch"):
            for t in self._active.values():
                task_args, task_reqs = self._gather_tenant_rounds(t)
                if not task_args:
                    continue
                if hasattr(t.evaluator, "submit_batch"):
                    handle = t.evaluator.submit_batch(task_args)
                else:
                    handle = None
                inflight.append((t, handle, task_args, task_reqs))

        n_evals = 0
        for t, handle, task_args, task_reqs in inflight:
            try:
                if handle is None:
                    results = list(t.evaluator.evaluate_batch(task_args))
                else:
                    buffered = {}
                    while not handle.done:
                        item = handle.poll(timeout=1.0)
                        if item is None:
                            continue
                        buffered[item[0]] = item[1]
                    results = [buffered[i] for i in sorted(buffered)]
                for res, req in zip(results, task_reqs):
                    if isinstance(res, EvalFailure):
                        raise RuntimeError(
                            f"tenant {t.handle.opt_id!r}: evaluation "
                            f"failed after {res.n_attempts} attempt(s)"
                        ) from res.error
                    wall = (
                        res.pop("time", -1.0) if isinstance(res, dict)
                        else -1.0
                    )
                    t.strat.complete_request(
                        req.parameters, np.asarray(res[0]),
                        epoch=req.epoch, pred=req.prediction, time=wall,
                    )
                    n_evals += 1
            except Exception as e:
                # per-tenant failure isolation: a broken objective takes
                # ITS tenant out (handle.error carries the cause), never
                # the service or its bucket-mates
                self._fail_tenant(t, e)
        return n_evals

    def _fail_tenant(self, tenant: _Tenant, error: BaseException):
        tenant.handle.error = error
        tenant.handle.done = True
        self._retire(tenant, "failed")
        if tenant.owns_evaluator and hasattr(tenant.evaluator, "close"):
            try:
                tenant.evaluator.close()
            except Exception:
                self.logger.exception(
                    f"tenant {tenant.handle.opt_id!r}: evaluator close "
                    f"failed during failure teardown"
                )
        self.logger.warning(
            f"tenant {tenant.handle.opt_id!r} failed and was retired "
            f"({type(error).__name__}: {error}); "
            f"{len(self._active)} tenant(s) continue"
        )
        if self.telemetry:
            self.telemetry.inc("tenants_failed_total")

    def _submit_write(self, fn, *args, **kwargs):
        if self._writer is None:
            self._writer = BackgroundWriter(telemetry=self.telemetry)
        self._writer.submit(fn, *args, **kwargs)

    def _stream_front(self, tenant: _Tenant, epoch: int):
        bx, by, _, _ = tenant.strat.get_best_evals()
        if bx is None:
            return
        tenant.handle._push(FrontUpdate(epoch, bx, by))
        if self.telemetry:
            self.telemetry.inc("tenant_front_updates_total")
        if tenant.file_path is not None:
            from dmosopt_tpu.storage import save_front_to_h5

            self._submit_write(
                save_front_to_h5,
                tenant.handle.opt_id, epoch, tenant.param_names,
                tenant.objective_names, bx, by, tenant.file_path,
                self.logger,
            )

    @contextlib.contextmanager
    def _step_phase(self, phases: Dict[str, float], name: str):
        """Time one sub-phase of `step()` into `phases` and
        `service_step_seconds{phase=}`. Tracing spans are composed at
        the call sites via `span_scope` so the span names stay
        string-literal-scannable by graftlint's metrics-catalog rule."""
        tel = self.telemetry
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            phases[name] = dt
            if tel:
                tel.observe("service_step_seconds", dt, phase=name)

    def _absorb_tenant_costs(self, tenant: _Tenant):
        """Move the epoch's attributed-cost keys from the strategy's
        stats into the handle's cumulative totals. Popping (not
        reading) matters: the stats dict persists across epochs, and a
        tenant that rides a bucket one epoch and the sequential path
        the next would otherwise re-count the stale share."""
        for key, phase in _COST_KEYS:
            v = tenant.strat.stats.pop(key, None)
            if v is not None:
                tenant.handle.cost_seconds[phase] += float(v)

    def step(self) -> int:
        """One epoch boundary: admit pending tenants, evaluate pending
        requests (initial designs and resample batches), advance every
        active tenant one epoch — bucket-mates batched — and stream
        fronts. Returns the number of tenants advanced.

        The step is decomposed into four timed phases — ``admit`` /
        ``eval`` / ``fit`` (the batched bucket advance, surrogate fit +
        inner EA) / ``fold`` (result installation + front streaming) —
        each observed into ``service_step_seconds{phase=}`` and, with
        tracing enabled, nested under one ``epoch`` span."""
        if self._closed:
            raise RuntimeError("service is closed")
        from dmosopt_tpu.tenants import initialize_epochs_batched
        from dmosopt_tpu.datatypes import StrategyState

        t0 = time.perf_counter()
        phases: Dict[str, float] = {}
        n_advanced = 0
        with span_scope(self.telemetry, "epoch", step=self._steps_run):
            with self._step_phase(phases, "admit"), span_scope(
                self.telemetry, "admit"
            ):
                self._admit_pending()
            if not self._active:
                self._finish_step(t0, phases, 0)
                return 0
            with self._step_phase(phases, "eval"), span_scope(
                self.telemetry, "eval_drain"
            ):
                self._drain_evaluations()

            strategies = {
                tid: t.strat for tid, t in self._active.items()
            }
            epochs = {tid: t.epochs_run for tid, t in self._active.items()}
            # no own span: the bucket runs open their gp_fit / ea_scan
            # spans (with tenant_cost children) directly under `epoch`
            with self._step_phase(phases, "fit"):
                initialize_epochs_batched(
                    strategies, epochs, min_bucket=self.min_bucket,
                    telemetry=self.telemetry, logger=self.logger,
                )

            with self._step_phase(phases, "fold"), span_scope(
                self.telemetry, "fold"
            ):
                finished = []
                for tid, t in list(self._active.items()):
                    try:
                        resample = (t.epochs_run + 1) < t.n_epochs
                        state, _res, _evals = t.strat.update_epoch(
                            resample=resample
                        )
                        if state != StrategyState.CompletedEpoch:
                            raise RuntimeError(
                                f"tenant {t.handle.opt_id!r}: epoch did not "
                                f"complete in one update (state {state}); the "
                                f"service requires surrogate-mode tenants"
                            )
                        epoch = t.epochs_run
                        t.epochs_run += 1
                        self._absorb_tenant_costs(t)
                        self._stream_front(t, epoch)
                    except Exception as e:
                        self._fail_tenant(t, e)
                        continue
                    if t.epochs_run >= t.n_epochs:
                        finished.append(tid)

                for tid in finished:
                    t = self._active[tid]
                    t.handle.done = True
                    self._retire(t, "completed")
                    if t.owns_evaluator and hasattr(t.evaluator, "close"):
                        t.evaluator.close()
                    if self.telemetry:
                        self.telemetry.inc("tenants_completed_total")
            if self._writer is not None:
                self._writer.flush()
            n_advanced = len(strategies)
        if self.telemetry:
            self.telemetry.inc("service_epochs_total")
            self.telemetry.gauge("tenants_active", len(self._active))
            self.telemetry.observe(
                "phase_duration_seconds",
                time.perf_counter() - t0,
                phase="service_step",
            )
        self._finish_step(t0, phases, n_advanced)
        return n_advanced

    def _finish_step(self, t0: float, phases: Dict[str, float], n_advanced: int):
        """Step-end bookkeeping: the whole-step timing series, the
        per-tenant-normalized throughput baseline, and the status-file
        snapshot."""
        wall = time.perf_counter() - t0
        if self.telemetry:
            self.telemetry.observe("service_step_seconds", wall, phase="step")
        self._steps_run += 1
        self._last_step = {
            "wall_s": wall,
            "n_advanced": n_advanced,
            "phases": {k: round(v, 6) for k, v in phases.items()},
        }
        if n_advanced > 0:
            per_tenant = wall / n_advanced
            self._last_step["wall_s_per_tenant"] = per_tenant
            if (
                self._best_step_s_per_tenant is None
                or per_tenant < self._best_step_s_per_tenant
            ):
                self._best_step_s_per_tenant = per_tenant
        self._write_status()

    # ------------------------------------------------------ introspection

    @staticmethod
    def _tenant_snapshot(t: _Tenant, state: str) -> Dict[str, Any]:
        cost = dict(t.handle.cost_seconds)
        snap = {
            "opt_id": t.handle.opt_id,
            "tenant_id": t.handle.tenant_id,
            "state": state,
            "epoch": t.epochs_run,
            "n_epochs": t.n_epochs,
            "cost_seconds": {k: round(v, 6) for k, v in cost.items()},
        }
        # attributed throughput: the tenant's generation budget over its
        # attributed EA seconds per epoch — only meaningful once a
        # batched epoch has landed a cost share
        if cost.get("ea", 0.0) > 0 and t.epochs_run > 0:
            snap["gens_per_sec"] = round(
                t.strat.num_generations * t.epochs_run / cost["ea"], 3
            )
        return snap

    def _retire_summary(self, t: _Tenant, state: str) -> Dict[str, Any]:
        return self._tenant_snapshot(t, state)

    def _throughput_check(self) -> Dict[str, Any]:
        """Loadavg-normalized step-throughput regression check — the
        BENCH_r04/r05 trap detected at runtime: a contended host
        inflates wall clocks 3-9x, so a slow step on a loaded machine
        reads ``host_contended`` (re-measure idle before believing it),
        while a slow step on an idle machine is a genuine
        ``regression_suspect``. Baseline = the best per-tenant step
        wall this service has seen."""
        try:
            load1 = os.getloadavg()[0]
        except OSError:  # pragma: no cover - platform without loadavg
            load1 = None
        ncpu = os.cpu_count() or 1
        last = self._last_step.get("wall_s_per_tenant")
        best = self._best_step_s_per_tenant
        out: Dict[str, Any] = {
            "last_step_s_per_tenant": round(last, 6) if last else last,
            "best_step_s_per_tenant": round(best, 6) if best else best,
            "loadavg_1m": round(load1, 2) if load1 is not None else None,
            "cpu_count": ncpu,
            "load_ratio": (
                round(load1 / ncpu, 3) if load1 is not None else None
            ),
        }
        if last is None or best is None:
            out["status"] = "no_data"
        elif last <= 2.0 * best:
            out["status"] = "ok"
        elif load1 is not None and load1 > 1.5 * ncpu:
            out["status"] = "host_contended"
            out["note"] = (
                "step wall regressed but the host is contended "
                "(1-min loadavg above 1.5x cores) — walls can be 3-9x "
                "inflated; re-measure idle before trusting this"
            )
        else:
            out["status"] = "regression_suspect"
            out["note"] = (
                "step wall regressed more than 2x against this "
                "service's best on an apparently idle host"
            )
        return out

    def introspect(self) -> Dict[str, Any]:
        """Live service snapshot: every tenant's state/epoch/attributed
        cost, queue depths (pending submissions, writer backlog),
        telemetry series-overflow state, the last step's per-phase
        seconds, and the loadavg-normalized throughput check. Plain
        JSON-able dict — also written to ``status_path`` after every
        step and rendered by the ``status`` CLI subcommand. Safe to
        call from a monitoring thread while another thread steps.
        ``tenant_counts`` is cumulative and exact; the ``tenants`` list
        shows active/pending tenants plus the most recent retirees (the
        `_retired` bound), not the full lifetime history."""
        with self._lock:
            pending_tenants = list(self._pending)
            active_tenants = list(self._active.values())
            retired = list(self._retired)
            counts = dict(self._retired_counts)
        tenants = [
            self._tenant_snapshot(t, "active") for t in active_tenants
        ]
        tenants.extend(
            self._tenant_snapshot(t, "pending") for t in pending_tenants
        )
        tenants.extend(retired)
        if active_tenants:
            counts["active"] = len(active_tenants)
        if pending_tenants:
            counts["pending"] = len(pending_tenants)
        overflow = 0.0
        if self.telemetry:
            overflow = self.telemetry.registry.counter_value(
                "telemetry_series_overflow_total"
            )
        snap = {
            "ts": time.time(),
            "closed": self._closed,
            "steps": self._steps_run,
            "tenant_counts": counts,
            "tenants": sorted(tenants, key=lambda t: t["tenant_id"]),
            "queue_depths": {
                "pending_submissions": len(pending_tenants),
                "writer_backlog": (
                    self._writer.queue_depth if self._writer is not None else 0
                ),
            },
            "series_overflow_total": overflow,
            "last_step": dict(self._last_step),
            "throughput": self._throughput_check(),
        }
        if self.telemetry and self.telemetry.tracer is not None:
            snap["trace_path"] = self.telemetry.tracer.path
        return snap

    def _write_status(self):
        """Atomically publish the introspection snapshot to
        ``status_path`` (tmp + rename, so a concurrent `status` CLI
        reader never sees a torn file). Best-effort: a failing status
        write must never take the service down."""
        if self.status_path is None:
            return
        try:
            tmp = self.status_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(self.introspect(), fh, default=json_default)
            os.replace(tmp, self.status_path)
        except OSError:
            self.logger.warning(
                f"status snapshot write to {self.status_path!r} failed",
                exc_info=True,
            )

    def run(self, max_steps: Optional[int] = None) -> int:
        """Step until every submitted tenant completes (or `max_steps`);
        returns the number of steps taken."""
        steps = 0
        while (self._active or self._pending) and (
            max_steps is None or steps < max_steps
        ):
            self.step()
            steps += 1
        return steps

    # ------------------------------------------------------------- close

    def close(self):
        if self._closed:
            return
        self._closed = True
        with self._lock:
            to_cancel = list(self._active.values()) + list(self._pending)
        for t in to_cancel:
            t.handle.done = True
            self._retire(t, "cancelled")
            if t.epochs_run < t.n_epochs and t.handle.error is None:
                # an interim (or absent) front must not read as a
                # completed optimization: result() re-raises this, while
                # best()/updates() still serve whatever was streamed
                t.handle.error = RuntimeError(
                    f"service closed before tenant {t.handle.opt_id!r} "
                    f"completed ({t.epochs_run}/{t.n_epochs} epochs)"
                )
            if t.owns_evaluator and hasattr(t.evaluator, "close"):
                try:
                    t.evaluator.close()
                except Exception:
                    self.logger.exception(
                        f"tenant {t.handle.opt_id!r}: evaluator close failed"
                    )
        with self._lock:
            self._active.clear()
            self._pending = []
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        self._write_status()
        if self.telemetry is not None and self._owns_telemetry:
            # exports the Chrome trace when a trace_path is configured
            self.telemetry.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
