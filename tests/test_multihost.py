"""Multi-host (DCN) path: a 2-process jax.distributed loopback cluster
drives `initialize_distributed` plus a mesh spanning both processes'
devices through one sharded surrogate epoch (reference capability:
`mpirun -n K` multi-node runs, dmosopt.py:2518-2536 — here one SPMD
program over DCN instead of an MPI task farm)."""

import os

import pytest

from dmosopt_tpu.parallel.loopback import launch_loopback_cluster

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_multihost_worker.py")


@pytest.mark.slow
def test_two_process_dcn_loopback():
    num_procs, devs_per_proc = 2, 4
    results = launch_loopback_cluster(
        WORKER, n_processes=num_procs, devices_per_process=devs_per_proc,
        timeout=600,
    )
    for rc, out in results:
        if rc != 0 and "does not support" in out.lower():
            pytest.skip(f"multi-process CPU backend unavailable:\n{out[-500:]}")
        assert rc == 0, out[-3000:]
        assert "MULTIHOST_OK" in out, out[-3000:]
        assert f"global_devices={num_procs * devs_per_proc}" in out
