"""Multi-host (DCN) path: a 2-process jax.distributed loopback cluster
drives `initialize_distributed` plus a mesh spanning both processes'
devices through one sharded surrogate epoch (reference capability:
`mpirun -n K` multi-node runs, dmosopt.py:2518-2536 — here one SPMD
program over DCN instead of an MPI task farm)."""

import os
import sys

import pytest

from dmosopt_tpu.parallel.loopback import launch_loopback_cluster

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_multihost_worker.py")
RUN_WORKER = os.path.join(REPO, "tests", "_multihost_run_worker.py")
if os.path.join(REPO, "tests") not in sys.path:
    sys.path.insert(0, os.path.join(REPO, "tests"))


def _assert_cluster_ok(results, marker):
    """Common rank-result check: skip when the CPU backend can't do
    multi-process, else every rank must exit 0 and print `marker`."""
    for rc, out in results:
        if rc != 0 and "does not support" in out.lower():
            pytest.skip(f"multi-process CPU backend unavailable:\n{out[-500:]}")
        assert rc == 0, out[-3000:]
        assert marker in out, out[-3000:]


@pytest.mark.slow
def test_two_process_dcn_loopback():
    """Each rank checks in-worker that the DCN-spanning sharded epoch
    equals its replicated single-process twin (same seeds)."""
    num_procs, devs_per_proc = 2, 4
    results = launch_loopback_cluster(
        WORKER, n_processes=num_procs, devices_per_process=devs_per_proc,
        timeout=600,
    )
    _assert_cluster_ok(results, "MULTIHOST_OK")
    for _, out in results:
        assert f"global_devices={num_procs * devs_per_proc}" in out


@pytest.mark.slow
def test_multihost_resume_from_existing_checkpoint(tmp_path):
    """Cluster resume end-to-end: a single-process run writes the
    checkpoint, then a 2-process cluster runs the same config — both
    ranks take the resume path (the broadcast True branch executes;
    note the loopback filesystem is shared, so a non-primary rank's own
    isfile() would agree anyway — the divergence-under-unshared-fs case
    is covered by the loud FileNotFoundError in driver.py, not here),
    append new epochs with advancing labels, and agree on the result."""
    import h5py
    import numpy as np

    import dmosopt_tpu
    from dmosopt_tpu.benchmarks.zdt import zdt1
    from _multihost_run_worker import multihost_run_params

    h5_path = tmp_path / "multihost_run.h5"
    params = multihost_run_params(zdt1, file_path=str(h5_path))
    dmosopt_tpu.run(params, verbose=False)
    with h5py.File(h5_path, "r") as f:
        n_before = f["multihost_run/0/parameters"].shape[0]
        e_before = int(np.asarray(f["multihost_run/0/epochs"]).max())

    results = launch_loopback_cluster(
        RUN_WORKER, n_processes=2, devices_per_process=4, timeout=600,
        extra_args=(str(tmp_path),),
    )
    _assert_cluster_ok(results, "MULTIHOST_RUN_OK")

    with h5py.File(h5_path, "r") as f:
        n_after = f["multihost_run/0/parameters"].shape[0]
        e_after = int(np.asarray(f["multihost_run/0/epochs"]).max())
    assert n_after > n_before, (n_before, n_after)
    assert e_after > e_before, (e_before, e_after)

    # SPMD: the resumed cluster ranks agree on the final archive
    r0 = np.load(tmp_path / "best_rank0.npz")
    r1 = np.load(tmp_path / "best_rank1.npz")
    np.testing.assert_array_equal(r0["y"], r1["y"])


@pytest.mark.slow
def test_multihost_public_run_end_to_end_equivalence(tmp_path):
    """The PUBLIC `dmosopt_tpu.run()` across a 2-process cluster: full
    epoch loop with rank-0-only H5 writes over a mesh spanning both
    processes, and the final archive must equal the same-seed
    single-process run (the reference runs its whole loop under
    `mpirun -n K`, dmosopt.py:2518-2536)."""
    import numpy as np

    results = launch_loopback_cluster(
        RUN_WORKER, n_processes=2, devices_per_process=4, timeout=600,
        extra_args=(str(tmp_path),),
    )
    _assert_cluster_ok(results, "MULTIHOST_RUN_OK")

    # rank 0 wrote the checkpoint; it must be a loadable schema
    h5_path = tmp_path / "multihost_run.h5"
    assert h5_path.is_file()
    import h5py

    with h5py.File(h5_path, "r") as f:
        assert "multihost_run" in f

    # SPMD: both ranks computed the identical archive
    r0 = np.load(tmp_path / "best_rank0.npz")
    r1 = np.load(tmp_path / "best_rank1.npz")
    np.testing.assert_array_equal(r0["y"], r1["y"])

    # equivalence against the same-seed SINGLE-PROCESS run over the SAME
    # 8-device mesh (this test process holds 8 virtual devices): crossing
    # the process boundary must not change the numbers. (A fully
    # replicated mesh-less run is NOT the comparator: its per-epoch
    # differences sit at the f32 reduction-order floor (~1e-5, see
    # test_parallel.py equivalences) but amplify through the discrete
    # surrogate-refit/selection chain across epochs — the same reason two
    # XLA topologies are never bitwise identical over a whole run.)
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs the 8-virtual-device test process")

    import dmosopt_tpu
    from dmosopt_tpu.benchmarks.zdt import zdt1
    from dmosopt_tpu.parallel.mesh import create_mesh
    from _multihost_run_worker import multihost_run_params

    params = multihost_run_params(
        zdt1, mesh=create_mesh(8, axis_names=("pop",))
    )
    best = dmosopt_tpu.run(params, verbose=False)
    prms, lres = best
    y_single = np.column_stack([v for _, v in lres])
    y_cluster = r0["y"]
    assert y_cluster.shape == y_single.shape, (y_cluster.shape, y_single.shape)
    np.testing.assert_allclose(
        np.sort(y_cluster, axis=0), np.sort(y_single, axis=0),
        rtol=1e-4, atol=1e-4,
    )
