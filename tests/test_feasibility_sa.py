"""Feasibility classifier and sensitivity-analysis tests
(reference semantics: dmosopt/feasibility.py, dmosopt/sa.py)."""

import numpy as np
import pytest

from dmosopt_tpu.feasibility import LogisticFeasibilityModel
from dmosopt_tpu.sa import SA_DGSM, SA_FAST


def test_feasibility_learns_linear_boundary():
    rng = np.random.default_rng(0)
    X = rng.uniform(-1, 1, size=(300, 4))
    # constraint 0: feasible iff x0 > 0; constraint 1: feasible iff x1 < 0.3
    C = np.column_stack([X[:, 0], 0.3 - X[:, 1]])
    m = LogisticFeasibilityModel(X, C)

    x_test = np.array([[0.8, -0.5, 0.0, 0.0], [-0.8, 0.8, 0.0, 0.0]])
    pred = m.predict(x_test)
    assert pred.shape == (2, 2)
    assert pred[0].tolist() == [1, 1]
    assert pred[1].tolist() == [0, 0]

    r = m.rank(x_test)
    assert r.shape == (2,)
    assert r[0] > 0.8 and r[1] < 0.2

    proba = m.predict_proba(x_test)
    assert proba.shape == (2, 2, 2)
    assert np.allclose(proba.sum(axis=-1), 1.0)


def test_feasibility_single_class_constraint():
    rng = np.random.default_rng(1)
    X = rng.uniform(size=(50, 3))
    C = np.ones((50, 1))  # always feasible: no classifier trainable
    m = LogisticFeasibilityModel(X, C)
    assert m.weights[0] is None
    assert np.allclose(m.rank(X[:5]), 1.0)


class _QuadModel:
    """y0 depends strongly on x0, weakly on x1, not at all on x2."""

    def evaluate(self, X):
        X = np.asarray(X)
        y0 = 10.0 * X[:, 0] + 0.5 * X[:, 1]
        y1 = 5.0 * X[:, 1] ** 2
        return np.column_stack([y0, y1])


@pytest.mark.parametrize("cls,kwargs", [
    (SA_FAST, {"num_samples": 2048}),
    (SA_DGSM, {"num_samples": 400}),
])
def test_sensitivity_orders_parameters(cls, kwargs):
    sa = cls(
        np.zeros(3), np.ones(3), ["x0", "x1", "x2"], ["f0", "f1"]
    )
    res = sa.analyze(_QuadModel(), **kwargs)
    S1_f0 = res["S1"]["f0"]
    S1_f1 = res["S1"]["f1"]
    assert S1_f0.shape == (3,)
    # f0 is driven by x0; x2 is irrelevant everywhere
    assert S1_f0[0] > S1_f0[1] > S1_f0[2] - 1e-9
    assert S1_f1[1] > S1_f1[0]
    assert S1_f1[2] == pytest.approx(0.0, abs=1e-6)


def test_sa_di_mapping_in_moasmo():
    from dmosopt_tpu.moasmo import analyze_sensitivity

    di = analyze_sensitivity(
        _QuadModel(),
        np.zeros(3),
        np.ones(3),
        ["x0", "x1", "x2"],
        ["f0", "f1"],
        sensitivity_method_name="fast",
        sensitivity_method_kwargs={},
    )
    dm = di["di_mutation"]
    assert dm is not None and dm.shape == (3,)
    # most sensitive parameter gets the largest di; all within [di_min, 20]
    assert dm.max() == pytest.approx(20.0)
    assert dm.min() >= 1.0
