"""Overlapped epoch pipeline: async evaluation streaming, background
persistence, quorum/speculative semantics, and the failure policies.

The contract under test (docs/parallel.md "Overlapped epoch pipeline"):
``overlap_io`` (the default) may only change WHEN the driver blocks —
archives are byte-identical to ``serial`` on a seeded run; result
arrival order never leaks into archive row order; a request that raises
or times out kills only itself; ``speculative`` returns at quorum and
reconciles stragglers into the next training set.
"""

import threading
import time

import numpy as np
import pytest

import dmosopt_tpu
from dmosopt_tpu.parallel.evaluator import (
    EvalFailure,
    HostFunEvaluator,
    JaxBatchEvaluator,
)
from dmosopt_tpu.parallel.pipeline import BackgroundWriter, PipelineConfig
from dmosopt_tpu.telemetry import Telemetry

N_DIM = 4


def zdt1_host(pp):
    x = np.array([pp[f"x{i}"] for i in range(N_DIM)])
    f1 = x[0]
    g = 1.0 + 9.0 / (N_DIM - 1) * np.sum(x[1:])
    return np.array([f1, g * (1.0 - np.sqrt(f1 / g))])


def _params(**over):
    params = {
        "opt_id": "test_pipeline",
        "obj_fun": zdt1_host,
        "objective_names": ["f1", "f2"],
        "space": {f"x{i}": [0.0, 1.0] for i in range(N_DIM)},
        "problem_parameters": {},
        "n_initial": 4,
        "n_epochs": 2,
        "population_size": 16,
        "num_generations": 5,
        "resample_fraction": 0.5,
        "optimizer_name": "nsga2",
        "surrogate_method_name": "gpr",
        "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 20, "seed": 0},
        "random_seed": 7,
        "telemetry": False,
    }
    params.update(over)
    return params


# ------------------------------------------------------- PipelineConfig


def test_pipeline_config_from_spec():
    assert PipelineConfig.from_spec(None).mode == "overlap_io"
    assert PipelineConfig.from_spec("serial").mode == "serial"
    cfg = PipelineConfig.from_spec(
        {"mode": "speculative", "quorum_fraction": 0.5, "eval_retries": 2}
    )
    assert cfg.speculative and cfg.quorum_fraction == 0.5
    assert PipelineConfig.from_spec(cfg) is cfg
    assert not PipelineConfig.from_spec("serial").overlaps_io
    with pytest.raises(ValueError):
        PipelineConfig(mode="warp")
    with pytest.raises(ValueError):
        PipelineConfig(quorum_fraction=0.0)
    with pytest.raises(ValueError):
        PipelineConfig(on_eval_failure="shrug")
    with pytest.raises(TypeError):
        PipelineConfig.from_spec(3)


# ----------------------------------------------------- BackgroundWriter


def test_background_writer_executes_in_submission_order():
    seen = []
    w = BackgroundWriter()
    for i in range(50):
        w.submit(lambda i=i: (time.sleep(0.001 if i % 7 == 0 else 0), seen.append(i)))
    w.flush()
    assert seen == list(range(50))
    w.close()


def test_background_writer_surfaces_errors_and_skips_rest():
    seen = []
    w = BackgroundWriter()
    w.submit(seen.append, 1)

    def boom():
        raise OSError("disk gone")

    w.submit(boom)
    w.submit(seen.append, 2)  # must be skipped after the failure
    with pytest.raises(RuntimeError, match="background persistence"):
        w.flush()
    assert seen == [1]
    # the failure is terminal: new submissions are refused and never
    # execute — a failed append can never be followed by later writes
    with pytest.raises(RuntimeError, match="dead"):
        w.submit(seen.append, 3)
    w.close()
    assert seen == [1]


def test_background_writer_retries_only_transient_errors():
    """OSError (the HDF5/filesystem hiccup class) is retried with
    backoff up to the budget; any other exception kills the writer
    immediately — a logic bug must not be retried into the archive.
    (The retry-then-success and retry-exhaustion paths are driven
    deterministically by FaultyStore in tests/test_faults.py.)"""
    w = BackgroundWriter(max_retries=3, backoff=0.01, backoff_cap=0.05)

    def logic_bug():
        raise ValueError("not transient")

    w.submit(logic_bug)
    with pytest.raises(RuntimeError, match="background persistence"):
        w.flush()
    assert w.retries_total == 0  # no retry for a non-OSError
    assert w.writer_failed
    w.close()


def test_background_writer_close_is_idempotent_and_final():
    w = BackgroundWriter()
    w.submit(lambda: None)
    w.close()
    w.close()
    with pytest.raises(RuntimeError, match="closed"):
        w.submit(lambda: None)


# ----------------------------------------------- HostFunEvaluator async


def test_host_submit_batch_streams_as_completed():
    """Requests finish out of submission order (reversed sleeps); the
    handle must deliver them in completion order with correct indices
    and results."""

    def obj(sv):
        i = int(sv["i"])
        time.sleep(0.02 * (4 - i))
        return {0: np.array([float(i)]), "time": 0.0}

    ev = HostFunEvaluator(obj, n_workers=4)
    try:
        h = ev.submit_batch([{"i": np.array(i)} for i in range(4)])
        got = []
        while not h.done:
            item = h.poll(timeout=5.0)
            assert item is not None
            got.append(item)
        order = [i for i, _ in got]
        assert sorted(order) == [0, 1, 2, 3]
        assert order != [0, 1, 2, 3]  # genuinely completion-ordered
        for i, res in got:
            assert res[0][0] == float(i)
    finally:
        ev.close()


def test_host_submit_batch_failure_isolated_to_request():
    def obj(sv):
        if int(sv["i"]) == 1:
            raise ValueError("bad point")
        return {0: np.array([1.0]), "time": 0.0}

    ev = HostFunEvaluator(obj, n_workers=2)
    try:
        h = ev.submit_batch([{"i": np.array(i)} for i in range(3)])
        results = {}
        while not h.done:
            i, res = h.poll(timeout=5.0)
            results[i] = res
        assert isinstance(results[1], EvalFailure)
        assert isinstance(results[1].error, ValueError)
        assert not results[1].timed_out
        assert results[0][0][0] == 1.0 and results[2][0][0] == 1.0
    finally:
        ev.close()


def test_host_submit_batch_timeout_retry_giveup_telemetry():
    """A hung objective: timeout -> retry -> give-up, the whole path
    recorded in telemetry counters."""
    calls = []

    def obj(sv):
        calls.append(1)
        time.sleep(10.0)

    tel = Telemetry()
    ev = HostFunEvaluator(obj, n_workers=2)
    ev.telemetry = tel
    try:
        h = ev.submit_batch([{"i": np.array(0)}], timeout=0.1, retries=1)
        i, res = h.poll(timeout=30.0)
        assert i == 0
        assert isinstance(res, EvalFailure)
        assert res.timed_out and res.n_attempts == 2
        r = tel.registry
        assert r.counter_value("eval_timeouts_total") == 2
        assert r.counter_value("eval_retries_total") == 1
        assert r.counter_value("eval_failures_total") == 1
    finally:
        ev.close()


def test_host_close_drains_inflight_calls():
    """Satellite pin: close() must wait for running objective calls
    (they used to outlive the driver and race HDF5 teardown under
    shutdown(wait=False)) and cancel queued-but-unstarted ones."""
    started = threading.Event()
    finished = threading.Event()

    def obj(sv):
        if int(sv["i"]) == 0:
            started.set()
            time.sleep(0.3)
            finished.set()
        return {0: np.array([0.0]), "time": 0.0}

    ev = HostFunEvaluator(obj, n_workers=1)
    h = ev.submit_batch([{"i": np.array(i)} for i in range(5)])
    assert started.wait(5.0)
    ev.close()
    # the in-flight call ran to completion BEFORE close returned
    assert finished.is_set()
    # the queued requests never started; they are cancellable afterwards
    assert h.cancel_pending() >= 0


def test_host_retry_not_starved_by_saturated_pool():
    """A hung objective on a 1-worker pool: the abandoned attempt holds
    the only worker forever, so the retry must run on a dedicated thread
    — its timeout clock ticks and the EvalFailure is delivered in
    bounded time instead of the handle polling forever."""

    def obj(sv):
        time.sleep(60.0)

    ev = HostFunEvaluator(obj, n_workers=1)
    try:
        h = ev.submit_batch([{"i": np.array(0)}], timeout=0.1, retries=1)
        t0 = time.perf_counter()
        i, res = h.poll(timeout=30.0)
        assert time.perf_counter() - t0 < 10.0
        assert isinstance(res, EvalFailure)
        assert res.timed_out and res.n_attempts == 2
    finally:
        ev.close()


def test_host_hung_worker_does_not_starve_queued_requests():
    """One hung objective on a 1-worker pool must not strand the
    requests queued behind it: after the hang is detected they migrate
    to dedicated threads, so the batch completes — one EvalFailure, the
    rest real results — in bounded time."""

    def obj(sv):
        if int(sv["i"]) == 0:
            time.sleep(60.0)
        return {0: np.array([float(sv["i"])]), "time": 0.0}

    ev = HostFunEvaluator(obj, n_workers=1)
    try:
        h = ev.submit_batch(
            [{"i": np.array(i)} for i in range(3)], timeout=0.2, retries=0
        )
        t0 = time.perf_counter()
        results = {}
        while not h.done:
            item = h.poll(timeout=30.0)
            assert item is not None
            results[item[0]] = item[1]
        assert time.perf_counter() - t0 < 15.0
        assert isinstance(results[0], EvalFailure) and results[0].timed_out
        assert results[1][0][0] == 1.0 and results[2][0][0] == 2.0
    finally:
        ev.close(drain_timeout=1.0)


def test_submit_batch_empty_is_done_handle():
    from dmosopt_tpu.benchmarks.zdt import zdt1

    ev = HostFunEvaluator(lambda sv: {0: np.zeros(2), "time": 0.0})
    h = ev.submit_batch([])
    assert h.done and h.poll(timeout=0.01) is None
    ev.close()
    jev = JaxBatchEvaluator(zdt1, problem_ids=[0])
    h = jev.submit_batch([])
    assert h.done and h.poll(timeout=0.01) is None


def test_host_queued_completion_beats_stale_expiry():
    """Speculative mode can go a whole surrogate fit without polling. A
    result that completed WITHIN its timeout budget but sat in the
    completion queue during that gap must be delivered, not expired by
    its stale wall-clock reading."""

    def obj(sv):
        return {0: np.array([7.0]), "time": 0.0}

    ev = HostFunEvaluator(obj, n_workers=1)
    try:
        h = ev.submit_batch([{"i": np.array(0)}], timeout=0.2, retries=0)
        time.sleep(0.8)  # result completed instantly; driver was away
        i, res = h.poll(timeout=5.0)
        assert not isinstance(res, EvalFailure), res
        assert res[0][0] == 7.0
    finally:
        ev.close()


def test_host_close_prompt_after_abandoned_timeout():
    """close() drains normal in-flight calls, but must NOT join a
    worker stuck in a timed-out (abandoned, un-killable) objective —
    teardown would hang for the exact hung-objective case the timeout
    policy exists to survive."""

    def obj(sv):
        time.sleep(60.0)

    ev = HostFunEvaluator(obj, n_workers=1)
    h = ev.submit_batch([{"i": np.array(0)}], timeout=0.1, retries=0)
    i, res = h.poll(timeout=30.0)
    assert isinstance(res, EvalFailure) and res.timed_out
    t0 = time.perf_counter()
    ev.close(drain_timeout=1.0)
    assert time.perf_counter() - t0 < 5.0


# ----------------------------------------------- JaxBatchEvaluator async


def test_jax_submit_batch_chunked_matches_blocking():
    from dmosopt_tpu.benchmarks.zdt import zdt1

    ev = JaxBatchEvaluator(zdt1, problem_ids=[0])
    rng = np.random.default_rng(0)
    reqs = [{0: rng.random(6).astype(np.float32)} for _ in range(7)]
    blocking = ev.evaluate_batch(reqs)
    h = ev.submit_batch(reqs, n_chunks=3)
    streamed = {}
    while not h.done:
        i, res = h.poll()
        streamed[i] = res
    assert sorted(streamed) == list(range(7))
    for i in range(7):
        np.testing.assert_allclose(streamed[i][0], blocking[i][0], rtol=1e-6)


def test_jax_handle_poll_honors_timeout():
    """The AsyncEvalHandle contract: poll(timeout) returns None while
    the chunk is still executing, the result once it lands (driven with
    synthetic chunks — device execution itself is not interruptible)."""
    from dmosopt_tpu.parallel.evaluator import _JaxEvalHandle

    state = {"ready": False}
    r0, r1 = {0: np.array([1.0])}, {0: np.array([2.0])}
    h = _JaxEvalHandle(
        2, [([0, 1], lambda: [r0, r1], lambda: state["ready"])]
    )
    t0 = time.perf_counter()
    assert h.poll(timeout=0.05) is None
    assert 0.04 < time.perf_counter() - t0 < 2.0
    state["ready"] = True
    assert h.poll(timeout=5.0) == (0, r0)
    assert h.poll() == (1, r1)
    assert h.done


# ------------------------------------------------------- driver-level


def _archive(opt_id="test_pipeline"):
    from dmosopt_tpu.driver import dopt_dict

    strat = dopt_dict[opt_id].optimizer_dict[0]
    return np.asarray(strat.x), np.asarray(strat.y)


def test_out_of_order_arrival_preserves_archive_row_order():
    """4 workers + parameter-dependent sleeps scramble completion order;
    the overlap_io archive must equal the serial archive row for row."""

    def sleepy(pp):
        y = zdt1_host(pp)
        time.sleep(0.01 * (1.0 - float(pp["x0"])))  # later rows finish first
        return y

    dmosopt_tpu.run(
        _params(opt_id="ooo_serial", obj_fun=sleepy, pipeline="serial"),
        verbose=False,
    )
    xs, ys = _archive("ooo_serial")
    dmosopt_tpu.run(
        _params(
            opt_id="ooo_overlap", obj_fun=sleepy, pipeline="overlap_io",
            n_eval_workers=4,
        ),
        verbose=False,
    )
    xo, yo = _archive("ooo_overlap")
    np.testing.assert_array_equal(xs, xo)
    np.testing.assert_array_equal(ys, yo)


def test_overlap_io_archive_byte_identical_to_serial(tmp_path, monkeypatch):
    """Acceptance pin: on a seeded run, pipeline="overlap_io" produces a
    byte-identical HDF5 archive to serial mode. Wall-clock readings are
    the one legitimately nondeterministic archive input (eval-time stats
    differ even between two serial runs), so the clock is frozen — what
    remains is exactly the write-sequence determinism the overlap mode
    guarantees."""
    monkeypatch.setattr(time, "time", lambda: 0.0)
    monkeypatch.setattr(time, "perf_counter", lambda: 0.0)
    blobs = {}
    for mode in ("serial", "overlap_io", "serial_again"):
        fp = tmp_path / f"{mode}.h5"
        dmosopt_tpu.run(
            _params(
                opt_id="bytes", file_path=str(fp), save=True, save_eval=5,
                pipeline="serial" if mode == "serial_again" else mode,
            ),
            verbose=False,
        )
        blobs[mode] = fp.read_bytes()
    # control: the harness itself is deterministic across serial runs
    assert blobs["serial"] == blobs["serial_again"]
    assert blobs["overlap_io"] == blobs["serial"]


def test_speculative_quorum_reconciles_stragglers():
    """Speculative mode: the epoch-opening drain returns at quorum (the
    fit overlaps the stragglers), every straggler still lands in the
    archive, and the telemetry proves the overlap happened."""

    def sleepy(pp):
        time.sleep(0.02)
        return zdt1_host(pp)

    tel = Telemetry()
    dmosopt_tpu.run(
        _params(
            opt_id="spec", obj_fun=sleepy, n_epochs=3, telemetry=tel,
            pipeline={"mode": "speculative", "quorum_fraction": 0.5},
        ),
        verbose=False,
    )
    from dmosopt_tpu.driver import dopt_dict

    dopt = dopt_dict["spec"]
    assert not dopt._inflight  # every straggler reconciled by run end
    r = tel.registry
    assert r.counter_value("eval_quorum_returns_total") >= 1
    assert r.counter_value("eval_stragglers_total") >= 1
    # no evaluation was lost to speculation: each drained request is
    # archived (x rows accumulate initial design + both resample batches)
    x, y = _archive("spec")
    assert x.shape[0] == int(r.counter_value("evals_total"))
    assert np.all(np.isfinite(y))
    # overlap accounting emitted pipeline events with nonzero overlap
    assert any(
        ev.kind == "pipeline" and ev.fields.get("overlap_s", 0) > 0
        for ev in tel.log.records()
    )


def test_overlap_io_never_counts_quorum():
    """Quorum/straggler counters are speculative-mode bookkeeping; a
    plain overlap_io run (even one with slow, out-of-order evals) must
    report zero for both."""

    def sleepy(pp):
        time.sleep(0.005)
        return zdt1_host(pp)

    tel = Telemetry()
    dmosopt_tpu.run(
        _params(
            opt_id="noquorum", obj_fun=sleepy, telemetry=tel,
            pipeline="overlap_io", n_eval_workers=2,
        ),
        verbose=False,
    )
    r = tel.registry
    assert r.counter_value("eval_quorum_returns_total") == 0
    assert r.counter_value("eval_stragglers_total") == 0
    # the async path keeps the eval-latency histograms alive (they must
    # not go dark under the overlap default)
    batch = r.histogram_summary("eval_batch_duration_seconds", backend="host")
    assert batch is not None and batch["count"] >= 1


def test_time_limit_soft_stop_salvages_completed_results():
    """A time limit expiring mid-drain: the run stops promptly, and
    every evaluation that had already completed is folded into the
    archive (serial folds its whole blocking batch; overlap modes must
    not silently lose finished results)."""

    def slow(pp):
        time.sleep(0.15)
        return zdt1_host(pp)

    tel = Telemetry()
    t0 = time.perf_counter()
    dmosopt_tpu.run(
        _params(
            opt_id="softstop", obj_fun=slow, telemetry=tel,
            pipeline="overlap_io", n_epochs=5,
        ),
        time_limit=1.0,
        verbose=False,
    )
    assert time.perf_counter() - t0 < 30.0
    from dmosopt_tpu.driver import dopt_dict

    dopt = dopt_dict["softstop"]
    assert not dopt._inflight
    # whatever was counted as evaluated is actually in strategy state
    strat = dopt.optimizer_dict[0]
    n_rows = (0 if strat.x is None else strat.x.shape[0]) + len(strat.completed)
    assert n_rows == dopt.eval_count > 0


def test_failed_request_skip_policy_drops_only_that_row():
    """An objective that raises on one specific request marks only that
    request failed under on_eval_failure="skip": the run completes and
    the archive simply misses that row."""
    bad = {"n": 0}

    def flaky(pp):
        # fail exactly once, on the first evaluation of epoch-1 resamples
        if bad["n"] == 6:
            bad["n"] += 1
            raise RuntimeError("sensor glitch")
        bad["n"] += 1
        return zdt1_host(pp)

    tel = Telemetry()
    dmosopt_tpu.run(
        _params(
            opt_id="skip", obj_fun=flaky, telemetry=tel,
            pipeline={"mode": "overlap_io", "on_eval_failure": "skip"},
        ),
        verbose=False,
    )
    r = tel.registry
    assert r.counter_value("eval_failures_total") == 1
    x, _ = _archive("skip")
    # every successful evaluation is archived; only the failed one is gone
    assert x.shape[0] == int(r.counter_value("evals_total"))
    assert bad["n"] > 7  # the run continued past the failure


def test_skip_policy_rejected_without_surrogate():
    """No-surrogate mode sends each generation's results back into the
    epoch generator row-aligned with the x it yielded — a skipped round
    would misalign everything after it, so the config is rejected up
    front."""
    with pytest.raises(ValueError, match="skip"):
        dmosopt_tpu.run(
            _params(
                opt_id="skipnosurr", surrogate_method_name=None,
                pipeline={"mode": "overlap_io", "on_eval_failure": "skip"},
            ),
            verbose=False,
        )


def test_failed_request_raise_policy_aborts():
    def flaky(pp):
        raise RuntimeError("dead objective")

    with pytest.raises(RuntimeError, match="failed terminally"):
        dmosopt_tpu.run(
            _params(opt_id="raisepol", obj_fun=flaky, pipeline="overlap_io"),
            verbose=False,
        )
