"""Telemetry layer tests: registry semantics, event log round-trip,
the disabled path's zero-call guarantee, HDF5 persistence across a
save/restore cycle, and the `telemetry` CLI (docs/observability.md)."""

import json
import math

import numpy as np
import pytest

import dmosopt_tpu
from dmosopt_tpu.telemetry import (
    EventLog,
    MetricsRegistry,
    Telemetry,
    create_telemetry,
    phase_scope,
    read_jsonl,
)

h5py = pytest.importorskip("h5py")

N_DIM = 5


def zdt1_obj(pp):
    x = np.array([pp[f"x{i}"] for i in range(N_DIM)])
    f1 = x[0]
    g = 1.0 + 9.0 / (N_DIM - 1) * np.sum(x[1:])
    f2 = g * (1.0 - np.sqrt(f1 / g))
    return np.array([f1, f2])


def _run_params(file_path, **over):
    params = {
        "opt_id": "tel_run",
        "obj_fun": zdt1_obj,
        "objective_names": ["f1", "f2"],
        "space": {f"x{i}": [0.0, 1.0] for i in range(N_DIM)},
        "problem_parameters": {},
        "n_initial": 6,
        "n_epochs": 2,
        "population_size": 24,
        "num_generations": 8,
        "resample_fraction": 0.5,
        "surrogate_method_name": "gpr",
        "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 20, "seed": 0},
        "random_seed": 11,
        "save": True,
        "file_path": str(file_path),
    }
    params.update(over)
    return params


# ------------------------------------------------------------- registry


def test_registry_counter_labels_are_independent_series():
    reg = MetricsRegistry()
    reg.counter_inc("evals_total", 2, backend="host")
    reg.counter_inc("evals_total", 3, backend="host")
    reg.counter_inc("evals_total", 7, backend="jax")
    assert reg.counter_value("evals_total", backend="host") == 5
    assert reg.counter_value("evals_total", backend="jax") == 7
    # unlabeled is its own series, zero-valued until touched
    assert reg.counter_value("evals_total") == 0.0
    assert reg.metric_names() == {"evals_total"}
    with pytest.raises(ValueError):
        reg.counter_inc("evals_total", -1)


def test_registry_gauge_last_value_wins():
    reg = MetricsRegistry()
    reg.gauge_set("device_memory_bytes_in_use", 100.0, device="0")
    reg.gauge_set("device_memory_bytes_in_use", 250.0, device="0")
    assert reg.gauge_value("device_memory_bytes_in_use", device="0") == 250.0
    assert reg.gauge_value("device_memory_bytes_in_use", device="1") is None


def test_registry_histogram_buckets():
    reg = MetricsRegistry(
        histogram_buckets={"phase_duration_seconds": (0.1, 1.0, 10.0)}
    )
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        reg.histogram_observe("phase_duration_seconds", v, phase="train")
    s = reg.histogram_summary("phase_duration_seconds", phase="train")
    assert s["count"] == 5
    assert s["min"] == 0.05 and s["max"] == 50.0
    assert s["sum"] == pytest.approx(56.05)
    assert s["mean"] == pytest.approx(56.05 / 5)
    # custom boundaries: one below 0.1, two in (0.1, 1.0], one in
    # (1.0, 10.0], one in the +inf overflow bucket
    assert s["buckets"] == {"0.1": 1, "1.0": 2, "10.0": 1, "inf": 1}
    # a name without custom buckets falls back to the defaults
    reg.histogram_observe("other_duration", 0.3)
    assert reg.histogram_summary("other_duration")["count"] == 1


def test_registry_snapshot_is_jsonable():
    reg = MetricsRegistry()
    reg.counter_inc("epochs_total")
    reg.gauge_set("compile_cache_hits", 3)
    reg.histogram_observe("phase_duration_seconds", 0.2, phase="eval")
    snap = reg.snapshot()
    json.dumps(snap)  # must serialize without a custom encoder
    assert snap["counters"]["epochs_total"][""] == 1.0
    assert snap["histograms"]["phase_duration_seconds"]["phase=eval"]["count"] == 1


# ------------------------------------------------------ disabled = no-op


def test_disabled_telemetry_is_true_noop():
    tel = Telemetry(enabled=False)
    assert not tel
    tel.inc("evals_total", 5)
    tel.gauge("compile_cache_hits", 1.0)
    tel.observe("phase_duration_seconds", 0.1, phase="train")
    assert tel.event("epoch", duration_s=1.0) is None
    with tel.phase("train") as ph:
        ph["n_train"] = 10  # the throwaway dict is still writable
    assert tel.registry.metric_names() == set()
    assert len(tel.log) == 0


def test_create_telemetry_spec_resolution(tmp_path):
    assert create_telemetry(None).enabled
    assert create_telemetry(True).enabled
    assert create_telemetry(False) is None
    assert create_telemetry({"enabled": False}) is None
    tel = create_telemetry({"ring_size": 8, "profile_epochs": [1, 3]})
    assert tel.log._ring.maxlen == 8
    assert tel.profile_epochs == frozenset({1, 3})
    assert create_telemetry(tel) is tel
    assert create_telemetry(Telemetry(enabled=False)) is None
    with pytest.raises(TypeError):
        create_telemetry("yes")


def test_phase_scope_none_is_nullcontext():
    with phase_scope(None, "train") as ph:
        ph["x"] = 1  # throwaway dict; no telemetry object touched


def test_should_trace_gating(tmp_path):
    assert not Telemetry().should_trace(0)  # no profile_dir
    tel = Telemetry(profile_dir=str(tmp_path), profile_epochs=[2])
    assert tel.should_trace(2) and not tel.should_trace(1)
    # profile_epochs=None traces every epoch once a dir is set
    assert Telemetry(profile_dir=str(tmp_path)).should_trace(7)


# ------------------------------------------------------------- event log


def test_event_log_ring_is_bounded():
    log = EventLog(ring_size=4)
    for i in range(10):
        log.emit("phase", epoch=i, phase="train")
    assert len(log) == 4
    assert [e.epoch for e in log.records()] == [6, 7, 8, 9]
    assert [e.epoch for e in log.records(epoch=8)] == [8]


def test_jsonl_roundtrip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLog(ring_size=16, jsonl_path=path)
    log.emit(
        "phase", epoch=np.int64(0), phase="train",
        duration_s=np.float32(1.5), n_train=np.int32(40),
        losses=np.array([0.5, 0.25]),
    )
    log.emit("epoch", epoch=1, duration_s=2.0)
    log.close()

    back = list(read_jsonl(path))
    assert [(e.kind, e.epoch) for e in back] == [("phase", 0), ("epoch", 1)]
    f = back[0].fields
    # numpy payloads landed as plain JSON types
    assert f["phase"] == "train" and f["n_train"] == 40
    assert f["duration_s"] == pytest.approx(1.5)
    assert f["losses"] == [0.5, 0.25]
    # the file is valid JSONL for external tooling
    lines = [json.loads(l) for l in open(path)]
    assert all("ts" in d and "kind" in d for d in lines)


def test_jsonl_sink_rotates_at_max_bytes(tmp_path):
    """ISSUE 12 satellite: a size-bounded sink rotates instead of
    growing unbounded — the live file becomes `.1` (shifting existing
    rotated files), at most `keep` rotated files survive, and every
    event is still on disk across the chain until age drops it."""
    path = str(tmp_path / "events.jsonl")
    log = EventLog(ring_size=16, jsonl_path=path, max_bytes=256, keep=2)
    rotations_seen = []
    log.on_rotate = lambda: rotations_seen.append(1)
    n = 40
    for i in range(n):
        log.emit("phase", epoch=i, phase="train", duration_s=1.0)
    log.close()

    assert log.rotations >= 2
    assert len(rotations_seen) == log.rotations
    import os as _os

    files = [path, path + ".1", path + ".2"]
    assert all(_os.path.exists(f) for f in files)
    assert not _os.path.exists(path + ".3")  # keep=2 bounds the chain
    # every retained file honors the byte bound (one event may overhang
    # the live file before its next write triggers rotation, so allow
    # one line of slack) and holds valid JSONL
    events = []
    for f in files:
        size = _os.path.getsize(f)
        lines = [l for l in open(f) if l.strip()]
        assert lines, f
        assert size <= 256 + len(lines[0]) + 1, (f, size)
        events.append([json.loads(l)["epoch"] for l in lines])
    # the chain reads newest-first: live file, then .1, then .2 — and
    # every retained file holds contiguous ascending epochs
    flat = [e for per_file in reversed(events) for e in per_file]
    assert flat == sorted(flat), flat
    assert flat[-1] == n - 1


def test_jsonl_rotation_counted_by_telemetry(tmp_path):
    """The facade wires `on_rotate` to the cataloged
    `telemetry_sink_rotations_total` counter."""
    tel = Telemetry(
        jsonl_path=str(tmp_path / "e.jsonl"),
        jsonl_max_bytes=200,
        jsonl_keep=1,
    )
    for i in range(30):
        tel.event("phase", epoch=i, phase="train", duration_s=1.0)
    tel.close()
    assert tel.log.rotations >= 1
    assert tel.registry.counter_value(
        "telemetry_sink_rotations_total"
    ) == tel.log.rotations


def test_jsonl_rotation_counted_when_reopen_fails(tmp_path, monkeypatch):
    """A rotation whose renames succeeded but whose live-file reopen
    failed (disk-full/EMFILE) DID happen on disk: it must count in
    `rotations` and fire `on_rotate` — the counter has to agree with
    the on-disk state it explains — while the sink goes dark instead
    of crashing the next emit."""
    import builtins

    path = str(tmp_path / "events.jsonl")
    log = EventLog(ring_size=32, jsonl_path=path, max_bytes=128, keep=2)
    rotations_seen = []
    log.on_rotate = lambda: rotations_seen.append(1)

    real_open = builtins.open
    fail = {"armed": True}

    def flaky_open(file, mode="r", *a, **kw):
        if fail["armed"] and file == path and "a" in mode:
            raise OSError("disk full")
        return real_open(file, mode, *a, **kw)

    monkeypatch.setattr(builtins, "open", flaky_open)
    for i in range(20):  # enough bytes to cross max_bytes and rotate
        log.emit("phase", epoch=i, phase="train", duration_s=1.0)
    monkeypatch.setattr(builtins, "open", real_open)

    import os as _os

    assert _os.path.exists(path + ".1")  # the chain really moved
    assert log.rotations == 1
    assert len(rotations_seen) == 1
    assert log._fh is None  # dark, but emit survived
    assert len(log.records()) == 20  # ring buffer kept everything
    log.close()


def test_jsonl_unbounded_sink_never_rotates(tmp_path):
    log = EventLog(ring_size=16, jsonl_path=str(tmp_path / "e.jsonl"))
    for i in range(50):
        log.emit("phase", epoch=i, phase="train", duration_s=1.0)
    log.close()
    assert log.rotations == 0


def test_jsonl_crash_tail_survives_kill(tmp_path):
    """Satellite: the JSONL sink flushes on every `phase` close, so a
    run killed WITHOUT `close()` keeps everything up to its last
    completed phase. Simulated faithfully: a subprocess emits events
    and dies via os._exit (no interpreter teardown, no atexit, no
    buffered-file flush)."""
    import subprocess
    import sys

    sink = tmp_path / "crash.jsonl"
    script = f"""
import os, sys
sys.path.insert(0, {repr(str(tmp_path.parent))})
from dmosopt_tpu.telemetry import EventLog
log = EventLog(jsonl_path={str(sink)!r})
log.emit("epoch", epoch=0, duration_s=1.0)
log.emit("phase", epoch=1, phase="train", duration_s=0.5)
log.emit("phase", epoch=1, phase="optimize", duration_s=0.25)
os._exit(9)  # killed: no close(), no interpreter shutdown
"""
    import os as _os

    env = dict(_os.environ)
    env["PYTHONPATH"] = _os.pathsep.join(
        p for p in (env.get("PYTHONPATH"),
                    str(_os.path.dirname(_os.path.dirname(__file__))))
        if p
    )
    proc = subprocess.run(
        [sys.executable, "-c", script], env=env,
        capture_output=True, text=True,
    )
    assert proc.returncode == 9, proc.stderr
    events = list(read_jsonl(str(sink)))
    # everything up to the last completed phase survived the kill
    assert [e.kind for e in events] == ["epoch", "phase", "phase"]
    assert events[-1].fields["phase"] == "optimize"
    assert events[-1].epoch == 1


def test_epoch_summary_folds_phase_and_eval_events():
    tel = Telemetry()
    tel.set_epoch(0)
    with tel.phase("train") as ph:
        ph.update(n_train=32, duplicates_removed=2, surrogate="gpr")
    with tel.phase("optimize") as ph:
        ph.update(n_generations=10, gens_per_sec=5.0, termination="hvkn")
    # two eval drains in one epoch merge min/max/sum
    tel.event("phase", phase="eval", duration_s=0.2, n_evals=4,
              eval_min=0.01, eval_max=0.05, eval_sum=0.1)
    tel.event("phase", phase="eval", duration_s=0.3, n_evals=6,
              eval_min=0.005, eval_max=0.08, eval_sum=0.2)
    tel.event("epoch", duration_s=1.25, eval_count=10, save_count=1)
    tel.event("resample", resample_batch=8, resample_duplicates_removed=1)

    s = tel.epoch_summary(0)
    assert set(s["phases"]) == {"train", "optimize", "eval"}
    assert s["n_train"] == 32 and s["surrogate"] == "gpr"
    assert s["n_generations"] == 10 and s["termination"] == "hvkn"
    assert s["wall_s"] == 1.25 and s["resample_batch"] == 8
    ev = s["eval"]
    assert ev["eval_n"] == 10
    assert ev["eval_min"] == 0.005 and ev["eval_max"] == 0.08
    assert ev["eval_mean"] == pytest.approx(0.3 / 10)
    json.dumps(s)


def test_epoch_summary_aggregates_multiproblem_events():
    """A multi-problem epoch emits one train/optimize event per
    problem: counters must sum, ratio fields average, terminations
    union, and gens_per_sec must be recomputed from the totals —
    last-writer-wins paired one problem's throughput with the summed
    durations."""
    tel = Telemetry()
    tel.set_epoch(0)
    tel.event("phase", phase="train", duration_s=1.0, n_train=30,
              surrogate_loss=2.0, surrogate="gpr")
    tel.event("phase", phase="train", duration_s=3.0, n_train=10,
              surrogate_loss=4.0, surrogate="gpr")
    tel.event("phase", phase="optimize", duration_s=2.0, n_generations=10,
              n_evals=100, termination="num_generations")
    tel.event("phase", phase="optimize", duration_s=3.0, n_generations=15,
              n_evals=150, termination="hvkn")
    tel.event("resample", resample_batch=8, resample_duplicates_removed=1)
    tel.event("resample", resample_batch=4, resample_duplicates_removed=2)

    s = tel.epoch_summary(0)
    assert s["phases"]["train"] == pytest.approx(4.0)
    assert s["n_train"] == 40
    assert s["surrogate_loss"] == pytest.approx(3.0)  # mean over problems
    assert s["n_generations"] == 25 and s["n_evals"] == 250
    assert s["gens_per_sec"] == pytest.approx(25 / 5.0)
    assert s["termination"] == "num_generations+hvkn"
    assert s["resample_batch"] == 12
    assert s["resample_duplicates_removed"] == 3


def test_epoch_summary_survives_ring_eviction():
    """An event-heavy epoch (one eval drain per generation in
    evaluation mode) must not evict its own early events from the
    persisted summary: epoch_summary reads the complete per-epoch
    index, not the bounded ring."""
    tel = Telemetry(ring_size=4)
    tel.set_epoch(0)
    with tel.phase("train") as ph:
        ph.update(n_train=32, surrogate="gpr")
    for _ in range(20):  # far beyond the ring capacity
        tel.event("phase", phase="eval", duration_s=0.01, n_evals=1,
                  eval_min=0.01, eval_max=0.01, eval_sum=0.01)
    assert len(tel.log) == 4  # the ring itself stays bounded
    s = tel.epoch_summary(0)
    assert s["n_train"] == 32 and "train" in s["phases"]
    assert s["eval"]["eval_n"] == 20

    # advancing the epoch prunes the old index; summaries for pruned
    # epochs fall back to whatever the ring still holds
    tel.set_epoch(1)
    assert 0 not in tel._events_by_epoch
    assert tel.epoch_summary(0)["eval"]["eval_n"] == 4


def test_optimize_phase_excludes_eval_suspension():
    """Evaluation-mode epochs suspend at `yield` while the driver runs
    objective evaluations; that wall time belongs to the `eval` phase,
    so the `optimize` duration / gens_per_sec must exclude it."""
    import time as _time

    from dmosopt_tpu import moasmo

    rng = np.random.default_rng(3)
    dim = 6
    Xinit = rng.uniform(size=(40, dim)).astype(np.float32)

    def eval_batch(X):
        X = np.asarray(X)
        f1 = X[:, 0]
        g = 1.0 + 9.0 / (dim - 1) * np.sum(X[:, 1:], axis=1)
        return np.stack([f1, g * (1.0 - np.sqrt(f1 / g))], axis=1)

    tel = Telemetry()
    tel.set_epoch(0)
    gen = moasmo.epoch(
        num_generations=4,
        param_names=[f"x{i}" for i in range(dim)],
        objective_names=["f1", "f2"],
        xlb=np.zeros(dim), xub=np.ones(dim),
        pct=0.25, Xinit=Xinit, Yinit=eval_batch(Xinit), C=None,
        pop=16, optimizer_name="nsga2",
        surrogate_method_name=None, local_random=5,
        telemetry=tel,
    )
    sleep_per_round = 0.1
    t_total0 = _time.perf_counter()
    item = next(gen)
    n_rounds = 0
    while True:
        x_gen, _ = item
        _time.sleep(sleep_per_round)  # stand-in for slow objectives
        n_rounds += 1
        try:
            item = gen.send((x_gen, eval_batch(x_gen), None))
        except StopIteration:
            break
    t_total = _time.perf_counter() - t_total0
    (ev,) = [
        e for e in tel.log.records(kind="phase")
        if e.fields.get("phase") == "optimize"
    ]
    # the reported optimize duration may include EA compile/compute but
    # must NOT include the time this driver loop held the generator
    # suspended at `yield` (n_rounds sleeps)
    total_suspended = n_rounds * sleep_per_round
    assert ev.fields["duration_s"] <= t_total - total_suspended + 0.05, (
        ev.fields["duration_s"], t_total, total_suspended,
    )
    assert ev.fields["n_generations"] == 4
    assert ev.fields["gens_per_sec"] == pytest.approx(
        4 / ev.fields["duration_s"], rel=0.01
    )


# ----------------------------------------------------- metric catalog


def test_every_emitted_metric_is_cataloged():
    """The fast-suite arm of `make lint-metrics`: any metric name the
    package emits must be documented in docs/observability.md."""
    import importlib.util
    import pathlib

    tool = (
        pathlib.Path(__file__).resolve().parent.parent
        / "tools" / "lint_metrics.py"
    )
    spec = importlib.util.spec_from_file_location("lint_metrics", tool)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    missing = mod.check()
    assert not missing, f"metrics missing from the catalog: {missing}"
    assert len(mod.emitted_metrics()) > 0  # the scanner still finds emissions


# --------------------------------------------- driver + storage + CLI


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    fp = tmp_path_factory.mktemp("telemetry") / "run.h5"
    dmosopt_tpu.run(_run_params(fp), verbose=False)
    return str(fp)


def test_h5_telemetry_group_written(store):
    from dmosopt_tpu.storage import load_telemetry_from_h5

    summaries = load_telemetry_from_h5(store, "tel_run")
    assert sorted(summaries) == [0, 1]
    s0 = summaries[0]
    # every acceptance phase made it to disk for the first epoch
    assert {"xinit", "train", "optimize", "eval"} <= set(s0["phases"])
    assert s0["n_train"] > 0 and s0["surrogate"] == "gpr"
    assert s0["n_generations"] > 0 and s0["eval"]["eval_n"] > 0


def test_h5_telemetry_survives_restore_cycle(store):
    from dmosopt_tpu.storage import load_telemetry_from_h5

    before = load_telemetry_from_h5(store, "tel_run")
    # resume the archive for two more epochs: pre-restart summaries must
    # survive and the resumed epochs must extend the history
    dmosopt_tpu.run(_run_params(store, n_epochs=2), verbose=False)
    after = load_telemetry_from_h5(store, "tel_run")
    assert set(before) <= set(after)
    assert max(after) > max(before)
    for e in before:
        assert set(before[e]["phases"]) <= set(after[e]["phases"])
    # the resumed run's xinit phase is tagged with its first epoch —
    # an epoch-0 tag would be pruned before any summary could keep it
    first_resumed = min(set(after) - set(before))
    assert "xinit" in after[first_resumed]["phases"]


def test_cli_telemetry_table_and_export(store, tmp_path):
    click = pytest.importorskip("click")
    from click.testing import CliRunner
    from dmosopt_tpu.cli import telemetry as telemetry_cmd

    out = tmp_path / "telemetry.json"
    result = CliRunner().invoke(
        telemetry_cmd,
        ["-p", store, "--opt-id", "tel_run", "--hv", "-o", str(out)],
    )
    assert result.exit_code == 0, result.output
    lines = result.output.splitlines()
    header = lines[0]
    for col in ("epoch", "wall_s", "xinit", "train", "optimize",
                "eval", "gens/s", "hv"):
        assert col in header, header
    # one row per stored epoch, first column is the epoch number
    rows = [l for l in lines[2:] if l and not l.startswith("wrote")]
    assert [int(r.split()[0]) for r in rows] == sorted(
        int(k) for k in json.loads(out.read_text())
    )
    payload = json.loads(out.read_text())
    assert payload["0"]["phases"]["optimize"] > 0
    assert isinstance(payload["0"].get("hypervolume"), float)


def test_cli_telemetry_missing_group_errors(tmp_path):
    pytest.importorskip("click")
    from click.testing import CliRunner
    from dmosopt_tpu.cli import telemetry as telemetry_cmd

    fp = tmp_path / "empty.h5"
    with h5py.File(fp, "w") as h5:
        h5.create_group("other_run")
    result = CliRunner().invoke(
        telemetry_cmd, ["-p", str(fp), "--opt-id", "other_run"]
    )
    assert result.exit_code != 0
    assert "no telemetry group" in result.output


def test_disabled_run_makes_zero_telemetry_calls(tmp_path, monkeypatch):
    """telemetry=False: the driver holds no Telemetry at all — no
    instance is even constructed, so the epoch loop cannot make a
    telemetry call (acceptance criterion: zero calls on the hot path)."""

    def _boom(*a, **k):
        raise AssertionError("telemetry touched in a telemetry=False run")

    monkeypatch.setattr(Telemetry, "__init__", _boom)
    monkeypatch.setattr(MetricsRegistry, "counter_inc", _boom)
    monkeypatch.setattr(MetricsRegistry, "gauge_set", _boom)
    monkeypatch.setattr(MetricsRegistry, "histogram_observe", _boom)
    monkeypatch.setattr(EventLog, "emit", _boom)

    fp = tmp_path / "silent.h5"
    dmosopt_tpu.run(
        _run_params(
            fp, telemetry=False, n_epochs=1, num_generations=5,
            surrogate_method_name=None, n_initial=4, population_size=16,
        ),
        verbose=False,
    )
    with h5py.File(fp, "r") as h5:
        assert "telemetry" not in h5["tel_run"]


def test_registry_label_series_limit_collapses_overflow():
    """Label-cardinality guard: past `series_limit` distinct label sets
    per metric name, emissions collapse into one overflow="true" series
    (totals preserved) and are counted by
    telemetry_series_overflow_total."""
    from dmosopt_tpu.telemetry.registry import MetricsRegistry

    reg = MetricsRegistry(series_limit=4)
    for i in range(10):
        reg.counter_inc("evals_total", 1.0, problem=str(i))
    snap = reg.snapshot()["counters"]
    series = snap["evals_total"]
    assert len(series) == 5  # 4 real + 1 overflow
    assert series["overflow=true"] == 6.0
    assert sum(series.values()) == 10.0
    assert reg.counter_value("telemetry_series_overflow_total") == 6.0

    # existing series keep incrementing in place after the cap
    reg.counter_inc("evals_total", 1.0, problem="0")
    assert reg.counter_value("evals_total", problem="0") == 2.0

    # unlabeled series and other metric names are unaffected
    reg.counter_inc("evals_total")
    assert reg.counter_value("evals_total") == 1.0
    reg.gauge_set("tenants_active", 3.0)
    assert reg.gauge_value("tenants_active") == 3.0


def test_registry_series_limit_applies_per_store_kind():
    from dmosopt_tpu.telemetry.registry import MetricsRegistry

    reg = MetricsRegistry(series_limit=2)
    for i in range(4):
        reg.histogram_observe("phase_duration_seconds", 0.1, phase=str(i))
    snap = reg.snapshot()["histograms"]["phase_duration_seconds"]
    assert len(snap) == 3  # 2 real + overflow
    assert snap["overflow=true"]["count"] == 2


def test_telemetry_label_series_limit_knob():
    from dmosopt_tpu.telemetry import Telemetry

    tel = Telemetry(label_series_limit=1)
    tel.inc("evals_total", problem="a")
    tel.inc("evals_total", problem="b")
    assert tel.registry.counter_value(
        "telemetry_series_overflow_total"
    ) == 1.0
    tel.close()

    # None disables the guard entirely
    tel = Telemetry(label_series_limit=None)
    for i in range(600):
        tel.inc("evals_total", problem=str(i))
    assert tel.registry.counter_value(
        "telemetry_series_overflow_total"
    ) == 0.0
    tel.close()
