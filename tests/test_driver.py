"""End-to-end driver tests through the public `run()` API, mirroring the
reference's ZDT oracle pattern (reference: tests/test_zdt1_nsga2_trs.py)."""

import numpy as np
import pytest

import dmosopt_tpu
from dmosopt_tpu.benchmarks.zdt import zdt1_pareto, distance_to_front


N_DIM = 8


def zdt1_obj(pp):
    """Host-Python objective taking a parameter dict (reference style)."""
    x = np.array([pp[f"x{i}"] for i in range(N_DIM)])
    f1 = x[0]
    g = 1.0 + 9.0 / (N_DIM - 1) * np.sum(x[1:])
    f2 = g * (1.0 - np.sqrt(f1 / g))
    return np.array([f1, f2])


def _space(n=N_DIM):
    return {f"x{i}": [0.0, 1.0] for i in range(n)}


def _base_params(**over):
    params = {
        "opt_id": "test_zdt1",
        "obj_fun": zdt1_obj,
        "objective_names": ["f1", "f2"],
        "space": _space(),
        "problem_parameters": {},
        "n_initial": 8,
        "n_epochs": 3,
        "population_size": 64,
        "num_generations": 40,
        "resample_fraction": 0.5,
        "initial_method": "slh",
        "optimizer_name": "nsga2",
        "surrogate_method_name": "gpr",
        "surrogate_method_kwargs": {"n_starts": 4, "n_iter": 60, "seed": 0},
        "random_seed": 42,
    }
    params.update(over)
    return params


def test_run_zdt1_moasmo_quality():
    best = dmosopt_tpu.run(_base_params(), verbose=False)
    prms, lres = best
    y = np.column_stack([v for _, v in lres])
    d = distance_to_front(y, zdt1_pareto(500))
    # solution-quality oracle in the style of the reference ZDT tests
    assert (d < 0.1).sum() >= 10, (y.shape, float(np.median(d)))


def test_run_no_surrogate():
    params = _base_params(
        surrogate_method_name=None, n_epochs=1, num_generations=5,
        population_size=32,
    )
    best = dmosopt_tpu.run(params, verbose=False)
    prms, lres = best
    assert len(prms) == N_DIM
    assert len(lres) == 2


def test_run_jax_objective_batch():
    import jax.numpy as jnp

    def zdt1_batch(X):
        f1 = X[:, 0]
        g = 1.0 + 9.0 / (X.shape[1] - 1) * jnp.sum(X[:, 1:], axis=1)
        f2 = g * (1.0 - jnp.sqrt(f1 / g))
        return jnp.stack([f1, f2], axis=1)

    # n_epochs=3, not 2: at 2 epochs this seeded run sits exactly on a
    # quality cliff — host-class-dependent XLA fusion (an ulp in the GP
    # fit) decides whether the front lands 3 points at d~0.3 or 26+
    # points under 0.1, which made the oracle fail on some hosts since
    # the seed. One more epoch moves it far from the cliff on every
    # host class measured (31 points < 0.1 vs the >= 5 < 0.2 oracle)
    # while still catching real jax-objective-path regressions.
    params = _base_params(
        obj_fun=zdt1_batch, jax_objective=True, n_epochs=3,
    )
    best = dmosopt_tpu.run(params, verbose=False)
    prms, lres = best
    y = np.column_stack([v for _, v in lres])
    d = distance_to_front(y, zdt1_pareto(500))
    assert (d < 0.2).sum() >= 5


def test_run_optimizer_cycling_and_problem_ids():
    # optimizer cycling: nsga2 on epoch 0, nsga2 again epoch 1 (single name
    # cycles trivially); multi-problem multiplexing via problem_ids
    def mp_obj(mpp):
        out = {}
        for pid, pp in mpp.items():
            scale = 1.0 + 0.1 * pid
            x = np.array([pp[f"x{i}"] for i in range(N_DIM)])
            f1 = scale * x[0]
            g = 1.0 + 9.0 / (N_DIM - 1) * np.sum(x[1:])
            f2 = g * (1.0 - np.sqrt(np.clip(f1 / g, 0, None)))
            out[pid] = np.array([f1, f2])
        return out

    params = _base_params(
        obj_fun=mp_obj,
        problem_ids=set([0, 1]),
        n_epochs=2,
        num_generations=10,
        population_size=32,
        n_initial=4,
    )
    best = dmosopt_tpu.run(params, verbose=False)
    assert set(best.keys()) == {0, 1}


def test_unequal_multiproblem_queues_do_not_deadlock():
    """Per-problem request queues of different lengths (e.g. after resample
    dedupe) must still drain — partial evaluation rounds are allowed."""

    def mp_obj(mpp):
        out = {}
        for pid, pp in mpp.items():
            x = np.array([pp[f"x{i}"] for i in range(N_DIM)])
            out[pid] = np.array([x[0] + 0.01 * pid, 1.0 - x[0]])
        return out

    params = _base_params(
        obj_fun=mp_obj,
        problem_ids=set([0, 1]),
        n_epochs=2,
        num_generations=8,
        population_size=16,
        n_initial=3,
    )
    import dmosopt_tpu.driver as driver

    dopt = driver.dopt_init(params, verbose=False, initialize_strategy=True)
    # force unequal queues before the run
    extra = np.full((N_DIM,), 0.5)
    dopt.optimizer_dict[1].append_request(
        dmosopt_tpu.EvalRequest(extra, None, 0)
    )
    while dopt.epoch_count < dopt.n_epochs:
        dopt.run_epoch()
    # all queues drained, both problems produced results
    for pid in (0, 1):
        assert not dopt.optimizer_dict[pid].has_requests()
        assert dopt.optimizer_dict[pid].x is not None


def test_multiproblem_stats_keys_are_disjoint():
    """get_stats must prefix EVERY problem's keys in a multi-problem run
    — problem 0 included. Unprefixed, problem 0's phase names collided
    with the merged stats dict and silently overwrote each other."""

    def mp_obj(mpp):
        out = {}
        for pid, pp in mpp.items():
            x = np.array([pp[f"x{i}"] for i in range(N_DIM)])
            out[pid] = np.array([x[0] + 0.01 * pid, 1.0 - x[0]])
        return out

    params = _base_params(
        obj_fun=mp_obj,
        problem_ids=set([0, 1]),
        n_epochs=1,
        num_generations=6,
        population_size=16,
        n_initial=3,
    )
    import dmosopt_tpu.driver as driver

    dopt = driver.dopt_init(params, verbose=False, initialize_strategy=True)
    while dopt.epoch_count < dopt.n_epochs:
        dopt.run_epoch()
    stats = dopt.get_stats()
    # both problems' strategies produced the same per-epoch stat names;
    # with deterministic prefixes both survive the merge
    for pid in (0, 1):
        pid_keys = [k for k in stats if k.startswith(f"{pid}_")]
        assert any(k == f"{pid}_model_init" for k in pid_keys), stats.keys()
        assert f"{pid}_eval_sum" in stats, stats.keys()
    # problem stats never land unprefixed in a multi-problem run, so
    # they cannot shadow (or be shadowed by) the driver's own entries
    assert "model_init" not in stats
    assert "eval_sum" not in stats

    # single-problem runs keep the historical unprefixed keys
    single = _base_params(
        n_epochs=1, num_generations=6, population_size=16, n_initial=3,
        opt_id="test_stats_single",
    )
    dopt1 = driver.dopt_init(single, verbose=False, initialize_strategy=True)
    while dopt1.epoch_count < dopt1.n_epochs:
        dopt1.run_epoch()
    stats1 = dopt1.get_stats()
    assert "model_init" in stats1 and "eval_sum" in stats1


def test_time_limit_soft_stop():
    import time as _time

    calls = {"n": 0}

    def slow_obj(pp):
        calls["n"] += 1
        _time.sleep(0.05)
        return zdt1_obj(pp)

    params = _base_params(
        obj_fun=slow_obj, n_epochs=5, num_generations=5, population_size=16,
        surrogate_method_name=None,
    )
    t0 = _time.time()
    dmosopt_tpu.run(params, time_limit=2.0, verbose=False)
    # must return promptly after the limit, not loop forever
    assert _time.time() - t0 < 30.0


def test_run_validates_params():
    with pytest.raises(ValueError):
        dmosopt_tpu.run({"opt_id": "x", "obj_fun": zdt1_obj,
                         "objective_names": ["f1"]}, verbose=False)


def test_run_dotted_flat_space(tmp_path):
    """Dotted parameter names in a flat space survive the whole loop with
    h5 persistence (capability of reference tests/test_zdt1_age_dotname.py)."""
    def obj(pp):
        x = np.asarray([pp[k] for k in sorted(pp)])
        return np.asarray([x[0], 1.0 - x[0] + float((x[1:] ** 2).sum())])

    fp = str(tmp_path / "dotname.h5")
    names = [f"x.{i+1}" for i in range(4)]
    best = dmosopt_tpu.run(_base_params(
        opt_id="dotname",
        obj_fun=obj,
        space={n: [0.0, 1.0] for n in names},
        objective_names=["y1", "y2"],
        population_size=16,
        num_generations=5,
        surrogate_method_kwargs={"n_starts": 2, "n_iter": 20},
        n_initial=2,
        n_epochs=2,
        random_seed=11,
        optimizer_name="age",
        file_path=fp,
        save=True,
    ), verbose=False)
    prms, lres = best
    assert [n for n, _ in prms] == names
    assert np.all(np.isfinite(np.column_stack([v for _, v in lres])))
    # the dotted names must survive in storage verbatim
    from dmosopt_tpu.storage import h5_load_raw

    raw = h5_load_raw(fp, "dotname")
    assert list(raw["parameter_space"].parameter_names) == names


def test_run_nested_parameter_space():
    """nested_parameter_space=True hands the objective a nested dict built
    from dotted paths (capability of reference tests/test_zdt1_age_nested.py)."""
    seen = {}

    def obj(pp):
        # the merged dict must arrive nested: {"a": {"x1","x2"}, "b": {"x3"}}
        seen["keys"] = (sorted(pp), sorted(pp.get("a", {})))
        x = np.asarray([pp["a"]["x1"], pp["a"]["x2"], pp["b"]["x3"]])
        return np.asarray([x[0], 1.0 - x[0] + float((x[1:] ** 2).sum())])

    best = dmosopt_tpu.run(_base_params(
        opt_id="nested_space",
        obj_fun=obj,
        space={"a": {"x1": [0.0, 1.0], "x2": [0.0, 1.0]}, "b": {"x3": [0.0, 1.0]}},
        nested_parameter_space=True,
        objective_names=["y1", "y2"],
        population_size=16,
        num_generations=5,
        surrogate_method_kwargs={"n_starts": 2, "n_iter": 20},
        n_initial=2,
        n_epochs=2,
        random_seed=12,
    ), verbose=False)
    assert seen["keys"] == (["a", "b"], ["x1", "x2"])
    prms, lres = best
    assert np.all(np.isfinite(np.column_stack([v for _, v in lres])))


def test_run_optimize_mean_variance(tmp_path):
    """optimize_mean_variance=True: the optimizer works on the surrogate's
    (mean, variance) output and stored predictions carry 2d columns
    (reference dmosopt.py surrogate_mean_variance path)."""
    fp = str(tmp_path / "meanvar.h5")
    best = dmosopt_tpu.run(_base_params(
        opt_id="meanvar",
        optimize_mean_variance=True,
        population_size=16,
        num_generations=5,
        surrogate_method_kwargs={"n_starts": 2, "n_iter": 20, "seed": 0},
        n_initial=2,
        n_epochs=2,
        random_seed=13,
        file_path=fp,
        save=True,
    ), verbose=False)
    prms, lres = best
    y = np.column_stack([v for _, v in lres])
    assert np.all(np.isfinite(y))
    # persisted predictions carry [means..., variances...] columns, and
    # resampled (epoch>0) evaluations actually have them
    import h5py

    with h5py.File(fp, "r") as f:
        preds = np.asarray(f["meanvar"]["0"]["predictions"])
        epochs = np.asarray(f["meanvar"]["0"]["epochs"])
    n_obj = len(_base_params()["objective_names"])
    assert preds.shape[1] == 2 * n_obj
    assert np.isfinite(preds[epochs > 0]).all()
    assert (epochs > 0).any()


_quota_calls = []


def _quota_sampler(file_path, iteration, evaluated_samples, next_samples,
                   sampler, quota=12, **_):
    """Round-by-round epoch-0 sampler (the reference's
    dynamic_initial_sampling contract, dmosopt.py:1357-1402): request
    4-point batches until `quota` evaluations exist, then stop."""
    _quota_calls.append(iteration)
    if len(evaluated_samples) >= quota:
        return None
    return np.asarray(next_samples)[:4]


def test_dynamic_initial_sampling():
    """The epoch-0 dynamic sampler hook drives extra evaluation rounds
    until it returns None."""
    _quota_calls.clear()
    quota = 18
    best = dmosopt_tpu.run(_base_params(
        opt_id="dyninit",
        dynamic_initial_sampling=f"{__name__}._quota_sampler",
        dynamic_initial_sampling_kwargs={"quota": quota},
        population_size=16,
        num_generations=5,
        surrogate_method_kwargs={"n_starts": 2, "n_iter": 20, "seed": 0},
        n_initial=2,
        n_epochs=2,
        random_seed=14,
    ), verbose=False)
    from dmosopt_tpu.driver import dopt_dict

    strat = dopt_dict["dyninit"].optimizer_dict[0]
    assert len(_quota_calls) >= 2  # at least one extra round ran
    assert strat.x.shape[0] >= quota  # archive holds the quota'd evals
    prms, lres = best
    assert np.all(np.isfinite(np.column_stack([v for _, v in lres])))


def test_run_with_sensitivity_analysis():
    """sensitivity_method_name through run(): surrogate sensitivities map
    to per-dimension distribution indices without disturbing the loop."""
    best = dmosopt_tpu.run(_base_params(
        opt_id="sa_run",
        sensitivity_method_name="dgsm",
        population_size=16, num_generations=5,
        surrogate_method_kwargs={"n_starts": 2, "n_iter": 15, "seed": 0},
        n_initial=2, n_epochs=2, random_seed=3,
    ), verbose=False)
    assert np.all(np.isfinite(np.column_stack([v for _, v in best[1]])))


def test_run_jax_objective_with_constraints():
    """jax_objective=True with constraints: the batched evaluator handles
    the (y, c) tuple protocol and the feasibility path stays live."""
    import jax.numpy as jnp

    def obj_c(X):
        y = jnp.stack(
            [X[:, 0], 1.0 - X[:, 0] + jnp.sum(X[:, 1:] ** 2, axis=1)], axis=1
        )
        return y, X[:, :1] - 0.1  # feasible iff x0 > 0.1

    best = dmosopt_tpu.run(_base_params(
        opt_id="jaxc",
        obj_fun=obj_c,
        jax_objective=True,
        constraint_names=["c1"],
        feasibility_method_name="logreg",
        population_size=16, num_generations=5,
        surrogate_method_kwargs={"n_starts": 2, "n_iter": 15, "seed": 0},
        n_initial=2, n_epochs=2, random_seed=3,
    ), verbose=False)
    from dmosopt_tpu.driver import dopt_dict

    strat = dopt_dict["jaxc"].optimizer_dict[0]
    assert strat.c is not None and strat.c.shape[1] == 1
    assert np.all(np.isfinite(np.column_stack([v for _, v in best[1]])))
