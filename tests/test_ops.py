"""Kernel unit tests with independent numpy oracles.

Oracle style follows reference tests/test_dda.py: re-derive the expected
ranking with a naive implementation and compare.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dmosopt_tpu.ops import (
    crowding_distance,
    duplicate_mask,
    euclidean_distance_metric,
    non_dominated_rank,
    polynomial_mutation,
    remove_worst,
    sbx_crossover,
    sort_mo,
    tournament_selection,
)


# ---------------------------------------------------------------- oracles


def naive_pareto_rank(Y):
    """Straightforward front-peeling: i dominated iff exists j with
    y_j <= y_i componentwise and y_j != y_i."""
    Y = np.asarray(Y)
    n = len(Y)
    rank = np.full(n, -1)
    alive = np.ones(n, dtype=bool)
    k = 0
    while alive.any():
        front = []
        for i in np.where(alive)[0]:
            dominated = False
            for j in np.where(alive)[0]:
                if i == j:
                    continue
                if np.all(Y[j] <= Y[i]) and np.any(Y[j] < Y[i]):
                    dominated = True
                    break
            if not dominated:
                front.append(i)
        for i in front:
            rank[i] = k
            alive[i] = False
        k += 1
    return rank


def naive_crowding(Y):
    Y = np.asarray(Y, dtype=float)
    n, d = Y.shape
    if n == 1:
        return np.array([1.0])
    lb, ub = Y.min(0), Y.max(0)
    span = np.where(ub - lb == 0, 1.0, ub - lb)
    U = (Y - lb) / span
    idx = U.argsort(axis=0)
    US = np.take_along_axis(U, idx, axis=0)
    DS = np.zeros((n, d))
    DS[0], DS[-1] = 1.0, 1.0
    for i in range(1, n - 1):
        DS[i] = US[i + 1] - US[i - 1]
    D = np.zeros(n)
    for i in range(n):
        for j in range(d):
            D[idx[i, j]] += DS[i, j]
    D[np.isnan(D)] = 0.0
    return D


# ------------------------------------------------------------------ tests


@pytest.mark.parametrize("n,d", [(20, 2), (50, 3), (100, 5)])
def test_rank_matches_naive(n, d, rng):
    Y = rng.random((n, d))
    got = np.asarray(non_dominated_rank(jnp.asarray(Y)))
    np.testing.assert_array_equal(got, naive_pareto_rank(Y))


def test_rank_with_duplicates(rng):
    Y = rng.random((10, 3))
    Y = np.vstack([Y, Y[:4]])  # exact duplicates
    got = np.asarray(non_dominated_rank(jnp.asarray(Y)))
    np.testing.assert_array_equal(got, naive_pareto_rank(Y))
    # duplicates land in the same front
    np.testing.assert_array_equal(got[:4], got[10:])


def test_rank_single_front():
    # anti-chain: all on the y = -x line
    t = np.linspace(0, 1, 16)
    Y = np.stack([t, 1 - t], axis=1)
    assert (np.asarray(non_dominated_rank(jnp.asarray(Y))) == 0).all()


def test_rank_chain():
    # total order: each point dominates the next
    t = np.arange(8.0)
    Y = np.stack([t, t], axis=1)
    np.testing.assert_array_equal(
        np.asarray(non_dominated_rank(jnp.asarray(Y))), np.arange(8)
    )


def test_rank_masked(rng):
    Y = rng.random((30, 3))
    mask = np.ones(30, dtype=bool)
    mask[17:] = False
    got = np.asarray(non_dominated_rank(jnp.asarray(Y), mask=jnp.asarray(mask)))
    np.testing.assert_array_equal(got[:17], naive_pareto_rank(Y[:17]))
    assert (got[17:] == 30).all()


def test_biobjective_sweep_matches_matrix_peel(rng):
    """The d == 2 O(N log N) sweep must be BITWISE identical to the
    general matrix peel — duplicates, shared single coordinates, NaN
    rows, infinities, and masks included — so every bi-objective
    optimizer trajectory is unchanged by the routing."""
    from dmosopt_tpu.ops.dominance import _rank_matrix_peel

    for trial in range(25):
        n = int(rng.integers(3, 120))
        Y = rng.random((n, 2)).astype(np.float32)
        if n > 10:
            Y[rng.integers(0, n, 5)] = Y[rng.integers(0, n, 5)]  # dup rows
            Y[rng.integers(0, n, 5), 0] = Y[rng.integers(0, n, 5), 0]  # ties
        if trial % 5 == 1:
            Y[rng.integers(0, n, max(1, n // 8)), 1] = np.nan
        if trial % 7 == 2:
            Y[rng.integers(0, n, max(1, n // 8)), 0] = np.inf
        mask = None
        if trial % 3 == 0:
            mask = jnp.asarray(rng.random(n) > 0.3)
        ref = np.asarray(_rank_matrix_peel(jnp.asarray(Y), mask=mask))
        got = np.asarray(non_dominated_rank(jnp.asarray(Y), mask=mask))
        np.testing.assert_array_equal(got, ref, err_msg=f"trial {trial}")


def test_biobjective_sweep_stop_count_refinement(rng):
    """With stop_count the sweep returns exact ranks beyond the cut
    (instead of the matrix path's n-1 sentinel) — every rank within the
    peeled fronts must still agree exactly, and beyond-cut ranks must
    order strictly after them (the property survival slicing relies on)."""
    from dmosopt_tpu.ops.dominance import _rank_matrix_peel

    Y = jnp.asarray(rng.random((60, 2)).astype(np.float32))
    ref = np.asarray(_rank_matrix_peel(Y, stop_count=20))
    got = np.asarray(non_dominated_rank(Y, stop_count=20))
    peeled = ref < 59  # matrix path: unpeeled rows carry the n-1 sentinel
    np.testing.assert_array_equal(got[peeled], ref[peeled])
    if (~peeled).any():
        assert got[~peeled].min() > ref[peeled].max()


@pytest.mark.parametrize("d", [2, 3, 5])
def test_tiled_rank_bitwise_matches_matrix_peel(d, rng):
    """The tiled sweep must be BITWISE identical to the dense matrix
    peel for every d — duplicates, shared coordinates, NaN rows,
    infinities, masks, and tile sizes that do not divide the population
    included — so rerouting d >= 3 ranking through it changes no
    trajectory."""
    from dmosopt_tpu.ops.dominance import _rank_matrix_peel, _rank_tiled

    for trial in range(20):
        n = int(rng.integers(3, 150))
        Y = rng.random((n, d)).astype(np.float32)
        if n > 10:
            Y[rng.integers(0, n, 5)] = Y[rng.integers(0, n, 5)]  # dup rows
            Y[rng.integers(0, n, 5), 0] = Y[rng.integers(0, n, 5), 0]  # ties
        if trial % 5 == 1:
            Y[rng.integers(0, n, max(1, n // 8)), d - 1] = np.nan
        if trial % 7 == 2:
            Y[rng.integers(0, n, max(1, n // 8)), 0] = np.inf
        mask = None
        if trial % 3 == 0:
            mask = jnp.asarray(rng.random(n) > 0.3)
        tile = int(rng.choice([16, 48, 64, 100, 512]))  # rarely divides n
        ref = np.asarray(_rank_matrix_peel(jnp.asarray(Y), mask=mask))
        got, iters = _rank_tiled(jnp.asarray(Y), mask, tile=tile)
        np.testing.assert_array_equal(
            np.asarray(got), ref, err_msg=f"trial {trial} tile {tile}"
        )
        assert int(iters) >= 0


@pytest.mark.parametrize("d", [3, 5])
def test_rank_routing_matches_peel_general_d(d, rng):
    """The public dispatcher's d >= 3 route (tiled) equals the peel,
    including with masks — the contract every consumer relies on."""
    from dmosopt_tpu.ops.dominance import _rank_matrix_peel

    Y = rng.random((130, d)).astype(np.float32)
    Y[3:7] = Y[20:24]  # duplicates across the array
    mask = jnp.asarray(rng.random(130) > 0.25)
    for m in (None, mask):
        ref = np.asarray(_rank_matrix_peel(jnp.asarray(Y), mask=m))
        got = np.asarray(non_dominated_rank(jnp.asarray(Y), mask=m))
        np.testing.assert_array_equal(got, ref)


def test_tiled_rank_stop_count_refinement(rng):
    """With stop_count the tiled route returns exact ranks beyond the
    cut (the matrix path's n-1 sentinel is one legal answer, exactness
    another) — ranks within the peeled fronts must agree exactly, and
    beyond-cut ranks must order strictly after them (the property
    survival slicing relies on). Mirrors the d == 2 sweep's pin."""
    from dmosopt_tpu.ops.dominance import _rank_matrix_peel

    Y = jnp.asarray(rng.random((90, 4)).astype(np.float32))
    ref = np.asarray(_rank_matrix_peel(Y, stop_count=30))
    got = np.asarray(non_dominated_rank(Y, stop_count=30))
    peeled = ref < 89  # matrix path: unpeeled rows carry the n-1 sentinel
    np.testing.assert_array_equal(got[peeled], ref[peeled])
    if (~peeled).any():
        assert got[~peeled].min() > ref[peeled].max()


def test_tiled_rank_inside_jit(rng):
    """Ranking must stay traceable — every optimizer calls it inside a
    jitted update step."""
    Y = rng.random((64, 3)).astype(np.float32)

    @jax.jit
    def ranked(y):
        return non_dominated_rank(y)

    np.testing.assert_array_equal(
        np.asarray(ranked(jnp.asarray(Y))),
        np.asarray(non_dominated_rank(jnp.asarray(Y))),
    )


def test_rank_telemetry_counters(rng):
    """Eager d >= 3 calls with a telemetry hook attached record the tile
    statistics; detaching the hook stops recording."""
    from dmosopt_tpu.ops import dominance
    from dmosopt_tpu.telemetry import Telemetry

    tel = Telemetry()
    dominance.set_rank_telemetry(tel)
    try:
        non_dominated_rank(jnp.asarray(rng.random((40, 3)), jnp.float32))
    finally:
        dominance.set_rank_telemetry(None)
    reg = tel.registry
    assert reg.counter_value("rank_tile_sweeps_total") >= 1
    assert reg.counter_value("rank_peel_iterations_total") >= 0
    assert "rank_peel_iterations_total" in reg.metric_names()
    assert reg.gauge_value("rank_tile_size") >= 64
    # hook detached: no further recording
    before = reg.counter_value("rank_tile_sweeps_total")
    non_dominated_rank(jnp.asarray(rng.random((40, 3)), jnp.float32))
    assert reg.counter_value("rank_tile_sweeps_total") == before


@pytest.mark.parametrize("n,d", [(2, 2), (17, 2), (40, 4)])
def test_crowding_matches_naive(n, d, rng):
    Y = rng.random((n, d))
    got = np.asarray(crowding_distance(jnp.asarray(Y)))
    np.testing.assert_allclose(got, naive_crowding(Y), rtol=1e-5, atol=1e-6)


def test_crowding_masked_equals_subset(rng):
    Y = rng.random((25, 3))
    mask = np.zeros(25, dtype=bool)
    mask[:18] = True
    got = np.asarray(crowding_distance(jnp.asarray(Y), jnp.asarray(mask)))
    np.testing.assert_allclose(got[:18], naive_crowding(Y[:18]), rtol=1e-5, atol=1e-6)
    assert (got[18:] == 0).all()


def test_euclidean_distance_metric(rng):
    Y = rng.random((12, 3))
    lb, ub = Y.min(0), Y.max(0)
    U = (Y - lb) / (ub - lb)
    expect = np.sqrt((U**2).sum(1))
    got = np.asarray(euclidean_distance_metric(jnp.asarray(Y)))
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


def test_sbx_within_bounds_and_symmetric(rng):
    key = jax.random.PRNGKey(0)
    B, n = 64, 10
    xlb, xub = jnp.zeros(n), jnp.ones(n)
    p1 = jnp.asarray(rng.random((B, n)))
    p2 = jnp.asarray(rng.random((B, n)))
    c1, c2 = sbx_crossover(key, p1, p2, 15.0, xlb, xub)
    assert (c1 >= 0).all() and (c1 <= 1).all()
    # children midpoint equals parents midpoint wherever clipping didn't bite
    c1n, c2n = np.asarray(c1), np.asarray(c2)
    unclipped = (c1n > 0) & (c1n < 1) & (c2n > 0) & (c2n < 1)
    np.testing.assert_allclose(
        (c1n + c2n)[unclipped], np.asarray(p1 + p2)[unclipped], rtol=1e-4, atol=1e-4
    )


def test_sbx_large_di_recovers_parents(rng):
    key = jax.random.PRNGKey(1)
    n = 6
    p1 = jnp.asarray(rng.random((32, n)))
    p2 = jnp.asarray(rng.random((32, n)))
    c1, c2 = sbx_crossover(key, p1, p2, 1e6, jnp.zeros(n), jnp.ones(n))
    # with huge distribution index, beta ~= 1 so children ~= parents
    d = np.minimum(
        np.abs(np.asarray(c1 - p1)).max(), np.abs(np.asarray(c1 - p2)).max()
    )
    assert np.abs(np.asarray(c1 + c2 - p1 - p2)).max() < 1e-3


def test_mutation_within_bounds_and_scale(rng):
    key = jax.random.PRNGKey(2)
    B, n = 256, 8
    parents = jnp.asarray(rng.random((B, n)) * 0.5 + 0.25)
    children = polynomial_mutation(key, parents, 20.0, jnp.zeros(n), jnp.ones(n))
    assert (children >= 0).all() and (children <= 1).all()
    # di=20 keeps perturbations small on average
    assert np.abs(np.asarray(children - parents)).mean() < 0.1


def test_tournament_selection_prefers_best(rng):
    key = jax.random.PRNGKey(3)
    n, pool = 50, 10
    rank = jnp.asarray(np.arange(n))  # identity: index == quality order
    counts = np.zeros(n)
    for i in range(200):
        idx = np.asarray(
            tournament_selection(jax.random.fold_in(key, i), pool, rank)
        )
        assert len(set(idx.tolist())) == pool  # without replacement
        counts[idx] += 1
    # best individual should be picked far more often than median one
    assert counts[0] > counts[25] * 2


def test_sort_mo_orders_by_rank_then_crowding(rng):
    Y = rng.random((40, 2))
    X = rng.random((40, 5))
    xs, ys, rank, (cd,), perm = sort_mo(jnp.asarray(X), jnp.asarray(Y))
    rank = np.asarray(rank)
    assert (np.diff(rank) >= 0).all()
    cd = np.asarray(cd)
    for r in np.unique(rank):
        seg = cd[rank == r]
        assert (np.diff(seg) <= 1e-12).all()  # descending crowding within front


def test_remove_worst_keeps_front(rng):
    Y = rng.random((60, 2))
    X = rng.random((60, 3))
    ranks = naive_pareto_rank(Y)
    xs, ys, rk, perm = remove_worst(jnp.asarray(X), jnp.asarray(Y), 20)
    kept = set(np.asarray(perm).tolist())
    # every front-0 point either kept or displaced only by front-0 points
    front0 = np.where(ranks == 0)[0]
    if len(front0) <= 20:
        assert set(front0.tolist()) <= kept


def test_duplicate_mask(rng):
    X = rng.random((10, 4))
    X = np.vstack([X, X[3:5]])
    got = np.asarray(duplicate_mask(jnp.asarray(X)))
    assert not got[:10].any()
    assert got[10:].all()


def test_duplicate_mask_chunk_invariant(rng):
    """The row-chunked duplicate scan must be bitwise independent of the
    chunk size — including non-divisible chunks, masks, and NaN rows."""
    X = rng.random((53, 4)).astype(np.float32)
    X[11] = X[3]
    X[29] = X[3]
    X[40, 2] = np.nan
    mask = jnp.asarray(rng.random(53) > 0.2)
    for m in (None, mask):
        base = np.asarray(duplicate_mask(jnp.asarray(X), mask=m))
        for chunk in (7, 16, 53, 64):
            got = np.asarray(duplicate_mask(jnp.asarray(X), mask=m, chunk=chunk))
            np.testing.assert_array_equal(got, base, err_msg=f"chunk {chunk}")
    # ground truth on the unmasked case
    unmasked = np.asarray(duplicate_mask(jnp.asarray(X)))
    assert unmasked[11] and unmasked[29] and not unmasked[3]


def test_pairwise_distances_chunk_invariant(rng):
    from dmosopt_tpu.ops import pairwise_distances

    X = rng.random((37, 5)).astype(np.float32)
    Y = rng.random((21, 5)).astype(np.float32)
    base = np.asarray(pairwise_distances(jnp.asarray(X), jnp.asarray(Y)))
    for chunk in (4, 10, 37):
        got = np.asarray(
            pairwise_distances(jnp.asarray(X), jnp.asarray(Y), row_chunk=chunk)
        )
        # per-row dot products; only matmul tiling may vary with chunk
        np.testing.assert_allclose(got, base, rtol=1e-6, atol=1e-7)
    expect = np.sqrt(((X[:, None, :] - Y[None, :, :]) ** 2).sum(-1))
    np.testing.assert_allclose(base, expect, rtol=1e-4, atol=1e-5)


def test_rank_stop_count_prefix_exact(rng):
    """Early-stopped peeling: every front up to the covering cut matches
    the full ranking; leftovers carry the legal sentinel n-1."""
    Y = jnp.asarray(rng.random((300, 2)).astype(np.float32))
    full = np.asarray(non_dominated_rank(Y))
    stopped = np.asarray(non_dominated_rank(Y, stop_count=100))
    n = Y.shape[0]
    kmax = stopped[stopped < n - 1].max()  # last exactly-peeled front
    covered = full <= kmax
    assert covered.sum() >= 100
    assert np.array_equal(full[covered], stopped[covered])
    assert np.all(stopped[~covered] == n - 1)


def test_agemoea_survival_matches_bruteforce_greedy(rng):
    """The incremental two-smallest-distance maintenance in the AGE-MOEA
    survival score must equal the brute-force greedy recomputation."""
    from dmosopt_tpu.optimizers import agemoea as A

    N, d, nf = 48, 3, 30
    y = jnp.asarray(rng.random((N, d)).astype(np.float32))
    mask = jnp.asarray(np.arange(N) < nf)
    ideal = jnp.min(jnp.where(mask[:, None], y, A._INF), axis=0)
    norm, p, crowd = map(np.asarray, A._survival_score(y, mask, ideal))

    # brute-force reference: identical normalization and D, greedy loop
    # recomputes the two smallest distances to the selected set each step
    yf = (np.asarray(y) - np.asarray(ideal)[None]) / norm
    pf = float(p)
    D = np.sum(np.abs(yf[:, None] - yf[None, :]) ** pf, axis=2) ** (1 / pf)
    nn = np.sum(np.abs(yf) ** pf, axis=1) ** (1 / pf)
    D = D / np.where(nn[:, None] == 0, 1.0, nn[:, None])
    maskn = np.asarray(mask)
    extreme = np.asarray(A._find_corner_solutions(
        jnp.asarray(np.asarray(y) - np.asarray(ideal)[None]), mask))
    selected = np.zeros(N, bool)
    selected[extreme] = True
    selected &= maskn
    expect = np.where(selected, np.inf, 0.0)
    n_greedy = maskn.sum() - selected.sum()
    for _ in range(int(n_greedy)):
        remaining = maskn & ~selected
        if not remaining.any():
            break
        Dm = np.where(selected[None, :], D, np.inf)
        two = np.sort(Dm, axis=1)[:, :2]
        val = two[:, 0] + (two[:, 1] if selected.sum() >= 2 else 0.0)
        val = np.where(remaining, val, -np.inf)
        best = int(np.argmax(val))
        expect[best] = val[best]
        selected[best] = True
    expect = np.where(maskn, expect, 0.0)
    np.testing.assert_allclose(crowd, expect, rtol=1e-4, atol=1e-5)


def test_agemoea_survival_column_path_matches_dense(monkeypatch, rng):
    """Above `_DENSE_SURVIVAL_MAX` the AGE-MOEA survival score switches
    to on-demand Minkowski columns (no (N, N) matrix); the two regimes
    must agree to float tolerance — the dense regime stays bitwise
    frozen for trajectory stability, the column regime unlocks 16k+
    fronts."""
    from dmosopt_tpu.optimizers import agemoea as A

    N, d, nf = 64, 3, 40
    y = jnp.asarray(rng.random((N, d)).astype(np.float32))
    mask = jnp.asarray(np.arange(N) < nf)
    ideal = jnp.min(jnp.where(mask[:, None], y, A._INF), axis=0)
    dense = [np.asarray(v) for v in A._survival_score(y, mask, ideal)]
    monkeypatch.setattr(A, "_DENSE_SURVIVAL_MAX", 8)  # force column path
    cols = [np.asarray(v) for v in A._survival_score(y, mask, ideal)]
    for a, b in zip(dense, cols):
        finite = np.isfinite(a)
        np.testing.assert_array_equal(finite, np.isfinite(b))
        np.testing.assert_allclose(a[finite], b[finite], rtol=1e-4, atol=1e-5)


def test_variation_pallas_route_matches_dense(monkeypatch):
    """The Pallas SBX/mutation kernels (ISSUE 19 tentpole residual) run
    over PRECOMPUTED uniforms, so the route only changes how the
    post-uniform math executes. Under jit — how the EA programs always
    run these cores — the Pallas route (interpret mode off-TPU) must be
    bitwise-equal to the frozen dense path; and with DMOSOPT_PALLAS
    unset the CPU backend must keep routing dense."""
    from dmosopt_tpu.ops import variation as V

    monkeypatch.delenv("DMOSOPT_PALLAS", raising=False)
    if jax.default_backend() != "tpu":
        assert V._pallas_route() is False
    monkeypatch.setenv("DMOSOPT_PALLAS", "0")
    assert V._pallas_route() is False
    monkeypatch.setenv("DMOSOPT_PALLAS", "1")
    assert V._pallas_route() is True

    B, n = 16, 5
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(3), 3)
    p1 = jax.random.uniform(k1, (B, n))
    p2 = jax.random.uniform(k2, (B, n))
    xlb, xub = jnp.zeros(n), jnp.ones(n)
    u = jax.random.uniform(k3, (B, n), dtype=p1.dtype)
    di = jnp.broadcast_to(jnp.asarray(15.0, p1.dtype), (n,))

    m_dense = np.asarray(
        jax.jit(V._mutation_core)(u, p1, di, xlb, xub, 0.5)
    )
    m_pallas = np.asarray(V._mutation_pallas(u, p1, di, xlb, xub, 0.5))
    np.testing.assert_array_equal(m_dense, m_pallas)

    c1_d, c2_d = jax.jit(V._sbx_core)(u, p1, p2, di, xlb, xub)
    c1_p, c2_p = V._sbx_pallas(u, p1, p2, di, xlb, xub)
    np.testing.assert_array_equal(np.asarray(c1_d), np.asarray(c1_p))
    np.testing.assert_array_equal(np.asarray(c2_d), np.asarray(c2_p))

    # the public entry points honor the route and keep the same RNG
    # draw (uniforms outside the kernel): same key -> same children
    # within float tolerance across routes, exactly-equal in-bounds
    key = jax.random.PRNGKey(7)
    with_pallas = np.asarray(
        V.polynomial_mutation(key, p1, 20.0, xlb, xub)
    )
    monkeypatch.setenv("DMOSOPT_PALLAS", "0")
    dense = np.asarray(V.polynomial_mutation(key, p1, 20.0, xlb, xub))
    np.testing.assert_allclose(with_pallas, dense, rtol=1e-6, atol=1e-7)
    assert with_pallas.min() >= 0.0 and with_pallas.max() <= 1.0
