"""Sampler + discrepancy tests: LH stratification invariants, symmetric LH
mirror property, GLP lattice structure, discrepancy formulas vs naive oracles."""

import numpy as np
import jax.numpy as jnp
import pytest

from dmosopt_tpu import sampling
from dmosopt_tpu import discrepancy


def naive_cd2(X):
    num, dim = X.shape
    D1 = (13.0 / 12.0) ** dim
    D2 = 0.0
    D3 = 0.0
    for k in range(num):
        DD2 = 1.0
        for j in range(dim):
            DD2 *= 1 + 0.5 * abs(X[k, j] - 0.5) - 0.5 * abs(X[k, j] - 0.5) ** 2
        D2 += DD2
        for j in range(num):
            DD3 = 1.0
            for i in range(dim):
                DD3 *= (
                    1
                    + 0.5 * abs(X[k, i] - 0.5)
                    + 0.5 * abs(X[j, i] - 0.5)
                    - 0.5 * abs(X[k, i] - X[j, i])
                )
            D3 += DD3
    return np.sqrt(D1 - 2.0 * D2 / num + D3 / num**2)


def naive_wd2(X):
    num, dim = X.shape
    D3 = 0.0
    for k in range(num):
        for j in range(num):
            DD3 = 1.0
            for i in range(dim):
                a = abs(X[k, i] - X[j, i])
                DD3 *= 1.5 - a * (1 - a)
            D3 += DD3
    return np.sqrt(-((4.0 / 3.0) ** dim) + D3 / num**2)


@pytest.mark.parametrize("name", ["mc", "lh", "slh", "sobol", "glp"])
def test_samplers_in_unit_box(name):
    fn = getattr(sampling, name)
    x = fn(33, 4, 7)
    assert x.shape == (33, 4)
    assert (x >= 0).all() and (x <= 1).all()


def test_lh_stratification():
    n, s = 50, 3
    x = sampling.lh(n, s, 123)
    # exactly one point per stratum per dimension
    for j in range(s):
        strata = np.floor(x[:, j] * n).astype(int)
        assert sorted(strata.tolist()) == list(range(n))


def test_slh_symmetry():
    n, s = 20, 4
    x = sampling.slh(n, s, 5)
    # rows i and n-1-i are mirrors: x[i] + x[n-1-i] == 1 elementwise
    np.testing.assert_allclose(x + x[::-1], 1.0, atol=1e-12)
    # and it is still an LH
    for j in range(s):
        strata = np.floor(x[:, j] * n).astype(int)
        assert sorted(strata.tolist()) == list(range(n))


def test_slh_odd_n():
    x = sampling.slh(21, 3, 11)
    np.testing.assert_allclose(x + x[::-1], 1.0, atol=1e-12)


def test_sobol_low_discrepancy():
    x = sampling.sobol(64, 2, 3)
    r = sampling.mc(64, 2, 3)
    assert float(discrepancy.CD2(jnp.asarray(x))) < float(
        discrepancy.CD2(jnp.asarray(r))
    )


def test_glp_beats_random_cd2():
    x = sampling.glp(21, 3, 3)
    assert x.shape == (21, 3)
    cds = [
        float(discrepancy.CD2(jnp.asarray(sampling.mc(21, 3, seed))))
        for seed in range(5)
    ]
    assert float(discrepancy.CD2(jnp.asarray(x))) < min(cds)


def test_cd2_matches_naive(rng):
    X = rng.random((17, 3))
    np.testing.assert_allclose(
        float(discrepancy.CD2(jnp.asarray(X))), naive_cd2(X), rtol=1e-5
    )


def test_wd2_matches_naive(rng):
    X = rng.random((11, 4))
    np.testing.assert_allclose(
        float(discrepancy.WD2(jnp.asarray(X))), naive_wd2(X), rtol=1e-5
    )


def test_mindist(rng):
    X = np.array([[0.0, 0.0], [0.3, 0.4], [1.0, 1.0]])
    np.testing.assert_allclose(float(discrepancy.MinDist(jnp.asarray(X))), 0.5)


def test_decorr_reduces_correlation():
    x = sampling.mc(100, 5, 9)
    xd = sampling.decorr(x)
    assert discrepancy.corrscore(xd.T) <= discrepancy.corrscore(x.T) + 1e-9


def test_seed_determinism():
    a = sampling.lh(16, 3, 42)
    b = sampling.lh(16, 3, 42)
    np.testing.assert_array_equal(a, b)
    c = sampling.lh(16, 3, 43)
    assert not np.array_equal(a, c)
