"""graftlint: per-rule fixture snippets (true positive / suppressed /
known-clean), jit-region resolver unit tests, suppression hygiene, the
frozen-registry mutation gate, and the fast-suite arm of ``make lint``
(`test_lint_clean`). Pure ast — no jax import anywhere in the engine,
so these tests run even when the TPU tunnel is down."""

import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.graftlint import load_context, run_lint  # noqa: E402
from tools.graftlint.engine import DEFAULT_TARGETS, frozen_hash  # noqa: E402
from tools.graftlint.registry import all_rules  # noqa: E402


def _mkpkg(tmp_path, files):
    """Write {relpath: source} under tmp_path and return tmp_path."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def _lint(tmp_path, files, rules=None, targets=("pkg",), options=None):
    root = _mkpkg(tmp_path, files)
    return run_lint(root, targets, rules=rules, options=options)


def _live(findings, rule=None):
    return [
        f for f in findings
        if not f.suppressed and (rule is None or f.rule == rule)
    ]


# ------------------------------------------------------ resolver units


def test_resolver_marks_jit_decorated_and_callees_hot(tmp_path):
    root = _mkpkg(tmp_path, {"pkg/a.py": """
        import jax
        import jax.numpy as jnp

        def helper(x):
            return x + 1

        @jax.jit
        def entry(x):
            return helper(x)

        def eager_dispatcher(x):
            return entry(x)
    """})
    ctx = load_context(root, ("pkg",))
    assert ctx.functions["pkg.a.entry"].hot
    assert ctx.functions["pkg.a.helper"].hot  # called from a jit region
    # calling INTO a jit entry does not make the caller hot
    assert not ctx.functions["pkg.a.eager_dispatcher"].hot


def test_resolver_marks_combinator_bodies_and_nested_defs(tmp_path):
    root = _mkpkg(tmp_path, {"pkg/a.py": """
        import jax
        from jax import lax
        from functools import partial

        def scan_body(c, x):
            return c, x

        def eager(xs):
            return lax.scan(scan_body, 0, xs)

        @partial(jax.jit, static_argnames=("n",))
        def entry(x, n=2):
            def inner(y):
                return y * n
            return inner(x)

        def wrapped(x):
            return x

        jitted = jax.jit(wrapped)
    """})
    ctx = load_context(root, ("pkg",))
    assert ctx.functions["pkg.a.scan_body"].hot  # lax.scan body
    assert not ctx.functions["pkg.a.eager"].hot  # the caller stays eager
    assert ctx.functions["pkg.a.entry"].hot  # partial(jax.jit, ...)
    assert ctx.functions["pkg.a.entry.inner"].hot  # nested in a jit region
    assert ctx.functions["pkg.a.wrapped"].hot  # jax.jit(fn) call form


def test_resolver_chases_relative_reexports_in_package_init(tmp_path):
    """`from .impl import kernel` in a package __init__ must resolve
    against the package itself, not one level up."""
    root = _mkpkg(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/sub/__init__.py": "from .impl import kernel\n",
        "pkg/sub/impl.py": """
            def kernel(x):
                return x
        """,
        "pkg/user.py": """
            import jax
            from pkg.sub import kernel

            @jax.jit
            def entry(x):
                return kernel(x)
        """,
    })
    ctx = load_context(root, ("pkg",))
    assert ctx.modules_by_name["pkg.sub"].aliases["kernel"] == (
        "pkg.sub.impl.kernel"
    )
    assert ctx.functions["pkg.sub.impl.kernel"].hot


def test_resolver_chases_package_reexports(tmp_path):
    root = _mkpkg(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/ops/__init__.py": "from pkg.ops.impl import kernel\n",
        "pkg/ops/impl.py": """
            def kernel(x):
                return x
        """,
        "pkg/user.py": """
            import jax
            from pkg.ops import kernel

            @jax.jit
            def entry(x):
                return kernel(x)
        """,
    })
    ctx = load_context(root, ("pkg",))
    assert ctx.functions["pkg.ops.impl.kernel"].hot


def test_resolver_fans_out_dynamic_dispatch_to_overrides(tmp_path):
    root = _mkpkg(tmp_path, {
        "pkg/base.py": """
            import jax

            class Base:
                def __init__(self):
                    self._jit_step = jax.jit(self.step)

                def step(self, x):
                    return x
        """,
        "pkg/sub.py": """
            from pkg.base import Base

            class Sub(Base):
                def step(self, x):
                    return self.helper(x)

                def helper(self, x):
                    return x * 2
        """,
    })
    ctx = load_context(root, ("pkg",))
    assert ctx.functions["pkg.base.Base.step"].hot
    # jax.jit(self.step) on the base class reaches the subclass override
    assert ctx.functions["pkg.sub.Sub.step"].hot
    assert ctx.functions["pkg.sub.Sub.helper"].hot


def test_resolver_traces_through_lambda_bindings(tmp_path):
    """`loss_fn = lambda p: -elbo(p)` then `jax.grad(loss_fn)` inside a
    jit region must mark `elbo` traced (the svgp fit pattern)."""
    root = _mkpkg(tmp_path, {"pkg/a.py": """
        import jax

        def elbo(p):
            return p

        def fit(params):
            loss_fn = lambda p: -elbo(p)

            @jax.jit
            def train(p):  # graftlint: disable=retrace-hazard -- fixture: per-fit closure
                return jax.grad(loss_fn)(p)
            return train(params)
    """})
    ctx = load_context(root, ("pkg",))
    assert ctx.functions["pkg.a.elbo"].hot


def test_resolver_traces_inline_lambdas(tmp_path):
    """An inline lambda handed to a combinator is a traced body: its
    contents and callees must be visible to the hot-path rules."""
    root = _mkpkg(tmp_path, {"pkg/a.py": """
        import jax
        from jax import lax

        def helper(row):
            print("host io")
            return row

        def eager(X):
            a = lax.map(lambda row: helper(row), X)
            b = lax.cond(X.sum() > 0, lambda: X.sum(), lambda: 0.0)
            return a, b
    """})
    ctx = load_context(root, ("pkg",))
    assert ctx.functions["pkg.a.helper"].hot
    assert not ctx.functions["pkg.a.eager"].hot
    findings = run_lint(root, ("pkg",), rules=["hot-path-purity"])
    assert any(
        f.qualname == "pkg.a.helper" and "print" in f.message
        for f in _live(findings)
    )


def test_nonexistent_target_is_a_usage_error(tmp_path):
    import pytest

    _mkpkg(tmp_path, {"pkg/a.py": "x = 1\n"})
    with pytest.raises(ValueError, match="does not exist"):
        run_lint(tmp_path, ("pkg/typo.py",))


def test_overlapping_targets_do_not_duplicate_findings(tmp_path):
    files = {"pkg/a.py": """
        import jax

        @jax.jit
        def bad(x):
            print(x)
            return x
    """}
    once = _lint(tmp_path, files, rules=["hot-path-purity"])
    twice = run_lint(tmp_path, ("pkg", "pkg/a.py"), rules=["hot-path-purity"])
    assert len(_live(once)) == len(_live(twice)) == 1


def test_resolver_shard_map_and_defvjp(tmp_path):
    root = _mkpkg(tmp_path, {"pkg/a.py": """
        import jax
        from jax.experimental.shard_map import shard_map

        def smap_body(x):
            return x

        def build(mesh, specs):
            return shard_map(smap_body, mesh, in_specs=specs, out_specs=specs)

        @jax.custom_vjp
        def op(x):
            return x

        def op_fwd(x):
            return x, None

        def op_bwd(res, g):
            return (g,)

        op.defvjp(op_fwd, op_bwd)
    """})
    ctx = load_context(root, ("pkg",))
    assert ctx.functions["pkg.a.smap_body"].hot
    assert ctx.functions["pkg.a.op"].hot
    assert ctx.functions["pkg.a.op_fwd"].hot
    assert ctx.functions["pkg.a.op_bwd"].hot


# --------------------------------------------------- rule: hot-path-purity

_HOTPATH_VARIANTS = """
    import jax
    import numpy as np
    import time

    @jax.jit
    def bad(tel, x):
        print("gen", x)
        tel.inc("my_counter_total")
        t0 = time.perf_counter()
        y = np.asarray(x)
        return x.item() + t0

    @jax.jit
    def suppressed(tel, x):
        tel.inc("my_counter_total")  # graftlint: disable=hot-path-purity -- fixture: guarded eager-only emission
        return x

    def clean_eager(tel, x):
        print("eager is fine")
        tel.inc("my_counter_total")
        return np.asarray(x).item()
"""


def test_hot_path_purity_fixture(tmp_path):
    findings = _lint(
        tmp_path, {"pkg/a.py": _HOTPATH_VARIANTS}, rules=["hot-path-purity"]
    )
    live = _live(findings, "hot-path-purity")
    msgs = "\n".join(f.message for f in live)
    assert len(live) == 5, msgs  # print, .inc, clock, np.asarray, .item
    assert all(f.qualname == "pkg.a.bad" for f in live)
    assert [f for f in findings if f.suppressed], "suppressed variant fires"
    assert not any(f.qualname == "pkg.a.clean_eager" for f in live)


# -------------------------------------------------- rule: dtype-discipline


def test_dtype_discipline_fixture(tmp_path):
    findings = _lint(tmp_path, {"pkg/a.py": """
        import json
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def bad(x):
            return x.astype(jnp.float64)

        def bad_alloc(n):
            return jnp.zeros((n,), dtype=np.float64)

        def bad_dump(d):
            return json.dumps(d)

        def clean_host(d):
            arr = np.asarray(d, dtype=np.float64)  # host path: fine
            return json.dumps({"x": 1}, default=str)

        @jax.jit
        def suppressed(x):
            return x.astype(jnp.float64)  # graftlint: disable=dtype-discipline -- fixture: deliberate x64 path
    """}, rules=["dtype-discipline"])
    live = _live(findings, "dtype-discipline")
    quals = sorted(f.qualname for f in live)
    assert quals == ["pkg.a.bad", "pkg.a.bad_alloc", "pkg.a.bad_dump"], quals
    assert [f for f in findings if f.suppressed]


def test_dtype_discipline_bare_name_float64(tmp_path):
    """`from numpy import float64` used by bare name on a device path
    is the same r03 class as np.float64; a local merely NAMED float64
    is not flagged."""
    findings = _lint(tmp_path, {"pkg/a.py": """
        import jax
        import jax.numpy as jnp
        from numpy import float64

        @jax.jit
        def bad(x, n):
            return jnp.zeros((n,), dtype=float64) + x

        @jax.jit
        def clean(x):
            float64 = x * 2  # a local, not the dtype
            return float64
    """}, rules=["dtype-discipline"])
    live = _live(findings, "dtype-discipline")
    assert len(live) == 1 and live[0].qualname == "pkg.a.bad", [
        (f.qualname, f.message) for f in live
    ]


def test_class_scope_statements_are_scanned(tmp_path):
    """Class bodies execute in the enclosing scope: a class-scope
    `jax.jit(fn)` registers the entry, a class-scope bare json.dumps is
    the r03 shape."""
    findings = _lint(tmp_path, {"pkg/a.py": """
        import json
        import jax
        import numpy as np

        def kern(x):
            print(x)
            return x

        class Holder:
            step = jax.jit(kern)
            BANNER = json.dumps({"v": np.float64(1.0)})
    """})
    live = _live(findings)
    assert any(
        f.rule == "hot-path-purity" and f.qualname == "pkg.a.kern"
        for f in live
    ), [f.format() for f in live]
    assert any(f.rule == "dtype-discipline" for f in live)


def test_dtype_discipline_module_level(tmp_path):
    """The literal BENCH_r03 shape: module-scope bare json.dumps of a
    numpy payload, and a module-scope f64 device allocation."""
    findings = _lint(tmp_path, {"pkg/a.py": """
        import json
        import jax.numpy as jnp
        import numpy as np

        BANNER = json.dumps({"v": np.float64(1.0)})
        GRID = jnp.zeros((4,), dtype=np.float64)
    """}, rules=["dtype-discipline"])
    live = _live(findings, "dtype-discipline")
    assert len(live) == 2, [f.message for f in live]
    assert all(f.qualname.endswith("<module>") for f in live)


# --------------------------------------------------- rule: retrace-hazard


def test_retrace_hazard_fixture(tmp_path):
    findings = _lint(tmp_path, {"pkg/a.py": """
        import jax

        @jax.jit
        def clean_module_level(x):
            return x

        def loops(fns, xs):
            out = []
            for f in fns:
                jf = jax.jit(f)
                out.append(jf(xs))
            return out

        def loop_def(xs):
            for _ in range(3):
                @jax.jit
                def body(x):
                    return x
                xs = body(xs)
            return xs

        def lam(x):
            return jax.jit(lambda y: y + 1)(x)

        def closure_capture(scale):
            @jax.jit
            def inner(x):
                return x * scale
            return inner

        def suppressed_closure(scale):
            @jax.jit
            def inner(x):  # graftlint: disable=retrace-hazard -- fixture: built once per config, reused
                return x * scale
            return inner

        @jax.jit
        def clean_nested_noncapture(x):
            def inner(y):
                return y + 1
            return inner(x)
    """}, rules=["retrace-hazard"])
    live = _live(findings, "retrace-hazard")
    by_qual = {}
    for f in live:
        by_qual.setdefault(f.qualname, []).append(f.message)
    assert "pkg.a.loops" in by_qual  # jit() in loop
    assert "pkg.a.loop_def" in by_qual  # @jit def in loop
    assert "pkg.a.lam" in by_qual  # jit(lambda)
    assert "pkg.a.closure_capture.inner" in by_qual  # capture
    assert "scale" in by_qual["pkg.a.closure_capture.inner"][0]
    assert "pkg.a.clean_module_level" not in by_qual
    assert "pkg.a.clean_nested_noncapture.inner" not in by_qual
    assert [f for f in findings if f.suppressed]


def test_retrace_hazard_nested_jit_without_captures_still_fires(tmp_path):
    """jit's cache is identity-keyed: a capture-free nested jit def is
    a fresh callable (full retrace) per outer call; a module-global
    reference is stable state, not an 'enclosing local' capture."""
    findings = _lint(tmp_path, {"pkg/a.py": """
        import jax

        EPS = 1e-9

        def build():
            @jax.jit
            def inner(x):
                return x + EPS
            return inner
    """}, rules=["retrace-hazard"])
    live = _live(findings, "retrace-hazard")
    assert len(live) == 1, [f.message for f in live]
    assert "hoist" in live[0].message
    assert "EPS" not in live[0].message  # module global, not a capture


def test_retrace_hazard_mutable_static_default(tmp_path):
    findings = _lint(tmp_path, {"pkg/a.py": """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("opts",))
        def bad(x, opts={}):
            return x

        @partial(jax.jit, static_argnames=("n",))
        def clean(x, n=4):
            return x
    """}, rules=["retrace-hazard"])
    live = _live(findings, "retrace-hazard")
    assert len(live) == 1 and "opts" in live[0].message


# ------------------------------------------------- rule: frozen-path-guard

_FROZEN_SRC = {"pkg/a.py": """
    def frozen_fn(x):
        '''docstring does not count.'''
        return x + 1
"""}


def _frozen_registry(root):
    ctx = load_context(root, ("pkg",))
    return {
        "pkg.a.frozen_fn": {
            "sha256": frozen_hash(ctx.functions["pkg.a.frozen_fn"].node),
            "reason": "fixture", "pinned_by": "this test",
        },
    }


def test_frozen_guard_passes_on_unchanged_source(tmp_path):
    root = _mkpkg(tmp_path, _FROZEN_SRC)
    reg = _frozen_registry(root)
    findings = run_lint(
        root, ("pkg",), rules=["frozen-path-guard"],
        options={"frozen_registry": reg},
    )
    assert not _live(findings)


def test_frozen_guard_ignores_comment_and_docstring_churn(tmp_path):
    root = _mkpkg(tmp_path, _FROZEN_SRC)
    reg = _frozen_registry(root)
    (root / "pkg/a.py").write_text(textwrap.dedent("""
        # a new comment
        def frozen_fn(x):
            '''Rewritten docstring.'''
            # another comment
            return x + 1
    """))
    findings = run_lint(
        root, ("pkg",), rules=["frozen-path-guard"],
        options={"frozen_registry": reg},
    )
    assert not _live(findings)


def test_frozen_guard_fires_on_code_change_and_rename(tmp_path):
    root = _mkpkg(tmp_path, _FROZEN_SRC)
    reg = _frozen_registry(root)
    (root / "pkg/a.py").write_text("def frozen_fn(x):\n    return x + 2\n")
    findings = run_lint(
        root, ("pkg",), rules=["frozen-path-guard"],
        options={"frozen_registry": reg},
    )
    live = _live(findings, "frozen-path-guard")
    assert len(live) == 1 and "changed" in live[0].message
    # rename: the registered name disappears
    (root / "pkg/a.py").write_text("def renamed(x):\n    return x + 1\n")
    findings = run_lint(
        root, ("pkg",), rules=["frozen-path-guard"],
        options={"frozen_registry": reg},
    )
    live = _live(findings, "frozen-path-guard")
    assert len(live) == 1 and "not found" in live[0].message


def test_frozen_guard_real_registry_mutation_turns_lint_red(tmp_path):
    """The acceptance gate: mutate a registered frozen function of the
    REAL package (in a copy) without bumping the registry -> red."""
    dst = tmp_path / "dmosopt_tpu" / "ops"
    dst.mkdir(parents=True)
    src = (REPO / "dmosopt_tpu" / "ops" / "dominance.py").read_text()
    # a one-token change inside _rank_matrix_peel's body: the kind of
    # "harmless" edit the dtlz7 bisection proved is a trajectory break
    needle = "front = jnp.where(jnp.any(front), front, alive)"
    assert needle in src
    (dst / "dominance.py").write_text(
        src.replace(needle, "front = jnp.where(jnp.any(front), alive, front)")
    )
    findings = run_lint(
        tmp_path, ("dmosopt_tpu",), rules=["frozen-path-guard"]
    )
    live = _live(findings, "frozen-path-guard")
    assert any("_rank_matrix_peel" in f.message for f in live), [
        f.message for f in live
    ]
    # the untouched frozen function in the same module stays green
    assert not any("_rank_biobjective_sweep" in f.message for f in live)


# ------------------------------------------------- rule: metrics-catalog


def test_metrics_catalog_fixture(tmp_path):
    files = {
        "docs/observability.md": "Catalog: `documented_total` is here.\n",
        "dmosopt_tpu/a.py": """
            def emit(tel):
                tel.inc("documented_total")
                tel.gauge("undocumented_gauge", 1.0)
        """,
    }
    findings = _lint(
        tmp_path, files, rules=["metrics-catalog"], targets=("dmosopt_tpu",)
    )
    live = _live(findings, "metrics-catalog")
    assert len(live) == 1 and "undocumented_gauge" in live[0].message


def test_metrics_catalog_scans_span_names(tmp_path):
    """ISSUE 9 extension: span names opened via `.span(` /
    `.record_span(` / `span_scope(tel, ...)` are held to the same
    catalog — an undocumented span is a red finding."""
    files = {
        "docs/observability.md": (
            "Spans: `epoch` and `gp_fit` are cataloged.\n"
        ),
        "dmosopt_tpu/a.py": """
            from dmosopt_tpu.telemetry import span_scope

            def traced(tel, tracer):
                with tel.span("epoch"):
                    pass
                with span_scope(tel, "gp_fit"):
                    pass
                with tel.span("mystery_span"):
                    pass
                tracer.record_span("orphan_span", 0.0, 1.0)
                with span_scope(tel, "helper_orphan"):
                    pass
        """,
    }
    findings = _lint(
        tmp_path, files, rules=["metrics-catalog"], targets=("dmosopt_tpu",)
    )
    live = _live(findings, "metrics-catalog")
    missing = {
        name
        for f in live
        for name in ("mystery_span", "orphan_span", "helper_orphan")
        if name in f.message
    }
    assert missing == {"mystery_span", "orphan_span", "helper_orphan"}
    assert len(live) == 3, [f.message for f in live]
    assert all("span" in f.message for f in live)


def test_metrics_catalog_scans_health_rules(tmp_path):
    """ISSUE 14 extension (red-path fixture): a `HealthRule(...)` whose
    metric expression references a counter/gauge absent from the
    catalog turns lint red; cataloged references and `introspect:`
    paths (not registry metrics) stay green."""
    files = {
        "docs/observability.md": (
            "Catalog: `documented_total` and `documented_gauge`.\n"
        ),
        "dmosopt_tpu/rules.py": """
            from dmosopt_tpu.telemetry.health import HealthRule

            RULES = [
                HealthRule(
                    name="green_counter",
                    metric="counter:documented_total",
                    threshold=1.0,
                ),
                HealthRule("green_gauge", "gauge:documented_gauge", 0.5),
                HealthRule(
                    name="red_rule",
                    metric="counter:phantom_metric_total",
                    threshold=1.0,
                ),
                HealthRule("red_positional", "gauge:phantom_gauge", 2.0),
                HealthRule(
                    name="introspect_exempt",
                    metric="introspect:writer.failed",
                    threshold=1.0,
                ),
            ]
        """,
    }
    findings = _lint(
        tmp_path, files, rules=["metrics-catalog"], targets=("dmosopt_tpu",)
    )
    live = _live(findings, "metrics-catalog")
    assert len(live) == 2, [f.message for f in live]
    flagged = {
        name
        for f in live
        for name in ("phantom_metric_total", "phantom_gauge")
        if name in f.message
    }
    assert flagged == {"phantom_metric_total", "phantom_gauge"}
    assert all("health rule" in f.message for f in live)


# ------------------------------------------------- suppression hygiene


def test_suppression_requires_justification_and_use(tmp_path):
    findings = _lint(tmp_path, options={"check_unused": True}, files={"pkg/a.py": """
        import jax

        @jax.jit
        def f(tel, x):
            print(x)  # graftlint: disable=hot-path-purity
            return x

        def g(x):
            return x  # graftlint: disable=hot-path-purity -- nothing fires here

        def h(x):
            return x  # graftlint: disable=no-such-rule -- bogus rule name
    """})
    hyg = _live(findings, "suppression-hygiene")
    assert any("lacks a justification" in f.message for f in hyg)
    assert any("unused suppression" in f.message for f in hyg)
    assert any("unknown rule" in f.message for f in hyg)
    # the bare directive still suppresses (hygiene flags it separately)
    assert not _live(findings, "hot-path-purity")


def test_suppression_directive_in_string_literal_is_inert(tmp_path):
    """Directive-shaped text inside a docstring/string (e.g. docs of
    the syntax itself) is neither a suppression nor 'unused'."""
    findings = _lint(tmp_path, options={"check_unused": True}, files={
        "pkg/a.py": '''
            """Write `# graftlint: disable=hot-path-purity -- why` inline."""
            import jax

            SYNTAX = "# graftlint: disable=retrace-hazard -- nope"

            @jax.jit
            def f(tel, x):
                print(x)
                return x
        ''',
    })
    assert not _live(findings, "suppression-hygiene"), [
        f.message for f in findings
    ]
    # and the real violation is NOT suppressed by the string on line 5
    assert _live(findings, "hot-path-purity")


def test_multirule_suppression_reports_stale_half(tmp_path):
    findings = _lint(tmp_path, options={"check_unused": True}, files={
        "pkg/a.py": """
            import jax

            @jax.jit
            def f(tel, x):
                print(x)  # graftlint: disable=hot-path-purity,retrace-hazard -- only the first ever fires
                return x
    """})
    assert not _live(findings, "hot-path-purity")
    hyg = _live(findings, "suppression-hygiene")
    assert len(hyg) == 1 and "retrace-hazard" in hyg[0].message, [
        f.message for f in hyg
    ]
    assert "hot-path-purity" not in hyg[0].message


def test_target_outside_repo_root_is_a_usage_error(tmp_path):
    import pytest

    _mkpkg(tmp_path, {"pkg/a.py": "x = 1\n"})
    with pytest.raises(ValueError, match="outside the repo root"):
        run_lint(tmp_path, ("/etc/passwd",))


def test_partial_target_run_has_no_spurious_hygiene():
    """Linting a subdirectory (the documented `--select`/path workflow)
    must not report the full-run suppressions as unused: hot marks from
    callers outside the target set are missing there, so the unused
    check only runs over the default target set."""
    findings = run_lint(REPO, ("dmosopt_tpu/ops",))
    live = _live(findings)
    assert not live, "\n".join(f.format() for f in live)


# ------------------------------------------------------- the repo gate


def test_lint_clean():
    """The fast-suite arm of ``make lint``: zero unsuppressed findings
    across dmosopt_tpu/ + bench.py + __graft_entry__.py, and every
    suppression carries a rule name and justification."""
    findings = run_lint(REPO, DEFAULT_TARGETS)
    live = _live(findings)
    assert not live, "\n".join(f.format() for f in live)
    suppressed = [f for f in findings if f.suppressed]
    assert suppressed, "the seeded deliberate exceptions should be visible"
    for f in suppressed:
        assert f.justification, f.format()


def test_rule_catalog_complete():
    """Exactly the shipped rule set, each with a description and the
    incident it encodes (docs/static-analysis.md mirrors this)."""
    rules = {r.name: r for r in all_rules(None)}
    assert set(rules) == {
        "hot-path-purity", "frozen-path-guard", "dtype-discipline",
        "retrace-hazard", "metrics-catalog",
        # the concurrency & state-integrity suite (ISSUE 11)
        "shared-state-guard", "lock-discipline", "checkpoint-schema",
        "resource-lifecycle",
    }
    for r in rules.values():
        assert r.description and r.incident


def test_lint_metrics_alias_delegates():
    """`make lint-metrics` keeps working through the alias module."""
    import importlib.util

    tool = REPO / "tools" / "lint_metrics.py"
    spec = importlib.util.spec_from_file_location("lint_metrics_alias", tool)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.check() == []
    assert len(mod.emitted_metrics()) > 0


# --------------------------------------------------- --bump-frozen helper


def _write_sandbox_registry(root, reg):
    lines = ["FROZEN = {"]
    for name, entry in reg.items():
        lines.append(f'    "{name}": {{')
        for k, v in entry.items():
            lines.append(f'        "{k}": {v!r},')
        lines.append("    },")
    lines.append("}")
    path = root / "frozen_registry.py"
    path.write_text("\n".join(lines) + "\n")
    return path


def _load_registry_file(path):
    ns = {}
    exec(path.read_text(), ns)
    return ns["FROZEN"]


def test_bump_frozen_makes_red_lint_green_again(tmp_path):
    """The ISSUE-8 loop: mutate a frozen function (lint red), run the
    bump helper, lint is green against the rewritten registry — with
    reason/pinned_by text untouched."""
    from tools.graftlint.bump import bump_frozen

    root = _mkpkg(tmp_path, _FROZEN_SRC)
    reg_path = _write_sandbox_registry(root, _frozen_registry(root))

    (root / "pkg/a.py").write_text("def frozen_fn(x):\n    return x + 2\n")
    red = run_lint(
        root, ("pkg",), rules=["frozen-path-guard"],
        options={"frozen_registry": _load_registry_file(reg_path)},
    )
    assert _live(red, "frozen-path-guard")

    changed = bump_frozen(
        root, ("pkg",), ["all"], registry_path=reg_path
    )
    assert list(changed) == ["pkg.a.frozen_fn"]
    old, new = changed["pkg.a.frozen_fn"]
    assert old != new and len(new) == 64

    bumped = _load_registry_file(reg_path)
    assert bumped["pkg.a.frozen_fn"]["reason"] == "fixture"
    assert bumped["pkg.a.frozen_fn"]["pinned_by"] == "this test"
    green = run_lint(
        root, ("pkg",), rules=["frozen-path-guard"],
        options={"frozen_registry": bumped},
    )
    assert not _live(green)


def test_bump_frozen_noop_and_unknown_names(tmp_path):
    import pytest

    from tools.graftlint.bump import bump_frozen

    root = _mkpkg(tmp_path, _FROZEN_SRC)
    reg_path = _write_sandbox_registry(root, _frozen_registry(root))
    before = reg_path.read_text()
    assert bump_frozen(root, ("pkg",), ["all"], registry_path=reg_path) == {}
    assert reg_path.read_text() == before  # in-sync bump rewrites nothing
    with pytest.raises(KeyError, match="not in the frozen registry"):
        bump_frozen(root, ("pkg",), ["pkg.a.missing"], registry_path=reg_path)


def test_bump_frozen_real_registry_is_in_sync(tmp_path):
    """The shipped registry matches the shipped source: a bump against a
    COPY of the real registry is a no-op (`make lint` is green and the
    helper agrees). Catches a drifted hash landing without its bump."""
    import shutil

    from tools.graftlint.bump import DEFAULT_REGISTRY, bump_frozen

    copy = tmp_path / "frozen_registry.py"
    shutil.copy(DEFAULT_REGISTRY, copy)
    changed = bump_frozen(
        REPO, DEFAULT_TARGETS, ["all"], registry_path=copy
    )
    assert changed == {}, f"registry out of sync with source: {changed}"


def test_bump_frozen_cli(tmp_path):
    """CLI surface: --registry-file is honored end to end. The CLI
    resolves lint targets against the real repo root, so point it at a
    sandbox registry naming a function absent from those targets — the
    usage-error exit proves the file was read and the names resolved."""
    import os
    import shutil
    import subprocess
    import sys as _sys

    root = _mkpkg(tmp_path, _FROZEN_SRC)
    reg_path = _write_sandbox_registry(root, _frozen_registry(root))
    shutil.copy(reg_path, reg_path.parent / "copy.py")
    proc = subprocess.run(
        [_sys.executable, "-m", "tools.graftlint", "--bump-frozen", "all",
         "--registry-file", str(reg_path.parent / "copy.py")],
        capture_output=True, text=True, cwd=str(REPO),
        env={**os.environ, "PYTHONPATH": str(REPO)},
    )
    assert proc.returncode == 2
    assert "not found in lint targets" in proc.stderr


def test_bump_frozen_missing_sha_never_rewrites_neighbor(tmp_path):
    """An entry missing its sha256 line must error, NOT cross the entry
    boundary and rewrite the next entry's hash."""
    import pytest

    from tools.graftlint.bump import bump_frozen

    root = _mkpkg(tmp_path, {"pkg/a.py": """
        def frozen_fn(x):
            return x + 1

        def other_fn(x):
            return x - 1
    """})
    ctx = load_context(root, ("pkg",))
    other_hash = frozen_hash(ctx.functions["pkg.a.other_fn"].node)
    reg_path = root / "frozen_registry.py"
    reg_path.write_text(
        "FROZEN = {\n"
        '    "pkg.a.frozen_fn": {\n'
        '        "reason": "no sha line here",\n'
        "    },\n"
        '    "pkg.a.other_fn": {\n'
        f'        "sha256": "{"0" * 64}",\n'
        '        "reason": "stale on purpose",\n'
        "    },\n"
        "}\n"
    )
    with pytest.raises(KeyError, match="no sha256 line"):
        bump_frozen(
            root, ("pkg",), ["pkg.a.frozen_fn"], registry_path=reg_path
        )
    assert "0" * 64 in reg_path.read_text()  # neighbor untouched

    # bumping the neighbor itself still works inside its own block
    changed = bump_frozen(
        root, ("pkg",), ["pkg.a.other_fn"], registry_path=reg_path
    )
    assert changed["pkg.a.other_fn"] == ("0" * 64, other_hash)


def test_bump_frozen_brace_in_reason_string(tmp_path):
    """Entry spans come from the AST: braces inside reason strings must
    not skew the boundary (a text-level brace scan truncated the entry
    at 'fig 3}' and missed its sha256 line)."""
    from tools.graftlint.bump import bump_frozen

    root = _mkpkg(tmp_path, _FROZEN_SRC)
    reg = _frozen_registry(root)
    reg["pkg.a.frozen_fn"]["reason"] = "re-baselined, see fig 3} {open"
    reg["pkg.a.frozen_fn"] = dict(
        reason=reg["pkg.a.frozen_fn"]["reason"],
        sha256=reg["pkg.a.frozen_fn"]["sha256"],  # sha AFTER the reason
        pinned_by="this test",
    )
    reg_path = _write_sandbox_registry(root, reg)
    assert bump_frozen(root, ("pkg",), ["all"], registry_path=reg_path) == {}
    (root / "pkg/a.py").write_text("def frozen_fn(x):\n    return x + 9\n")
    changed = bump_frozen(root, ("pkg",), ["all"], registry_path=reg_path)
    assert list(changed) == ["pkg.a.frozen_fn"]
