"""MO-ASMO epoch engine tests (reference semantics: dmosopt/MOASMO.py)."""

import numpy as np
import pytest

from dmosopt_tpu import moasmo
from dmosopt_tpu.benchmarks.zdt import zdt1, zdt1_pareto, distance_to_front

PARAM_NAMES = [f"x{i}" for i in range(6)]
XLB = np.zeros(6)
XUB = np.ones(6)


def _eval_zdt1(x):
    return np.asarray(zdt1(np.atleast_2d(np.asarray(x, dtype=np.float32))))


def test_xinit_shapes_and_bounds():
    x = moasmo.xinit(5, PARAM_NAMES, XLB, XUB, method="slh", local_random=42)
    assert x.shape == (30, 6)
    assert np.all(x >= XLB) and np.all(x <= XUB)
    # nPrevious trims the head of the design
    x2 = moasmo.xinit(5, PARAM_NAMES, XLB, XUB, nPrevious=10, method="slh",
                      local_random=42)
    assert x2.shape == (20, 6)
    # exhausted budget -> None
    assert moasmo.xinit(5, PARAM_NAMES, XLB, XUB, nPrevious=30) is None


def test_xinit_dict_method():
    vals = {k: np.full(4, 0.5) for k in PARAM_NAMES}
    x = moasmo.xinit(5, PARAM_NAMES, XLB, XUB, method=vals)
    assert x.shape == (4, 6)
    bad = {k: np.full(4, 2.0) for k in PARAM_NAMES}
    with pytest.raises(ValueError):
        moasmo.xinit(5, PARAM_NAMES, XLB, XUB, method=bad)


def test_get_duplicates_semantics():
    X = np.array([[0.0, 0.0], [1.0, 1.0], [0.0, 0.0]])
    dup = moasmo.get_duplicates(X)
    assert dup.tolist() == [False, False, True]
    # cross-set: row i of X is compared only against rows j<i of Y
    # (reference masks the upper triangle incl. diagonal, MOEA.py:426-437)
    Y = np.array([[1.0, 1.0], [0.0, 0.0]])
    dup_xy = moasmo.get_duplicates(np.array([[9.0, 9.0], [1.0, 1.0]]), Y)
    assert dup_xy.tolist() == [False, True]


def test_epoch_surrogate_mode_resample():
    rng = np.random.default_rng(7)
    Xinit = rng.uniform(size=(60, 6)).astype(np.float32)
    Yinit = _eval_zdt1(Xinit)

    gen = moasmo.epoch(
        num_generations=20,
        param_names=PARAM_NAMES,
        objective_names=["f1", "f2"],
        xlb=XLB,
        xub=XUB,
        pct=0.25,
        Xinit=Xinit,
        Yinit=Yinit,
        C=None,
        pop=32,
        optimizer_name="nsga2",
        surrogate_method_name="gpr",
        surrogate_method_kwargs={"n_starts": 4, "n_iter": 50, "seed": 1},
        local_random=11,
    )
    with pytest.raises(StopIteration) as ex:
        next(gen)
    res = ex.value.value
    assert set(res) >= {"x_resample", "y_pred", "gen_index", "x_sm", "y_sm"}
    assert res["x_resample"].shape == (8, 6)
    assert res["y_pred"].shape == (8, 2)
    assert np.all(res["x_resample"] >= XLB) and np.all(res["x_resample"] <= XUB)
    # resample points must not duplicate the training set
    d = np.min(
        np.linalg.norm(res["x_resample"][:, None, :] - Xinit[None, :, :], axis=2),
        axis=1,
    )
    assert np.all(d > 1e-12)


def test_epoch_no_surrogate_mode_drives_real_evals():
    rng = np.random.default_rng(3)
    Xinit = rng.uniform(size=(40, 6)).astype(np.float32)
    Yinit = _eval_zdt1(Xinit)

    gen = moasmo.epoch(
        num_generations=5,
        param_names=PARAM_NAMES,
        objective_names=["f1", "f2"],
        xlb=XLB,
        xub=XUB,
        pct=0.25,
        Xinit=Xinit,
        Yinit=Yinit,
        C=None,
        pop=16,
        optimizer_name="nsga2",
        surrogate_method_name=None,
        local_random=5,
    )
    item = next(gen)
    n_yields = 0
    res = None
    while True:
        x_gen, _ = item
        n_yields += 1
        y_gen = _eval_zdt1(x_gen)
        try:
            item = gen.send((x_gen, y_gen, None))
        except StopIteration as ex:
            res = ex.value
            break
    # initial-design evaluation + one yield per generation
    assert n_yields == 6
    assert "best_x" in res and "best_y" in res
    assert res["best_x"].shape[1] == 6


def test_moasmo_two_epoch_loop_improves_front():
    """Two surrogate epochs with real re-evaluation shrink distance to the
    analytic ZDT1 front (the reference's core MO-ASMO claim)."""
    rng = np.random.default_rng(0)
    X = np.asarray(
        moasmo.xinit(10, PARAM_NAMES, XLB, XUB, method="slh", local_random=1),
        dtype=np.float32,
    )
    Y = _eval_zdt1(X)
    front = zdt1_pareto(200)
    d0 = float(np.mean(distance_to_front(Y, front)))

    for ep in range(2):
        gen = moasmo.epoch(
            num_generations=30,
            param_names=PARAM_NAMES,
            objective_names=["f1", "f2"],
            xlb=XLB,
            xub=XUB,
            pct=1.0,
            Xinit=X,
            Yinit=Y,
            C=None,
            pop=48,
            optimizer_name="nsga2",
            surrogate_method_name="gpr",
            surrogate_method_kwargs={"n_starts": 4, "n_iter": 80, "seed": ep},
            local_random=ep,
        )
        with pytest.raises(StopIteration) as ex:
            next(gen)
        res = ex.value.value
        x_new = res["x_resample"]
        y_new = _eval_zdt1(x_new)
        X = np.vstack([X, x_new])
        Y = np.vstack([Y, y_new])

    best = moasmo.get_best(X, Y, None, None, 6, 2)
    best_y = best[1]
    d1 = float(np.mean(distance_to_front(best_y, front)))
    assert d1 < d0 * 0.5, (d0, d1)


def test_get_best_and_feasible():
    y = np.array([[0.0, 1.0], [1.0, 0.0], [2.0, 2.0], [0.5, 0.5]])
    x = np.arange(8.0).reshape(4, 2)
    c = np.array([[1.0], [1.0], [1.0], [-1.0]])  # last point infeasible
    bx, by, bf, bc, bep, _ = moasmo.get_best(x, y, None, c, 2, 2)
    assert by.shape[0] == 2  # [0,1] and [1,0] (infeasible [0.5,0.5] excluded)
    assert np.all(np.asarray(bc) > 0)

    perm_arrs, rnk_arrs, epc_arrs, rnk_epc = moasmo.get_feasible(
        x, y, np.zeros(4), c, 2, 2, epochs=np.array([0, 0, 1, 1])
    )
    uniq_rank, rank_idx, rnk_cnt = rnk_arrs
    assert int(rnk_cnt.sum()) == 3  # 3 feasible points grouped


def test_epsilon_get_best():
    y = np.array([[0.0, 1.0], [1.0, 0.0], [0.01, 0.99], [2.0, 2.0]])
    x = np.arange(8.0).reshape(4, 2)
    bx, by, bf, bc, eps = moasmo.epsilon_get_best(x, y, None, None, epsilons=0.1)
    # [2,2] is dominated; [0,1] and [0.01,0.99] share an epsilon box -> one kept
    assert by.shape[0] == 2
    assert not np.any(np.all(by == np.array([2.0, 2.0]), axis=1))


def test_dmosopt_alias_module_and_profiling():
    """Drop-in import surface + phase-timer stats convention."""
    from dmosopt_tpu import dmosopt as alias
    from dmosopt_tpu.driver import run as real_run
    from dmosopt_tpu.utils.profiling import eval_time_stats, phase_timer

    assert alias.run is real_run
    assert alias.DistOptimizer is not None

    stats = {}
    with phase_timer(stats, "init_sampling"):
        pass
    assert stats["init_sampling_end"] >= stats["init_sampling_start"]

    agg = eval_time_stats([0.5, 1.5, -1.0])
    assert agg["eval_mean"] == pytest.approx(1.0)
    assert eval_time_stats([-1.0])["eval_mean"] == -1.0


def test_host_loop_escape_hatch_for_non_scannable_optimizer():
    """A user-registered optimizer with jit_compatible=False runs through
    the per-generation host loop (moasmo._optimize_host_loop) with the
    same result contract as the scan path."""
    import jax
    import jax.numpy as jnp

    from dmosopt_tpu.optimizers.nsga2 import NSGA2
    from dmosopt_tpu.models.gp import GPR_Matern
    from dmosopt_tpu.models import Model

    class HostNSGA2(NSGA2):
        jit_compatible = False

    dim, pop = 6, 16
    rng = np.random.default_rng(0)
    X = rng.uniform(size=(40, dim)).astype(np.float32)
    Y = np.asarray(zdt1(jnp.asarray(X)))
    sm = GPR_Matern(X, Y, dim, 2, np.zeros(dim), np.ones(dim),
                    seed=0, n_starts=2, n_iter=15)
    opt = HostNSGA2(popsize=pop, nInput=dim, nOutput=2, model=None)
    bounds = np.stack([np.zeros(dim), np.ones(dim)], 1)
    opt.initialize_strategy(X[:pop], Y[:pop], bounds, random=0)
    eval_fn = moasmo._surrogate_eval_fn(Model(objective=sm))

    x_new, y_new, gen_counts = moasmo._optimize_on_device(
        opt, eval_fn, num_generations=4, key=jax.random.PRNGKey(0)
    )
    assert len(gen_counts) == 4
    assert x_new.shape == (int(gen_counts.sum()), dim)
    assert np.all(np.isfinite(y_new))


def test_lazy_termination_defers_population_transfer():
    """The periodic termination check must not copy the population to
    host unless a criterion actually reads it: a generation-cap
    criterion costs ZERO transfers, a population-reading criterion
    triggers exactly one materialization per array per check. Pinned by
    LazyHostArray.transfer_count so the deferred copy can't silently
    regress into an eager one."""
    import jax
    import jax.numpy as jnp

    from dmosopt_tpu.models import Model
    from dmosopt_tpu.models.gp import GPR_Matern
    from dmosopt_tpu.moasmo import LazyHostArray
    from dmosopt_tpu.optimizers.nsga2 import NSGA2
    from dmosopt_tpu.termination import (
        MaximumGenerationTermination,
        MultiObjectiveToleranceTermination,
    )

    dim, pop = 4, 16
    rng = np.random.default_rng(0)
    X = rng.uniform(size=(32, dim)).astype(np.float32)
    Y = np.asarray(zdt1(jnp.asarray(X)))
    sm = GPR_Matern(X, Y, dim, 2, np.zeros(dim), np.ones(dim),
                    seed=0, n_starts=2, n_iter=15)
    eval_fn = moasmo._surrogate_eval_fn(Model(objective=sm))
    bounds = np.stack([np.zeros(dim), np.ones(dim)], 1)

    class Prob:
        lb = np.zeros(dim)
        ub = np.ones(dim)
        logger = None

    def run(term):
        opt = NSGA2(popsize=pop, nInput=dim, nOutput=2, model=None)
        opt.initialize_strategy(X[:pop], Y[:pop], bounds, random=0)
        before = LazyHostArray.transfer_count
        moasmo._optimize_on_device(
            opt, eval_fn, num_generations=6, key=jax.random.PRNGKey(0),
            termination=term, termination_check_interval=2,
        )
        return LazyHostArray.transfer_count - before

    # generation cap: n_gen only — the populations stay on device
    assert run(MaximumGenerationTermination(Prob(), n_max_gen=6)) == 0
    # objective-tolerance: reads opt.y (never opt.x) — y transfers, x not
    n = run(MultiObjectiveToleranceTermination(Prob(), n_max_gen=6))
    assert n >= 1
    # 4 checks (gens 0,2,4,6): one y materialization each, and no x
    assert n <= 4


def test_lazy_host_array_supports_operators():
    """Operator dunders bypass __getattr__; a user criterion doing
    `opt.y * 2.0` or `-opt.y` must keep working as it did on the eager
    ndarray (materializing on first use)."""
    import jax.numpy as jnp

    from dmosopt_tpu.moasmo import LazyHostArray

    lazy = LazyHostArray(jnp.asarray([[1.0, 2.0], [3.0, 4.0]]))
    np.testing.assert_allclose(lazy * 2.0, [[2.0, 4.0], [6.0, 8.0]])
    np.testing.assert_allclose(2.0 + lazy, [[3.0, 4.0], [5.0, 6.0]])
    np.testing.assert_allclose(-lazy, [[-1.0, -2.0], [-3.0, -4.0]])
    assert (lazy > 2.5).sum() == 2
    np.testing.assert_allclose(lazy / 2.0, [[0.5, 1.0], [1.5, 2.0]])
    assert lazy.shape == (2, 2) and lazy.ndim == 2


def test_fused_maxgen_path_bitwise_matches_chunked_oracle(monkeypatch):
    """ISSUE 19 fused sequential path: under a plain
    MaximumGenerationTermination the whole generation budget runs as
    ONE scanned program. The retained chunk-per-host-check loop is the
    parity oracle — trajectories must match bitwise — and with
    telemetry the fused run compiles exactly one `ea_scan` program per
    (signature, budget)."""
    import jax
    import jax.numpy as jnp

    from dmosopt_tpu.models import Model
    from dmosopt_tpu.models.gp import GPR_Matern
    from dmosopt_tpu.optimizers.nsga2 import NSGA2
    from dmosopt_tpu.telemetry import create_telemetry
    from dmosopt_tpu.termination import MaximumGenerationTermination

    dim, pop = 4, 16
    rng = np.random.default_rng(0)
    X = rng.uniform(size=(32, dim)).astype(np.float32)
    Y = np.asarray(zdt1(jnp.asarray(X)))
    sm = GPR_Matern(X, Y, dim, 2, np.zeros(dim), np.ones(dim),
                    seed=0, n_starts=2, n_iter=15)
    eval_fn = moasmo._surrogate_eval_fn(Model(objective=sm))
    bounds = np.stack([np.zeros(dim), np.ones(dim)], 1)

    class Prob:
        lb = np.zeros(dim)
        ub = np.ones(dim)
        logger = None

    def run(fused, tel=None):
        with monkeypatch.context() as mp:
            if not fused:
                # disable the fusion gate: the while loop below it IS
                # the pre-fusion chunked implementation, unchanged
                mp.setattr(moasmo, "_fused_generation_total",
                           lambda *a: 0)
            opt = NSGA2(popsize=pop, nInput=dim, nOutput=2, model=None)
            opt.initialize_strategy(X[:pop], Y[:pop], bounds, random=0)
            return moasmo._optimize_on_device(
                opt, eval_fn, num_generations=6, key=jax.random.PRNGKey(0),
                termination=MaximumGenerationTermination(Prob(), n_max_gen=6),
                termination_check_interval=2, telemetry=tel,
            )

    xf, yf, gf = run(True)
    xc, yc, gc = run(False)
    # the chunked loop checks at gens 0,2,4,6 (continue while <= 6) and
    # stops at 8 -> both paths run exactly 8 generations
    assert len(gf) == len(gc) == 8
    assert np.array_equal(gf, gc)
    assert np.array_equal(xf, xc)
    assert np.array_equal(yf, yc)

    # trace-time pin: ONE compiled program for the whole budget (the
    # chunked loop also compiles once but dispatches per chunk; the
    # fused path must never fan back out into per-chunk shapes)
    tel = create_telemetry(True)
    run(True, tel)
    compiles = [
        e for e in tel.log.records(kind="program_compile")
        if e.fields["program"] == "ea_scan"
    ]
    assert len(compiles) == 1
    assert compiles[0].fields["retrace"] is False


def test_fused_generation_total_gates():
    """Fusion only fires for a plain finite MaximumGenerationTermination;
    every data-dependent rule stays on the host-checked chunked loop."""
    from dmosopt_tpu.termination import (
        MaximumGenerationTermination,
        MultiObjectiveToleranceTermination,
    )

    class Prob:
        lb = np.zeros(2)
        ub = np.ones(2)
        logger = None

    assert moasmo._fused_generation_total(
        MaximumGenerationTermination(Prob(), n_max_gen=10), 10
    ) == 20
    assert moasmo._fused_generation_total(
        MaximumGenerationTermination(Prob(), n_max_gen=9), 10
    ) == 10
    assert moasmo._fused_generation_total(
        MaximumGenerationTermination(Prob(), n_max_gen=21), 10
    ) == 30
    # infinite cap, forced stop, and composite criteria never fuse
    assert moasmo._fused_generation_total(
        MaximumGenerationTermination(Prob(), n_max_gen=None), 10
    ) == 0
    forced = MaximumGenerationTermination(Prob(), n_max_gen=10)
    forced.force_termination = True
    assert moasmo._fused_generation_total(forced, 10) == 0
    assert moasmo._fused_generation_total(
        MultiObjectiveToleranceTermination(Prob(), n_max_gen=10), 10
    ) == 0
    assert moasmo._fused_generation_total(None, 10) == 0
