"""Test configuration: run the suite on a virtual 8-device CPU mesh.

The driver benches on a real TPU chip; tests exercise the same jitted code
paths on CPU with XLA's host-platform device-count override so multi-device
sharding is tested without TPU hardware.
"""

import os

# Must be set before jax is imported anywhere in the test process.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The axon TPU plugin in this image force-overrides JAX_PLATFORMS at import
# time; an explicit post-import config.update wins and restores the 8-device
# virtual CPU mesh the suite is designed for.
jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", jax.devices()

# The suite is compile-dominated on a 1-core box: persist XLA compilations
# across runs so only the first run pays (cache dir is gitignored,
# machine-keyed so a container migrating hosts doesn't load mismatched
# AOT entries — those spew cpu_aot_loader warnings and risk SIGILL).
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
from dmosopt_tpu.utils.compile_cache import enable_persistent_cache

enable_persistent_cache(os.path.join(os.path.dirname(__file__), ".jax_cache"))

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)
