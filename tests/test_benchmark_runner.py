"""BenchmarkRunner end-to-end capture tests (capability match of the
reference's tests/test_moo_benchmarks.py:25-216 harness).

One DTLZ2 benchmark run is shared (module-scoped fixture) between the
capture-fields test and the summary test; the trajectory-monotonicity
test needs its own multi-epoch run on DTLZ7.
"""

import json

import numpy as np
import pytest

from dmosopt_tpu.benchmarks.runner import BenchmarkResult, BenchmarkRunner


FAST = dict(
    population_size=16,
    num_generations=5,
    n_epochs=2,
    n_initial=4,
    surrogate_method_kwargs={"n_starts": 2, "n_iter": 20, "seed": 0},
)


@pytest.fixture(scope="module")
def dtlz2_run(tmp_path_factory):
    out = tmp_path_factory.mktemp("runner")
    runner = BenchmarkRunner(output_dir=str(out))
    res = runner.run_single_benchmark("dtlz2", 3, **FAST)
    return runner, res, out


def test_runner_captures_dtlz2(dtlz2_run):
    _, res, out = dtlz2_run

    assert isinstance(res, BenchmarkResult)
    assert res.problem_name == "dtlz2"
    assert res.n_objectives == 3
    assert res.n_variables == 12  # n_obj + 9
    assert len(res.hv_trajectory) == 2
    assert res.final_hv > 0.0
    assert res.computation_time_seconds > 0.0
    assert res.termination_reason == "epoch_budget"
    assert res.n_archive > 0
    assert res.metadata["pf_shape"] == "concave"

    payload = json.loads((out / "dtlz2_m3_result.json").read_text())
    assert payload["final_hv"] == pytest.approx(res.final_hv)
    assert payload["hv_trajectory"] == res.hv_trajectory


def test_runner_summary(dtlz2_run):
    runner, res, out = dtlz2_run
    runner.save_summary()
    rows = json.loads((out / "summary.json").read_text())
    assert len(rows) == 1 and rows[0]["problem_name"] == "dtlz2"
    assert rows[0]["n_objectives"] == 3
    assert rows[0]["final_hv"] == pytest.approx(res.final_hv)


def test_runner_maf2_many_objective(tmp_path):
    """The 5-objective path through the runner (ref-point sizing,
    save_json=False) — minimal budget; problem math itself is oracle-
    tested in test_benchmarks.py."""
    runner = BenchmarkRunner(output_dir=str(tmp_path))
    res = runner.run_single_benchmark(
        "maf2", 5, save_json=False,
        **{**FAST, "n_epochs": 1, "num_generations": 3, "population_size": 8},
    )
    assert res.n_objectives == 5
    assert res.final_hv > 0.0
    runner.save_summary()
    rows = json.loads((tmp_path / "summary.json").read_text())
    assert rows[0]["problem_name"] == "maf2" and rows[0]["n_objectives"] == 5


def test_runner_hv_improves_on_dtlz7(tmp_path):
    """The archive HV (fixed reference point) must not regress as epochs
    add resampled points — the trajectory is measured, not a placeholder."""
    runner = BenchmarkRunner(output_dir=str(tmp_path))
    res = runner.run_single_benchmark(
        "dtlz7", 3, save_json=False, **{**FAST, "n_epochs": 3}
    )
    traj = res.hv_trajectory
    assert len(traj) == 3
    # archive only grows; HV against a fixed reference is monotone
    assert traj[-1] >= traj[0] - 1e-9, traj
