"""Termination criteria tests (reference semantics: dmosopt/termination.py,
adaptive_termination.py, hv_termination.py)."""

import logging

import numpy as np
import pytest

from dmosopt_tpu.adaptive_termination import (
    CompositeAdaptiveTermination,
    MultiScaleStagnationTermination,
    PerObjectiveConvergence,
    ResourceAwareTermination,
    create_adaptive_termination,
)
from dmosopt_tpu.datatypes import OptHistory
from dmosopt_tpu.hv_termination import (
    HypervolumeProgressTermination,
    MultiFidelityHVTracker,
    ProgressivePrecisionScheduler,
)
from dmosopt_tpu.termination import (
    MaximumGenerationTermination,
    MultiObjectiveToleranceTermination,
    ParameterToleranceTermination,
    StandardTermination,
    TerminationCollection,
)


class Prob:
    n_objectives = 2
    lb = np.zeros(4)
    ub = np.ones(4)
    logger = logging.getLogger("term-test")


def _opt(n_gen, x, y, c=None):
    return OptHistory(n_gen, n_gen * len(x), np.asarray(x), np.asarray(y), c)


def _static_history(n=60, n_pts=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(size=(n_pts, 4))
    y = rng.uniform(size=(n_pts, 2))
    return [(i + 1, x, y) for i in range(n)]


def test_max_generation():
    t = MaximumGenerationTermination(Prob(), 10)
    x = np.zeros((4, 4))
    y = np.zeros((4, 2))
    assert not t.has_terminated(_opt(10, x, y))
    assert t.has_terminated(_opt(11, x, y))


def test_moo_tolerance_terminates_on_static_population():
    t = MultiObjectiveToleranceTermination(Prob(), tol=0.0025, n_last=5)
    terminated = False
    for i, x, y in _static_history():
        if t.has_terminated(_opt(i, x, y)):
            terminated = True
            break
    assert terminated


def test_moo_tolerance_continues_on_moving_population():
    t = MultiObjectiveToleranceTermination(Prob(), tol=1e-6, n_last=5)
    rng = np.random.default_rng(1)
    x = rng.uniform(size=(16, 4))
    for i in range(30):
        y = rng.uniform(size=(16, 2)) - 0.5 * i  # ideal keeps improving
        if t.has_terminated(_opt(i + 1, x, y)):
            pytest.fail("terminated on a steadily improving population")


def test_parameter_tolerance():
    t = ParameterToleranceTermination(Prob(), tol=1e-6, n_last=3)
    terminated = False
    for i, x, y in _static_history(30):
        if t.has_terminated(_opt(i, x, y)):
            terminated = True
            break
    assert terminated


def test_standard_and_collection():
    t = StandardTermination(Prob(), n_max_gen=100)
    assert isinstance(t, TerminationCollection)
    terminated = False
    for i, x, y in _static_history(60):
        if t.has_terminated(_opt(i, x, y)):
            terminated = True
            break
    assert terminated


def test_per_objective_convergence():
    t = PerObjectiveConvergence(Prob(), obj_tol=1e-3, n_last=5, nth_gen=1)
    terminated = False
    for i, x, y in _static_history(60):
        if t.has_terminated(_opt(i, x, y)):
            terminated = True
            break
    assert terminated


def test_multiscale_stagnation():
    t = MultiScaleStagnationTermination(
        Prob(), timescales=[3, 5, 8, 12], stagnation_tol=1e-3, nth_gen=1
    )
    terminated = False
    for i, x, y in _static_history(60):
        if t.has_terminated(_opt(i, x, y)):
            terminated = True
            break
    assert terminated


def test_hv_progress_termination_on_static_front():
    t = HypervolumeProgressTermination(
        Prob(), hv_tol=1e-4, n_last=4, nth_gen=1, min_generations=5
    )
    terminated = False
    for i, x, y in _static_history(80):
        if t.has_terminated(_opt(i, x, y)):
            terminated = True
            break
    assert terminated


def test_precision_scheduler_and_tracker():
    s = ProgressivePrecisionScheduler()
    assert s.get_epsilon(0) > s.get_epsilon(100)
    assert s.get_phase(0) == "early" and s.get_phase(100) == "late"

    tracker = MultiFidelityHVTracker(np.array([2.0, 2.0]))
    F = np.array([[1.0, 1.0], [0.5, 1.5]])
    for gen in range(11):
        tracker.compute_and_update(F, gen)
    assert len(tracker.state.history_coarse) == 11
    assert len(tracker.state.history_medium) == 3  # gens 0, 5, 10
    best = tracker.get_best_estimate(10)
    assert best is not None and best.fidelity == "fine"


def test_composite_and_factory():
    for strategy in ("comprehensive", "fast", "conservative", "simple"):
        t = create_adaptive_termination(Prob(), n_max_gen=50, strategy=strategy)
        assert t is not None
    with pytest.raises(ValueError):
        create_adaptive_termination(Prob(), strategy="bogus")

    t = CompositeAdaptiveTermination(Prob(), n_max_gen=30)
    x = np.zeros((4, 4))
    y = np.zeros((4, 2))
    assert t.has_terminated(_opt(31, x, y))  # max-gen member fires


def test_resource_aware():
    t = ResourceAwareTermination(Prob(), max_function_evals=100)
    x = np.zeros((4, 4))
    y = np.zeros((4, 2))
    assert not t.has_terminated(_opt(10, x, y))
    assert t.has_terminated(_opt(50, x, y))  # n_eval = 50*4 = 200 > 100


def test_resource_aware_requires_n_eval():
    """A set eval budget must refuse states with no n_eval counter rather
    than silently counting generations."""
    from collections import namedtuple

    GenOnly = namedtuple("GenOnly", ["n_gen"])
    t = ResourceAwareTermination(Prob(), max_function_evals=10)
    with pytest.raises(ValueError, match="n_eval"):
        t.has_terminated(GenOnly(5))


def test_resource_aware_eval_budget_stops_mid_run():
    """max_function_evals stops the scanned inner loop at the requested
    evaluation count, not at check-interval granularity."""
    import jax
    import jax.numpy as jnp

    from dmosopt_tpu import moasmo
    from dmosopt_tpu.benchmarks.zdt import zdt1

    pop = 16
    budget = 5 * pop  # 5 generations' worth: inside the default interval
    from dmosopt_tpu.optimizers import NSGA2

    opt = NSGA2(popsize=pop, nInput=4, nOutput=2, model=None)
    rng = np.random.default_rng(11)
    x0 = rng.uniform(size=(pop, 4)).astype(np.float32)
    y0 = np.asarray(zdt1(jnp.asarray(x0)))
    bounds = np.stack([np.zeros(4), np.ones(4)], 1).astype(np.float32)
    opt.initialize_strategy(x0, y0, bounds, random=1)

    t = ResourceAwareTermination(Prob(), max_function_evals=budget)
    assert t.eval_budget() == budget
    x_new, y_new, gen_counts = moasmo._optimize_on_device(
        opt, zdt1, 100, jax.random.PRNGKey(0),
        termination=t, termination_check_interval=50,
    )
    n_eval = x_new.shape[0]
    assert n_eval == budget, (n_eval, budget)
    assert len(gen_counts) == 5

    # the budget also propagates through a composite collection
    coll = TerminationCollection(
        Prob(),
        MaximumGenerationTermination(Prob(), 1000),
        ResourceAwareTermination(Prob(), max_function_evals=budget),
    )
    assert coll.eval_budget() == budget


def test_termination_in_moasmo_surrogate_loop():
    """End-to-end: adaptive termination stops the on-device EA early."""
    import jax.numpy as jnp

    from dmosopt_tpu import moasmo
    from dmosopt_tpu.benchmarks.zdt import zdt1

    rng = np.random.default_rng(5)
    X = rng.uniform(size=(40, 4)).astype(np.float32)
    Y = np.asarray(zdt1(jnp.asarray(X)))

    t = MultiObjectiveToleranceTermination(Prob(), tol=0.05, n_last=3, n_max_gen=500)
    gen = moasmo.epoch(
        num_generations=10,  # ignored: termination is the stopping rule
        param_names=[f"x{i}" for i in range(4)],
        objective_names=["f1", "f2"],
        xlb=np.zeros(4),
        xub=np.ones(4),
        pct=0.5,
        Xinit=X,
        Yinit=Y,
        C=None,
        pop=16,
        optimizer_name="nsga2",
        surrogate_method_name="gpr",
        surrogate_method_kwargs={"n_starts": 2, "n_iter": 20, "seed": 0},
        termination=t,
        local_random=3,
    )
    with pytest.raises(StopIteration) as ex:
        next(gen)
    res = ex.value.value
    assert res["x_resample"].shape[0] == 8


def test_resource_aware_eval_budget_never_overshoots():
    """A budget that is NOT a multiple of the offspring count is a hard
    cap: the loop runs only whole generations that fit under it."""
    import jax
    import jax.numpy as jnp

    from dmosopt_tpu import moasmo
    from dmosopt_tpu.benchmarks.zdt import zdt1
    from dmosopt_tpu.optimizers import NSGA2

    pop = 16
    budget = 4 * pop + 6  # 70: only 4 full generations fit
    opt = NSGA2(popsize=pop, nInput=4, nOutput=2, model=None)
    rng = np.random.default_rng(12)
    x0 = rng.uniform(size=(pop, 4)).astype(np.float32)
    y0 = np.asarray(zdt1(jnp.asarray(x0)))
    bounds = np.stack([np.zeros(4), np.ones(4)], 1).astype(np.float32)
    opt.initialize_strategy(x0, y0, bounds, random=1)

    t = ResourceAwareTermination(Prob(), max_function_evals=budget)
    x_new, _, gen_counts = moasmo._optimize_on_device(
        opt, zdt1, 100, jax.random.PRNGKey(0),
        termination=t, termination_check_interval=50,
    )
    n_eval = x_new.shape[0]
    assert n_eval == 4 * pop, (n_eval, budget)
    assert len(gen_counts) == 4
    # the stop is attributed to the budget criterion even though no
    # evaluation ever reached the cap
    assert t.stop_reasons() == ["ResourceAwareTermination"]

    # budget smaller than one generation: zero evaluations, not one over
    opt2 = NSGA2(popsize=pop, nInput=4, nOutput=2, model=None)
    opt2.initialize_strategy(x0, y0, bounds, random=1)
    t2 = ResourceAwareTermination(Prob(), max_function_evals=pop - 1)
    x_new2, _, gen_counts2 = moasmo._optimize_on_device(
        opt2, zdt1, 100, jax.random.PRNGKey(0),
        termination=t2, termination_check_interval=50,
    )
    assert len(gen_counts2) == 0 and x_new2.shape[0] == 0
