"""Adaptive population sizing (reference dmosopt/NSGA2.py:223-265,
dmosopt/AGEMOEA.py:217-260): the live size follows the diversity-driven
grow/shrink rule in-graph, and the static capacity grows at host chunk
boundaries when the live size pins at its ceiling."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from dmosopt_tpu import moasmo, sampling
from dmosopt_tpu.models import Model
from dmosopt_tpu.optimizers.adaptive import adapt_population_size
from dmosopt_tpu.optimizers.agemoea import AGEMOEA
from dmosopt_tpu.optimizers.nsga2 import NSGA2
from dmosopt_tpu.benchmarks.zdt import zdt1

DIM = 6


class _Obj:
    def __init__(self, fn):
        self.evaluate = fn


def _drive(opt, fn, ngen):
    bounds = np.stack([np.zeros(DIM), np.ones(DIM)], 1)
    x0 = sampling.lh(opt.popsize, DIM, 1)
    y0 = np.asarray(fn(jnp.asarray(x0)))
    opt.initialize_strategy(x0, y0, bounds, random=1)
    gen = moasmo.optimize(
        ngen, opt, Model(objective=_Obj(fn)), DIM, 2,
        np.zeros(DIM), np.ones(DIM), popsize=opt.popsize, local_random=3,
    )
    try:
        next(gen)
        raise AssertionError("surrogate-mode optimize must not yield")
    except StopIteration as ex:
        return ex.value


def test_formula_grow_shrink_hold():
    """Pin the reference update rule branch by branch
    (dmosopt/NSGA2.py:245-266), including the int() truncation."""
    cap = 64
    y = jnp.linspace(0.0, 1.0, cap)[:, None] * jnp.ones((1, 2))
    n = jnp.asarray(20, jnp.int32)

    # thin front: 1 of 20 on front 0 -> diversity 0.05, spread 0 -> grow
    rank = jnp.arange(cap, dtype=jnp.int32)
    assert int(
        adapt_population_size(y, rank, n, min_size=8, max_size=2000,
                              capacity=cap)
    ) == int(20 * 1.2)

    # everything on front 0 -> diversity 1.0 -> shrink (18 = int(20*0.9))
    rank0 = jnp.zeros((cap,), jnp.int32)
    assert int(
        adapt_population_size(y, rank0, n, min_size=8, max_size=2000,
                              capacity=cap)
    ) == 18

    # shrink respects min_size
    assert int(
        adapt_population_size(y, rank0, n, min_size=20, max_size=2000,
                              capacity=cap)
    ) == 20

    # growth clamps to the static capacity
    assert int(
        adapt_population_size(y, rank, jnp.asarray(60, jnp.int32),
                              min_size=8, max_size=2000, capacity=cap)
    ) == cap


@pytest.mark.parametrize("cls", [NSGA2, AGEMOEA])
def test_shrinks_on_converged_front(cls):
    """ZDT1 converges onto front 0 quickly -> diversity > 0.9 -> the live
    size shrinks toward min_population_size; host API returns only live
    rows."""
    opt = cls(
        popsize=16, nInput=DIM, nOutput=2, model=None,
        adaptive_population_size=True, min_population_size=8,
        max_population_size=64,
    )
    res = _drive(opt, zdt1, 40)
    na = int(opt.state.n_active)
    assert na == 8
    assert res.best_x.shape[0] == na
    assert np.all(np.isfinite(res.best_y))


@pytest.mark.parametrize("cls", [NSGA2, AGEMOEA])
def test_grows_and_expands_capacity(cls):
    """A near-single-objective landscape keeps front 0 thin (low
    diversity) -> the live size grows past the initial capacity, forcing
    a host-side capacity expansion and a re-trace."""

    def thin_front(X):  # strongly correlated objectives -> thin front
        s = jnp.sum(X, axis=1)
        q = jnp.sum((X - 0.05) ** 2, axis=1)
        return jnp.stack([s, q], axis=1)

    opt = cls(
        popsize=16, nInput=DIM, nOutput=2, model=None,
        adaptive_population_size=True, min_population_size=8,
        max_population_size=48,
    )
    res = _drive(opt, thin_front, 30)
    na = int(opt.state.n_active)
    assert opt.capacity > 16, "capacity never grew"
    assert opt.capacity <= 48
    assert na > 16
    assert res.best_x.shape[0] == na
    assert np.all(np.isfinite(res.best_y))
    # the expanded state stays internally consistent
    assert opt.state.population_parm.shape[0] == opt.capacity
    assert opt.state.rank.shape[0] == opt.capacity


def test_capacity_growth_training_set_has_no_padded_duplicates():
    """After a mid-run capacity growth the epoch's accumulated training
    set (EpochResults.x / gen_index) must contain only real, distinct
    evaluations: per-generation widths reflect the true pre-/post-growth
    offspring counts (not one padded rectangle), and no duplicated rows
    flow toward archives or surrogate training."""

    def thin_front(X):
        s = jnp.sum(X, axis=1)
        q = jnp.sum((X - 0.05) ** 2, axis=1)
        return jnp.stack([s, q], axis=1)

    opt = NSGA2(
        popsize=16, nInput=DIM, nOutput=2, model=None,
        adaptive_population_size=True, min_population_size=8,
        max_population_size=48,
    )
    res = _drive(opt, thin_front, 30)
    assert opt.capacity > 16, "capacity never grew"

    counts = np.bincount(res.gen_index)
    assert res.x.shape[0] == res.gen_index.shape[0] == counts.sum()
    widths = counts[1:]  # gen_index 0 is the initial sample
    # pre-growth generations are narrower than post-growth ones; a padded
    # rectangle would report one uniform width everywhere
    assert widths.min() == 16
    assert widths.max() > 16
    # every accumulated offspring row is distinct (padding duplicated the
    # last offspring of each narrow generation)
    n0 = int(counts[0])
    new_rows = res.x[n0:]
    assert np.unique(new_rows, axis=0).shape[0] == new_rows.shape[0]


def test_default_off_is_unchanged():
    """With the default (off), state carries n_active == popsize and the
    whole population is returned — bitwise-identical behavior."""
    opt = NSGA2(popsize=16, nInput=DIM, nOutput=2, model=None)
    res = _drive(opt, zdt1, 10)
    assert int(opt.state.n_active) == 16
    assert res.best_x.shape[0] == 16
