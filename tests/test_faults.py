"""Deterministic fault-injection harness (dmosopt_tpu.testing.faults).

Plans must be reproducible (stateless seeded decisions), rule windows
exact (`after`/`count`), and the wrappers must drive the REAL
timeout/retry machinery in the host evaluator rather than simulating
around it.
"""

import json

import numpy as np
import pytest

from dmosopt_tpu.parallel.evaluator import EvalFailure, HostFunEvaluator
from dmosopt_tpu.parallel.pipeline import BackgroundWriter
from dmosopt_tpu.testing.faults import (
    FAULT_PLAN_ENV,
    FaultPlan,
    FaultRule,
    FaultyEvaluator,
    FaultyStore,
)
from dmosopt_tpu.testing.faults import InjectedFault


def _drain(handle):
    out = {}
    while not handle.done:
        item = handle.poll(timeout=5.0)
        if item is not None:
            out[item[0]] = item[1]
    return [out[i] for i in sorted(out)]


def test_fault_rule_validation():
    with pytest.raises(ValueError, match="kind"):
        FaultRule(kind="meteor")
    with pytest.raises(ValueError, match="op"):
        FaultRule(kind="raise", op="network")
    with pytest.raises(ValueError, match="p must be"):
        FaultRule(kind="raise", p=1.5)


def test_fault_plan_windows_and_counts():
    plan = FaultPlan(
        [{"kind": "raise", "target": "a", "after": 2, "count": 2}]
    )
    fired = [plan.next_fault("eval", "a") is not None for _ in range(6)]
    # fires exactly on calls 2 and 3 (0-indexed), then the count is spent
    assert fired == [False, False, True, True, False, False]
    # other targets never match
    assert plan.next_fault("eval", "b") is None
    # accounting
    assert plan.calls("eval", "a") == 6
    assert plan.fires(kind="raise", target="a") == 2


def test_fault_plan_probability_is_seed_deterministic():
    def decisions(seed):
        plan = FaultPlan([{"kind": "nan", "p": 0.5}], seed=seed)
        return [
            plan.next_fault("eval", "t") is not None for _ in range(64)
        ]

    a, b, c = decisions(1), decisions(1), decisions(2)
    assert a == b  # same seed -> identical firing pattern
    assert a != c  # different seed -> different pattern
    assert 0 < sum(a) < 64  # p=0.5 actually mixes


def test_fault_plan_decisions_are_call_index_stateless():
    """Two plans consulted in DIFFERENT interleavings agree per
    (target, call index) — thread scheduling cannot change the plan."""
    rules = [{"kind": "raise", "target": "*", "p": 0.4}]
    p1, p2 = FaultPlan(rules, seed=3), FaultPlan(rules, seed=3)
    seq1 = [(t, p1.next_fault("eval", t) is not None)
            for t in ["a", "a", "b", "a", "b", "b"]]
    # interleave differently but keep per-target call order
    seq2 = {}
    for t in ["b", "a", "b", "b", "a", "a"]:
        seq2.setdefault(t, []).append(p2.next_fault("eval", t) is not None)
    per_target1 = {}
    for t, fired in seq1:
        per_target1.setdefault(t, []).append(fired)
    assert per_target1 == seq2


def test_fault_plan_from_env_inline_and_path(tmp_path, monkeypatch):
    monkeypatch.delenv(FAULT_PLAN_ENV, raising=False)
    assert FaultPlan.from_env() is None

    spec = {"seed": 5, "rules": [{"kind": "nan", "target": "x*"}]}
    monkeypatch.setenv(FAULT_PLAN_ENV, json.dumps(spec))
    plan = FaultPlan.from_env()
    assert plan.seed == 5 and plan.rules[0].kind == "nan"

    p = tmp_path / "plan.json"
    p.write_text(json.dumps(spec))
    monkeypatch.setenv(FAULT_PLAN_ENV, f"@{p}")
    plan = FaultPlan.from_env()
    assert plan.to_spec()["rules"][0]["target"] == "x*"

    with pytest.raises(ValueError, match="rules"):
        FaultPlan.from_spec({"seed": 1})


def _ok_eval(sv):
    return {0: np.asarray([float(sv["i"]), 1.0]), "time": 0.01}


def test_faulty_evaluator_host_raise_and_transient_retry():
    plan = FaultPlan(
        [{"kind": "raise", "target": "t", "count": 1,
          "message": "transient"}]
    )
    ev = FaultyEvaluator(HostFunEvaluator(_ok_eval), plan, "t")
    try:
        # retries=1: the injected first-attempt failure is retried and
        # the request SUCCEEDS — the real resubmission machinery ran
        h = ev.submit_batch([{"i": np.asarray(0)}], retries=1)
        (res,) = _drain(h)
        assert not isinstance(res, EvalFailure)
        assert res[0][0] == 0.0
        assert plan.fires(kind="raise") == 1

        # budget exhausted: a permanent raise surfaces as EvalFailure
        plan.rules.append(FaultRule(kind="raise", target="t"))
        h = ev.submit_batch([{"i": np.asarray(1)}], retries=1)
        (res,) = _drain(h)
        assert isinstance(res, EvalFailure)
        assert isinstance(res.error, InjectedFault)
        assert res.n_attempts == 2
    finally:
        ev.close()


def test_faulty_evaluator_host_hang_times_out():
    plan = FaultPlan([{"kind": "hang", "target": "t", "delay_s": 0.5}])
    ev = FaultyEvaluator(HostFunEvaluator(_ok_eval), plan, "t")
    try:
        h = ev.submit_batch([{"i": np.asarray(0)}], timeout=0.05, retries=0)
        (res,) = _drain(h)
        assert isinstance(res, EvalFailure) and res.timed_out
    finally:
        ev.close()


def test_faulty_evaluator_host_nan_and_inner_never_mutated():
    plan = FaultPlan([{"kind": "nan", "target": "t", "count": 1}])
    inner = HostFunEvaluator(_ok_eval)
    ev = FaultyEvaluator(inner, plan, "t")
    # the wrapper injects through ITS OWN eval_fun; the inner evaluator
    # is never patched (a caller-owned evaluator stays clean, and two
    # wrappers over one inner count their plans independently)
    assert inner.eval_fun is _ok_eval
    h = ev.submit_batch([{"i": np.asarray(3)}])
    (res,) = _drain(h)
    assert np.all(np.isnan(res[0])) and res["time"] == 0.01
    ev.close()
    assert inner.eval_fun is _ok_eval


def test_faulty_evaluator_jax_result_layer():
    from dmosopt_tpu.parallel.evaluator import JaxBatchEvaluator

    import jax.numpy as jnp

    def batch_fun(X):
        return jnp.stack([X[:, 0], X[:, 1]], axis=1)

    plan = FaultPlan(
        [
            {"kind": "nan", "target": "j", "count": 1},
            {"kind": "raise", "target": "j", "after": 1, "count": 1},
        ]
    )
    ev = FaultyEvaluator(JaxBatchEvaluator(batch_fun), plan, "j")
    X = [{0: np.asarray([0.1, 0.2], np.float32)},
         {0: np.asarray([0.3, 0.4], np.float32)},
         {0: np.asarray([0.5, 0.6], np.float32)}]
    results = _drain(ev.submit_batch(X))
    assert np.all(np.isnan(np.asarray(results[0][0])))
    assert isinstance(results[1], EvalFailure)
    np.testing.assert_allclose(
        np.asarray(results[2][0]), [0.5, 0.6], rtol=1e-6
    )


def test_faulty_store_drives_writer_retry_then_success():
    plan = FaultPlan(
        [{"kind": "io_error", "target": "writer", "count": 2,
          "op": "io", "message": "transient disk"}]
    )
    store = FaultyStore(plan, "writer")
    seen = []
    w = BackgroundWriter(max_retries=3, backoff=0.01, backoff_cap=0.05)
    w.submit(store.wrap(seen.append), 1)
    w.flush()  # two injected OSErrors were retried in place
    assert seen == [1]
    assert w.retries_total == 2
    assert not w.writer_failed
    w.close()


def test_faulty_store_exhausts_writer_retries():
    plan = FaultPlan(
        [{"kind": "io_error", "target": "writer", "op": "io"}]
    )
    store = FaultyStore(plan, "writer")
    w = BackgroundWriter(max_retries=2, backoff=0.01, backoff_cap=0.05)
    w.submit(store.wrap(lambda: None))
    with pytest.raises(RuntimeError, match="background persistence"):
        w.flush()
    assert w.writer_failed and w.retries_total == 2
    w.close()
