"""Span tracing, per-tenant cost attribution, and compile/retrace
observability (ISSUE 9).

The acceptance pins: a staggered multi-bucket service run exports
schema-valid Chrome trace JSON with nested epoch -> gp_fit/ea_scan/eval
spans carrying tenant labels; per-tenant `tenant_cost_seconds` sums to
each bucket's measured wall (exact by construction, pinned well inside
the 5% gate); a forced bucket-signature recompile produces exactly one
retrace-warning event; the `telemetry=False` zero-call pin is covered
by tests/test_telemetry.py (the tracer lives inside `Telemetry`, which
a disabled run never constructs).
"""

import json
import threading
import time

import numpy as np
import pytest

import dmosopt_tpu
from dmosopt_tpu import tenants
from dmosopt_tpu.benchmarks.zdt import zdt1
from dmosopt_tpu.driver import dopt_dict
from dmosopt_tpu.service import OptimizationService
from dmosopt_tpu.telemetry import Telemetry, span_scope
from dmosopt_tpu.telemetry.tracing import (
    Tracer,
    load_chrome_trace,
    validate_chrome_trace,
)

SMK = {"n_starts": 2, "n_iter": 25, "seed": 0}


# ---------------------------------------------------------- tracer units


def test_tracer_nesting_and_parent_links():
    tr = Tracer()
    with tr.span("epoch", epoch=0) as outer:
        with tr.span("gp_fit", bucket="b") as inner:
            assert inner.parent_id == outer.span_id
        with tr.span("ea_scan") as inner2:
            assert inner2.parent_id == outer.span_id
    assert outer.parent_id is None
    spans = tr.spans()
    assert [s.name for s in spans] == ["epoch", "gp_fit", "ea_scan"]
    assert all(s.t_end is not None and s.duration_s >= 0 for s in spans)
    assert spans[1].labels == {"bucket": "b"}


def test_tracer_record_span_and_out_of_order_close():
    tr = Tracer()
    with tr.span("epoch") as parent:
        t0 = time.perf_counter()
        rec = tr.record_span(
            "tenant_cost", t0, t0 + 0.5, parent=parent, tenant="3",
            phase="fit",
        )
    assert rec.parent_id == parent.span_id
    assert rec.duration_s == pytest.approx(0.5)
    # defensive out-of-order close: closing the outer context first
    # must not corrupt the stack
    a = tr.span("epoch")
    b = tr.span("gp_fit")
    sa = a.__enter__()
    sb = b.__enter__()
    a.__exit__(None, None, None)
    b.__exit__(None, None, None)
    assert sa.t_end is not None and sb.t_end is not None
    with tr.span("resample") as top:
        assert top.parent_id is None  # stack fully unwound


def test_tracer_threads_get_separate_stacks():
    tr = Tracer()
    seen = {}

    def worker():
        with tr.span("h5_write") as sp:
            seen["span"] = sp

    with tr.span("epoch"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    # the writer-thread span is parentless on its own track, not a
    # child of the driver thread's open epoch span
    assert seen["span"].parent_id is None


def test_tracer_bounded_keeps_most_recent_window():
    """Past max_spans the OLDEST spans are evicted (counted), so the
    export keeps the run's tail — the window an operator investigating
    a late slowdown actually needs — even when nothing ever drains
    (the service has no drain consumer)."""
    tr = Tracer(max_spans=3)
    for i in range(5):
        with tr.span("epoch", i=i):
            pass
    assert len(tr.spans()) == 3
    assert [s.labels["i"] for s in tr.spans()] == [2, 3, 4]
    assert tr.spans_dropped == 2
    assert tr.to_chrome_trace()["otherData"]["spans_dropped"] == 2


def test_tracer_drained_spans_are_evicted_before_dropping_new_ones():
    """A full buffer evicts the oldest already-persisted spans first,
    so per-epoch persistence (and attribution) keeps flowing on a
    long-lived service; only with nothing drained are NEW spans
    dropped. Either loss is counted."""
    tr = Tracer(max_spans=4)
    for i in range(4):
        with tr.span("epoch", i=i):
            pass
    assert len(tr.drain()) == 4  # "persisted"
    for i in range(3):
        with tr.span("gp_fit", i=i):
            pass
    # the new spans displaced drained ones instead of being dropped
    assert [s.name for s in tr.drain()] == ["gp_fit"] * 3
    assert tr.spans_dropped == 3  # the evicted epochs
    names = [s.name for s in tr.spans()]
    assert names == ["epoch", "gp_fit", "gp_fit", "gp_fit"]


def test_tracer_drain_returns_each_closed_span_once():
    tr = Tracer()
    with tr.span("epoch"):
        pass
    pending = tr.span("gp_fit")
    pending.__enter__()
    first = tr.drain()
    assert [s.name for s in first] == ["epoch"]
    pending.__exit__(None, None, None)
    second = tr.drain()
    assert [s.name for s in second] == ["gp_fit"]
    assert tr.drain() == []
    # draining never shortens the export buffer
    assert len(tr.spans()) == 2


def test_chrome_export_schema_and_labels(tmp_path):
    tr = Tracer(path=str(tmp_path / "t.trace.json"))
    with tr.span("epoch", epoch=1):
        with tr.span("gp_fit", bucket="d4_o2_p16"):
            pass
    path = tr.export()
    trace = load_chrome_trace(path)
    assert validate_chrome_trace(trace) == []
    xs = {e["name"]: e for e in trace["traceEvents"] if e["ph"] == "X"}
    assert xs["gp_fit"]["args"]["bucket"] == "d4_o2_p16"
    assert xs["gp_fit"]["args"]["parent_id"] == xs["epoch"]["args"]["span_id"]
    assert xs["gp_fit"]["dur"] <= xs["epoch"]["dur"]


def test_validate_chrome_trace_catches_breakage():
    good = {"traceEvents": [
        {"ph": "X", "name": "epoch", "pid": 1, "tid": 1, "ts": 0.0,
         "dur": 1.0, "args": {"span_id": 1}},
    ]}
    assert validate_chrome_trace(good) == []
    assert validate_chrome_trace({"nope": 1})
    assert validate_chrome_trace({"traceEvents": [{"ph": "X"}]})
    dangling = {"traceEvents": [
        {"ph": "X", "name": "epoch", "pid": 1, "tid": 1, "ts": 0.0,
         "dur": 1.0, "args": {"span_id": 1, "parent_id": 99}},
    ]}
    assert any("parent_id" in p for p in validate_chrome_trace(dangling))
    negative = {"traceEvents": [
        {"ph": "X", "name": "epoch", "pid": 1, "tid": 1, "ts": -5.0,
         "dur": 1.0, "args": {"span_id": 1}},
    ]}
    assert any("negative" in p for p in validate_chrome_trace(negative))


def test_chrome_export_under_buffer_overflow_stays_schema_valid(tmp_path):
    """ISSUE 12 satellite: fill PAST the bounded span buffer with
    nested spans, so evicted parents leave children behind — the export
    must stay schema-valid (dangling parent links dropped, the orphan
    becomes a root in the exported window) and `spans_dropped` must
    account exactly for the loss."""
    max_spans = 8
    n_epochs = 10  # 10 epochs x 3 spans = 30 spans through an 8-slot buffer
    tr = Tracer(path=str(tmp_path / "overflow.trace.json"), max_spans=max_spans)
    for i in range(n_epochs):
        with tr.span("epoch", epoch=i):
            with tr.span("gp_fit", epoch=i):
                pass
            with tr.span("ea_scan", epoch=i):
                pass
    total = n_epochs * 3
    assert len(tr.spans()) == max_spans
    # exact accounting: every span past the buffer bound was counted
    assert tr.spans_dropped == total - max_spans

    trace = load_chrome_trace(tr.export())
    assert validate_chrome_trace(trace) == []
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == max_spans
    assert trace["otherData"]["spans_dropped"] == total - max_spans
    # the kept window is the run's TAIL, and at least one surviving
    # child kept its (surviving) parent link while the oldest kept
    # child of an evicted epoch became a root rather than dangling
    epochs_seen = {e["args"]["epoch"] for e in xs}
    assert max(epochs_seen) == n_epochs - 1
    by_id = {e["args"]["span_id"]: e for e in xs}
    for e in xs:
        parent = e["args"].get("parent_id")
        if parent is not None:
            assert parent in by_id
    roots = [
        e for e in xs
        if e["name"] != "epoch" and "parent_id" not in e["args"]
    ]
    assert roots, "expected at least one orphaned child re-rooted"


def test_span_scope_disabled_paths_are_noops():
    with span_scope(None, "epoch") as sp:
        assert sp is None
    tel = Telemetry(enabled=False)
    assert tel.tracer is None
    with tel.span("epoch") as sp:
        assert sp is None


# ----------------------------------- staggered service trace (acceptance)


def _submit(svc, *, dim, seed, n_epochs=2, num_generations=4):
    return svc.submit(
        zdt1,
        {f"x{i}": [0.0, 1.0] for i in range(dim)},
        ["f1", "f2"],
        n_epochs=n_epochs,
        population_size=16,
        num_generations=num_generations,
        n_initial=3,
        surrogate_method_kwargs=dict(SMK),
        random_seed=seed,
    )


def test_service_trace_two_buckets_staggered_three_tenants(tmp_path):
    """The acceptance workload: 3 tenants across 2 buckets (two d4
    bucket-mates, one d6), the third submitted AFTER the first step
    (staggered epoch phases). The exported Chrome trace must be
    schema-valid and contain nested epoch -> gp_fit/ea_scan/eval spans
    with per-tenant cost labels, and the attributed
    `tenant_cost_seconds` must sum to the buckets' measured walls
    within 5%."""
    trace_path = str(tmp_path / "svc.trace.json")
    svc = OptimizationService(
        min_bucket=1, telemetry={"trace_path": trace_path}
    )
    _submit(svc, dim=4, seed=1, n_epochs=3)
    _submit(svc, dim=4, seed=2, n_epochs=3)
    svc.step()
    _submit(svc, dim=6, seed=3, n_epochs=2)
    svc.run()

    reg = svc.telemetry.registry
    cost_series = reg.snapshot()["counters"].get("tenant_cost_seconds", {})
    bucket_events = svc.telemetry.log.records(kind="tenant_bucket")
    svc.close()  # exports the trace

    trace = load_chrome_trace(trace_path)
    assert validate_chrome_trace(trace) == []
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    by_name = {}
    by_id = {}
    for e in xs:
        by_name.setdefault(e["name"], []).append(e)
        by_id[e["args"]["span_id"]] = e

    # nested epoch -> fit/ea/eval spans
    for name in ("epoch", "gp_fit", "ea_scan", "eval_drain", "tenant_cost"):
        assert name in by_name, sorted(by_name)
    for name in ("gp_fit", "ea_scan", "eval_drain"):
        for e in by_name[name]:
            parent = by_id[e["args"]["parent_id"]]
            assert parent["name"] == "epoch", (name, parent["name"])

    # tenant_cost spans tile their bucket spans and carry tenant labels
    tenant_labels = set()
    for e in by_name["tenant_cost"]:
        parent = by_id[e["args"]["parent_id"]]
        assert parent["name"] in ("gp_fit", "ea_scan")
        assert e["args"]["phase"] in ("fit", "ea")
        tenant_labels.add(e["args"]["tenant"])
    assert len(tenant_labels) == 3, tenant_labels

    # both buckets ran batched (min_bucket=1): d4 with 2 tenants, d6 solo
    buckets = {ev.fields["bucket"] for ev in bucket_events}
    assert buckets == {"d4_o2_p16", "d6_o2_p16"}, buckets

    # attribution sums to the measured bucket walls (5% acceptance
    # gate; exact by construction, so pin much tighter)
    attributed = sum(cost_series.values())
    bucket_wall = sum(
        ev.fields["fit_s"] + ev.fields["ea_s"] for ev in bucket_events
    )
    assert bucket_wall > 0
    assert attributed == pytest.approx(bucket_wall, rel=0.05)
    assert attributed == pytest.approx(bucket_wall, rel=1e-3)

    # per-tenant labels: one fit/ea/compile series per tenant
    phases_by_tenant = {}
    for lbl in cost_series:
        kv = dict(pair.split("=", 1) for pair in lbl.split(","))
        phases_by_tenant.setdefault(kv["tenant"], set()).add(kv["phase"])
    assert len(phases_by_tenant) == 3
    assert all(
        ph == {"fit", "ea", "compile"} for ph in phases_by_tenant.values()
    )


# -------------------------------------------- compile/retrace observability


def _zdt1_params(opt_id, ngen, **extra):
    params = {
        "opt_id": opt_id,
        "obj_fun": zdt1,
        "jax_objective": True,
        "objective_names": ["f1", "f2"],
        "space": {f"x{i}": [0.0, 1.0] for i in range(6)},
        "problem_parameters": {},
        "problem_ids": set([0, 1]),
        "n_initial": 4,
        "n_epochs": 2,
        "population_size": 16,
        "num_generations": ngen,
        "resample_fraction": 0.5,
        "optimizer_name": "nsga2",
        "surrogate_method_name": "gpr",
        "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 40, "seed": 0},
        "random_seed": 17,
        "telemetry": True,
        "tenant_batching": True,
    }
    params.update(extra)
    return params


def test_bucket_compile_event_and_forced_retrace():
    """First run of a (signature, T) key compiles once (a
    `bucket_compile` event with wall seconds and XLA cost-analysis
    estimates, NO retrace); a second run whose generation budget
    changes the scanned shapes recompiles the SAME key — exactly one
    `bucket_retrace` warning event."""
    tenants._PROGRAM_CACHE.clear()

    dmosopt_tpu.run(_zdt1_params("trace_compile_a", ngen=8), verbose=False)
    tel_a = dopt_dict["trace_compile_a"].telemetry
    compiles = tel_a.log.records(kind="bucket_compile")
    assert len(compiles) == 1, [e.to_dict() for e in compiles]
    ev = compiles[0].fields
    assert ev["compile_s"] > 0 and ev["retrace"] is False
    assert ev["n_tenants"] == 2
    assert ev["bucket"] == "d6_o2_p16"
    assert "nsga2_d6_o2_p16" in ev["signature"]
    if ev["flops"] is not None:  # backend-dependent; CPU reports it
        assert ev["flops"] > 0 and ev["bytes_accessed"] > 0
    assert tel_a.log.records(kind="bucket_retrace") == []
    assert tel_a.registry.counter_value(
        "tenant_bucket_compiles_total", bucket="d6_o2_p16"
    ) == 1.0

    # forced recompile: same bucket signature and tenant count, new
    # generation budget -> new scanned shapes for the cached key
    dmosopt_tpu.run(_zdt1_params("trace_compile_b", ngen=6), verbose=False)
    tel_b = dopt_dict["trace_compile_b"].telemetry
    retraces = tel_b.log.records(kind="bucket_retrace")
    assert len(retraces) == 1, [e.to_dict() for e in retraces]
    assert retraces[0].fields["n_shapes"] == 2
    assert tel_b.registry.counter_value(
        "tenant_bucket_retraces_total", bucket="d6_o2_p16"
    ) == 1.0


# ----------------------------------------------- per-epoch persistence


def test_spans_persisted_per_epoch_beside_summaries(tmp_path):
    from dmosopt_tpu.storage import load_spans_from_h5, load_telemetry_from_h5

    fp = str(tmp_path / "spans.h5")
    dmosopt_tpu.run(
        _zdt1_params(
            "trace_persist", ngen=4, file_path=fp, save=True,
            problem_ids=None, n_epochs=2,
        ),
        verbose=False,
    )
    summaries = load_telemetry_from_h5(fp, "trace_persist")
    spans = load_spans_from_h5(fp, "trace_persist")
    assert sorted(spans) == sorted(summaries)
    for epoch, span_list in spans.items():
        names = {s["name"] for s in span_list}
        assert "epoch" in names and "gp_fit" in names, (epoch, names)
        for s in span_list:
            assert s["duration_s"] is not None and s["duration_s"] >= 0
    # round-trips as plain JSON
    json.dumps(spans)


# ------------------------------------------------- span-name lint hook


def test_span_names_are_cataloged_and_scanner_sees_all_forms():
    """The metrics-catalog rule scans `.span(`/`.record_span(` attribute
    calls and `span_scope(tel, 'name')` helper calls; every span name
    the package opens must be backticked in docs/observability.md."""
    import ast
    from pathlib import Path

    from tools.graftlint.rules.metrics_catalog import (
        catalog_names,
        spans_in_tree,
    )

    repo = Path(dmosopt_tpu.__file__).resolve().parent.parent
    catalog = catalog_names(repo / "docs" / "observability.md")
    opened = {}
    for path in sorted((repo / "dmosopt_tpu").rglob("*.py")):
        tree = ast.parse(path.read_text())
        for name, _ in spans_in_tree(tree):
            opened.setdefault(name, []).append(path.name)
    # the taxonomy's core spans are all actually opened somewhere
    assert {
        "epoch", "gp_fit", "ea_scan", "resample", "eval_dispatch",
        "eval_drain", "h5_write", "tenant_cost", "admit", "fold",
    } <= set(opened), sorted(opened)
    missing = {n: f for n, f in opened.items() if n not in catalog}
    assert not missing, f"uncataloged spans: {missing}"

    # scanner fixtures: all three emission forms, plus a non-emission
    # `.span(` lookalike with a non-literal name (ignored)
    fixture = ast.parse(
        "tel.span('alpha')\n"
        "tracer.record_span('beta', 0, 1)\n"
        "span_scope(tel, 'gamma')\n"
        "telemetry.span_scope(tel, 'delta')\n"
        "tel.span(name)\n"
    )
    names = sorted(n for n, _ in spans_in_tree(fixture))
    assert names == ["alpha", "beta", "delta", "gamma"]
