"""Constrained sampling tests (reference semantics:
dmosopt/constrained_sampling.py, demo dmosopt/test_constrained.py)."""

import numpy as np
import pytest

from dmosopt_tpu.constrained_sampling import (
    BoundExpression,
    ParamSpacePoints,
    tokenize,
)


def test_expression_parser():
    env = {"a": np.array([2.0, 4.0]), "b": np.array([10.0, 20.0])}
    assert BoundExpression("1 + 2 * 3").evaluate({}) == pytest.approx(7.0)
    assert BoundExpression("2 ** 3").evaluate({}) == pytest.approx(8.0)
    assert BoundExpression("(1 + 2) * 3").evaluate({}) == pytest.approx(9.0)
    np.testing.assert_allclose(
        BoundExpression("a * 2 + 1").evaluate(env), [5.0, 9.0]
    )
    np.testing.assert_allclose(
        BoundExpression("a max 3").evaluate(env), [3.0, 4.0]
    )
    np.testing.assert_allclose(
        BoundExpression("b min 15").evaluate(env), [10.0, 15.0]
    )
    with pytest.raises(KeyError):
        BoundExpression("unknown + 1").evaluate(env)
    with pytest.raises(ValueError):
        tokenize("a $ b")


def test_reference_demo_space():
    """The reference's own demo configuration (test_constrained.py:5-26)."""
    space = {
        "gc": [0.01, 50],
        "soma_gnabar": [0.1, 50],
        "soma_gl": [0.001, 0.6],
        "soma_gkdrbar": {
            "abs": [0.0, 60.0],
            "lb": [("gc", "+ 5")],
            "ub": [("gc", "+ 10")],
            "method": ("uniform", None, None),
        },
        "soma_gkahpbar": {
            "abs": [0.001, 0.6],
            "method": ("normal", 0, 200),
        },
    }
    ps = ParamSpacePoints(50, space, seed=1)
    vals = ps.as_dict()
    gc = vals["gc"]
    gkdr = vals["soma_gkdrbar"]
    assert np.all(gkdr >= gc + 5 - 1e-9)
    assert np.all(gkdr <= gc + 10 + 1e-9)
    gkahp = vals["soma_gkahpbar"]
    assert np.all((gkahp >= 0.001) & (gkahp <= 0.6))
    assert np.all(np.isfinite(ps.values))


def test_chained_dependency_resolution():
    space = {
        "a": [0.0, 1.0],
        "b": {"abs": [0.0, 10.0], "lb": [("a", "+ 1")], "ub": [("a", "+ 2")],
              "method": ("uniform",)},
        "c": {"abs": [0.0, 20.0], "lb": [("b", "* 2")], "ub": [("b", "* 3")],
              "method": ("percentile", 0.5)},
    }
    ps = ParamSpacePoints(20, space, seed=2)
    v = ps.as_dict()
    assert np.all(v["b"] >= v["a"] + 1 - 1e-9)
    assert np.all(v["c"] >= 2 * v["b"] - 1e-9)
    assert np.all(v["c"] <= 3 * v["b"] + 1e-9)
    # percentile method is deterministic mid-range
    np.testing.assert_allclose(v["c"], 2.5 * v["b"], rtol=1e-6)


def test_circular_dependency_detected():
    space = {
        "a": {"abs": [0, 1], "lb": [("b", "* 1")], "method": ("uniform",)},
        "b": {"abs": [0, 1], "lb": [("a", "* 1")], "method": ("uniform",)},
    }
    with pytest.raises(ValueError, match="circular"):
        ParamSpacePoints(5, space, seed=0)


def test_overconstrained_falls_back_to_abs():
    space = {
        "a": [5.0, 6.0],
        "b": {"abs": [0.0, 1.0], "lb": [("a", "+ 1")], "ub": [("a", "+ 2")],
              "method": ("uniform",)},
    }
    # lb (6..8) clipped into abs [0,1] collapses -> falls back to abs range
    ps = ParamSpacePoints(10, space, seed=3)
    b = ps.as_dict()["b"]
    assert np.all((b >= 0.0) & (b <= 1.0))


def test_evolutionary_children():
    rng = np.random.default_rng(0)
    parent_vals = rng.uniform(0.2, 0.8, size=(16, 2))
    space = {"x": [0.0, 1.0], "y": [0.0, 1.0]}
    ps = ParamSpacePoints(
        16, space, seed=4,
        parents={
            "params": np.array(["x", "y"]),
            "values": parent_vals,
            "crossover_rate": 0.9,
        },
    )
    X = ps.values
    assert X.shape == (16, 2)
    assert np.all((X >= 0.0) & (X <= 1.0))
