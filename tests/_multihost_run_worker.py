"""Worker for the multi-host END-TO-END `run()` test: one JAX process of
a loopback cluster driving the PUBLIC `dmosopt_tpu.run()` entry point —
full epoch loop, surrogate fits, archive updates, and rank-0-only H5
checkpoint writes — over a mesh spanning every process's devices, so the
run's collectives cross the process boundary (the loopback equivalent of
the reference's `mpirun -n K` full runs, dmosopt.py:2518-2536).

Every rank saves its final best set to `<out_dir>/best_rank<r>.npz`; the
launching test compares them against a same-seed single-process run.

Usage: python _multihost_run_worker.py <coordinator> <num_procs> <proc_id> <out_dir>
"""

import os
import sys


def main():
    coordinator, num_procs, proc_id, out_dir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)

    import jax

    jax.config.update("jax_platforms", "cpu")

    from dmosopt_tpu.parallel.mesh import create_mesh, initialize_distributed

    rank = initialize_distributed(
        coordinator_address=coordinator,
        num_processes=num_procs,
        process_id=proc_id,
    )
    n_global = jax.device_count()

    import numpy as np

    import dmosopt_tpu
    from dmosopt_tpu.benchmarks.zdt import zdt1

    mesh = create_mesh(axis_names=("pop",))  # spans ALL processes' devices
    assert mesh.devices.size == n_global

    h5_path = os.path.join(out_dir, "multihost_run.h5")
    params = multihost_run_params(zdt1, mesh=mesh, file_path=h5_path)
    best = dmosopt_tpu.run(params, verbose=False)
    prms, lres = best
    best_x = np.column_stack([v for _, v in prms])
    best_y = np.column_stack([v for _, v in lres])

    np.savez(
        os.path.join(out_dir, f"best_rank{rank}.npz"), x=best_x, y=best_y
    )
    # only the primary process may have created/written the checkpoint
    wrote_h5 = os.path.isfile(h5_path)
    print(
        f"MULTIHOST_RUN_OK rank={rank} global_devices={n_global} "
        f"n_best={best_y.shape[0]} h5={wrote_h5}",
        flush=True,
    )


def multihost_run_params(obj_fun, mesh=None, file_path=None):
    """One config, shared verbatim by the cluster ranks and the
    single-process comparator so the equivalence check compares exactly
    the same run."""
    params = {
        "opt_id": "multihost_run",
        "obj_fun": obj_fun,
        "jax_objective": True,
        "objective_names": ["f1", "f2"],
        "space": {f"x{i}": [0.0, 1.0] for i in range(6)},
        "problem_parameters": {},
        "n_initial": 4,
        "n_epochs": 2,
        "population_size": 16,
        "num_generations": 8,
        "resample_fraction": 0.5,
        "optimizer_name": "nsga2",
        "surrogate_method_name": "gpr",
        "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 20, "seed": 0},
        "random_seed": 21,
    }
    if mesh is not None:
        params["mesh"] = mesh
    if file_path is not None:
        params["file_path"] = file_path
        params["save"] = True
    return params


if __name__ == "__main__":
    main()
