"""Unit tests for the process-group-safe subprocess helpers shared by
the repo-root orchestrators (bench.py, __graft_entry__.py). The
round-4 evidence artifact died on exactly the hazard these guard: a
killed child whose grandchild holds the stdout pipe and blocks the
post-kill communicate() forever."""

import os
import subprocess
import sys
import time

# conftest.py puts the repo root on sys.path
from _procutil import axon_free_pythonpath, communicate_bounded, run_probe


def test_communicate_bounded_normal_exit():
    proc = subprocess.Popen(
        [sys.executable, "-c", "print('hello')"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True,
    )
    out, err, rc = communicate_bounded(proc, 30)
    assert rc == 0 and out.strip() == "hello"


def test_communicate_bounded_kills_pipe_holding_grandchild():
    """The round-4 failure mode: the child spawns a grandchild that
    inherits the stdout pipe and sleeps, then the child itself hangs.
    communicate_bounded must return 'timeout' promptly (process-group
    kill takes the grandchild down too) instead of blocking on the
    still-open pipe."""
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import subprocess, sys, time\n"
         "subprocess.Popen([sys.executable, '-c', 'import time; time.sleep(120)'])\n"
         "time.sleep(120)"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True,
    )
    t0 = time.time()
    _, _, rc = communicate_bounded(proc, 2)
    wall = time.time() - t0
    assert rc == "timeout"
    assert wall < 15, f"bounded communicate took {wall:.0f}s"
    assert proc.returncode is not None  # reaped, no zombie


def test_run_probe_tags_and_times_out():
    out, rc = run_probe("import os; print('TAG=' + os.environ['_DMOSOPT_TPU_PROBE'])", 30)
    assert rc == 0 and "TAG=1" in out
    t0 = time.time()
    _, rc = run_probe("import time; time.sleep(60)", 2)
    assert rc == "timeout"
    assert time.time() - t0 < 15


def test_axon_free_pythonpath_strips_and_prepends():
    joined = os.pathsep.join(["/x/lib", "/y/fakeaxon_site", "/z"])
    out = axon_free_pythonpath("/repo", joined)
    parts = out.split(os.pathsep)
    assert parts[0] == "/repo"
    assert "/y/fakeaxon_site" not in parts
    assert "/x/lib" in parts and "/z" in parts
