"""Deep-kernel GP surrogate tests (capability analog of the reference
deep GP / DSPP models, model_gpytorch.py:991-1620) and early stopping."""

import numpy as np
import pytest

from dmosopt_tpu.models.deep_gp import MDGP_Matern, MDSPP_Matern
from dmosopt_tpu.models.early_stopping import (
    AdaptiveEarlyStopping,
    EarlyStoppingConfig,
    ModelType,
    analyze_loss_trajectory,
    suggest_hyperparameters,
)


def _nonstationary_data(n=250, seed=0):
    """Frequency doubles across the domain: stationary GPs struggle."""
    rng = np.random.default_rng(seed)
    X = rng.uniform(size=(n, 2))
    t = X[:, 0]
    y0 = np.sin(2 * np.pi * t * (1 + 3 * t))
    y1 = np.cos(4 * np.pi * X[:, 1] ** 2)
    Y = np.column_stack([y0, y1]) + 0.01 * rng.normal(size=(n, 2))
    return X, Y


@pytest.mark.parametrize("cls", [MDGP_Matern, MDSPP_Matern])
def test_deep_gp_fits_nonstationary(cls):
    X, Y = _nonstationary_data()
    m = cls(X, Y, 2, 2, np.zeros(2), np.ones(2), seed=0, n_iter=300)
    mean, var = m.predict(X[:100])
    mean = np.asarray(mean)
    assert mean.shape == (100, 2)
    assert np.all(np.asarray(var) > 0)
    resid = np.mean((mean - Y[:100]) ** 2, axis=0)
    assert np.all(resid < 0.3 * np.var(Y, axis=0)), resid


def test_deep_gp_in_registry():
    from dmosopt_tpu.config import default_surrogate_methods, resolve

    assert resolve("mdgp", default_surrogate_methods) is MDGP_Matern
    assert resolve("mdspp", default_surrogate_methods) is MDSPP_Matern


def test_early_stopping_converged_loss():
    cfg = EarlyStoppingConfig(
        min_iterations=10, window_size=20, patience=2,
        threshold_pct=0.5, absolute_tolerance=1e-3,
        warmup_iterations=10,  # checks are gated on max(min_iter, warmup)
    )
    stopper = AdaptiveEarlyStopping(cfg)
    flat = np.full(100, 1.2345)
    stopped = False
    for it in range(50, 100):
        stop, reason = stopper.should_stop(it, flat[:it])
        if stop:
            stopped = True
            assert reason
            break
    assert stopped


def test_early_stopping_keeps_running_on_progress():
    cfg = EarlyStoppingConfig(
        min_iterations=10, window_size=20, patience=2, warmup_iterations=10
    )
    stopper = AdaptiveEarlyStopping(cfg)
    falling = 100.0 * np.exp(-0.05 * np.arange(200))
    for it in range(30, 100):
        stop, _ = stopper.should_stop(it, falling[:it])
        assert not stop


def test_trajectory_analysis_and_suggestions():
    falling = 100.0 * np.exp(-0.05 * np.arange(400))
    stats = analyze_loss_trajectory(falling)
    assert stats["monotonic_decrease"]
    assert stats["final_loss"] < stats["mean_loss"]

    osc = 10 + np.sin(np.arange(300))
    stats_osc = analyze_loss_trajectory(osc)
    rec = suggest_hyperparameters(stats_osc, ModelType.DEEP_GP)
    assert rec.get("learning_rate") == "decrease"

    assert (
        EarlyStoppingConfig.for_model_type(ModelType.EXACT_GP).window_size == 200
    )
