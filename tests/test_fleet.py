"""Fleet telemetry rollup tests: a round trip through the real storage
API — N stores (multi-run results stores + a service checkpoint) are
written with known telemetry/span/alert/refit content, scanned, and
rolled up into per-signature distributions that must reproduce each
run's per-problem summaries and match hand-computed hyperparameter
statistics (docs/observability.md "Fleet telemetry rollup")."""

import json
import math

import numpy as np
import pytest

h5py = pytest.importorskip("h5py")

from dmosopt_tpu.datatypes import ParameterSpace  # noqa: E402
from dmosopt_tpu.storage import (  # noqa: E402
    init_h5,
    save_alerts_to_h5,
    save_front_to_h5,
    save_refit_state_to_h5,
    save_service_checkpoint_to_h5,
    save_spans_to_h5,
    save_telemetry_to_h5,
)
from dmosopt_tpu.telemetry.fleet import (  # noqa: E402
    fleet_summary,
    problem_signature,
    rollup,
    scan_store,
    write_fleet_summary,
)


def _space(dim):
    return ParameterSpace.from_dict(
        {f"x{i}": [0.0, 1.0] for i in range(dim)}
    )


def _write_run(
    path, opt_id, dim, *, amp, ls, noise, n_train, epochs, fronts=(),
    alerts=None,
):
    space = _space(dim)
    init_h5(
        opt_id, [0], False, space, space.parameter_names, ["f1", "f2"],
        None, None, None, {"kind": "fleet-test"}, 42, path,
    )
    for e in range(epochs):
        save_telemetry_to_h5(
            opt_id, e,
            {
                "epoch": e, "wall_s": 2.0 + e,
                "phases": {"train": 1.0, "optimize": 0.5},
                "n_generations": 10, "gens_per_sec": 20.0,
                "fit_n_steps": 30, "n_train": 8 * (e + 1),
                "eval": {"eval_n": 4, "eval_sum": 0.4},
            },
            path,
        )
    save_spans_to_h5(
        opt_id, 0,
        [
            {"name": "gp_fit", "duration_s": 0.5},
            {"name": "ea_scan", "duration_s": 0.25},
            {"name": "gp_fit", "duration_s": 0.75},
        ],
        path,
    )
    for a in alerts or []:
        save_alerts_to_h5(opt_id, a.pop("epoch"), [a], path)
    save_refit_state_to_h5(
        opt_id, 0,
        {
            "amp": amp, "ls": ls, "noise": noise,
            "eff_noise": noise, "n_train": n_train,
            "stable": 1, "warm_wins": 2, "fits_since_audit": 0,
            "n_iter_max": 100,
        },
        path,
    )
    for e in fronts:
        save_front_to_h5(
            opt_id, e, space.parameter_names, ["f1", "f2"],
            np.zeros((3, dim)), np.zeros((3, 2)), path,
        )


def _write_checkpoint(path, opt_id, dim, *, amp, ls, noise, n_train):
    payload = {
        "service": {"ts": 0.0, "steps": 4, "min_bucket": 2},
        "tenants": {
            "0": {
                "config": {
                    "space": {f"x{i}": [0.0, 1.0] for i in range(dim)},
                    "objective_names": ["f1", "f2"],
                    "n_epochs": 5,
                },
                "state": {
                    "opt_id": opt_id, "tenant_id": 0, "epochs_run": 3,
                    "n_epochs": 5, "epoch_index": 2, "optimizer_draws": 3,
                    "rng_state": {}, "quarantined": 2, "eval_failures": 1,
                    "refit": {
                        "amp": amp, "ls": ls, "noise": noise,
                        "n_train": n_train,
                    },
                },
                "arrays": {"x": np.zeros((4, dim))},
            }
        },
    }
    save_service_checkpoint_to_h5(payload, path)


def test_fleet_round_trip_over_two_stores(tmp_path):
    a = str(tmp_path / "run_a.h5")
    b = str(tmp_path / "run_b.h5")
    ckpt = str(tmp_path / "svc.h5")

    _write_run(
        a, "run_a", 4, amp=[1.0, 2.0], ls=[[0.5, 0.5, 1.0, 1.0]] * 2,
        noise=[0.01, 0.02], n_train=24, epochs=2, fronts=(1, 2),
        alerts=[
            {"epoch": 1, "rule": "quarantine_spike", "severity": "warning",
             "state": "firing", "value": 2.0, "threshold": 0.0, "step": 1},
        ],
    )
    # a second opt_id of a DIFFERENT signature in the same store
    _write_run(
        a, "run_c", 3, amp=[4.0], ls=[[2.0, 2.0, 2.0]], noise=[0.1],
        n_train=12, epochs=1,
    )
    _write_run(
        b, "run_b", 4, amp=[3.0, 4.0], ls=[[1.5, 1.5, 2.0, 2.0]] * 2,
        noise=[0.03, 0.04], n_train=40, epochs=3,
    )
    _write_checkpoint(
        ckpt, "tenant_x", 4, amp=[5.0, 6.0], ls=[[3.0, 3.0, 4.0, 4.0]] * 2,
        noise=[0.05, 0.06], n_train=16,
    )

    summary = fleet_summary([a, b, ckpt])
    assert summary["format"] == "dmosopt_tpu.fleet_summary"
    assert summary["n_stores"] == 3 and summary["n_runs"] == 4

    runs = {r["opt_id"]: r for r in summary["runs"]}
    assert set(runs) == {"run_a", "run_b", "run_c", "tenant_x"}

    # --- per-run records reproduce each run's per-problem summaries
    ra = runs["run_a"]
    assert ra["signature"] == "d4_o2" == problem_signature(4, 2)
    assert ra["telemetry"]["epochs"] == 2
    assert ra["telemetry"]["wall_s_total"] == pytest.approx(2.0 + 3.0)
    assert ra["telemetry"]["gens_total"] == 20
    assert ra["telemetry"]["fit_steps_total"] == 60
    assert ra["telemetry"]["evals_total"] == 8
    assert ra["telemetry"]["gens_per_sec_mean"] == pytest.approx(20.0)
    assert ra["spans"] == {
        "gp_fit": {"count": 2, "seconds": 1.25},
        "ea_scan": {"count": 1, "seconds": 0.25},
    }
    assert ra["alerts"] == {"quarantine_spike": 1}
    assert ra["refit"]["0"]["amp"] == [1.0, 2.0]
    assert ra["fronts"] == {
        "n_epochs": 2, "first_epoch": 1, "last_epoch": 2,
    }
    assert ra["epochs_to_front"] == 2

    rb = runs["run_b"]
    assert rb["telemetry"]["epochs"] == 3
    assert rb["telemetry"]["fit_steps_total"] == 90

    rx = runs["tenant_x"]
    assert rx["kind"] == "service_checkpoint"
    assert rx["signature"] == "d4_o2"
    assert rx["telemetry"]["epochs"] == 3
    assert rx["quarantined_total"] == 2
    # review fix: the checkpoint's archive rows + quarantined rows are
    # the evaluation denominator, so quarantine_rate is a true rate
    assert rx["telemetry"]["evals_total"] == 4 + 2
    assert rx["refit"]["0"]["noise"] == [0.05, 0.06]

    rc = runs["run_c"]
    assert rc["signature"] == "d3_o2"

    # --- per-signature hyperparameter distributions, hand-computed
    sig = summary["signatures"]["d4_o2"]
    assert sig["n_runs"] == 3
    amps = [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]
    amp_dist = sig["hyperparameters"]["amp"]["linear"]
    assert amp_dist["count"] == 6
    assert amp_dist["mean"] == pytest.approx(np.mean(amps))
    assert amp_dist["median"] == pytest.approx(np.median(amps))
    assert amp_dist["min"] == 1.0 and amp_dist["max"] == 6.0
    amp_log = sig["hyperparameters"]["amp"]["log10"]
    assert amp_log["mean"] == pytest.approx(
        np.mean([math.log10(v) for v in amps])
    )
    ls_dist = sig["hyperparameters"]["lengthscale"]["linear"]
    assert ls_dist["count"] == 3 * 8  # three runs x (2 obj x 4 dims)
    noise_dist = sig["hyperparameters"]["noise"]["linear"]
    assert noise_dist["min"] == pytest.approx(0.01)
    assert noise_dist["max"] == pytest.approx(0.06)
    assert sig["n_train"]["count"] == 3
    assert sig["n_train"]["max"] == 40.0
    assert sig["epochs"]["mean"] == pytest.approx((2 + 3 + 3) / 3)
    assert sig["epochs_to_front"]["mean"] == pytest.approx(2.0)
    assert sig["alert_firings"] == {"quarantine_spike": 1}
    assert sig["quarantine_rate"]["mean"] == pytest.approx(2.0 / 6.0)
    assert sig["quarantine_rate"]["count"] == 1

    other = summary["signatures"]["d3_o2"]
    assert other["n_runs"] == 1
    assert other["hyperparameters"]["amp"]["linear"]["mean"] == 4.0

    # --- the written JSON round-trips byte-for-byte as JSON
    out = str(tmp_path / "fleet.json")
    written = write_fleet_summary([a, b, ckpt], out)
    with open(out) as fh:
        loaded = json.load(fh)
    assert loaded == json.loads(
        json.dumps(written, default=lambda o: o)
    )
    assert loaded["signatures"]["d4_o2"]["hyperparameters"]["amp"][
        "linear"
    ]["count"] == 6


def test_scan_store_tolerates_runs_without_telemetry(tmp_path):
    path = str(tmp_path / "bare.h5")
    space = _space(2)
    init_h5(
        "bare", [0], False, space, space.parameter_names, ["f1", "f2"],
        None, None, None, None, 1, path,
    )
    records = scan_store(path)
    assert len(records) == 1
    rec = records[0]
    assert rec["telemetry"]["epochs"] == 0
    assert rec["spans"] == {} and rec["alerts"] == {} and rec["refit"] == {}
    # rolls up without error; no hyperparameter data -> None dists
    summary = rollup(records)
    hp = summary["signatures"]["d2_o2"]["hyperparameters"]
    assert hp["amp"]["linear"] is None and hp["amp"]["log10"] is None


def test_fleet_summary_missing_store_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        fleet_summary([str(tmp_path / "nope.h5")])


def test_fleet_cli_table_and_json(tmp_path):
    click = pytest.importorskip("click")  # noqa: F841
    from click.testing import CliRunner

    from dmosopt_tpu.cli import fleet as fleet_cmd

    a = str(tmp_path / "a.h5")
    b = str(tmp_path / "b.h5")
    _write_run(
        a, "cli_a", 4, amp=[1.0], ls=[[1.0] * 4], noise=[0.01],
        n_train=10, epochs=2,
    )
    _write_run(
        b, "cli_b", 4, amp=[2.0], ls=[[2.0] * 4], noise=[0.02],
        n_train=20, epochs=2,
    )
    out = str(tmp_path / "fleet.json")
    result = CliRunner().invoke(
        fleet_cmd, ["-p", a, "-p", b, "-o", out]
    )
    assert result.exit_code == 0, result.output
    assert "2 run(s) across 2 store(s)" in result.output
    assert "signature d4_o2" in result.output
    assert "lengthscale" in result.output
    with open(out) as fh:
        data = json.load(fh)
    assert data["signatures"]["d4_o2"]["n_runs"] == 2

    as_json = CliRunner().invoke(
        fleet_cmd, ["-p", a, "-p", b, "--as-json"]
    )
    assert as_json.exit_code == 0
    assert json.loads(as_json.output)["n_runs"] == 2

    bad_sig = CliRunner().invoke(
        fleet_cmd, ["-p", a, "-s", "d9_o9"]
    )
    assert bad_sig.exit_code != 0
    assert "d9_o9" in bad_sig.output

    filtered = CliRunner().invoke(
        fleet_cmd, ["-p", a, "-p", b, "-s", "d4_o2"]
    )
    assert filtered.exit_code == 0, filtered.output
