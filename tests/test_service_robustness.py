"""Fault tolerance of the ask/tell service (ISSUE 10).

The chaos acceptance contract: under a seeded fault plan, failing
tenants degrade and retire PER POLICY (never an exception out of
`step()`), surviving bucket-mates' trajectories stay **bitwise-equal**
to a fault-free run, non-finite objective rows are quarantined before
they can poison a GP fit, and a kill -9'd service resumes from its
epoch-boundary checkpoint seeded-trajectory-equivalent to an
uninterrupted run. `make chaos` runs the larger 2-bucket staggered
version of the same scenario (tools/chaos_smoke.py).
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from dmosopt_tpu.service import EvalPolicy, OptimizationService
from dmosopt_tpu.testing.faults import FaultPlan, FaultyEvaluator

SMK = {"n_starts": 2, "n_iter": 20, "seed": 0}
POLICY = dict(
    timeout=0.15, retries=0, on_eval_failure="quorum",
    min_success_fraction=0.5, max_failed_epochs=2,
)


def _host_obj(dim):
    def f(pp):
        x = np.asarray(
            [pp[f"x{i}"] for i in range(dim)], dtype=np.float32
        ).astype(np.float64)
        f1 = x[0]
        g = 1.0 + 9.0 * np.mean(x[1:])
        f2 = g * (1.0 - np.sqrt(f1 / g))
        return np.asarray([f1, f2], dtype=np.float64)

    return f


def _submit(svc, name, dim, seed, n_epochs=2, **kw):
    return svc.submit(
        _host_obj(dim),
        {f"x{i}": [0.0, 1.0] for i in range(dim)},
        ["f1", "f2"],
        opt_id=name, jax_objective=False, n_epochs=n_epochs,
        population_size=16, num_generations=4, n_initial=3,
        surrogate_method_kwargs=dict(SMK), random_seed=seed, **kw,
    )


def _fronts(handle):
    return [(u.epoch, u.x, u.y) for u in handle.updates()]


def _assert_fronts_equal(got, want, who=""):
    assert [e for e, _, _ in got] == [e for e, _, _ in want], who
    for (e, xg, yg), (_, xw, yw) in zip(got, want):
        np.testing.assert_array_equal(xg, xw, err_msg=f"{who} epoch {e}")
        np.testing.assert_array_equal(yg, yw, err_msg=f"{who} epoch {e}")


def test_eval_policy_validation():
    with pytest.raises(ValueError, match="on_eval_failure"):
        EvalPolicy(on_eval_failure="panic")
    with pytest.raises(ValueError, match="min_success_fraction"):
        EvalPolicy(min_success_fraction=0.0)
    with pytest.raises(ValueError, match="max_failed_epochs"):
        EvalPolicy(max_failed_epochs=0)
    with pytest.raises(ValueError, match="timeout"):
        EvalPolicy(timeout=-1.0)
    with pytest.raises(TypeError):
        EvalPolicy.from_spec(3)
    assert EvalPolicy.from_spec(None) is None
    assert EvalPolicy.from_spec({"retries": 2}).retries == 2
    p = EvalPolicy()
    assert EvalPolicy.from_spec(p) is p


def test_policy_without_faults_is_bitwise_noop():
    """The frozen-default pin: threading a full EvalPolicy (timeout,
    retries, backoff, quorum accounting) through a HEALTHY run changes
    nothing — streamed fronts bitwise-match the no-policy service."""

    def run(policy):
        svc = OptimizationService(telemetry=False, eval_policy=policy)
        handles = {
            "a": _submit(svc, "a", 4, seed=1),
            "b": _submit(svc, "b", 4, seed=2),
        }
        svc.run()
        out = {k: _fronts(h) for k, h in handles.items()}
        svc.close()
        return out

    base = run(None)
    poli = run(
        EvalPolicy(
            timeout=30.0, retries=2, backoff=0.01,
            on_eval_failure="quorum", min_success_fraction=0.5,
        )
    )
    for k in base:
        _assert_fronts_equal(poli[k], base[k], who=k)


def test_chaos_survivors_bitwise_invariant(monkeypatch):
    """The acceptance invariant: one of three bucket-mates' objectives
    raises, another hangs past the eval timeout — both degrade and are
    retired per policy with causes on their handles, while the
    survivor's fronts stay bitwise-equal to a fault-free run. Driven
    through the DMOSOPT_FAULT_PLAN env gate, exactly as `make chaos`
    drives the full service."""

    def run():
        svc = OptimizationService(telemetry=True, eval_policy=dict(POLICY))
        handles = {
            name: _submit(svc, name, 4, seed=30 + i, n_epochs=2)
            for i, name in enumerate(("good", "boom", "wedge"))
        }
        svc.run()
        out = {k: _fronts(h) for k, h in handles.items()}
        snap = svc.introspect()
        reg = svc.telemetry.registry
        svc.close()
        return out, handles, snap, reg

    monkeypatch.delenv("DMOSOPT_FAULT_PLAN", raising=False)
    ref, _, ref_snap, _ = run()
    assert ref_snap["tenant_counts"] == {"completed": 3}

    monkeypatch.setenv(
        "DMOSOPT_FAULT_PLAN",
        json.dumps(
            {
                "seed": 7,
                "rules": [
                    {"kind": "raise", "target": "boom"},
                    {"kind": "hang", "target": "wedge", "delay_s": 0.6},
                ],
            }
        ),
    )
    got, handles, snap, reg = run()

    # failing tenants: degraded then retired per policy, causes on the
    # handles, never an exception out of step()
    assert snap["tenant_counts"] == {"completed": 1, "degraded": 2}
    for bad in ("boom", "wedge"):
        h = handles[bad]
        assert h.done and h.error is not None
        with pytest.raises(RuntimeError, match="sub-quorum"):
            h.result()
    by_id = {t["opt_id"]: t for t in snap["tenants"]}
    for bad in ("boom", "wedge"):
        t = by_id[bad]
        assert t["state"] == "degraded"
        assert t["degraded"] is True
        assert t["eval_failures_total"] > 0
        assert t["failed_epochs_consecutive"] == POLICY["max_failed_epochs"]
        assert t["last_success_fraction"] == 0.0

    # the survivor: bitwise-equal trajectory, completed on schedule
    assert handles["good"].error is None and handles["good"].done
    _assert_fronts_equal(got["good"], ref["good"], who="good")

    # accounting: per-tenant failure counters and real timeouts
    assert reg.counter_value("tenant_eval_failures_total", tenant="boom") > 0
    assert reg.counter_value("tenant_eval_failures_total", tenant="wedge") > 0
    assert reg.counter_value("eval_timeouts_total") > 0
    assert reg.counter_value("tenants_failed_total") == 2.0


def test_nan_quarantine_skip_policy():
    """Non-finite objective rows returned "successfully" are diverted
    into the per-tenant quarantine — never the archive, never the GP
    training set — and under the `skip` policy the tenant completes,
    degraded-but-alive, with the quarantine counted."""
    plan = FaultPlan([{"kind": "nan", "target": "nanny", "p": 0.5}], seed=3)
    svc = OptimizationService(telemetry=True)
    h = _submit(
        svc, "nanny", 3, seed=40, n_epochs=2,
        eval_policy=EvalPolicy(on_eval_failure="skip", max_failed_epochs=3),
    )
    # wrap the tenant's own evaluator with the public wrapper API (the
    # env gate does exactly this internally)
    tenant = svc._pending[0]
    tenant.evaluator = FaultyEvaluator(tenant.evaluator, plan, "nanny")
    svc.run()

    assert h.done and h.error is None
    front = h.result()
    assert np.all(np.isfinite(front.y))
    snap = svc.introspect()
    t = {x["opt_id"]: x for x in snap["tenants"]}["nanny"]
    assert t["points_quarantined_total"] > 0
    assert t["state"] == "completed"
    reg = svc.telemetry.registry
    assert (
        reg.counter_value("tenant_points_quarantined_total", tenant="nanny")
        == t["points_quarantined_total"]
    )
    svc.close()
    assert plan.fires(kind="nan") > 0


def test_all_nan_initial_design_retires_not_hangs():
    """Review regression: an objective that returns NaN for EVERY call
    produces no EvalFailures (the calls 'succeed') and no archive —
    the quarantined requests must be re-issued and the tenant retired
    at max_failed_epochs, never left as a zombie that spins run()."""
    plan = FaultPlan([{"kind": "nan", "target": "void"}])
    svc = OptimizationService(telemetry=True)
    h = _submit(
        svc, "void", 3, seed=45, n_epochs=2,
        eval_policy=EvalPolicy(on_eval_failure="skip", max_failed_epochs=2),
    )
    tenant = svc._pending[0]
    tenant.evaluator = FaultyEvaluator(tenant.evaluator, plan, "void")
    steps = svc.run(max_steps=10)  # bounded: must terminate well before
    assert steps < 10
    assert h.done and h.error is not None
    with pytest.raises(RuntimeError, match="sub-quorum"):
        h.result()
    snap = svc.introspect()
    assert snap["tenant_counts"] == {"degraded": 1}
    reg = svc.telemetry.registry
    assert reg.counter_value(
        "tenant_points_quarantined_total", tenant="void"
    ) > 0
    svc.close()


def test_strategy_quarantine_unit():
    """`complete_request` level: NaN/inf rows land in `quarantined`
    (bounded window + exact cumulative count), finite rows in
    `completed`; the archive fold never sees a quarantined row."""
    from dmosopt_tpu.datatypes import OptProblem, ParameterSpace
    from dmosopt_tpu.strategy import DistOptStrategy

    space = ParameterSpace.from_dict({"x0": [0.0, 1.0], "x1": [0.0, 1.0]})
    prob = OptProblem(
        space.parameter_names, ["f1", "f2"], None, lambda f: f, None,
        space, lambda sv: None,
    )
    s = DistOptStrategy(
        prob, n_initial=2, population_size=8, num_generations=2,
        local_random=np.random.default_rng(0),
    )
    s.complete_request([0.1, 0.2], [1.0, 2.0], epoch=0)
    s.complete_request([0.3, 0.4], [np.nan, 2.0], epoch=0)
    s.complete_request([0.5, 0.6], [np.inf, 1.0], epoch=0)
    assert len(s.completed) == 1
    assert s.n_quarantined == 2 and len(s.quarantined) == 2
    assert s.stats["n_quarantined"] == 2
    # drain the request queue so the fold runs, then check the archive
    while s.get_next_request() is not None:
        pass
    s._update_evals()
    assert s.x.shape[0] == 1 and np.all(np.isfinite(s.y))


def test_epoch_init_failure_is_isolated(monkeypatch):
    """A tenant whose epoch initialization raises (surrogate blowup,
    optimizer bug) is retired with the cause on its handle; its
    bucket-mates complete — `initialize_epochs_batched(on_error=)`."""
    svc = OptimizationService(telemetry=True)
    good = _submit(svc, "good", 4, seed=50)
    bad = _submit(svc, "bad", 5, seed=51)  # own bucket (different dim)
    bad_tenant = [
        t for t in svc._pending if t.handle.opt_id == "bad"
    ][0]

    def explode(epoch_index):
        raise ValueError("surrogate exploded")

    monkeypatch.setattr(bad_tenant.strat, "initialize_epoch", explode)
    svc.run()
    assert bad.done and isinstance(bad.error, ValueError)
    assert good.done and good.error is None
    assert good.result().epoch == 1
    assert svc.telemetry.registry.counter_value("tenants_failed_total") == 1.0
    svc.close()


def test_writer_death_degrades_not_crashes(tmp_path):
    """A terminally failing persistence path (checkpoint into a missing
    directory) kills the writer AFTER its retry budget — the service
    keeps optimizing, and the failure is visible in introspect() and
    the status CLI instead of a cold stack trace from submit()."""
    svc = OptimizationService(
        telemetry=True,
        checkpoint_path=str(tmp_path / "no_such_dir" / "ck.h5"),
    )
    h = _submit(svc, "a", 4, seed=60)
    svc.run()
    assert h.done and h.error is None  # optimization unaffected
    snap = svc.introspect()
    assert snap["writer"]["failed"] is True
    assert snap["writer"]["retries_total"] >= 1
    assert svc.telemetry.registry.counter_value("writer_retries_total") >= 1

    from click.testing import CliRunner

    from dmosopt_tpu.cli import status as status_cmd

    status_path = tmp_path / "status.json"
    from dmosopt_tpu.utils import json_default

    status_path.write_text(json.dumps(snap, default=json_default))
    out = CliRunner().invoke(status_cmd, ["-p", str(status_path)])
    assert out.exit_code == 0, out.output
    assert "failed=True" in out.output and "DEAD" in out.output
    svc.close()


def test_checkpoint_resume_midrun_equivalence(tmp_path):
    """Stop a checkpointing service after one boundary, resume it in
    the same process, and run BOTH the original and the resumed service
    to completion: every subsequent front must be bitwise-identical —
    the checkpoint captured archive, RNG state, epoch counters, and the
    in-flight resample batch exactly."""
    ckpt = str(tmp_path / "svc.h5")
    svc = OptimizationService(telemetry=False, checkpoint_path=ckpt)
    h_a = _submit(svc, "a", 4, seed=70, n_epochs=3)
    h_b = _submit(svc, "b", 4, seed=71, n_epochs=3)
    svc.step()
    for h in (h_a, h_b):
        h.updates()  # drop epoch-0 fronts; compare the continuation

    from dmosopt_tpu.storage import load_service_checkpoint_from_h5

    data = load_service_checkpoint_from_h5(ckpt)
    assert sorted(st["state"]["opt_id"] for st in data["tenants"].values()) \
        == ["a", "b"]
    for tp in data["tenants"].values():
        st = tp["state"]
        assert st["epochs_run"] == 1 and st["epoch_index"] == 0
        # the next epoch's resample batch is in flight in the snapshot
        assert tp["arrays"]["pending_x"].shape[0] == 4
        assert tp["arrays"]["pending_has_pred"].all()

    objectives = {"a": _host_obj(4), "b": _host_obj(4)}
    svc2, handles2 = OptimizationService.resume(
        ckpt + "", objectives, checkpoint=False
    )
    assert sorted(handles2) == ["a", "b"]
    # resumed tenants keep their ids and epoch positions
    for k, h2 in handles2.items():
        assert h2.tenant_id == (h_a if k == "a" else h_b).tenant_id

    svc.run()
    svc2.run()
    for k, h2 in handles2.items():
        cont = _fronts(h_a if k == "a" else h_b)
        res = _fronts(h2)
        assert [e for e, _, _ in res] == [1, 2]
        _assert_fronts_equal(res, cont, who=f"resumed {k}")
        assert h2.done and h2.error is None
    svc.close()
    svc2.close()


def test_kill9_resume_subprocess(tmp_path):
    """The crash-resume acceptance: a running 3-tenant checkpointing
    service is SIGKILLed mid-epoch (no teardown of any kind), resumed
    from its last durable epoch-boundary checkpoint, and completes with
    every remaining front bitwise-equal to an uninterrupted run — the
    final fronts (and with them the front quality) match exactly."""
    import tests._service_crash_worker as worker

    ckpt = str(tmp_path / "crash.h5")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p
        for p in (
            env.get("PYTHONPATH"),
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        if p
    )
    proc = subprocess.run(
        [sys.executable, worker.__file__, ckpt],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == -signal.SIGKILL, (
        proc.returncode, proc.stdout, proc.stderr,
    )
    assert "BOUNDARY2" in proc.stdout
    assert "UNREACHABLE" not in proc.stdout

    # uninterrupted reference, same configuration, in THIS process
    ref_svc = OptimizationService(telemetry=False)
    ref_handles = worker.submit_all(ref_svc)
    ref_svc.run()
    ref = {k: _fronts(h) for k, h in ref_handles.items()}
    ref_svc.close()

    objectives = {f"t{i}": worker.host_zdt1 for i in range(worker.N_TENANTS)}
    svc, handles = OptimizationService.resume(
        ckpt, objectives, telemetry=False, checkpoint=False
    )
    # the in-flight epoch-2 resample batches were re-issued
    for t in svc._pending:
        assert len(t.strat.reqs) == 4
        assert t.epochs_run == 2
    svc.run()
    for k, h in handles.items():
        assert h.done and h.error is None
        got = _fronts(h)
        assert [e for e, _, _ in got] == [2, 3]
        _assert_fronts_equal(got, ref[k][2:], who=f"kill9 {k}")
        # final front quality: identical front, identical quality
        np.testing.assert_array_equal(h.best().y, ref_handles[k].best().y)
    svc.close()


def test_chaos_scheduler_degrades_only_faulty_dag_branch(monkeypatch):
    """ISSUE 19 fault-plan interaction: under the task-graph scheduler,
    an eval node that raises or times out (EvalPolicy) degrades only
    ITS tenant's DAG branch — sibling branches keep running and the
    survivor's fronts stay bitwise-equal to a fault-free scheduler run
    (itself bitwise-equal to lockstep)."""

    def run(scheduler):
        svc = OptimizationService(
            telemetry=True, eval_policy=dict(POLICY), scheduler=scheduler
        )
        handles = {
            name: _submit(svc, name, 4, seed=40 + i, n_epochs=2)
            for i, name in enumerate(("good", "boom", "wedge"))
        }
        svc.run()
        out = {k: _fronts(h) for k, h in handles.items()}
        snap = svc.introspect()
        svc.close()
        return out, handles, snap

    monkeypatch.delenv("DMOSOPT_FAULT_PLAN", raising=False)
    ref_sched, _, _ = run(scheduler=3)
    ref_lock, _, _ = run(scheduler=None)
    # fault-free cross-check: the concurrent scheduler IS the lockstep
    # trajectory (per-tenant RNG independence)
    for k in ref_lock:
        _assert_fronts_equal(ref_sched[k], ref_lock[k], who=f"sched {k}")

    monkeypatch.setenv(
        "DMOSOPT_FAULT_PLAN",
        json.dumps(
            {
                "seed": 7,
                "rules": [
                    {"kind": "raise", "target": "boom"},
                    {"kind": "hang", "target": "wedge", "delay_s": 0.6},
                ],
            }
        ),
    )
    got, handles, snap = run(scheduler=3)

    # faulty branches degraded + retired per policy, never an exception
    # out of step(); causes travel on the handles
    assert snap["tenant_counts"] == {"completed": 1, "degraded": 2}
    for bad in ("boom", "wedge"):
        assert handles[bad].done and handles[bad].error is not None

    # the survivor's branch never saw the faults
    assert handles["good"].error is None and handles["good"].done
    _assert_fronts_equal(got["good"], ref_sched["good"], who="good")

    # DAG-level containment: policy-degraded evals are handled INSIDE
    # their eval node (no node failures, nothing skipped)
    nodes = snap["scheduler"]["last_graph"]["nodes"]
    assert nodes and all(n["state"] == "done" for n in nodes)
