"""ParameterSpace flatten/unflatten round-trips and ordering invariants.

Oracle pattern follows reference tests/test_parameter_space.py and
tests/test_parameter_space_order.py: nested round-trips and stable sorted
parameter ordering.
"""

import numpy as np

from dmosopt_tpu.datatypes import ParameterSpace, update_nested_dict


NESTED = {
    "soma": {
        "gkabar_kap": [0.001, 0.1, False],
        "gkdrbar_kdr": [0.001, 0.1],
    },
    "axon": {"gbar_nax": [0.01, 0.2]},
    "dend": {
        "deep": {"a": [0.0, 1.0], "b": [2.0, 3.0, True]},
    },
}


def test_flatten_order_is_sorted_depth_first():
    space = ParameterSpace.from_dict(NESTED)
    assert space.parameter_names == [
        "axon.gbar_nax",
        "dend.deep.a",
        "dend.deep.b",
        "soma.gkabar_kap",
        "soma.gkdrbar_kdr",
    ]
    assert space.n_parameters == 5
    np.testing.assert_allclose(space.bound1, [0.01, 0.0, 2.0, 0.001, 0.001])
    np.testing.assert_allclose(space.bound2, [0.2, 1.0, 3.0, 0.1, 0.1])
    np.testing.assert_array_equal(
        space.is_integer, [False, False, True, False, False]
    )


def test_roundtrip_flatten_unflatten():
    space = ParameterSpace.from_dict(NESTED)
    flat = np.array([0.15, 0.5, 2.0, 0.05, 0.02])
    nested = space.unflatten(flat)
    assert nested["axon"]["gbar_nax"] == 0.15
    assert nested["dend"]["deep"]["b"] == 2.0
    back = space.flatten(nested)
    np.testing.assert_allclose(back, flat)


def test_flat_space():
    space = ParameterSpace.from_dict({"x": [0.0, 1.0], "y": [-1.0, 1.0]})
    assert space.parameter_names == ["x", "y"]
    d = space.unflatten(np.array([0.3, 0.7]))
    assert d == {"x": 0.3, "y": 0.7}


def test_value_space():
    space = ParameterSpace.from_dict({"a": 1.5, "b": {"c": 2}}, is_value_only=True)
    assert space.is_value_space
    np.testing.assert_allclose(space.parameter_values, [1.5, 2.0])
    assert space.unflatten() == {"a": 1.5, "b": {"c": 2.0}}


def test_bounds_property_shape():
    space = ParameterSpace.from_dict(NESTED)
    assert space.bounds.shape == (5, 2)
    assert (space.bounds[:, 0] <= space.bounds[:, 1]).all()


def test_swapped_bounds_normalized():
    space = ParameterSpace.from_dict({"x": [1.0, 0.0]})
    assert space.bound1[0] == 0.0 and space.bound2[0] == 1.0


def test_update_nested_dict():
    base = {"a": {"b": 1, "c": 2}, "d": 3}
    upd = {"a": {"c": 5}, "e": 6}
    out = update_nested_dict(base, upd)
    assert out == {"a": {"b": 1, "c": 5}, "d": 3, "e": 6}
    assert base == {"a": {"b": 1, "c": 2}, "d": 3}
