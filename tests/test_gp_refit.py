"""Cross-epoch surrogate-reuse tests: rank-k Cholesky update parity,
warm-start quality, controller scheduling (pruning, audits, bucket
fallback), cold-mode bitwise regression, and checkpoint round-trip.

Oracle pattern: the rank-k extension is pinned against the full masked
refactorization at the SAME hyperparameters (`posterior_from_params`) —
identical math in exact arithmetic, f32 reduction-order tolerance in
practice. Cold mode is pinned BITWISE against the pre-refit
constructor path.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dmosopt_tpu import moasmo
from dmosopt_tpu.models import gp
from dmosopt_tpu.models.gp import (
    GPR_Matern,
    extend_cholesky_rank_k,
    gp_predict,
    posterior_from_params,
)
from dmosopt_tpu.models.refit import (
    SurrogateRefitConfig,
    SurrogateRefitController,
)


def _objective(x):
    return np.column_stack(
        [np.sum(x**2, axis=1), np.sum((x - 0.5) ** 2, axis=1)]
    )


def _pool(n, dim=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(size=(n, dim))
    return X, _objective(X)


FAST = {"n_starts": 4, "n_iter": 80, "seed": 0}


class _Telemetry:
    """Minimal counter/event recorder standing in for the facade."""

    def __init__(self):
        self.counters = {}
        self.events = []

    def inc(self, name, value=1.0, **labels):
        self.counters[name] = self.counters.get(name, 0.0) + value

    def event(self, kind, **fields):
        self.events.append((kind, fields))


def _train(ctrl, X, Y, tel=None, dim=5, kwargs=FAST):
    return moasmo.train(
        dim, 2, np.zeros(dim), np.ones(dim), X, Y, None,
        surrogate_method_kwargs=dict(kwargs),
        surrogate_refit=ctrl, telemetry=tel,
    )


def _drive_to_rank(ctrl, X, Y, sizes, tel=None):
    """Run one train() per size; returns the last model."""
    sm = None
    for n in sizes:
        sm = _train(ctrl, X[:n], Y[:n], tel=tel)
    return sm


# ------------------------------------------------------------ rank parity


@pytest.mark.parametrize("n0,k", [(70, 8), (100, 28)])
def test_rank_update_parity_vs_refactorization(n0, k):
    """An in-bucket rank-k append must reproduce the full masked
    refactorization at the same hyperparameters: L bit-comparable up to
    f32 reduction order, alpha/predictions to f32 tolerance. (70, 8)
    appends into a partially padded 128 bucket; (100, 28) fills the
    bucket to its exact edge (128 = no padded rows left)."""
    dim = 5
    X, Y = _pool(n0 + k, dim=dim)
    base = GPR_Matern(
        X[:n0], Y[:n0], dim, 2, np.zeros(dim), np.ones(dim), **FAST
    )
    fit = base.fit
    P = fit.X.shape[0]
    assert n0 + k <= P, "test shapes must stay inside the bucket"

    # standardize the appended rows with the BASE fit's statistics
    y_mean = np.asarray(fit.y_mean, np.float64)
    y_std = np.asarray(fit.y_std, np.float64)
    Xu = np.asarray(X, np.float64)  # bounds are the unit box already
    Yn = (np.asarray(Y, np.float64) - y_mean) / y_std

    X_pad = np.asarray(fit.X).copy()
    X_pad[n0 : n0 + k] = Xu[n0 : n0 + k].astype(X_pad.dtype)
    mask = (np.arange(P) < n0 + k).astype(X_pad.dtype)
    Yn_pad = np.zeros((P, 2), X_pad.dtype)
    Yn_pad[: n0 + k] = Yn.astype(X_pad.dtype)

    L_up, a_up, nmll_up = extend_cholesky_rank_k(
        fit.L, jnp.asarray(X_pad), jnp.asarray(mask), jnp.asarray(Yn_pad),
        fit.amp, fit.ls, fit.noise, kernel="matern52",
        n_old=n0, n_new=n0 + k, rel_jitter=base._rel_jitter,
    )
    L_full, a_full, nmll_full = posterior_from_params(
        jnp.asarray(X_pad), jnp.asarray(Yn_pad), jnp.asarray(mask),
        fit.amp, fit.ls, fit.noise, kernel="matern52",
        rel_jitter=base._rel_jitter,
    )

    # f32 tolerance, scale-normalized: alpha = K^-1 y amplifies the
    # Schur complement's reduction-order noise by the condition number,
    # so it is judged against its own magnitude; the predictions below
    # (the quantity consumers see) agree far tighter
    def norm_diff(a, b):
        a, b = np.asarray(a), np.asarray(b)
        return float(np.max(np.abs(a - b)) / max(1.0, np.max(np.abs(b))))

    assert norm_diff(L_up, L_full) < 1e-3
    assert norm_diff(a_up, a_full) < 3e-2
    np.testing.assert_allclose(
        np.asarray(nmll_up), np.asarray(nmll_full), rtol=1e-3, atol=1e-2
    )

    # predictions through the updated fit match the refactorized ones
    fit_up = fit._replace(
        X=jnp.asarray(X_pad), L=L_up, alpha=a_up,
        train_mask=jnp.asarray(mask),
    )
    fit_full = fit._replace(
        X=jnp.asarray(X_pad), L=L_full, alpha=a_full,
        train_mask=jnp.asarray(mask),
    )
    Xq = jnp.asarray(np.random.default_rng(3).uniform(size=(20, dim)), jnp.float32)
    m1, v1 = gp_predict(fit_up, Xq)
    m2, v2 = gp_predict(fit_full, Xq)
    np.testing.assert_allclose(np.asarray(m1), np.asarray(m2), atol=2e-3)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), atol=1e-4)


def test_rank_update_unpadded_start():
    """Appending to a fit whose training set exactly fills its bucket
    (no padded rows at all in the masked sense: mask all-ones) is the
    bucket-boundary case — the controller must fall back to the
    refactorization path and still produce a posterior matching a
    from-scratch one at the same hyperparameters."""
    dim = 5
    X, Y = _pool(200, dim=dim)
    tel = _Telemetry()
    # rank_update_after=0: rank-eligible right after the first fit
    ctrl = SurrogateRefitController(
        SurrogateRefitConfig("warm", rank_update_after=0, audit_every=10)
    )
    sm0 = _train(ctrl, X[:64], Y[:64], tel=tel)  # 64 = exact bucket, no padding
    assert ctrl.path_history == ["cold"]
    assert float(jnp.sum(sm0.fit.train_mask)) == 64.0

    sm1 = _train(ctrl, X[:80], Y[:80], tel=tel)  # crosses into the 128 bucket
    assert ctrl.path_history == ["cold", "rank_refactor"]
    assert sm1.fit.X.shape[0] == 128

    # oracle: same hyperparams, fresh refactorization
    y_mean = np.asarray(sm0.fit.y_mean, np.float64)
    y_std = np.asarray(sm0.fit.y_std, np.float64)
    Yn = (Y[:80] - y_mean) / y_std
    X_pad, Yn_pad, mask = gp._pad_to_bucket(
        X[:80].astype(np.float32), Yn.astype(np.float32)
    )
    L, a, _ = posterior_from_params(
        jnp.asarray(X_pad), jnp.asarray(Yn_pad), jnp.asarray(mask),
        sm0.fit.amp, sm0.fit.ls, sm0.fit.noise, kernel="matern52",
        rel_jitter=sm0._rel_jitter,
    )
    np.testing.assert_allclose(
        np.asarray(sm1.fit.L), np.asarray(L), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(sm1.fit.alpha), np.asarray(a), rtol=2e-3, atol=2e-3
    )
    assert tel.counters["gp_rank_update_rows_total"] == 16


def test_rank_update_quality_tracks_full_fit():
    """A surrogate grown by rank-k updates keeps predicting the
    objective: MAE on held-out points stays comparable to a cold fit
    of the full training set."""
    dim = 5
    X, Y = _pool(140, dim=dim, seed=4)
    ctrl = SurrogateRefitController(
        SurrogateRefitConfig("warm", rank_update_after=0, audit_every=50)
    )
    sm = _drive_to_rank(ctrl, X, Y, [100, 110, 120])
    assert ctrl.path_history == ["cold", "rank", "rank"]
    cold = GPR_Matern(
        X[:120], Y[:120], dim, 2, np.zeros(dim), np.ones(dim), **FAST
    )
    Xq = X[120:]
    mae_rank = np.abs(np.asarray(sm.predict(Xq)[0]) - Y[120:]).mean()
    mae_cold = np.abs(np.asarray(cold.predict(Xq)[0]) - Y[120:]).mean()
    assert mae_rank < max(2.0 * mae_cold, 0.05), (mae_rank, mae_cold)


# ------------------------------------------------------- controller logic


def test_controller_schedule_and_counters():
    """cold first, warm until stable, rank once stable, audit on the
    configured cadence — with the telemetry counters and events the
    observability catalog documents."""
    dim = 5
    X, Y = _pool(130, dim=dim)
    tel = _Telemetry()
    ctrl = SurrogateRefitController(
        SurrogateRefitConfig(
            "warm", rank_update_after=0, audit_every=3, hyper_tol=0.1
        )
    )
    sizes = [70, 78, 86, 94, 102]  # all inside the 128 bucket
    _drive_to_rank(ctrl, X, Y, sizes, tel=tel)
    # fit 0 cold (resets the audit clock); fits 1-3 rank (stable
    # immediately with rank_update_after=0); fit 4 audits once
    # fits_since_audit reaches audit_every=3
    assert ctrl.path_history == ["cold", "rank", "rank", "rank", "audit"]
    assert tel.counters["gp_rank_updates_total"] == 3
    assert tel.counters["gp_rank_update_rows_total"] == 24
    assert tel.counters["gp_refit_audits_total"] == 1
    # every rank update banks the whole n_iter budget
    assert tel.counters["gp_refit_steps_saved_total"] == 3 * FAST["n_iter"]
    audit_events = [f for k, f in tel.events if k == "surrogate_refit"
                    and f["path"] == "audit"]
    assert len(audit_events) == 1 and "movement" in audit_events[0]


def test_warm_start_pruning_and_steps_saved():
    """Warm refits record warm-slot wins; after prune_after consecutive
    wins the restart grid shrinks to pruned_starts (visible through the
    fit event), and steps saved accumulate."""
    dim = 5
    X, Y = _pool(120, dim=dim)
    tel = _Telemetry()
    # rank disabled (huge threshold) so every epoch is a warm refit
    ctrl = SurrogateRefitController(
        SurrogateRefitConfig(
            "warm", rank_update_after=99, prune_after=2, pruned_starts=2,
            audit_every=99,
        )
    )
    for n in (60, 70, 80, 90, 100):
        _train(ctrl, X[:n], Y[:n], tel=tel)
    warm_events = [f for k, f in tel.events if f.get("path") == "warm"]
    assert len(warm_events) == 4
    assert tel.counters["gp_warm_starts_total"] == 4
    # smooth objective: the warm slot keeps winning, so later refits run
    # pruned
    if all(e["warm_won"] for e in warm_events[:2]):
        assert warm_events[2]["pruned"] and warm_events[3]["pruned"]
    assert tel.counters.get("gp_refit_steps_saved_total", 0) > 0


def test_warm_fit_matches_cold_quality():
    """A warm-started refit lands at (or below) the cold fit's NMLL —
    reusing hyperparameters must never cost model quality."""
    dim = 5
    X, Y = _pool(110, dim=dim)
    ctrl = SurrogateRefitController(
        SurrogateRefitConfig("warm", rank_update_after=99, audit_every=99)
    )
    _train(ctrl, X[:70], Y[:70])
    warm = _train(ctrl, X[:100], Y[:100])
    cold = _train(None, X[:100], Y[:100])
    warm_nmll = np.asarray(warm.fit.nmll)
    cold_nmll = np.asarray(cold.fit.nmll)
    # per objective: within 1% relative or strictly better
    slack = 0.01 * np.maximum(1.0, np.abs(cold_nmll))
    assert np.all(warm_nmll <= cold_nmll + slack), (warm_nmll, cold_nmll)


def test_refit_ineligible_training_set_falls_back_to_warm():
    """A training set that is NOT an append-only extension (rows
    reordered/replaced) must not take the rank path."""
    dim = 5
    X, Y = _pool(120, dim=dim)
    ctrl = SurrogateRefitController(
        SurrogateRefitConfig("warm", rank_update_after=0, audit_every=99)
    )
    _train(ctrl, X[:80], Y[:80])
    # different leading rows — prefix check must reject
    _train(ctrl, X[20:110], Y[20:110])
    assert ctrl.path_history == ["cold", "warm"]


def test_unsupported_surrogate_falls_back_cold():
    """MEGP (shared-kernel fit) is outside the warm family: the
    controller steps aside and the plain constructor runs."""
    dim = 3
    X, Y = _pool(60, dim=dim)
    ctrl = SurrogateRefitController(SurrogateRefitConfig("warm"))
    sm = moasmo.train(
        dim, 2, np.zeros(dim), np.ones(dim), X, Y, None,
        surrogate_method_name="megp",
        surrogate_method_kwargs={"n_starts": 2, "n_iter": 40, "seed": 0},
        surrogate_refit=ctrl,
    )
    assert ctrl.path_history == []  # controller never engaged
    assert sm.predict(X[:4])[0].shape == (4, 2)


# -------------------------------------------------- cold-mode regression


def test_cold_mode_is_bitwise_identical():
    """`surrogate_refit="cold"` (and the default None) must reproduce
    the pre-refit fit outputs exactly: same Cholesky, alpha, and
    hyperparameters, bit for bit."""
    dim = 5
    X, Y = _pool(90, dim=dim)
    base = _train(None, X, Y)
    # mode="cold" resolves to no controller at the strategy layer; at
    # the train() layer the equivalent is surrogate_refit=None — also
    # pin the explicit constructor spelling
    again = _train(None, X, Y)
    direct = GPR_Matern(
        X, Y, dim, 2, np.zeros(dim), np.ones(dim), **FAST
    )
    for a, b in ((base, again), (base, direct)):
        for field in ("L", "alpha", "amp", "ls", "noise", "nmll"):
            assert np.array_equal(
                np.asarray(getattr(a.fit, field)),
                np.asarray(getattr(b.fit, field)),
            ), field


def test_cold_mode_driver_trajectory_identical(tmp_path):
    """End-to-end: a seeded driver run with surrogate_refit="cold" and
    one with the default produce byte-identical archives."""
    import dmosopt_tpu

    def run(opt_id, **extra):
        params = {
            "opt_id": opt_id,
            "obj_fun": _objective_flat,
            "objective_names": ["f1", "f2"],
            "space": {f"x{i}": [0.0, 1.0] for i in range(4)},
            "problem_parameters": {},
            "n_initial": 3,
            "n_epochs": 3,
            "population_size": 16,
            "num_generations": 8,
            "resample_fraction": 0.5,
            "optimizer_name": "nsga2",
            "surrogate_method_name": "gpr",
            "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 30, "seed": 0},
            "random_seed": 11,
            "telemetry": False,
            **extra,
        }
        dmosopt_tpu.run(params, verbose=False)
        from dmosopt_tpu.driver import dopt_dict

        strat = dopt_dict[opt_id].optimizer_dict[0]
        return strat.x.copy(), strat.y.copy()

    x_default, y_default = run("refit_traj_default")
    x_cold, y_cold = run("refit_traj_cold", surrogate_refit="cold")
    assert np.array_equal(x_default, x_cold)
    assert np.array_equal(y_default, y_cold)


def _objective_flat(pp):
    x = np.array([pp[f"x{i}"] for i in range(4)])
    return np.array([float(np.sum(x**2)), float(np.sum((x - 0.5) ** 2))])


# ------------------------------------------------------- warm end-to-end


def test_warm_driver_run_quality_and_state(tmp_path):
    """A seeded warm-mode driver run engages the reuse paths, persists
    its warm state with the checkpoint, and matches the cold run's
    solution quality (non-dominated front within tolerance on ZDT1)."""
    import dmosopt_tpu
    from dmosopt_tpu.benchmarks.zdt import zdt1, zdt1_pareto, distance_to_front
    from dmosopt_tpu.storage import load_refit_state_from_h5

    def run(opt_id, refit, file_path=None):
        params = {
            "opt_id": opt_id,
            "obj_fun": zdt1,
            "jax_objective": True,
            "objective_names": ["f1", "f2"],
            "space": {f"x{i}": [0.0, 1.0] for i in range(6)},
            "problem_parameters": {},
            "n_initial": 6,
            "n_epochs": 4,
            "population_size": 32,
            "num_generations": 20,
            "resample_fraction": 0.5,
            "optimizer_name": "nsga2",
            "surrogate_method_name": "gpr",
            "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 40, "seed": 0},
            "surrogate_refit": refit,
            "random_seed": 21,
            "telemetry": False,
        }
        if file_path is not None:
            params.update(file_path=file_path, save=True)
        best = dmosopt_tpu.run(params, verbose=False)
        _, lres = best
        y = np.column_stack([v for _, v in lres])
        from dmosopt_tpu.driver import dopt_dict

        return y, dopt_dict[opt_id]

    h5 = str(tmp_path / "warm.h5")
    y_cold, _ = run("refit_e2e_cold", "cold")
    y_warm, dopt = run(
        "refit_e2e_warm",
        {"mode": "warm", "rank_update_after": 1, "audit_every": 10},
        file_path=h5,
    )
    ctrl = dopt.optimizer_dict[0].refit_controller
    assert ctrl is not None
    assert ctrl.path_history[0] == "cold"
    assert any(p in ("warm", "rank", "rank_refactor")
               for p in ctrl.path_history[1:])

    front = zdt1_pareto(300)
    d_cold = float(np.median(distance_to_front(y_cold, front)))
    d_warm = float(np.median(distance_to_front(y_warm, front)))
    # warm within tolerance of cold (generous: tiny budgets are noisy)
    assert d_warm <= max(2.0 * d_cold, 0.25), (d_warm, d_cold)

    # warm state landed in the checkpoint and seeds a resumed controller
    state = load_refit_state_from_h5(h5, "refit_e2e_warm", 0)
    assert state is not None and "amp" in state
    seeded = SurrogateRefitController(
        SurrogateRefitConfig("warm"), seed_state=state
    )
    assert seeded.has_state
    np.testing.assert_allclose(
        seeded._hyper["amp"], np.asarray(ctrl._hyper["amp"])
    )


def test_seeded_controller_first_fit_is_warm():
    """A controller seeded from checkpoint state warm-starts its first
    fit (no cached factor — never a rank update)."""
    dim = 5
    X, Y = _pool(80, dim=dim)
    donor = SurrogateRefitController(SurrogateRefitConfig("warm"))
    _train(donor, X[:70], Y[:70])
    state = donor.export_state()
    # even a "stable" seeded counter must not produce a rank update
    state["stable"] = 5
    seeded = SurrogateRefitController(
        SurrogateRefitConfig("warm", rank_update_after=1),
        seed_state=state,
    )
    sm = _train(seeded, X, Y)
    assert seeded.path_history == ["warm"]
    assert sm.predict(X[:3])[0].shape == (3, 2)


def test_mismatched_warm_state_refits_cold():
    """Warm state whose lengthscale shape no longer matches the fit
    configuration (e.g. a resume after flipping `anisotropic`) falls
    back to a cold fit instead of crashing."""
    dim = 5
    X, Y = _pool(90, dim=dim)
    donor = SurrogateRefitController(SurrogateRefitConfig("warm"))
    _train(donor, X[:70], Y[:70])  # isotropic: ls shape (2, 1)
    seeded = SurrogateRefitController(
        SurrogateRefitConfig("warm"), seed_state=donor.export_state()
    )
    sm = moasmo.train(
        dim, 2, np.zeros(dim), np.ones(dim), X, Y, None,
        surrogate_method_kwargs=dict(FAST, anisotropic=True),  # ls (2, 5)
        surrogate_refit=seeded,
    )
    assert seeded.path_history == ["cold"]
    assert sm.fit.ls.shape == (2, dim)


def test_refit_config_validation():
    with pytest.raises(ValueError):
        SurrogateRefitConfig("lukewarm")
    with pytest.raises(TypeError):
        SurrogateRefitConfig.from_spec(3.14)
    cfg = SurrogateRefitConfig.from_spec({"mode": "warm", "audit_every": 7})
    assert cfg.audit_every == 7
    assert SurrogateRefitConfig.from_spec(None).mode == "cold"
    assert SurrogateRefitConfig.from_spec(cfg) is cfg
    with pytest.raises(ValueError, match="mode"):
        # a tuning dict without an explicit mode must not silently
        # resolve to the cold default
        SurrogateRefitConfig.from_spec({"hyper_tol": 0.2})
