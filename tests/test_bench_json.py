"""bench.py JSON writer: numpy/jax scalars must serialize (the
BENCH_r03 crash was a device scalar reaching `json.dumps` and dying in
dtype conversion against an unreachable backend)."""

import json
import sys

import numpy as np
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])
import bench


def test_json_round_trips_numpy_and_jax_scalars():
    result = {
        "np_f32": np.float32(1.5),
        "np_f64": np.float64(2.25),
        "np_i64": np.int64(7),
        "np_bool": np.bool_(True),
        "np_arr": np.arange(3, dtype=np.float32),
        "jax_scalar": jnp.float32(3.5),
        "jax_arr": jnp.asarray([1.0, 2.0], jnp.float32),
        "nested": {"v": np.float32(0.25), "l": [np.int32(1), jnp.int32(2)]},
        "plain": {"s": "x", "f": 1.0, "i": 3, "none": None},
    }
    line = bench._dumps(result)
    back = json.loads(line)
    assert back["np_f32"] == 1.5
    assert back["np_f64"] == 2.25
    assert back["np_i64"] == 7
    assert back["np_bool"] is True
    assert back["np_arr"] == [0.0, 1.0, 2.0]
    assert back["jax_scalar"] == 3.5
    assert back["jax_arr"] == [1.0, 2.0]
    assert back["nested"] == {"v": 0.25, "l": [1, 2]}
    assert back["plain"] == result["plain"]


def test_json_default_rejects_arbitrary_objects():
    class Opaque:
        pass

    try:
        bench._dumps({"bad": Opaque()})
    except TypeError:
        pass
    else:  # pragma: no cover
        raise AssertionError("expected TypeError for non-coercible object")


def test_import_bench_stays_jax_free():
    """`import bench` must not import jax (the orchestrator's
    wedged-tunnel survival contract) — the sanitizer is duck-typed for
    exactly this reason. Checked in a clean subprocess: this test
    module itself imports jax, so an in-process check proves nothing."""
    import os
    import subprocess

    repo = __file__.rsplit("/", 2)[0]
    code = (
        "import sys, bench\n"
        "assert bench.jax is None\n"
        "assert 'jax' not in sys.modules, 'import bench pulled in jax'\n"
        "assert bench._json_default(type('D', (), {'item': lambda s: 42})()) == 42\n"
        "print('OK')\n"
    )
    env = dict(os.environ, PYTHONPATH=repo)
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, cwd=repo,
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0 and "OK" in out.stdout, out.stderr[-1000:]


def test_append_history_skips_non_baseline_rows(tmp_path, monkeypatch):
    """Smoke/partial/fault-injected rows AND failed-run error stubs
    never enter BENCH_HISTORY.jsonl — an error stub measured nothing,
    so a later `bench-diff` judging it would vacuously pass while the
    junk row polluted the baseline pool."""
    path = tmp_path / "hist.jsonl"
    monkeypatch.setenv(bench._HISTORY_ENV, str(path))
    good = {"value": 1.0, "configs": {}, "backend": "cpu"}
    assert bench._append_history(dict(good)) == str(path)
    for bad in (
        {**good, "smoke": True},
        {**good, "partial": True},
        {**good, "fault_plan": "seed=1"},
        {**good, "value": 0.0, "error": "bench child produced no result"},
    ):
        assert bench._append_history(bad) is None
    rows = path.read_text().strip().splitlines()
    assert len(rows) == 1
