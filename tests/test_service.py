"""Ask/tell service over the problem-batched core (dmosopt_tpu.service).

The mixed-bucket contract: tenants with different dims land in
different buckets, tenants submitted at different times (staggered
epoch phases) share buckets through masked rows — and every tenant's
results equal the sequential path's, pinned bitwise in-process.
"""

import os

import numpy as np
import pytest

from dmosopt_tpu.benchmarks.zdt import zdt1
from dmosopt_tpu.service import OptimizationService

SMK = {"n_starts": 2, "n_iter": 30, "seed": 0}


def _submit(svc, *, dim, seed, n_epochs=2, num_generations=6, **extra):
    return svc.submit(
        zdt1,
        {f"x{i}": [0.0, 1.0] for i in range(dim)},
        ["f1", "f2"],
        n_epochs=n_epochs,
        population_size=16,
        num_generations=num_generations,
        n_initial=3,
        surrogate_method_kwargs=dict(SMK),
        random_seed=seed,
        **extra,
    )


def test_service_staggered_mixed_buckets_match_sequential():
    """Two dims + a late join with a shorter generation budget: the d6
    bucket holds tenants at STAGGERED epoch phases (different archive
    sizes -> masked training rows; different generation budgets ->
    inactive generation rows), the d4 tenant rides its own route. Every
    tenant's streamed fronts must be bitwise-equal to a sequential-only
    service run with the same seeds."""

    def run(min_bucket):
        svc = OptimizationService(min_bucket=min_bucket, telemetry=True)
        handles = {}
        handles["a"] = _submit(svc, dim=6, seed=10, n_epochs=3)
        handles["b"] = _submit(svc, dim=6, seed=11, n_epochs=3)
        svc.step()  # a, b complete epoch 0
        # late joins: d (same bucket shape, SHORTER generation budget,
        # epoch phase one behind) and c (different dim -> other bucket)
        handles["d"] = _submit(
            svc, dim=6, seed=13, n_epochs=2, num_generations=4
        )
        handles["c"] = _submit(svc, dim=4, seed=12, n_epochs=2)
        svc.run()
        fronts = {
            k: [(u.epoch, u.x, u.y) for u in h.updates()]
            for k, h in handles.items()
        }
        assert all(h.done for h in handles.values())
        tel = svc.telemetry
        svc.close()
        return fronts, tel

    batched, tel = run(min_bucket=2)
    sequential, _ = run(min_bucket=99)

    # the d6 bucket really ran batched: 2 tenants at step 1, then 3
    # (a, b at epoch 1/2 alongside d at epoch 0/1)
    reg = tel.registry
    assert reg.counter_value("tenants_batched_total") >= 4.0
    assert reg.counter_value(
        "tenant_bucket_epochs_total", bucket="d6_o2_p16"
    ) >= 2.0

    for k in ("a", "b", "c", "d"):
        assert [e for e, _, _ in batched[k]] == [
            e for e, _, _ in sequential[k]
        ]
        for (eb, xb, yb), (es, xs, ys) in zip(batched[k], sequential[k]):
            assert xb.shape == xs.shape and yb.shape == ys.shape, (k, eb)
            np.testing.assert_array_equal(xb, xs, err_msg=f"{k} epoch {eb}")
            np.testing.assert_array_equal(yb, ys, err_msg=f"{k} epoch {eb}")


def test_service_streams_and_persists(tmp_path):
    svc = OptimizationService(telemetry=True)
    h0 = _submit(
        svc, dim=4, seed=1, file_path=str(tmp_path / "t0.h5"),
        opt_id="tenant_a",
    )
    h1 = _submit(svc, dim=4, seed=2)
    steps = svc.run()
    assert steps == 2  # both tenants: 2 epochs each, admitted together
    for h in (h0, h1):
        ups = h.updates()
        assert [u.epoch for u in ups] == [0, 1]
        assert h.done
        assert h.result().epoch == 1
        # a drained handle still serves the latest front
        assert h.best().epoch == 1
        assert h.updates() == []
    from dmosopt_tpu.storage import load_fronts_from_h5

    fronts = load_fronts_from_h5(str(tmp_path / "t0.h5"), "tenant_a")
    assert sorted(fronts) == [0, 1]
    for _, (x, y) in fronts.items():
        assert x.shape[1] == 4 and y.shape[1] == 2
    reg = svc.telemetry.registry
    assert reg.counter_value("tenants_submitted_total") == 2.0
    assert reg.counter_value("tenants_completed_total") == 2.0
    assert reg.counter_value("tenant_front_updates_total") == 4.0
    assert reg.gauge_value("tenants_active") == 0.0
    svc.close()


def test_service_host_objective():
    def host_zdt1(pp):
        x = np.asarray([pp[f"x{i}"] for i in range(4)], dtype=np.float32)
        y = np.asarray(zdt1(x[None, :]))[0]
        return y

    svc = OptimizationService(telemetry=False)
    h = svc.submit(
        host_zdt1,
        {f"x{i}": [0.0, 1.0] for i in range(4)},
        ["f1", "f2"],
        jax_objective=False,
        n_epochs=2, population_size=16, num_generations=4, n_initial=3,
        surrogate_method_kwargs=dict(SMK), random_seed=3,
    )
    svc.run()
    assert h.done
    front = h.result()
    assert front.x.shape[1] == 4 and front.y.shape[1] == 2
    assert np.all(np.isfinite(front.y))
    svc.close()


def test_service_usage_errors():
    svc = OptimizationService()
    h = _submit(svc, dim=4, seed=5)
    with pytest.raises(RuntimeError, match="still running"):
        h.result()
    with pytest.raises(ValueError, match="surrogate"):
        svc.submit(
            zdt1, {"x0": [0.0, 1.0]}, ["f1", "f2"],
            surrogate_method_name=None,
        )
    svc.close()
    assert h.done  # closing finalizes pending tenants
    with pytest.raises(RuntimeError, match="closed"):
        _submit(svc, dim=4, seed=6)
    with pytest.raises(RuntimeError, match="closed"):
        svc.step()


def test_service_failure_isolation():
    """A broken objective retires ITS tenant (handle.error carries the
    cause) while bucket-mates run to completion."""

    def broken(X):
        raise RuntimeError("objective exploded")

    svc = OptimizationService(telemetry=True)
    bad = svc.submit(
        broken, {f"x{i}": [0.0, 1.0] for i in range(4)}, ["f1", "f2"],
        jax_objective=False,  # host path: the exception surfaces per call
        n_epochs=2, population_size=16, num_generations=4, n_initial=3,
        surrogate_method_kwargs=dict(SMK), random_seed=7,
    )
    good = _submit(svc, dim=4, seed=8)
    svc.run()
    assert bad.done and bad.error is not None
    with pytest.raises(RuntimeError):
        bad.result()
    assert good.done and good.error is None
    assert good.result().epoch == 1
    reg = svc.telemetry.registry
    assert reg.counter_value("tenants_failed_total") == 1.0
    assert reg.counter_value("tenants_completed_total") == 1.0
    svc.close()


def test_service_step_phase_timers_and_introspect(tmp_path):
    """step() decomposes into admit/eval/fit/fold timers surfaced as
    `service_step_seconds{phase=}`; introspect() reports per-tenant
    state + attributed cost, queue depths, the last step's phases, and
    the loadavg-normalized throughput check; the status_path snapshot
    is published atomically and the `status` CLI renders it."""
    import json

    from click.testing import CliRunner

    status_path = str(tmp_path / "status.json")
    svc = OptimizationService(telemetry=True, status_path=status_path)
    h0 = _submit(svc, dim=4, seed=1)
    h1 = _submit(svc, dim=4, seed=2)
    svc.step()

    reg = svc.telemetry.registry
    for phase in ("admit", "eval", "fit", "fold", "step"):
        summ = reg.histogram_summary("service_step_seconds", phase=phase)
        assert summ is not None and summ["count"] == 1, phase
    step_s = reg.histogram_summary("service_step_seconds", phase="step")
    parts = sum(
        reg.histogram_summary("service_step_seconds", phase=p)["sum"]
        for p in ("admit", "eval", "fit", "fold")
    )
    assert parts <= step_s["sum"]

    snap = svc.introspect()
    assert snap["steps"] == 1 and not snap["closed"]
    assert snap["tenant_counts"] == {"active": 2}
    by_id = {t["opt_id"]: t for t in snap["tenants"]}
    for h in (h0, h1):
        t = by_id[h.opt_id]
        assert t["state"] == "active" and t["epoch"] == 1
        # batched epoch landed attributed cost on the handle
        assert t["cost_seconds"]["fit"] > 0 and t["cost_seconds"]["ea"] > 0
        assert t["gens_per_sec"] > 0
        assert h.cost_seconds["fit"] > 0
    assert snap["queue_depths"]["pending_submissions"] == 0
    assert snap["last_step"]["n_advanced"] == 2
    assert set(snap["last_step"]["phases"]) == {"admit", "eval", "fit", "fold"}
    # first step: its own wall IS the baseline
    assert snap["throughput"]["status"] == "ok"
    assert snap["throughput"]["cpu_count"] >= 1

    # status file published atomically, CLI renders it
    with open(status_path) as fh:
        published = json.load(fh)
    assert published["steps"] == 1
    from dmosopt_tpu.cli import status as status_cmd

    result = CliRunner().invoke(status_cmd, ["-p", status_path])
    assert result.exit_code == 0, result.output
    assert "active=2" in result.output
    assert "throughput: ok" in result.output
    for opt_id in (h0.opt_id, h1.opt_id):
        assert opt_id in result.output
    as_json = CliRunner().invoke(
        status_cmd, ["-p", status_path, "--as-json"]
    )
    assert as_json.exit_code == 0
    assert json.loads(as_json.output)["steps"] == 1

    svc.run()
    done = svc.introspect()
    assert done["tenant_counts"] == {"completed": 2}
    # cumulative handle cost grew across both epochs and stays
    # consistent with the retired snapshots
    by_id = {t["opt_id"]: t for t in done["tenants"]}
    for h in (h0, h1):
        # snapshots round to 6 decimals
        assert by_id[h.opt_id]["cost_seconds"]["fit"] == pytest.approx(
            h.cost_seconds["fit"], abs=1e-6
        )
    svc.close()
    final = json.load(open(status_path))
    assert final["closed"] is True


def test_service_throughput_check_normalizes_by_loadavg(monkeypatch):
    """The BENCH_r04/r05 trap at runtime: a >2x per-tenant step
    regression reads `host_contended` on a loaded host and
    `regression_suspect` on an idle one."""
    svc = OptimizationService(telemetry=False)
    svc._best_step_s_per_tenant = 1.0
    svc._last_step = {"wall_s_per_tenant": 5.0}
    ncpu = os.cpu_count() or 1

    monkeypatch.setattr(os, "getloadavg", lambda: (ncpu * 2.0, 0.0, 0.0))
    assert svc._throughput_check()["status"] == "host_contended"
    monkeypatch.setattr(os, "getloadavg", lambda: (0.1, 0.0, 0.0))
    assert svc._throughput_check()["status"] == "regression_suspect"
    svc._last_step = {"wall_s_per_tenant": 1.5}
    assert svc._throughput_check()["status"] == "ok"
    svc._last_step = {}
    assert svc._throughput_check()["status"] == "no_data"
    svc.close()


def test_service_close_marks_incomplete_tenants_errored():
    svc = OptimizationService()
    h = _submit(svc, dim=4, seed=9, n_epochs=3)
    svc.step()  # one of three epochs
    partial = h.best()
    svc.close()
    assert h.done
    with pytest.raises(RuntimeError, match="service closed before"):
        h.result()
    # the interim front is still readable, just not presented as final
    assert h.best() is partial and partial.epoch == 0

    svc2 = OptimizationService()
    h2 = _submit(svc2, dim=4, seed=9)
    svc2.close()  # never stepped: no front at all
    with pytest.raises(RuntimeError, match="service closed before"):
        h2.result()
