"""The round-end evidence artifacts must be unkillable: `python bench.py`
and `dryrun_multichip(n)` have to produce green output on a host whose
accelerator tunnel is wedged (backend init hangs) or whose backend is
simply absent. These tests drive both entry points as real subprocesses
the way the driver does.

Reference for what the artifacts cover:
/root/reference/tests/test_moo_benchmarks.py:25-48 (bench configs) and
/root/reference/dmosopt/dmosopt.py:2518-2536 (distributed launch).
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env(**over):
    """Env for a child that must NOT inherit the test process's forced
    CPU platform/device-count settings."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO
    env.update(over)
    return env


def test_env_forced_cpu_devices_parsing():
    import __graft_entry__ as g

    saved = {
        k: os.environ.get(k) for k in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = (
            "--foo --xla_force_host_platform_device_count=8"
        )
        assert g._env_forced_cpu_devices() == 8
        os.environ["XLA_FLAGS"] = ""
        assert g._env_forced_cpu_devices() == 1
        os.environ["JAX_PLATFORMS"] = "axon"
        assert g._env_forced_cpu_devices() is None
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_entry_is_jittable_and_runs():
    """The driver compile-checks `entry()` single-chip; mirror that here:
    the returned step must jit, execute on its example args, and produce
    a finite next state."""
    import jax
    import numpy as np

    import __graft_entry__ as g

    fn, args = g.entry()
    state = jax.jit(fn)(*args)
    pop_obj = np.asarray(state.population_obj)
    assert np.all(np.isfinite(pop_obj)), "entry() step produced non-finite objectives"


@pytest.mark.slow
def test_bench_emits_json_even_with_broken_backend():
    """bench.py orchestration: a default env whose backend init FAILS
    must still yield rc=0 and one parseable JSON line, flagged as the
    CPU fallback."""
    env = _clean_env(
        JAX_PLATFORMS="definitely-not-a-backend",
        DMOSOPT_BENCH_SMOKE="1",
        DMOSOPT_BENCH_PROBE_TIMEOUT="60",
        DMOSOPT_BENCH_TIMEOUT="600",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, proc.stdout
    result = json.loads(lines[0])
    assert result["device_mode"] == "cpu-fallback"
    assert result["metric"] == "zdt1_nsga2_generations_per_sec"
    assert result["value"] > 0  # the smoke loop actually ran on CPU


@pytest.mark.slow
def test_dryrun_multichip_wall_clock_budget():
    """dryrun_multichip(8) from a single-device parent must respawn onto
    the virtual CPU mesh and finish well inside the driver's budget
    (round 3 regressed to >20 min and timed out; the bar here is 420 s
    on this 1-core box, cold-cache worst case ~2 min)."""
    env = _clean_env(JAX_PLATFORMS="cpu")  # 1 CPU device -> respawn path
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420,
    )
    wall = time.time() - t0
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "VIRTUAL CPU mesh" in proc.stdout
    assert wall < 420, f"dryrun took {wall:.0f}s"


@pytest.mark.slow
def test_dryrun_multichip_env_forced_parent_stays_jax_free(tmp_path):
    """The round-5 red gate, pinned by construction: under the exact
    axon-style driver env (`JAX_PLATFORMS=cpu` + 8 forced virtual
    devices) the dryrun parent must never import jax — a poisoned `jax`
    package sits on the parent's PYTHONPATH and raises on import. The
    poison dir's basename contains 'axon', so `axon_free_pythonpath`
    strips it from the respawned child, which gets the real jax and must
    complete the full dryrun."""
    site = tmp_path / "fakeaxon_jaxpoison"
    (site / "jax").mkdir(parents=True)
    (site / "jax" / "__init__.py").write_text(
        "raise RuntimeError('BACKEND TOUCHED: jax imported in the "
        "dryrun parent')\n"
    )
    env = _clean_env(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    env["PYTHONPATH"] = str(site) + os.pathsep + REPO
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420,
    )
    tail = proc.stdout[-3000:] + proc.stderr[-3000:]
    assert proc.returncode == 0, tail
    assert "BACKEND TOUCHED" not in tail
    # the work ran in the delegated child, on the env's 8-device CPU mesh
    assert "VIRTUAL CPU mesh" in proc.stdout, tail
    assert "sharded batch evaluator OK" in proc.stdout, tail


@pytest.mark.slow
def test_dryrun_multichip_survives_wedged_probe(tmp_path):
    """The driver-real failure mode that cost rounds 3 AND 4: no
    JAX_PLATFORMS short-circuit, so dryrun_multichip pays the real
    backend probe — and the probe child hangs at interpreter startup
    (a sitecustomize stall, like the wedged accelerator tunnel) while
    holding a grandchild on the stdout pipe (the process that blocked
    round 4's post-kill communicate()). The run must kill the probe's
    process group at its deadline, respawn on the virtual mesh with a
    COLD compile cache, and finish inside the driver's ~600 s budget
    with progress lines localizing every stage."""
    site = tmp_path / "fakeaxon_site"  # "axon" in basename -> stripped
    site.mkdir()                       # from the respawned child's path
    (site / "sitecustomize.py").write_text(
        "import os, subprocess, sys, time\n"
        "if os.environ.get('_DMOSOPT_TPU_PROBE'):\n"
        "    subprocess.Popen([sys.executable, '-c',\n"
        "                      'import time; time.sleep(600)'])\n"
        "    time.sleep(600)\n"
    )
    cold_cache = tmp_path / "cold_cache"
    env = _clean_env()  # no JAX_PLATFORMS: the real probe path runs
    env["PYTHONPATH"] = REPO + os.pathsep + str(site)
    env["DMOSOPT_TPU_CACHE_DIR"] = str(cold_cache)
    env["DMOSOPT_DRYRUN_PROBE_TIMEOUT"] = "20"  # keep the test brisk
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=450,
    )
    wall = time.time() - t0
    tail = proc.stdout[-3000:] + proc.stderr[-3000:]
    assert proc.returncode == 0, tail
    assert "probe timed out" in proc.stdout, tail
    assert "VIRTUAL CPU mesh" in proc.stdout, tail
    # stage lines must localize progress for a post-mortem tail read
    assert "[dryrun-child]" in proc.stdout, tail
    assert "sharded batch evaluator OK" in proc.stdout, tail
    assert wall < 450, f"wedged-probe dryrun took {wall:.0f}s"
