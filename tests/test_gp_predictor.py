"""Predictor-layer tests: regime parity against the frozen solve oracle,
nystrom distillation gating, rank-k cache extension, telemetry, and the
bitwise default-path trajectory pin.

Oracle pattern: `gp_predict` (the ``solve`` regime) is bitwise-frozen —
the ``matmul`` regime is pinned against it to tight f32 tolerance at
every shape family the epoch loop produces (padded buckets, exact-bucket
boundaries, d ∈ {1, 3}, post-rank-k appends), and the ``nystrom`` regime
is bounded by its own distillation probe gate (a build that passes the
gate may not exceed the gate's tolerances on the probe slab; a build
that fails must serve matmul instead).
"""

import hashlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dmosopt_tpu.models import predictor as pr
from dmosopt_tpu.models.gp import GPR_Matern, fit_gp_batch, gp_predict
from dmosopt_tpu.models.predictor import (
    GPPredictor,
    build_nystrom_cache,
    build_whitened_cache,
    extend_whitened_rank_k,
    gp_predict_matmul,
    gp_predict_nystrom,
)
from dmosopt_tpu.models.refit import (
    SurrogateRefitConfig,
    SurrogateRefitController,
)


def _objective(x, d=2):
    cols = [np.sum(x**2, axis=1), np.sum((x - 0.5) ** 2, axis=1),
            np.sin(3.0 * x[:, 0]) + x[:, -1]]
    return np.column_stack(cols[:d])


def _pool(n, dim=5, d=2, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(size=(n, dim))
    return X, _objective(X, d=d)


FAST = {"n_starts": 2, "n_iter": 40, "seed": 0}


def _assert_matmul_parity(fit, Xq, atol_mean=1e-5, rtol_var=5e-3,
                          atol_var=1e-5):
    """solve-vs-matmul at one fit: mean is the identical contraction
    (near-bitwise), variance differs only by W·Ks vs back-substitution
    reduction order."""
    W = build_whitened_cache(fit)
    m0, v0 = map(np.asarray, gp_predict(fit, Xq))
    m1, v1 = map(np.asarray, gp_predict_matmul(fit, W, Xq))
    np.testing.assert_allclose(m1, m0, atol=atol_mean, rtol=1e-5)
    np.testing.assert_allclose(v1, v0, rtol=rtol_var, atol=atol_var)


# ------------------------------------------------------------- regime parity


@pytest.mark.parametrize(
    "n,dim,d",
    [
        (90, 5, 2),   # padded 128 bucket
        (64, 4, 2),   # exact bucket edge: no padded rows at all
        (70, 3, 1),   # single objective
        (100, 5, 3),  # three objectives
    ],
)
def test_matmul_parity_across_shapes(n, dim, d):
    X, Y = _pool(n, dim=dim, d=d)
    Yn = (Y - Y.mean(0)) / Y.std(0)
    X32 = jnp.asarray(X, jnp.float32)
    from dmosopt_tpu.models.gp import _pad_to_bucket

    Xp, Yp, mask = _pad_to_bucket(
        X.astype(np.float32), Yn.astype(np.float32)
    )
    fit = fit_gp_batch(
        jax.random.PRNGKey(0), jnp.asarray(Xp), jnp.asarray(Yp),
        train_mask=jnp.asarray(mask), n_starts=2, n_iter=40,
    )
    Xq = jnp.asarray(
        np.random.default_rng(3).uniform(size=(37, dim)), jnp.float32
    )
    _assert_matmul_parity(fit, Xq)


def test_predictor_objects_route_and_agree():
    """The surrogate-level `predictor=` knob routes `predict_normalized`
    and all three regimes agree on the mean to the solve oracle's
    accuracy class (nystrom may fall back — then it IS matmul)."""
    dim = 5
    X, Y = _pool(110, dim=dim)
    mk = lambda **kw: GPR_Matern(
        X, Y, dim, 2, np.zeros(dim), np.ones(dim), **FAST, **kw
    )
    solve, mm = mk(), mk(predictor="matmul")
    Xq = jnp.asarray(
        np.random.default_rng(1).uniform(size=(25, dim)), jnp.float32
    )
    m0, v0 = map(np.asarray, solve.predict_normalized(Xq))
    m1, v1 = map(np.asarray, mm.predict_normalized(Xq))
    assert solve.predictor_regime == "solve"
    assert mm.predictor_regime == "matmul"
    np.testing.assert_allclose(m1, m0, atol=1e-5)
    np.testing.assert_allclose(v1, v0, rtol=5e-3, atol=1e-5)
    # cache accounting: (d, P, P) f32
    P = solve.fit.X.shape[0]
    assert mm.build_predictor().cache_bytes() == 2 * P * P * 4


def test_predictor_mode_validation():
    dim = 3
    X, Y = _pool(40, dim=dim)
    with pytest.raises(ValueError, match="predictor"):
        GPR_Matern(
            X, Y, dim, 2, np.zeros(dim), np.ones(dim), **FAST,
            predictor="cholesky",
        )


# ---------------------------------------------------------- nystrom gating


def test_nystrom_full_rank_is_exact_and_passes_probe():
    """m == N distillation reproduces the exact posterior (the Nyström
    projection with Z = X is the identity on the training span) — the
    probe passes and the nystrom regime serves."""
    dim = 3
    X, Y = _pool(60, dim=dim, seed=2)
    sm = GPR_Matern(
        X, Y, dim, 2, np.zeros(dim), np.ones(dim), **FAST,
        predictor="nystrom", nystrom_points=4096,
    )
    p = sm.build_predictor()
    assert sm.predictor_regime == "nystrom", p.distill_error
    assert p.distill_error["ok"]
    Xq = jnp.asarray(
        np.random.default_rng(5).uniform(size=(30, dim)), jnp.float32
    )
    m0, v0 = map(np.asarray, gp_predict(sm.fit, Xq))
    m2, v2 = map(np.asarray, sm.predict_normalized(Xq))
    # full-rank distillation: errors bounded by the probe gate's own
    # tolerances (far tighter in practice at m == N)
    y_std = np.asarray(sm.fit.y_std)
    assert np.max(np.abs(m2 - m0) / y_std[None, :]) <= 0.1
    ratio = np.maximum(v2, 1e-10) / np.maximum(v0, 1e-10)
    assert np.max(np.maximum(ratio, 1.0 / ratio)) <= 3.0


def test_nystrom_probe_gates_fallback_to_matmul():
    """A distillation the probe rejects must NOT serve: the predictor
    falls back to matmul and predictions equal the matmul regime's."""
    dim = 5
    X, Y = _pool(120, dim=dim, seed=3)
    sm = GPR_Matern(
        X, Y, dim, 2, np.zeros(dim), np.ones(dim), **FAST,
        predictor="nystrom", nystrom_points=12,  # far too few columns
        nystrom_mean_tol=1e-4, nystrom_var_ratio_tol=1.01,  # strict gate
    )
    p = sm.build_predictor()
    assert p.mode == "nystrom" and p.regime == "matmul"
    assert p.distill_error is not None and not p.distill_error["ok"]
    assert p.nystrom is None and p.whitened is not None
    Xq = jnp.asarray(
        np.random.default_rng(7).uniform(size=(20, dim)), jnp.float32
    )
    m2, v2 = map(np.asarray, sm.predict_normalized(Xq))
    m1, v1 = map(
        np.asarray, gp_predict_matmul(sm.fit, p.whitened, Xq)
    )
    np.testing.assert_array_equal(m2, m1)
    np.testing.assert_array_equal(v2, v1)


def test_nystrom_error_bounded_by_probe_gate():
    """When the probe accepts, the served distillation respects the
    gate's bounds on the probe slab — the property the gate certifies."""
    dim = 2
    X, Y = _pool(150, dim=dim, seed=4)
    sm = GPR_Matern(
        X, Y, dim, 2, np.zeros(dim), np.ones(dim), **FAST,
        predictor="nystrom", nystrom_points=100,
    )
    p = sm.build_predictor()
    if sm.predictor_regime != "nystrom":
        pytest.skip(f"distillation rejected here: {p.distill_error}")
    err = p.distill_error
    assert err["ok"]
    assert err["mean_err"] <= p._opts["nystrom_mean_tol"]
    assert err["var_ratio"] <= p._opts["nystrom_var_ratio_tol"]


# ------------------------------------------------------- rank-k composition


def _drive(ctrl, X, Y, sizes, dim):
    from dmosopt_tpu import moasmo

    sm = None
    for n in sizes:
        sm = moasmo.train(
            dim, 2, np.zeros(dim), np.ones(dim), X[:n], Y[:n], None,
            surrogate_method_kwargs=dict(FAST, predictor="matmul"),
            surrogate_refit=ctrl,
        )
    return sm


def test_rank_update_extends_whitened_cache():
    """A rank-k refit extends the previous epoch's whitening cache by
    the block triangular-inverse identity; the extended cache matches a
    from-scratch build of the new factor and the solve oracle."""
    dim = 5
    X, Y = _pool(140, dim=dim, seed=6)
    ctrl = SurrogateRefitController(
        SurrogateRefitConfig("warm", rank_update_after=0, audit_every=50)
    )
    sm0 = _drive(ctrl, X, Y, [100], dim)
    # build the epoch's predictor the way moasmo.train does, then extend
    assert sm0.build_predictor().whitened is not None
    sm1 = _drive(ctrl, X, Y, [120], dim)
    assert ctrl.path_history == ["cold", "rank"]
    p1 = sm1._predictor_obj
    assert p1 is not None, "rank path must carry the cache forward"
    W_fresh = build_whitened_cache(sm1.fit)
    np.testing.assert_allclose(
        np.asarray(p1.whitened), np.asarray(W_fresh), rtol=2e-3, atol=2e-4
    )
    Xq = jnp.asarray(
        np.random.default_rng(9).uniform(size=(30, dim)), jnp.float32
    )
    m0, v0 = map(np.asarray, gp_predict(sm1.fit, Xq, kernel=sm1.kernel))
    m1, v1 = map(np.asarray, sm1.predict_normalized(Xq))
    np.testing.assert_allclose(m1, m0, atol=1e-5)
    np.testing.assert_allclose(v1, v0, rtol=1e-2, atol=1e-4)


def test_extend_whitened_rank_k_matches_fresh_inverse():
    """Kernel-level pin: the blocked W update equals the from-scratch
    triangular inverse of the extended factor."""
    dim = 4
    n0, k = 70, 20
    X, Y = _pool(n0 + k, dim=dim, seed=8)
    Yn = (Y - Y.mean(0)) / Y.std(0)
    from dmosopt_tpu.models.gp import _pad_to_bucket, extend_cholesky_rank_k

    Xp, Yp, mask = _pad_to_bucket(
        X[:n0].astype(np.float32), Yn[:n0].astype(np.float32)
    )
    fit = fit_gp_batch(
        jax.random.PRNGKey(1), jnp.asarray(Xp), jnp.asarray(Yp),
        train_mask=jnp.asarray(mask), n_starts=2, n_iter=30,
    )
    P = Xp.shape[0]
    assert n0 + k <= P
    X_pad = Xp.copy()
    X_pad[n0 : n0 + k] = X[n0 : n0 + k].astype(np.float32)
    mask2 = (np.arange(P) < n0 + k).astype(np.float32)
    Yn_pad = np.zeros((P, 2), np.float32)
    Yn_pad[: n0 + k] = Yn[: n0 + k].astype(np.float32)
    L_new, _, _ = extend_cholesky_rank_k(
        fit.L, jnp.asarray(X_pad), jnp.asarray(mask2), jnp.asarray(Yn_pad),
        fit.amp, fit.ls, fit.noise, kernel="matern52",
        n_old=n0, n_new=n0 + k, rel_jitter=1e-4,
    )
    W_old = build_whitened_cache(fit)
    W_up = extend_whitened_rank_k(W_old, L_new, n_old=n0, n_new=n0 + k)
    W_fresh = jax.vmap(
        lambda L: jax.scipy.linalg.solve_triangular(
            L, jnp.eye(P, dtype=L.dtype), lower=True
        )
    )(L_new)
    np.testing.assert_allclose(
        np.asarray(W_up), np.asarray(W_fresh), rtol=2e-3, atol=2e-4
    )


def test_clone_never_serves_stale_predictor():
    """`clone_with_fit` drops the previous predictor object — a clone
    with an updated posterior must rebuild, not serve the old cache."""
    from dmosopt_tpu.models import gp

    dim = 4
    X, Y = _pool(80, dim=dim)
    sm = GPR_Matern(
        X, Y, dim, 2, np.zeros(dim), np.ones(dim), **FAST,
        predictor="matmul",
    )
    sm.build_predictor()
    clone = gp.clone_with_fit(sm, sm.fit, dict(sm.fit_info))
    assert clone._predictor_obj is None
    assert clone._predictor_spec == sm._predictor_spec


# ----------------------------------------------------------------- telemetry


class _Telemetry:
    def __init__(self):
        self.counters = {}
        self.gauges = {}
        self.observed = []
        self.events = []

    def __bool__(self):
        return True

    def inc(self, name, value=1.0, **labels):
        key = (name, tuple(sorted(labels.items())))
        self.counters[key] = self.counters.get(key, 0.0) + value

    def gauge(self, name, value, **labels):
        self.gauges[name] = value

    def observe(self, name, value, **labels):
        self.observed.append((name, value))

    def event(self, kind, **fields):
        self.events.append((kind, fields))


def test_predictor_telemetry_and_hook_detach():
    dim = 4
    X, Y = _pool(70, dim=dim)
    tel = _Telemetry()
    pr.set_predictor_telemetry(tel)
    try:
        sm = GPR_Matern(
            X, Y, dim, 2, np.zeros(dim), np.ones(dim), **FAST,
            predictor="matmul",
        )
        sm.build_predictor()
        key = ("gp_predictor_builds_total", (("regime", "matmul"),))
        assert tel.counters[key] == 1
        assert tel.gauges["gp_predictor_cache_bytes"] > 0
        kinds = [k for k, _ in tel.events]
        assert "gp_predictor" in kinds
        Xq = jnp.asarray(
            np.random.default_rng(2).uniform(size=(10, dim)), jnp.float32
        )
        sm.predict_normalized(Xq)  # eager: records predict latency
        assert any(n == "gp_predict_seconds" for n, _ in tel.observed)
    finally:
        pr.set_predictor_telemetry(None)
    # detached: further predicts record nothing
    n_obs = len(tel.observed)
    sm.predict_normalized(
        jnp.asarray(np.random.default_rng(2).uniform(size=(4, dim)),
                    jnp.float32)
    )
    assert len(tel.observed) == n_obs


# -------------------------------------------------- default-path regression


def test_default_solve_trajectory_bitwise_pinned():
    """A seeded zdt1 driver run with the DEFAULT predictor (solve) is
    bitwise-identical to the pre-predictor HEAD: the baked SHA-256 was
    captured on the commit before the predictor layer landed (same
    config, same host class, JAX_PLATFORMS=cpu). The solve regime is the
    frozen program — any ulp drift here is a trajectory break."""
    import dmosopt_tpu
    from dmosopt_tpu.benchmarks.zdt import zdt1

    params = {
        "opt_id": "predictor_traj_pin",
        "obj_fun": zdt1,
        "jax_objective": True,
        "objective_names": ["f1", "f2"],
        "space": {f"x{i}": [0.0, 1.0] for i in range(6)},
        "problem_parameters": {},
        "n_initial": 4,
        "n_epochs": 3,
        "population_size": 24,
        "num_generations": 12,
        "resample_fraction": 0.5,
        "optimizer_name": "nsga2",
        "surrogate_method_name": "gpr",
        "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 40, "seed": 0},
        "random_seed": 17,
        "telemetry": False,
    }
    dmosopt_tpu.run(params, verbose=False)
    from dmosopt_tpu.driver import dopt_dict

    strat = dopt_dict["predictor_traj_pin"].optimizer_dict[0]
    x, y = strat.x, strat.y
    assert x.shape == (48, 6) and y.shape == (48, 2)
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(x.astype(np.float32)).tobytes())
    h.update(np.ascontiguousarray(y.astype(np.float32)).tobytes())
    assert h.hexdigest() == (
        "f62934d055ddfeba411ec700253d6d73ffabd199969d85fc2e8ae21f23783867"
    ), (float(np.sum(x.astype(np.float64))), float(np.sum(y.astype(np.float64))))


def test_matmul_driver_run_matches_solve_quality():
    """End-to-end: predictor="matmul" through the whole driver loop
    lands the same solution-quality class as the default (the EA
    consumes the cache for every generation; this is the e2e seam)."""
    import dmosopt_tpu
    from dmosopt_tpu.benchmarks.zdt import zdt1, zdt1_pareto, distance_to_front

    def run(opt_id, predictor):
        params = {
            "opt_id": opt_id,
            "obj_fun": zdt1,
            "jax_objective": True,
            "objective_names": ["f1", "f2"],
            "space": {f"x{i}": [0.0, 1.0] for i in range(6)},
            "problem_parameters": {},
            "n_initial": 6,
            "n_epochs": 3,
            "population_size": 32,
            "num_generations": 20,
            "resample_fraction": 0.5,
            "optimizer_name": "nsga2",
            "surrogate_method_name": "gpr",
            "surrogate_method_kwargs": {
                "n_starts": 2, "n_iter": 40, "seed": 0,
                "predictor": predictor,
            },
            "random_seed": 23,
            "telemetry": False,
        }
        best = dmosopt_tpu.run(params, verbose=False)
        _, lres = best
        return np.column_stack([v for _, v in lres])

    front = zdt1_pareto(300)
    d_solve = float(np.median(distance_to_front(run("pred_e2e_s", "solve"), front)))
    d_mm = float(np.median(distance_to_front(run("pred_e2e_m", "matmul"), front)))
    assert d_mm <= max(2.0 * d_solve, 0.25), (d_mm, d_solve)
