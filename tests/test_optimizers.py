"""All-optimizer convergence and integration tests on ZDT1, in the style
of the reference optimizer-cycling oracle (reference:
tests/test_zdt1_nsga2_trs.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dmosopt_tpu import sampling
from dmosopt_tpu.benchmarks.zdt import zdt1, zdt1_pareto, distance_to_front
from dmosopt_tpu.optimizers import AGEMOEA, CMAES, SMPSO, TRS
from dmosopt_tpu.optimizers.base import run_ea_loop

DIM = 10
POP = 48
BOUNDS = np.stack([np.zeros(DIM), np.ones(DIM)], 1)
FRONT = zdt1_pareto(400)


def _init(n):
    x = sampling.lh(n, DIM, 42)
    y = np.asarray(zdt1(jnp.asarray(x)))
    return x, y


def _mean_dist(y):
    return float(np.mean(distance_to_front(np.asarray(y), FRONT)))


def _host_loop(opt, ngen):
    for _ in range(ngen):
        xg, st = opt.generate()
        yg = np.asarray(zdt1(jnp.asarray(np.asarray(xg, np.float32))))
        opt.update(xg, yg, st)
    return opt.population_objectives


def test_agemoea_improves_and_is_scannable():
    x0, y0 = _init(POP)
    opt = AGEMOEA(popsize=POP, nInput=DIM, nOutput=2, model=None)
    opt.initialize_strategy(x0, y0, BOUNDS, random=1)
    d0 = _mean_dist(opt.state.population_obj)
    st = run_ea_loop(opt, opt.state, jax.random.PRNGKey(3), 60, zdt1)
    d1 = _mean_dist(st.population_obj)
    assert d1 < d0 * 0.2, (d0, d1)
    # survival scores: extremes get inf, others finite positive
    assert np.isinf(np.asarray(st.crowd_dist)).sum() >= 2


def test_smpso_improves_and_is_scannable():
    x0, y0 = _init(POP * 5)  # swarm_size=5 swarms
    opt = SMPSO(popsize=POP, nInput=DIM, nOutput=2, model=None)
    opt.initialize_strategy(x0, y0, BOUNDS, random=1)
    d0 = _mean_dist(opt.state.population_obj.reshape(-1, 2))
    st = run_ea_loop(opt, opt.state, jax.random.PRNGKey(3), 60, zdt1)
    d1 = _mean_dist(st.population_obj.reshape(-1, 2))
    assert d1 < d0 * 0.5, (d0, d1)


def test_cmaes_improves_and_is_scannable():
    x0, y0 = _init(POP)
    opt = CMAES(popsize=POP, nInput=DIM, nOutput=2, model=None)
    opt.initialize_strategy(x0, y0, BOUNDS, random=2)
    d0 = _mean_dist(opt.state.parents_y)
    st = run_ea_loop(opt, opt.state, jax.random.PRNGKey(5), 40, zdt1)
    d1 = _mean_dist(st.parents_y)
    assert d1 < d0, (d0, d1)
    assert st.parents_x.shape == (POP, DIM)
    # sigma adaptation happened: step sizes grew from the tiny init
    # (they may saturate uniformly at the sigma_max_frac cap)
    assert float(np.mean(np.asarray(st.sigmas))) > 10 * float(
        np.mean(np.asarray(opt.state.sigmas))
    )


def test_trs_improves_and_adapts_region():
    x0, y0 = _init(POP)
    opt = TRS(popsize=POP, nInput=DIM, nOutput=2, model=None)
    opt.initialize_strategy(x0, y0, BOUNDS, random=3)
    d0 = _mean_dist(opt.state.population_obj)
    st = run_ea_loop(opt, opt.state, jax.random.PRNGKey(6), 40, zdt1)
    d1 = _mean_dist(st.population_obj)
    assert d1 < d0, (d0, d1)
    # success window drives the trust region; length stays in bounds
    assert int(st.succ_count) == 40
    assert (
        opt.opt_params.length_min
        <= float(st.tr_length)
        <= opt.opt_params.length_max
    )


@pytest.mark.slow
def test_cmaes_trs_solution_quality_oracles():
    """Per-optimizer solution-quality oracles on ZDT1 and DTLZ2 (VERDICT
    r2 item 6): direct 250-generation loops against the true objective,
    same initial design as the reference head-to-head measurement in
    BASELINE.md. Bars are set at/below the measured reference quality
    (its unit-variance-EHVI selection), so passing means the crowding
    tie-break is equivalence-or-better on these oracles."""
    from dmosopt_tpu.benchmarks.moo_benchmarks import dtlz2

    pop, ngen = 200, 250
    # (problem, dim, nobj, objective, distance fn, median bar, within-.05 bar)
    # reference medians: zdt1 cmaes 0.174, trs 2.871; dtlz2 cmaes 2.217,
    # trs 0.688 (tools/refbench comparison, 2026-07-30)
    front = zdt1_pareto(1000)
    cases = [
        ("cmaes", CMAES, "zdt1", 30, 2, zdt1,
         lambda y: distance_to_front(y, front), 0.175, 5),
        ("trs", TRS, "zdt1", 30, 2, zdt1,
         lambda y: distance_to_front(y, front), 0.5, 0),
        ("cmaes", CMAES, "dtlz2", 12, 3, lambda X: dtlz2(X, n_obj=3),
         lambda y: np.abs(np.linalg.norm(y, axis=1) - 1.0), 0.2, 20),
        ("trs", TRS, "dtlz2", 12, 3, lambda X: dtlz2(X, n_obj=3),
         lambda y: np.abs(np.linalg.norm(y, axis=1) - 1.0), 0.05, 100),
    ]
    for name, cls, prob, dim, nobj, obj, dist, med_bar, within_bar in cases:
        x0 = sampling.lh(pop, dim, 21).astype(np.float32)
        y0 = np.asarray(obj(jnp.asarray(x0)))
        opt = cls(popsize=pop, nInput=dim, nOutput=nobj, model=None)
        bounds = np.stack([np.zeros(dim), np.ones(dim)], 1)
        opt.initialize_strategy(x0, y0, bounds, random=21)
        st = run_ea_loop(opt, opt.state, jax.random.PRNGKey(21), ngen, obj)
        y = np.asarray(st.parents_y if name == "cmaes" else st.population_obj)
        d = dist(y.reshape(-1, nobj))
        assert np.median(d) < med_bar, (name, prob, float(np.median(d)))
        assert (d <= 0.05).sum() >= within_bar, (name, prob, int((d <= 0.05).sum()))


def test_cmaes_host_api_matches_scan_contract():
    """The stateful host API (generate/update) still drives CMAES — the
    pure functions back both paths."""
    x0, y0 = _init(POP)
    opt = CMAES(popsize=POP, nInput=DIM, nOutput=2, model=None)
    opt.initialize_strategy(x0, y0, BOUNDS, random=2)
    _, y = _host_loop(opt, 5)
    assert np.all(np.isfinite(y))


def test_moasmo_epoch_with_each_optimizer():
    from dmosopt_tpu import moasmo

    names = [f"x{i}" for i in range(DIM)]
    rng = np.random.default_rng(0)
    X = rng.uniform(size=(50, DIM)).astype(np.float32)
    Y = np.asarray(zdt1(jnp.asarray(X)))
    for name in ("age", "smpso", "cmaes", "trs"):
        gen = moasmo.epoch(
            num_generations=5,
            param_names=names,
            objective_names=["f1", "f2"],
            xlb=np.zeros(DIM),
            xub=np.ones(DIM),
            pct=0.25,
            Xinit=X,
            Yinit=Y,
            C=None,
            pop=16,
            optimizer_name=name,
            surrogate_method_name="gpr",
            surrogate_method_kwargs={"n_starts": 2, "n_iter": 20, "seed": 0},
            local_random=4,
        )
        with pytest.raises(StopIteration) as ex:
            next(gen)
        res = ex.value.value
        assert res["x_resample"].shape[0] == 4, name
        assert np.all(np.isfinite(res["x_resample"])), name


def test_optimizer_cycling_nsga2_trs():
    """The reference's headline cycling config (test_zdt1_nsga2_trs.py)."""
    import dmosopt_tpu

    def obj(pp):
        x = np.array([pp[f"x{i}"] for i in range(DIM)])
        f1 = x[0]
        g = 1.0 + 9.0 / (DIM - 1) * np.sum(x[1:])
        return np.array([f1, g * (1.0 - np.sqrt(f1 / g))])

    best = dmosopt_tpu.run(
        {
            "opt_id": "cycling",
            "obj_fun": obj,
            "objective_names": ["f1", "f2"],
            "space": {f"x{i}": [0.0, 1.0] for i in range(DIM)},
            "problem_parameters": {},
            "n_initial": 6,
            "n_epochs": 4,
            "population_size": 48,
            "num_generations": 25,
            "resample_fraction": 0.5,
            "optimizer_name": ["nsga2", "trs"],
            "surrogate_method_name": "gpr",
            "surrogate_method_kwargs": {"n_starts": 3, "n_iter": 50, "seed": 0},
            "random_seed": 7,
        },
        verbose=False,
    )
    prms, lres = best
    y = np.column_stack([v for _, v in lres])
    d = distance_to_front(y, FRONT)
    assert (d < 0.15).sum() >= 8, (len(d), float(np.median(d)))


def test_cmaes_cholesky_update_invariants():
    """Oracle for the batched rank-1 Cholesky update (capability of
    reference tests/test_update_cholesky.py): after the update,
    A_new A_new^T == alpha (A A^T) + ccov pc_new pc_new^T and
    Ainv_new == A_new^{-1}, on both the active (psucc < pthresh) and
    passive branches."""
    from dmosopt_tpu.optimizers.cmaes import _update_cholesky_batch

    rng = np.random.default_rng(5)
    B, n = 4, 6
    cc, ccov, pthresh = 0.2, 0.3, 0.44
    # random SPD Cholesky factors + inverses
    A = np.stack([np.linalg.cholesky(
        (lambda M: M @ M.T + n * np.eye(n))(rng.normal(size=(n, n)))
    ) for _ in range(B)]).astype(np.float32)
    Ainv = np.linalg.inv(A).astype(np.float32)
    z = rng.normal(size=(B, n)).astype(np.float32)
    pc = rng.normal(size=(B, n)).astype(np.float32)
    psucc = np.array([0.1, 0.9, 0.2, 0.8], np.float32)  # both branches

    A2, Ainv2, pc2 = map(
        np.asarray,
        _update_cholesky_batch(
            jnp.asarray(A), jnp.asarray(Ainv), jnp.asarray(z),
            jnp.asarray(psucc), jnp.asarray(pc), cc, ccov, pthresh,
        ),
    )

    below = psucc < pthresh
    pc_expect = np.where(
        below[:, None],
        (1 - cc) * pc + np.sqrt(cc * (2 - cc)) * z,
        (1 - cc) * pc,
    )
    np.testing.assert_allclose(pc2, pc_expect, rtol=1e-5, atol=1e-6)
    alpha = np.where(below, 1 - ccov, (1 - ccov) + ccov * cc * (2 - cc))
    for b in range(B):
        C_new = A2[b] @ A2[b].T
        C_expect = alpha[b] * (A[b] @ A[b].T) + ccov * np.outer(pc2[b], pc2[b])
        np.testing.assert_allclose(C_new, C_expect, rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            Ainv2[b] @ A2[b], np.eye(n), rtol=1e-3, atol=2e-3
        )


# ------------------------------------------------- front-fill survival


def test_front_fill_single_computation(monkeypatch):
    """front_fill_selection computes the ranking and the mid-front
    crowding each AT MOST once per trace, and zero times when the caller
    supplies them — the single-computation contract CMAES/TRS (and any
    future consumer holding precomputed ranks) rely on."""
    import dmosopt_tpu.optimizers.survival as sv

    sv.front_fill_selection.clear_cache()  # count at trace time
    rng = np.random.default_rng(7)
    calls = {"rank": 0, "crowd": 0}
    real_rank, real_crowd = sv.non_dominated_rank, sv.crowding_distance

    def counting_rank(*a, **k):
        calls["rank"] += 1
        return real_rank(*a, **k)

    def counting_crowd(*a, **k):
        calls["crowd"] += 1
        return real_crowd(*a, **k)

    monkeypatch.setattr(sv, "non_dominated_rank", counting_rank)
    monkeypatch.setattr(sv, "crowding_distance", counting_crowd)

    y = jnp.asarray(rng.random((60, 3)), jnp.float32)
    sel, chosen, rank, crowd = sv.front_fill_selection(y, 24)
    assert calls == {"rank": 1, "crowd": 1}
    assert int(chosen.sum()) == 24 and sel.shape == (24,)

    # supplying both skips every recompute and reproduces the selection
    sel2, chosen2, rank2, crowd2 = sv.front_fill_selection(
        y, 24, rank=rank, crowding=crowd
    )
    assert calls == {"rank": 1, "crowd": 1}
    np.testing.assert_array_equal(np.asarray(sel), np.asarray(sel2))
    np.testing.assert_array_equal(np.asarray(rank), np.asarray(rank2))
    np.testing.assert_array_equal(np.asarray(crowd), np.asarray(crowd2))


def test_front_fill_matches_rank_order():
    """Selected set = the best `popsize` by (rank, -mid-front crowding):
    every fully-fitting front is taken whole and only the straddling
    front is crowding-filtered."""
    from dmosopt_tpu.ops.dominance import _rank_matrix_peel
    from dmosopt_tpu.optimizers.survival import front_fill_selection

    rng = np.random.default_rng(3)
    y = rng.random((80, 4)).astype(np.float32)
    popsize = 30
    sel, chosen, rank, crowd = front_fill_selection(jnp.asarray(y), popsize)
    full = np.asarray(_rank_matrix_peel(jnp.asarray(y)))
    chosen = np.asarray(chosen)
    # fronts fully below the cut are entirely chosen; fronts fully above
    # entirely unchosen
    counts = np.cumsum(np.bincount(full, minlength=80))
    for r in range(full.max() + 1):
        members = full == r
        if counts[r] <= popsize:
            assert chosen[members].all()
        elif (counts[r - 1] if r else 0) >= popsize:
            assert not chosen[members].any()


# --------------------------------------------- lorenz_smpso bench routing


def test_smpso_biobjective_generation_routes_fast_rank(monkeypatch):
    """Regression pin for the `lorenz_smpso_sec_per_gen` bench config
    (bench.py config 5): the SMPSO generation program at the bench's
    d == 2 shape family must trace through the O(N log N) bi-objective
    rank sweep, never the dense dominance-matrix peel or the d >= 3
    tiled sweep.

    Context (investigated 2026-08-03): BENCH_r04/r05 recorded this
    config at ~28 s/gen — the pre-PR-2 number — which looked like the
    PR 2 fast path never landed. Re-measured in the bench child's own
    environment on an idle host, the config runs at ~3.0 s/gen
    (matching PR 2's claim): eval-only wall for the 40960 RK4
    integrations of one generation is ~3.6 s, i.e. the config is
    eval-bound and the SMPSO update is fully hidden. The r04/r05
    numbers are host-contention artifacts (CMAES in the same rounds ran
    3.6-4.6x its idle wall too). This pins the structural half — the
    rank routing — so a rot here can't hide behind a noisy wall-clock
    number again."""
    import dmosopt_tpu.ops.dominance as dom
    from dmosopt_tpu.optimizers import SMPSO

    calls = {"sweep": 0, "tiled": 0, "peel": 0}
    real_sweep = dom._rank_biobjective_sweep
    real_tiled = dom._rank_tiled
    real_peel = dom._rank_matrix_peel

    def counting(name, real):
        def fn(*a, **k):
            calls[name] += 1
            return real(*a, **k)

        return fn

    monkeypatch.setattr(
        dom, "_rank_biobjective_sweep", counting("sweep", real_sweep)
    )
    monkeypatch.setattr(dom, "_rank_tiled", counting("tiled", real_tiled))
    monkeypatch.setattr(
        dom, "_rank_matrix_peel", counting("peel", real_peel)
    )

    # the bench family shrunk to test scale: 2 objectives, multi-swarm;
    # an unusual popsize guarantees a fresh trace (counts are per-trace)
    pop, dim, S = 11, 3, 2
    rng = np.random.default_rng(0)
    lb, ub = np.zeros(dim), np.ones(dim)
    bounds = np.stack([lb, ub], 1)
    x0 = rng.uniform(size=(pop * S, dim)).astype(np.float32)
    y0 = np.column_stack(
        [x0[:, 0], 1.0 - x0[:, 0] + x0[:, 1] ** 2]
    ).astype(np.float32)
    opt = SMPSO(popsize=pop, nInput=dim, nOutput=2, model=None, swarm_size=S)
    opt.initialize_strategy(x0, y0, bounds, random=1)
    assert calls["sweep"] > 0, "init sort must already ride the sweep"
    assert calls["tiled"] == 0 and calls["peel"] == 0

    calls.update(sweep=0, tiled=0, peel=0)

    def gen(state, key):
        x_gen, state = opt.generate_strategy(key, state)
        y_gen = jnp.column_stack(
            [x_gen[:, 0], 1.0 - x_gen[:, 0] + x_gen[:, 1] ** 2]
        )
        return opt.update_strategy(state, x_gen, y_gen)

    jax.jit(gen)(opt.state, jax.random.PRNGKey(3))  # fresh trace
    assert calls["sweep"] > 0, "generation update must ride the sweep"
    assert calls["tiled"] == 0 and calls["peel"] == 0
