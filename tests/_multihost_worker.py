"""Worker script for the multi-host (DCN) loopback test: one JAX process
of a 2-process cluster. Each process owns a set of virtual CPU devices;
the mesh spans BOTH processes' devices, so the sharded epoch's
collectives cross the process boundary — the loopback equivalent of a
DCN-spanning pod (reference capability: `mpirun -n K` + distwq,
dmosopt.py:2518-2536).

Usage: python _multihost_worker.py <coordinator> <num_procs> <proc_id>
"""

import os
import sys


def main():
    coordinator, num_procs, proc_id = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)

    import jax

    jax.config.update("jax_platforms", "cpu")

    from dmosopt_tpu.parallel.mesh import create_mesh, initialize_distributed

    rank = initialize_distributed(
        coordinator_address=coordinator,
        num_processes=num_procs,
        process_id=proc_id,
    )
    assert rank == proc_id, (rank, proc_id)
    n_global = jax.device_count()
    n_local = len(jax.local_devices())
    assert n_global == num_procs * n_local, (n_global, n_local)

    import numpy as np
    import jax.numpy as jnp

    from dmosopt_tpu import moasmo
    from dmosopt_tpu.benchmarks.zdt import zdt1
    from dmosopt_tpu.models import Model
    from dmosopt_tpu.models.gp import GPR_Matern
    from dmosopt_tpu.optimizers.nsga2 import NSGA2

    # mesh over ALL global devices: the population axis crosses the
    # process boundary, so the epoch's collectives ride "DCN"
    mesh = create_mesh(axis_names=("pop",))
    assert mesh.devices.size == n_global

    dim, pop = 6, 2 * n_global
    rng = np.random.default_rng(0)
    x0 = rng.uniform(size=(pop, dim)).astype(np.float32)
    y0 = np.asarray(zdt1(jnp.asarray(x0)))
    sm = GPR_Matern(
        x0, y0, dim, 2, np.zeros(dim), np.ones(dim),
        seed=0, n_starts=2, n_iter=10,
    )
    def run_epoch(use_mesh):
        o = NSGA2(popsize=pop, nInput=dim, nOutput=2, model=None)
        o.initialize_strategy(
            x0, y0, np.stack([np.zeros(dim), np.ones(dim)], 1), random=0
        )
        gen = moasmo.optimize(
            2, o, Model(objective=sm), dim, 2,
            np.zeros(dim), np.ones(dim),
            popsize=pop, local_random=1, mesh=use_mesh,
        )
        try:
            next(gen)
            raise AssertionError("surrogate-mode optimize must not yield")
        except StopIteration as ex:
            return ex.value

    # equivalence, not just finiteness: the DCN-spanning sharded epoch
    # must reproduce the replicated single-process epoch (same seeds)
    res = run_epoch(mesh)
    res_repl = run_epoch(None)
    np.testing.assert_allclose(res.y, res_repl.y, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        res.best_y, res_repl.best_y, rtol=1e-4, atol=1e-4
    )
    print(f"MULTIHOST_OK rank={rank} global_devices={n_global}", flush=True)


if __name__ == "__main__":
    main()
