"""Subprocess worker for the kill-9 crash-resume test.

Runs a 3-tenant checkpointing service, completes two epoch boundaries
(each durable on disk when its `step()` returns), then arms a
`FaultRule(kind="kill")` on tenant ``t0`` through the service's
env-gated fault plan — the NEXT objective call SIGKILLs the process
mid-epoch-3 evaluation. No interpreter teardown, no atexit, no flush:
whatever `resume()` finds is exactly what the atomic write-temp-rename
checkpoint protocol guaranteed.

The service/tenant parameters live HERE so the parent test builds its
uninterrupted reference run (and the resumed continuation) from the
identical configuration.
"""

import os
import sys

import numpy as np

N_TENANTS = 3
DIM = 4
N_EPOCHS = 4
SEEDS = (21, 22, 23)
SUBMIT_KW = dict(
    population_size=16,
    num_generations=4,
    n_initial=3,
    surrogate_method_kwargs={"n_starts": 2, "n_iter": 20, "seed": 0},
)
SPACE = {f"x{i}": [0.0, 1.0] for i in range(DIM)}


def host_zdt1(pp):
    """Pure-numpy zdt1 per-point objective — bitwise-identical across
    the worker, the reference run, and the resumed run."""
    x = np.asarray(
        [pp[f"x{i}"] for i in range(DIM)], dtype=np.float32
    ).astype(np.float64)
    f1 = x[0]
    g = 1.0 + 9.0 * np.mean(x[1:])
    f2 = g * (1.0 - np.sqrt(f1 / g))
    return np.asarray([f1, f2], dtype=np.float64)


def submit_all(svc):
    from dmosopt_tpu.service import OptimizationService  # noqa: F401

    return {
        f"t{i}": svc.submit(
            host_zdt1, SPACE, ["f1", "f2"],
            opt_id=f"t{i}", jax_objective=False,
            n_epochs=N_EPOCHS, random_seed=SEEDS[i], **SUBMIT_KW,
        )
        for i in range(N_TENANTS)
    }


def main(checkpoint_path: str) -> None:
    # empty plan: the env gate activates injection plumbing; the kill
    # rule is armed only once two boundaries are durable
    os.environ["DMOSOPT_FAULT_PLAN"] = '{"seed": 0, "rules": []}'
    from dmosopt_tpu.service import OptimizationService
    from dmosopt_tpu.testing.faults import FaultRule

    svc = OptimizationService(
        telemetry=False, checkpoint_path=checkpoint_path
    )
    submit_all(svc)
    svc.step()
    svc.step()
    print("BOUNDARY2", flush=True)
    svc._fault_plan.rules.append(FaultRule(kind="kill", target="t0"))
    svc.step()  # SIGKILLed mid-epoch-3 evaluation
    print("UNREACHABLE", flush=True)


if __name__ == "__main__":
    main(sys.argv[1])
