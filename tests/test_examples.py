"""The runnable examples are the first thing a reference user tries;
they must keep working against the public API. Each runs as a real
subprocess on the CPU backend with the example's own configuration
(examples point run() at the repo-local .jax_example_cache, so only the
first-ever invocation pays cold compiles)."""

import os
import subprocess
import sys
import types

import pytest

# conftest.py puts the repo root on sys.path
from _procutil import axon_free_pythonpath, communicate_bounded

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(name, timeout=900):
    """Run an example in its own session with a process-group-killed
    deadline (_procutil): a wedged example with a pipe-holding helper
    child must fail at the deadline, not hang the slow suite."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = axon_free_pythonpath(REPO)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "examples", name)],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, start_new_session=True,
    )
    out, err, rc = communicate_bounded(proc, timeout)
    assert rc != "timeout", f"{name} exceeded {timeout}s; tail:\n{out[-2000:]}"
    return types.SimpleNamespace(returncode=rc, stdout=out, stderr=err)


@pytest.mark.slow
def test_example_zdt1_runs_and_converges():
    proc = _run_example("example_zdt1.py")
    assert proc.returncode == 0, proc.stderr[-3000:]
    # the example prints "<n> best points; <k> within 0.05 of the front"
    lines = [l for l in proc.stdout.splitlines() if "best points" in l]
    assert lines, f"no 'best points' line in stdout:\n{proc.stdout[-2000:]}"
    n_close = int(lines[-1].split(";")[1].split()[0])
    assert n_close >= 10, lines[-1]


@pytest.mark.slow
def test_example_sharded_runs_on_virtual_mesh():
    proc = _run_example("example_sharded.py")
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "non-dominated points from the sharded run" in proc.stdout, (
        proc.stdout[-2000:]
    )
