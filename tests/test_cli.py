"""CLI tests: analyze/train/onestep against a real results store
(reference intent: dmosopt_analyze.py / dmosopt_train.py / dmosopt_onestep.py)."""

import json

import numpy as np
import pytest

click = pytest.importorskip("click")
from click.testing import CliRunner

import dmosopt_tpu
from dmosopt_tpu.cli import analyze, onestep, train

N_DIM = 5


def zdt1_obj(pp):
    x = np.array([pp[f"x{i}"] for i in range(N_DIM)])
    f1 = x[0]
    g = 1.0 + 9.0 / (N_DIM - 1) * np.sum(x[1:])
    return np.array([f1, g * (1.0 - np.sqrt(f1 / g))])


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    fp = tmp_path_factory.mktemp("cli") / "run.h5"
    dmosopt_tpu.run(
        {
            "opt_id": "cli_run",
            "obj_fun": zdt1_obj,
            "objective_names": ["f1", "f2"],
            "space": {f"x{i}": [0.0, 1.0] for i in range(N_DIM)},
            "problem_parameters": {},
            "n_initial": 6,
            "n_epochs": 2,
            "population_size": 24,
            "num_generations": 8,
            "resample_fraction": 0.5,
            "surrogate_method_name": "gpr",
            "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 25, "seed": 0},
            "random_seed": 9,
            "save": True,
            "file_path": str(fp),
        },
        verbose=False,
    )
    return str(fp)


def test_analyze(store, tmp_path):
    out = tmp_path / "best.json"
    result = CliRunner().invoke(
        analyze,
        ["-p", store, "--opt-id", "cli_run", "--knn", "5",
         "--output-file", str(out)],
    )
    assert result.exit_code == 0, result.output
    data = json.loads(out.read_text())
    assert "0" in data and len(data["0"]) >= 1
    row = next(iter(data["0"].values()))
    assert set(row["objectives"]) == {"f1", "f2"}
    assert len(row["parameters"]) == N_DIM


def test_train(store, tmp_path):
    out = tmp_path / "surrogate.joblib"
    result = CliRunner().invoke(
        train,
        ["-p", store, "--opt-id", "cli_run", "-o", str(out),
         "--surrogate-kwargs", '{"n_starts": 2, "n_iter": 20}'],
    )
    assert result.exit_code == 0, result.output
    import joblib

    sm = joblib.load(out)
    mean, var = sm.predict(np.full((3, N_DIM), 0.5))
    assert np.asarray(mean).shape == (3, 2)


def test_onestep(store, tmp_path):
    out = tmp_path / "resample.npz"
    result = CliRunner().invoke(
        onestep,
        ["-p", store, "--opt-id", "cli_run", "--population-size", "16",
         "--num-generations", "5", "--resample-fraction", "0.5",
         "-o", str(out),
         "--surrogate-kwargs", '{"n_starts": 2, "n_iter": 20}'],
    )
    assert result.exit_code == 0, result.output
    data = np.load(out)
    assert data["x_resample"].shape == (8, N_DIM)
    assert data["y_pred"].shape == (8, 2)


def test_analyze_sort_key(store, tmp_path):
    out = tmp_path / "sorted.json"
    result = CliRunner().invoke(
        analyze,
        ["-p", store, "--opt-id", "cli_run", "--sort-key", "f1",
         "--output-file", str(out)],
    )
    assert result.exit_code == 0, result.output
    rows = list(json.loads(out.read_text())["0"].values())
    f1s = [r["objectives"]["f1"] for r in rows]
    assert f1s == sorted(f1s)

    # unknown key errors cleanly
    bad = CliRunner().invoke(
        analyze, ["-p", store, "--opt-id", "cli_run", "--sort-key", "nope"]
    )
    assert bad.exit_code != 0
    assert "unknown sort key" in bad.output


def test_analyze_epsilon_and_hv(store, tmp_path):
    out = tmp_path / "eps.json"
    result = CliRunner().invoke(
        analyze,
        ["-p", store, "--opt-id", "cli_run", "--epsilons", "0.05",
         "--hv", "--output-file", str(out)],
    )
    assert result.exit_code == 0, result.output
    assert "epsilon boxes" in result.output
    assert "hypervolume" in result.output
    payload = json.loads(out.read_text())["0"]
    assert payload["hypervolume"] > 0
    assert len(payload["rows"]) >= 1

    # explicit reference point and per-objective epsilons
    result = CliRunner().invoke(
        analyze,
        ["-p", store, "--opt-id", "cli_run", "--epsilons", "0.05,0.1",
         "--hv", "--hv-ref", "2,2"],
    )
    assert result.exit_code == 0, result.output

    bad = CliRunner().invoke(
        analyze, ["-p", store, "--opt-id", "cli_run", "--hv", "--hv-ref", "2"]
    )
    assert bad.exit_code != 0 and "--hv-ref needs" in bad.output

    bad = CliRunner().invoke(
        analyze, ["-p", store, "--opt-id", "cli_run", "--epsilons", "1,2,3"]
    )
    assert bad.exit_code != 0 and "--epsilons needs" in bad.output


# ------------------------------------------------------- status / watch


def _status_snapshot():
    return {
        "ts": 0.0, "closed": False, "steps": 3,
        "tenant_counts": {"active": 1, "completed": 2},
        "tenants": [
            {"opt_id": "t0", "tenant_id": 0, "state": "active",
             "epoch": 2, "n_epochs": 5,
             "cost_seconds": {"fit": 1.0, "ea": 0.5, "compile": 0.2}},
        ],
        "queue_depths": {"pending_submissions": 0, "writer_backlog": 0},
        "writer": {"failed": False, "retries_total": 0},
        "checkpoint_path": None,
        "series_overflow_total": 0,
        "last_step": {"wall_s": 0.5, "n_advanced": 1,
                      "phases": {"eval": 0.1, "fit": 0.3}},
        "throughput": {"status": "ok", "last_step_s_per_tenant": 0.5,
                       "best_step_s_per_tenant": 0.4, "loadavg_1m": 0.5,
                       "cpu_count": 8, "load_ratio": 0.06},
        "health": {
            "status": "alerting",
            "firing": [
                {"rule": "eval_timeout_surge", "severity": "warning",
                 "since_step": 2, "value": 4.0},
            ],
            "firing_counts": {"warning": 1},
            "transitions_total": 3,
            "rules": 10,
        },
        "exporter": {"host": "127.0.0.1", "port": 9464,
                     "url": "http://127.0.0.1:9464"},
    }


def test_status_renders_health_block_and_exporter(tmp_path):
    from dmosopt_tpu.cli import status as status_cmd

    path = tmp_path / "status.json"
    path.write_text(json.dumps(_status_snapshot()))
    result = CliRunner().invoke(status_cmd, ["-p", str(path)])
    assert result.exit_code == 0, result.output
    assert "health: alerting (1 firing / 10 rules, 3 transitions)" in result.output
    assert "ALERT [warning] eval_timeout_surge since step 2" in result.output
    assert "exporter: http://127.0.0.1:9464" in result.output


def test_status_watch_rerenders_until_interrupted(tmp_path, monkeypatch):
    """Satellite: `status --watch N` re-renders from the status file
    every N seconds (live operation); Ctrl-C exits cleanly with code
    0. Pinned by interrupting the loop from a patched sleep after two
    renders — the second render must reflect a status file UPDATED
    between iterations."""
    import time as time_mod

    from dmosopt_tpu.cli import status as status_cmd

    path = tmp_path / "status.json"
    snap = _status_snapshot()
    path.write_text(json.dumps(snap))

    calls = {"n": 0}

    def fake_sleep(seconds):
        assert seconds == 0.25
        calls["n"] += 1
        if calls["n"] == 1:
            # the service "advances" between renders
            snap["steps"] = 4
            snap["health"]["status"] = "ok"
            snap["health"]["firing"] = []
            snap["health"]["firing_counts"] = {}
            path.write_text(json.dumps(snap))
            return
        raise KeyboardInterrupt

    monkeypatch.setattr(time_mod, "sleep", fake_sleep)
    result = CliRunner().invoke(
        status_cmd, ["-p", str(path), "--watch", "0.25"]
    )
    assert result.exit_code == 0, result.output
    assert calls["n"] == 2
    assert "steps=3" in result.output and "steps=4" in result.output
    assert "health: ok" in result.output
    assert "watching" in result.output
