"""Benchmark-suite tests: analytic Pareto-front properties per problem
(reference oracle style: tests/test_moo_benchmarks.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dmosopt_tpu.benchmarks.moo_benchmarks import (
    PROBLEMS,
    generate_problem_space,
    get_problem,
    get_problem_metadata,
)


def _optimal_x(name, n_obj, n_var):
    """A point on the true Pareto set (distance variables at their optimum)."""
    x = np.full(n_var, 0.3)
    if name in ("dtlz1", "dtlz2", "dtlz3", "dtlz4", "dtlz5", "maf2", "maf4"):
        x[n_obj - 1 :] = 0.5  # g = 0
    elif name == "dtlz7":
        x[n_obj - 1 :] = 0.0  # g = 1
    return x


def test_dtlz1_front_property():
    # on the front: sum f_i = 0.5
    x = _optimal_x("dtlz1", 3, 7)
    f = np.asarray(get_problem("dtlz1", 3)(x))
    assert f.shape == (3,)
    assert np.sum(f) == pytest.approx(0.5, abs=1e-5)


@pytest.mark.parametrize("name", ["dtlz2", "dtlz3", "dtlz4", "maf2"])
def test_spherical_front_property(name):
    n_obj = 3 if name.startswith("dtlz") else 5
    n_var = n_obj + 9
    x = _optimal_x(name, n_obj, n_var)
    f = np.asarray(get_problem(name, n_obj)(x))
    assert np.sum(f**2) == pytest.approx(1.0, abs=1e-4)


def test_maf4_scaling():
    x = _optimal_x("maf4", 5, 14)
    f = np.asarray(get_problem("maf4", 5)(x))
    # scales 1, 100, ..., 10^8
    assert np.sum((f / 10.0 ** (2 * np.arange(5))) ** 2) == pytest.approx(
        1.0, abs=1e-4
    )


def test_dtlz7_head_objectives_pass_through():
    x = _optimal_x("dtlz7", 3, 22)
    f = np.asarray(get_problem("dtlz7", 3)(x))
    assert np.allclose(f[:2], x[:2], atol=1e-6)


@pytest.mark.parametrize("name", sorted(PROBLEMS))
def test_batched_matches_single_and_jits(name):
    n_obj = 5 if name.startswith("maf") else 3
    space = generate_problem_space(name, n_obj)
    n_var = len(space)
    lo = np.array([v[0] for v in space.values()])
    hi = np.array([v[1] for v in space.values()])
    rng = np.random.default_rng(0)
    X = (lo + rng.uniform(size=(8, n_var)) * (hi - lo)).astype(np.float32)
    fn = get_problem(name, n_obj)
    F_batch = np.asarray(jax.jit(fn)(jnp.asarray(X)))
    assert F_batch.shape == (8, n_obj)
    assert np.all(np.isfinite(F_batch))
    for i in (0, 7):
        f_single = np.asarray(fn(X[i]))
        assert np.allclose(f_single, F_batch[i], rtol=1e-5, atol=1e-5), name


def test_problem_space_and_metadata():
    space = generate_problem_space("dtlz1", 3)
    assert len(space) == 7
    space = generate_problem_space("wfg1", 3)
    assert space["x5"] == [0.0, 10.0]
    meta = get_problem_metadata("dtlz3", 5)
    assert meta["difficulty"] == "very_hard"
    assert meta["n_obj_in_standard_range"]


def test_wfg_high_objective_count_robust():
    # the reference crashes here (empty shape-vector block); ours must not
    fn = get_problem("wfg1", 5)
    space = generate_problem_space("wfg1", 5)
    n_var = len(space)
    x = np.full((4, n_var), 0.5) * 2 * np.arange(1, n_var + 1)
    f = np.asarray(fn(x.astype(np.float32)))
    assert f.shape == (4, 5) and np.all(np.isfinite(f))


@pytest.mark.slow
def test_dtlz7_m5_archive_quality_floor():
    """Pin the quality cliff that motivated the objective-count-resolved
    GP convergence defaults: bench config 4's DTLZ7-m5 run (shared
    params from bench.py — fixed surrogate budget n_starts=4 n_iter=100,
    with the d-resolved convergence `auto` defaults flowing through)
    must reach final HV >= 10.0 at the fixed reference point (10.3244
    measured; any convergence pair faster than the strict (1e-4, 20)
    collapses it to ~8.88 — BASELINE.md round-5)."""
    import dmosopt_tpu
    from bench import DTLZ_HV_REFS, dtlz_bench_params
    from dmosopt_tpu.benchmarks.moo_benchmarks import get_problem
    from dmosopt_tpu.driver import dopt_dict
    from dmosopt_tpu.hv import AdaptiveHyperVolume

    params = dict(
        dtlz_bench_params("dtlz7", opt_id="quality_floor_dtlz7"),
        obj_fun=get_problem("dtlz7", 5),
    )
    dmosopt_tpu.run(params, verbose=False)
    y = dopt_dict[params["opt_id"]].optimizer_dict[0].y
    ref, _ = DTLZ_HV_REFS["dtlz7"]
    hv = AdaptiveHyperVolume(np.asarray(ref), epsilon=0.02)
    final_hv = float(hv.compute_hypervolume(y))
    assert final_hv >= 10.0, (
        f"DTLZ7-m5 final HV {final_hv:.4f} below the 10.0 floor — "
        f"surrogate-fit accuracy regressed (see BASELINE.md round-5)"
    )


@pytest.mark.slow
def test_rank_throughput_microbench_memory_bound():
    """The `rank_throughput` microbench (large-pop evidence for the
    tiled ranking path): at pop 4096 x d 5 the tiled program's peak
    temp allocation must undercut the dense matrix peel's by >= 5x, and
    pop 16384 must complete — the scale where the peel's ~1.3 GB of
    (N, N) temporaries makes it unrunnable on this host."""
    import bench

    out = bench.bench_rank_throughput(pops=(4096, 16384), dims=(5,))
    rows = out["rank_throughput"]
    r4k = rows["rank_pop4096_d5"]
    assert r4k["peak_bytes_ratio"] >= 5.0, r4k
    assert r4k["points_per_sec"] > 0 and r4k["peel_wall_sec"] > 0
    r16k = rows["rank_pop16384_d5"]
    assert r16k["points_per_sec"] > 0  # tiled path actually ran at 16k
    assert r16k["peel_peak_temp_bytes"] > 1e9  # the blowup being removed
    assert r16k["tiled_peak_temp_bytes"] * 5 < r16k["peel_peak_temp_bytes"]


def test_surrogate_predict_microbench_smoke():
    """Fast-suite smoke of the `surrogate_predict` microbench harness at
    tiny N: every regime row materializes with positive walls, real
    cache/temp accounting, and the cross-N nystrom flatness ratio — so
    the bench config (`make bench-predict`) can't silently rot."""
    import bench

    out = bench.bench_surrogate_predict(
        archive_sizes=(64, 96), n_queries=16, nystrom_m=32, e2e=False
    )
    rows = out["surrogate_predict"]
    for n in (64, 96):
        row = rows[f"predict_n{n}"]
        for key in ("solve_ms", "matmul_ms", "nystrom_ms"):
            assert row[key] > 0, (n, key, row)
        assert row["matmul_cache_bytes"] == 2 * n * n * 4
        assert row["nystrom_m"] == 32
        assert row["nystrom_cache_bytes"] > 0
        for key in (
            "solve_temp_bytes", "matmul_temp_bytes", "nystrom_temp_bytes",
        ):
            assert row[key] >= 0, (n, key, row)
    assert rows["nystrom_flatness"] > 0
