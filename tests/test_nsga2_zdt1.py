"""End-to-end NSGA-II on ZDT1 (no surrogate): the minimum slice oracle.

Mirrors the reference solution-quality oracle
(tests/test_zdt1_nsga2_trs.py:39-72,117): after optimization, >= 30
population members must lie within epsilon of the analytic Pareto front.
"""

import numpy as np
import jax
import jax.numpy as jnp

from dmosopt_tpu.benchmarks.zdt import distance_to_front, zdt1, zdt1_pareto
from dmosopt_tpu.optimizers.base import run_ea_loop
from dmosopt_tpu.optimizers.nsga2 import NSGA2
from dmosopt_tpu import sampling


def _setup(popsize=100, dim=30, seed=0):
    bounds = np.stack([np.zeros(dim), np.ones(dim)], axis=1)
    x0 = sampling.lh(popsize * 2, dim, seed)
    y0 = np.asarray(zdt1(jnp.asarray(x0)))
    opt = NSGA2(popsize=popsize, nInput=dim, nOutput=2, model=None)
    opt.initialize_strategy(x0, y0, bounds, random=seed)
    return opt


def test_nsga2_state_shapes():
    opt = _setup(popsize=50, dim=10)
    s = opt.state
    assert s.population_parm.shape == (50, 10)
    assert s.population_obj.shape == (50, 2)
    assert s.rank.shape == (50,)


def test_nsga2_generate_update_roundtrip():
    opt = _setup(popsize=50, dim=10)
    x_gen, state = opt.generate()
    assert x_gen.shape == (50, 10)
    assert (np.asarray(x_gen) >= 0).all() and (np.asarray(x_gen) <= 1).all()
    y_gen = zdt1(x_gen)
    opt.update(x_gen, y_gen, state)
    assert opt.state.population_parm.shape == (50, 10)


def test_nsga2_converges_on_zdt1():
    popsize, dim = 100, 30
    opt = _setup(popsize=popsize, dim=dim, seed=1)
    key = jax.random.PRNGKey(2)
    # 300 generations: the reference oracle budget is 4 MOASMO epochs x ~200
    # surrogate generations (tests/test_zdt1_nsga2_trs.py:117); a direct-EA
    # run needs a comparable budget and 200 is seed-marginal.
    state = run_ea_loop(opt, opt.state, key, n_generations=300, eval_fn=zdt1)
    y = np.asarray(state.population_obj)
    dists = distance_to_front(y, zdt1_pareto(1000))
    n_on_front = int((dists <= 0.01).sum())
    assert n_on_front >= 30, f"only {n_on_front} solutions within eps of front"
    # front coverage: f1 spread should span a good part of [0, 1]
    on = y[dists <= 0.01]
    assert on[:, 0].max() - on[:, 0].min() > 0.5


def test_nsga2_improves_hypervolume_proxy():
    opt = _setup(popsize=64, dim=10, seed=3)
    y0 = np.asarray(opt.state.population_obj).mean(0).sum()
    state = run_ea_loop(
        opt, opt.state, jax.random.PRNGKey(4), n_generations=50, eval_fn=zdt1
    )
    y1 = np.asarray(state.population_obj).mean(0).sum()
    assert y1 < y0  # objectives (both minimized) improved on average


def test_nsga2_adaptive_rates_run():
    popsize, dim = 40, 8
    bounds = np.stack([np.zeros(dim), np.ones(dim)], axis=1)
    x0 = sampling.lh(popsize, dim, 5)
    y0 = np.asarray(zdt1(jnp.asarray(x0)))
    opt = NSGA2(
        popsize=popsize, nInput=dim, nOutput=2, model=None,
        adaptive_operator_rates=True,
    )
    opt.initialize_strategy(x0, y0, bounds, random=5)
    state = run_ea_loop(opt, opt.state, jax.random.PRNGKey(6), 10, zdt1)
    assert np.isfinite(float(state.crossover_prob))
    assert 0.0 < float(state.mutation_prob) <= 1.0
