"""Sharding/mesh tests on a virtual 8-device CPU mesh (SURVEY §4 item:
multi-device tests via xla_force_host_platform_device_count)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from dmosopt_tpu.parallel import (
    JaxBatchEvaluator,
    create_mesh,
    shard_population,
    shard_state,
)


needs_devices = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)


@needs_devices
def test_sharded_batch_evaluator_matches_single_device():
    from dmosopt_tpu.benchmarks.zdt import zdt1

    mesh = create_mesh(8)
    ev = JaxBatchEvaluator(zdt1, mesh=mesh, batch_axis="pop")
    rng = np.random.default_rng(0)
    # batch of 13 (not a multiple of 8): padding must be transparent
    reqs = [{0: rng.uniform(size=6).astype(np.float32)} for _ in range(13)]
    results = ev.evaluate_batch(reqs)
    assert len(results) == 13
    y_direct = np.asarray(zdt1(jnp.asarray(np.stack([r[0] for r in reqs]))))
    y_shard = np.stack([res[0] for res in results])
    np.testing.assert_allclose(y_shard, y_direct, rtol=1e-6)


@needs_devices
def test_sharded_nsga2_step_matches_replicated():
    """One NSGA-II generation over a sharded population produces the same
    result as unsharded (SPMD correctness)."""
    from dmosopt_tpu.benchmarks.zdt import zdt1
    from dmosopt_tpu.optimizers.nsga2 import NSGA2
    from dmosopt_tpu import sampling

    pop, dim = 32, 6
    bounds = np.stack([np.zeros(dim), np.ones(dim)], 1)
    x0 = sampling.lh(pop, dim, 0)
    y0 = np.asarray(zdt1(jnp.asarray(x0)))
    opt = NSGA2(popsize=pop, nInput=dim, nOutput=2, model=None)
    opt.initialize_strategy(x0, y0, bounds, random=0)

    def step(state, key):
        x_gen, state = opt.generate_strategy(key, state)
        x_gen = jnp.clip(x_gen, bounds[:, 0], bounds[:, 1])
        y_gen = zdt1(x_gen)
        return opt.update_strategy(state, x_gen, y_gen)

    key = jax.random.PRNGKey(5)
    ref_state = jax.jit(step)(opt.state, key)

    mesh = create_mesh(8)
    sharded = shard_state(opt.state, pop, mesh)
    out = jax.jit(step)(sharded, key)
    np.testing.assert_allclose(
        np.asarray(out.population_obj),
        np.asarray(ref_state.population_obj),
        rtol=1e-5, atol=1e-5,
    )


@needs_devices
def test_shard_population_layout():
    mesh = create_mesh(8)
    x = jnp.zeros((40, 4))
    xs = shard_population(x, mesh)
    assert len(xs.sharding.device_set) == 8


@needs_devices
def test_sharded_surrogate_epoch_matches_replicated():
    """A full surrogate-mode MO-ASMO epoch with the production `mesh`
    plumbing (moasmo.optimize -> _optimize_on_device -> shard_state)
    produces the same trajectory as the replicated run."""
    from dmosopt_tpu import moasmo, sampling
    from dmosopt_tpu.benchmarks.zdt import zdt1
    from dmosopt_tpu.models import Model
    from dmosopt_tpu.models.gp import GPR_Matern
    from dmosopt_tpu.optimizers.nsga2 import NSGA2

    pop, dim = 32, 6
    x0 = sampling.lh(64, dim, 11)
    y0 = np.asarray(zdt1(jnp.asarray(x0)))
    sm = GPR_Matern(
        x0, y0, dim, 2, np.zeros(dim), np.ones(dim),
        seed=0, n_starts=2, n_iter=20,
    )
    mdl = Model(objective=sm)

    def run(mesh):
        opt = NSGA2(popsize=pop, nInput=dim, nOutput=2, model=mdl)
        gen = moasmo.optimize(
            8, opt, mdl, dim, 2,
            np.zeros(dim), np.ones(dim),
            popsize=pop, initial=(x0, y0), local_random=3, mesh=mesh,
        )
        try:
            next(gen)
        except StopIteration as ex:
            return ex.value
        raise AssertionError("surrogate-mode optimize must not yield")

    res_repl = run(None)
    res_shard = run(create_mesh(8))
    np.testing.assert_allclose(
        res_shard.y, res_repl.y, rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(
        res_shard.best_y, res_repl.best_y, rtol=1e-4, atol=1e-4
    )


@needs_devices
def test_driver_run_with_mesh():
    """Top-level run() accepts a mesh and drives a sharded epoch."""
    import dmosopt_tpu

    dim = 6

    def obj(pp):
        x = np.array([pp[f"x{i}"] for i in range(dim)])
        f1 = x[0]
        g = 1.0 + 9.0 / (dim - 1) * np.sum(x[1:])
        return np.array([f1, g * (1.0 - np.sqrt(f1 / g))])

    best = dmosopt_tpu.run(
        {
            "opt_id": "mesh_smoke",
            "obj_fun": obj,
            "objective_names": ["f1", "f2"],
            "space": {f"x{i}": [0.0, 1.0] for i in range(dim)},
            "problem_parameters": {},
            "n_initial": 6,
            "n_epochs": 2,
            "population_size": 16,
            "num_generations": 5,
            "resample_fraction": 0.5,
            "optimizer_name": "nsga2",
            "surrogate_method_name": "gpr",
            "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 10, "seed": 0},
            "random_seed": 7,
            "mesh": create_mesh(8),
        },
        verbose=False,
    )
    prms, lres = best
    y = np.column_stack([v for _, v in lres])
    assert np.all(np.isfinite(y))


@needs_devices
def test_gp_fit_sharded_model_axis_matches_unsharded():
    """The GP fit's multi-start axis sharded over a "model" mesh axis
    must produce the same fit as the unsharded program (same seed; the
    constraint only changes layout, not math)."""
    from dmosopt_tpu.models.gp import fit_gp_batch, gp_predict
    from dmosopt_tpu.utils.prng import as_key

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.random((48, 4)).astype(np.float32))
    Y = jnp.asarray(
        np.stack([np.sin(3 * np.asarray(X[:, 0])), np.asarray(X).sum(1)], 1)
        .astype(np.float32)
    )
    Y = (Y - Y.mean(0)) / Y.std(0)
    common = dict(n_starts=4, n_iter=40)

    plain = fit_gp_batch(as_key(1), X, Y, **common)
    mesh = create_mesh(8, axis_names=("pop", "model"), shape=(4, 2))
    sharded = fit_gp_batch(as_key(1), X, Y, mesh=mesh, **common)

    np.testing.assert_allclose(plain.amp, sharded.amp, rtol=2e-3)
    np.testing.assert_allclose(plain.ls, sharded.ls, rtol=2e-3)
    Xq = jnp.asarray(rng.random((16, 4)).astype(np.float32))
    mu0, v0 = gp_predict(plain, Xq)
    mu1, v1 = gp_predict(sharded, Xq)
    np.testing.assert_allclose(mu0, mu1, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(v0, v1, rtol=2e-3, atol=1e-5)


@needs_devices
def test_gp_predict_matmul_sharded_query_matches_unsharded():
    """The matmul predictor's query-axis sharding constraint (the seam
    the mesh-sharded inner EA loop rides) must not change results: same
    fit, same queries, constrained vs unconstrained predict agree to
    reduction-order tolerance, and the mesh-built predictor routes
    through the constrained program."""
    from dmosopt_tpu.models.gp import GPR_Matern, fit_gp_batch
    from dmosopt_tpu.models.predictor import (
        GPPredictor,
        build_whitened_cache,
        gp_predict_matmul,
    )
    from dmosopt_tpu.utils.prng import as_key
    from jax.sharding import NamedSharding, PartitionSpec

    rng = np.random.default_rng(4)
    dim = 4
    X = jnp.asarray(rng.random((56, dim)).astype(np.float32))
    Y = np.stack([np.sin(2 * np.asarray(X[:, 0])), np.asarray(X).sum(1)], 1)
    Y = jnp.asarray(((Y - Y.mean(0)) / Y.std(0)).astype(np.float32))
    fit = fit_gp_batch(as_key(2), X, Y, n_starts=2, n_iter=30)
    W = build_whitened_cache(fit)
    Xq = jnp.asarray(rng.random((64, dim)).astype(np.float32))  # 8 | 64

    mesh = create_mesh(8)
    shard = NamedSharding(mesh, PartitionSpec("pop"))
    m0, v0 = gp_predict_matmul(fit, W, Xq)
    m1, v1 = gp_predict_matmul(fit, W, Xq, query_sharding=shard)
    np.testing.assert_allclose(np.asarray(m1), np.asarray(m0), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(v1), np.asarray(v0), rtol=5e-3, atol=1e-5
    )

    p = GPPredictor(fit, "matern52", mode="matmul", mesh=mesh)
    assert p._query_sharding is not None
    m2, v2 = p.predict_normalized(Xq)
    np.testing.assert_array_equal(np.asarray(m2), np.asarray(m1))
    np.testing.assert_array_equal(np.asarray(v2), np.asarray(v1))


@needs_devices
def test_train_forwards_mesh_to_gp():
    """moasmo.train with a two-axis mesh forwards it into the exact-GP
    family (constructor names `mesh`) and the fit remains sound."""
    from dmosopt_tpu import moasmo
    from dmosopt_tpu.models.gp import GPR_Matern

    rng = np.random.default_rng(2)
    X = rng.random((40, 3))
    Y = np.stack([X[:, 0], X.sum(1)], 1)
    mesh = create_mesh(8, axis_names=("pop", "model"), shape=(4, 2))
    m = moasmo.train(
        3, 2, np.zeros(3), np.ones(3), X, Y, None,
        surrogate_method_name="gpr",
        surrogate_method_kwargs={"n_starts": 4, "n_iter": 30, "seed": 0},
        mesh=mesh,
    )
    assert isinstance(m, GPR_Matern)
    mu, var = m.predict(X[:5])
    assert np.all(np.isfinite(np.asarray(mu))) and np.all(np.asarray(var) > 0)


@needs_devices
def test_driver_run_with_mesh_jax_objective():
    """run() with a mesh AND jax_objective=True: the batch evaluator must
    shard over the mesh's leading axis whatever it is named (regression:
    it assumed an axis literally called "batch")."""
    import dmosopt_tpu

    def zdt1b(X):
        f1 = X[:, 0]
        g = 1.0 + 9.0 / (X.shape[1] - 1) * jnp.sum(X[:, 1:], axis=1)
        return jnp.stack([f1, g * (1.0 - jnp.sqrt(f1 / g))], axis=1)

    for mesh in (
        create_mesh(8),
        create_mesh(8, axis_names=("pop", "model"), shape=(4, 2)),
    ):
        best = dmosopt_tpu.run(
            {
                "opt_id": f"mesh_jax_{len(mesh.axis_names)}",
                "obj_fun": zdt1b,
                "jax_objective": True,
                "objective_names": ["f1", "f2"],
                "space": {f"x{i}": [0.0, 1.0] for i in range(6)},
                "problem_parameters": {},
                "n_initial": 3,
                "n_epochs": 2,
                "population_size": 16,
                "num_generations": 5,
                "optimizer_name": "nsga2",
                "surrogate_method_name": "gpr",
                "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 10, "seed": 0},
                "random_seed": 7,
                "mesh": mesh,
            },
            verbose=False,
        )
        y = np.column_stack([v for _, v in best[1]])
        assert np.isfinite(y).all()


# ------------------------------------------- explicit-collective ranking


@needs_devices
@pytest.mark.slow
def test_sharded_rank_bitwise_matches_tiled_and_peel():
    """The shard_map ranking sweep on the forced 8-device CPU mesh must
    be bitwise identical to both the single-device tiled sweep and the
    dense matrix-peel oracle — masks, duplicate rows, NaN rows, and
    populations that divide into neither the tile nor the shard count
    included (same evidence pattern as the multichip dryrun's parity
    check in __graft_entry__)."""
    from dmosopt_tpu.ops.dominance import _rank_matrix_peel, non_dominated_rank
    from dmosopt_tpu.parallel.mesh import non_dominated_rank_sharded

    assert jax.device_count() >= 8
    mesh = create_mesh(8, axis_names=("pop",))
    rng = np.random.default_rng(5)
    for trial in range(10):
        n = int(rng.integers(9, 500))  # rarely divisible by 8 or the tile
        d = int(rng.choice([3, 5]))
        Y = rng.random((n, d)).astype(np.float32)
        if n > 20:
            Y[rng.integers(0, n, 5)] = Y[rng.integers(0, n, 5)]
        if trial % 4 == 1:
            Y[rng.integers(0, n, 3), 0] = np.nan
        mask = jnp.asarray(rng.random(n) > 0.3) if trial % 3 == 0 else None
        tile = int(rng.choice([16, 48, 64]))
        ref = np.asarray(_rank_matrix_peel(jnp.asarray(Y), mask=mask))
        host = np.asarray(
            non_dominated_rank(jnp.asarray(Y), mask=mask, tile=tile)
        )
        sharded = np.asarray(
            non_dominated_rank_sharded(Y, mesh, mask=mask, tile=tile)
        )
        np.testing.assert_array_equal(sharded, ref, err_msg=f"trial {trial}")
        np.testing.assert_array_equal(sharded, host, err_msg=f"trial {trial}")


@needs_devices
@pytest.mark.slow
def test_sharded_rank_two_axis_mesh():
    """The pop-axis sweep composes with a 2-D ("pop", "model") mesh —
    the layout the multichip dryrun builds."""
    from dmosopt_tpu.ops.dominance import _rank_matrix_peel
    from dmosopt_tpu.parallel.mesh import non_dominated_rank_sharded

    mesh = create_mesh(8, axis_names=("pop", "model"), shape=(4, 2))
    rng = np.random.default_rng(9)
    Y = rng.random((257, 5)).astype(np.float32)
    got = np.asarray(non_dominated_rank_sharded(Y, mesh, axis="pop"))
    ref = np.asarray(_rank_matrix_peel(jnp.asarray(Y)))
    np.testing.assert_array_equal(got, ref)
