"""graftlint concurrency & state-integrity suite: thread-root resolver
units (lambda targets, partial submits, daemon threads, executors in
context managers, self-dispatched methods, dispatcher chains), the
true-positive / suppressed / clean fixture triple per new rule family
(shared-state-guard, lock-discipline, checkpoint-schema,
resource-lifecycle), the real-package mutation gates of the acceptance
criteria, the --bump-schema helper, and the incremental result cache.
Pure ast, like the rest of tests/test_graftlint.py."""

import os
import shutil
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.graftlint import load_context, run_lint  # noqa: E402
from tools.graftlint.engine import DEFAULT_TARGETS  # noqa: E402


def _mkpkg(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def _lint(tmp_path, files, rules=None, targets=("pkg",), options=None):
    root = _mkpkg(tmp_path, files)
    return run_lint(root, targets, rules=rules, options=options)


def _live(findings, rule=None):
    return [
        f for f in findings
        if not f.suppressed and (rule is None or f.rule == rule)
    ]


# ------------------------------------------------ thread-root resolver


def test_resolver_thread_targets_and_reachability(tmp_path):
    root = _mkpkg(tmp_path, {"pkg/a.py": """
        import threading

        def work(x):
            return x

        def helper():
            return work(1)

        def spawn():
            t = threading.Thread(target=helper)
            t.start()
            t.join()

        def eager():
            return helper()
    """})
    ctx = load_context(root, ("pkg",))
    assert ctx.functions["pkg.a.helper"].thread_target
    root_name = "pkg.a.helper"
    assert ctx.functions["pkg.a.helper"].thread_roots == {root_name}
    # reachable from the root, with provenance
    assert root_name in ctx.functions["pkg.a.work"].thread_roots
    # the spawner itself does not run on the thread
    assert not ctx.functions["pkg.a.spawn"].threaded
    assert not ctx.functions["pkg.a.eager"].threaded


def test_resolver_lambda_targets_and_partial_submits(tmp_path):
    root = _mkpkg(tmp_path, {"pkg/a.py": """
        import threading
        from functools import partial
        from concurrent.futures import ThreadPoolExecutor

        def lam_work():
            return 1

        def sub_work(cfg):
            return cfg

        def map_work(x):
            return x

        def spawn():
            threading.Thread(target=lambda: lam_work(), daemon=True).start()
            with ThreadPoolExecutor(max_workers=2) as pool:
                pool.submit(partial(sub_work, 1))
                list(pool.map(map_work, [1, 2]))
    """})
    ctx = load_context(root, ("pkg",))
    # the inline lambda is its own root; its callee is thread-reachable
    assert ctx.functions["pkg.a.lam_work"].threaded
    assert ctx.functions["pkg.a.sub_work"].thread_target  # partial unwrap
    assert ctx.functions["pkg.a.map_work"].thread_target  # pool.map


def test_resolver_self_method_target_and_dispatcher_chain(tmp_path):
    """`Thread(target=self._run)` resolves through self-dispatch, and a
    function forwarding a parameter to `.submit` (the service's
    `_submit_write`) makes its call-site arguments thread targets."""
    root = _mkpkg(tmp_path, {"pkg/a.py": """
        import threading

        def persisted(x):
            return x

        class Svc:
            def __init__(self, writer):
                self._writer = writer
                self._thread = threading.Thread(target=self._run, daemon=True)
                self._thread.start()

            def _run(self):
                pass

            def _submit_write(self, fn, *args):
                self._writer.submit(fn, *args)

            def stream(self):
                self._submit_write(persisted, 1)

            def close(self):
                self._thread.join()
    """})
    ctx = load_context(root, ("pkg",))
    assert ctx.functions["pkg.a.Svc._run"].thread_target
    assert "fn" in ctx.functions["pkg.a.Svc._submit_write"].dispatch_params
    assert ctx.functions["pkg.a.persisted"].thread_target
    assert "dispatched through" in ctx.functions["pkg.a.persisted"].thread_via


def test_resolver_dispatcher_of_dispatcher_chain(tmp_path):
    """A forwarder that hands its own parameter to ANOTHER dispatcher
    (two levels above the raw `.submit`) still marks call-site
    arguments as thread roots — the indirection a service refactor
    naturally introduces over `_submit_write`."""
    root = _mkpkg(tmp_path, {"pkg/a.py": """
        def work_two():
            return 2

        class Svc:
            def __init__(self, pool):
                self._pool = pool

            def inner(self, fn):
                self._pool.submit(fn)

            def outer(self, fn):
                self.inner(fn)

            def stream(self):
                self.outer(work_two)
    """})
    ctx = load_context(root, ("pkg",))
    assert "fn" in ctx.functions["pkg.a.Svc.inner"].dispatch_params
    assert "fn" in ctx.functions["pkg.a.Svc.outer"].dispatch_params
    assert ctx.functions["pkg.a.work_two"].thread_target


def test_resolver_jax_combinators_are_not_thread_dispatch(tmp_path):
    root = _mkpkg(tmp_path, {"pkg/a.py": """
        from jax import lax

        def body(x):
            return x

        def eager(X):
            return lax.map(body, X)
    """})
    ctx = load_context(root, ("pkg",))
    assert not ctx.functions["pkg.a.body"].thread_target
    assert ctx.functions["pkg.a.body"].traced_body  # still a jit region


# ------------------------------------------------ rule: shared-state-guard

_SHARED_STATE_SRC = {"pkg/a.py": """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.bad = 0
            self.guarded = 0
            self.atomic = 0
            self.local_only = 0

        def _worker(self):
            self.bad += 1
            with self._lock:
                self.guarded += 1
            self.atomic += 1  # graftlint: disable=shared-state-guard -- fixture: GIL-atomic monotonic counter, single writer

        def start(self):
            threading.Thread(target=self._worker, daemon=True).start()

        def snapshot(self):
            with self._lock:
                return self.bad + self.guarded + self.atomic

        def main_only(self):
            self.local_only += 1
            return self.local_only
"""}


def test_shared_state_guard_fixture(tmp_path):
    findings = _lint(
        tmp_path, _SHARED_STATE_SRC, rules=["shared-state-guard"]
    )
    live = _live(findings, "shared-state-guard")
    assert len(live) == 1, [f.format() for f in live]
    assert "'bad'" in live[0].message
    assert live[0].qualname == "pkg.a.Counter._worker"
    assert [f for f in findings if f.suppressed], "suppressed variant fires"
    # guarded / single-context attrs stay silent
    assert not any("'guarded'" in f.message for f in live)
    assert not any("'local_only'" in f.message for f in live)


def test_shared_state_guard_module_global_and_queue_exemption(tmp_path):
    findings = _lint(tmp_path, {"pkg/a.py": """
        import queue
        import threading

        CACHE = {}

        class Pump:
            def __init__(self):
                self._q = queue.Queue()

            def _worker(self):
                CACHE["k"] = 1
                self._q.put(1)

            def start(self):
                threading.Thread(target=self._worker, daemon=True).start()

            def read(self):
                return CACHE.get("k"), self._q.get_nowait()
    """}, rules=["shared-state-guard"])
    live = _live(findings, "shared-state-guard")
    # the module-global write races the main read; the Queue is exempt
    assert any("CACHE" in f.message for f in live), [f.format() for f in live]
    assert not any("_q" in f.message for f in live)


def test_shared_state_guard_caller_holds_lock_idiom(tmp_path):
    """A helper whose EVERY call site runs under the lock is lock-held
    (the repo's documented 'caller holds self._lock' discipline) — no
    finding; remove one guarded call site and the helper turns red."""
    files = {"pkg/a.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def _append(self, x):
                self.items.append(x)

            def _worker(self):
                with self._lock:
                    self._append(1)

            def start(self):
                threading.Thread(target=self._worker, daemon=True).start()

            def push(self, x):
                with self._lock:
                    self._append(x)
    """}
    findings = _lint(tmp_path, files, rules=["shared-state-guard"])
    assert not _live(findings), [f.format() for f in _live(findings)]

    # same class, but one call site drops the lock -> the helper's
    # entry condition collapses and the access is flagged
    leaky = files["pkg/a.py"].replace(
        """            def push(self, x):
                with self._lock:
                    self._append(x)""",
        """            def push(self, x):
                self._append(x)""",
    )
    findings = _lint(tmp_path / "b", {"pkg/a.py": leaky},
                     rules=["shared-state-guard"])
    live = _live(findings, "shared-state-guard")
    assert any("items" in f.message for f in live), [
        f.format() for f in live
    ]


# -------------------------------------------------- rule: lock-discipline


def test_lock_discipline_ordering_cycle(tmp_path):
    findings = _lint(tmp_path, {"pkg/a.py": """
        import threading

        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def ab():
            with lock_a:
                with lock_b:
                    pass

        def ba():
            with lock_b:
                with lock_a:
                    pass
    """}, rules=["lock-discipline"])
    live = _live(findings, "lock-discipline")
    assert len(live) == 1 and "cycle" in live[0].message, [
        f.format() for f in live
    ]


def test_lock_discipline_interprocedural_cycle_and_clean_order(tmp_path):
    """The A->B edge through a call (holding A, calling a function that
    takes B) composes with a lexical B->A elsewhere into a cycle; a
    consistent one-way order stays green."""
    findings = _lint(tmp_path, {"pkg/a.py": """
        import threading

        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def takes_b():
            with lock_b:
                pass

        def under_a():
            with lock_a:
                takes_b()

        def reversed_order():
            with lock_b:
                with lock_a:
                    pass
    """}, rules=["lock-discipline"])
    assert any("cycle" in f.message for f in _live(findings))

    clean = _lint(tmp_path / "clean", {"pkg/a.py": """
        import threading

        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def takes_b():
            with lock_b:
                pass

        def under_a():
            with lock_a:
                takes_b()
    """}, rules=["lock-discipline"])
    assert not _live(clean), [f.format() for f in _live(clean)]


def test_lock_discipline_manual_acquire_and_blocking(tmp_path):
    findings = _lint(tmp_path, {"pkg/a.py": """
        import subprocess
        import threading
        import time

        lock = threading.Lock()

        def manual():
            lock.acquire()
            lock.release()

        def protected():
            lock.acquire()
            try:
                pass
            finally:
                lock.release()

        def sleepy():
            with lock:
                time.sleep(1)

        def shelling():
            with lock:
                subprocess.run(["true"])

        def suppressed():
            with lock:
                time.sleep(0.1)  # graftlint: disable=lock-discipline -- fixture: deliberate bounded stall

        def clean():
            with lock:
                x = 1
            time.sleep(0)
            return x

        def str_join_is_fine():
            with lock:
                return ", ".join(["a", "b"])
    """}, rules=["lock-discipline"])
    live = _live(findings, "lock-discipline")
    by_qual = {}
    for f in live:
        by_qual.setdefault(f.qualname, []).append(f.message)
    assert "pkg.a.manual" in by_qual
    assert "acquire" in by_qual["pkg.a.manual"][0]
    assert "pkg.a.protected" not in by_qual
    assert "pkg.a.sleepy" in by_qual
    assert "pkg.a.shelling" in by_qual
    assert "pkg.a.clean" not in by_qual
    assert "pkg.a.str_join_is_fine" not in by_qual
    assert [f for f in findings if f.suppressed]


def test_lock_discipline_same_lock_nesting_and_rlock(tmp_path):
    findings = _lint(tmp_path, {"pkg/a.py": """
        import threading

        lock = threading.Lock()
        rlock = threading.RLock()

        def deadlock():
            with lock:
                with lock:
                    pass

        def reentrant_ok():
            with rlock:
                with rlock:
                    pass
    """}, rules=["lock-discipline"])
    live = _live(findings, "lock-discipline")
    assert len(live) == 1, [f.format() for f in live]
    assert "deadlock" in live[0].message
    assert live[0].qualname == "pkg.a.deadlock"


def test_lock_discipline_repo_is_clean():
    """The real tree's lock hierarchy (service -> handle -> accounting
    -> telemetry) is acyclic and free of blocking-under-lock — the
    invariant ROADMAP item 2's task-graph scheduler must preserve."""
    findings = run_lint(REPO, DEFAULT_TARGETS, rules=["lock-discipline"])
    assert not _live(findings), "\n".join(
        f.format() for f in _live(findings)
    )


# ------------------------------------------------ rule: checkpoint-schema

_CKPT_REGISTRY = {
    "version": 1,
    "writers": {"state": ["pkg.svc.save"]},
    "readers": ["pkg.svc.load"],
    "fields": {"state": {"a": {}, "b": {}}},
    "storage_arrays": "pkg.svc._ARRAYS",
    "storage_version": "pkg.svc._VERSION",
}

_CKPT_SRC = {"pkg/svc.py": """
    def save(tenant):
        state = {"a": tenant.a, "b": tenant.b}
        return {"state": state}

    def load(payload):
        st = payload["state"]
        return st["a"], st.get("b")
"""}


def test_checkpoint_schema_symmetric_is_green(tmp_path):
    findings = _lint(
        tmp_path, _CKPT_SRC, rules=["checkpoint-schema"],
        options={"checkpoint_registry": _CKPT_REGISTRY},
    )
    assert not _live(findings), [f.format() for f in _live(findings)]


def test_checkpoint_schema_write_without_read_is_red(tmp_path):
    src = {"pkg/svc.py": _CKPT_SRC["pkg/svc.py"].replace(
        'return st["a"], st.get("b")', 'return st["a"]'
    )}
    findings = _lint(
        tmp_path, src, rules=["checkpoint-schema"],
        options={"checkpoint_registry": _CKPT_REGISTRY},
    )
    live = _live(findings, "checkpoint-schema")
    assert len(live) == 1 and "never consumed" in live[0].message
    assert "'state.b'" in live[0].message

    # ... unless the registry marks it write_only (with its reason)
    reg = {
        **_CKPT_REGISTRY,
        "fields": {"state": {"a": {}, "b": {"write_only": True,
                                           "reason": "fixture"}}},
    }
    findings = _lint(
        tmp_path / "wo", src, rules=["checkpoint-schema"],
        options={"checkpoint_registry": reg},
    )
    assert not _live(findings)


def test_checkpoint_schema_read_without_write_and_drift(tmp_path):
    # reader consumes a field nobody writes
    src = {"pkg/svc.py": _CKPT_SRC["pkg/svc.py"].replace(
        'return st["a"], st.get("b")',
        'return st["a"], st.get("b"), st["ghost"]',
    )}
    findings = _lint(
        tmp_path, src, rules=["checkpoint-schema"],
        options={"checkpoint_registry": _CKPT_REGISTRY},
    )
    live = _live(findings, "checkpoint-schema")
    assert any("ghost" in f.message and "no writer" in f.message
               for f in live), [f.format() for f in live]

    # writer gains a field the registry does not know -> bump-schema hint
    src = {"pkg/svc.py": _CKPT_SRC["pkg/svc.py"].replace(
        '"b": tenant.b}', '"b": tenant.b, "c": 1}'
    )}
    findings = _lint(
        tmp_path / "w", src, rules=["checkpoint-schema"],
        options={"checkpoint_registry": _CKPT_REGISTRY},
    )
    live = _live(findings, "checkpoint-schema")
    assert any("bump-schema" in f.message for f in live)

    # writer drops a registered field -> red the other way
    src = {"pkg/svc.py": _CKPT_SRC["pkg/svc.py"].replace(
        ', "b": tenant.b}', '}'
    )}
    findings = _lint(
        tmp_path / "d", src, rules=["checkpoint-schema"],
        options={"checkpoint_registry": _CKPT_REGISTRY},
    )
    live = _live(findings, "checkpoint-schema")
    assert any("no longer written" in f.message for f in live)


def test_checkpoint_schema_storage_allowlist_and_version(tmp_path):
    src = {"pkg/svc.py": _CKPT_SRC["pkg/svc.py"] + (
        '    _ARRAYS = ("x", "y")\n'
        '    _VERSION = 2\n'
    )}
    reg = {
        **_CKPT_REGISTRY,
        "fields": {
            "state": {"a": {}, "b": {}},
            "arrays": {"x": {}, "y": {}, "z": {}},
        },
    }
    findings = _lint(
        tmp_path, src, rules=["checkpoint-schema"],
        options={"checkpoint_registry": reg},
    )
    live = _live(findings, "checkpoint-schema")
    msgs = "\n".join(f.message for f in live)
    assert "does not match the schema registry's arrays" in msgs
    assert "SCHEMA_VERSION" in msgs


def _copy_service_sandbox(tmp_path, mutate=None):
    dst = tmp_path / "dmosopt_tpu"
    dst.mkdir(parents=True)
    src = (REPO / "dmosopt_tpu" / "service.py").read_text()
    if mutate:
        src = mutate(src)
    (dst / "service.py").write_text(src)
    shutil.copy(REPO / "dmosopt_tpu" / "storage.py", dst / "storage.py")
    return tmp_path


def test_checkpoint_schema_real_package_green_and_mutation_red(tmp_path):
    """The acceptance gate: the shipped save/load paths are symmetric;
    deleting the `optimizer_draws` read from `_apply_restore` (the PR
    10 near-miss, verbatim) turns checkpoint-schema red."""
    root = _copy_service_sandbox(tmp_path / "green")
    findings = run_lint(root, ("dmosopt_tpu",), rules=["checkpoint-schema"])
    assert not _live(findings), [f.format() for f in _live(findings)]

    needle = 'draws = int(st.get("optimizer_draws", s.epoch_index + 1))'

    def mutate(src):
        assert needle in src
        return src.replace(needle, "draws = int(s.epoch_index + 1)")

    root = _copy_service_sandbox(tmp_path / "red", mutate)
    findings = run_lint(root, ("dmosopt_tpu",), rules=["checkpoint-schema"])
    live = _live(findings, "checkpoint-schema")
    assert len(live) == 1, [f.format() for f in live]
    assert "optimizer_draws" in live[0].message
    assert "never consumed" in live[0].message


# ----------------------------------------------- rule: resource-lifecycle


def test_resource_lifecycle_fixture(tmp_path):
    findings = _lint(tmp_path, {"pkg/a.py": """
        import threading
        from concurrent.futures import ThreadPoolExecutor

        def work():
            pass

        class Leaky:
            def __init__(self):
                self._t = threading.Thread(target=work)
                self._t.start()

        class Closed:
            def __init__(self):
                self._t = threading.Thread(target=work, daemon=True)
                self._t.start()

            def close(self):
                self._t.join()

        class Suppressed:
            def __init__(self):
                self._t = threading.Thread(target=work)  # graftlint: disable=resource-lifecycle -- fixture: process-lifetime service thread by design
                self._t.start()

        def local_leak():
            t = threading.Thread(target=work)
            t.start()

        def local_joined():
            t = threading.Thread(target=work)
            t.start()
            t.join()

        def local_daemon():
            threading.Thread(target=work, daemon=True).start()

        def pool_ctx():
            with ThreadPoolExecutor(max_workers=2) as pool:
                pool.submit(work)

        def pool_leak():
            pool = ThreadPoolExecutor(max_workers=2)
            pool.submit(work)
    """}, rules=["resource-lifecycle"])
    live = _live(findings, "resource-lifecycle")
    by_qual = {}
    for f in live:
        by_qual.setdefault(f.qualname, []).append(f.message)
    assert "pkg.a.Leaky.__init__" in by_qual  # no teardown path at all
    assert "no teardown path" in by_qual["pkg.a.Leaky.__init__"][0]
    assert "pkg.a.Closed.__init__" not in by_qual
    assert "pkg.a.Suppressed.__init__" not in by_qual
    assert "pkg.a.local_leak" in by_qual
    assert "pkg.a.local_joined" not in by_qual
    assert "pkg.a.local_daemon" not in by_qual
    assert "pkg.a.pool_ctx" not in by_qual
    assert "pkg.a.pool_leak" in by_qual
    assert [f for f in findings if f.suppressed]


def test_resource_lifecycle_alias_swap_and_resource_class(tmp_path):
    """The HostFunEvaluator teardown idiom — `pool, self._pool =
    self._pool, None` drained inside a nested closure — satisfies the
    rule, and a class OWNING such a resource class must close it."""
    findings = _lint(tmp_path, {"pkg/a.py": """
        import threading
        from concurrent.futures import ThreadPoolExecutor

        class Pooled:
            def __init__(self):
                self._pool = ThreadPoolExecutor(max_workers=2)

            def close(self):
                pool, self._pool = self._pool, None
                t = threading.Thread(
                    target=lambda: pool.shutdown(wait=True), daemon=True
                )
                t.start()
                t.join(5.0)

        class Owner:
            def __init__(self):
                self._writer = Pooled()

            def close(self):
                self._writer.close()

        class LeakyOwner:
            def __init__(self):
                self._writer = Pooled()

            def close(self):
                pass
    """}, rules=["resource-lifecycle"])
    live = _live(findings, "resource-lifecycle")
    quals = {f.qualname for f in live}
    assert quals == {"pkg.a.LeakyOwner.__init__"}, [
        f.format() for f in live
    ]


def test_resource_lifecycle_real_package_mutations(tmp_path):
    """Acceptance mutations on real modules: leaking the writer thread
    past `close()` and unguarding shared writer state both turn their
    rules red; the shipped source is green."""
    src = (REPO / "dmosopt_tpu" / "parallel" / "pipeline.py").read_text()
    dst = tmp_path / "leak" / "dmosopt_tpu" / "parallel"
    dst.mkdir(parents=True)
    assert "        self._thread.join()\n" in src
    (dst / "pipeline.py").write_text(
        src.replace("        self._thread.join()\n", "")
    )
    findings = run_lint(
        tmp_path / "leak", ("dmosopt_tpu",), rules=["resource-lifecycle"]
    )
    live = _live(findings, "resource-lifecycle")
    assert any("_thread" in f.message for f in live), [
        f.format() for f in live
    ]

    # shared-state: drop the state lock around the worker's error write
    dst = tmp_path / "race" / "dmosopt_tpu" / "parallel"
    dst.mkdir(parents=True)
    needle = (
        "    def _record_error(self, e: BaseException):\n"
        "        with self._state_lock:\n"
        "            self._error = e\n"
    )
    assert needle in src
    (dst / "pipeline.py").write_text(src.replace(
        needle,
        "    def _record_error(self, e: BaseException):\n"
        "        self._error = e\n",
    ))
    findings = run_lint(
        tmp_path / "race", ("dmosopt_tpu",), rules=["shared-state-guard"]
    )
    live = _live(findings, "shared-state-guard")
    assert any("_error" in f.message for f in live), [
        f.format() for f in live
    ]


# --------------------------------------------------- --bump-schema helper

_SCHEMA_REGISTRY_SRC = '''
SCHEMA_VERSION = 1
WRITERS = {"state": ["pkg.svc.save"]}
READERS = ["pkg.svc.load"]
FIELDS = {
    "state": {
        "a": {},
        "b": {"write_only": True, "reason": "kept for humans"},
    },
}
STORAGE_ARRAYS = "pkg.svc._ARRAYS"
STORAGE_VERSION = "pkg.svc._VERSION"
'''


def test_bump_schema_rewrites_fields_preserving_meta(tmp_path):
    from tools.graftlint.bump import bump_schema

    root = _mkpkg(tmp_path, _CKPT_SRC)
    reg_path = root / "checkpoint_registry.py"
    reg_path.write_text(_SCHEMA_REGISTRY_SRC)

    # in sync -> no-op, file untouched
    before = reg_path.read_text()
    assert bump_schema(root, ("pkg",), registry_path=reg_path) == {}
    assert reg_path.read_text() == before

    # writer gains "c" and drops "a" -> bump updates FIELDS, keeps b's
    # write_only meta verbatim
    (root / "pkg/svc.py").write_text(textwrap.dedent("""
        def save(tenant):
            state = {"b": tenant.b, "c": 1}
            return {"state": state}

        def load(payload):
            st = payload["state"]
            return st.get("c")
    """))
    changed = bump_schema(root, ("pkg",), registry_path=reg_path)
    assert changed == {"state": ({"c"}, {"a"})}
    ns = {}
    exec(reg_path.read_text(), ns)
    assert set(ns["FIELDS"]["state"]) == {"b", "c"}
    assert ns["FIELDS"]["state"]["b"] == {
        "write_only": True, "reason": "kept for humans"
    }
    assert ns["FIELDS"]["state"]["c"] == {}


def test_bump_schema_real_registry_is_in_sync():
    """The shipped checkpoint registry matches the shipped save path —
    a schema drift cannot land without its bump (mirrors the frozen-
    hash in-sync gate)."""
    import shutil as _shutil

    import tempfile

    from tools.graftlint.bump import DEFAULT_SCHEMA_REGISTRY, bump_schema

    with tempfile.TemporaryDirectory() as td:
        copy = Path(td) / "checkpoint_registry.py"
        _shutil.copy(DEFAULT_SCHEMA_REGISTRY, copy)
        changed = bump_schema(REPO, DEFAULT_TARGETS, registry_path=copy)
        assert changed == {}, f"schema registry out of sync: {changed}"


# --------------------------------------------------- incremental cache


def _cache_fixture(tmp_path):
    root = _mkpkg(tmp_path, {"pkg/a.py": """
        import jax

        @jax.jit
        def bad(x):
            print(x)
            return x
    """})
    return root


def test_cache_roundtrip_touch_and_invalidation(tmp_path):
    from tools.graftlint.cache import LintCache

    root = _cache_fixture(tmp_path)
    cache = LintCache(root)
    targets, rules = ("pkg",), ["hot-path-purity"]
    assert cache.load(targets, rules) is None  # cold

    findings = run_lint(root, targets, rules=rules)
    assert _live(findings)
    cache.store(targets, rules, findings)

    hit = cache.load(targets, rules)
    assert hit is not None
    assert [f.format() for f in hit] == [f.format() for f in findings]
    assert (root / ".graftlint_cache.json").is_file()

    # touch (mtime moves, content identical) -> still a hit
    p = root / "pkg" / "a.py"
    st = p.stat()
    os.utime(p, ns=(st.st_mtime_ns + 10**9, st.st_mtime_ns + 10**9))
    assert cache.load(targets, rules) is not None

    # different rule selection -> its own (empty) slot, AND storing it
    # must not evict the first entry (multi-entry cache)
    assert cache.load(targets, None) is None
    cache.store(targets, None, run_lint(root, targets))
    assert cache.load(targets, None) is not None
    assert cache.load(targets, rules) is not None
    # real edit -> miss for every entry
    p.write_text(p.read_text().replace("print(x)", "pass"))
    assert cache.load(targets, rules) is None
    assert cache.load(targets, None) is None


def test_cache_invalidates_on_new_target_file(tmp_path):
    from tools.graftlint.cache import LintCache

    root = _cache_fixture(tmp_path)
    cache = LintCache(root)
    findings = run_lint(root, ("pkg",))
    cache.store(("pkg",), None, findings)
    assert cache.load(("pkg",), None) is not None
    (root / "pkg" / "b.py").write_text("x = 1\n")
    assert cache.load(("pkg",), None) is None


def test_cache_cli_roundtrip_matches_uncached(tmp_path):
    """`python -m tools.graftlint` (the `make lint` surface) returns
    identical findings and exit status on the cached second run, and
    --no-cache never writes the cache file."""
    import subprocess

    env = {**os.environ, "PYTHONPATH": str(REPO)}
    cmd = [sys.executable, "-m", "tools.graftlint", "--select",
           "hot-path-purity,shared-state-guard"]
    first = subprocess.run(
        cmd, capture_output=True, text=True, cwd=str(REPO), env=env
    )
    second = subprocess.run(
        cmd, capture_output=True, text=True, cwd=str(REPO), env=env
    )
    assert first.returncode == second.returncode == 0
    assert first.stdout == second.stdout
    assert (REPO / ".graftlint_cache.json").is_file()
