"""GP surrogate tests: interpolation quality, variance sanity, API parity.

Oracle pattern follows the reference's surrogate usage: fit on a smooth
function, check the surrogate reproduces training targets and generalizes
(the reference logs surrogate MAE per epoch, dmosopt/dmosopt.py:1434-1449).
"""

import numpy as np
import pytest

from dmosopt_tpu.models.gp import EGP_Matern, GPR_Matern, GPR_RBF, MEGP_Matern


def _data(n=50, dim=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.random((n, dim))
    Y = np.stack(
        [np.sin(3.0 * X[:, 0]) + X[:, 1] ** 2, np.sum(X, axis=1)], axis=1
    )
    return X, Y


FAST = dict(n_starts=4, n_iter=100)


@pytest.mark.parametrize("cls", [GPR_Matern, GPR_RBF, EGP_Matern, MEGP_Matern])
def test_gp_interpolates_training_data(cls):
    X, Y = _data()
    m = cls(X, Y, 3, 2, np.zeros(3), np.ones(3), seed=1, **FAST)
    mu, var = m.predict(X)
    assert mu.shape == (50, 2)
    assert var.shape == (50, 2)
    assert np.all(np.asarray(var) > 0)
    mae = np.abs(np.asarray(mu) - Y).mean()
    assert mae < 0.2, mae


def test_bucket_padding_is_exact():
    """A bucket-padded masked fit must match the unpadded fit: identical
    math (padding decouples exactly), differing only by f32 reduction-order
    noise accumulated over the Adam trajectory."""
    import jax.numpy as jnp

    from dmosopt_tpu.models.gp import fit_gp_batch, gp_predict
    from dmosopt_tpu.utils.prng import as_key

    X, Y = _data(n=40)
    ym, ys = Y.mean(0), Y.std(0)
    Yn = (Y - ym) / ys
    Xq = jnp.asarray(_data(n=12, seed=7)[0], jnp.float32)

    Xj = jnp.asarray(X, jnp.float32)
    Yj = jnp.asarray(Yn, jnp.float32)
    fit_plain = fit_gp_batch(as_key(1), Xj, Yj, n_starts=3, n_iter=40)

    pad = 24
    Xp = jnp.concatenate([Xj, jnp.full((pad, 3), 0.5, jnp.float32)])
    Yp = jnp.concatenate([Yj, jnp.zeros((pad, 2), jnp.float32)])
    tm = jnp.concatenate([jnp.ones((40,)), jnp.zeros((pad,))]).astype(jnp.float32)
    fit_pad = fit_gp_batch(as_key(1), Xp, Yp, n_starts=3, n_iter=40, train_mask=tm)

    np.testing.assert_allclose(fit_plain.amp, fit_pad.amp, rtol=2e-2)
    np.testing.assert_allclose(fit_plain.ls, fit_pad.ls, rtol=2e-2)
    np.testing.assert_allclose(fit_plain.nmll, fit_pad.nmll, rtol=2e-2, atol=5e-3)
    mu0, v0 = gp_predict(fit_plain, Xq)
    mu1, v1 = gp_predict(fit_pad, Xq)
    np.testing.assert_allclose(mu0, mu1, rtol=1e-2, atol=5e-3)
    np.testing.assert_allclose(v0, v1, rtol=2e-2, atol=1e-4)


def test_gp_generalizes():
    X, Y = _data(n=80)
    Xt, Yt = _data(n=30, seed=9)
    m = GPR_Matern(X, Y, 3, 2, np.zeros(3), np.ones(3), seed=1, **FAST)
    mu, _ = m.predict(Xt)
    mae = np.abs(np.asarray(mu) - Yt).mean()
    assert mae < 0.25, mae


def test_gp_variance_grows_off_data():
    X, Y = _data(n=40)
    m = GPR_Matern(X, Y, 3, 2, np.zeros(3), np.ones(3), seed=1, **FAST)
    _, var_on = m.predict(X[:5])
    far = np.full((5, 3), 3.0)  # outside the unit box of training data
    _, var_off = m.predict(far)
    assert np.asarray(var_off).mean() > np.asarray(var_on).mean()


def test_gp_nan_filtering():
    X, Y = _data(n=40)
    Y = Y.copy()
    Y[3, 0] = np.nan
    m = GPR_Matern(X, Y, 3, 2, np.zeros(3), np.ones(3), seed=1, nan="remove", **FAST)
    # the NaN row is dropped; remaining rows are bucket-padded to a static
    # shape with the padding masked out
    assert int(np.asarray(m.fit.train_mask).sum()) == 39


def test_gp_evaluate_mean_variance_flag():
    X, Y = _data(n=30)
    m = GPR_Matern(
        X, Y, 3, 2, np.zeros(3), np.ones(3), seed=1, return_mean_variance=True, **FAST
    )
    out = m.evaluate(X[:4])
    assert isinstance(out, tuple) and len(out) == 2
    m2 = GPR_Matern(X, Y, 3, 2, np.zeros(3), np.ones(3), seed=1, **FAST)
    out2 = m2.evaluate(X[:4])
    assert not isinstance(out2, tuple)


def test_gp_single_output():
    X, Y = _data(n=30)
    m = GPR_Matern(X, Y[:, 0], 3, 1, np.zeros(3), np.ones(3), seed=1, **FAST)
    mu, var = m.predict(X[:7])
    assert mu.shape == (7, 1)


# ------------------------------------------------------- large-N routing


def test_large_n_routing_logic():
    """Dense-kernel registry names reroute to svgp past the threshold;
    import paths and sub-threshold sets are honored as given."""
    from dmosopt_tpu.moasmo import _route_large_n

    assert _route_large_n("gpr", 5000, 4096) == "svgp"
    assert _route_large_n("megp", 5000, 4096) == "svgp"
    assert _route_large_n("mdgp", 5000, 4096) == "svgp"
    assert _route_large_n("vgp", 5000, 4096) == "svgp"  # inducing set = N
    assert _route_large_n("gpr", 4096, 4096) == "gpr"  # at threshold: keep
    assert _route_large_n("svgp", 9999, 4096) == "svgp"
    # custom import paths are never rerouted
    assert (
        _route_large_n("my.pkg.MySurrogate", 9999, 4096) == "my.pkg.MySurrogate"
    )
    # None/0 disables
    assert _route_large_n("gpr", 9999, None) == "gpr"
    assert _route_large_n("gpr", 9999, 0) == "gpr"


@pytest.mark.slow
def test_large_n_train_routes_and_fits_10k():
    """moasmo.train at N=10k must not build the dense (N,N) kernel: the
    fit routes to the sparse family and completes on the CPU mesh
    (VERDICT r2 item 7; reference chunks instead,
    model_gpytorch.py:53-100)."""
    from dmosopt_tpu import moasmo
    from dmosopt_tpu.models.svgp import SVGP_Matern

    rng = np.random.default_rng(7)
    N, dim = 10_000, 6
    X = rng.random((N, dim))
    Y = np.stack(
        [np.sin(3.0 * X[:, 0]) + X[:, 1] ** 2, X.sum(axis=1)], axis=1
    )
    m = moasmo.train(
        dim,
        2,
        np.zeros(dim),
        np.ones(dim),
        X,
        Y,
        None,
        surrogate_method_name="gpr",
        surrogate_method_kwargs={
            "inducing_fraction": 0.01,
            "min_inducing": 64,
            "n_iter": 30,
            "batch_size": 256,
        },
    )
    assert isinstance(m, SVGP_Matern)
    mu, var = m.predict(X[:200])
    assert np.all(np.isfinite(np.asarray(mu)))
    assert np.all(np.asarray(var) > 0)
    # sparse fit still tracks the function
    mae = np.abs(np.asarray(mu) - Y[:200]).mean()
    assert mae < 0.5, mae


def test_large_n_reroute_filters_gpr_kwargs():
    """On reroute, kwargs tuned for the dense GP that the sparse trainer
    does not name are dropped (not silently swallowed by **kwargs)."""
    from dmosopt_tpu import moasmo
    from dmosopt_tpu.models.svgp import SVGP_Matern

    rng = np.random.default_rng(3)
    N, dim = 64, 3
    X = rng.random((N, dim))
    Y = np.stack([X[:, 0], X.sum(axis=1)], axis=1)
    m = moasmo.train(
        dim,
        2,
        np.zeros(dim),
        np.ones(dim),
        X,
        Y,
        None,
        surrogate_method_name="gpr",
        surrogate_method_kwargs={
            "large_n_threshold": 32,
            # GPR-only knobs: must be dropped on reroute, not passed through
            "n_starts": 4,
            "length_scale_bounds": (1e-2, 10.0),
            # shared/sparse knobs: forwarded
            "n_iter": 20,
            "min_inducing": 8,
            "inducing_fraction": 0.1,
            "batch_size": 32,
        },
    )
    assert isinstance(m, SVGP_Matern)
    mu, var = m.predict(X[:5])
    assert np.all(np.isfinite(np.asarray(mu)))


def test_scan_with_convergence_semantics():
    """The shared in-graph convergence harness (_scan_with_convergence):
    early exit when the winner stops improving, exact n_iter semantics
    when it never converges (remainder steps included), the remainder
    skipped when the final full chunk already converged, and tol=None
    reproducing the fixed-length scan bit for bit. Total step counts are
    pinned via both the iteration-counter carry and the returned
    n_steps."""
    import jax
    import jax.numpy as jnp

    from dmosopt_tpu.models.gp import _scan_with_convergence

    # carry layout contract: (params, opt_state, best_params, best_vals)
    def make_step(decrement):
        def step(carry, _):
            params, opt_state, best_params, best_vals = carry
            params = params + 1.0  # iteration counter in disguise
            vals = best_vals - decrement(params)
            return (params, opt_state, best_params, jnp.minimum(vals, best_vals)), None

        return step

    z = jnp.zeros(())
    v0 = jnp.asarray([10.0, 10.0])

    # steadily improving: never converges -> runs all n_iter steps,
    # including the remainder chunk (27 = 2 full chunks of 10 + 7)
    step = make_step(lambda p: 1.0)
    (p, _, _, vals), n_steps = _scan_with_convergence(
        step, (z, z, z, v0), 27, 1e-3, 10, jnp.min, jnp.float32
    )
    assert float(p) == 27.0 and int(n_steps) == 27
    np.testing.assert_allclose(np.asarray(vals), 10.0 - 27.0)

    # improvement collapses after step 10 -> stops after chunk 2 (the
    # chunk that observed no winner movement), far short of n_iter=1000
    step = make_step(lambda p: jnp.where(p <= 10.0, 1.0, 0.0))
    (p, _, _, _), n_steps = _scan_with_convergence(
        step, (z, z, z, v0), 1000, 1e-3, 10, jnp.min, jnp.float32
    )
    assert float(p) == 20.0 and int(n_steps) == 20

    # same collapse with a remainder in play (27 = 2 chunks + 7): the
    # final full chunk observed no improvement, so the remainder steps
    # are NOT owed — previously the `i_done == n_full` predicate alone
    # paid them unconditionally
    (p, _, _, _), n_steps = _scan_with_convergence(
        step, (z, z, z, v0), 27, 1e-3, 10, jnp.min, jnp.float32
    )
    assert float(p) == 20.0 and int(n_steps) == 20

    # tol=None: fixed-length scan, identical to lax.scan
    (p_none, _, _, vals_none), n_steps = _scan_with_convergence(
        step, (z, z, z, v0), 50, None, 10, jnp.min, jnp.float32
    )
    ref, _ = jax.lax.scan(step, (z, z, z, v0), None, length=50)
    assert float(p_none) == 50.0 and int(n_steps) == 50
    np.testing.assert_array_equal(np.asarray(vals_none), np.asarray(ref[3]))


def test_auto_convergence_defaults_resolve_by_objective_count():
    """The quality-critical default resolution: bi-objective fits get the
    fast pair, anything above gets the strict pair (DTLZ7-m5 final HV
    collapses under every faster combination — BASELINE.md)."""
    from dmosopt_tpu.models.gp import _resolve_convergence_defaults

    assert _resolve_convergence_defaults(2, "auto", None) == (1e-3, 10)
    assert _resolve_convergence_defaults(5, "auto", None) == (1e-4, 20)
    # explicit values pass through untouched, including None (disabled)
    assert _resolve_convergence_defaults(5, None, 7) == (None, 7)
    assert _resolve_convergence_defaults(2, 0.01, None) == (0.01, 10)
