"""GP surrogate tests: interpolation quality, variance sanity, API parity.

Oracle pattern follows the reference's surrogate usage: fit on a smooth
function, check the surrogate reproduces training targets and generalizes
(the reference logs surrogate MAE per epoch, dmosopt/dmosopt.py:1434-1449).
"""

import numpy as np
import pytest

from dmosopt_tpu.models.gp import EGP_Matern, GPR_Matern, GPR_RBF, MEGP_Matern


def _data(n=50, dim=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.random((n, dim))
    Y = np.stack(
        [np.sin(3.0 * X[:, 0]) + X[:, 1] ** 2, np.sum(X, axis=1)], axis=1
    )
    return X, Y


FAST = dict(n_starts=4, n_iter=100)


@pytest.mark.parametrize("cls", [GPR_Matern, GPR_RBF, EGP_Matern, MEGP_Matern])
def test_gp_interpolates_training_data(cls):
    X, Y = _data()
    m = cls(X, Y, 3, 2, np.zeros(3), np.ones(3), seed=1, **FAST)
    mu, var = m.predict(X)
    assert mu.shape == (50, 2)
    assert var.shape == (50, 2)
    assert np.all(np.asarray(var) > 0)
    mae = np.abs(np.asarray(mu) - Y).mean()
    assert mae < 0.2, mae


def test_gp_generalizes():
    X, Y = _data(n=80)
    Xt, Yt = _data(n=30, seed=9)
    m = GPR_Matern(X, Y, 3, 2, np.zeros(3), np.ones(3), seed=1, **FAST)
    mu, _ = m.predict(Xt)
    mae = np.abs(np.asarray(mu) - Yt).mean()
    assert mae < 0.25, mae


def test_gp_variance_grows_off_data():
    X, Y = _data(n=40)
    m = GPR_Matern(X, Y, 3, 2, np.zeros(3), np.ones(3), seed=1, **FAST)
    _, var_on = m.predict(X[:5])
    far = np.full((5, 3), 3.0)  # outside the unit box of training data
    _, var_off = m.predict(far)
    assert np.asarray(var_off).mean() > np.asarray(var_on).mean()


def test_gp_nan_filtering():
    X, Y = _data(n=40)
    Y = Y.copy()
    Y[3, 0] = np.nan
    m = GPR_Matern(X, Y, 3, 2, np.zeros(3), np.ones(3), seed=1, nan="remove", **FAST)
    assert m.fit.X.shape[0] == 39


def test_gp_evaluate_mean_variance_flag():
    X, Y = _data(n=30)
    m = GPR_Matern(
        X, Y, 3, 2, np.zeros(3), np.ones(3), seed=1, return_mean_variance=True, **FAST
    )
    out = m.evaluate(X[:4])
    assert isinstance(out, tuple) and len(out) == 2
    m2 = GPR_Matern(X, Y, 3, 2, np.zeros(3), np.ones(3), seed=1, **FAST)
    out2 = m2.evaluate(X[:4])
    assert not isinstance(out2, tuple)


def test_gp_single_output():
    X, Y = _data(n=30)
    m = GPR_Matern(X, Y[:, 0], 3, 1, np.zeros(3), np.ones(3), seed=1, **FAST)
    mu, var = m.predict(X[:7])
    assert mu.shape == (7, 1)
