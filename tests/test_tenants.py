"""Problem-batched multi-tenant core (dmosopt_tpu.tenants).

The regime-split contract: buckets of one (every single-problem run)
take the UNCHANGED sequential path — pinned bitwise against the baked
pre-PR trajectory hash — while buckets of two or more advance through
one compiled program whose per-tenant results are pinned against the
sequential path computed in the same process.
"""

import hashlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import dmosopt_tpu
from dmosopt_tpu import tenants
from dmosopt_tpu.benchmarks.zdt import zdt1
from dmosopt_tpu.driver import DistOptimizer, dopt_dict


def _zdt1_params(opt_id, *, tenant_batching=False, problem_ids=None,
                 n_epochs=2, population_size=16, num_generations=8,
                 surrogate_extra=None, telemetry=False, **extra):
    smk = {"n_starts": 2, "n_iter": 40, "seed": 0}
    smk.update(surrogate_extra or {})
    params = {
        "opt_id": opt_id,
        "obj_fun": zdt1,
        "jax_objective": True,
        "objective_names": ["f1", "f2"],
        "space": {f"x{i}": [0.0, 1.0] for i in range(6)},
        "problem_parameters": {},
        "n_initial": 4,
        "n_epochs": n_epochs,
        "population_size": population_size,
        "num_generations": num_generations,
        "resample_fraction": 0.5,
        "optimizer_name": "nsga2",
        "surrogate_method_name": "gpr",
        "surrogate_method_kwargs": smk,
        "random_seed": 17,
        "telemetry": telemetry,
        "tenant_batching": tenant_batching,
    }
    if problem_ids is not None:
        params["problem_ids"] = problem_ids
    params.update(extra)
    return params


# ------------------------------------------------- single-tenant bitwise pin


def test_single_tenant_trajectory_bitwise_pinned_through_batched_core():
    """tenant_batching=True with ONE problem must be byte-identical to
    the pre-PR HEAD: the bucket-of-one routes through the sequential
    path, so the archive hash equals the SAME baked SHA-256 the
    predictor-era pin (tests/test_gp_predictor.py) froze."""
    params = _zdt1_params(
        "tenants_pin", tenant_batching=True, n_epochs=3,
        population_size=24, num_generations=12,
    )
    dmosopt_tpu.run(params, verbose=False)
    strat = dopt_dict["tenants_pin"].optimizer_dict[0]
    x, y = strat.x, strat.y
    assert x.shape == (48, 6) and y.shape == (48, 2)
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(x.astype(np.float32)).tobytes())
    h.update(np.ascontiguousarray(y.astype(np.float32)).tobytes())
    assert h.hexdigest() == (
        "f62934d055ddfeba411ec700253d6d73ffabd199969d85fc2e8ae21f23783867"
    ), (float(np.sum(x.astype(np.float64))), float(np.sum(y.astype(np.float64))))


# ------------------------------------------------ batched vs sequential pins


def test_two_problem_batched_matches_sequential_bitwise(monkeypatch):
    """Two bucket-mates through the batched core produce per-tenant
    archives bitwise-equal to the sequential loop (same seeds, same
    process): the per-tenant PRNG streams are reproduced exactly and
    the vmapped programs run the same math."""
    routings = []
    orig = tenants.initialize_epochs_batched

    def spy(*a, **k):
        r = orig(*a, **k)
        routings.append(dict(r))
        return r

    # the driver imports the symbol from the module at call time, so
    # patching the module attribute intercepts every epoch
    monkeypatch.setattr(tenants, "initialize_epochs_batched", spy)

    dmosopt_tpu.run(
        _zdt1_params("tenants_seq2", problem_ids=set([0, 1])),
        verbose=False,
    )
    dmosopt_tpu.run(
        _zdt1_params(
            "tenants_bat2", tenant_batching=True, problem_ids=set([0, 1]),
        ),
        verbose=False,
    )
    # every epoch of both problems actually rode the batched path
    assert routings and all(
        set(r.values()) == {"batched"} for r in routings
    ), routings
    seq = dopt_dict["tenants_seq2"]
    bat = dopt_dict["tenants_bat2"]
    for pid in (0, 1):
        xs, ys = seq.optimizer_dict[pid].x, seq.optimizer_dict[pid].y
        xb, yb = bat.optimizer_dict[pid].x, bat.optimizer_dict[pid].y
        assert xs.shape == xb.shape and ys.shape == yb.shape
        np.testing.assert_array_equal(xs, xb)
        np.testing.assert_array_equal(ys, yb)


def test_batched_epoch_emits_bucket_telemetry():
    dmosopt_tpu.run(
        _zdt1_params(
            "tenants_tel", tenant_batching=True, problem_ids=set([0, 1]),
            telemetry=True,
        ),
        verbose=False,
    )
    reg = dopt_dict["tenants_tel"].telemetry.registry
    label = tenants.bucket_label(6, 2, 16)
    assert reg.counter_value(
        "tenant_bucket_epochs_total", bucket=label
    ) == 2.0  # one per epoch
    assert reg.counter_value("tenants_batched_total") == 4.0  # 2 x 2 epochs
    assert reg.gauge_value("tenant_bucket_size", bucket=label) == 2.0


# ------------------------------------------------------- component parity


def test_fit_gp_problems_matches_per_problem_fits():
    """The problems-axis fit is per-tenant bitwise-equal to standalone
    `fit_gp_batch` calls at the same padding capacity (vmap lifts the
    same program; per-problem Adam trajectories are independent)."""
    from dmosopt_tpu.models.gp import (
        _pad_to_bucket, fit_gp_batch, fit_gp_problems,
    )

    rng = np.random.default_rng(0)
    cap = 64
    Xs, Ys, Ms, keys = [], [], [], []
    for i, N in enumerate([20, 35, 50]):
        X = rng.uniform(size=(N, 3))
        Y = rng.normal(size=(N, 2))
        Xp, Yp, m = _pad_to_bucket(X, Y, cap=cap)
        Xs.append(jnp.asarray(Xp, jnp.float32))
        Ys.append(jnp.asarray(Yp, jnp.float32))
        Ms.append(jnp.asarray(m, jnp.float32))
        keys.append(jax.random.PRNGKey(i))

    common = dict(n_starts=2, n_iter=30, convergence_tol=None)
    fb = fit_gp_problems(
        jnp.stack(keys), jnp.stack(Xs), jnp.stack(Ys), jnp.stack(Ms),
        **common,
    )
    for i in range(3):
        fs = fit_gp_batch(keys[i], Xs[i], Ys[i], train_mask=Ms[i], **common)
        for name in ("amp", "ls", "noise", "alpha", "L", "nmll"):
            np.testing.assert_array_equal(
                np.asarray(getattr(fb, name)[i]),
                np.asarray(getattr(fs, name)),
                err_msg=f"problem {i} field {name}",
            )


def test_pad_to_bucket_cap_override():
    from dmosopt_tpu.models.gp import _pad_to_bucket

    X = np.zeros((10, 2))
    Y = np.zeros((10, 1))
    Xp, Yp, m = _pad_to_bucket(X, Y, cap=32)
    assert Xp.shape == (32, 2) and Yp.shape == (32, 1)
    assert m.sum() == 10
    with pytest.raises(ValueError):
        _pad_to_bucket(X, Y, cap=4)


# ------------------------------------------------------- eligibility gates


def test_eligibility_gates_route_sequential():
    """Configs the batched core does not cover fall back per tenant —
    and still complete the run."""
    params = _zdt1_params(
        "tenants_gate", tenant_batching=True, problem_ids=set([0, 1]),
        telemetry=True, n_epochs=1, num_generations=4,
        # a termination criterion is host-side state: sequential path
        termination_conditions={"strategy": "simple", "n_max_gen": 4},
    )
    dmosopt_tpu.run(params, verbose=False)
    reg = dopt_dict["tenants_gate"].telemetry.registry
    assert reg.counter_value("tenants_sequential_total") >= 2.0
    assert reg.counter_value("tenants_batched_total") == 0.0


def test_batch_eligibility_reasons():
    class FakeStrat:
        x = np.zeros((8, 3))
        optimizer_name = ("nsga2",)
        optimizer_kwargs = ({},)
        surrogate_method_name = "gpr"
        surrogate_method_kwargs = {}
        surrogate_custom_training = None
        sensitivity_method_name = None
        feasibility_method_name = None
        optimize_mean_variance = False
        termination = None
        refit_controller = None
        mesh = None
        distance_metric = None
        num_generations = 10

    ok = FakeStrat()
    assert tenants.batch_eligibility(ok) is None

    cases = [
        ("x", None, "empty archive"),
        ("optimizer_name", ("nsga2", "age"), "cycled"),
        ("optimizer_name", ("smpso",), "not batchable"),
        ("surrogate_method_name", "svgp", "not batchable"),
        ("optimize_mean_variance", True, "mean-variance"),
        ("termination", object(), "termination"),
        ("mesh", object(), "mesh"),
        ("surrogate_method_kwargs", {"predictor": "matmul"}, "predictor"),
        ("surrogate_method_kwargs", {"dtype": "float64"}, "float32"),
        ("surrogate_method_kwargs", {"surrogate_mesh": True}, "kwargs"),
        ("optimizer_kwargs", ({"adaptive_population_size": True},),
         "adaptive"),
    ]
    for attr, value, needle in cases:
        s = FakeStrat()
        setattr(s, attr, value)
        reason = tenants.batch_eligibility(s)
        assert reason is not None and needle in reason, (attr, reason)


# ------------------------------------------------- stats cardinality guard


def _driver_with_fake_strategies(opt_id, n_problems, **kwargs):
    d = DistOptimizer(
        opt_id, zdt1, jax_objective=True,
        objective_names=["f1", "f2"],
        space={"x0": [0.0, 1.0], "x1": [0.0, 1.0]},
        problem_parameters={},
        problem_ids=set(range(n_problems)),
        telemetry=False,
        **kwargs,
    )
    from types import SimpleNamespace

    for pid in d.problem_ids:
        d.optimizer_dict[pid] = SimpleNamespace(
            stats={"model_init_start": 10.0, "model_init_end": 11.0 + pid,
                   "eval_mean": 0.5 + pid}
        )
    return d


def test_get_stats_aggregates_beyond_limit():
    n = DistOptimizer._STATS_PER_PROBLEM_LIMIT + 4
    d = _driver_with_fake_strategies("stats_agg", n)
    out = d.get_stats()
    # no per-problem prefixes at 20 problems: flat in tenant count
    assert not any(k.startswith(f"{n - 1}_") for k in out)
    assert out["stats_n_problems"] == n
    assert out["model_init_mean"] == pytest.approx(
        np.mean([1.0 + pid for pid in range(n)])
    )
    assert out["eval_mean_mean"] == pytest.approx(
        np.mean([0.5 + pid for pid in range(n)])
    )


def test_get_stats_per_problem_below_limit_unchanged():
    d = _driver_with_fake_strategies("stats_pp", 2)
    out = d.get_stats()
    assert out["0_model_init"] == pytest.approx(1.0)
    assert out["1_model_init"] == pytest.approx(2.0)
    assert "stats_n_problems" not in out


def test_get_stats_per_problem_forced_beyond_limit():
    n = DistOptimizer._STATS_PER_PROBLEM_LIMIT + 4
    d = _driver_with_fake_strategies("stats_force", n, stats_per_problem=True)
    out = d.get_stats()
    assert out[f"{n - 1}_model_init"] == pytest.approx(float(n))


def test_stats_per_problem_validation():
    with pytest.raises(ValueError, match="stats_per_problem"):
        _driver_with_fake_strategies("stats_bad", 2, stats_per_problem="yes")


def test_batched_tenants_carry_cost_attribution():
    """Each batched tenant's stats carry its attributed share of the
    bucket's fit/EA/compile walls; shares sum to the measured bucket
    wall (exact by construction — the 5% acceptance gate is pinned far
    tighter), and `get_stats` serves them under the usual per-problem
    prefixes."""
    dmosopt_tpu.run(
        _zdt1_params(
            "tenants_cost", tenant_batching=True, problem_ids=set([0, 1]),
            telemetry=True,
        ),
        verbose=False,
    )
    d = dopt_dict["tenants_cost"]
    for pid in (0, 1):
        stats = d.optimizer_dict[pid].stats
        assert stats["cost_fit_seconds"] > 0
        assert stats["cost_ea_seconds"] > 0
        assert stats["cost_compile_seconds"] >= 0
    # the LAST bucket epoch's shares sum to its measured wall
    last = d.telemetry.log.records(kind="tenant_bucket")[-1].fields
    total = sum(
        d.optimizer_dict[pid].stats[k]
        for pid in (0, 1)
        for k in (
            "cost_fit_seconds", "cost_ea_seconds", "cost_compile_seconds",
        )
    )
    assert total == pytest.approx(last["fit_s"] + last["ea_s"], rel=1e-3)
    # per-problem stats prefixes (the PR 5 collision fix) apply to the
    # cost keys like any other numeric stat
    out = d.get_stats()
    assert out["0_cost_fit_seconds"] > 0 and out["1_cost_fit_seconds"] > 0
    assert "cost_fit_seconds" not in out
    # cumulative attribution across BOTH epochs matches the registry
    attributed = sum(
        d.telemetry.registry.snapshot()["counters"]
        .get("tenant_cost_seconds", {})
        .values()
    )
    walls = sum(
        ev.fields["fit_s"] + ev.fields["ea_s"]
        for ev in d.telemetry.log.records(kind="tenant_bucket")
    )
    assert attributed == pytest.approx(walls, rel=1e-3)


def test_get_stats_cost_keys_aggregate_beyond_limit():
    """Satellite: beyond the 16-problem guard the per-tenant cost keys
    aggregate to `_mean`s (never colliding into one unprefixed key —
    the PR 5 class)."""
    n = DistOptimizer._STATS_PER_PROBLEM_LIMIT + 4
    d = _driver_with_fake_strategies("stats_cost_agg", n)
    for pid in d.problem_ids:
        d.optimizer_dict[pid].stats.update(
            cost_fit_seconds=0.1 * (pid + 1),
            cost_ea_seconds=0.01,
            cost_compile_seconds=0.0,
        )
    out = d.get_stats()
    assert "cost_fit_seconds" not in out  # no unprefixed collision
    assert not any(k.startswith(f"{n - 1}_cost") for k in out)
    assert out["cost_fit_seconds_mean"] == pytest.approx(
        np.mean([0.1 * (pid + 1) for pid in range(n)])
    )
    assert out["cost_ea_seconds_mean"] == pytest.approx(0.01)
    assert out["stats_n_problems"] == n


def test_batched_tenants_carry_fit_stats():
    """The batched path records the same stats["objective"] fit summary
    the sequential epoch gets from mdl.get_stats()."""
    dmosopt_tpu.run(
        _zdt1_params(
            "tenants_stats", tenant_batching=True, problem_ids=set([0, 1]),
        ),
        verbose=False,
    )
    for pid in (0, 1):
        obj = dopt_dict["tenants_stats"].optimizer_dict[pid].stats["objective"]
        assert set(obj) >= {
            "loss", "nmll_per_objective", "n_steps", "n_iter_max",
            "early_stopped",
        }
        assert np.isfinite(obj["loss"]) and len(obj["nmll_per_objective"]) == 2
        assert 0 < obj["n_steps"] <= obj["n_iter_max"] == 40
