"""Surrogate-quality parity vs the reference's sklearn GP configuration
(VERDICT r1 item 7): fit both on identical data, compare held-out MAE and
predictive log-likelihood. The reference surrogate is one sklearn
GaussianProcessRegressor per objective with C*Matern(nu=2.5)+White
(reference model.py:1227-1229), float64 throughout."""

import os
import subprocess
import sys

import numpy as np
import pytest

sklearn = pytest.importorskip("sklearn")

from sklearn.gaussian_process import GaussianProcessRegressor
from sklearn.gaussian_process.kernels import (
    ConstantKernel as C,
    Matern,
    WhiteKernel,
)

import jax.numpy as jnp

from dmosopt_tpu.benchmarks.zdt import zdt1
from dmosopt_tpu.models.gp import GPR_Matern

D = 6


def _data(seed=0, n_train=64, n_test=200):
    rng = np.random.default_rng(seed)
    Xtr = rng.uniform(size=(n_train, D))
    Xte = rng.uniform(size=(n_test, D))
    Ytr = np.asarray(zdt1(jnp.asarray(Xtr.astype(np.float32))))
    Yte = np.asarray(zdt1(jnp.asarray(Xte.astype(np.float32))))
    return Xtr, Ytr, Xte, Yte


def _metrics(mu, var, Yte):
    mae = np.abs(mu - Yte).mean(axis=0)
    ll = (-0.5 * np.log(2 * np.pi * var) - 0.5 * (Yte - mu) ** 2 / var).mean(
        axis=0
    )
    return mae, ll


def _sklearn_reference(Xtr, Ytr, Xte, Yte):
    """The reference's surrogate: per-objective sklearn GP, reference
    kernel and bounds, y standardized as model.py:1216-1222 does."""
    ym, ys = Ytr.mean(0), Ytr.std(0)
    mu = np.empty((len(Xte), Ytr.shape[1]))
    var = np.empty_like(mu)
    for j in range(Ytr.shape[1]):
        k = (
            C(1.0, (1e-4, 1e3))
            * Matern(0.5, length_scale_bounds=(1e-3, 100.0), nu=2.5)
            + WhiteKernel(1e-6, (1e-9, 1e-2))
        )
        g = GaussianProcessRegressor(
            kernel=k, n_restarts_optimizer=7, random_state=0
        )
        g.fit(Xtr, (Ytr[:, j] - ym[j]) / ys[j])
        m, s = g.predict(Xte, return_std=True)
        mu[:, j] = m * ys[j] + ym[j]
        var[:, j] = (s * ys[j]) ** 2
    return _metrics(mu, var, Yte)


def test_f32_gp_parity_with_reference_sklearn():
    """f32 (TPU-native default): parity on nonlinear objectives; the
    documented 1e-4-relative jitter floor bounds error on near-noiseless
    ones (here: f1 = x0, exactly linear)."""
    Xtr, Ytr, Xte, Yte = _data()
    sm = GPR_Matern(
        Xtr, Ytr, D, 2, np.zeros(D), np.ones(D), seed=0, n_starts=8, n_iter=200
    )
    mu, var = map(np.asarray, sm.predict(Xte))
    mae, ll = _metrics(mu, var, Yte)
    mae_sk, ll_sk = _sklearn_reference(Xtr, Ytr, Xte, Yte)
    # nonlinear objective: within 25% of the reference's MAE
    assert mae[1] <= mae_sk[1] * 1.25, (mae, mae_sk)
    # noiseless objective: bounded by the documented f32 jitter floor
    assert mae[0] <= 5e-3, (mae, mae_sk)
    # calibrated predictive distribution (LL not far below reference)
    assert ll[1] >= ll_sk[1] - 0.25, (ll, ll_sk)


def test_f64_gp_matches_reference_sklearn():
    """dtype="float64" closes the jitter gap to the reference's float64
    sklearn numerics. Runs in a subprocess: x64 is a global jax mode."""
    code = r"""
import numpy as np, jax.numpy as jnp
from dmosopt_tpu.benchmarks.zdt import zdt1
from dmosopt_tpu.models.gp import GPR_Matern
rng = np.random.default_rng(0)
Xtr = rng.uniform(size=(64, 6)); Xte = rng.uniform(size=(200, 6))
Ytr = np.asarray(zdt1(jnp.asarray(Xtr.astype(np.float32))))
Yte = np.asarray(zdt1(jnp.asarray(Xte.astype(np.float32))))
sm = GPR_Matern(Xtr, Ytr, 6, 2, np.zeros(6), np.ones(6), seed=0,
                n_starts=8, n_iter=200, dtype="float64")
mu, var = map(np.asarray, sm.predict(Xte))
mae = np.abs(mu - Yte).mean(axis=0)
assert mu.dtype == np.float64
# measured: [1.8e-5, 3.94e-2] vs sklearn [6.6e-6, 3.94e-2]
assert mae[0] < 2e-4, mae   # ~100x below the f32 jitter floor
assert mae[1] < 4.5e-2, mae
print("F64_OK", mae[0], mae[1])
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "F64_OK" in proc.stdout
