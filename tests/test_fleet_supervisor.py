"""Fleet tier: worker supervision, failure detection, live migration.

The acceptance shape (ISSUE 15): a 2-worker fleet with >= 4 tenants
loses one worker to SIGKILL mid-epoch and NOTHING is lost — the
survivor adopts the dead worker's tenants from its lease-stamped
epoch-boundary checkpoint and every final front is bitwise-equal to an
uninterrupted single-service run; the ownership lease makes double
adoption structurally impossible (docs/robustness.md "Fleet failure
model").
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from dmosopt_tpu.fleet import (
    AdmissionPolicy,
    FleetAdmissionError,
    FleetSupervisor,
    LivenessPolicy,
)
from dmosopt_tpu.fleet.objectives import host_zdt1
from dmosopt_tpu.fleet.wire import read_json
from dmosopt_tpu.service import OptimizationService
from dmosopt_tpu.storage import (
    CheckpointLeaseError,
    load_fronts_from_h5,
    load_service_checkpoint_from_h5,
)

SMK = {"n_starts": 2, "n_iter": 20, "seed": 0}
SPACE4 = {f"x{i}": [0.0, 1.0] for i in range(4)}
SUBMIT_KW = dict(
    jax_objective=False,
    n_epochs=4,
    population_size=16,
    num_generations=4,
    n_initial=3,
    surrogate_method_kwargs=SMK,
)
OBJECTIVE_REF = "dmosopt_tpu.fleet.objectives:host_zdt1"


def _fleet_spec(i, tmp_path, **overrides):
    spec = {
        "opt_id": f"t{i}",
        "objective": OBJECTIVE_REF,
        "space": dict(SPACE4),
        "objective_names": ["f1", "f2"],
        "random_seed": 40 + i,
        "file_path": str(tmp_path / "results" / f"t{i}.h5"),
        **SUBMIT_KW,
    }
    spec.update(overrides)
    return spec


def _fronts(handle):
    return [(u.epoch, u.x.copy(), u.y.copy()) for u in handle.updates()]


# --------------------------------------------------------------- lease unit


def test_lease_claim_adopt_bitwise_and_double_adoption_refused(tmp_path):
    """The migration wire format end-to-end, in-process: worker service
    w0 checkpoints two epoch boundaries and 'dies'; a survivor service
    that already owns a tenant adopts w0's checkpoint under the lease
    protocol and finishes the migrated tenants BITWISE-equal to an
    uninterrupted reference run. A second adoption attempt — the
    double-ownership hazard — raises `CheckpointLeaseError`."""
    ck = str(tmp_path / "w0.h5")

    ref = OptimizationService(telemetry=False)
    rh = {
        f"t{i}": ref.submit(
            host_zdt1, SPACE4, ["f1", "f2"],
            opt_id=f"t{i}", random_seed=40 + i, **SUBMIT_KW,
        )
        for i in range(2)
    }
    ref.run()
    ref_fronts = {k: _fronts(h) for k, h in rh.items()}
    ref.close()

    w0 = OptimizationService(
        telemetry=False, checkpoint_path=ck, owner="w0", placement_epoch=0
    )
    for i in range(2):
        w0.submit(
            None, SPACE4, ["f1", "f2"], opt_id=f"t{i}",
            random_seed=40 + i, objective_ref=OBJECTIVE_REF, **SUBMIT_KW,
        )
    w0.step()
    w0.step()
    # no close(): the checkpoint on disk is the last epoch boundary,
    # exactly what a SIGKILL would leave

    data = load_service_checkpoint_from_h5(ck)
    assert data["service"]["owner"] == "w0"
    assert data["service"]["placement_epoch"] == 0

    w1 = OptimizationService(telemetry=True, owner="w1", placement_epoch=0)
    own = w1.submit(
        host_zdt1, SPACE4, ["f1", "f2"], opt_id="own",
        random_seed=99, **SUBMIT_KW,
    )
    adopted = w1.adopt_checkpoint(ck, expected_owner="w0", placement_epoch=1)
    assert sorted(adopted) == ["t0", "t1"]
    assert (
        w1.telemetry.registry.counter_value("tenants_adopted_total") == 2.0
    )

    # the claim rewrote the lease: a SECOND survivor handed the same
    # migration order is refused before it can double-own the tenants
    w2 = OptimizationService(telemetry=False, owner="w2")
    with pytest.raises(CheckpointLeaseError):
        w2.adopt_checkpoint(ck, expected_owner="w0", placement_epoch=2)
    # and a stale fencing token is refused even with the right owner
    with pytest.raises(CheckpointLeaseError):
        w2.adopt_checkpoint(ck, expected_owner="w1", placement_epoch=1)
    # the adopter itself re-running the order trips the duplicate
    # opt_id validation BEFORE the lease is touched
    with pytest.raises(ValueError):
        w1.adopt_checkpoint(ck, expected_owner="w1", placement_epoch=2)
    w2.close()
    stamped = load_service_checkpoint_from_h5(ck)["service"]
    assert stamped["owner"] == "w1"
    assert stamped["placement_epoch"] == 1
    assert stamped["claimed_from"] == "w0"

    w1.run()
    for k, h in adopted.items():
        got = _fronts(h)
        assert [e for e, _, _ in got] == [2, 3]
        for (e, x, y), (er, xr, yr) in zip(got, ref_fronts[k][2:]):
            assert e == er
            np.testing.assert_array_equal(x, xr)
            np.testing.assert_array_equal(y, yr)
        assert h.done and h.error is None
    assert own.done and own.error is None
    w1.close()


def test_resume_honors_and_checks_lease(tmp_path):
    """`resume` keeps the stored lease identity by default and refuses
    a checkpoint whose owner is not the expected one."""
    ck = str(tmp_path / "svc.h5")
    svc = OptimizationService(
        telemetry=False, checkpoint_path=ck, owner="w7", placement_epoch=3
    )
    svc.submit(
        None, SPACE4, ["f1", "f2"], opt_id="a", random_seed=1,
        objective_ref=OBJECTIVE_REF, **SUBMIT_KW,
    )
    svc.step()

    with pytest.raises(CheckpointLeaseError):
        OptimizationService.resume(
            ck, {}, telemetry=False, checkpoint=False,
            expected_owner="someone_else",
        )
    svc2, handles = OptimizationService.resume(
        ck, {}, telemetry=False, checkpoint=False, expected_owner="w7",
    )
    # no objectives dict needed: the stored objective_ref resolves
    assert sorted(handles) == ["a"]
    assert svc2.owner == "w7" and svc2.placement_epoch == 3
    svc2.close()
    svc.close()


# --------------------------------------------------- admission + placement


def _fake_status(wid, *, ts=None, tenants=None, load_ratio=0.1,
                 thr_status="ok", exporter=None):
    return {
        "worker_id": wid,
        "pid": 1,
        "seq": 1,
        "ts": time.time() if ts is None else ts,
        "state": "running",
        "steps": 1,
        "exporter": exporter,
        "tenants": tenants or {},
        "lease_conflicts": 0,
        "service": {
            "throughput": {"status": thr_status, "load_ratio": load_ratio},
        },
    }


def test_admission_caps_shedding_and_weighted_placement(tmp_path):
    """Placement unit (no subprocesses): the EA-budget cap sheds,
    all-contended sheds, and an unpinned submission lands on the
    least-loaded worker by remaining-budget + attributed-cost weight."""
    from dmosopt_tpu.fleet.wire import atomic_write_json, worker_dir

    sup = FleetSupervisor(
        str(tmp_path), n_workers=2, telemetry=True,
        admission=AdmissionPolicy(max_ea_budget=1000),
    )
    for w in sup.workers.values():
        os.makedirs(w.dir, exist_ok=True)
        w.state = "alive"

    # budget cap: 16 * 40 * 4 = 2560 > 1000 -> shed
    with pytest.raises(FleetAdmissionError):
        sup.submit(_fleet_spec(9, tmp_path, num_generations=40))
    assert sup.shed[0]["reason"] == "budget"
    assert (
        sup.telemetry.registry.counter_value(
            "fleet_tenants_shed_total", reason="budget"
        )
        == 1.0
    )

    # weighted placement: w0 is busy (an active tenant with most of its
    # budget remaining plus attributed cost), w1 idle -> w1 wins
    atomic_write_json(
        os.path.join(worker_dir(str(tmp_path), "w0"), "status.json"),
        _fake_status(
            "w0",
            tenants={
                "busy": {
                    "state": "active", "epoch": 0, "n_epochs": 4,
                    "cost_seconds": {"fit": 5.0, "ea": 5.0},
                }
            },
        ),
    )
    atomic_write_json(
        os.path.join(worker_dir(str(tmp_path), "w1"), "status.json"),
        _fake_status("w1"),
    )
    sup.placements["busy"] = {"worker": "w0", "budget": 256, "spec": {}}
    placement = sup.submit(_fleet_spec(0, tmp_path))
    assert placement["worker"] == "w1"
    inbox = os.listdir(os.path.join(worker_dir(str(tmp_path), "w1"), "inbox"))
    assert any(n.endswith("-submit.json") for n in inbox)

    # every worker contended -> shed (the rejection path)
    for wid in ("w0", "w1"):
        atomic_write_json(
            os.path.join(worker_dir(str(tmp_path), wid), "status.json"),
            _fake_status(wid, thr_status="host_contended", load_ratio=9.9),
        )
    with pytest.raises(FleetAdmissionError):
        sup.submit(_fleet_spec(1, tmp_path))
    assert sup.shed[-1]["reason"] == "contended"
    sup._closed = True  # no processes were spawned; nothing to stop


def test_heartbeat_hysteresis_and_checkpointless_migration(tmp_path):
    """Failure-detector unit (no subprocesses): a stale heartbeat must
    persist for `confirm_rounds` CONSECUTIVE rounds before the worker
    is declared dead; with no checkpoint on disk the migration falls
    back to restart-from-spec submit orders on the survivor."""
    from dmosopt_tpu.fleet.wire import atomic_write_json, worker_dir

    sup = FleetSupervisor(
        str(tmp_path), n_workers=2, telemetry=True,
        liveness=LivenessPolicy(
            heartbeat_timeout=5.0, confirm_rounds=2, fence_grace=0.1
        ),
    )
    for w in sup.workers.values():
        os.makedirs(w.dir, exist_ok=True)
        w.state = "alive"
        w.spawn_ts = time.monotonic()
    atomic_write_json(
        os.path.join(worker_dir(str(tmp_path), "w0"), "status.json"),
        _fake_status("w0", ts=time.time() - 600.0),  # long stale
    )
    atomic_write_json(
        os.path.join(worker_dir(str(tmp_path), "w1"), "status.json"),
        _fake_status("w1"),
    )
    sup.placements["t0"] = {
        "worker": "w0", "budget": 256, "spec": _fleet_spec(0, tmp_path),
    }
    sup.tenant_states["t0"] = "placed"

    events = sup.monitor_once()
    assert events == []  # round 1: suspect, hysteresis holds
    assert sup.workers["w0"].state == "suspect"
    events = sup.monitor_once()  # round 2: confirmed dead
    kinds = [e["event"] for e in events]
    assert "worker_dead" in kinds and "migration" in kinds
    migration = next(e for e in events if e["event"] == "migration")
    assert migration["checkpoint_claimed"] is False
    assert migration["resubmitted"] == ["t0"]
    assert sup.placements["t0"]["worker"] == "w1"
    assert os.path.exists(
        os.path.join(worker_dir(str(tmp_path), "w0"), "fence")
    )
    inbox = os.listdir(os.path.join(worker_dir(str(tmp_path), "w1"), "inbox"))
    assert any(n.endswith("-submit.json") for n in inbox)
    reg = sup.telemetry.registry
    assert reg.counter_value("fleet_worker_deaths_total", worker="w0") == 1.0
    assert reg.counter_value("fleet_migrations_total") == 1.0
    # a healthy heartbeat never accumulates suspicion
    assert sup.workers["w1"].suspect_rounds == 0
    sup._closed = True


# --------------------------------------------------------- worker harness


def test_worker_harness_fault_kinds_and_flags(tmp_path, monkeypatch):
    """Worker-level fault kinds and control flags, in-process: a
    ``heartbeat_hang`` rule mutes the status heartbeat while it fires,
    ``partition`` additionally closes the exporter (probe blackhole),
    a fence flag exits with `EXIT_FENCED` writing nothing, a stop flag
    closes gracefully."""
    from dmosopt_tpu.fleet.wire import EXIT_FENCED, EXIT_OK, touch_flag
    from dmosopt_tpu.fleet.worker import WorkerHarness

    plan = {
        "seed": 0,
        "rules": [
            {"kind": "heartbeat_hang", "op": "worker", "target": "wh",
             "after": 0, "count": 2},
            {"kind": "partition", "op": "worker", "target": "wh",
             "after": 2, "count": 1},
        ],
    }
    monkeypatch.setenv("DMOSOPT_FAULT_PLAN", json.dumps(plan))
    h = WorkerHarness(
        str(tmp_path), "wh", poll=0.01, exporter=True, telemetry=True
    )
    status0 = read_json(h._status_path)
    assert status0["state"] == "starting"
    assert status0["exporter"]["port"] > 0  # ephemeral bind surfaced

    h.run(max_loops=2)  # both loops heartbeat_hang -> no status writes
    st = read_json(h._status_path)
    assert st["seq"] == status0["seq"] == 0  # heartbeat stayed muted
    assert st["state"] == "starting"

    h.run(max_loops=1)  # partition loop: exporter closed, still muted
    assert h.service.exporter is None
    assert read_json(h._status_path)["state"] == "starting"
    h.run(max_loops=1)  # plan exhausted: heartbeat resumes
    st = read_json(h._status_path)
    assert st["state"] == "running" and st["seq"] >= 1
    assert st["exporter"] is None  # the blackhole is visible
    h.service.close()

    # fence beats everything and writes nothing
    h3 = WorkerHarness(str(tmp_path), "wf", poll=0.01, exporter=False,
                       telemetry=False)
    touch_flag(h3._fence_path)
    before = read_json(h3._status_path)
    assert h3.run() == EXIT_FENCED
    assert read_json(h3._status_path) == before  # no further writes
    h3.service.close()

    h4 = WorkerHarness(str(tmp_path), "ws", poll=0.01, exporter=False,
                       telemetry=False)
    touch_flag(h4._stop_path)
    assert h4.run() == EXIT_OK
    assert read_json(h4._status_path)["state"] == "stopped"


# ----------------------------------------------------- exporter coexistence


def test_exporter_ephemeral_ports_coexist_and_surface(tmp_path):
    """Multi-worker single-host satellite: N services with
    ``exporter=True`` bind DISTINCT ephemeral ports, each surfaced
    through ``introspect()["exporter"]`` and rendered by the `status`
    CLI — and each /metrics endpoint serves its own registry."""
    import urllib.request

    from click.testing import CliRunner

    from dmosopt_tpu.cli import status as status_cmd
    from dmosopt_tpu.utils import json_default

    svcs = [OptimizationService(telemetry=True, exporter=True)
            for _ in range(3)]
    try:
        ports = [s.introspect()["exporter"]["port"] for s in svcs]
        assert len(set(ports)) == 3 and all(p > 0 for p in ports)
        for s in svcs:
            snap = s.introspect()
            url = snap["exporter"]["url"]
            with urllib.request.urlopen(url + "/metrics", timeout=5) as r:
                assert r.status == 200

        status_path = tmp_path / "status.json"
        status_path.write_text(
            json.dumps(svcs[0].introspect(), default=json_default)
        )
        out = CliRunner().invoke(status_cmd, ["-p", str(status_path)])
        assert out.exit_code == 0, out.output
        assert f":{ports[0]}" in out.output
    finally:
        for s in svcs:
            s.close()


# --------------------------------------------------------- subprocess fleet


def _supervisor(tmp_path, n_workers=2, worker_env=None):
    return FleetSupervisor(
        str(tmp_path), n_workers=n_workers, telemetry=True,
        liveness=LivenessPolicy(
            heartbeat_timeout=20.0, confirm_rounds=2, fence_grace=10.0,
            probe_timeout=2.0, probe_retries=1,
        ),
        worker_env=worker_env,
        python=sys.executable,
    )


def test_fleet_kill9_migration_bitwise(tmp_path):
    """THE acceptance test: 2 workers, 4 tenants (2 per worker), one
    worker SIGKILLed mid-epoch by an armed eval-op kill rule. The
    supervisor confirms the death, fences the corpse, claims its
    checkpoint under the lease, and the survivor adopts — every tenant
    completes, and ALL final fronts are bitwise-equal to an
    uninterrupted single-service run of the same 4 tenants. Exactly
    one migration, zero lease conflicts, no tenant ever owned twice."""
    # ---- uninterrupted reference: one in-process service, same seeds
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    ref = OptimizationService(telemetry=False)
    ref_handles = {}
    for i in range(4):
        ref_handles[f"t{i}"] = ref.submit(
            host_zdt1, SPACE4, ["f1", "f2"], opt_id=f"t{i}",
            random_seed=40 + i,
            file_path=str(ref_dir / f"t{i}.h5"), **SUBMIT_KW,
        )
    ref.run()
    ref.close()

    # ---- the fleet: t0's 19th evaluation call SIGKILLs worker w0
    # (12-point initial design + 4 per epoch: mid-epoch-3, two epoch
    # boundaries durable — the _service_crash_worker shape, one level up)
    plan = {
        "seed": 0,
        "rules": [{"kind": "kill", "target": "t0", "op": "eval",
                   "after": 18}],
    }
    sup = _supervisor(
        tmp_path, worker_env={"w0": {"DMOSOPT_FAULT_PLAN": json.dumps(plan)}}
    )
    with sup:
        sup.start(timeout=120)
        for i in range(4):
            sup.submit(_fleet_spec(i, tmp_path), worker=f"w{i % 2}")
        summary = sup.run(poll=0.2, timeout=600)

    assert summary["tenants"] == {f"t{i}": "completed" for i in range(4)}
    assert summary["workers"]["w0"]["state"] in ("dead", "fenced")
    assert summary["workers"]["w0"]["exit_code"] == -9
    assert len(summary["migrations"]) == 1
    migration = summary["migrations"][0]
    assert migration["from"] == "w0" and migration["to"] == "w1"
    assert sorted(migration["tenants"]) == ["t0", "t2"]
    assert migration["checkpoint_claimed"] is True
    assert summary["lease_conflicts"] == 0

    reg = sup.telemetry.registry
    assert reg.counter_value("fleet_worker_deaths_total", worker="w0") == 1.0
    assert reg.counter_value("fleet_migrations_total") == 1.0
    assert reg.counter_value("fleet_tenants_migrated_total") == 2.0

    # the lease pin: the dead worker's checkpoint is stamped with its
    # adopter, so ANY later claim fails the expected-owner check
    stamped = load_service_checkpoint_from_h5(
        str(tmp_path / "workers" / "w0" / "checkpoint.h5")
    )["service"]
    assert stamped["owner"] == "w1" and stamped["claimed_from"] == "w0"
    with pytest.raises(CheckpointLeaseError):
        from dmosopt_tpu.storage import claim_service_checkpoint

        claim_service_checkpoint(
            str(tmp_path / "workers" / "w0" / "checkpoint.h5"),
            "w0", "w9", 99,
        )

    # ---- bitwise: every tenant's every stored front epoch matches the
    # uninterrupted run exactly (the migrated t0/t2 included)
    for i in range(4):
        opt_id = f"t{i}"
        got = load_fronts_from_h5(
            str(tmp_path / "results" / f"{opt_id}.h5"), opt_id
        )
        want = load_fronts_from_h5(str(ref_dir / f"{opt_id}.h5"), opt_id)
        assert sorted(got) == sorted(want) == [0, 1, 2, 3]
        for e in want:
            np.testing.assert_array_equal(got[e][0], want[e][0],
                                          err_msg=f"{opt_id} epoch {e} x")
            np.testing.assert_array_equal(got[e][1], want[e][1],
                                          err_msg=f"{opt_id} epoch {e} y")


def test_fleet_smoke_and_cli_aggregation(tmp_path):
    """Fast fleet smoke: 2 workers, 2 tenants, no faults — distinct
    ephemeral exporter ports, graceful stop, and the `status
    --fleet-dir` / `fleet --dir` CLI aggregations render the worker
    liveness + placement tables from the directory alone."""
    from click.testing import CliRunner

    from dmosopt_tpu.cli import fleet as fleet_cmd
    from dmosopt_tpu.cli import status as status_cmd

    sup = _supervisor(tmp_path)
    with sup:
        sup.start(timeout=120)
        for i in range(2):
            sup.submit(
                _fleet_spec(i, tmp_path, n_epochs=2), worker=f"w{i}"
            )
        summary = sup.run(poll=0.2, timeout=300)
    assert summary["tenants"] == {"t0": "completed", "t1": "completed"}
    ports = {
        wid: (w.get("exporter") or {}).get("port")
        for wid, w in summary["workers"].items()
    }
    assert None not in ports.values() and len(set(ports.values())) == 2
    assert summary["migrations"] == [] and summary["lease_conflicts"] == 0

    out = CliRunner().invoke(status_cmd, ["-d", str(tmp_path)])
    assert out.exit_code == 0, out.output
    assert "w0" in out.output and "w1" in out.output
    assert "t0" in out.output and "completed" in out.output

    out = CliRunner().invoke(fleet_cmd, ["--dir", str(tmp_path)])
    assert out.exit_code == 0, out.output
    assert "fleet:" in out.output

    # exactly one of -p/-d is required
    out = CliRunner().invoke(status_cmd, [])
    assert out.exit_code != 0
