"""Hypervolume stack tests with analytical ground truths, mirroring the
reference oracle style (reference: tests/test_hv_box_decomposition.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dmosopt_tpu import hv
from dmosopt_tpu.indicators import (
    Hypervolume,
    HypervolumeImprovement,
    IGD,
    PopulationDiversity,
    SlidingWindow,
)


# --------------------------------------------------------- analytic truths


def test_hv_empty_and_single_point():
    ref = np.array([2.0, 2.0])
    assert hv.hypervolume_exact(np.zeros((0, 2)), ref) == 0.0
    assert hv.hypervolume_exact(np.array([[1.0, 1.0]]), ref) == pytest.approx(1.0)
    # out-of-box point contributes nothing
    assert hv.hypervolume_exact(np.array([[3.0, 3.0]]), ref) == 0.0


def test_hv_2d_staircase():
    ref = np.array([3.0, 3.0])
    pts = np.array([[1.0, 2.0], [2.0, 1.0]])
    # two unit-overlapping rectangles: 2*2 + 1*2 - overlap -> compute directly
    # box1 = (3-1)*(3-2)=2 ; box2 adds (3-2)*(2-1)=1 -> 3
    assert hv.hypervolume_exact(pts, ref) == pytest.approx(3.0)
    # dominated point changes nothing
    pts2 = np.vstack([pts, [[2.5, 2.5]]])
    assert hv.hypervolume_exact(pts2, ref) == pytest.approx(3.0)


def test_hv_2d_jitted_matches_host():
    ref = np.array([3.0, 3.0], dtype=np.float32)
    rng = np.random.default_rng(0)
    pts = rng.uniform(0, 4, size=(40, 2)).astype(np.float32)
    got = float(hv.hypervolume_2d(jnp.asarray(pts), jnp.asarray(ref)))
    want = hv.hypervolume_exact(pts, ref)
    assert got == pytest.approx(want, rel=1e-5)


def test_hv_3d_cube():
    ref = np.array([1.0, 1.0, 1.0])
    # single point at origin dominates the whole unit cube
    assert hv.hypervolume_exact(np.zeros((1, 3)), ref) == pytest.approx(1.0)
    # two points: [0,0,.5] and [.5,.5,0]:
    # vol = 0.5 + 0.25*0.5 = 0.625
    pts = np.array([[0.0, 0.0, 0.5], [0.5, 0.5, 0.0]])
    assert hv.hypervolume_exact(pts, ref) == pytest.approx(0.625)


def test_hv_mc_close_to_exact():
    rng = np.random.default_rng(1)
    # random 3-obj front
    pts = rng.uniform(0, 1, size=(30, 3))
    ref = np.array([1.1, 1.1, 1.1])
    exact = hv.hypervolume_exact(pts, ref)
    est, ci = hv.hypervolume_mc(
        pts, ref, n_samples=200_000, key=jax.random.PRNGKey(2), return_ci=True
    )
    assert abs(est - exact) < max(4 * ci, 0.02 * exact)


def test_adaptive_facade_routing():
    ref2 = np.array([2.0, 2.0])
    ahv = hv.AdaptiveHyperVolume(ref2)
    assert ahv.compute_hypervolume(np.array([[1.0, 1.0]])) == pytest.approx(1.0)
    assert ahv.last_method == "exact"

    d = 12
    ref = np.full(d, 1.0)
    ahv = hv.AdaptiveHyperVolume(ref, mc_samples=20_000)
    pts = np.random.default_rng(3).uniform(0, 1, size=(50, d)) * 0.9
    v = ahv.compute_hypervolume(pts)
    assert ahv.last_method == "mc"
    assert 0.0 < v < 1.0
    est, ci = ahv.compute_hypervolume_with_confidence(pts)
    assert ci > 0.0


# ------------------------------------------- box decomposition cross-checks


def test_box_decomposition_matches_wfg_oracle():
    rng = np.random.default_rng(5)
    for d in (3, 4):
        ref = np.full(d, 1.2)
        pts = rng.uniform(0, 1, size=(15, d))
        got = hv.hypervolume_exact(pts, ref)
        want = hv._hypervolume_wfg(pts.copy(), ref)
        assert got == pytest.approx(want, rel=1e-9), (d, got, want)


def test_box_decomposition_exact_with_tied_coordinates():
    """Regression: tied coordinates (ubiquitous on real archives — points
    sharing an objective value, integer-grid fronts) used to make the
    local-upper-bound update drop needed bounds and silently undercount
    HV; a growing archive could then show decreasing hypervolume."""
    rng = np.random.default_rng(11)
    # the originally observed shape: two points tied in objective 0
    front = np.array(
        [[0.0, 0.49153617, 16.42065],
         [0.0, 0.571942, 15.836044],
         [0.61845076, 0.96437263, 12.834977]]
    )
    ref = np.array([1.09375, 1.09375, 25.613188])
    got = hv.hypervolume_exact(front, ref)
    want = hv._hypervolume_wfg(front.copy(), ref)
    assert got == pytest.approx(want, rel=1e-12), (got, want)

    # integer-grid torture: every coordinate tied many times over
    for d in (3, 4):
        for _ in range(20):
            pts = rng.integers(0, 4, size=(8, d)) / 4.0
            ref = np.ones(d)
            got = hv.hypervolume_exact(pts, ref)
            want = hv._hypervolume_wfg(pts.copy(), ref)
            assert got == pytest.approx(want, abs=1e-12), (d, got, want)

    # monotonicity: HV of a superset never decreases (fixed ref)
    base = rng.random((12, 3))
    extra = rng.random((6, 3))
    ref = np.ones(3)
    hv_base = hv.hypervolume_exact(base, ref)
    hv_all = hv.hypervolume_exact(np.vstack([base, extra]), ref)
    assert hv_all >= hv_base - 1e-12


def test_dominated_boxes_partition_volume_2d():
    # in 2-D the box-decomposition volume must equal the staircase sweep
    rng = np.random.default_rng(6)
    pts = rng.uniform(0, 1, size=(12, 2))
    ref = np.array([1.1, 1.1])
    lowers, uppers = hv.dominated_boxes(
        hv._filter_dominated(pts), ref
    )
    vol = float(np.sum(np.prod(uppers - lowers, axis=1)))
    assert vol == pytest.approx(hv.hypervolume_exact(pts, ref), rel=1e-9)


# -------------------------------------------------------------------- EHVI


def test_ehvi_prefers_improving_candidate():
    ref = np.array([2.0, 2.0])
    front = np.array([[1.0, 1.0]])
    box = hv.HyperVolumeBoxDecomposition(ref)
    means = np.array([[0.5, 0.5], [1.5, 1.5]])  # first dominates the front
    variances = np.full((2, 2), 0.01)
    idx, scores = box.select_candidates(front, means, variances, n_select=1)
    assert scores[0] > 0
    assert int(idx[0]) == 0


def test_ehvi_empty_front():
    box = hv.HyperVolumeBoxDecomposition(np.array([1.0, 1.0]))
    means = np.array([[0.2, 0.2], [0.8, 0.8]])
    variances = np.full((2, 2), 0.01)
    idx, scores = box.select_candidates(
        np.zeros((0, 2)), means, variances, n_select=2
    )
    assert int(idx[0]) == 0  # deeper-dominating candidate wins


def test_ehvi_matches_monte_carlo_expectation():
    """EHVI formula vs brute-force E[HV(front+y) - HV(front)]."""
    rng = np.random.default_rng(7)
    ref = np.array([2.0, 2.0])
    front = np.array([[0.4, 1.5], [1.0, 1.0], [1.6, 0.3]])
    mean = np.array([[0.8, 0.7]])
    var = np.array([[0.04, 0.09]])
    box = hv.HyperVolumeBoxDecomposition(ref)
    _, score = box.select_candidates(front, mean, var, n_select=1)

    hv0 = hv.hypervolume_exact(front, ref)
    samples = rng.normal(mean[0], np.sqrt(var[0]), size=(4000, 2))
    hvi = [
        hv.hypervolume_exact(np.vstack([front, s[None, :]]), ref) - hv0
        for s in samples
    ]
    mc = float(np.mean(hvi))
    se = float(np.std(hvi) / np.sqrt(len(hvi)))
    assert score[0] == pytest.approx(mc, abs=max(4 * se, 0.01))


def test_ehvi_3d_matches_monte_carlo():
    rng = np.random.default_rng(8)
    ref = np.full(3, 1.5)
    front = np.array([[0.5, 0.9, 0.8], [0.9, 0.4, 0.9], [0.8, 0.8, 0.3]])
    mean = np.array([[0.6, 0.6, 0.6]])
    var = np.full((1, 3), 0.02)
    box = hv.HyperVolumeBoxDecomposition(ref)
    _, score = box.select_candidates(front, mean, var, n_select=1)

    hv0 = hv.hypervolume_exact(front, ref)
    samples = rng.normal(mean[0], np.sqrt(var[0]), size=(3000, 3))
    hvi = [
        hv.hypervolume_exact(np.vstack([front, s[None, :]]), ref) - hv0
        for s in samples
    ]
    mc, se = float(np.mean(hvi)), float(np.std(hvi) / np.sqrt(len(hvi)))
    assert score[0] == pytest.approx(mc, abs=max(4 * se, 0.01))


# -------------------------------------------------------------- indicators


def test_igd_zero_on_front_itself():
    pf = np.array([[0.0, 1.0], [0.5, 0.5], [1.0, 0.0]])
    igd = IGD(pf)
    assert igd.do(pf) == pytest.approx(0.0)
    assert igd.do(pf + 0.1) > 0


def test_hypervolume_indicator_nds():
    ref = np.array([2.0, 2.0])
    ind = Hypervolume(ref_point=ref, nds=True, norm_ref_point=False)
    F = np.array([[1.0, 1.0], [1.5, 1.5]])  # second dominated
    assert ind.do(F) == pytest.approx(1.0)


def test_hvi_indicator_selects_k():
    ind = HypervolumeImprovement(
        ref_point=np.array([2.0, 2.0]), norm_ref_point=False
    )
    F = np.array([[1.0, 1.0]])
    means = np.array([[0.5, 0.5], [1.8, 1.8], [0.6, 0.4]])
    var = np.full((3, 2), 0.01)
    sel = ind.do(F, means, var, 2)
    assert len(sel) == 2
    assert 1 not in sel  # the non-improving candidate is not picked


def test_population_diversity_and_sliding_window():
    pd = PopulationDiversity()
    F = np.array([0, 0, 1, 1])
    Y = np.array([[0.0, 1.0], [1.0, 0.0], [2.0, 2.0], [3.0, 3.0]])
    diversity, spread = pd.do(F[None, :], Y)
    assert diversity == pytest.approx(0.5)

    w = SlidingWindow(3)
    for i in range(5):
        w.append(i)
    assert list(w) == [2, 3, 4]
    assert w.is_full()


# ------------------------------------------------- adaptive FPRAS estimator


def test_on_device_dominance_prune_matches_host():
    """The chunked on-device dominated mask equals the host O(N^2 d)
    filter, including at sizes that cross the chunk boundary."""
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    for n in (7, 512, 1300):
        pts = rng.random((n, 6)).astype(np.float32)
        mask = np.asarray(hv._dominated_mask_chunked(jnp.asarray(pts)))
        kept = pts[~mask]
        want = hv._filter_dominated(pts)
        assert kept.shape == want.shape, (n, kept.shape, want.shape)
        assert np.allclose(np.sort(kept, axis=0), np.sort(want, axis=0))


@pytest.mark.slow
def test_fpras_large_archive_prune_speed_and_agreement():
    """Archive-scale FPRAS (N=10k, mostly dominated points): the
    on-device prune must (a) leave the estimate within the joint CI of
    the pruned-front run, and (b) be measurably cheaper per sample than
    the unpruned cover scan (VERDICT r2 item 8 done-criterion; the role
    of the reference's kd-tree prescreen, hv_adaptive.py:40-263)."""
    import time

    import jax

    from dmosopt_tpu.hv import hypervolume_fpras

    rng = np.random.default_rng(0)
    d = 8
    pts = rng.random((10_000, d))
    ref = np.ones(d)

    def run(prune):
        t0 = time.time()
        est, (ci, ns) = hypervolume_fpras(
            pts, ref, epsilon=0.015, key=jax.random.PRNGKey(1),
            return_info=True, prune=prune,
        )
        return est, ci, ns, time.time() - t0

    # first calls pay XLA compiles for both paths; time the warm calls
    run(True), run(False)
    est_p, ci_p, ns_p, t_pruned = run(True)
    est_u, ci_u, ns_u, t_unpruned = run(False)

    assert abs(est_p - est_u) <= ci_p + ci_u, (est_p, est_u, ci_p, ci_u)
    # pruning shrinks the box set from 10k to the front (~hundreds), so
    # the per-sample cover scan over box chunks collapses
    assert t_pruned < t_unpruned, (t_pruned, t_unpruned)


def test_fpras_matches_exact_high_dim():
    """CI-target-driven FPRAS agrees with the exact oracle at d=10,15
    within the requested epsilon (VERDICT r1 item 5 done-criterion)."""
    from dmosopt_tpu.hv import hypervolume_exact, hypervolume_fpras
    import jax

    rng = np.random.default_rng(0)
    for d in (10, 15):
        pts = rng.dirichlet(np.ones(d), size=8) + 0.1 * rng.uniform(size=(8, d))
        ref = np.full(d, 2.0)
        exact = hypervolume_exact(pts, ref)
        est, (ci, ns) = hypervolume_fpras(
            pts, ref, epsilon=0.02, key=jax.random.PRNGKey(1), return_info=True
        )
        assert abs(est - exact) / exact < 3 * 0.02, (d, est, exact)
        assert ci <= 0.02 * est * 1.01
        assert 0 < ns <= 2_000_000


def test_fpras_survives_tiny_dominated_fraction():
    """Rejection MC sees ~no dominated samples when the dominated region
    is a vanishing fraction of the bounding box; FPRAS samples inside the
    union and keeps relative accuracy."""
    import jax
    from dmosopt_tpu.hv import hypervolume_fpras, hypervolume_mc

    d = 12
    # one small coordinate per point: union volume ~ 1e-20 of the bbox
    pts = np.full((d, d), 0.98) - 0.95 * np.eye(d)
    ref = np.ones(d)
    est, (ci, ns) = hypervolume_fpras(
        pts, ref, epsilon=0.02, key=jax.random.PRNGKey(3), return_info=True
    )
    # analytic: union of d boxes, each vol 0.97 * 0.02^(d-1); overlaps are
    # O(0.02^(2(d-1))) -- negligible
    analytic = d * 0.97 * 0.02 ** (d - 1)
    assert est == pytest.approx(analytic, rel=0.1)
    mc = hypervolume_mc(pts, ref, n_samples=100_000, key=jax.random.PRNGKey(4))
    assert mc == 0.0  # rejection MC finds nothing at this budget


def test_adaptive_hv_routing_and_router():
    from dmosopt_tpu.hv import AdaptiveHyperVolume
    from dmosopt_tpu.hv_termination import HVAlgorithmRouter

    rng = np.random.default_rng(1)
    d = 12
    F = rng.uniform(0.2, 0.8, size=(40, d))
    ref = np.full(d, 2.0)

    hv_eps = AdaptiveHyperVolume(ref, epsilon=0.05)
    v, ci = hv_eps.compute_hypervolume_with_confidence(F)
    assert hv_eps.last_method == "fpras"
    assert v > 0 and 0 < ci <= 0.05 * v * 1.01
    assert hv_eps.last_n_samples > 0

    hv_fixed = AdaptiveHyperVolume(ref, mc_samples=50_000)
    v2 = hv_fixed.compute_hypervolume(F)
    assert hv_fixed.last_method == "mc"
    assert v2 == pytest.approx(v, rel=0.1)

    router = HVAlgorithmRouter()
    v3 = router.compute(F, ref, epsilon=0.05)
    assert router.last_method == "fpras" and router.last_n_samples > 0
    assert v3 == pytest.approx(v, rel=0.1)
    # low-d stays exact
    v4 = router.compute(np.array([[1.0, 1.0]]), np.array([2.0, 2.0]), 0.05)
    assert router.last_method == "exact" and v4 == pytest.approx(1.0)
