"""OpenMetrics exposition tests: render/parse round trip, exact
agreement with the registry snapshot, parser red paths, the HTTP
exporter's three endpoints (including the /healthz critical flip), and
the exporter thread lifecycle on a live service
(docs/observability.md "OpenMetrics exposition")."""

import http.client
import json
import math
import threading

import pytest

from dmosopt_tpu.telemetry import MetricsRegistry, Telemetry
from dmosopt_tpu.telemetry.exposition import (
    MetricsExporter,
    OpenMetricsParseError,
    parse_openmetrics,
    render_openmetrics,
    samples_as_snapshot,
)
from dmosopt_tpu.telemetry.health import HealthEngine, HealthRule


def _populated_registry() -> MetricsRegistry:
    reg = MetricsRegistry()
    reg.counter_inc("evals_total", 12)
    reg.counter_inc("eval_batches_total", 3, backend="host")
    reg.counter_inc("eval_batches_total", 9, backend="jax")
    reg.counter_inc("tenant_cost_seconds", 1.25, tenant="t0", phase="ea")
    reg.gauge_set("tenants_active", 4)
    reg.gauge_set("device_memory_bytes_in_use", 1024.0, device="0")
    for v in (0.002, 0.3, 0.3, 7.0):
        reg.histogram_observe("phase_duration_seconds", v, phase="train")
    reg.histogram_observe("eval_wait_seconds", 0.05)
    return reg


# ----------------------------------------------------------- round trip


def test_render_parses_as_valid_openmetrics():
    fams = parse_openmetrics(render_openmetrics(_populated_registry().snapshot()))
    assert fams["evals"]["type"] == "counter"
    assert fams["tenants_active"]["type"] == "gauge"
    assert fams["phase_duration_seconds"]["type"] == "histogram"


def test_exposition_agrees_exactly_with_snapshot():
    """The acceptance pin: what /metrics serves IS the snapshot —
    every counter and gauge series value round-trips, and histogram
    count/sum samples match the snapshot summaries."""
    reg = _populated_registry()
    snap = reg.snapshot()
    fams = parse_openmetrics(render_openmetrics(snap))
    back = samples_as_snapshot(fams)
    # counters: family name = registry name minus _total
    for name, series in snap["counters"].items():
        family = name[:-len("_total")] if name.endswith("_total") else name
        assert back["counters"][family] == series, name
    for name, series in snap["gauges"].items():
        assert back["gauges"][name] == series, name
    # histograms: count/sum per series
    for name, series in snap["histograms"].items():
        samples = {
            (n, tuple(sorted(lbl.items()))): v
            for n, lbl, v in fams[name]["samples"]
        }
        for label_str, summary in series.items():
            base = tuple(
                sorted(
                    tuple(p.split("=", 1))
                    for p in label_str.split(",")
                    if p
                )
            )
            assert samples[(f"{name}_count", base)] == summary["count"]
            assert samples[(f"{name}_sum", base)] == pytest.approx(
                summary["sum"]
            )


def test_label_escaping_round_trips():
    reg = MetricsRegistry()
    reg.counter_inc("evals_total", 1, note='quo"te\\back\nline')
    fams = parse_openmetrics(render_openmetrics(reg.snapshot()))
    (_, labels, value), = fams["evals"]["samples"]
    assert labels == {"note": 'quo"te\\back\nline'} and value == 1.0


def test_histogram_buckets_are_cumulative_with_inf():
    reg = MetricsRegistry()
    for v in (0.002, 0.3, 0.3, 7.0):
        reg.histogram_observe("eval_wait_seconds", v)
    fams = parse_openmetrics(render_openmetrics(reg.snapshot()))
    buckets = {
        labels["le"]: v
        for n, labels, v in fams["eval_wait_seconds"]["samples"]
        if n.endswith("_bucket")
    }
    assert buckets["+Inf"] == 4.0
    # cumulative: every finite bucket <= the next one
    finite = sorted(
        (float(le), v) for le, v in buckets.items() if le != "+Inf"
    )
    assert all(a[1] <= b[1] for a, b in zip(finite, finite[1:]))


# ------------------------------------------------------ parser red paths


def test_parser_rejects_missing_eof():
    with pytest.raises(OpenMetricsParseError, match="EOF"):
        parse_openmetrics("# TYPE x counter\nx_total 1\n")


def test_parser_rejects_content_after_eof():
    with pytest.raises(OpenMetricsParseError, match="after"):
        parse_openmetrics("# EOF\nx_total 1\n")


def test_parser_rejects_counter_without_total_suffix():
    with pytest.raises(OpenMetricsParseError, match="_total"):
        parse_openmetrics("# TYPE x counter\nx 1\n# EOF\n")


def test_parser_rejects_sample_outside_family():
    with pytest.raises(OpenMetricsParseError, match="family"):
        parse_openmetrics("# TYPE x counter\ny_total 1\n# EOF\n")


def test_parser_rejects_duplicate_series():
    text = "# TYPE x counter\nx_total 1\nx_total 2\n# EOF\n"
    with pytest.raises(OpenMetricsParseError, match="duplicate"):
        parse_openmetrics(text)


def test_parser_rejects_non_cumulative_histogram():
    text = (
        "# TYPE h histogram\n"
        'h_bucket{le="1"} 5\n'
        'h_bucket{le="2"} 3\n'
        'h_bucket{le="+Inf"} 5\n'
        "h_count 5\nh_sum 2.0\n# EOF\n"
    )
    with pytest.raises(OpenMetricsParseError, match="cumulative"):
        parse_openmetrics(text)


def test_parser_rejects_inf_bucket_count_mismatch():
    text = (
        "# TYPE h histogram\n"
        'h_bucket{le="+Inf"} 5\n'
        "h_count 7\nh_sum 2.0\n# EOF\n"
    )
    with pytest.raises(OpenMetricsParseError, match="_count"):
        parse_openmetrics(text)


def test_parser_rejects_negative_counter():
    with pytest.raises(OpenMetricsParseError, match="negative"):
        parse_openmetrics("# TYPE x counter\nx_total -1\n# EOF\n")


# -------------------------------------------------------------- exporter


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode(), dict(resp.getheaders())
    finally:
        conn.close()


def test_exporter_serves_metrics_healthz_statusz():
    tel = Telemetry()
    tel.registry.counter_inc("evals_total", 5)
    eng = HealthEngine(
        rules=[
            HealthRule(
                name="critical_watch", metric="counter:eval_failures_total",
                threshold=0.0, mode="delta", severity="critical",
            )
        ],
        telemetry=tel,
    )
    exporter = MetricsExporter(
        snapshot_fn=tel.registry.snapshot,
        health_fn=eng.summary,
        status_fn=lambda: {"steps": 7, "closed": False},
    ).start()
    try:
        port = exporter.port
        assert exporter.url == f"http://127.0.0.1:{port}"

        status, body, headers = _get(port, "/metrics")
        assert status == 200
        assert "openmetrics-text" in headers["Content-Type"]
        fams = parse_openmetrics(body)
        assert fams["evals"]["samples"][0][2] == 5.0

        status, body, _ = _get(port, "/healthz")
        assert status == 200 and json.loads(body)["status"] == "ok"

        # a critical alert flips /healthz non-200 ...
        tel.registry.counter_inc("eval_failures_total", 3)
        eng.evaluate(tel.registry.snapshot(), step=1)
        status, body, _ = _get(port, "/healthz")
        assert status == 503
        payload = json.loads(body)
        assert payload["status"] == "critical"
        assert payload["firing"][0]["rule"] == "critical_watch"

        # ... and recovers on resolve
        eng.evaluate(tel.registry.snapshot(), step=2)
        status, body, _ = _get(port, "/healthz")
        assert status == 200 and json.loads(body)["status"] == "ok"

        status, body, _ = _get(port, "/statusz")
        assert status == 200 and json.loads(body)["steps"] == 7

        status, _, _ = _get(port, "/nope")
        assert status == 404
    finally:
        exporter.close()
    assert exporter.port is None


def test_exporter_close_joins_thread_and_frees_port():
    tel = Telemetry()
    exporter = MetricsExporter(snapshot_fn=tel.registry.snapshot).start()
    port = exporter.port
    thread = exporter._thread
    assert thread.is_alive()
    exporter.close()
    assert not thread.is_alive()
    with pytest.raises(OSError):
        _get(port, "/metrics")
    # close is idempotent
    exporter.close()


def test_exporter_broken_snapshot_returns_500_and_survives():
    calls = {"n": 0}

    def snapshot():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("boom")
        return {"counters": {}, "gauges": {}, "histograms": {}}

    with MetricsExporter(snapshot_fn=snapshot) as exporter:
        status, body, _ = _get(exporter.port, "/metrics")
        assert status == 500 and "boom" in body
        status, _, _ = _get(exporter.port, "/metrics")
        assert status == 200  # the thread survived the broken scrape


def test_torn_snapshot_never_served_under_concurrent_emission():
    """Satellite pin: the whole snapshot is one lock acquisition, so a
    scrape concurrent with emission can never see a histogram whose
    count disagrees with its buckets, or a counter going backwards."""
    reg = MetricsRegistry()
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            reg.counter_inc("evals_total", 1)
            reg.counter_inc("evals_total", 1, backend="host")
            reg.histogram_observe("eval_wait_seconds", 0.01)
            reg.gauge_set("tenants_active", 1)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        prev_total = -1.0
        for _ in range(200):
            snap = reg.snapshot()
            for series in snap["histograms"].values():
                for summary in series.values():
                    assert summary["count"] == sum(
                        summary["buckets"].values()
                    )
                    assert summary["sum"] == pytest.approx(
                        0.01 * summary["count"]
                    )
            total = sum(
                snap["counters"].get("evals_total", {}).values()
            )
            assert total >= prev_total  # counters never run backwards
            prev_total = total
            # the rendered exposition of any snapshot stays valid
            if total:
                parse_openmetrics(render_openmetrics(snap))
    finally:
        stop.set()
        for t in threads:
            t.join()


# ------------------------------------------------------ service exporter


def test_service_exporter_lifecycle_and_introspect():
    import numpy as np

    from dmosopt_tpu.service import OptimizationService

    def obj(pp):
        x = np.asarray([pp["x0"], pp["x1"]], dtype=np.float64)
        return np.asarray([x[0], 1.0 - x[0] + x[1]], dtype=np.float64)

    svc = OptimizationService(telemetry=True, exporter=True)
    try:
        info = svc.introspect()["exporter"]
        assert info["url"].startswith("http://127.0.0.1:")
        port = info["port"]
        svc.submit(
            obj, {"x0": [0.0, 1.0], "x1": [0.0, 1.0]}, ["f1", "f2"],
            opt_id="exp_t0", jax_objective=False,
            population_size=8, num_generations=2, n_initial=3, n_epochs=1,
            surrogate_method_kwargs={"n_starts": 1, "n_iter": 10, "seed": 0},
            random_seed=1,
        )
        svc.step()
        status, body, _ = _get(port, "/metrics")
        assert status == 200
        fams = parse_openmetrics(body)
        assert fams["service_epochs"]["samples"][0][2] >= 1.0
        status, body, _ = _get(port, "/statusz")
        assert status == 200
        snap = json.loads(body)
        assert snap["steps"] >= 1 and snap["health"]["status"] == "ok"
        status, _, _ = _get(port, "/healthz")
        assert status == 200
    finally:
        svc.close()
    assert svc.exporter is None
    with pytest.raises(OSError):
        _get(port, "/metrics")


def test_service_exporter_requires_telemetry():
    from dmosopt_tpu.service import OptimizationService

    with pytest.raises(ValueError, match="telemetry"):
        OptimizationService(telemetry=False, exporter=True)


def test_format_value_inf():
    reg = MetricsRegistry()
    reg.gauge_set("gp_distill_error", math.inf)
    fams = parse_openmetrics(render_openmetrics(reg.snapshot()))
    assert fams["gp_distill_error"]["samples"][0][2] == math.inf


def test_user_supplied_label_values_with_commas_and_equals_round_trip():
    """Review fix: opt_ids are user-supplied and land verbatim in
    `tenant=` labels — a value containing ',' or '=' must still render
    and parse back to the original label set."""
    reg = MetricsRegistry()
    reg.counter_inc(
        "tenant_cost_seconds", 2.0, tenant="sweep=lr0.1,bs32", phase="ea"
    )
    fams = parse_openmetrics(render_openmetrics(reg.snapshot()))
    (_, labels, value), = fams["tenant_cost_seconds"]["samples"]
    assert labels == {"tenant": "sweep=lr0.1,bs32", "phase": "ea"}
    assert value == 2.0


def test_exporter_close_not_blocked_by_idle_keepalive_client():
    """Review fix: the server is single-threaded and HTTP/1.1
    keep-alive — an idle client holding its connection open (what
    Prometheus does between scrapes) must not block close(): the
    per-connection socket timeout bounds the wait."""
    import time

    tel = Telemetry()
    exporter = MetricsExporter(snapshot_fn=tel.registry.snapshot).start()
    port = exporter.port
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", "/metrics")
        conn.getresponse().read()
        # connection stays open (keep-alive); close() must still return
        t0 = time.monotonic()
        exporter.close()
        assert time.monotonic() - t0 < 9.0, "close() blocked on keep-alive"
    finally:
        conn.close()
