"""Device-time ledger (ISSUE 12): per-compiled-program device truth.

Pins the tentpole's three layers:

- the jax-free trace parser and join (synthetic Chrome traces: host
  annotation lanes, device lanes — `/device:*` processes and
  `tf_XLAEigen*` CPU worker threads — marker exclusion, interval
  unions, per-tenant attribution through `tenant_cost` span shares);
- the compile-side rows: the sequential path's explicit
  `lower().compile()` (a `program_compile` event + an `ea_scan` ledger
  row with cost/memory analysis) and the batched core's bucket
  programs feeding the same ledger;
- the acceptance workload: a profiled 2-bucket multi-tenant service
  run on the CPU backend whose ledger joins >= 90% of gp_fit/ea_scan
  host spans by annotation name, with trace-derived
  `device_busy_fraction` / `device_overlap_ratio` exposed through
  `introspect()` and the `status` CLI.
"""

import json

import pytest
from click.testing import CliRunner

import dmosopt_tpu
from dmosopt_tpu.benchmarks.zdt import zdt1
from dmosopt_tpu.cli import status
from dmosopt_tpu.driver import dopt_dict
from dmosopt_tpu.service import OptimizationService
from dmosopt_tpu.telemetry import Telemetry
from dmosopt_tpu.telemetry.device_ledger import (
    DeviceLedger,
    _merge_intervals,
    parse_chrome_trace,
)
from dmosopt_tpu.telemetry.tracing import Span

SMK = {"n_starts": 2, "n_iter": 25, "seed": 0}


# ------------------------------------------------------------ parser units


def _meta(pid, pname=None, tid=None, tname=None):
    if tname is None:
        return {
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": pname},
        }
    return {
        "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
        "args": {"name": tname},
    }


def _x(pid, tid, name, ts_us, dur_us):
    return {"ph": "X", "pid": pid, "tid": tid, "name": name,
            "ts": ts_us, "dur": dur_us}


def _tpu_like_trace():
    """Host process with a python thread carrying annotations; one
    /device: process with two op lanes."""
    return {
        "traceEvents": [
            _meta(1, pname="/host:CPU"),
            _meta(1, tid=10, tname="python"),
            _meta(7, pname="/device:TPU:0"),
            _meta(7, tid=1, tname="lane-0"),
            _meta(7, tid=2, tname="lane-1"),
            # annotations: two gp_fit windows, one ea_scan
            _x(1, 10, "gp_fit", 0, 100),
            _x(1, 10, "ea_scan", 100, 100),
            _x(1, 10, "gp_fit", 200, 100),
            # device ops: nested events on lane-0 must not double count
            _x(7, 1, "fusion.1", 10, 50),
            _x(7, 1, "fusion.1.inner", 20, 30),
            _x(7, 2, "fusion.2", 120, 40),
            _x(7, 1, "fusion.3", 250, 20),
        ]
    }


def test_parse_trace_lanes_annotations_and_union():
    parsed = parse_chrome_trace(_tpu_like_trace(), {"gp_fit", "ea_scan"})
    assert len(parsed.annotations["gp_fit"]) == 2
    assert len(parsed.annotations["ea_scan"]) == 1
    # nested lane events merged into one interval per lane
    busy = parsed.device_busy
    assert busy == [
        (10e-6, 60e-6), (120e-6, 160e-6), (250e-6, 270e-6)
    ]


def test_parse_trace_cpu_backend_lanes_and_marker_exclusion():
    trace = {
        "traceEvents": [
            _meta(1, pname="/host:CPU"),
            _meta(1, tid=10, tname="python"),
            _meta(1, tid=20, tname="tf_XLAEigen/123"),
            _x(1, 10, "gp_fit", 0, 100),
            # real op on the Eigen worker + zero-ish marker noise
            _x(1, 20, "matmul.7", 10, 50),
            _x(1, 20, "ThreadpoolListener::StartRegion", 11, 1),
            _x(1, 20, "ThreadpoolListener::StopRegion", 60, 1),
        ]
    }
    parsed = parse_chrome_trace(trace, {"gp_fit"})
    assert parsed.device_busy == [(10e-6, 60e-6)]
    # the python thread is a host lane: its gp_fit event is an
    # annotation, never device busy time
    assert parsed.annotations["gp_fit"] == [(0.0, 100e-6)]


def test_merge_intervals():
    assert _merge_intervals([(3, 4), (0, 1), (0.5, 2), (4, 4)]) == [
        (0, 2), (3, 4)
    ]


def _span(name, span_id, t0, t1, parent=None, **labels):
    return Span(
        name=name, trace_id="t", span_id=span_id, parent_id=parent,
        t_start=t0, t_end=t1, labels=labels,
    )


def test_ledger_join_by_name_and_order_with_tenant_attribution():
    led = DeviceLedger()
    # two gp_fit host spans (order matters: first gets the busy window)
    # and tenant_cost children splitting the first one 75/25
    spans = [
        _span("gp_fit", 1, 100.0, 100.1, bucket="d4_o2_p16"),
        _span("tenant_cost", 2, 100.0, 100.075, parent=1,
              tenant="a", phase="fit"),
        _span("tenant_cost", 3, 100.075, 100.1, parent=1,
              tenant="b", phase="fit"),
        _span("gp_fit", 4, 100.2, 100.3, bucket="d4_o2_p16"),
    ]
    cap = led.ingest_chrome_trace(_tpu_like_trace(), spans)
    # first annotation window [0,100]us holds the merged (10,60)us op;
    # second [200,300]us holds (250,270)us
    rows = {(r.program, r.bucket): r for r in led.program_rows()}
    row = rows[("gp_fit", "d4_o2_p16")]
    assert row.n_spans == 2 and row.n_joined == 2
    assert row.device_time_s == pytest.approx(70e-6)
    tds = led.tenant_device_seconds()
    assert tds["a"]["fit"] == pytest.approx(50e-6 * 0.75, rel=1e-6)
    assert tds["b"]["fit"] == pytest.approx(50e-6 * 0.25, rel=1e-6)
    # capture-level fractions: busy union 110us over the 300us window
    assert cap.device_busy_fraction == pytest.approx(110 / 300, rel=1e-6)
    # extent = first device start (10us) -> last end (270us)
    assert cap.device_overlap_ratio == pytest.approx(110 / 260, rel=1e-6)
    assert cap.join_fraction == 1.0


def test_ledger_unjoined_spans_lower_join_fraction():
    led = DeviceLedger()
    spans = [
        _span("gp_fit", 1, 0.0, 0.1),
        _span("gp_fit", 2, 0.2, 0.3),
        _span("gp_fit", 3, 0.4, 0.5),
    ]
    trace = {
        "traceEvents": [
            _meta(1, pname="/host:CPU"),
            _meta(1, tid=10, tname="python"),
            _x(1, 10, "gp_fit", 0, 100),  # only ONE annotation
        ]
    }
    led.ingest_chrome_trace(trace, spans)
    (row,) = led.program_rows()
    assert row.n_spans == 3 and row.n_joined == 1
    assert row.to_dict()["join_fraction"] == pytest.approx(1 / 3, abs=1e-4)


def test_ledger_tail_aligns_when_spans_were_evicted():
    """When the span buffer evicted capture-era spans, the trace holds
    more annotation windows than surviving spans; the survivors must
    join the most RECENT windows (the buffer drops oldest-first), not
    silently take an earlier span's device time."""
    led = DeviceLedger()
    # one survivor, but TWO gp_fit windows in the trace: [0,100]us
    # holds (10,60)us busy; [200,300]us holds (250,270)us
    survivor = _span("gp_fit", 9, 200.0, 200.1)
    led.ingest_chrome_trace(_tpu_like_trace(), [survivor])
    (row,) = led.program_rows()
    assert row.n_spans == 1 and row.n_joined == 1
    # charged the SECOND window's 20us, not the first window's 50us
    assert row.device_time_s == pytest.approx(20e-6)


def test_ledger_record_compile_rows_accumulate():
    led = DeviceLedger()
    led.record_compile("ea_scan", 0.5, flops=100.0, bucket="d4_o2_p16")
    led.record_compile(
        "ea_scan", 0.25, flops=200.0, bucket="d4_o2_p16", retrace=True
    )
    (row,) = led.program_rows()
    assert row.compiles == 2 and row.retraces == 1
    assert row.compile_s == pytest.approx(0.75)
    assert row.flops == 200.0  # latest executable wins
    assert led.has_data
    summary = led.summary()
    assert summary["programs"][0]["bucket"] == "d4_o2_p16"
    json.dumps(summary)  # JSON-able end to end


def test_ingest_profile_dir_missing_capture_is_none(tmp_path):
    led = DeviceLedger()
    assert led.ingest_profile_dir(str(tmp_path), []) is None
    assert not led.has_data


# ----------------------------------------------- sequential-path compiles


def test_sequential_driver_run_feeds_ledger_compiles(tmp_path):
    """A plain (non-profiled) sequential driver run with telemetry on
    compiles its generation-loop program OBSERVABLY: an `ea_scan`
    ledger row with compile seconds and XLA cost/memory analysis, plus
    `program_compile` events."""
    params = {
        "opt_id": "ledger_seq",
        "obj_fun": zdt1,
        "jax_objective": True,
        "objective_names": ["f1", "f2"],
        "space": {f"x{i}": [0.0, 1.0] for i in range(4)},
        "problem_parameters": {},
        "n_initial": 3,
        "n_epochs": 2,
        "population_size": 16,
        "num_generations": 4,
        "resample_fraction": 0.5,
        "optimizer_name": "nsga2",
        "surrogate_method_name": "gpr",
        "surrogate_method_kwargs": dict(SMK),
        "random_seed": 11,
        "telemetry": True,
    }
    dmosopt_tpu.run(params, verbose=False)
    tel = dopt_dict["ledger_seq"].telemetry
    rows = {
        (r.program, r.bucket): r for r in tel.ledger.program_rows()
    }
    row = rows[("ea_scan", None)]
    assert row.compiles >= 1
    assert row.compile_s > 0
    # the CPU backend reports both analyses for AOT-compiled programs
    assert row.flops is not None and row.flops > 0
    assert row.memory_bytes is not None and row.memory_bytes > 0
    events = tel.log.records(kind="program_compile")
    assert len(events) >= 1
    assert events[0].fields["program"] == "ea_scan"
    assert events[0].fields["compile_s"] > 0


# -------------------------------------------------- acceptance (service)


@pytest.fixture(scope="module")
def profiled_service(tmp_path_factory):
    """The acceptance workload: a 2-bucket, 3-tenant service whose
    step 1 runs under a jax.profiler capture on the CPU backend."""
    prof_dir = str(tmp_path_factory.mktemp("prof"))
    svc = OptimizationService(
        min_bucket=1,
        telemetry={"profile_dir": prof_dir, "profile_epochs": [1]},
    )

    def submit(dim, seed, n_epochs):
        return svc.submit(
            zdt1,
            {f"x{i}": [0.0, 1.0] for i in range(dim)},
            ["f1", "f2"],
            n_epochs=n_epochs,
            population_size=16,
            num_generations=4,
            n_initial=3,
            surrogate_method_kwargs=dict(SMK),
            random_seed=seed,
        )

    submit(4, 1, 3)
    submit(4, 2, 3)
    submit(6, 3, 3)
    svc.run()
    snap = svc.introspect()
    yield svc, snap
    svc.close()


def test_profiled_service_ledger_joins_90_percent(profiled_service):
    """Acceptance: per-program device times join >= 90% of
    gp_fit/ea_scan host spans by annotation name on the CPU backend's
    profiler output, and the trace-derived fractions are exposed
    through introspect()."""
    _, snap = profiled_service
    dl = snap.get("device_ledger")
    assert dl is not None, "profiled step produced no ledger data"
    assert dl["captures"] >= 1
    rows = {
        (r["program"], r.get("bucket")): r for r in dl["programs"]
    }
    fit_ea = [
        r for (name, _), r in rows.items() if name in ("gp_fit", "ea_scan")
    ]
    assert fit_ea, sorted(rows)
    n_spans = sum(r["n_spans"] for r in fit_ea)
    n_joined = sum(r["n_joined"] for r in fit_ea)
    assert n_spans > 0
    assert n_joined / n_spans >= 0.9, (n_joined, n_spans)
    # device time actually accrued to the joined programs
    assert sum(r["device_time_s"] for r in fit_ea) > 0
    # trace-derived fractions, from device events
    assert 0 < dl["device_busy_fraction"] <= 1.0
    assert 0 < dl["device_overlap_ratio"] <= 1.0
    # both buckets' EA programs recorded observable compiles
    ea_buckets = {
        b for (name, b) in rows if name == "ea_scan" and b is not None
    }
    assert {"d4_o2_p16", "d6_o2_p16"} <= ea_buckets, ea_buckets


def test_profiled_service_attributes_tenant_device_seconds(profiled_service):
    """Per-tenant DEVICE seconds land beside the host cost attribution:
    every tenant that rode a profiled bucket epoch gets a share, and
    the `tenant_device_seconds` counter carries the same totals."""
    svc, snap = profiled_service
    tds = snap["device_ledger"].get("tenant_device_seconds")
    assert tds, snap["device_ledger"].keys()
    assert len(tds) == 3
    for tenant, phases in tds.items():
        assert sum(phases.values()) > 0, (tenant, phases)
    counters = svc.telemetry.registry.snapshot()["counters"].get(
        "tenant_device_seconds", {}
    )
    assert counters, "tenant_device_seconds counter never incremented"
    assert sum(counters.values()) == pytest.approx(
        sum(sum(p.values()) for p in tds.values()), rel=1e-6
    )


def test_profiled_service_spans_dropped_and_gauges(profiled_service):
    svc, snap = profiled_service
    assert snap["spans_dropped"] == 0  # no buffer pressure at this scale
    busy = svc.telemetry.registry.gauge_value("device_busy_fraction")
    overlap = svc.telemetry.registry.gauge_value("device_overlap_ratio")
    assert busy is not None and 0 < busy <= 1.0
    assert overlap is not None and 0 < overlap <= 1.0
    caps = svc.telemetry.log.records(kind="device_capture")
    assert len(caps) == 1
    assert caps[0].fields["n_joined"] > 0


def test_status_cli_renders_device_ledger(profiled_service, tmp_path):
    """The `status` CLI renders the device-truth block: busy/overlap
    fractions, per-program device seconds, per-tenant device totals,
    and the spans_dropped field."""
    _, snap = profiled_service
    status_file = tmp_path / "status.json"
    status_file.write_text(json.dumps(snap, default=str))
    result = CliRunner().invoke(status, ["-p", str(status_file)])
    assert result.exit_code == 0, result.output
    out = result.output
    assert "device: busy_fraction=" in out
    assert "overlap_ratio=" in out
    assert "program ea_scan" in out
    assert "tenant device seconds:" in out
    assert "spans_dropped=0" in out


# ------------------------------------------- concurrent-scheduler joins


def test_ledger_overlapping_spans_join_by_duration():
    """ISSUE 19: under the task-graph scheduler, same-name spans from
    concurrent worker threads overlap in host time, and host start
    order no longer predicts trace window order. The join must match
    windows by duration similarity, not rank — otherwise device time
    cross-wires between buckets."""
    trace = {
        "traceEvents": [
            _meta(1, pname="/host:CPU"),
            _meta(1, tid=10, tname="python"),
            _meta(7, pname="/device:TPU:0"),
            _meta(7, tid=1, tname="lane-0"),
            _x(1, 10, "gp_fit", 0, 100),   # window A: 100us
            _x(1, 10, "gp_fit", 200, 30),  # window B: 30us
            _x(7, 1, "op.1", 10, 50),      # 50us busy inside A
            _x(7, 1, "op.2", 205, 10),     # 10us busy inside B
        ]
    }
    led = DeviceLedger()
    # the SHORT span starts first on the host clock (rank join would
    # hand it window A); both overlap — concurrent scheduler nodes
    spans = [
        _span("gp_fit", 1, 50.0, 50.0 + 30e-6, bucket="b_small"),
        _span("gp_fit", 2, 50.0 + 10e-6, 50.0 + 110e-6, bucket="b_big"),
    ]
    cap = led.ingest_chrome_trace(trace, spans)
    rows = {(r.program, r.bucket): r for r in led.program_rows()}
    # duration match: the 100us span owns window A's 50us of device
    # time, the 30us span owns window B's 10us
    assert rows[("gp_fit", "b_big")].device_time_s == pytest.approx(50e-6)
    assert rows[("gp_fit", "b_small")].device_time_s == pytest.approx(10e-6)
    assert cap.join_fraction == 1.0


def test_ledger_overlapping_spans_attribution_stays_exact():
    """Tenant attribution under the duration join: every joined
    window's device seconds split by the host-share weights, and the
    total attributed equals the total joined device time exactly."""
    trace = {
        "traceEvents": [
            _meta(1, pname="/host:CPU"),
            _meta(1, tid=10, tname="python"),
            _meta(7, pname="/device:TPU:0"),
            _meta(7, tid=1, tname="lane-0"),
            _x(1, 10, "gp_fit", 0, 100),
            _x(1, 10, "gp_fit", 200, 30),
            _x(7, 1, "op.1", 10, 50),
            _x(7, 1, "op.2", 205, 10),
        ]
    }
    led = DeviceLedger()
    spans = [
        _span("gp_fit", 1, 50.0, 50.0 + 30e-6, bucket="b_small"),
        _span("tenant_cost", 2, 50.0, 50.0 + 30e-6, parent=1,
              tenant="c", phase="fit"),
        _span("gp_fit", 3, 50.0 + 10e-6, 50.0 + 110e-6, bucket="b_big"),
        _span("tenant_cost", 4, 50.0 + 10e-6, 50.0 + 70e-6, parent=3,
              tenant="a", phase="fit"),
        _span("tenant_cost", 5, 50.0 + 70e-6, 50.0 + 110e-6, parent=3,
              tenant="b", phase="fit"),
    ]
    led.ingest_chrome_trace(trace, spans)
    tds = led.tenant_device_seconds()
    # b_big's 50us splits 60/40 across a/b; b_small's 10us all to c
    assert tds["a"]["fit"] == pytest.approx(50e-6 * 0.6, rel=1e-6)
    assert tds["b"]["fit"] == pytest.approx(50e-6 * 0.4, rel=1e-6)
    assert tds["c"]["fit"] == pytest.approx(10e-6, rel=1e-6)
    total = sum(sum(p.values()) for p in tds.values())
    assert total == pytest.approx(60e-6, rel=1e-6)


@pytest.fixture(scope="module")
def profiled_scheduler_service(tmp_path_factory):
    """The ISSUE-19 acceptance workload: the same profiled 2-bucket,
    3-tenant service, stepped by the task-graph scheduler (concurrency
    3) so bucket/seq nodes run on worker threads and their gp_fit /
    ea_scan spans can overlap during the profiled step."""
    prof_dir = str(tmp_path_factory.mktemp("prof_sched"))
    svc = OptimizationService(
        min_bucket=1,
        scheduler=3,
        telemetry={"profile_dir": prof_dir, "profile_epochs": [1]},
    )

    def submit(dim, seed, n_epochs):
        return svc.submit(
            zdt1,
            {f"x{i}": [0.0, 1.0] for i in range(dim)},
            ["f1", "f2"],
            n_epochs=n_epochs,
            population_size=16,
            num_generations=4,
            n_initial=3,
            surrogate_method_kwargs=dict(SMK),
            random_seed=seed,
        )

    submit(4, 1, 3)
    submit(4, 2, 3)
    submit(6, 3, 3)
    svc.run()
    snap = svc.introspect()
    yield svc, snap
    svc.close()


def test_scheduler_profiled_ledger_joins_and_attribution_sum(
    profiled_scheduler_service,
):
    """Re-pin the ISSUE-12 device-truth gates with the scheduler
    enabled (out-of-order node completion): gp_fit/ea_scan spans still
    join >= 90%, and the tenant_device_seconds counter total still
    equals the ledger's attributed total exactly."""
    svc, snap = profiled_scheduler_service
    dl = snap.get("device_ledger")
    assert dl is not None, "profiled scheduler step produced no ledger"
    assert dl["captures"] >= 1
    fit_ea = [
        r for r in dl["programs"] if r["program"] in ("gp_fit", "ea_scan")
    ]
    assert fit_ea
    n_spans = sum(r["n_spans"] for r in fit_ea)
    n_joined = sum(r["n_joined"] for r in fit_ea)
    assert n_spans > 0
    assert n_joined / n_spans >= 0.9, (n_joined, n_spans)
    assert sum(r["device_time_s"] for r in fit_ea) > 0
    # the attribution-sum gate, scheduler-enabled
    tds = dl.get("tenant_device_seconds")
    assert tds and len(tds) == 3
    counters = svc.telemetry.registry.snapshot()["counters"].get(
        "tenant_device_seconds", {}
    )
    assert counters, "tenant_device_seconds counter never incremented"
    assert sum(counters.values()) == pytest.approx(
        sum(sum(p.values()) for p in tds.values()), rel=1e-6
    )
    # the step that profiled ran through the task graph
    assert snap.get("scheduler", {}).get("last_graph", {}).get("nodes")
