"""Task-DAG scheduler (dmosopt_tpu.parallel.taskgraph) + the service's
async task-graph epochs (ISSUE 19 tentpole).

The load-bearing pins: a scheduler step at concurrency 1 executes the
lockstep sequence bitwise, and at concurrency N per-tenant fronts stay
bitwise-equal because every tenant owns an independent RNG stream —
only the interleaving changes.
"""

import contextlib

import numpy as np
import pytest

from dmosopt_tpu.benchmarks.zdt import zdt1
from dmosopt_tpu.parallel.taskgraph import (
    DONE,
    FAILED,
    SKIPPED,
    TaskGraph,
    resolve_concurrency,
)
from dmosopt_tpu.service import OptimizationService

SMK = {"n_starts": 2, "n_iter": 30, "seed": 0}


class _FakeTel:
    """Minimal telemetry facade recording metric calls."""

    def __init__(self):
        self.incs = []
        self.gauges = []
        self.observes = []
        self.events = []

    def inc(self, name, value=1, **labels):
        self.incs.append((name, value, labels))

    def gauge(self, name, value, **labels):
        self.gauges.append((name, value, labels))

    def observe(self, name, value, **labels):
        self.observes.append((name, value, labels))

    def event(self, kind, epoch=None, **fields):
        self.events.append((kind, fields))

    def span(self, name, **labels):
        return contextlib.nullcontext(None)


# ------------------------------------------------------------ graph unit


def test_add_rejects_forward_or_foreign_dep():
    g = TaskGraph("t")
    a = g.add("a", lambda: 1)
    other = TaskGraph("other")
    b_other = other.add("b", lambda: 2)
    with pytest.raises(ValueError):
        g.add("c", lambda: 3, deps=[b_other])
    # same-seq node of ANOTHER graph must not pass the identity check
    assert a.seq == b_other.seq


def test_serial_runs_in_creation_order_and_skips_failed_branch():
    order = []

    def mk(name):
        def fn():
            order.append(name)
            return name

        return fn

    def boom():
        order.append("c")
        raise RuntimeError("c failed")

    g = TaskGraph("t")
    a = g.add("a", mk("a"))
    b = g.add("b", mk("b"), deps=[a])
    c = g.add("c", boom, deps=[a])
    d = g.add("d", mk("d"), deps=[c])  # rides the failed branch
    e = g.add("e", mk("e"), deps=[b])
    run = g.run(concurrency=1)
    assert order == ["a", "b", "c", "e"]
    assert (a.state, b.state, e.state) == (DONE, DONE, DONE)
    assert c.state == FAILED and isinstance(c.error, RuntimeError)
    assert d.state == SKIPPED
    assert run.counts == {"done": 3, "failed": 1, "skipped": 1}
    assert [n.result for n in (a, b, e)] == ["a", "b", "e"]


def test_pooled_diamond_per_branch_degradation():
    """A failed node skips only ITS transitive dependents; the sibling
    branch and the all-deps join behave per-branch."""
    g = TaskGraph("t")
    root = g.add("root", lambda: "r")
    evals = [
        g.add(f"eval{i}", (lambda i=i: i), deps=[root], kind="eval")
        for i in range(4)
    ]
    bad = g.add(
        "bad", lambda: (_ for _ in ()).throw(ValueError("x")),
        deps=[evals[0]], kind="bucket",
    )
    good = g.add("good", lambda: "ok", deps=[evals[1]], kind="bucket")
    dead = g.add("dead", lambda: "never", deps=[bad], kind="fold")
    live = g.add("live", lambda: "alive", deps=[good], kind="fold")
    joined = g.add("join", lambda: "j", deps=[dead, live], kind="checkpoint")
    run = g.run(concurrency=3)
    assert [n.result for n in evals] == [0, 1, 2, 3]
    assert bad.state == FAILED
    assert good.state == DONE and live.result == "alive"
    assert dead.state == SKIPPED
    assert joined.state == SKIPPED  # a dep was skipped -> join skipped
    assert run.counts[DONE] == 7 and run.counts[FAILED] == 1
    assert len(run.failed) == 1 and len(run.skipped) == 2


def test_pooled_matches_serial_results():
    def build():
        g = TaskGraph("t")
        a = g.add("a", lambda: 2)
        bs = [
            g.add(f"b{i}", (lambda i=i: i * 10), deps=[a]) for i in range(6)
        ]
        g.add("c", lambda: sum(n.result for n in bs), deps=bs)
        return g

    serial = build().run(concurrency=1)
    pooled = build().run(concurrency=4)
    assert [n.result for n in serial.nodes] == [n.result for n in pooled.nodes]
    assert all(n.state == DONE for n in pooled.nodes)


def test_emit_telemetry_names_and_stall():
    tel = _FakeTel()
    g = TaskGraph("t")
    a = g.add("a", lambda: 1, kind="bucket")
    g.add("b", lambda: 2, deps=[a], kind="fold")
    run = g.run(concurrency=2, telemetry=tel)
    inc_names = {n for n, _, _ in tel.incs}
    assert "scheduler_nodes_total" in inc_names
    gauge_names = {n for n, _, _ in tel.gauges}
    assert {"scheduler_queue_depth", "scheduler_stall_seconds"} <= gauge_names
    obs_names = {n for n, _, _ in tel.observes}
    assert {
        "scheduler_node_wait_seconds", "scheduler_node_run_seconds"
    } <= obs_names
    assert tel.events and tel.events[0][0] == "scheduler_run"
    assert run.stall_s >= 0.0


def test_resolve_concurrency():
    assert resolve_concurrency(None) == 0
    assert resolve_concurrency(False) == 0
    assert resolve_concurrency(0) == 0
    assert resolve_concurrency(1) == 1
    assert resolve_concurrency(5) == 5
    assert resolve_concurrency(True) >= 2
    assert resolve_concurrency({"concurrency": 3}) == 3
    assert resolve_concurrency({}) >= 2


# ------------------------------------------------------- service parity


def _submit(svc, *, dim, seed, n_epochs=2, num_generations=4):
    return svc.submit(
        zdt1,
        {f"x{i}": [0.0, 1.0] for i in range(dim)},
        ["f1", "f2"],
        n_epochs=n_epochs,
        population_size=16,
        num_generations=num_generations,
        n_initial=3,
        surrogate_method_kwargs=dict(SMK),
        random_seed=seed,
    )


def _run_service(scheduler):
    svc = OptimizationService(
        min_bucket=2, telemetry=True, scheduler=scheduler
    )
    handles = {
        "a": _submit(svc, dim=5, seed=21),
        "b": _submit(svc, dim=5, seed=22),
        "c": _submit(svc, dim=3, seed=23),
    }
    svc.run()
    fronts = {
        k: [(u.epoch, u.x, u.y) for u in h.updates()]
        for k, h in handles.items()
    }
    assert all(h.done for h in handles.values())
    snap = svc.introspect()
    reg = svc.telemetry.registry
    svc.close()
    return fronts, snap, reg


def _assert_fronts_equal(a, b, tag):
    for k in a:
        assert [e for e, _, _ in a[k]] == [e for e, _, _ in b[k]], (tag, k)
        for (ea, xa, ya), (eb, xb, yb) in zip(a[k], b[k]):
            assert np.array_equal(xa, xb), (tag, k, ea)
            assert np.array_equal(ya, yb), (tag, k, ea)


def test_service_scheduler_bitwise_parity_and_introspection():
    """The acceptance pin: scheduler concurrency 1 reproduces lockstep
    bitwise; concurrency 4 reproduces it too (independent per-tenant
    RNG streams); introspect() exposes the graph; scheduler_* metrics
    flow."""
    lockstep, lock_snap, _ = _run_service(None)
    assert "scheduler" not in lock_snap

    serial, snap1, reg1 = _run_service(1)
    _assert_fronts_equal(lockstep, serial, "concurrency=1")

    pooled, snap4, reg4 = _run_service(4)
    _assert_fronts_equal(lockstep, pooled, "concurrency=4")

    for snap, conc in ((snap1, 1), (snap4, 4)):
        sched = snap["scheduler"]
        assert sched["concurrency"] == conc
        nodes = sched["last_graph"]["nodes"]
        kinds = {n["kind"] for n in nodes}
        assert {"dispatch", "eval", "fold", "checkpoint"} <= kinds
        assert "bucket" in kinds or "seq" in kinds
        assert all(n["state"] == "done" for n in nodes)
    # one bucket (d5 pair) + one seq-or-bucket route for the d3 tenant,
    # and the scheduler counters flowed through the shared registry
    for reg in (reg1, reg4):
        assert reg.counter_value("scheduler_nodes_total", kind="eval") > 0
        assert (
            reg.counter_value("scheduler_nodes_total", kind="bucket")
            + reg.counter_value("scheduler_nodes_total", kind="seq")
        ) > 0
