"""tools/perfdiff.py: the contention-immune bench regression gate
(ISSUE 12) on fixture histories — a clean pass, a seeded device-time
regression turning red, and the BENCH_r04/r05 replay: a wall-only
regression under recorded contention (or on the CPU fallback) reads
`host_contended`/`cpu_fallback` instead of failing.
"""

import copy
import json

from tools.perfdiff import (
    comparable,
    diff,
    flatten_metrics,
    load_history,
    main,
    row_contended,
)

NCPU = 8


def _row(**over):
    """One bench-result row with full self-id provenance, idle host,
    real backend (the shape bench.py appends to BENCH_HISTORY.jsonl)."""
    row = {
        "metric": "zdt1_nsga2_generations_per_sec",
        "value": 3700.0,
        "backend": "tpu",
        "cpu_fallback": False,
        "device_kind": "TPU v4",
        "device_count": 4,
        "cpu_count": NCPU,
        "loadavg_start": [1.0, 1.0, 1.0],
        "loadavg_end": [1.2, 1.0, 1.0],
        "configs": {
            "multi_tenant": {
                "tenants_64": {"wall_sec": 10.0, "tenants_per_sec": 6.4},
                "device": {
                    "device_busy_fraction": 0.8,
                    "programs": {
                        "ea_scan[d4_o2_p16]": {
                            "device_time_s": 2.0,
                            "compile_s": 1.0,
                        },
                        "gp_fit": {"device_time_s": 3.0},
                    },
                },
            },
            "zdt1_agemoea_gpr": {"wall_sec": 90.0},
        },
    }
    row.update(over)
    return row


def test_flatten_classifies_wall_and_device_metrics():
    m = flatten_metrics(_row())
    assert m["value"] == (3700.0, "wall", "higher")
    assert m["configs.multi_tenant.tenants_64.wall_sec"] == (
        10.0, "wall", "lower"
    )
    assert m["configs.multi_tenant.tenants_64.tenants_per_sec"] == (
        6.4, "wall", "higher"
    )
    key = (
        "configs.multi_tenant.device.programs.ea_scan[d4_o2_p16]"
        ".device_time_s"
    )
    assert m[key] == (2.0, "device", "lower")
    # informational leaves are never gated
    assert not any("device_busy_fraction" in k for k in m)
    assert not any(k.endswith("compile_s") for k in m)


def test_comparability_rules():
    run = _row()
    assert comparable(run, _row())
    assert not comparable(run, _row(backend="cpu"))
    assert not comparable(run, _row(cpu_fallback=True))
    assert not comparable(run, _row(device_kind="TPU v5e"))
    # rows without device_kind (pre-ISSUE-12 history) stay comparable
    old = _row()
    del old["device_kind"]
    assert comparable(run, old)
    # TPU device events are host-independent: core count never splits
    # the pool there, but CPU rows' "device" lanes are the host's own
    # threadpool — a different core count is a different instrument
    assert comparable(run, _row(cpu_count=NCPU * 3))
    cpu_run = _row(backend="cpu", device_kind="cpu")
    assert comparable(cpu_run, _row(backend="cpu", device_kind="cpu"))
    assert not comparable(
        cpu_run, _row(backend="cpu", device_kind="cpu", cpu_count=NCPU * 3)
    )


def test_contention_detection():
    assert not row_contended(_row())
    assert row_contended(_row(loadavg_end=[NCPU * 2.0, 1.0, 1.0]))


def test_clean_history_passes():
    history = [_row(), _row()]
    report = diff(_row(), history)
    assert report["status"] == "pass"
    assert report["n_comparable"] == 2
    assert all(c["status"] in ("ok", "improved") for c in report["checks"])


def test_seeded_device_regression_fails_even_under_contention():
    """Device-time regressions gate hard: host contention cannot
    inflate device events, so even a contended run fails on one."""
    bad = _row(loadavg_end=[NCPU * 2.0, 1.0, 1.0])  # contended AND
    bad["configs"]["multi_tenant"]["device"]["programs"][
        "ea_scan[d4_o2_p16]"
    ]["device_time_s"] = 4.0  # 2x the baseline's 2.0s device time
    report = diff(bad, [_row()])
    assert report["status"] == "fail"
    failing = [
        c for c in report["checks"] if c["status"] == "device_regression"
    ]
    assert len(failing) == 1
    assert failing[0]["metric"].endswith("device_time_s")
    assert failing[0]["kind"] == "device"


def test_device_regression_on_contended_cpu_backend_is_suspect():
    """The CPU backend's \"device lanes\" are XLA's Eigen host threads,
    which contention stretches like any wall — a contended CPU run's
    device regression must classify suspect, not fail. On an IDLE CPU
    host the same regression still gates hard (CPU execute time is
    meaningful there)."""
    base = _row(backend="cpu", device_kind="cpu")

    def seeded(**over):
        bad = _row(backend="cpu", device_kind="cpu", **over)
        bad["configs"]["multi_tenant"]["device"]["programs"][
            "ea_scan[d4_o2_p16]"
        ]["device_time_s"] = 4.0
        return bad

    contended = diff(
        seeded(loadavg_end=[NCPU * 2.0, 1.0, 1.0]), [base]
    )
    assert contended["status"] == "suspect"
    assert not any(
        c["status"] == "device_regression" for c in contended["checks"]
    )
    idle = diff(seeded(), [base])
    assert idle["status"] == "fail"
    assert any(
        c["status"] == "device_regression" for c in idle["checks"]
    )


def test_tiny_device_delta_below_absolute_floor_never_gates():
    """A 3x ratio on a 20ms program is a 40ms delta — scheduler noise,
    not a regression; the absolute floor keeps it from hard-failing."""
    base = _row()
    base["configs"]["multi_tenant"]["device"]["programs"][
        "ea_scan[d4_o2_p16]"
    ]["device_time_s"] = 0.02
    noisy = copy.deepcopy(base)
    noisy["configs"]["multi_tenant"]["device"]["programs"][
        "ea_scan[d4_o2_p16]"
    ]["device_time_s"] = 0.06
    report = diff(noisy, [base])
    assert report["status"] == "pass"
    assert not any(
        c["status"] == "device_regression" for c in report["checks"]
    )


def test_wall_regression_on_idle_real_backend_fails():
    bad = _row()
    bad["configs"]["multi_tenant"]["tenants_64"]["wall_sec"] = 30.0
    report = diff(bad, [_row()])
    assert report["status"] == "fail"
    assert any(
        c["status"] == "wall_regression" for c in report["checks"]
    )


def test_wall_only_regression_under_contention_reads_host_contended():
    """The BENCH_r04/r05 replay: walls 3x inflated, loadavg recorded
    above 1.5x cores, device times UNCHANGED — suspect, never failing."""
    bad = _row(loadavg_end=[NCPU * 3.0, NCPU * 2.0, NCPU])
    bad["configs"]["multi_tenant"]["tenants_64"]["wall_sec"] = 30.0
    bad["configs"]["zdt1_agemoea_gpr"]["wall_sec"] = 400.0
    bad["value"] = 900.0
    report = diff(bad, [_row()])
    assert report["status"] == "suspect"
    statuses = {c["status"] for c in report["checks"]}
    assert "host_contended" in statuses
    assert "wall_regression" not in statuses
    assert "device_regression" not in statuses


def test_cpu_fallback_wall_regression_is_suspect_not_failing():
    """The other half of the r04/r05 trap: a CPU-fallback run's walls
    are incomparable to accelerator baselines by construction; within
    its own (cpu_fallback) pool a wall regression is still suspect."""
    base = _row(cpu_fallback=True, backend="cpu")
    bad = copy.deepcopy(base)
    bad["configs"]["multi_tenant"]["tenants_64"]["wall_sec"] = 30.0
    report = diff(bad, [base])
    assert report["status"] == "suspect"
    assert any(c["status"] == "cpu_fallback" for c in report["checks"])


def test_no_comparable_baseline_passes():
    report = diff(_row(backend="tpu"), [_row(backend="cpu")])
    assert report["status"] == "no_baseline"


def _write_history(path, rows):
    with open(path, "w") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\n")


def test_load_history_skips_smoke_partial_fault_and_corrupt(tmp_path):
    p = tmp_path / "h.jsonl"
    with open(p, "w") as fh:
        fh.write(json.dumps(_row()) + "\n")
        fh.write("not json\n")
        fh.write(json.dumps(_row(smoke=True)) + "\n")
        fh.write(json.dumps(_row(partial=True)) + "\n")
        fh.write(json.dumps(_row(fault_plan="seed=1")) + "\n")
        fh.write(
            json.dumps(
                _row(value=0.0, configs={}, error="bench child died")
            )
            + "\n"
        )
        fh.write("\n")
    rows = load_history(str(p))
    assert len(rows) == 1


def test_missing_device_metrics_read_missing_in_run():
    """A device metric every baseline knows but the fresh run did not
    record (capture failed / DMOSOPT_BENCH_DEVICE=0) must surface as a
    `missing_in_run` suspect check, never silently pass — while a
    config absent wholesale (subset run) flags nothing."""
    gap = _row()
    del gap["configs"]["multi_tenant"]["device"]  # config ran, no capture
    report = diff(gap, [_row()])
    assert report["status"] == "suspect"
    missing = [
        c for c in report["checks"] if c["status"] == "missing_in_run"
    ]
    assert {c["metric"] for c in missing} == {
        "configs.multi_tenant.device.programs.ea_scan[d4_o2_p16]"
        ".device_time_s",
        "configs.multi_tenant.device.programs.gp_fit.device_time_s",
    }
    assert all(c["kind"] == "device" and c["value"] is None for c in missing)
    # render must handle the value-less checks
    from tools.perfdiff import render

    assert "missing_in_run" in render(report)

    subset = _row()
    del subset["configs"]["multi_tenant"]  # whole config skipped
    report = diff(subset, [_row()])
    assert report["status"] == "pass"
    assert not any(
        c["status"] == "missing_in_run" for c in report["checks"]
    )


def test_cli_clean_pass_and_seeded_regression_exit_codes(tmp_path, capsys):
    """The `make bench-diff` entry point: last history row judged
    against the rows before it."""
    clean = tmp_path / "clean.jsonl"
    _write_history(clean, [_row(), _row()])
    assert main(["--history", str(clean)]) == 0
    assert "status=pass" in capsys.readouterr().out

    bad_row = _row()
    bad_row["configs"]["multi_tenant"]["device"]["programs"]["gp_fit"][
        "device_time_s"
    ] = 9.0
    red = tmp_path / "red.jsonl"
    _write_history(red, [_row(), bad_row])
    assert main(["--history", str(red)]) == 1
    assert "device_regression" in capsys.readouterr().out

    contended = _row(loadavg_end=[NCPU * 3.0, 1.0, 1.0])
    contended["configs"]["multi_tenant"]["tenants_64"]["wall_sec"] = 40.0
    sus = tmp_path / "sus.jsonl"
    _write_history(sus, [_row(), contended])
    assert main(["--history", str(sus)]) == 0
    assert "host_contended" in capsys.readouterr().out


def test_cli_explicit_run_file_and_empty_history(tmp_path, capsys):
    run_file = tmp_path / "run.json"
    run_file.write_text(json.dumps(_row()))
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert main(
        ["--history", str(empty), "--run", str(run_file)]
    ) == 0
    assert "no_baseline" in capsys.readouterr().out
    # empty history, no --run: clean no-op
    assert main(["--history", str(empty)]) == 0
