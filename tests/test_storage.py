"""HDF5 persistence and checkpoint/resume tests
(reference semantics: dmosopt/dmosopt.py:1474-2324, §5.4 of SURVEY)."""

import numpy as np
import pytest

import dmosopt_tpu
from dmosopt_tpu import storage
from dmosopt_tpu.datatypes import ParameterSpace

h5py = pytest.importorskip("h5py")

N_DIM = 6


def zdt1_obj(pp):
    x = np.array([pp[f"x{i}"] for i in range(N_DIM)])
    f1 = x[0]
    g = 1.0 + 9.0 / (N_DIM - 1) * np.sum(x[1:])
    f2 = g * (1.0 - np.sqrt(f1 / g))
    return np.array([f1, f2])


def _params(file_path, **over):
    params = {
        "opt_id": "zdt1_store",
        "obj_fun": zdt1_obj,
        "objective_names": ["f1", "f2"],
        "space": {f"x{i}": [0.0, 1.0] for i in range(N_DIM)},
        "problem_parameters": {"beta": 0.5},
        "n_initial": 6,
        "n_epochs": 2,
        "population_size": 32,
        "num_generations": 10,
        "resample_fraction": 0.5,
        "optimizer_name": "nsga2",
        "surrogate_method_name": "gpr",
        "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 30, "seed": 0},
        "random_seed": 17,
        "save": True,
        "save_eval": 5,
        "save_surrogate_evals": True,
        "file_path": str(file_path),
        "metadata": {"note": "unit-test"},
    }
    params.update(over)
    return params


def zdt1_obj_with_beta(pp):
    assert "beta" in pp  # problem parameter must be merged in
    return zdt1_obj(pp)


def test_space_json_roundtrip():
    space = ParameterSpace.from_dict(
        {"a": [0, 1], "grp": {"b": [1, 5, True], "c": [-2.0, 2.0]}}
    )
    s = storage._space_to_json(space)
    space2 = storage._space_from_json(s)
    assert space2.parameter_names == space.parameter_names
    assert np.allclose(space2.bound1, space.bound1)
    assert np.allclose(space2.bound2, space.bound2)
    assert list(space2.is_integer) == list(space.is_integer)


def test_save_creates_layout(tmp_path):
    fp = tmp_path / "run.h5"
    # surrogate-eval logs require an epoch with advance_epoch and epoch>0
    # (reference dmosopt.py:1451-1462), i.e. >= 3 epochs
    dmosopt_tpu.run(
        _params(fp, obj_fun=zdt1_obj_with_beta, n_epochs=3, num_generations=5),
        verbose=False,
    )
    with h5py.File(fp, "r") as h5:
        grp = h5["zdt1_store"]
        assert int(grp["random_seed"][()]) == 17
        p = grp["0"]
        n = p["parameters"].shape[0]
        assert n > 0
        assert p["objectives"].shape == (n, 2)
        assert p["epochs"].shape == (n,)
        assert p["predictions"].shape[0] == n
        # epoch-1 resample evals carry surrogate predictions
        preds = p["predictions"][:]
        assert np.isfinite(preds).any()
        assert "surrogate_evals" in p
        assert "optimizer_params" in p


def test_resume_continues_without_reeval(tmp_path):
    fp = tmp_path / "resume.h5"
    dmosopt_tpu.run(_params(fp, n_epochs=2), verbose=False)
    with h5py.File(fp, "r") as h5:
        n_before = h5["zdt1_store"]["0"]["parameters"].shape[0]
        max_epoch_before = int(h5["zdt1_store"]["0"]["epochs"][:].max())

    # resume: same file, 2 more epochs (the final epoch of any run does not
    # evaluate its resamples, so a 1-epoch resume adds no real evals)
    dmosopt_tpu.run(_params(fp, n_epochs=2), verbose=False)
    with h5py.File(fp, "r") as h5:
        X = h5["zdt1_store"]["0"]["parameters"][:]
        epochs = h5["zdt1_store"]["0"]["epochs"][:]
    assert X.shape[0] > n_before
    # the resumed run starts from a later epoch, not epoch 0
    assert int(epochs.max()) > max_epoch_before
    # no point should be evaluated twice
    from scipy.spatial.distance import cdist

    D = cdist(X, X)
    np.fill_diagonal(D, np.inf)
    assert (D < 1e-12).sum() == 0


def test_init_from_h5_name_mismatch(tmp_path):
    fp = tmp_path / "mismatch.h5"
    dmosopt_tpu.run(_params(fp, n_epochs=1), verbose=False)
    with pytest.raises(RuntimeError):
        storage.init_from_h5(str(fp), ["wrong", "names"], "zdt1_store")


def test_resume_restores_space_from_file_alone(tmp_path):
    # file_path-only init: space/problem_parameters come from the store
    fp = tmp_path / "fileonly.h5"
    dmosopt_tpu.run(_params(fp, n_epochs=1), verbose=False)
    params = {
        "opt_id": "zdt1_store",
        "obj_fun": zdt1_obj,
        "n_epochs": 1,
        "population_size": 32,
        "num_generations": 5,
        "surrogate_method_name": "gpr",
        "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 20, "seed": 1},
        "file_path": str(fp),
        "save": True,
    }
    best = dmosopt_tpu.run(params, verbose=False)
    prms, lres = best
    assert len(prms) == N_DIM


def test_multiproblem_constrained_resume(tmp_path):
    """Resume a saved multi-problem constrained run: both problems'
    archives restore and extend without re-evaluating stored points."""
    import dmosopt_tpu
    import dmosopt_tpu.driver as drv

    DIM = 5

    def mp_obj(mpp):
        out = {}
        for pid, pp in mpp.items():
            x = np.array([pp[f"x{i}"] for i in range(DIM)])
            y = np.array([x[0] + 0.01 * pid, 1.0 - x[0]])
            out[pid] = (y, np.array([x[0] - 0.1]))
        return out

    fp = str(tmp_path / "mpres.h5")
    params = {
        "opt_id": "mpres",
        "obj_fun": mp_obj,
        "objective_names": ["f1", "f2"],
        "constraint_names": ["c1"],
        "problem_ids": set([0, 1]),
        "space": {f"x{i}": [0.0, 1.0] for i in range(DIM)},
        "problem_parameters": {},
        "n_initial": 2,
        "n_epochs": 2,
        "population_size": 16,
        "num_generations": 5,
        "surrogate_method_name": "gpr",
        "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 15, "seed": 0},
        "random_seed": 21,
        "file_path": fp,
        "save": True,
    }
    dmosopt_tpu.run(params, verbose=False)
    n_before = {
        pid: drv.dopt_dict["mpres"].optimizer_dict[pid].x.shape[0]
        for pid in (0, 1)
    }
    drv.dopt_dict.clear()

    dmosopt_tpu.run(params, verbose=False)  # resume from the same file
    n_after = {
        pid: drv.dopt_dict["mpres"].optimizer_dict[pid].x.shape[0]
        for pid in (0, 1)
    }
    for pid in (0, 1):
        assert n_after[pid] > n_before[pid], (n_before, n_after)
        strat = drv.dopt_dict["mpres"].optimizer_dict[pid]
        # constraints restored and carried through the resumed epochs
        assert strat.c is not None and strat.c.shape == (n_after[pid], 1)

    # no stored point was re-evaluated: the append-only h5 parameter log
    # (every evaluation ever run) contains no duplicate rows
    import h5py
    from scipy.spatial.distance import cdist

    with h5py.File(fp, "r") as f:
        for pid in ("0", "1"):
            P = np.asarray(f["mpres"][pid]["parameters"])
            D = cdist(P, P)
            np.fill_diagonal(D, np.inf)
            assert D.min() > 1e-12, f"re-evaluated stored point, pid={pid}"
            # the resume advances the epoch labels by exactly the resumed
            # run's epoch count (regression: start_epoch used to advance
            # once PER RESTORED PROBLEM, compounding gaps — 2 problems
            # gave [0, 1, 4] instead of [0, 1, 3])
            ep = np.unique(np.asarray(f["mpres"][pid]["epochs"]))
            assert list(ep) == [0, 1, 3], ep


def test_structured_features_save_and_resume(tmp_path):
    """Compound-dtype feature records (the reference's feature
    convention) flatten to float columns in storage and stay
    concatenable across a resume."""
    import dmosopt_tpu
    import dmosopt_tpu.driver as drv
    import h5py

    DIM = 5

    def obj(pp):
        x = np.array([pp[f"x{i}"] for i in range(DIM)])
        y = np.array([x[0], 1.0 - x[0] + (x[1:] ** 2).sum()])
        f = np.array(
            [(float(x.mean()), float(x.std()))],
            dtype=[("mean_x", "f8"), ("std_x", "f8")],
        )
        return y, f

    fp = str(tmp_path / "feat.h5")
    params = {
        "opt_id": "feat",
        "obj_fun": obj,
        "objective_names": ["f1", "f2"],
        "feature_dtypes": [("mean_x", "f8"), ("std_x", "f8")],
        "space": {f"x{i}": [0.0, 1.0] for i in range(DIM)},
        "problem_parameters": {},
        "n_initial": 2,
        "n_epochs": 2,
        "population_size": 16,
        "num_generations": 5,
        "surrogate_method_name": "gpr",
        "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 15, "seed": 0},
        "random_seed": 4,
        "save": True,
        "file_path": fp,
    }
    best = dmosopt_tpu.run(params, return_features=True, verbose=False)
    assert len(best[2]) > 0  # feature records returned to the caller
    # field names survive the flat-column archive via the default
    # feature constructor built from feature_dtypes
    assert best[2].dtype.names == ("mean_x", "std_x")
    n1 = None
    with h5py.File(fp, "r") as f:
        n1 = f["feat"]["0"]["features"].shape
    assert n1[1] == 2

    drv.dopt_dict.clear()
    dmosopt_tpu.run(params, verbose=False)  # resume must concat cleanly
    with h5py.File(fp, "r") as f:
        F = np.asarray(f["feat"]["0"]["features"])
    assert F.shape[0] > n1[0] and F.shape[1] == 2
    assert np.isfinite(F).all()


def test_subarray_feature_dtype_roundtrip(tmp_path):
    """Subarray feature fields (name, dtype, shape) and class dtype specs
    survive the JSON round trip and the resumed constructor."""
    import dmosopt_tpu
    import dmosopt_tpu.driver as drv

    DIM = 4

    def obj(pp):
        x = np.array([pp[f"x{i}"] for i in range(DIM)])
        f = np.zeros((1,), dtype=[("hist", "f8", (3,)), ("m", np.float64)])
        f["hist"][0] = x[:3]
        f["m"][0] = x.mean()
        return np.array([x[0], 1.0 - x[0]]), f

    fp = str(tmp_path / "subarr.h5")
    params = {
        "opt_id": "subarr",
        "obj_fun": obj,
        "objective_names": ["f1", "f2"],
        "feature_dtypes": [("hist", "f8", (3,)), ("m", np.float64)],
        "space": {f"x{i}": [0.0, 1.0] for i in range(DIM)},
        "problem_parameters": {},
        "n_initial": 2,
        "n_epochs": 2,
        "population_size": 16,
        "num_generations": 5,
        "surrogate_method_name": "gpr",
        "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 15, "seed": 0},
        "random_seed": 4,
        "save": True,
        "file_path": fp,
    }
    best = dmosopt_tpu.run(params, return_features=True, verbose=False)
    assert best[2]["hist"].shape[1:] == (3,)
    drv.dopt_dict.clear()
    dmosopt_tpu.run(params, verbose=False)  # resume: dtype reconstructed
    raw = storage.h5_load_raw(fp, "subarr")
    assert raw["feature_dtypes"] == [("hist", "<f8", (3,)), ("m", "<f8")]


def test_int_subarray_feature_dtype_roundtrip(tmp_path):
    """A bare-int subarray shape — ("hist", "f8", 3), a form np.dtype
    accepts — must survive init_h5 -> h5_load_raw; it used to crash the
    load with TypeError ('int' object is not iterable)."""
    import json

    fp = str(tmp_path / "intshape.h5")
    space = ParameterSpace.from_dict({"x0": [0.0, 1.0]})
    storage.init_h5(
        "intshape", [0], False, space, ["x0"], ["f1"],
        [("hist", "f8", 3), ("m", np.float64)], None, None, None, 1, fp,
    )
    raw = storage.h5_load_raw(fp, "intshape")
    assert raw["feature_dtypes"] == [("hist", "<f8", (3,)), ("m", "<f8")]
    np.dtype(raw["feature_dtypes"])  # numpy accepts the canonical form

    # stores written before the save-time canonicalization carry the raw
    # int; the load guard must normalize it
    with h5py.File(fp, "a") as h5:
        h5["intshape"].attrs["feature_dtypes"] = json.dumps([["hist", "<f8", 3]])
    raw = storage.h5_load_raw(fp, "intshape")
    assert raw["feature_dtypes"] == [("hist", "<f8", (3,))]


def test_non_numeric_plain_feature_passthrough(tmp_path):
    """A plain (non-structured) non-numeric feature array must pass
    through evaluation completion raw instead of crashing the float64
    cast in feature_columns (memory-only; persistence rejects it)."""

    def obj(pp):
        x = np.array([pp[f"x{i}"] for i in range(N_DIM)])
        label = np.array(["lo" if x[0] < 0.5 else "hi"])
        return np.array([x[0], 1.0 - x[0]]), label

    params = {
        "opt_id": "strfeat",
        "obj_fun": obj,
        "objective_names": ["f1", "f2"],
        "feature_dtypes": [("label", "U8")],
        "space": {f"x{i}": [0.0, 1.0] for i in range(N_DIM)},
        "problem_parameters": {},
        "n_initial": 4,
        "n_epochs": 1,
        "population_size": 16,
        "num_generations": 5,
        "surrogate_method_name": "gpr",
        "surrogate_method_kwargs": {"n_starts": 2, "n_iter": 15, "seed": 0},
        "random_seed": 3,
    }
    best = dmosopt_tpu.run(params, return_features=True, verbose=False)
    assert best is not None
    # presentation keeps the raw string labels (no float round trip)
    labels = np.asarray(best[2]).ravel()
    assert set(np.unique(labels)) <= {"lo", "hi"}

    # numeric-parseable strings must NOT be silently float-ified: the
    # dtype decides, so feature_columns rejects any non-numeric array
    with pytest.raises(TypeError, match="not numeric"):
        storage.feature_columns(np.array(["12", "34"]))

    # with persistence on, non-numeric feature dtypes fail at init —
    # not at save time after a completed epoch
    import dmosopt_tpu.driver as drv

    drv.dopt_dict.clear()
    with pytest.raises(ValueError, match="numeric"):
        dmosopt_tpu.run(
            dict(params, save=True,
                 file_path=str(tmp_path / "strfeat_reject.h5")),
            verbose=False,
        )

    # bool features are column-safe (lossless float64 cast) — must not
    # be caught by the non-numeric gate
    assert np.allclose(
        storage.feature_columns(np.array([True, False])), [1.0, 0.0]
    )

    # complex is NOT column-safe: the cast would silently drop the
    # imaginary part
    with pytest.raises(TypeError, match="not numeric"):
        storage.feature_columns(np.array([1.0 + 2.0j]))
    with pytest.raises(TypeError, match="not numeric"):
        storage.feature_columns(np.zeros((1,), dtype=[("z", "c16")]))

    # timedelta64 is a np.number subtype but its unit would be
    # discarded by the cast — also rejected
    with pytest.raises(TypeError, match="not numeric"):
        storage.feature_columns(np.array([1, 2], dtype="m8[us]"))
