"""Run-health engine tests: rule validation, the deterministic
firing -> resolved lifecycle, the seeded rulebook's pinned alert set
under a chaos plan (the fast-suite arm of `make health-smoke`), alert
crash-tail durability, HDF5 alert persistence, and the zero-object
pins (docs/observability.md "Run-health engine")."""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from dmosopt_tpu.telemetry import Telemetry, read_jsonl
from dmosopt_tpu.telemetry.health import (
    HealthEngine,
    HealthRule,
    default_rulebook,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- rules


def test_health_rule_validation():
    HealthRule(name="ok_rule", metric="counter:evals_total", threshold=1.0)
    with pytest.raises(ValueError):
        HealthRule(name="BadName", metric="counter:evals_total", threshold=1)
    with pytest.raises(ValueError):
        HealthRule(name="bad_expr", metric="evals_total", threshold=1)
    with pytest.raises(ValueError):
        HealthRule(
            name="bad_sev", metric="counter:evals_total", threshold=1,
            severity="fatal",
        )
    with pytest.raises(ValueError):
        HealthRule(
            name="bad_cmp", metric="counter:evals_total", threshold=1,
            compare="!=",
        )
    with pytest.raises(ValueError):
        HealthRule(
            name="bad_mode", metric="counter:evals_total", threshold=1,
            mode="rate",
        )
    with pytest.raises(ValueError):
        HealthRule(
            name="bad_for", metric="counter:evals_total", threshold=1,
            for_steps=0,
        )
    # round-trips through the dict spec
    r = HealthRule(
        name="rt", metric="gauge:tenants_active", threshold=3.0,
        compare="<", for_steps=2, mode="value", severity="critical",
    )
    assert HealthRule.from_spec(r.to_dict()) == r


def test_engine_rejects_duplicate_rule_names():
    rules = [
        HealthRule(name="dup", metric="counter:evals_total", threshold=1),
        HealthRule(name="dup", metric="counter:epochs_total", threshold=1),
    ]
    with pytest.raises(ValueError, match="duplicate"):
        HealthEngine(rules=rules)


# -------------------------------------------------------------- lifecycle


def _snapshot(counters=None, gauges=None):
    return {
        "counters": {
            k: {"": float(v)} for k, v in (counters or {}).items()
        },
        "gauges": {k: {"": float(v)} for k, v in (gauges or {}).items()},
        "histograms": {},
    }


def test_value_rule_with_hysteresis_fires_and_resolves():
    eng = HealthEngine(rules=[
        HealthRule(
            name="low_gauge", metric="gauge:tenants_active",
            threshold=2.0, compare="<", for_steps=2,
        ),
    ])
    # one breaching round is NOT enough (for_steps=2)
    assert eng.evaluate(_snapshot(gauges={"tenants_active": 1}), step=1) == []
    tr = eng.evaluate(_snapshot(gauges={"tenants_active": 1}), step=2)
    assert [t["state"] for t in tr] == ["firing"]
    assert eng.active()[0]["rule"] == "low_gauge"
    assert eng.summary()["status"] == "alerting"
    # recovery resolves immediately and clears the streak
    tr = eng.evaluate(_snapshot(gauges={"tenants_active": 5}), step=3)
    assert [t["state"] for t in tr] == ["resolved"]
    assert eng.active() == [] and eng.summary()["status"] == "ok"
    # one breach again: streak restarted from zero
    assert eng.evaluate(_snapshot(gauges={"tenants_active": 0}), step=4) == []


def test_delta_rule_baselines_at_zero_and_tracks_increments():
    eng = HealthEngine(rules=[
        HealthRule(
            name="timeout_surge", metric="counter:eval_timeouts_total",
            threshold=2.0, mode="delta",
        ),
    ])
    # counters are implicitly zero before first emission: a first
    # sighting of 3 is a delta of 3 (the spike must not hide behind a
    # first-observation baseline)
    tr = eng.evaluate(_snapshot(counters={"eval_timeouts_total": 3}), step=1)
    assert [t["state"] for t in tr] == ["firing"] and tr[0]["value"] == 3.0
    # unchanged counter -> delta 0 -> resolve
    tr = eng.evaluate(_snapshot(counters={"eval_timeouts_total": 3}), step=2)
    assert [t["state"] for t in tr] == ["resolved"]
    # +2 is under the >2 threshold
    assert eng.evaluate(
        _snapshot(counters={"eval_timeouts_total": 5}), step=3
    ) == []


def test_missing_gauge_and_introspect_paths_skip_the_rule():
    eng = HealthEngine(rules=[
        HealthRule(
            name="busy_collapse", metric="gauge:device_busy_fraction",
            threshold=0.1, compare="<", for_steps=1,
        ),
        HealthRule(
            name="backlog", metric="introspect:queue_depths.writer_backlog",
            threshold=10.0,
        ),
    ])
    # neither source can answer: no transitions, state frozen
    assert eng.evaluate(_snapshot(), introspect={}, step=1) == []
    assert eng.summary()["status"] == "ok"
    # gauge appears below threshold -> fires; introspect path appears
    tr = eng.evaluate(
        _snapshot(gauges={"device_busy_fraction": 0.05}),
        introspect={"queue_depths": {"writer_backlog": 99}},
        step=2,
    )
    assert sorted(t["rule"] for t in tr) == ["backlog", "busy_collapse"]


def test_critical_alert_and_bool_introspect_leaf():
    eng = HealthEngine(rules=[
        HealthRule(
            name="writer_dead", metric="introspect:writer.failed",
            threshold=1.0, compare=">=", severity="critical",
        ),
    ])
    assert not eng.has_critical()
    tr = eng.evaluate(
        _snapshot(), introspect={"writer": {"failed": True}}, step=1
    )
    assert tr[0]["severity"] == "critical"
    assert eng.has_critical()
    assert eng.summary()["status"] == "critical"
    eng.evaluate(_snapshot(), introspect={"writer": {"failed": False}}, step=2)
    assert not eng.has_critical()


def test_engine_emits_events_and_counters_through_telemetry():
    tel = Telemetry()
    eng = HealthEngine(
        rules=[
            HealthRule(
                name="epoch_watch", metric="counter:epochs_total",
                threshold=0.0, mode="delta",
            )
        ],
        telemetry=tel,
    )
    tel.registry.counter_inc("epochs_total")
    eng.evaluate(tel.registry.snapshot(), step=0, epoch=4)
    events = tel.log.records(kind="health_alert")
    assert len(events) == 1
    ev = events[0]
    assert ev.epoch == 4
    assert ev.fields["rule"] == "epoch_watch"
    assert ev.fields["state"] == "firing"
    assert tel.registry.counter_value(
        "health_alerts_total", rule="epoch_watch", severity="warning"
    ) == 1.0
    # resolved transitions are events only, never counted
    eng.evaluate(tel.registry.snapshot(), step=1, epoch=5)
    assert tel.registry.counter_value(
        "health_alerts_total", rule="epoch_watch", severity="warning"
    ) == 1.0
    assert len(tel.log.records(kind="health_alert")) == 2
    json.dumps([e.to_dict() for e in tel.log.records(kind="health_alert")])


def test_default_rulebook_is_valid_and_deduplicated():
    rules = default_rulebook()
    names = [r.name for r in rules]
    assert len(names) == len(set(names))
    assert "writer_dead" in names and "host_contention" in names
    det = default_rulebook(include_host=False)
    assert "host_contention" not in [r.name for r in det]
    # every rule constructs an engine cleanly
    HealthEngine(rules=rules)


def test_determinism_same_snapshots_same_transitions():
    snaps = [
        _snapshot(counters={"eval_timeouts_total": v})
        for v in (0, 4, 4, 9, 9)
    ]

    def run():
        eng = HealthEngine(rules=default_rulebook(include_host=False))
        out = []
        for i, s in enumerate(snaps):
            out.extend(eng.evaluate(s, step=i))
        return [(t["rule"], t["state"], t["value"], t["step"]) for t in out]

    assert run() == run() != []


# ------------------------------------------------- chaos-plan pinned set

SMK = {"n_starts": 2, "n_iter": 20, "seed": 0}
POLICY = {
    "timeout": 0.15,
    "retries": 0,
    "on_eval_failure": "quorum",
    "min_success_fraction": 0.5,
    "max_failed_epochs": 2,
}
FAULT_PLAN = {
    "seed": 11,
    "rules": [
        {"kind": "hang", "target": "h_hang", "delay_s": 0.6},
        {"kind": "nan", "target": "h_nan", "p": 1.0},
    ],
}
EXPECTED_ALERTS = [
    ("eval_failure_surge", "warning"),
    ("eval_timeout_surge", "warning"),
    ("tenant_quarantine_spike", "warning"),
]


def _host_zdt1(dim):
    def f(pp):
        x = np.asarray([pp[f"x{i}"] for i in range(dim)], dtype=np.float64)
        f1 = x[0]
        g = 1.0 + 9.0 * np.mean(x[1:])
        f2 = g * (1.0 - np.sqrt(f1 / g))
        return np.asarray([f1, f2], dtype=np.float64)

    return f


def _run_health_service():
    from dmosopt_tpu.service import OptimizationService

    svc = OptimizationService(
        min_bucket=2, telemetry=True, eval_policy=dict(POLICY),
        health_rules=default_rulebook(include_host=False),
    )

    def submit(name, seed, n_epochs, policy=None):
        svc.submit(
            _host_zdt1(3),
            {f"x{i}": [0.0, 1.0] for i in range(3)},
            ["f1", "f2"],
            opt_id=name, jax_objective=False,
            population_size=16, num_generations=4, n_initial=3,
            n_epochs=n_epochs, surrogate_method_kwargs=dict(SMK),
            random_seed=seed, eval_policy=policy,
        )

    submit("h_ok", 21, 3)
    submit("h_hang", 22, 2)
    submit("h_nan", 23, 2, policy=dict(POLICY, on_eval_failure="skip"))
    svc.run()
    fired = svc.health.fired()
    active = svc.health.active()
    snap = svc.introspect()
    reg = svc.telemetry.registry
    counts = {
        (r, s): reg.counter_value("health_alerts_total", rule=r, severity=s)
        for r, s in EXPECTED_ALERTS
    }
    svc.close()
    return fired, active, snap, counts


def test_seeded_chaos_plan_fires_exact_alert_set(monkeypatch):
    """The ISSUE 14 determinism pin (mirrors `make health-smoke`): the
    seeded fault plan fires EXACTLY the expected (rule, severity) set,
    every firing is counted, the alerts surface in introspect()['health'],
    and all of them resolve once the faulty tenants are retired."""
    monkeypatch.setenv("DMOSOPT_FAULT_PLAN", json.dumps(FAULT_PLAN))
    fired, active, snap, counts = _run_health_service()
    assert fired == EXPECTED_ALERTS
    assert all(v >= 1 for v in counts.values()), counts
    assert active == [], "alerts must resolve after the faulty retire"
    health = snap["health"]
    assert health["status"] == "ok"
    # firing + resolved for each alert
    assert health["transitions_total"] >= 2 * len(EXPECTED_ALERTS)


def test_fault_free_run_fires_no_alerts(monkeypatch):
    monkeypatch.delenv("DMOSOPT_FAULT_PLAN", raising=False)
    fired, active, snap, counts = _run_health_service()
    assert fired == [] and active == []
    assert snap["health"]["status"] == "ok"
    assert snap["health"]["transitions_total"] == 0
    assert all(v == 0 for v in counts.values())


# ------------------------------------------------------- crash durability


def test_alert_crash_tail_survives_kill(tmp_path):
    """Satellite: every alert fired before the last completed phase
    survives in the JSONL sink when the process dies via os._exit(9) —
    the sink flushes on health_alert transitions exactly like phase
    closes (the PR 10 crash-tail discipline extended to alerts)."""
    sink = tmp_path / "alerts.jsonl"
    script = f"""
import os
from dmosopt_tpu.telemetry import Telemetry
from dmosopt_tpu.telemetry.health import HealthEngine, HealthRule

tel = Telemetry(jsonl_path={str(sink)!r})
eng = HealthEngine(
    rules=[HealthRule(name="crash_watch", metric="counter:evals_total",
                      threshold=0.0, mode="delta")],
    telemetry=tel,
)
tel.registry.counter_inc("evals_total", 5)
eng.evaluate(tel.registry.snapshot(), step=0, epoch=0)
tel.event("phase", epoch=0, phase="train", duration_s=0.5)
os._exit(9)  # killed: no close(), no interpreter shutdown
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (env.get("PYTHONPATH"), REPO) if p
    )
    proc = subprocess.run(
        [sys.executable, "-c", script], env=env,
        capture_output=True, text=True,
    )
    assert proc.returncode == 9, proc.stderr
    events = list(read_jsonl(str(sink)))
    kinds = [e.kind for e in events]
    assert kinds == ["health_alert", "phase"]
    assert events[0].fields["rule"] == "crash_watch"
    assert events[0].fields["state"] == "firing"


# ------------------------------------------------------ HDF5 persistence


def test_alerts_h5_round_trip(tmp_path):
    h5py = pytest.importorskip("h5py")  # noqa: F841
    from dmosopt_tpu.storage import load_alerts_from_h5, save_alerts_to_h5

    path = str(tmp_path / "alerts.h5")
    t0 = [
        {"rule": "quarantine_spike", "severity": "warning",
         "state": "firing", "value": 3.0, "threshold": 0.0, "step": 0},
    ]
    t1 = [
        {"rule": "quarantine_spike", "severity": "warning",
         "state": "resolved", "value": 0.0, "threshold": 0.0, "step": 1},
    ]
    save_alerts_to_h5("run", 0, t0, path)
    save_alerts_to_h5("run", 1, t1, path)
    out = load_alerts_from_h5(path, "run")
    assert out == {0: t0, 1: t1}
    # overwrite-safe on a resumed epoch
    save_alerts_to_h5("run", 1, t0, path)
    assert load_alerts_from_h5(path, "run")[1] == t0
    assert load_alerts_from_h5(path, "other") == {}


# ------------------------------------------------------ zero-object pins


def test_service_without_telemetry_holds_no_health_engine():
    from dmosopt_tpu.service import OptimizationService

    svc = OptimizationService(telemetry=False)
    assert svc.telemetry is None and svc.health is None
    assert "health" not in svc.introspect()
    svc.close()


def test_service_health_rules_false_disables_engine():
    from dmosopt_tpu.service import OptimizationService

    svc = OptimizationService(telemetry=True, health_rules=False)
    assert svc.telemetry is not None and svc.health is None
    svc.close()


# ------------------------------------------------------ driver wiring


def test_driver_epoch_boundary_alerts_persist_to_h5(tmp_path):
    """Driver arm of the tentpole: a NaN-poisoned objective quarantines
    rows, the epoch-boundary health evaluation fires `quarantine_spike`
    (delta of `points_quarantined_total`), and the transitions land in
    the HDF5 `telemetry_alerts` group beside the spans."""
    import dmosopt_tpu
    from dmosopt_tpu.storage import load_alerts_from_h5

    n_dim = 5

    def nan_obj(pp):
        x = np.array([pp[f"x{i}"] for i in range(n_dim)])
        if x[0] > 0.5:
            return np.array([np.nan, np.nan])
        f1 = x[0]
        g = 1.0 + 9.0 / (n_dim - 1) * np.sum(x[1:])
        return np.array([f1, g * (1.0 - np.sqrt(f1 / g))])

    fp = str(tmp_path / "nan_run.h5")
    dmosopt_tpu.run(
        {
            "opt_id": "health_run",
            "obj_fun": nan_obj,
            "objective_names": ["f1", "f2"],
            "space": {f"x{i}": [0.0, 1.0] for i in range(n_dim)},
            "problem_parameters": {},
            "n_initial": 8,
            "n_epochs": 2,
            "population_size": 24,
            "num_generations": 8,
            "resample_fraction": 0.5,
            "surrogate_method_name": "gpr",
            "surrogate_method_kwargs": {
                "n_starts": 2, "n_iter": 20, "seed": 0,
            },
            "random_seed": 11,
            "save": True,
            "file_path": fp,
        },
        verbose=False,
    )
    from dmosopt_tpu.dmosopt import dopt_dict

    dopt = dopt_dict["health_run"]
    assert dopt.health is not None
    fired = dopt.health.fired()
    assert ("quarantine_spike", "warning") in fired
    alerts = load_alerts_from_h5(fp, "health_run")
    assert alerts, "alert transitions must persist beside the spans"
    flat = [a for evs in alerts.values() for a in evs]
    assert any(
        a["rule"] == "quarantine_spike" and a["state"] == "firing"
        for a in flat
    )
    # counted under the cataloged counter with rule/severity labels
    assert dopt.telemetry.registry.counter_value(
        "health_alerts_total", rule="quarantine_spike", severity="warning"
    ) >= 1.0
