"""Mesh-sharded GP fit tests (models/gp_sharded.py) on the forced
8-device CPU mesh.

Oracle pattern, mirroring the sharded rank sweep's: the tiled
shard_map programs are pinned against the single-device dense path —
`posterior_from_params` for the factorization at fixed hyperparameters
(identical math, f32 reduction-order tolerance), jax autodiff of the
dense NMLL for the analytic custom VJP, and `fit_gp_batch` for the full
distributed Adam fit (same restart grid, trajectory-level tolerance).
Routing is pinned by call counting so the single-device default can't
silently change.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from dmosopt_tpu.models import gp, gp_sharded
from dmosopt_tpu.models.gp import GPR_Matern, gp_predict
from dmosopt_tpu.models.predictor import build_whitened_cache
from dmosopt_tpu.parallel.mesh import create_mesh
from dmosopt_tpu.utils.prng import as_key

needs_devices = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)


def _data(P, dim=5, d=2, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    X = rng.uniform(size=(P, dim)).astype(dtype)
    Y = np.stack([np.sin(3.0 * X[:, 0]), X.sum(1)], 1)[:, :d]
    Y = ((Y - Y.mean(0)) / Y.std(0)).astype(dtype)
    return jnp.asarray(X), jnp.asarray(Y)


# -------------------------------------------------- factorization parity


@needs_devices
@pytest.mark.parametrize(
    "n_real,P,tile",
    [
        (64, 64, 16),   # exact bucket, tile < slab
        (50, 64, 64),   # padded bucket, single panel
        (96, 96, 32),   # panel width not aligned with the 12-row slabs
    ],
)
def test_posterior_sharded_matches_oracle(n_real, P, tile):
    """The tiled blocked Cholesky + column-sharded whitening solve must
    reproduce the dense masked factorization at the same (fixed)
    hyperparameters: L, W = L⁻¹, alpha, and the NMLL — including bucket
    padding (identity-decoupled rows) and panels that straddle device
    slab boundaries."""
    mesh = create_mesh(8)
    X, Y = _data(P)
    tm = jnp.asarray((np.arange(P) < n_real).astype(np.float32))
    Ym = Y * tm[:, None]
    amp = jnp.asarray([1.3, 0.8], jnp.float32)
    ls = jnp.asarray([[0.4], [0.7]], jnp.float32)
    noise = jnp.asarray([1e-4, 3e-4], jnp.float32)

    L, W, alpha, nmll = gp_sharded.posterior_sharded(
        X, Ym, tm, amp, ls, noise, kernel="matern52", rel_jitter=1e-4,
        mesh=mesh, shard_axis="pop", tile=tile,
    )
    L0, a0, n0 = gp.posterior_from_params(
        X, Ym, tm, amp, ls, noise, kernel="matern52", rel_jitter=1e-4
    )
    np.testing.assert_allclose(np.asarray(L), np.asarray(L0), atol=2e-5)
    np.testing.assert_allclose(np.asarray(alpha), np.asarray(a0), atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(nmll), np.asarray(n0), rtol=1e-4, atol=1e-3
    )
    # the whitening factor the predictor adopts
    fit0 = gp.GPFit(
        X=X, L=L0, alpha=a0, amp=amp, ls=ls, noise=noise,
        y_mean=jnp.zeros(2), y_std=jnp.ones(2), nmll=n0, train_mask=tm,
    )
    np.testing.assert_allclose(
        np.asarray(W), np.asarray(build_whitened_cache(fit0)), atol=1e-3
    )


@needs_devices
def test_nmll_gradient_matches_autodiff():
    """The analytic custom VJP (½(K⁻¹ − ααᵀ) chained through the local
    kernel rows) must match jax autodiff of the dense NMLL — value and
    gradients w.r.t. amp, lengthscale, and noise — on both exact and
    masked shapes."""
    mesh = create_mesh(8)
    P = 48
    X, Y = _data(P, d=1, seed=3)
    for n_real in (P, 40):
        tm = jnp.asarray((np.arange(P) < n_real).astype(np.float32))
        y = Y[:, 0] * tm

        def ref(a, l, nz):
            K = gp._apply_train_mask(
                gp._regularized_kernel(
                    X, l, a, nz, gp._KERNELS["matern52"], 1e-4
                ),
                tm,
            )
            Lc = jnp.linalg.cholesky(K)
            al = jax.scipy.linalg.cho_solve((Lc, True), y)
            return (
                0.5 * jnp.dot(y, al)
                + jnp.sum(jnp.log(jnp.diagonal(Lc)))
                + 0.5 * jnp.sum(tm) * gp._LOG2PI
            )

        def sh(a, l, nz):
            return gp_sharded.nmll_sharded(
                a, l, nz, X, tm, y, mesh=mesh, tile=16, rel_jitter=1e-4
            )

        args = (
            jnp.float32(1.3), jnp.asarray([0.45], jnp.float32),
            jnp.float32(2e-4),
        )
        v0, g0 = jax.value_and_grad(ref, argnums=(0, 1, 2))(*args)
        v1, g1 = jax.jit(jax.value_and_grad(sh, argnums=(0, 1, 2)))(*args)
        np.testing.assert_allclose(float(v1), float(v0), rtol=1e-5)
        for r, s in zip(g0, g1):
            np.testing.assert_allclose(
                np.asarray(s), np.asarray(r), rtol=2e-3, atol=1e-4
            )


# ------------------------------------------------------- full-fit parity


@needs_devices
@pytest.mark.slow
@pytest.mark.parametrize(
    "n_real,ard",
    [
        (64, False),   # exact bucket
        (50, False),   # padded bucket (mask-decoupled rows)
        (128, True),   # bigger exact bucket, ARD lengthscales
    ],
)
def test_fit_gp_sharded_matches_single_device(n_real, ard):
    """The full distributed Adam fit from the identical restart grid
    must land where `fit_gp_batch` lands: hyperparameters, winning
    restart, NMLL, and the resulting posterior (L/alpha via predict)
    within trajectory tolerance — the gradients are mathematically
    equal, so only f32 reduction order separates the paths."""
    mesh = create_mesh(8)
    dim = 5
    rng = np.random.default_rng(7 + n_real)
    Xr = rng.uniform(size=(n_real, dim))
    Yr = np.stack([np.sin(3.0 * Xr[:, 0]), Xr.sum(1)], 1)
    Yr = (Yr - Yr.mean(0)) / Yr.std(0)
    Xp, Yp, tmask = gp._pad_to_bucket(
        Xr.astype(np.float32), Yr.astype(np.float32)
    )
    X, Y = jnp.asarray(Xp), jnp.asarray(Yp)
    tm = jnp.asarray(tmask)
    common = dict(n_starts=4, n_iter=60, ard=ard)

    ref = gp.fit_gp_batch(as_key(2), X, Y, train_mask=tm, **common)
    sh = gp_sharded.fit_gp_sharded(
        as_key(2), X, Y, train_mask=tm, mesh=mesh, tile=16, **common
    )

    np.testing.assert_array_equal(
        np.asarray(sh.best_start), np.asarray(ref.best_start)
    )
    assert int(sh.n_steps) == int(ref.n_steps)
    np.testing.assert_allclose(
        np.asarray(sh.nmll), np.asarray(ref.nmll), rtol=5e-3, atol=5e-3
    )
    # lengthscales shape the posterior mean — pinned tightly; amplitude
    # sits on the amp/noise ridge the NMLL barely sees (the same
    # non-identifiability refit.py's stability metric accounts for), so
    # two equal-NMLL trajectories may separate along it — pinned loosely
    np.testing.assert_allclose(
        np.log(np.asarray(sh.ls)), np.log(np.asarray(ref.ls)), atol=0.15
    )
    np.testing.assert_allclose(
        np.log(np.asarray(sh.amp)), np.log(np.asarray(ref.amp)), atol=0.3
    )
    # L and alpha at the (close) fitted hyperparameters, via predictions:
    # the mean is the functional gate; variance inherits the amp ridge
    Xq = jnp.asarray(rng.uniform(size=(32, dim)).astype(np.float32))
    mu0, v0 = gp_predict(ref, Xq)
    mu1, v1 = gp_predict(sh, Xq)
    np.testing.assert_allclose(
        np.asarray(mu1), np.asarray(mu0), atol=2e-2
    )
    np.testing.assert_allclose(
        np.asarray(v1), np.asarray(v0), rtol=0.35, atol=1e-4
    )


# ---------------------------------------------------------------- routing


def _count_calls(monkeypatch):
    """Wrap both fit entry points with call counters (the trace-time
    pin: routing happens eagerly in the constructor, so Python-level
    call counts ARE the routing decision)."""
    counts = {"batch": 0, "sharded": 0}
    orig_batch = gp.fit_gp_batch
    orig_sharded = gp_sharded.fit_gp_sharded

    def batch(*a, **k):
        counts["batch"] += 1
        return orig_batch(*a, **k)

    def sharded(*a, **k):
        counts["sharded"] += 1
        return orig_sharded(*a, **k)

    monkeypatch.setattr(gp, "fit_gp_batch", batch)
    monkeypatch.setattr(gp_sharded, "fit_gp_sharded", sharded)
    return counts


@needs_devices
def test_routing_counts_pin_single_device_default(monkeypatch):
    """The single-device default can't silently change: without
    ``surrogate_mesh`` (or below its threshold, or without a mesh) the
    constructor must call `fit_gp_batch` exactly once and the sharded
    fit never; with the opt-in satisfied, the reverse."""
    mesh = create_mesh(8)
    rng = np.random.default_rng(0)
    dim = 4
    xin = rng.uniform(size=(48, dim))
    yin = np.stack([xin[:, 0], xin.sum(1)], 1)
    args = (xin, yin, dim, 2, np.zeros(dim), np.ones(dim))
    fast = dict(seed=0, n_starts=2, n_iter=10)

    # default: no surrogate_mesh knob at all
    counts = _count_calls(monkeypatch)
    GPR_Matern(*args, mesh=mesh, **fast)
    assert counts == {"batch": 1, "sharded": 0}

    # opted in but below the archive-size threshold
    counts = _count_calls(monkeypatch)
    GPR_Matern(
        *args, mesh=mesh, surrogate_mesh={"min_points": 10_000}, **fast
    )
    assert counts == {"batch": 1, "sharded": 0}

    # opted in but no mesh to shard over
    counts = _count_calls(monkeypatch)
    GPR_Matern(*args, surrogate_mesh={"min_points": 0}, **fast)
    assert counts == {"batch": 1, "sharded": 0}

    # fully opted in: the sharded path serves, the dense fit never runs.
    # Default predictor is "solve" — the unused W = L⁻¹ factor must be
    # dropped (holding it would double resident fit memory for nothing)
    counts = _count_calls(monkeypatch)
    sm = GPR_Matern(
        *args, mesh=mesh,
        surrogate_mesh={"min_points": 0, "tile": 16}, **fast,
    )
    assert counts == {"batch": 0, "sharded": 1}
    assert sm.fit_info.get("sharded") is True
    assert sm.fit_info.get("shard_devices") == 8
    assert sm.fit.whitened is None

    # a matmul predictor keeps the factor (it serves predict)
    counts = _count_calls(monkeypatch)
    sm = GPR_Matern(
        *args, mesh=mesh, predictor="matmul",
        surrogate_mesh={"min_points": 0, "tile": 16}, **fast,
    )
    assert counts == {"batch": 0, "sharded": 1}
    assert sm.fit.whitened is not None

    # a tile that does not divide the padding bucket degrades to the
    # default tile instead of crashing mid-run (the fallback discipline)
    counts = _count_calls(monkeypatch)
    sm = GPR_Matern(
        *args, mesh=mesh,
        surrogate_mesh={"min_points": 0, "tile": 100}, **fast,
    )
    assert counts == {"batch": 0, "sharded": 1}
    assert sm.fit_info.get("shard_tile") == gp_sharded.default_chol_tile(
        sm.fit.X.shape[0]
    )


@needs_devices
def test_routing_falls_back_on_nonfinite_probe(monkeypatch):
    """The post-fit finite probe: a sharded fit returning a non-finite
    NMLL is discarded and the single-device fit serves instead — the
    routed path may fail, it must never be served failed."""
    mesh = create_mesh(8)
    rng = np.random.default_rng(1)
    dim = 4
    xin = rng.uniform(size=(48, dim))
    yin = np.stack([xin[:, 0], xin.sum(1)], 1)
    counts = _count_calls(monkeypatch)
    orig = gp_sharded.fit_gp_sharded

    def poisoned(*a, **k):
        counts["sharded"] += 1
        fit = orig(*a, **k)
        return fit._replace(nmll=jnp.full_like(fit.nmll, jnp.inf))

    monkeypatch.setattr(gp_sharded, "fit_gp_sharded", poisoned)
    sm = GPR_Matern(
        xin, yin, dim, 2, np.zeros(dim), np.ones(dim),
        mesh=mesh, surrogate_mesh={"min_points": 0, "tile": 16},
        seed=0, n_starts=2, n_iter=10,
    )
    assert counts["batch"] == 1  # fell back
    assert "sharded" not in sm.fit_info
    assert np.all(np.isfinite(np.asarray(sm.fit.nmll)))


def test_surrogate_mesh_spec_validation():
    assert gp._resolve_surrogate_mesh_spec(None) is None
    assert gp._resolve_surrogate_mesh_spec(False) is None
    spec = gp._resolve_surrogate_mesh_spec(True)
    assert spec["min_points"] == 4096 and spec["tile"] is None
    spec = gp._resolve_surrogate_mesh_spec({"min_points": 16, "tile": 32})
    assert spec["min_points"] == 16 and spec["tile"] == 32
    with pytest.raises(ValueError):
        gp._resolve_surrogate_mesh_spec({"bogus_knob": 1})
    with pytest.raises(TypeError):
        gp._resolve_surrogate_mesh_spec("yes")


def test_default_chol_tile_divides():
    for P in (64, 96, 128, 320, 512, 768, 4096, 32768):
        B = gp_sharded.default_chol_tile(P)
        assert P % B == 0 and B <= 512


# -------------------------------------------------- predictor composition


@needs_devices
def test_matmul_predictor_adopts_fit_whitened():
    """A routed sharded fit carries W = L⁻¹; the matmul predictor must
    adopt it (no O(N³) rebuild) and serve the same answers as a
    predictor that built its own cache from the same posterior."""
    mesh = create_mesh(8)
    rng = np.random.default_rng(4)
    dim = 4
    xin = rng.uniform(size=(56, dim))
    yin = np.stack([np.sin(2 * xin[:, 0]), xin.sum(1)], 1)
    sm = GPR_Matern(
        xin, yin, dim, 2, np.zeros(dim), np.ones(dim),
        mesh=mesh, surrogate_mesh={"min_points": 0, "tile": 16},
        seed=0, n_starts=2, n_iter=20, predictor="matmul",
    )
    pred = sm.build_predictor()
    assert pred.regime == "matmul"
    assert pred.whitened is sm.fit.whitened  # adopted, not rebuilt
    np.testing.assert_allclose(
        np.asarray(pred.whitened),
        np.asarray(build_whitened_cache(sm.fit)),
        atol=2e-4,
    )
    Xq = jnp.asarray(rng.uniform(size=(16, dim)).astype(np.float32))
    mu, var = pred.predict_normalized(Xq)
    mu0, var0 = gp_predict(sm.fit, Xq)
    np.testing.assert_allclose(np.asarray(mu), np.asarray(mu0), atol=1e-3)
    np.testing.assert_allclose(
        np.asarray(var), np.asarray(var0), rtol=2e-2, atol=1e-5
    )


@needs_devices
def test_nystrom_predictor_releases_fit_whitened():
    """With predictor="nystrom" the fit-carried W = L⁻¹ exists only as
    the probe-failure matmul fallback; once the distillation probe
    passes, the (d, P, P) factor must be released rather than held
    resident all epoch."""
    mesh = create_mesh(8)
    rng = np.random.default_rng(6)
    dim = 4
    xin = rng.uniform(size=(56, dim))
    yin = np.stack([np.sin(2 * xin[:, 0]), xin.sum(1)], 1)
    sm = GPR_Matern(
        xin, yin, dim, 2, np.zeros(dim), np.ones(dim),
        mesh=mesh, surrogate_mesh={"min_points": 0, "tile": 16},
        seed=0, n_starts=2, n_iter=20, predictor="nystrom",
    )
    assert sm.fit.whitened is not None  # held for the fallback...
    pred = sm.build_predictor()
    if pred.regime == "nystrom":  # ...released once the probe passes
        assert sm.fit.whitened is None
    else:  # probe-failure fallback adopted it instead
        assert pred.whitened is not None


def test_rank_update_drops_stale_whitened():
    """A rank-k posterior update changes L, so a fit-carried whitening
    factor would be stale — the refit controller must drop it (the
    predictor layer rebuilds or extends its own cache)."""
    from dmosopt_tpu.models.refit import (
        SurrogateRefitConfig,
        SurrogateRefitController,
    )
    from dmosopt_tpu import moasmo

    rng = np.random.default_rng(2)
    dim = 4
    X = rng.uniform(size=(80, dim))
    Y = np.column_stack([X.sum(1), ((X - 0.5) ** 2).sum(1)])
    # rank_update_after=0: rank-eligible right after the first fit
    ctrl = SurrogateRefitController(
        SurrogateRefitConfig("warm", rank_update_after=0)
    )
    kwargs = {"n_starts": 2, "n_iter": 40, "seed": 0}

    def train(n):
        return moasmo.train(
            dim, 2, np.zeros(dim), np.ones(dim), X[:n], Y[:n], None,
            surrogate_method_kwargs=dict(kwargs), surrogate_refit=ctrl,
        )

    sm = train(56)
    # simulate a sharded fit's factor riding the cached posterior
    sm.fit = sm.fit._replace(whitened=build_whitened_cache(sm.fit))
    sm2 = train(60)  # append inside the bucket -> rank path
    assert ctrl.path_history[-1] == "rank"
    assert sm2.fit_info.get("refit_path") == "rank"
    assert sm2.fit.whitened is None
