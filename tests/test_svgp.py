"""Sparse variational GP surrogate tests
(reference semantics: dmosopt/model.py GPflow family)."""

import numpy as np
import jax.numpy as jnp
import pytest

from dmosopt_tpu.models.svgp import (
    CRV_Matern,
    SIV_Matern,
    SPV_Matern,
    SVGP_Matern,
    VGP_Matern,
)


def _data(n=200, d_in=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(size=(n, d_in))
    Y = np.column_stack(
        [
            np.sin(3 * X[:, 0]) + 0.5 * X[:, 1],
            np.cos(2 * X[:, 1]) * X[:, 2],
        ]
    )
    Y += 0.01 * rng.normal(size=Y.shape)
    return X, Y


FIT_KW = dict(n_iter=200, batch_size=128, seed=0)
# for tests asserting only shapes/interfaces, not fit quality
SHAPE_KW = dict(n_iter=40, batch_size=128, seed=0)


@pytest.mark.parametrize(
    "cls", [SVGP_Matern, SPV_Matern, SIV_Matern, CRV_Matern, VGP_Matern]
)
def test_svgp_variants_fit_and_predict(cls):
    X, Y = _data()
    m = cls(X, Y, 4, 2, np.zeros(4), np.ones(4), **FIT_KW)
    mean, var = m.predict(X[:50])
    mean, var = np.asarray(mean), np.asarray(var)
    assert mean.shape == (50, 2) and var.shape == (50, 2)
    assert np.all(var > 0)
    # in-sample fit should beat predicting the mean
    resid = np.mean((mean - Y[:50]) ** 2, axis=0)
    base = np.var(Y, axis=0)
    assert np.all(resid < 0.5 * base), (cls.__name__, resid, base)


def test_svgp_uses_fewer_inducing_points():
    X, Y = _data(n=300)
    m = SVGP_Matern(
        X, Y, 4, 2, np.zeros(4), np.ones(4),
        inducing_fraction=0.2, min_inducing=30, **SHAPE_KW,
    )
    assert m.fit.params.Z.shape[1] == 60  # 0.2 * 300
    v = VGP_Matern(X, Y, 4, 2, np.zeros(4), np.ones(4), **SHAPE_KW)
    assert v.fit.params.Z.shape[1] == 300


def test_crv_has_mixing_matrix():
    X, Y = _data()
    m = CRV_Matern(X, Y, 4, 2, np.zeros(4), np.ones(4), **SHAPE_KW)
    assert m.fit.params.W is not None
    assert m.fit.params.W.shape == (2, 2)


def test_svgp_mean_variance_interface():
    X, Y = _data(n=120)
    m = SVGP_Matern(
        X, Y, 4, 2, np.zeros(4), np.ones(4),
        return_mean_variance=True, **SHAPE_KW,
    )
    out = m.evaluate(X[:10])
    assert isinstance(out, tuple) and len(out) == 2


def test_svgp_in_moasmo_epoch():
    from dmosopt_tpu import moasmo
    from dmosopt_tpu.benchmarks.zdt import zdt1

    rng = np.random.default_rng(1)
    X = rng.uniform(size=(120, 6)).astype(np.float32)
    Y = np.asarray(zdt1(jnp.asarray(X)))
    gen = moasmo.epoch(
        num_generations=5,
        param_names=[f"x{i}" for i in range(6)],
        objective_names=["f1", "f2"],
        xlb=np.zeros(6),
        xub=np.ones(6),
        pct=0.5,
        Xinit=X,
        Yinit=Y,
        C=None,
        pop=16,
        optimizer_name="nsga2",
        surrogate_method_name="svgp",
        surrogate_method_kwargs={"n_iter": 60, "min_inducing": 40, "seed": 0},
        local_random=2,
    )
    with pytest.raises(StopIteration) as ex:
        next(gen)
    res = ex.value.value
    assert res["x_resample"].shape == (8, 6)
