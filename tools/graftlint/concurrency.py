"""Shared concurrency model for the thread-aware graftlint rules.

Built once per `LintContext` (cached on the context) and consumed by
``shared-state-guard``, ``lock-discipline`` and ``resource-lifecycle``:

- **lock discovery**: instance attributes assigned
  ``threading.Lock()``/``RLock()``/``Condition()``/``Semaphore()`` and
  module-level names bound to the same, each with a canonical *lock id*
  (``pkg.mod.Class._lock`` / ``pkg.mod.LOCK``). Attributes assigned
  intrinsically thread-safe types (``queue.Queue``, ``threading.Event``,
  executors, ``threading.local``) are discovered too — the shared-state
  rule exempts them.
- **lexical lock regions**: per function, every ``with <lock>:`` region
  and the tuple of lock ids held at each interesting node (attribute
  access, call, manual ``acquire()``), plus lock-ordering edges and
  same-lock nestings.
- **caller-holds-lock propagation**: the repo's documented "caller
  holds ``self._lock``" idiom, computed instead of trusted — a function
  is *entry-locked* on L when EVERY analyzed call site runs with L held
  (lexically or itself entry-locked). Thread targets are never
  entry-locked: a spawn does not inherit the spawner's locks.
- **execution contexts**: per function, the set of thread roots it is
  reachable from (engine thread-root resolver) plus ``<main>`` when it
  is reachable from non-threaded code (public API, module scope).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from tools.graftlint.engine import (
    FunctionInfo,
    LintContext,
    _function_targets,
)

#: lock constructors (RLock is reentrant — same-lock nesting is legal)
LOCK_TYPES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "multiprocessing.Lock", "multiprocessing.RLock",
}
REENTRANT_TYPES = {"threading.RLock", "multiprocessing.RLock"}

#: intrinsically thread-safe attribute types — exempt from the
#: shared-state guard (their own synchronization is the guard)
THREADSAFE_TYPES = {
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue",
    "threading.Event", "threading.local", "threading.Barrier",
    "concurrent.futures.ThreadPoolExecutor",
    "concurrent.futures.ProcessPoolExecutor",
}

#: attribute-method calls that MUTATE their receiver in place — a
#: ``self.X.append(...)`` is a write to the shared container X
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "add", "update",
    "pop", "popleft", "popitem", "remove", "discard", "clear",
    "insert", "setdefault", "sort", "reverse", "put", "put_nowait",
}

#: method qualname tails whose writes are construction-time — they
#: happen before any thread can observe the object
INIT_METHODS = {"__init__", "__post_init__", "__new__"}

MAIN = "<main>"


@dataclasses.dataclass
class Access:
    """One shared-state touch: ``self.attr`` or a module global."""

    owner: str  # canonical owner id (class component root / module)
    name: str  # attribute or global name
    fn: FunctionInfo
    node: ast.AST
    write: bool
    held: Tuple[str, ...]  # lexically held lock ids at the node


@dataclasses.dataclass
class CallSite:
    targets: List[str]
    node: ast.Call
    held: Tuple[str, ...]


@dataclasses.dataclass
class FnConc:
    """Per-function lexical concurrency facts."""

    regions: List[Tuple[str, ast.AST]] = dataclasses.field(default_factory=list)
    order_edges: List[Tuple[str, str, ast.AST]] = dataclasses.field(
        default_factory=list
    )
    same_lock_nesting: List[Tuple[str, ast.AST]] = dataclasses.field(
        default_factory=list
    )
    acquires: List[Tuple[Optional[str], ast.AST, bool, Tuple[str, ...]]] = (
        dataclasses.field(default_factory=list)
    )  # (lock id, node, release-protected, held)
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    attr_accesses: List[Access] = dataclasses.field(default_factory=list)
    global_accesses: List[Access] = dataclasses.field(default_factory=list)
    blocking: List[Tuple[str, ast.AST, Tuple[str, ...]]] = dataclasses.field(
        default_factory=list
    )
    #: lock ids released in ANY try/finally of the function — the
    #: classic acquire-before-try form counts as release-protected
    finally_releases: Set[str] = dataclasses.field(default_factory=set)


#: canonical names whose call blocks the calling thread
BLOCKING_CANON = {
    "time.sleep": "time.sleep()",
    "h5py.File": "h5py.File() (file IO)",
    "subprocess.run": "subprocess.run()",
    "subprocess.call": "subprocess.call()",
    "subprocess.check_call": "subprocess.check_call()",
    "subprocess.check_output": "subprocess.check_output()",
    "subprocess.Popen": "subprocess.Popen()",
}
#: attribute-call names that block (joins, future results, cond waits);
#: excluded when the receiver is plainly a string/path join
BLOCKING_ATTRS = {"result", "join", "wait", "acquire"}
_JOIN_EXCLUDE_CANON = {"os.path.join", "posixpath.join", "ntpath.join",
                       "str.join", "shlex.join", "bytes.join"}


class ConcurrencyModel:
    def __init__(self, ctx: LintContext):
        self.ctx = ctx
        # lock/thread-safe/queue attribute discovery
        self.class_lock_attrs: Dict[str, Dict[str, str]] = {}  # cls -> {attr: ctor}
        self.class_safe_attrs: Dict[str, Set[str]] = {}
        self.class_queue_attrs: Dict[str, Set[str]] = {}
        self.module_locks: Dict[str, Dict[str, str]] = {}  # mod -> {name: ctor}
        self._discover_locks()
        self.lock_ctor: Dict[str, str] = {}
        for cls, attrs in self.class_lock_attrs.items():
            for attr, ctor in attrs.items():
                self.lock_ctor[f"{cls}.{attr}"] = ctor
        for modname, names in self.module_locks.items():
            for name, ctor in names.items():
                self.lock_ctor[f"{modname}.{name}"] = ctor
        # class components: self.attr storage is shared across the
        # hierarchy, so accesses group under one canonical owner
        self._owner_cache: Dict[str, str] = {}
        # per-function lexical walk
        self.fn_conc: Dict[str, FnConc] = {}
        for info in ctx.functions.values():
            self.fn_conc[info.full_name] = _walk_function(self, info)
        # caller-holds-lock propagation and main-path reachability
        self.entry_locks = self._compute_entry_locks()
        self.main_set = self._compute_main_set()

    # ------------------------------------------------------------ locks

    def _discover_locks(self):
        ctx = self.ctx
        for mod in ctx.modules:
            # module-level NAME = threading.Lock()
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Call
                ):
                    ctor = mod.resolve(stmt.value.func)
                    if ctor in LOCK_TYPES:
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                self.module_locks.setdefault(
                                    mod.modname, {}
                                )[t.id] = ctor
            # self.X = threading.Lock() / queue.Queue() / ... anywhere
            # in a method body (usually __init__, but lazily-created
            # pools count too). AnnAssign and conditional-expression
            # values (`x if cond else None`) are unwrapped.
            for info in mod.functions.values():
                if not info.class_name:
                    continue
                cls = f"{mod.modname}.{info.class_name}"
                for node in ast.walk(info.node):
                    if isinstance(node, ast.Assign):
                        targets, value = node.targets, node.value
                    elif (
                        isinstance(node, ast.AnnAssign)
                        and node.value is not None
                    ):
                        targets, value = [node.target], node.value
                    else:
                        continue
                    ctors = {
                        mod.resolve(sub.func)
                        for sub in ast.walk(value)
                        if isinstance(sub, ast.Call)
                    }
                    for t in targets:
                        if not (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id in ("self", "cls")
                        ):
                            continue
                        for ctor in ctors:
                            if ctor in LOCK_TYPES:
                                self.class_lock_attrs.setdefault(
                                    cls, {}
                                )[t.attr] = ctor
                            elif ctor in THREADSAFE_TYPES:
                                self.class_safe_attrs.setdefault(
                                    cls, set()
                                ).add(t.attr)
                                if (ctor or "").startswith("queue."):
                                    self.class_queue_attrs.setdefault(
                                        cls, set()
                                    ).add(t.attr)

    def is_reentrant(self, lock_id: str) -> bool:
        return self.lock_ctor.get(lock_id) in REENTRANT_TYPES

    def _class_component(self, cls: str) -> str:
        """Canonical owner for a class: the lexicographically smallest
        member of its relatives closure (self.attr storage is shared
        across the hierarchy)."""
        cached = self._owner_cache.get(cls)
        if cached is None:
            rel = self.ctx.class_relatives.get(cls, {cls})
            cached = self._owner_cache[cls] = min(rel | {cls})
        return cached

    def lock_id(self, info: FunctionInfo, expr: ast.AST) -> Optional[str]:
        """Canonical lock id for a with-item / acquire receiver, or
        None when the expression is not lock-like. Known lock attrs and
        module locks match structurally; otherwise a name containing
        'lock'/'mutex' is accepted (fixture-friendly fallback)."""
        mod = info.module
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id in ("self", "cls")
            and info.class_name
        ):
            own = f"{mod.modname}.{info.class_name}"
            for cls in sorted(self.ctx.class_relatives.get(own, {own}) | {own}):
                if expr.attr in self.class_lock_attrs.get(cls, {}):
                    return f"{cls}.{expr.attr}"
            if "lock" in expr.attr.lower() or "mutex" in expr.attr.lower():
                return f"{own}.{expr.attr}"
            return None
        if isinstance(expr, (ast.Name, ast.Attribute)):
            canon = mod.resolve(expr)
            if canon is not None:
                # a bare in-module name resolves unqualified: anchor it
                # to this module (the module_locks/lock_ctor key shape)
                if "." not in canon:
                    qualified = f"{mod.modname}.{canon}"
                    if canon in self.module_locks.get(mod.modname, {}):
                        return qualified
                    if "lock" in canon.lower() or "mutex" in canon.lower():
                        return qualified
                    return None
                if canon in self.lock_ctor:
                    return canon
                for modname, names in self.module_locks.items():
                    # import-aliased module lock (re-exported)
                    resolved = self.ctx.resolve_symbol(
                        canon, {f"{modname}.{n}": 1 for n in names}
                    )
                    if resolved:
                        return resolved
                leaf = canon.split(".")[-1].lower()
                if "lock" in leaf or "mutex" in leaf:
                    return canon
        return None

    # --------------------------------------------- entry-lock propagation

    def _compute_entry_locks(self) -> Dict[str, FrozenSet[str]]:
        sites: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
        for fname, conc in self.fn_conc.items():
            for cs in conc.calls:
                for t in cs.targets:
                    sites.setdefault(t, []).append(
                        (fname, frozenset(cs.held))
                    )
        all_locks = frozenset(self.lock_ctor) | {
            lid
            for conc in self.fn_conc.values()
            for lid, _ in conc.regions
        }
        entry: Dict[str, FrozenSet[str]] = {}
        for name, info in self.ctx.functions.items():
            if info.thread_target or name not in sites:
                entry[name] = frozenset()
            else:
                entry[name] = all_locks  # TOP; intersection-refined below
        for _ in range(len(self.ctx.functions) + 2):
            changed = False
            for name, slist in sites.items():
                info = self.ctx.functions.get(name)
                if info is None or info.thread_target:
                    continue
                new: Optional[FrozenSet[str]] = None
                for caller, held in slist:
                    eff = held | entry.get(caller, frozenset())
                    new = eff if new is None else (new & eff)
                new = new or frozenset()
                if new != entry[name]:
                    entry[name] = new
                    changed = True
            if not changed:
                break
        return entry

    def held_at(self, fn: FunctionInfo, lexical: Tuple[str, ...]) -> FrozenSet[str]:
        """Effective lock set at a node: lexical `with` nesting plus the
        locks provably held at every entry to the function."""
        return frozenset(lexical) | self.entry_locks.get(
            fn.full_name, frozenset()
        )

    # ----------------------------------------------- main-path contexts

    def _compute_main_set(self) -> Set[str]:
        ctx = self.ctx
        callers: Dict[str, int] = {}
        for f in ctx.functions.values():
            for name in f.calls:
                callers[name] = callers.get(name, 0) + 1
        children: Dict[FunctionInfo, List[FunctionInfo]] = {}
        for f in ctx.functions.values():
            if f.parent is not None:
                children.setdefault(f.parent, []).append(f)
        main: Set[str] = set()
        work: List[FunctionInfo] = []
        for f in ctx.functions.values():
            # seeds: top-level defs/methods nobody in the analyzed set
            # calls — invocable from outside (public API, tests, module
            # scope) — that are not thread spawn targets
            if f.thread_target or f.parent is not None:
                continue
            if not callers.get(f.full_name):
                main.add(f.full_name)
                work.append(f)
        while work:
            f = work.pop()
            nxt: List[FunctionInfo] = []
            for name in f.calls:
                g = ctx.functions.get(name)
                if g is not None:
                    nxt.append(g)
            nxt.extend(children.get(f, ()))
            for g in nxt:
                if g.thread_target or g.full_name in main:
                    continue
                main.add(g.full_name)
                work.append(g)
        return main

    def contexts(self, fn: FunctionInfo) -> FrozenSet[str]:
        """Execution contexts this function's body can run in: the
        thread roots it is reachable from, plus ``<main>`` when it is
        reachable outside any spawned thread."""
        out = set(fn.thread_roots)
        if fn.full_name in self.main_set or not out:
            out.add(MAIN)
        return frozenset(out)

    # -------------------------------------------------- owner utilities

    def attr_owner(self, info: FunctionInfo) -> Optional[str]:
        if not info.class_name:
            return None
        return self._class_component(
            f"{info.module.modname}.{info.class_name}"
        )

    def exempt_attr(self, info: FunctionInfo, attr: str) -> bool:
        """Lock attributes and intrinsically thread-safe containers are
        not shared-state findings."""
        own = f"{info.module.modname}.{info.class_name}"
        for cls in self.ctx.class_relatives.get(own, {own}) | {own}:
            if attr in self.class_lock_attrs.get(cls, {}):
                return True
            if attr in self.class_safe_attrs.get(cls, set()):
                return True
        return False


def get_model(ctx: LintContext) -> ConcurrencyModel:
    model = getattr(ctx, "_concurrency_model", None)
    if model is None:
        model = ConcurrencyModel(ctx)
        ctx._concurrency_model = model
    return model


# -------------------------------------------------------------- walker


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id in ("self", "cls")
    ):
        return node.attr
    return None


def _walk_function(model: ConcurrencyModel, info: FunctionInfo) -> FnConc:
    """One pass over a function's own body (nested defs/lambdas are
    separate scopes) tracking the lexical lock stack."""
    out = FnConc()
    mod = info.module
    ctx = model.ctx

    # names bound locally (shadowing module globals), minus `global`s
    if isinstance(info.node, ast.Module):
        local_names: Set[str] = set()
        global_decls: Set[str] = set()
        body = list(info.node.body)
    else:
        from tools.graftlint.engine import _function_scope_locals

        global_decls = {
            n
            for sub in ast.walk(info.node)
            for n in (sub.names if isinstance(sub, ast.Global) else ())
        }
        local_names = _function_scope_locals(info.node) - global_decls
        body = info.node.body if isinstance(info.node.body, list) else [
            info.node.body
        ]

    tracked_globals = {
        n
        for n in mod.global_names
        if n not in mod.aliases
        and f"{mod.modname}.{n}" not in ctx.functions
        and f"{mod.modname}.{n}" not in ctx.classes
    }

    def record_attr(attr: str, node: ast.AST, write: bool, held):
        if not info.class_name:
            return
        if model.exempt_attr(info, attr):
            return
        owner = model.attr_owner(info)
        if owner is None:
            return
        out.attr_accesses.append(
            Access(owner, attr, info, node, write, tuple(held))
        )

    def record_global(name: str, node: ast.AST, write: bool, held):
        if name not in tracked_globals:
            return
        if name in local_names and name not in global_decls:
            return
        out.global_accesses.append(
            Access(mod.modname, name, info, node, write, tuple(held))
        )

    def handle_call(node: ast.Call, held):
        canon = mod.resolve(node.func)
        targets = _function_targets(ctx, info, node.func)
        if targets:
            out.calls.append(CallSite(targets, node, tuple(held)))
        # manual acquire / blocking calls
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            recv = node.func.value
            if attr == "acquire":
                lid = model.lock_id(info, recv)
                if lid is not None:
                    out.acquires.append((lid, node, False, tuple(held)))
                    return
            if attr in BLOCKING_ATTRS and attr != "acquire":
                if canon in _JOIN_EXCLUDE_CANON:
                    return_block = False
                elif isinstance(recv, ast.Constant):
                    return_block = False  # "sep".join(...)
                else:
                    return_block = True
                if return_block:
                    out.blocking.append(
                        (f".{attr}()", node, tuple(held))
                    )
                return
            if attr in ("get",) and info.class_name:
                # blocking Queue.get on a known queue attribute
                rattr = _self_attr(recv)
                own = f"{mod.modname}.{info.class_name}"
                if rattr is not None and any(
                    rattr in model.class_queue_attrs.get(cls, set())
                    for cls in ctx.class_relatives.get(own, {own}) | {own}
                ):
                    out.blocking.append(
                        (f"Queue.get() on self.{rattr}", node, tuple(held))
                    )
                return
        if canon in BLOCKING_CANON:
            out.blocking.append((BLOCKING_CANON[canon], node, tuple(held)))
        elif (
            isinstance(node.func, ast.Name)
            and node.func.id == "open"
            and "open" not in mod.aliases
            and "open" not in local_names
        ):
            out.blocking.append(("open() (file IO)", node, tuple(held)))

    def visit(node: ast.AST, held: Tuple[str, ...], released: Tuple[str, ...]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # separate scope, walked via its own FunctionInfo
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                visit(item.context_expr, held, released)
                lid = model.lock_id(info, item.context_expr)
                if lid is not None:
                    if lid in new_held and not model.is_reentrant(lid):
                        out.same_lock_nesting.append((lid, node))
                    for h in new_held:
                        if h != lid:
                            out.order_edges.append((h, lid, node))
                    out.regions.append((lid, node))
                    new_held = new_held + (lid,)
            for sub in node.body:
                visit(sub, new_held, released)
            return
        if isinstance(node, ast.Try):
            rel = set(released)
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "release"
                    ):
                        lid = model.lock_id(info, sub.func.value)
                        if lid is not None:
                            rel.add(lid)
                            out.finally_releases.add(lid)
            rel_t = tuple(rel)
            for sub in node.body + node.handlers + node.orelse:
                visit(sub, held, rel_t)
            for sub in node.finalbody:
                visit(sub, held, released)
            return
        if isinstance(node, ast.ExceptHandler):
            for sub in node.body:
                visit(sub, held, released)
            return
        if isinstance(node, ast.Call):
            handle_call(node, held)
            # patch release-protection onto the acquire just recorded
            if (
                out.acquires
                and out.acquires[-1][1] is node
                and out.acquires[-1][0] in released
            ):
                lid, n, _, h = out.acquires[-1]
                out.acquires[-1] = (lid, n, True, h)
            # mutating method call on self.attr / a module global
            if isinstance(node.func, ast.Attribute) and (
                node.func.attr in MUTATOR_METHODS
            ):
                recv = node.func.value
                attr = _self_attr(recv)
                if attr is not None:
                    record_attr(attr, node, True, held)
                elif isinstance(recv, ast.Name):
                    record_global(recv.id, node, True, held)
        elif isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None:
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                record_attr(attr, node, write, held)
        elif isinstance(node, ast.Subscript):
            # self.X[i] = v / del GLOBAL[k]: container mutation
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                attr = _self_attr(node.value)
                if attr is not None:
                    record_attr(attr, node, True, held)
                elif isinstance(node.value, ast.Name):
                    record_global(node.value.id, node, True, held)
        elif isinstance(node, ast.Name):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                if node.id in global_decls:
                    record_global(node.id, node, True, held)
            elif isinstance(node.ctx, ast.Load):
                record_global(node.id, node, False, held)
        for child in ast.iter_child_nodes(node):
            visit(child, held, released)

    for stmt in body:
        visit(stmt, (), ())
    return out
