"""graftlint CLI.

    python -m tools.graftlint [paths...] [options]

Default targets: ``dmosopt_tpu/``, ``bench.py``, ``__graft_entry__.py``
(relative to the repo root — the ``make lint`` surface). Jax-free by
construction: runs even when the TPU tunnel is down.

Options:
    --json            machine-readable output (findings + summary)
    --select R1,R2    run only these rules
    --list-rules      print the rule catalog and exit
    --hot             print every jit-region function with provenance
    --threads         print every thread root and its reachable set
                      with provenance (the thread-root resolver)
    --frozen-hashes   print current normalized hashes of all registered
                      frozen functions (copy-paste for registry bumps)
    --bump-frozen N   rewrite tools/graftlint/frozen_registry.py hashes
                      from the CURRENT source for the named qualnames
                      (comma list, or "all"); pair every bump with a
                      re-bake of the run-time pins the entry names
    --bump-schema     rewrite tools/graftlint/checkpoint_registry.py
                      FIELDS from the CURRENT checkpoint-writer AST
                      (write_only flags of surviving fields preserved)
    --registry-file P registry file --bump-frozen/--bump-schema rewrite
                      (tests; defaults to the shipped registry)
    --no-cache        bypass the incremental result cache
                      (.graftlint_cache.json); the cache self-
                      invalidates on any source/rule/registry change

Exit status: 0 when no unsuppressed findings, 1 otherwise, 2 on usage
errors.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[2]
if str(REPO_ROOT) not in sys.path:  # `python tools/graftlint` direct runs
    sys.path.insert(0, str(REPO_ROOT))

from tools.graftlint.engine import (  # noqa: E402
    DEFAULT_TARGETS,
    frozen_hash,
    load_context,
    run_lint,
)
from tools.graftlint.registry import all_rules  # noqa: E402


def _print_rules() -> int:
    for rule in all_rules(None):
        print(f"{rule.name}")
        print(f"    {rule.description}")
        print(f"    incident: {rule.incident}")
    return 0


def _print_hot(targets) -> int:
    ctx = load_context(REPO_ROOT, targets)
    for info in sorted(ctx.hot_functions(), key=lambda f: f.full_name):
        kind = (
            "jit entry" if info.jit_entry
            else "traced body" if info.traced_body
            else info.hot_via
        )
        print(f"{info.full_name}  ({kind})  {info.module.relpath}:{info.line}")
    print(f"{len(ctx.hot_functions())} jit-region function(s)")
    return 0


def _print_threads(targets) -> int:
    """The thread-root resolver's verdict: every root (Thread target /
    executor-dispatched callable) with its provenance, then the set of
    functions reachable from it — the surface the concurrency rules
    police."""
    ctx = load_context(REPO_ROOT, targets)
    roots = ctx.thread_root_names()
    for root in roots:
        info = ctx.functions[root]
        print(f"{root}  [{info.thread_via}]  "
              f"{info.module.relpath}:{info.line}")
        reachable = sorted(
            f.full_name
            for f in ctx.threaded_functions()
            if root in f.thread_roots and f.full_name != root
        )
        for name in reachable:
            g = ctx.functions[name]
            print(f"    -> {name}  ({g.thread_via})  "
                  f"{g.module.relpath}:{g.line}")
    print(
        f"{len(roots)} thread root(s), "
        f"{len(ctx.threaded_functions())} thread-reachable function(s)"
    )
    return 0


def _print_frozen_hashes(targets) -> int:
    from tools.graftlint.frozen_registry import FROZEN

    ctx = load_context(REPO_ROOT, targets)
    for name in sorted(FROZEN):
        info = ctx.functions.get(name)
        if info is None:
            print(f"{name}: NOT FOUND in lint targets")
        else:
            print(f'"{name}": "{frozen_hash(info.node)}"')
    return 0


def _bump_frozen(targets, spec: str, registry_file) -> int:
    from tools.graftlint.bump import bump_frozen

    names = [n.strip() for n in spec.split(",") if n.strip()]
    changed = bump_frozen(
        REPO_ROOT, targets, names, registry_path=registry_file
    )
    if not changed:
        print("graftlint: frozen registry already in sync — no bump needed")
        return 0
    for name, (old, new) in sorted(changed.items()):
        print(f"{name}: {old[:12]}… -> {new[:12]}…")
    print(
        f"graftlint: bumped {len(changed)} frozen hash(es); re-bake the "
        f"run-time pins named in each entry's pinned_by"
    )
    return 0


def _bump_schema(targets, registry_file) -> int:
    from tools.graftlint.bump import bump_schema

    changed = bump_schema(REPO_ROOT, targets, registry_path=registry_file)
    if not changed:
        print("graftlint: checkpoint schema already in sync — no bump needed")
        return 0
    for section, (added, removed) in sorted(changed.items()):
        if added:
            print(f"{section}: +{sorted(added)}")
        if removed:
            print(f"{section}: -{sorted(removed)}")
    print(
        "graftlint: checkpoint schema bumped; make the resume path "
        "consume every new field (or mark it write_only with a reason) "
        "and re-run the kill -9 resume pin"
    )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="graftlint", add_help=True)
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--select", default=None)
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--hot", action="store_true")
    ap.add_argument("--threads", action="store_true")
    ap.add_argument("--frozen-hashes", action="store_true")
    ap.add_argument("--bump-frozen", default=None, metavar="NAMES")
    ap.add_argument("--bump-schema", action="store_true")
    ap.add_argument("--registry-file", default=None)
    ap.add_argument("--no-cache", action="store_true")
    args = ap.parse_args(argv)

    targets = args.paths or list(DEFAULT_TARGETS)
    rules = None
    if args.select:
        rules = [r.strip() for r in args.select.split(",") if r.strip()]
    try:
        if args.list_rules:
            return _print_rules()
        if args.hot:
            return _print_hot(targets)
        if args.threads:
            return _print_threads(targets)
        if args.frozen_hashes:
            return _print_frozen_hashes(targets)
        if args.bump_frozen:
            return _bump_frozen(targets, args.bump_frozen, args.registry_file)
        if args.bump_schema:
            return _bump_schema(targets, args.registry_file)
        findings = None
        cache = None
        if not args.no_cache:
            from tools.graftlint.cache import LintCache

            cache = LintCache(REPO_ROOT)
            findings = cache.load(targets, rules)
        if findings is None:
            findings = run_lint(REPO_ROOT, targets, rules=rules)
            if cache is not None:
                cache.store(targets, rules, findings)
    except (KeyError, ValueError) as e:
        print(f"graftlint: {e.args[0]}", file=sys.stderr)
        return 2

    live = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in live],
            "suppressed": [f.to_dict() for f in suppressed],
            "counts": {
                "findings": len(live),
                "suppressed": len(suppressed),
            },
        }, indent=2))
        return 1 if live else 0

    for f in live:
        print(f.format())
    if live:
        by_rule: dict = {}
        for f in live:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        summary = ", ".join(f"{k}: {v}" for k, v in sorted(by_rule.items()))
        print(f"graftlint: {len(live)} finding(s) ({summary}); "
              f"{len(suppressed)} suppressed")
        return 1
    print(f"graftlint: OK — 0 findings ({len(suppressed)} suppressed with "
          f"justification)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
