"""Frozen-registry bump helper: rewrite baked source hashes in place.

The frozen-path guard (rules/frozen_path.py) bakes a normalized-source
SHA-256 per registered qualname; editing a frozen function turns
``make lint`` red until the registry is bumped. The manual procedure in
docs/static-analysis.md (run ``--frozen-hashes``, paste each hex back
into ``frozen_registry.py``) is error-prone when a refactor touches
several frozen paths at once — ``--bump-frozen`` performs it
mechanically:

    python -m tools.graftlint --bump-frozen all
    python -m tools.graftlint --bump-frozen dmosopt_tpu.models.gp.fit_gp_batch

Only the ``"sha256"`` hex of each named entry changes; reasons,
``pinned_by`` pointers, and comments stay untouched — a bump is a
statement that the CURRENT source is the newly frozen program, so the
run-time pins named in ``pinned_by`` must be re-baked in the same
change (the registry records the lint-time half only).
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, Iterable, Optional, Tuple

from tools.graftlint.engine import frozen_hash, load_context

DEFAULT_REGISTRY = Path(__file__).resolve().parent / "frozen_registry.py"
DEFAULT_SCHEMA_REGISTRY = (
    Path(__file__).resolve().parent / "checkpoint_registry.py"
)


def registered_names(registry_path: Optional[Path] = None):
    """Qualnames registered in the registry file (textual scan — the
    file stays importable, but the bump operates on source text so it
    can run against sandbox copies in tests)."""
    path = Path(registry_path or DEFAULT_REGISTRY)
    return re.findall(
        r'^\s*["\']([A-Za-z_][\w.]*)["\']\s*:\s*\{', path.read_text(), re.M
    )


def _entry_span(text: str, name: str):
    """(begin, end) character offsets of the registry VALUE dict for
    `name`, located via the AST so string contents can never skew the
    boundary. Works on any module-level dict literal whose keys are
    string constants (the FROZEN registry shape)."""
    import ast

    tree = ast.parse(text)
    lines = text.splitlines(keepends=True)
    starts = [0]
    for ln in lines:
        starts.append(starts[-1] + len(ln))

    def offset(lineno, col):
        return starts[lineno - 1] + col

    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            if (
                isinstance(k, ast.Constant)
                and k.value == name
                and isinstance(v, ast.Dict)
            ):
                return (
                    offset(v.lineno, v.col_offset),
                    offset(v.end_lineno, v.end_col_offset),
                )
    raise KeyError(f"registry entry for {name!r} not found")


def bump_frozen(
    repo_root,
    targets: Iterable[str],
    names: Iterable[str],
    registry_path: Optional[Path] = None,
) -> Dict[str, Tuple[str, str]]:
    """Rewrite the ``"sha256"`` entries for `names` (or every registered
    name, for ``["all"]``) with the hash of the CURRENT normalized
    source. Returns {qualname: (old_hash, new_hash)} for the entries
    that actually changed; raises KeyError for names missing from the
    registry or the lint targets."""
    path = Path(registry_path or DEFAULT_REGISTRY)
    text = path.read_text()
    known = registered_names(path)
    names = list(names)
    if names == ["all"]:
        names = known
    unknown = sorted(set(names) - set(known))
    if unknown:
        raise KeyError(
            f"not in the frozen registry ({path.name}): {unknown}"
        )

    ctx = load_context(Path(repo_root), tuple(targets))
    changed: Dict[str, Tuple[str, str]] = {}
    for name in names:
        info = ctx.functions.get(name)
        if info is None:
            raise KeyError(
                f"frozen function {name!r} not found in lint targets "
                f"{tuple(targets)}"
            )
        new = frozen_hash(info.node)
        # scope the sha256 search to THIS entry's value dict, with the
        # span taken from the AST (immune to braces inside reason
        # strings): a lazy cross-entry match would silently rewrite the
        # NEXT entry's hash when the named entry is missing its own
        begin, end = _entry_span(text, name)
        m = re.search(
            r'(["\']sha256["\']\s*:\s*["\'])([0-9a-f]{64})',
            text[begin:end],
        )
        if m is None:
            raise KeyError(
                f"registry entry for {name!r} has no sha256 line"
            )
        start = begin + m.start(2)
        old = m.group(2)
        if old != new:
            text = text[:start] + new + text[start + 64:]
            changed[name] = (old, new)
    if changed:
        path.write_text(text)
    return changed


# ------------------------------------------------- checkpoint schema bump


def _toplevel_value_span(text: str, name: str):
    """(begin, end) character offsets of the VALUE of the module-level
    assignment ``name = <value>`` (AST-located, comment/string-safe)."""
    import ast

    tree = ast.parse(text)
    lines = text.splitlines(keepends=True)
    starts = [0]
    for ln in lines:
        starts.append(starts[-1] + len(ln))

    def offset(lineno, col):
        return starts[lineno - 1] + col

    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return (
                        offset(node.value.lineno, node.value.col_offset),
                        offset(node.value.end_lineno, node.value.end_col_offset),
                    )
    raise KeyError(f"module-level assignment {name!r} not found")


def _format_fields(fields: Dict[str, Dict[str, dict]]) -> str:
    lines = ["{"]
    for section in ("service", "state", "arrays"):
        if section not in fields:
            continue
        lines.append(f'    "{section}": {{')
        for fname in sorted(fields[section]):
            meta = fields[section][fname]
            lines.append(f'        "{fname}": {meta!r},')
        lines.append("    },")
    for section in sorted(set(fields) - {"service", "state", "arrays"}):
        lines.append(f'    "{section}": {{')
        for fname in sorted(fields[section]):
            lines.append(f'        "{fname}": {fields[section][fname]!r},')
        lines.append("    },")
    lines.append("}")
    return "\n".join(lines)


def bump_schema(
    repo_root,
    targets: Iterable[str],
    registry_path: Optional[Path] = None,
) -> Dict[str, Tuple[set, set]]:
    """Rewrite the checkpoint-schema registry's FIELDS block (and
    SCHEMA_VERSION) from the CURRENT writer AST. The meta dict of every
    surviving field — ``write_only`` flags and their reasons — is
    preserved; new fields default to required-on-load. Returns
    ``{section: (added, removed)}`` for sections that changed (plus a
    ``"version"`` pseudo-section when the version moved)."""
    from tools.graftlint.rules.checkpoint_schema import (
        _module_constant,
        writer_fields,
    )

    path = Path(registry_path or DEFAULT_SCHEMA_REGISTRY)
    text = path.read_text()
    ns: Dict = {}
    exec(compile(text, str(path), "exec"), ns)  # registry files are data

    ctx = load_context(Path(repo_root), tuple(targets))
    changed: Dict[str, Tuple[set, set]] = {}
    new_fields: Dict[str, Dict[str, dict]] = {}
    for section, writer_names in ns["WRITERS"].items():
        infos = [ctx.functions[n] for n in writer_names if n in ctx.functions]
        if not infos:
            raise KeyError(
                f"checkpoint writer(s) {writer_names} for section "
                f"{section!r} not found in lint targets {tuple(targets)}"
            )
        written: set = set()
        for info in infos:
            written |= writer_fields(info, section)
        old = ns["FIELDS"].get(section, {})
        new_fields[section] = {
            f: dict(old.get(f, {})) for f in sorted(written)
        }
        added = written - set(old)
        removed = set(old) - written
        if added or removed:
            changed[section] = (added, removed)

    new_version = ns["SCHEMA_VERSION"]
    vconst = _module_constant(ctx, ns["STORAGE_VERSION"])
    if vconst is not None and vconst[2] is not None:
        if vconst[2] != ns["SCHEMA_VERSION"]:
            changed["version"] = ({vconst[2]}, {ns["SCHEMA_VERSION"]})
            new_version = vconst[2]

    if not changed:
        return changed
    begin, end = _toplevel_value_span(text, "FIELDS")
    text = text[:begin] + _format_fields(new_fields) + text[end:]
    if "version" in changed:
        begin, end = _toplevel_value_span(text, "SCHEMA_VERSION")
        text = text[:begin] + repr(new_version) + text[end:]
    path.write_text(text)
    return changed
