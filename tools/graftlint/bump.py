"""Frozen-registry bump helper: rewrite baked source hashes in place.

The frozen-path guard (rules/frozen_path.py) bakes a normalized-source
SHA-256 per registered qualname; editing a frozen function turns
``make lint`` red until the registry is bumped. The manual procedure in
docs/static-analysis.md (run ``--frozen-hashes``, paste each hex back
into ``frozen_registry.py``) is error-prone when a refactor touches
several frozen paths at once — ``--bump-frozen`` performs it
mechanically:

    python -m tools.graftlint --bump-frozen all
    python -m tools.graftlint --bump-frozen dmosopt_tpu.models.gp.fit_gp_batch

Only the ``"sha256"`` hex of each named entry changes; reasons,
``pinned_by`` pointers, and comments stay untouched — a bump is a
statement that the CURRENT source is the newly frozen program, so the
run-time pins named in ``pinned_by`` must be re-baked in the same
change (the registry records the lint-time half only).
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, Iterable, Optional, Tuple

from tools.graftlint.engine import frozen_hash, load_context

DEFAULT_REGISTRY = Path(__file__).resolve().parent / "frozen_registry.py"


def registered_names(registry_path: Optional[Path] = None):
    """Qualnames registered in the registry file (textual scan — the
    file stays importable, but the bump operates on source text so it
    can run against sandbox copies in tests)."""
    path = Path(registry_path or DEFAULT_REGISTRY)
    return re.findall(
        r'^\s*["\']([A-Za-z_][\w.]*)["\']\s*:\s*\{', path.read_text(), re.M
    )


def _entry_span(text: str, name: str):
    """(begin, end) character offsets of the registry VALUE dict for
    `name`, located via the AST so string contents can never skew the
    boundary. Works on any module-level dict literal whose keys are
    string constants (the FROZEN registry shape)."""
    import ast

    tree = ast.parse(text)
    lines = text.splitlines(keepends=True)
    starts = [0]
    for ln in lines:
        starts.append(starts[-1] + len(ln))

    def offset(lineno, col):
        return starts[lineno - 1] + col

    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            if (
                isinstance(k, ast.Constant)
                and k.value == name
                and isinstance(v, ast.Dict)
            ):
                return (
                    offset(v.lineno, v.col_offset),
                    offset(v.end_lineno, v.end_col_offset),
                )
    raise KeyError(f"registry entry for {name!r} not found")


def bump_frozen(
    repo_root,
    targets: Iterable[str],
    names: Iterable[str],
    registry_path: Optional[Path] = None,
) -> Dict[str, Tuple[str, str]]:
    """Rewrite the ``"sha256"`` entries for `names` (or every registered
    name, for ``["all"]``) with the hash of the CURRENT normalized
    source. Returns {qualname: (old_hash, new_hash)} for the entries
    that actually changed; raises KeyError for names missing from the
    registry or the lint targets."""
    path = Path(registry_path or DEFAULT_REGISTRY)
    text = path.read_text()
    known = registered_names(path)
    names = list(names)
    if names == ["all"]:
        names = known
    unknown = sorted(set(names) - set(known))
    if unknown:
        raise KeyError(
            f"not in the frozen registry ({path.name}): {unknown}"
        )

    ctx = load_context(Path(repo_root), tuple(targets))
    changed: Dict[str, Tuple[str, str]] = {}
    for name in names:
        info = ctx.functions.get(name)
        if info is None:
            raise KeyError(
                f"frozen function {name!r} not found in lint targets "
                f"{tuple(targets)}"
            )
        new = frozen_hash(info.node)
        # scope the sha256 search to THIS entry's value dict, with the
        # span taken from the AST (immune to braces inside reason
        # strings): a lazy cross-entry match would silently rewrite the
        # NEXT entry's hash when the named entry is missing its own
        begin, end = _entry_span(text, name)
        m = re.search(
            r'(["\']sha256["\']\s*:\s*["\'])([0-9a-f]{64})',
            text[begin:end],
        )
        if m is None:
            raise KeyError(
                f"registry entry for {name!r} has no sha256 line"
            )
        start = begin + m.start(2)
        old = m.group(2)
        if old != new:
            text = text[:start] + new + text[start + 64:]
            changed[name] = (old, new)
    if changed:
        path.write_text(text)
    return changed
