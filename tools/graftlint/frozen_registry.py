"""The frozen-path registry: bitwise-frozen functions and their baked
normalized-source hashes (see rules/frozen_path.py for the hash
definition and docs/static-analysis.md for the bump procedure).

The seed set names exactly the paths the seeded-trajectory / parity pin
tests already freeze, so the registry and the SHA-256 pins guard the
same program text from both sides: the pins catch numeric drift at run
time, this registry catches the source edit at lint time.
"""

FROZEN = {
    # PR 5: the solve-regime predictor is the frozen oracle every other
    # predict regime (matmul, nystrom) is parity-pinned against.
    "dmosopt_tpu.models.gp.gp_predict": {
        "sha256": "cf74d08b7a4be99acb96270b27ffeed3d8d55b422a674a1446ec85bc84b867be",
        "reason": "solve-regime predict oracle; default path of every "
                  "exact-GP surrogate — an ulp of drift breaks the baked "
                  "zdt1 driver-trajectory hash",
        "pinned_by": "tests/test_gp_predictor.py::"
                     "test_default_solve_trajectory_bitwise_pinned",
    },
    # PR 4: the cold fit is the default surrogate_refit="cold" program;
    # warm/rank paths are pinned bitwise against it.
    "dmosopt_tpu.models.gp.fit_gp_batch": {
        "sha256": "188c5cf5e81a7b34bc2dc3ed98ee9dd789e208eecae60bf5bf9520c31ed1a083",
        "reason": "cold-fit path; surrogate_refit='cold' default is "
                  "pinned bitwise vs HEAD at fit and seeded-trajectory "
                  "level",
        "pinned_by": "tests/test_gp_refit.py (cold bitwise fit + "
                     "trajectory regressions)",
    },
    # PR 3: the dense dominance-degree peel is the oracle both live rank
    # routes are bitwise equivalence-pinned against.
    "dmosopt_tpu.ops.dominance._rank_matrix_peel": {
        "sha256": "738082444c074551ed28be00548d58148780344fa37278208e91bbc0224b59c6",
        "reason": "dense dominance oracle; the tiled sweep and the d==2 "
                  "sweep are bitwise-pinned against it",
        "pinned_by": "tests/test_ops.py rank equivalence pins",
    },
    # PR 2/3: the d==2 patience-sorting sweep serves every bi-objective
    # ranking (the ZDT sweep path) and is routing-pinned.
    "dmosopt_tpu.ops.dominance._rank_biobjective_sweep": {
        "sha256": "b27ddd45a32347c52b2888aa149203c96100a91780b2ac70ef127ccbccfe609a",
        "reason": "d==2 ZDT sweep; byte-identical trajectories across "
                  "PRs depend on it (routing pinned at trace time)",
        "pinned_by": "tests/test_ops.py + PR 5 d==2 routing count test",
    },
    # PR 3: the dense duplicate-mask kernel is kept VERBATIM for the
    # single-chunk regime — wrapping the same math in lax.scan shifted
    # fusion by an ulp and flipped borderline D <= eps comparisons
    # (the dtlz7 HV 13.49 -> 14.54 bisection).
    "dmosopt_tpu.ops.distances._duplicate_mask_dense": {
        "sha256": "9f1baad4456f89f2b926c55a6e2f15747f9f42af5d0cfd53c8519e78b7b57297",
        "reason": "dense duplicate-mask branch, frozen verbatim after "
                  "the dtlz7 ulp/fusion trajectory bisection",
        "pinned_by": "tests/test_ops.py dense-vs-chunked agreement pins",
    },
    # PR 19: the dense variation cores are the bitwise-frozen CPU
    # fallback behind the Pallas TPU kernels — both routes consume the
    # same precomputed uniforms and the jitted dense core is the parity
    # oracle the Pallas route is pinned bitwise against.
    "dmosopt_tpu.ops.variation._mutation_core": {
        "sha256": "d16f255c25939032f98c3a437f4a002fa84fc3c00989732c2f1922e57782c90f",
        "reason": "polynomial-mutation dense core; the Pallas route is "
                  "bitwise-pinned against its jitted form and every CPU "
                  "trajectory hash flows through it",
        "pinned_by": "tests/test_ops.py::"
                     "test_variation_pallas_route_matches_dense",
    },
    "dmosopt_tpu.ops.variation._sbx_core": {
        "sha256": "f57e59c76ecaac42545f5d7db0d235b63cdae18c1fe0cd231fb8a7294ea5ef96",
        "reason": "SBX dense core; the Pallas route is bitwise-pinned "
                  "against its jitted form and every CPU trajectory "
                  "hash flows through it",
        "pinned_by": "tests/test_ops.py::"
                     "test_variation_pallas_route_matches_dense",
    },
    # PR 3: the dense pairwise-distance kernel backs the single-chunk
    # regime of every crowding/survival distance consumer.
    "dmosopt_tpu.ops.distances._pairwise_distances_dense": {
        "sha256": "d9a428c1b85eb10fe9cdb21b3f6a02c320c078959a462eef470f054673b8c6c8",
        "reason": "dense pairwise-distance branch (single-chunk regime "
                  "kept identical to the historical kernel)",
        "pinned_by": "tests/test_ops.py dense-vs-chunked agreement pins",
    },
}
