"""graftlint incremental mode: a per-file mtime+content-hash run cache.

``make lint`` runs the whole rule suite on every invocation; as the
rule count grows (nine families as of the concurrency suite) the
repo-wide parse+analyze wall starts to matter inside the fast test
loop. Every finding, however, is a pure function of (engine + rule
sources, registries, lint targets, rule selection, the catalog doc) —
so a run whose complete input fingerprint matches the previous one can
replay its findings without parsing anything.

The fingerprint is per-file: for each input we record
``(mtime_ns, size, sha256)``. Validation is the classic two-tier check:
an unchanged ``(mtime_ns, size)`` pair trusts the cached hash without
reading the file; a changed mtime re-reads and re-hashes — a pure
``touch`` (same content) refreshes the stored mtime and the cache stays
valid, so only real edits pay a full run. Any engine/rule/registry
change invalidates everything (those files are fingerprinted too), as
does a different target list or ``--select`` set.

The cache lives at ``<repo>/.graftlint_cache.json`` and is used by the
CLI only (``python -m tools.graftlint``, hence ``make lint``);
``--no-cache`` bypasses it, and the library entry point ``run_lint``
stays pure for tests.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

from tools.graftlint.engine import Finding, _iter_target_files

CACHE_BASENAME = ".graftlint_cache.json"
CACHE_FORMAT = 3
#: distinct (targets, rule-selection) entries kept; oldest evicted
MAX_ENTRIES = 8

#: non-target inputs findings depend on: the analyzer itself, the
#: registries, and the metrics/span catalog document
def _tool_inputs(repo_root: Path) -> List[Path]:
    tool_dir = repo_root / "tools" / "graftlint"
    files = sorted(tool_dir.rglob("*.py")) if tool_dir.is_dir() else []
    catalog = repo_root / "docs" / "observability.md"
    if catalog.is_file():
        files.append(catalog)
    return files


def _fingerprint(path: Path) -> Optional[Tuple[int, int, str]]:
    try:
        st = path.stat()
        digest = hashlib.sha256(path.read_bytes()).hexdigest()
    except OSError:
        return None
    return (st.st_mtime_ns, st.st_size, digest)


def _stat_pair(path: Path) -> Optional[Tuple[int, int]]:
    try:
        st = path.stat()
    except OSError:
        return None
    return (st.st_mtime_ns, st.st_size)


class LintCache:
    def __init__(self, repo_root: Path, path: Optional[Path] = None):
        self.repo_root = Path(repo_root)
        self.path = Path(path) if path else self.repo_root / CACHE_BASENAME

    def _key(self, targets: Iterable[str], rules) -> str:
        spec = {
            "targets": list(targets),
            "rules": sorted(rules) if rules else None,
            "format": CACHE_FORMAT,
        }
        return hashlib.sha256(
            json.dumps(spec, sort_keys=True).encode()
        ).hexdigest()

    def _input_files(self, targets) -> List[Path]:
        files = list(_iter_target_files(self.repo_root, targets))
        files.extend(_tool_inputs(self.repo_root))
        return files

    def _read(self) -> Optional[dict]:
        try:
            data = json.loads(self.path.read_text())
        except (OSError, ValueError):
            return None
        if data.get("format") != CACHE_FORMAT:
            return None
        if not isinstance(data.get("entries"), dict):
            return None
        return data

    def load(self, targets, rules) -> Optional[List[Finding]]:
        """Cached findings when every fingerprint of this run key's
        entry matches, else None. Entries are keyed by (targets, rule
        selection), so `make lint`, `make lint-threads` and the tier-1
        cache test each keep their own slot instead of evicting each
        other. Touch-only changes (new mtime, identical content)
        revalidate and refresh the stored mtime in place."""
        data = self._read()
        if data is None:
            return None
        entry = data["entries"].get(self._key(targets, rules))
        if entry is None:
            return None
        stored: Dict[str, list] = entry.get("files", {})
        try:
            current = self._input_files(targets)
        except ValueError:
            return None
        if {str(p) for p in current} != set(stored):
            return None
        refreshed = False
        for p in current:
            mtime_ns, size, digest = stored[str(p)]
            pair = _stat_pair(p)
            if pair is None:
                return None
            if pair == (mtime_ns, size):
                continue  # fast path: stat matches, trust the hash
            fp = _fingerprint(p)
            if fp is None or fp[2] != digest:
                return None  # real edit
            stored[str(p)] = list(fp)  # touch: refresh the mtime
            refreshed = True
        if refreshed:
            self._write(data)
        return [Finding(**f) for f in entry.get("findings", [])]

    def store(self, targets, rules, findings: List[Finding]) -> None:
        try:
            files = {
                str(p): list(fp)
                for p in self._input_files(targets)
                for fp in [_fingerprint(p)]
                if fp is not None
            }
        except ValueError:
            return
        data = self._read() or {"format": CACHE_FORMAT, "entries": {}}
        entries = data["entries"]
        key = self._key(targets, rules)
        entries.pop(key, None)  # re-insert so eviction order is LRU-ish
        entries[key] = {
            "files": files,
            "findings": [_finding_dict(f) for f in findings],
        }
        while len(entries) > MAX_ENTRIES:
            entries.pop(next(iter(entries)))
        self._write(data)

    def _write(self, data) -> None:
        try:
            tmp = self.path.with_suffix(".json.tmp")
            tmp.write_text(json.dumps(data))
            tmp.replace(self.path)
        except OSError:
            pass  # a cache that cannot be written is just a miss


def _finding_dict(f: Finding) -> dict:
    d = dataclasses.asdict(f)
    # Finding fields only — forward-compatible with suppression state
    return d
