"""graftlint rule registry.

A rule is a class with a ``name``, a one-line ``description``, the
incident it encodes (``incident``, shown by ``--list-rules`` and in
docs/static-analysis.md), and a ``check(ctx) -> list[Finding]``.
Registration is by decorator; ``all_rules()`` imports the rule modules
on first use so the registry is populated lazily but deterministically.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Type

REGISTRY: Dict[str, "Rule"] = {}


class Rule:
    name: str = ""
    description: str = ""
    incident: str = ""

    def check(self, ctx) -> list:
        raise NotImplementedError


def register(cls: Type[Rule]) -> Type[Rule]:
    inst = cls()
    if not inst.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if inst.name in REGISTRY:
        raise ValueError(f"duplicate rule name {inst.name}")
    REGISTRY[inst.name] = inst
    return cls

_LOADED = False


def _load_rule_modules():
    global _LOADED
    if _LOADED:
        return
    # import order is alphabetical and irrelevant: rules are independent
    from tools.graftlint.rules import (  # noqa: F401
        checkpoint_schema,
        dtype_discipline,
        frozen_path,
        hot_path,
        lock_discipline,
        metrics_catalog,
        resource_lifecycle,
        retrace_hazard,
        shared_state,
    )
    _LOADED = True


def all_rules(names: Optional[Iterable[str]] = None) -> List[Rule]:
    _load_rule_modules()
    if names is None:
        return [REGISTRY[k] for k in sorted(REGISTRY)]
    out = []
    for n in names:
        if n not in REGISTRY:
            raise KeyError(
                f"unknown rule '{n}' (known: {', '.join(sorted(REGISTRY))})"
            )
        out.append(REGISTRY[n])
    return out


def get_rule(name: str) -> Rule:
    _load_rule_modules()
    return REGISTRY[name]
