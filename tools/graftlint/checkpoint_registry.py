"""The service-checkpoint schema registry: the frozen field set of the
crash-safe checkpoint payload (see rules/checkpoint_schema.py for the
cross-check and docs/concurrency.md for the bump procedure).

The PR 10 incident this freezes: ``optimizer_draws`` was written by
``_tenant_checkpoint`` but its read was nearly dropped from the resume
path in review — a field asymmetry that silently breaks bitwise resume.
Every field written on the ``save_service_checkpoint_to_h5`` path must
be consumed on the ``load_service_checkpoint_from_h5``/``resume`` path
and vice versa; ``write_only: True`` marks the deliberate exceptions
(informational fields a resume never needs).

Bump procedure: edit the save/load paths together, then run
``python -m tools.graftlint --bump-schema`` — it rewrites FIELDS from
the CURRENT writer AST (preserving ``write_only`` flags of surviving
fields) and updates SCHEMA_VERSION to match
``storage.SERVICE_CHECKPOINT_VERSION``. A new field defaults to
required-on-load; mark it ``write_only`` only with a reason, and bump
``SERVICE_CHECKPOINT_VERSION`` in storage.py when the layout change is
incompatible.
"""

#: must equal storage.SERVICE_CHECKPOINT_VERSION (cross-checked)
#: (v2: the fleet ownership lease — ``service.owner`` +
#: ``service.placement_epoch``, consumed by ``resume``'s lease check
#: and ``claim_service_checkpoint``'s double-adoption guard)
SCHEMA_VERSION = 2

#: where the payload is WRITTEN: section -> producer functions whose
#: dict literals / subscript stores define the field set
WRITERS = {
    "service": ["dmosopt_tpu.service.OptimizationService._checkpoint_payload"],
    "state": ["dmosopt_tpu.service.OptimizationService._tenant_checkpoint"],
    "arrays": ["dmosopt_tpu.service.OptimizationService._tenant_checkpoint"],
}

#: where the payload is CONSUMED: every non-write_only field must be
#: read (``st["f"]`` / ``st.get("f")``) in at least one of these
READERS = [
    "dmosopt_tpu.service.OptimizationService._apply_restore",
    "dmosopt_tpu.service.OptimizationService.resume",
    "dmosopt_tpu.service.OptimizationService.submit",
]

#: the frozen field sets; ``write_only`` fields are persisted for
#: humans/tools but deliberately never read back by resume — each
#: carries its reason (``--bump-schema`` regenerates this block,
#: preserving the meta of surviving fields)
FIELDS = {
    "service": {
        "min_bucket": {},
        "owner": {},
        "placement_epoch": {},
        "steps": {"write_only": True,
                  "reason": "service step counter, informational"},
        "ts": {"write_only": True,
               "reason": "snapshot wall-clock, informational"},
    },
    "state": {
        "cost_seconds": {},
        "degraded": {},
        "epoch_index": {},
        "epochs_run": {},
        "eval_failures": {},
        "failed_epochs": {},
        "n_epochs": {"write_only": True,
                     "reason": "duplicated in the submit config resume "
                               "rebuilds from; stored for introspection"},
        "opt_id": {},
        "optimizer_draws": {},
        "pred_width": {"write_only": True,
                       "reason": "load path re-derives the width from "
                                 "the pending_pred array shape"},
        "quarantined": {},
        "quarantined_seen": {},
        "refit": {},
        "rng_state": {},
        "tenant_id": {},
    },
    "arrays": {
        "c": {},
        "f": {},
        "pending_epoch": {},
        "pending_has_pred": {},
        "pending_pred": {},
        "pending_x": {},
        "t": {},
        "x": {},
        "y": {},
    },
}

#: the storage-side array allowlist must match FIELDS["arrays"] exactly
#: (an array the service writes but storage drops is a silent data loss)
STORAGE_ARRAYS = "dmosopt_tpu.storage._CHECKPOINT_ARRAYS"

#: the storage-side version constant SCHEMA_VERSION mirrors
STORAGE_VERSION = "dmosopt_tpu.storage.SERVICE_CHECKPOINT_VERSION"
